"""Layer library unit tests (shapes, numerics, state handling)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.ops import (
    Activation, AvgPool, BatchNorm, Conv2D, ConvTranspose2D, Dense, Dropout,
    Embedding, Flatten, GlobalAvgPool, LayerNorm, LRN, LSTM, MaxPool,
    Sequential,
)

KEY = jax.random.PRNGKey(0)


def test_dense_shapes_and_linearity():
    layer = Dense(7)
    params, state, out_shape = layer.init(KEY, (4,))
    assert out_shape == (7,)
    x = jnp.ones((3, 4))
    y, _ = layer.apply(params, state, x)
    assert y.shape == (3, 7)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(x @ params["w"] + params["b"]), rtol=1e-6
    )


@pytest.mark.parametrize(
    "padding,expect_hw", [("SAME", (8, 8)), ("VALID", (6, 6)), (1, (8, 8))]
)
def test_conv_padding_modes(padding, expect_hw):
    layer = Conv2D(5, kernel=3, padding=padding)
    params, state, out_shape = layer.init(KEY, (8, 8, 2))
    assert out_shape == (*expect_hw, 5)
    y, _ = layer.apply(params, state, jnp.ones((2, 8, 8, 2)))
    assert y.shape == (2, *expect_hw, 5)


def test_conv_stride_and_groups():
    layer = Conv2D(8, kernel=3, stride=2, padding="SAME", groups=2)
    params, _, out_shape = layer.init(KEY, (8, 8, 4))
    assert out_shape == (4, 4, 8)
    assert params["w"].shape == (3, 3, 2, 8)  # C/groups input channels


def test_conv_identity_kernel():
    # 1x1 identity kernel: conv must reproduce input exactly
    layer = Conv2D(3, kernel=1, use_bias=False)
    params, state, _ = layer.init(KEY, (5, 5, 3))
    params = {"w": jnp.eye(3).reshape(1, 1, 3, 3)}
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 5, 3))
    y, _ = layer.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)


def test_conv_transpose_upsamples():
    layer = ConvTranspose2D(4, kernel=4, stride=2)
    _, _, out_shape = layer.init(KEY, (8, 8, 3))
    assert out_shape == (16, 16, 4)


def test_pools():
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y, _ = MaxPool(2).apply({}, {}, x)
    np.testing.assert_array_equal(
        np.asarray(y).squeeze(), [[5, 7], [13, 15]]
    )
    y, _ = AvgPool(2).apply({}, {}, x)
    np.testing.assert_allclose(
        np.asarray(y).squeeze(), [[2.5, 4.5], [10.5, 12.5]]
    )
    _, _, s = MaxPool(3, stride=2, padding="SAME").init(KEY, (7, 7, 2))
    assert s == (4, 4, 2)
    y, _ = GlobalAvgPool().apply({}, {}, x)
    np.testing.assert_allclose(np.asarray(y), [[7.5]])


def test_flatten():
    _, _, s = Flatten().init(KEY, (3, 4, 5))
    assert s == (60,)
    y, _ = Flatten().apply({}, {}, jnp.ones((2, 3, 4, 5)))
    assert y.shape == (2, 60)


def test_dropout_train_eval():
    layer = Dropout(0.5)
    x = jnp.ones((4, 100))
    y_eval, _ = layer.apply({}, {}, x, train=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_tr, _ = layer.apply({}, {}, x, train=True, rng=KEY)
    arr = np.asarray(y_tr)
    assert set(np.unique(arr)).issubset({0.0, 2.0})  # scaled by 1/keep
    assert 0.3 < (arr == 0).mean() < 0.7
    with pytest.raises(ValueError):
        layer.apply({}, {}, x, train=True, rng=None)


def test_batchnorm_normalizes_and_tracks():
    layer = BatchNorm(momentum=0.5)
    params, state, _ = layer.init(KEY, (3,))
    x = jax.random.normal(jax.random.PRNGKey(2), (256, 3)) * 4.0 + 2.0
    y, new_state = layer.apply(params, state, x, train=True)
    arr = np.asarray(y)
    np.testing.assert_allclose(arr.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(arr.std(0), 1.0, atol=1e-2)
    # running stats moved halfway toward batch stats (momentum 0.5)
    np.testing.assert_allclose(np.asarray(new_state["mean"]), 1.0, atol=0.2)
    # eval mode uses running stats, state unchanged
    y2, s2 = layer.apply(params, new_state, x, train=False)
    assert s2 is new_state


def test_sync_batchnorm_matches_global_stats(mesh8):
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map

    layer = BatchNorm(axis_name=DATA_AXIS)
    params, state, _ = layer.init(KEY, (3,))
    x = jax.random.normal(jax.random.PRNGKey(3), (64, 3)) * 3.0 + 1.0

    def f(x_local):
        y, st = layer.apply(params, state, x_local, train=True)
        return y, st["mean"][None]

    y, means = shard_map(
        f, mesh8, in_specs=P(DATA_AXIS), out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
    )(x)
    # every replica must have computed identical (global) running means
    m = np.asarray(means)
    for i in range(1, 8):
        np.testing.assert_allclose(m[i], m[0], rtol=1e-5)
    # and the global mean must match the full-batch statistics
    ref_layer = BatchNorm()
    _, ref_state, _ = ref_layer.init(KEY, (3,))
    _, ref_new = ref_layer.apply(params, ref_state, x, train=True)
    np.testing.assert_allclose(m[0], np.asarray(ref_new["mean"]), rtol=1e-4)


def test_layernorm():
    layer = LayerNorm()
    params, state, _ = layer.init(KEY, (8,))
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 8)) * 5 + 3
    y, _ = layer.apply(params, state, x)
    arr = np.asarray(y)
    np.testing.assert_allclose(arr.mean(-1), 0.0, atol=1e-4)
    np.testing.assert_allclose(arr.std(-1), 1.0, atol=1e-2)


def test_lrn_matches_manual():
    layer = LRN(size=3, alpha=1e-4, beta=0.75, k=2.0)
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 2, 2, 4))
    y, _ = layer.apply({}, {}, x)
    xn = np.asarray(x)
    sq = xn**2
    padded = np.pad(sq, [(0, 0)] * 3 + [(1, 1)])
    win = padded[..., 0:4] + padded[..., 1:5] + padded[..., 2:6]
    expect = xn / (2.0 + (1e-4 / 3) * win) ** 0.75
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5)


def test_embedding_and_lstm():
    emb = Embedding(vocab=11, dim=6)
    params, state, out_shape = emb.init(KEY, (5,))
    assert out_shape == (5, 6)
    ids = jnp.array([[1, 2, 3, 4, 10]])
    e, _ = emb.apply(params, state, ids)
    assert e.shape == (1, 5, 6)

    lstm = LSTM(hidden=8)
    params, state, out_shape = lstm.init(KEY, (5, 6))
    assert out_shape == (5, 8)
    h, _ = lstm.apply(params, state, e)
    assert h.shape == (1, 5, 8)
    assert bool(jnp.all(jnp.isfinite(h)))
    # grads flow through the scan
    g = jax.grad(lambda p: jnp.sum(lstm.apply(p, state, e)[0] ** 2))(params)
    assert float(jnp.abs(g["wh"]).sum()) > 0


def test_sequential_smoke_cnn():
    net = Sequential([
        Conv2D(4, 3), BatchNorm(), Activation("relu"), MaxPool(2),
        Conv2D(8, 3), Activation("relu"), GlobalAvgPool(),
        Dropout(0.1), Dense(10),
    ])
    params, state, out_shape = net.init(KEY, (16, 16, 3))
    assert out_shape == (10,)
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 16, 16, 3))
    y, new_state = net.apply(params, state, x, train=True, rng=KEY)
    assert y.shape == (2, 10)
    # BN state updated
    assert not np.allclose(
        np.asarray(new_state["01_batchnorm"]["mean"]),
        np.asarray(state["01_batchnorm"]["mean"]),
    )
    # bf16 compute path: cast input, params stay fp32
    y16, _ = net.apply(params, state, x.astype(jnp.bfloat16), train=False)
    assert y16.dtype == jnp.bfloat16


def test_layernorm_bf16_tracks_fp32_reference():
    """ADVICE r2: the bf16 elementwise-normalize path (fp32 stats, input-
    dtype affine) must stay within bf16 resolution of the full-fp32
    computation — the bandwidth tradeoff documented in LayerNorm.apply."""
    from theanompi_tpu.ops.layers import LayerNorm

    ln = LayerNorm()
    r = np.random.RandomState(0)
    x32 = jnp.asarray(r.randn(64, 128).astype(np.float32) * 3 + 1.5)
    params, _, _ = ln.init(jax.random.PRNGKey(0), (128,))
    params = {"scale": params["scale"] * 1.7, "bias": params["bias"] + 0.3}
    # isolate the COMPUTATION dtype: both paths see the same bf16-rounded
    # input (input quantization error would otherwise dominate via
    # (x - mean) cancellation and say nothing about the arithmetic)
    x16 = x32.astype(jnp.bfloat16)
    y32, _ = ln.apply(params, {}, x16.astype(jnp.float32))
    y16, _ = ln.apply(params, {}, x16)
    y32a = np.asarray(y32)
    err = np.abs(np.asarray(y16, np.float32) - y32a)
    # scale-relative error: near the normalize's zero crossings the
    # per-element relative error is unbounded for ANY finite precision,
    # so measure against |y| + the output scale.  A few bf16 ulps
    # (eps = 2^-8) through the subtract/rsqrt/affine chain is the budget.
    denom = np.abs(y32a) + y32a.std()
    assert float((err / denom).max()) < 0.02, float((err / denom).max())


def test_batchnorm_bf16_tracks_fp32_reference():
    from theanompi_tpu.ops.layers import BatchNorm

    bn = BatchNorm()
    r = np.random.RandomState(1)
    x32 = jnp.asarray(r.randn(32, 8, 8, 16).astype(np.float32) * 2 - 0.5)
    params, state, _ = bn.init(jax.random.PRNGKey(0), (8, 8, 16))
    x16 = x32.astype(jnp.bfloat16)
    y32, _ = bn.apply(params, state, x16.astype(jnp.float32), train=True)
    y16, _ = bn.apply(params, state, x16, train=True)
    y32a = np.asarray(y32)
    err = np.abs(np.asarray(y16, np.float32) - y32a)
    denom = np.abs(y32a) + y32a.std()
    assert float((err / denom).max()) < 0.02, float((err / denom).max())
