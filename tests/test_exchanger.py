"""Exchanger strategy tests: every strategy must compute the cross-replica mean.

Reference parity target (SURVEY.md §2.1): BSP_Exchanger.exchange() averaging
worker gradients; strategies ar/asa32/asa16/nccl32/nccl16 → psum/ring/…bf16.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from theanompi_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.exchanger import STRATEGIES, Exchanger
from theanompi_tpu.parallel.mesh import DATA_AXIS


def _run_exchange(mesh, strategy, per_device_vals):
    """per_device_vals: [n, ...] array; returns exchanged per-device output."""
    n = mesh.shape[DATA_AXIS]
    ex = Exchanger(strategy=strategy)

    def f(x):
        return jax.tree.map(lambda a: a[0], ex.exchange({"g": x}))["g"][None]

    out = shard_map(
        f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
        check=False,
    )(per_device_vals)
    return np.asarray(out)


@pytest.mark.parametrize(
    "strategy", sorted(s for s in STRATEGIES if s != "none")
)  # 'none' deliberately skips the mean (see test_scaling.py)
def test_strategy_computes_mean(mesh8, strategy):
    rng = np.random.RandomState(0)
    vals = rng.randn(8, 3, 5).astype(np.float32)
    out = _run_exchange(mesh8, strategy, jnp.asarray(vals))
    expect = vals.mean(axis=0)
    tol = 1e-2 if "bf16" in strategy else 1e-6
    for i in range(8):
        np.testing.assert_allclose(out[i], expect, rtol=tol, atol=tol)


@pytest.mark.parametrize("strategy", ["ring", "psum"])
def test_strategy_ragged_sizes(mesh8, strategy):
    # sizes not divisible by n exercise the ring's padding path
    rng = np.random.RandomState(1)
    vals = rng.randn(8, 13).astype(np.float32)  # 13 not divisible by 8
    out = _run_exchange(mesh8, strategy, jnp.asarray(vals))
    for i in range(8):
        np.testing.assert_allclose(out[i], vals.mean(axis=0), rtol=1e-5, atol=1e-5)


def test_exchange_identity_on_single_device_mesh():
    from theanompi_tpu.parallel.mesh import make_mesh

    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    ex = Exchanger()

    def f(t):
        out = ex.exchange(jax.tree.map(lambda a: a[0], t))
        return jax.tree.map(lambda a: a[None], out)

    tree = {"a": jnp.ones((1, 2)), "b": [jnp.zeros((1, 3))]}
    out = shard_map(f, mesh1, P(DATA_AXIS), P(DATA_AXIS), check=False)(tree)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.ones((1, 2)))


def test_exchange_outside_mapped_context_raises():
    ex = Exchanger()
    with pytest.raises(ValueError, match="inside shard_map"):
        ex.exchange({"a": jnp.ones((2,))})


def test_int_leaves_pass_through_unreduced(mesh8):
    # opt-state pytrees may carry int step counters; exchange must not
    # mean-reduce them into floats
    ex = Exchanger(strategy="psum")

    def f(t):
        local = jax.tree.map(lambda a: a[0], t)
        return jax.tree.map(lambda a: a[None], ex.exchange(local))

    out = shard_map(
        f, mesh=mesh8, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS), check=False
    )({"w": jnp.ones((8, 2)), "step": jnp.full((8, 1), 7, jnp.int32)})
    assert out["step"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out["step"]).ravel(), 7)


def test_unknown_strategy_raises():
    with pytest.raises(ValueError):
        Exchanger(strategy="warp_drive")


def test_bf16_strategy_halves_error_not_correctness(mesh8):
    # all-equal inputs: bf16 path must be exact
    vals = jnp.full((8, 4), 3.0, jnp.float32)
    out = _run_exchange(mesh8, "psum_bf16", vals)
    np.testing.assert_allclose(out, 3.0)


def test_exchanger_inside_jit_grad_pipeline(mesh8):
    """End-to-end shape: per-device grads -> exchange -> identical updates."""
    n = 8
    ex = Exchanger(strategy="psum")

    def per_device_loss(w, x):
        return jnp.sum((x @ w) ** 2)

    def step(w, x):
        g = jax.grad(per_device_loss)(w[0], x)
        g = ex.exchange(g)
        return (w[0] - 0.1 * g)[None]

    rng = np.random.RandomState(2)
    w = jnp.asarray(np.tile(rng.randn(1, 4, 2).astype(np.float32), (8, 1, 1)))
    x = jnp.asarray(rng.randn(8 * 3, 4).astype(np.float32))

    f = jax.jit(
        shard_map(
            step, mesh=mesh8,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
            check=False,
        )
    )
    w_new = np.asarray(f(w, x.reshape(8, 3, 4).reshape(24, 4)))
    # every replica must hold the same updated params
    for i in range(1, 8):
        np.testing.assert_allclose(w_new[i], w_new[0], rtol=1e-6, atol=1e-6)
