"""Exchanger strategy tests: every strategy must compute the cross-replica mean.

Reference parity target (SURVEY.md §2.1): BSP_Exchanger.exchange() averaging
worker gradients; strategies ar/asa32/asa16/nccl32/nccl16 → psum/ring/…bf16.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from theanompi_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.exchanger import (
    BUCKETED_STRATEGIES,
    STRATEGIES,
    Exchanger,
    _bucket_layout,
    fused_pmean,
)
from theanompi_tpu.parallel.mesh import DATA_AXIS

#: every strategy whose plain exchange() computes a mean (zero1 fuses the
#: exchange into the optimizer update — covered by the train-step matrix;
#: 'none' deliberately skips the mean, see test_scaling.py)
MEAN_STRATEGIES = sorted(
    (set(STRATEGIES) | set(BUCKETED_STRATEGIES)) - {"none", "zero1"}
)

#: documented numeric tolerance per wire format: fp32 strategies are
#: float-round-off; bf16 accumulates ~O(n) wire-dtype rounding; int8's
#: per-chunk-scale stochastic rounding error is zero-mean with worst case
#: ~hops x max|partial|/127/n (measured ~5e-3 on the randn config below)
TOL = {"bf16": 1e-2, "int8": 5e-2, "fp32": 1e-6}


def _tol(strategy: str) -> float:
    if "int8" in strategy:
        return TOL["int8"]
    return TOL["bf16"] if "bf16" in strategy else TOL["fp32"]


def _run_exchange(mesh, strategy, per_device_vals):
    """per_device_vals: [n, ...] array; returns exchanged per-device output."""
    n = mesh.shape[DATA_AXIS]
    ex = Exchanger(strategy=strategy)

    def f(x):
        return jax.tree.map(lambda a: a[0], ex.exchange({"g": x}))["g"][None]

    out = shard_map(
        f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
        check=False,
    )(per_device_vals)
    return np.asarray(out)


@pytest.mark.parametrize("strategy", MEAN_STRATEGIES)
def test_strategy_computes_mean(mesh4, strategy):
    """Every strategy computes the cross-replica mean — AND leaves every
    replica bit-identical (for ring_int8 that is a designed property: the
    all-gather circulates each owner's quantized payload verbatim, so
    dequantization cannot drift across devices)."""
    rng = np.random.RandomState(0)
    vals = rng.randn(4, 3, 5).astype(np.float32)
    out = _run_exchange(mesh4, strategy, jnp.asarray(vals))
    expect = vals.mean(axis=0)
    tol = _tol(strategy)
    for i in range(4):
        np.testing.assert_allclose(out[i], expect, rtol=tol, atol=tol)
    for i in range(1, 4):
        np.testing.assert_array_equal(out[i], out[0])


@pytest.mark.parametrize("strategy", ["ring", "psum", "ring_int8"])
def test_strategy_ragged_sizes(mesh4, strategy):
    # sizes not divisible by n exercise the ring/bucket padding paths
    rng = np.random.RandomState(1)
    vals = rng.randn(4, 13).astype(np.float32)  # 13 not divisible by 4
    out = _run_exchange(mesh4, strategy, jnp.asarray(vals))
    tol = max(_tol(strategy), 1e-5)
    for i in range(4):
        np.testing.assert_allclose(out[i], vals.mean(axis=0), rtol=tol, atol=tol)


def test_exchange_identity_on_single_device_mesh():
    from theanompi_tpu.parallel.mesh import make_mesh

    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    ex = Exchanger()

    def f(t):
        out = ex.exchange(jax.tree.map(lambda a: a[0], t))
        return jax.tree.map(lambda a: a[None], out)

    tree = {"a": jnp.ones((1, 2)), "b": [jnp.zeros((1, 3))]}
    out = shard_map(f, mesh1, P(DATA_AXIS), P(DATA_AXIS), check=False)(tree)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.ones((1, 2)))


def test_exchange_outside_mapped_context_raises():
    ex = Exchanger()
    with pytest.raises(ValueError, match="inside shard_map"):
        ex.exchange({"a": jnp.ones((2,))})


def test_int_leaves_pass_through_unreduced(mesh8):
    # opt-state pytrees may carry int step counters; exchange must not
    # mean-reduce them into floats
    ex = Exchanger(strategy="psum")

    def f(t):
        local = jax.tree.map(lambda a: a[0], t)
        return jax.tree.map(lambda a: a[None], ex.exchange(local))

    out = shard_map(
        f, mesh=mesh8, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS), check=False
    )({"w": jnp.ones((8, 2)), "step": jnp.full((8, 1), 7, jnp.int32)})
    assert out["step"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out["step"]).ravel(), 7)


def test_unknown_strategy_raises():
    with pytest.raises(ValueError):
        Exchanger(strategy="warp_drive")


def test_bf16_strategy_halves_error_not_correctness(mesh8):
    # all-equal inputs: bf16 path must be exact
    vals = jnp.full((8, 4), 3.0, jnp.float32)
    out = _run_exchange(mesh8, "psum_bf16", vals)
    np.testing.assert_allclose(out, 3.0)


# -- bucket layout (ISSUE 2 tentpole) ----------------------------------------

def test_bucket_layout_greedy_dtype_grouped():
    """Layout unit: dtype grouping, greedy fill, oversized-leaf bucket,
    int passthrough, padding to a multiple of n — all host-side."""
    leaves = [
        np.zeros((100,), np.float32),   # 400 B
        np.zeros((100,), np.float32),   # 400 B -> same bucket (800 <= 1024)
        np.zeros((100,), np.float32),   # would overflow -> new bucket
        np.zeros((1000,), np.float32),  # oversized (4000 B > 1024): own bucket
        jnp.zeros((10,), jnp.bfloat16),  # separate dtype group
        np.zeros((), np.int32),         # non-inexact: not bucketed at all
    ]
    buckets = _bucket_layout(leaves, bucket_bytes=1024, n=8)
    by_dtype = {}
    for b in buckets:
        by_dtype.setdefault(str(np.dtype(b.dtype)), []).append(b)
    f32 = by_dtype["float32"]
    assert [list(b.indices) for b in f32] == [[0, 1], [2], [3]]
    assert all(b.padded % 8 == 0 and b.padded >= b.elems for b in buckets)
    assert [list(b.indices) for b in by_dtype["bfloat16"]] == [[4]]
    assert not any(5 in b.indices for b in buckets)


def test_bucketed_exchange_splits_on_bucket_size(mesh4):
    """A small bucket_bytes must split the tree into several fused buckets
    and still compute the exact mean."""
    rng = np.random.RandomState(5)
    host = {f"w{i}": rng.randn(4, 11).astype(np.float32) for i in range(6)}
    ex = Exchanger(strategy="psum_bucket", bucket_bytes=2 * 11 * 4)  # 2 leaves

    def f(t):
        local = jax.tree.map(lambda a: a[0], t)
        return jax.tree.map(lambda a: a[None], ex.exchange(local))

    out = shard_map(f, mesh4, P(DATA_AXIS), P(DATA_AXIS), check=False)(
        jax.tree.map(jnp.asarray, host))
    layout = _bucket_layout(
        [jax.ShapeDtypeStruct((11,), np.float32)] * 6, 2 * 11 * 4, 4)
    assert len(layout) == 3
    for k, v in host.items():
        np.testing.assert_allclose(np.asarray(out[k])[0], v.mean(axis=0),
                                   rtol=1e-6, atol=1e-6)


def test_fused_pmean_matches_leafwise(mesh4):
    """fused_pmean == per-leaf lax.pmean for floats; ints pass through."""
    rng = np.random.RandomState(6)
    host = {"a": rng.randn(4, 4).astype(np.float32),
            "b": rng.randn(4, 3, 2).astype(np.float32),
            "c": rng.randn(4).astype(np.float32),
            "n": np.full((4, 1), 3, np.int32)}

    def f(t):
        local = jax.tree.map(lambda a: a[0], t)
        return jax.tree.map(lambda a: a[None], fused_pmean(local, DATA_AXIS))

    out = shard_map(f, mesh4, P(DATA_AXIS), P(DATA_AXIS), check=False)(
        jax.tree.map(jnp.asarray, host))
    for k in ("a", "b", "c"):
        np.testing.assert_allclose(np.asarray(out[k])[0],
                                   host[k].mean(axis=0), rtol=1e-6, atol=1e-6)
    assert out["n"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out["n"]).ravel(), 3)


# -- wire-byte invariants (ISSUE 2 satellite) --------------------------------

@pytest.mark.parametrize("strategy,num,den", [
    ("psum", 1, 1),
    ("psum_bucket", 1, 1),
    ("ring", 1, 1),
    ("ring_bucket", 1, 1),
    ("zero1", 1, 1),          # reduce-scatter grads + all-gather params
    ("psum_bf16", 1, 2),      # bf16 wire: exactly half
    ("psum_bf16_bucket", 1, 2),
    ("ring_bf16", 1, 2),
    ("ring_bf16_bucket", 1, 2),
    ("ring_int8", 1, 4),      # int8 wire: exactly a quarter
])
def test_wire_bytes_compression_invariants(strategy, num, den):
    """The EXACT byte ratios vs psum the accounting contract documents —
    including under element counts the ring factor floors (the 33-element
    leaf) and with an int leaf that must not be counted at all."""
    tree = {"w": np.zeros((64, 32), np.float32),
            "b": np.zeros((33,), np.float32),
            "step": np.zeros((), np.int32)}
    base = Exchanger("psum").wire_bytes(tree, 8)
    got = Exchanger(strategy).wire_bytes(tree, 8)
    assert base > 0 and got * den == base * num
    # single worker: nothing on the wire for any strategy
    assert Exchanger(strategy).wire_bytes(tree, 1) == 0


def test_zero1_wire_bytes_matches_scatter_gather_arithmetic():
    """zero1 = (n-1)/n of the grad buckets out + (n-1)/n of the param
    buckets back, both fp32 — the sum IS psum's 2(n-1)/n ring total."""
    n = 8
    tree = {"w": np.zeros((1000,), np.float32)}
    elems = 1000
    scatter = (n - 1) * elems // n * 4
    gather = (n - 1) * elems // n * 4
    assert Exchanger("zero1").wire_bytes(tree, n) == scatter + gather


# -- full-train-step strategy equivalence (ISSUE 2 acceptance) ---------------
# The two-step runs live in conftest's session-scoped ``exchange_run``
# fixture (ISSUE 12 satellite, ROADMAP item 4): the fused-vs-overlapped
# bit-equality locks in test_overlap.py share these baselines instead of
# retraining them per module.


@pytest.fixture(scope="module")
def psum_two_step_params(mesh4, exchange_run):
    return exchange_run(mesh4, "psum")[1]


@pytest.mark.parametrize("strategy", ["psum_bucket", "ring_int8"])
def test_train_step_matches_psum(mesh4, exchange_run, psum_two_step_params,
                                 strategy):
    """Acceptance: the new strategies' full BSP train step matches psum
    numerics on the 4-device CPU mesh within the documented tolerance
    (fp32 bucket layouts are reduction-order-identical — near-bit-exact;
    int8 carries its wire-format rounding).  The bf16/ring bucket variants'
    numerics are covered at exchange level by the mean matrix above —
    their train-step plumbing is identical to psum_bucket's."""
    _, got = exchange_run(mesh4, strategy)
    tol = _tol(strategy)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(psum_two_step_params)):
        np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


# -- zero1 specifics (one shared training run) -------------------------------

@pytest.fixture(scope="module")
def zero1_run(mesh4, exchange_run):
    return exchange_run(mesh4, "zero1")


def test_zero1_train_step_matches_psum(zero1_run, psum_two_step_params):
    """Acceptance: reduce-scatter mean + shard-local update + all-gather
    reproduces the psum-exchange step (same elementwise update math)."""
    _, got = zero1_run
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(psum_two_step_params)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_zero1_opt_state_sharded_one_nth(zero1_run):
    """The ZeRO-1 claim itself: each device stores exactly 1/n of every
    momentum buffer (flat bucket layout, sharded over data)."""
    t, _ = zero1_run
    vels = t.opt_state["velocity"]
    assert isinstance(vels, list) and vels
    for vel in vels:
        assert vel.shape[0] % 4 == 0
        shards = vel.addressable_shards
        assert len({s.device for s in shards}) == 4
        for s in shards:
            assert s.data.shape[0] == vel.shape[0] // 4


def test_zero1_replicas_stay_in_sync(zero1_run):
    """All-gathered params must be identical on every device after steps."""
    t, _ = zero1_run
    leaf = jax.tree.leaves(t.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(s, shards[0])


def test_zero1_plain_exchange_raises():
    ex = Exchanger(strategy="zero1")
    with pytest.raises(ValueError, match="exchange_and_update"):
        ex.exchange({"a": jnp.ones((2,))})


def test_zero1_rejects_sharded_params(mesh4x2):
    """zero1 + tensor parallelism is refused up front, not silently wrong."""
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.utils.recorder import Recorder

    model = TransformerLM({
        "batch_size": 2, "seq_len": 8, "vocab": 64, "dim": 16, "heads": 2,
        "n_layers": 1, "dropout": 0.0, "n_train": 16, "n_val": 8,
        "precision": "fp32", "verbose": False, "attn_impl": "blockwise",
    })
    t = BSPTrainer(model, mesh=mesh4x2, exch_strategy="zero1",
                   recorder=Recorder(verbose=False))
    with pytest.raises(ValueError, match="replicated"):
        t.compile_iter_fns()


def test_single_ring_strategies_reject_multi_axis():
    for strategy in ("ring", "ring_bucket", "ring_int8", "zero1"):
        with pytest.raises(ValueError, match="single ring"):
            Exchanger(strategy=strategy, axis_name=("data", "seq"))
    # the psum family (leaf-wise AND bucketed) accepts axis tuples
    for strategy in ("psum", "psum_bucket", "psum_bf16_bucket"):
        Exchanger(strategy=strategy, axis_name=("data", "seq"))


def test_exchanger_inside_jit_grad_pipeline(mesh8):
    """End-to-end shape: per-device grads -> exchange -> identical updates."""
    n = 8
    ex = Exchanger(strategy="psum")

    def per_device_loss(w, x):
        return jnp.sum((x @ w) ** 2)

    def step(w, x):
        g = jax.grad(per_device_loss)(w[0], x)
        g = ex.exchange(g)
        return (w[0] - 0.1 * g)[None]

    rng = np.random.RandomState(2)
    w = jnp.asarray(np.tile(rng.randn(1, 4, 2).astype(np.float32), (8, 1, 1)))
    x = jnp.asarray(rng.randn(8 * 3, 4).astype(np.float32))

    f = jax.jit(
        shard_map(
            step, mesh=mesh8,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
            check=False,
        )
    )
    w_new = np.asarray(f(w, x.reshape(8, 3, 4).reshape(24, 4)))
    # every replica must hold the same updated params
    for i in range(1, 8):
        np.testing.assert_allclose(w_new[i], w_new[0], rtol=1e-6, atol=1e-6)
