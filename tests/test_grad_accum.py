"""Gradient accumulation (``n_subb`` — reference contract SURVEY.md §2.3:
file-batches trained in sub-batches with cumulative gradients).

The core claim is exactness: with per-example normalization the
micro-batched scan's mean gradient IS the full-batch gradient, so training
with ``n_subb`` must reproduce full-batch training step for step.
"""

import numpy as np
import pytest

import jax

from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.models.wide_resnet import WideResNet
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import make_mesh

TLM_CFG = {
    "batch_size": 8, "n_train": 64, "n_val": 32, "seq_len": 64,
    "vocab": 64, "dim": 64, "heads": 2, "n_layers": 2, "dropout": 0.0,
    "n_epochs": 1, "precision": "fp32", "attn_impl": "blockwise",
}


def _trained_params(cfg, steps=3):
    model = TransformerLM(cfg)
    t = BSPTrainer(model, mesh=make_mesh(n_data=1,
                                         devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
    m = None
    for i in range(steps):
        m = t.train_iter(batches[i % len(batches)], lr=1e-2)
    return t.params, m


def test_accumulated_equals_full_batch():
    """n_subb=4 ≡ n_subb=1 on an LN-only model (exact up to fp assoc)."""
    p_full, m_full = _trained_params(dict(TLM_CFG))
    p_acc, m_acc = _trained_params({**TLM_CFG, "n_subb": 4})
    np.testing.assert_allclose(float(m_acc["cost"]), float(m_full["cost"]),
                               rtol=1e-5)
    flat_f = jax.tree_util.tree_leaves_with_path(p_full)
    flat_a = {tuple(str(k) for k in path): leaf
              for path, leaf in jax.tree_util.tree_leaves_with_path(p_acc)}
    for path, leaf in flat_f:
        key = tuple(str(k) for k in path)
        np.testing.assert_allclose(
            np.asarray(flat_a[key]), np.asarray(leaf),
            rtol=2e-5, atol=2e-6, err_msg=f"param {key} diverged",
        )


def test_accum_with_bn_trains(mesh8):
    """BN model: micro-batch statistics (documented semantics) — the step
    must run under the data-parallel exchange and stay finite."""
    model = WideResNet({
        "depth": 10, "widen": 1, "batch_size": 4, "n_train": 64,
        "n_val": 16, "n_epochs": 1, "precision": "fp32", "n_subb": 2,
    })
    t = BSPTrainer(model, mesh=mesh8)
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m1 = t.train_iter(batch, lr=0.05)
    m2 = t.train_iter(batch, lr=0.05)
    assert np.isfinite(float(m1["cost"])) and np.isfinite(float(m2["cost"]))


def test_indivisible_batch_raises():
    model = TransformerLM({**TLM_CFG, "n_subb": 3})  # 8 % 3 != 0
    t = BSPTrainer(model, mesh=make_mesh(n_data=1,
                                         devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    with pytest.raises(ValueError, match="n_subb"):
        t.train_iter(batch, lr=1e-2)


def test_custom_step_refuses_n_subb():
    from theanompi_tpu.models.dcgan import DCGAN

    model = DCGAN({"batch_size": 4, "n_train": 16, "n_val": 8,
                   "n_epochs": 1, "n_subb": 2})
    t = BSPTrainer(model, mesh=make_mesh(n_data=1,
                                         devices=jax.devices()[:1]))
    with pytest.raises(ValueError, match="n_subb"):
        t.compile_iter_fns()
