"""tmlint subsystem tests (ISSUE 7): per-rule fixtures, suppression
grammar, the declared layer DAG, CLI exit contract, JSON report schema,
and THE tier-1 acceptance: the full rule set runs clean over the package.

Fixture style: each rule gets synthetic sources asserting both the
firing and the non-firing case — the rule must catch its bug class AND
must not cry wolf on the idioms the repo actually uses (the conditional
``a = a.copy()`` ownership check, consumed-by-call asarray, early-return
guards above a rebinding, lazy cycle-breaking imports).
"""

import json

import pytest

from theanompi_tpu.analysis import cli, core
from theanompi_tpu.analysis import layers as L

# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def run_src(tmp_path, source, rules=None, rel="fx.py"):
    """Lint one synthetic source; -> (unsuppressed, suppressed) lists."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    findings, _ = core.lint_paths([str(path)], rules, root=str(tmp_path))
    return ([f for f in findings if not f.suppressed],
            [f for f in findings if f.suppressed])


# ---------------------------------------------------------------------------
# the tier-1 acceptance: the whole package is clean
# ---------------------------------------------------------------------------


def test_package_runs_clean_under_the_full_rule_set():
    """Zero unsuppressed findings over theanompi_tpu/ + bench.py with
    every registered rule on — the ISSUE 7 acceptance criterion.  Every
    suppression in the tree must carry its justification (the meta rule
    fires otherwise and shows up right here)."""
    findings, n_files = core.lint_paths()
    offenders = [f.format() for f in findings if not f.suppressed]
    assert n_files > 70, f"suspiciously small scan: {n_files}"
    assert not offenders, "tmlint findings in the tree:\n" + \
        "\n".join(offenders)


def test_registry_has_the_advertised_rules():
    names = set(core.all_rules())
    assert {"wall", "swallow", "np-load", "donated-escape", "host-sync",
            "jit-nondet", "exit-code", "import-dag",
            "data-determinism", "atomic-publish", "guarded-state",
            "thread-lifecycle", "lock-order"} <= names


# ---------------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------------


def test_suppression_requires_justification(tmp_path):
    active, sup = run_src(
        tmp_path, "import time\nt = time.time()  # lint: wall-ok\n")
    assert any(f.rule == "suppression" for f in active)
    assert any(f.rule == "wall" for f in active)  # bare marker = no effect
    assert not sup


def test_suppression_with_justification_is_recorded_not_silent(tmp_path):
    active, sup = run_src(
        tmp_path,
        "import time\nt = time.time()  # lint: wall-ok — epoch stamp\n")
    assert not active
    assert len(sup) == 1 and sup[0].justification == "epoch stamp"


def test_suppression_unknown_rule_is_a_finding(tmp_path):
    active, _ = run_src(
        tmp_path, "x = 1  # lint: no-such-rule-ok — because\n")
    assert any(f.rule == "suppression" and "unknown rule" in f.message
               for f in active)


def test_suppression_on_comment_block_above_counts(tmp_path):
    active, sup = run_src(
        tmp_path,
        "import time\n"
        "# lint: wall-ok — the long call below needs a stamp\n"
        "t = time.time()\n")
    assert not active and len(sup) == 1


def test_prose_mention_of_the_grammar_is_not_a_marker(tmp_path):
    """'use lint: wall-ok' mid-comment (or in a docstring) must neither
    suppress nor trip the meta rule — only a marker STARTING its comment
    counts (review fix)."""
    active, sup = run_src(
        tmp_path,
        '"""Docs may say lint: wall-ok freely."""\n'
        "import time\n"
        "t = time.perf_counter()  # to opt out, use lint: wall-ok\n"
        "w = time.time()  # silenceable via lint: wall-ok — but not here\n")
    assert not sup, sup  # the prose on line 4 does NOT suppress the wall hit
    rules_hit = {f.rule for f in active}
    assert rules_hit == {"wall"}, active  # and no `suppression` meta noise


def test_deselected_rules_still_get_marker_grammar_checks(tmp_path):
    """`--rules wall` must not hide a broken swallow-ok marker."""
    path = tmp_path / "fx.py"
    path.write_text("try:\n    x = 1\nexcept Exception:  "
                    "# lint: swallow-ok\n    pass\n")
    findings, _ = core.lint_paths([str(path)], ["wall"],
                                  root=str(tmp_path))
    assert any(f.rule == "suppression" for f in findings)


# ---------------------------------------------------------------------------
# donated-escape
# ---------------------------------------------------------------------------


def test_donated_escape_fires_on_returned_view(tmp_path):
    active, _ = run_src(
        tmp_path,
        "import numpy as np\ndef f(x):\n    return np.asarray(x)\n",
        ["donated-escape"])
    assert len(active) == 1 and active[0].rule == "donated-escape"


def test_donated_escape_fires_on_queue_and_thread_handoff(tmp_path):
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "def f(q, x, y):\n"
        "    q.put((1, np.asarray(x)))\n"
        "    a = np.asarray(y)\n"
        "    q.put(a)\n",
        ["donated-escape"])
    assert len(active) == 2, active


def test_donated_escape_respects_copy_and_the_ownership_idiom(tmp_path):
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "def direct(x):\n"
        "    return np.asarray(x).copy()\n"
        "def wrapped(x):\n"
        "    return g(np.broadcast_to(np.asarray(x), (2, 3)).copy())\n"
        "def conditional(v):\n"
        "    a = np.asarray(v)\n"
        "    if a.base is not None or not a.flags.owndata:\n"
        "        a = a.copy()\n"
        "    return a\n",
        ["donated-escape"])
    assert not active, active


def test_donated_escape_ignores_consumed_views_and_early_returns(tmp_path):
    """np.percentile(arr) returns derived data; `return x` ABOVE the
    rebinding returns the original object (the put_global regression)."""
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "def pct(xs):\n"
        "    arr = np.asarray(xs)\n"
        "    return float(np.percentile(arr, 50))\n"
        "def put(x, sharding):\n"
        "    if ready(x):\n"
        "        return x\n"
        "    x = np.asarray(x)\n"
        "    return device_put(x, sharding)\n",
        ["donated-escape"])
    assert not active, active


def test_donated_escape_fires_on_attribute_store(tmp_path):
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "def f(self, x):\n"
        "    a = np.asarray(x)\n"
        "    self.snapshot = a\n",
        ["donated-escape"])
    assert len(active) == 1, active


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

SPAN_SRC = """\
import numpy as np
def f(tel, x):
    with tel.span("train.step"):
        v = float(x)
    return v
def g(tel, x):
    with tel.span("validate"):
        acc = []
        acc.append(x)
    return float(np.asarray(x).mean())
def h(tel, x):
    s = tel.span("decode")
    with s:
        return x.item()
def cond(tel, x, nullcontext):
    with (tel.span("snap") if tel else nullcontext()):
        return np.asarray(x)
"""


def test_host_sync_fires_only_inside_spans(tmp_path):
    active, _ = run_src(tmp_path, SPAN_SRC, ["host-sync"])
    lines = sorted(f.line for f in active)
    # f: float inside span (4); g: pulls AFTER the span are clean;
    # h: .item() under a span-bound name (14); cond: asarray under the
    # conditional-span idiom (17)
    assert lines == [4, 14, 17], active


def test_host_sync_is_a_warning_and_suppressible(tmp_path):
    active, sup = run_src(
        tmp_path,
        "def f(tel, x):\n"
        "    with tel.span('serve.prefill'):\n"
        "        # lint: host-sync-ok — span measures execution by design\n"
        "        return float(x)\n",
        ["host-sync"])
    assert not active and len(sup) == 1
    assert sup[0].severity == "warning"


# ---------------------------------------------------------------------------
# jit-nondet
# ---------------------------------------------------------------------------

JIT_SRC = """\
import time
import numpy as np
import jax

@jax.jit
def decorated(x):
    return x * time.time()

def _impl(x):
    return x + np.random.randn()

step = jax.jit(_impl)

def host_side():
    return time.time()  # wall rule's business, not jit-nondet's

@jax.jit
def seeded_ok(x):
    rng = np.random.RandomState(0)
    return x
"""


def test_jit_nondet_fires_in_jitted_functions_only(tmp_path):
    active, _ = run_src(tmp_path, JIT_SRC, ["jit-nondet"])
    lines = sorted(f.line for f in active)
    assert lines == [7, 10], active  # decorated + jax.jit(_impl) form


def test_jit_nondet_guards_the_fault_plan_module(tmp_path):
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "def plan():\n"
        "    return np.random.rand()\n",
        ["jit-nondet"],
        rel="theanompi_tpu/resilience/faults.py")
    assert len(active) == 1 and "fault plan" in active[0].message


def test_jit_nondet_flags_unseeded_constructors(tmp_path):
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = np.random.default_rng()\n"
        "    b = np.random.default_rng(42)\n"
        "    return x\n",
        ["jit-nondet"])
    assert len(active) == 1 and "no seed" in active[0].message


# ---------------------------------------------------------------------------
# exit-code
# ---------------------------------------------------------------------------


def test_exit_code_fires_in_exit_contexts_only(tmp_path):
    active, _ = run_src(
        tmp_path,
        "import sys\n"
        "def f(rc):\n"
        "    if rc == 77:\n"
        "        sys.exit(75)\n"
        "    raise SystemExit(78)\n"
        "def not_an_exit_code():\n"
        "    width = 77\n"
        "    return width + 75\n",
        ["exit-code"])
    lines = sorted(f.line for f in active)
    assert lines == [3, 4, 5], active


def test_exit_code_source_module_is_exempt(tmp_path):
    active, _ = run_src(
        tmp_path,
        "EXIT_PREEMPTED = 75\nassert EXIT_PREEMPTED == 75\n",
        ["exit-code"],
        rel="theanompi_tpu/resilience/codes.py")
    assert not active, active


# ---------------------------------------------------------------------------
# data-determinism (ISSUE 10)
# ---------------------------------------------------------------------------

_DATA_REL = "theanompi_tpu/models/data/fx.py"


def test_data_determinism_fires_only_under_models_data(tmp_path):
    src = ("import numpy as np\n"
           "def order():\n"
           "    return np.random.permutation(8)\n")
    active, _ = run_src(tmp_path, src, ["data-determinism"], rel=_DATA_REL)
    assert len(active) == 1, active
    assert "np.random.permutation()" in active[0].message
    assert active[0].severity == "error"
    # the same draw OUTSIDE the data plane is this rule's non-concern
    # (jit-nondet still owns jitted scopes there)
    elsewhere, _ = run_src(tmp_path, src, ["data-determinism"],
                           rel="theanompi_tpu/parallel/fx.py")
    assert not elsewhere, elsewhere


def test_data_determinism_allows_derive_seed_keyed_randomstate(tmp_path):
    """The repo's sanctioned idiom — a RandomState keyed on
    derive_seed(..., epoch, position) — must pass untouched."""
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "from theanompi_tpu.models.data.base import derive_seed\n"
        "def order(seed, epoch):\n"
        "    rng = np.random.RandomState("
        "derive_seed('shuffle', seed, epoch))\n"
        "    return rng.permutation(8)\n",
        ["data-determinism"], rel=_DATA_REL)
    assert not active, active


def test_data_determinism_flags_unseeded_ctor_and_bare_random(tmp_path):
    """An unseeded RandomState(), global random.seed() and a bare
    random.random() draw are all order-dependent state a checkpoint
    cannot capture — each is its own finding."""
    active, _ = run_src(
        tmp_path,
        "import numpy as np\n"
        "import random\n"
        "def f():\n"
        "    rng = np.random.RandomState()\n"
        "    random.seed(0)\n"
        "    return rng, random.random()\n",
        ["data-determinism"], rel=_DATA_REL)
    lines = sorted(f.line for f in active)
    assert lines == [4, 5, 6], active
    assert any("no seed" in f.message for f in active)


# ---------------------------------------------------------------------------
# import-dag
# ---------------------------------------------------------------------------


def test_layer_dag_declaration_is_acyclic_by_construction():
    L.validate_dag()  # raises on forward refs / duplicates
    # spot-check the load-bearing assignments
    assert L.module_layer("theanompi_tpu.resilience.codes") == "codes"
    assert L.module_layer("theanompi_tpu.resilience.faults") == "resilience"
    assert L.module_layer("theanompi_tpu.telemetry.core") == "telemetry"
    assert L.module_layer("theanompi_tpu.parallel.mesh") == "mesh"
    assert L.module_layer("theanompi_tpu.parallel.trainer") == "training"
    assert L.module_layer("theanompi_tpu.serving.engine") == "serving"
    assert L.module_layer("theanompi_tpu.launcher") == "tooling"
    assert L.module_layer("theanompi_tpu.analysis.cli") == "analysis"


def test_layer_dag_rejects_forward_references(monkeypatch):
    bad = (("a", ("theanompi_tpu.a",), ("b",)),
           ("b", ("theanompi_tpu.b",), ()))
    monkeypatch.setattr(L, "LAYER_DAG", bad)
    with pytest.raises(ValueError, match="acyclic"):
        L.validate_dag()


def test_import_dag_flags_module_level_layer_violation(tmp_path):
    """telemetry is the bottom layer: a module-level mesh import fires."""
    active, _ = run_src(
        tmp_path,
        "from theanompi_tpu.parallel.mesh import DATA_AXIS\n",
        ["import-dag"],
        rel="theanompi_tpu/telemetry/bad.py")
    assert any("leaf subpackage" in f.message or "allowed set" in f.message
               for f in active), active


def test_import_dag_checks_class_body_imports(tmp_path):
    """A class-body import executes at module import time — it must obey
    the layering like any top-level import (review fix)."""
    active, _ = run_src(
        tmp_path,
        "class Sneaky:\n"
        "    from theanompi_tpu.parallel.mesh import DATA_AXIS\n",
        ["import-dag"],
        rel="theanompi_tpu/telemetry/bad.py")
    assert active, "class-body import-time dependency not checked"


def test_import_dag_allows_lazy_cycle_breaking_imports(tmp_path):
    """A function-local upward import is a deliberate lazy edge (the
    ops/opt.py idiom) — layering ignores it; only walls check deep."""
    active, _ = run_src(
        tmp_path,
        "def late():\n"
        "    from theanompi_tpu.parallel.trainer import BaseTrainer\n"
        "    return BaseTrainer\n",
        ["import-dag"],
        rel="theanompi_tpu/models/helper.py")
    assert not active, active


def test_import_dag_wall_catches_lazy_serving_import(tmp_path):
    active, _ = run_src(
        tmp_path,
        "def late():\n"
        "    from theanompi_tpu.parallel import exchanger\n"
        "    return exchanger\n",
        ["import-dag"],
        rel="theanompi_tpu/serving/bad.py")
    assert any("training machinery" in f.message for f in active), active


# ---------------------------------------------------------------------------
# CLI exit contract + JSON report schema
# ---------------------------------------------------------------------------

import os

VIOLATION_FIXTURE = os.path.join(core.REPO_ROOT, "tests", "fixtures",
                                 "tmlint_violation.py")


def test_cli_exits_nonzero_on_the_seeded_violation_file(capsys):
    rc = cli.main([VIOLATION_FIXTURE])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in ("wall", "swallow", "np-load", "donated-escape",
                 "exit-code", "suppression", "atomic-publish",
                 "thread-lifecycle"):
        assert f"[{rule}]" in out, f"seeded {rule} violation not caught"


def test_cli_exit_contract(tmp_path, capsys):
    assert cli.main(["--rules", "bogus"]) == 2
    err = capsys.readouterr().err
    assert err.startswith("tmlint: error:") and err.count("\n") == 1

    assert cli.main([str(tmp_path / "missing.py")]) == 2

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert cli.main([str(clean)]) == 0

    assert cli.main(["--no-such-flag"]) == 2  # argparse's own contract


def test_cli_report_schema(tmp_path, capsys):
    """The JSON artifact schema the runbook step publishes (LINT.json):
    version/tool/summary + per-finding keys, suppressed entries carrying
    their justification."""
    report_path = tmp_path / "LINT.json"
    rc = cli.main([VIOLATION_FIXTURE, "--report", str(report_path),
                   "--quiet"])
    assert rc == 1
    rep = json.loads(report_path.read_text())
    assert rep["version"] == 1 and rep["tool"] == "tmlint"
    assert rep["files_scanned"] == 1
    assert {r["name"] for r in rep["rules"]} == set(core.all_rules())
    for r in rep["rules"]:
        assert set(r) == {"name", "severity", "description"}
    assert rep["findings"], "seeded violations missing from the report"
    for f in rep["findings"]:
        assert set(f) == {"rule", "severity", "path", "line", "col",
                          "message", "suppressed"}
        assert f["suppressed"] is False
        assert isinstance(f["line"], int) and f["line"] > 0
    for f in rep["suppressed"]:
        assert f["suppressed"] is True and f["justification"]
    s = rep["summary"]
    assert s["errors"] == sum(f["severity"] == "error"
                              for f in rep["findings"])
    assert s["suppressed"] == len(rep["suppressed"])


def test_cli_clean_package_report(tmp_path):
    """tmlint over the package writes a findings-free report and exits 0
    — the exact runbook invocation (BASELINE.md)."""
    report_path = tmp_path / "LINT.json"
    rc = cli.main(["--report", str(report_path), "--quiet"])
    assert rc == 0
    rep = json.loads(report_path.read_text())
    assert rep["findings"] == []
    assert rep["summary"]["errors"] == 0
    assert rep["summary"]["suppressed"] > 0  # justified markers, visible


# ---------------------------------------------------------------------------
# the concurrency tier (ISSUE 15): atomic-publish / guarded-state /
# thread-lifecycle / lock-order
# ---------------------------------------------------------------------------

from theanompi_tpu.analysis import rules as R


def test_atomic_publish_flags_direct_write(tmp_path):
    active, _ = run_src(tmp_path, (
        "import json\n"
        "def publish(path, obj):\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump(obj, f)\n"), rules=["atomic-publish"])
    assert [f.rule for f in active] == ["atomic-publish"]
    assert "os.replace" in active[0].message


def test_atomic_publish_flags_append_mode(tmp_path):
    active, _ = run_src(tmp_path, (
        "def log(path, line):\n"
        "    with open(path, 'a') as f:\n"
        "        f.write(line)\n"), rules=["atomic-publish"])
    assert [f.rule for f in active] == ["atomic-publish"]
    assert "torn tail" in active[0].message


def test_atomic_publish_append_suppressible_with_justification(tmp_path):
    active, sup = run_src(tmp_path, (
        "def log(path, line):\n"
        "    # lint: atomic-publish-ok — JSONL, readers skip torn tails\n"
        "    with open(path, 'a') as f:\n"
        "        f.write(line)\n"), rules=["atomic-publish"])
    assert not active
    assert [f.rule for f in sup] == ["atomic-publish"]


def test_atomic_publish_flags_unpublished_tmp(tmp_path):
    active, _ = run_src(tmp_path, (
        "def publish(path, data):\n"
        "    with open(path + '.tmp', 'w') as f:\n"
        "        f.write(data)\n"), rules=["atomic-publish"])
    assert [f.rule for f in active] == ["atomic-publish"]
    assert "never published" in active[0].message


def test_atomic_publish_accepts_the_idiom(tmp_path):
    # direct-constant tmp suffix, name bound to a tmp expr, and f-string
    # tmp — the three spellings the package actually uses
    active, _ = run_src(tmp_path, (
        "import json, os\n"
        "def publish(path, obj):\n"
        "    with open(path + '.tmp', 'w') as f:\n"
        "        json.dump(obj, f)\n"
        "    os.replace(path + '.tmp', path)\n"
        "def publish2(path, obj):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        json.dump(obj, f)\n"
        "    os.replace(tmp, path)\n"
        "def publish3(path, obj):\n"
        "    tmp = f'{path}.tmp.{os.getpid()}'\n"
        "    with open(tmp, 'w') as f:\n"
        "        json.dump(obj, f)\n"
        "    os.replace(tmp, path)\n"), rules=["atomic-publish"])
    assert not active


def test_atomic_publish_ignores_reads_and_dynamic_modes(tmp_path):
    active, _ = run_src(tmp_path, (
        "def load(path, mode):\n"
        "    with open(path) as f:\n"
        "        a = f.read()\n"
        "    with open(path, 'r+b') as f:\n"
        "        b = f.read()\n"
        "    with open(path, mode) as f:\n"  # statically unknown: skip
        "        c = f.read()\n"
        "    return a, b, c\n"), rules=["atomic-publish"])
    assert not active


GUARDED_MIXED = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}

    def register(self, e):
        with self._lock:
            self.entries = e

    def reset(self):
        self.entries = None
"""


def test_guarded_state_flags_mixed_assignment(tmp_path):
    active, _ = run_src(tmp_path, GUARDED_MIXED, rules=["guarded-state"])
    assert [f.rule for f in active] == ["guarded-state"]
    assert "entries" in active[0].message
    # the flagged site is the UNGUARDED one (reset), not register
    assert active[0].line == GUARDED_MIXED.splitlines().index(
        "        self.entries = None") + 1


def test_guarded_state_init_is_exempt(tmp_path):
    active, _ = run_src(tmp_path, (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.v = 0\n"
        "    def set(self, v):\n"
        "        with self._lock:\n"
        "            self.v = v\n"), rules=["guarded-state"])
    assert not active


def test_guarded_state_ignores_lockless_classes(tmp_path):
    active, _ = run_src(tmp_path, (
        "class Plain:\n"
        "    def a(self):\n"
        "        self.v = 1\n"
        "    def b(self):\n"
        "        self.v = 2\n"), rules=["guarded-state"])
    assert not active


def test_guarded_state_helper_called_under_lock_counts_guarded(tmp_path):
    # the EventSink._rotate idiom: a helper whose every call site holds
    # the lock assigns state without a lexical with — not a finding
    active, _ = run_src(tmp_path, (
        "import threading\n"
        "class Sink:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.size = 0\n"
        "    def emit(self, n):\n"
        "        with self._lock:\n"
        "            self.size += n\n"
        "            if self.size > 10:\n"
        "                self._rotate()\n"
        "    def _rotate(self):\n"
        "        self.size = 0\n"), rules=["guarded-state"])
    assert not active


def test_guarded_state_helper_with_unlocked_call_site_fires(tmp_path):
    # one call site outside the lock disqualifies the helper — ambiguity
    # is the bug
    active, _ = run_src(tmp_path, (
        "import threading\n"
        "class Sink:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.size = 0\n"
        "    def emit(self, n):\n"
        "        with self._lock:\n"
        "            self.size += n\n"
        "            self._rotate()\n"
        "    def close(self):\n"
        "        self._rotate()\n"
        "    def _rotate(self):\n"
        "        self.size = 0\n"), rules=["guarded-state"])
    assert [f.rule for f in active] == ["guarded-state"]


def test_thread_lifecycle_flags_unnamed_thread(tmp_path):
    active, _ = run_src(tmp_path, (
        "import threading\n"
        "def go(fn):\n"
        "    t = threading.Thread(target=fn, daemon=True)\n"
        "    t.start()\n"
        "    return t\n"), rules=["thread-lifecycle"])
    assert [f.rule for f in active] == ["thread-lifecycle"]
    assert "unnamed" in active[0].message


def test_thread_lifecycle_accepts_named_daemon(tmp_path):
    active, _ = run_src(tmp_path, (
        "import threading\n"
        "def go(fn):\n"
        "    t = threading.Thread(target=fn, name='seam', daemon=True)\n"
        "    t.start()\n"
        "    return t\n"), rules=["thread-lifecycle"])
    assert not active


def test_thread_lifecycle_nondaemon_needs_a_join(tmp_path):
    src = (
        "import threading\n"
        "import os\n"
        "def go(fn, d):\n"
        "    p = os.path.join(d, 'x')\n"  # not a thread join
        "    t = threading.Thread(target=fn, name='seam')\n"
        "    t.start()\n"
        "    return t, p\n")
    active, _ = run_src(tmp_path, src, rules=["thread-lifecycle"])
    assert [f.rule for f in active] == ["thread-lifecycle"]
    assert "non-daemon" in active[0].message
    active, _ = run_src(tmp_path, src + (
        "def wait(t):\n"
        "    t.join()\n"), rules=["thread-lifecycle"])
    assert not active


def test_lock_order_dag_declaration_is_valid():
    R.validate_lock_order()  # the shipped declaration must parse


def test_lock_order_rejects_forward_references():
    with pytest.raises(ValueError):
        R.validate_lock_order((
            ("outer", ("pkg/a.py", "_lock"), ("inner",), False),
            ("inner", ("pkg/b.py", "_lock"), (), False),
        ))


_TEST_LOCK_DAG = (
    # prefix matches run_src's synthetic fixture path
    ("inner", ("fx.py", "_inner"), (), False),
    ("outer", ("fx.py", "_outer"), ("inner",), True),
)


def test_lock_order_flags_undeclared_nesting(tmp_path, monkeypatch):
    monkeypatch.setattr(R, "LOCK_ORDER_DAG", _TEST_LOCK_DAG)
    active, _ = run_src(tmp_path, (
        "class C:\n"
        "    def bad(self):\n"
        "        with self._inner:\n"
        "            with self._outer:\n"  # inner->outer: not declared
        "                pass\n"), rules=["lock-order"])
    assert [f.rule for f in active] == ["lock-order"]
    assert "LOCK_ORDER_DAG" in active[0].message


def test_lock_order_accepts_declared_nesting(tmp_path, monkeypatch):
    monkeypatch.setattr(R, "LOCK_ORDER_DAG", _TEST_LOCK_DAG)
    active, _ = run_src(tmp_path, (
        "class C:\n"
        "    def ok(self):\n"
        "        with self._outer:\n"
        "            with self._inner:\n"
        "                pass\n"
        "    def multi(self):\n"
        "        with self._outer, self._inner:\n"  # left-to-right
        "            pass\n"), rules=["lock-order"])
    assert not active


def test_lock_order_multi_item_with_is_ordered(tmp_path, monkeypatch):
    monkeypatch.setattr(R, "LOCK_ORDER_DAG", _TEST_LOCK_DAG)
    active, _ = run_src(tmp_path, (
        "class C:\n"
        "    def bad(self):\n"
        "        with self._inner, self._outer:\n"
        "            pass\n"), rules=["lock-order"])
    assert [f.rule for f in active] == ["lock-order"]


def test_lock_order_self_deadlock_vs_reentrant(tmp_path, monkeypatch):
    monkeypatch.setattr(R, "LOCK_ORDER_DAG", _TEST_LOCK_DAG)
    active, _ = run_src(tmp_path, (
        "class C:\n"
        "    def bad(self):\n"
        "        with self._inner:\n"
        "            with self._inner:\n"  # non-reentrant: deadlock
        "                pass\n"
        "    def ok(self):\n"
        "        with self._outer:\n"
        "            with self._outer:\n"  # declared reentrant (RLock)
        "                pass\n"), rules=["lock-order"])
    assert len(active) == 1
    assert "self-deadlock" in active[0].message


def test_lock_order_nested_def_resets_lexical_scope(tmp_path, monkeypatch):
    # a closure defined inside a with-block runs on its caller's
    # schedule, not under the enclosing lock — no finding
    monkeypatch.setattr(R, "LOCK_ORDER_DAG", _TEST_LOCK_DAG)
    active, _ = run_src(tmp_path, (
        "class C:\n"
        "    def ok(self):\n"
        "        with self._inner:\n"
        "            def cb():\n"
        "                with self._outer:\n"
        "                    pass\n"
        "            return cb\n"), rules=["lock-order"])
    assert not active
