"""ISSUE 16 step-time attribution profiler.

Synthetic half: pure-python interval streams with fake clocks drive the
exact attributor — claim precedence, no-double-count, serve segments,
the streaming fold, the profile-window parser and the None-safe
per-device memory path (no XLA compiles).  The ``data:stall`` test
drives the *real* fault site in ``read_with_retry`` (timed release, the
post-release ``FaultInjected`` is the documented contract) through a
profile-enabled ``Telemetry`` and asserts the stall lands in the
``data`` segment of ``ATTRIB.json``.

Integration half (module-scoped, one compile): a 5-step BSP/psum CPU run
with a mid-run checkpoint cadence must publish an ``ATTRIB.json`` whose
checkpoint saves land in the ``checkpoint`` segment, whose segments sum
to within 10% of the measured step wall time (the partition is exact by
construction — the bound is the acceptance criterion's), and whose
spans round-trip through the Chrome-trace export.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from theanompi_tpu import BSP
from theanompi_tpu.models.data.base import (
    read_with_retry,
    release_data_stalls,
    set_data_hooks,
)
from theanompi_tpu.resilience.faults import FaultInjected, FaultPlan
from theanompi_tpu.telemetry import (
    StepAttributor,
    Telemetry,
    attribute_events,
    parse_profile_window,
    per_device_memory_stats,
    read_attrib,
    read_events,
    sink_files,
)
from theanompi_tpu.telemetry import profile as profile_mod
from theanompi_tpu.telemetry.metrics import (
    ATTR_GAUGE_BY_SEGMENT,
    ATTR_GAUGES,
    PROF_GAUGES,
    device_memory_stats,
)
from theanompi_tpu.telemetry.profile import (
    attribute_rank_events,
    format_attribution,
)


def _span(name, ts, dur, tid=1, rank=0, **tags):
    return {"kind": "span", "name": name, "ts": ts, "dur": dur,
            "tid": tid, "rank": rank, **tags}


def _instant(name, ts, tid=1, rank=0, **tags):
    return {"kind": "instant", "name": name, "ts": ts, "tid": tid,
            "rank": rank, **tags}


def _train_steps(n, t0=100.0, step_s=0.1, data_s=0.02, comm_s=0.01):
    """n steps of the real emission shape: recorder.wait wrapping a
    prefetch.dequeue (nested), then a train.step, then exchange.overlap."""
    events = []
    t = t0
    for _ in range(n):
        events.append(_span("recorder.wait", t, data_s))
        events.append(_span("prefetch.dequeue", t + 0.001,
                            data_s - 0.002))  # nests inside the wait
        t += data_s
        events.append(_span("train.step", t, step_s))
        t += step_s
        events.append(_span("exchange.overlap", t, comm_s))
        t += comm_s
    return events


# -- profile_window rule key --------------------------------------------------

def test_parse_profile_window_forms():
    assert parse_profile_window(None) == (10, 20)
    assert parse_profile_window(None, default=(3, 7)) == (3, 7)
    assert parse_profile_window((5, 9)) == (5, 9)
    assert parse_profile_window([5, 9]) == (5, 9)
    # the launcher's --rule-set string forms
    assert parse_profile_window("10:20") == (10, 20)
    assert parse_profile_window("10-20") == (10, 20)
    assert parse_profile_window("10,20") == (10, 20)


@pytest.mark.parametrize("bad", ["10", "1:2:3", (3,), (9, 5), 7])
def test_parse_profile_window_rejects(bad):
    with pytest.raises((ValueError, TypeError)):
        parse_profile_window(bad)


# -- exact attribution (synthetic streams) ------------------------------------

def test_train_partition_is_exact():
    """Segments partition the window: sum == window to float precision,
    and the nested dequeue is not double-charged (union, not sum)."""
    events = _train_steps(3, data_s=0.01)
    res = attribute_rank_events(events)
    assert res["mode"] == "train" and res["steps"] == 3
    total = sum(s["total_s"] for s in res["segments"].values())
    assert total == pytest.approx(res["window_s"], abs=1e-6)
    # recorder.wait (0.01) contains prefetch.dequeue (0.008): union is
    # 0.01/step, not 0.018
    assert res["segments"]["data"]["total_s"] == pytest.approx(
        3 * 0.01, abs=1e-6)


def test_claim_precedence_comm_wins_overlap():
    """exchange.overlap inside the fenced step: comm claims it, compute
    gets the remainder — nothing is counted twice."""
    events = [
        _span("train.step", 100.0, 0.1),
        _span("exchange.overlap", 100.06, 0.03),  # inside the step
    ]
    res = attribute_rank_events(events)
    segs = res["segments"]
    assert segs["comm"]["total_s"] == pytest.approx(0.03, abs=1e-6)
    assert segs["compute"]["total_s"] == pytest.approx(0.07, abs=1e-6)
    assert sum(s["total_s"] for s in segs.values()) == pytest.approx(
        res["window_s"], abs=1e-6)


def test_checkpoint_and_validate_segments():
    events = _train_steps(2)
    end = max(e["ts"] + e["dur"] for e in events)
    events.append(_span("checkpoint.snapshot", end + 0.005, 0.04))
    events.append(_span("validate", end + 0.05, 0.06))
    events.extend(_train_steps(1, t0=end + 0.12))
    res = attribute_rank_events(events)
    assert res["segments"]["checkpoint"]["total_s"] == pytest.approx(
        0.04, abs=1e-6)
    assert res["segments"]["validate"]["total_s"] == pytest.approx(
        0.06, abs=1e-6)


def test_async_checkpoint_writer_thread_not_charged():
    """checkpoint.write on the writer thread overlaps training and must
    not be billed; a main-thread write (sync mode) is."""
    events = _train_steps(3)
    events.append(_span("checkpoint.write", 100.05, 0.2, tid=2))
    res = attribute_rank_events(events)
    assert res["segments"].get("checkpoint", {}).get("total_s", 0.0) == 0.0
    events.append(_span("checkpoint.snapshot", 100.02, 0.015, tid=1))
    res = attribute_rank_events(events)
    assert res["segments"]["checkpoint"]["total_s"] == pytest.approx(
        0.015, abs=1e-6)


def test_host_gap_is_remainder():
    events = [
        _span("train.step", 100.0, 0.1),
        _span("train.step", 100.5, 0.1),  # 0.4s unattributed gap
    ]
    res = attribute_rank_events(events)
    assert res["segments"]["host"]["total_s"] == pytest.approx(
        0.4, abs=1e-6)
    assert res["dominant"]["segment"] == "host"
    assert res["dominant"]["verdict"] == "host-bound"


def test_serve_segments_and_rollout_swap():
    events = [
        _span("serve.prefill", 100.0, 0.05),
        _span("serve.decode", 100.05, 0.1),
        # 0.3s gap holding a rollout instant -> rollout_swap
        _instant("serve.rollout", 100.30),
        _span("serve.decode", 100.45, 0.1),
        # 0.05s quiet gap -> queue_wait
        _span("serve.prefill", 100.60, 0.02),
        _span("serve.decode", 100.62, 0.1),
    ]
    res = attribute_rank_events(events)
    assert res["mode"] == "serve"
    segs = res["segments"]
    assert segs["prefill"]["total_s"] == pytest.approx(0.07, abs=1e-6)
    assert segs["decode"]["total_s"] == pytest.approx(0.3, abs=1e-6)
    assert segs["rollout_swap"]["total_s"] == pytest.approx(0.3, abs=1e-6)
    assert segs["queue_wait"]["total_s"] == pytest.approx(0.05, abs=1e-6)
    assert sum(s["total_s"] for s in segs.values()) == pytest.approx(
        res["window_s"], abs=1e-6)


def test_idle_stream_attributes_to_none():
    assert attribute_rank_events([_instant("train.boundary", 1.0)]) is None
    assert attribute_rank_events([]) is None


def test_attribute_events_splits_ranks():
    events = _train_steps(2) + [
        {**e, "rank": 1} for e in _train_steps(3, t0=200.0)]
    per_rank = attribute_events(events)
    assert set(per_rank) == {"0", "1"}
    assert per_rank["0"]["steps"] == 2 and per_rank["1"]["steps"] == 3


def test_format_attribution_table():
    table = format_attribution(attribute_events(_train_steps(3)))
    assert "rank 0" in table and "[train]" in table
    assert "verdict:" in table and "sum" in table
    for seg in ("data", "compute", "comm", "host"):
        assert seg in table


# -- streaming attributor -----------------------------------------------------

def test_streaming_fold_matches_exact(tmp_path, monkeypatch):
    """Folding every 64 events must agree with the one-shot attribution
    on segment totals (the fold is the same math applied piecewise)."""
    monkeypatch.setattr(profile_mod, "_FOLD_EVENTS", 64)
    events = _train_steps(100)
    exact = attribute_rank_events(events)
    attr = StepAttributor(str(tmp_path))
    for e in events:
        attr.observe(e)
    res = attr.result()
    assert res["steps"] == exact["steps"]
    assert res["window_s"] == pytest.approx(exact["window_s"], rel=0.02)
    for seg in ("data", "compute", "comm"):
        assert res["segments"][seg]["total_s"] == pytest.approx(
            exact["segments"][seg]["total_s"], rel=0.02)


def test_attributor_ignores_non_timeline_events(tmp_path):
    attr = StepAttributor(str(tmp_path))
    attr.observe({"kind": "gauge", "name": "x", "ts": 1.0, "value": 2.0,
                  "rank": 0})
    attr.observe({"kind": "counter", "name": "y", "ts": 1.0, "value": 1.0,
                  "total": 1.0, "rank": 0})
    assert attr.result() is None
    assert attr.gauges() == {}


def test_attributor_gauges_use_registered_names(tmp_path):
    attr = StepAttributor(str(tmp_path))
    for e in _train_steps(4):
        attr.observe(e)
    gauges = attr.gauges()
    assert gauges, "no gauges after 4 steps"
    assert set(gauges) <= set(ATTR_GAUGES)
    assert ATTR_GAUGE_BY_SEGMENT["step"] in gauges
    assert gauges[ATTR_GAUGE_BY_SEGMENT["compute"]] == pytest.approx(
        100.0, rel=0.05)  # 0.1s steps -> ~100ms p50


def test_attrib_json_atomic_write_and_read(tmp_path):
    attr = StepAttributor(str(tmp_path))
    for e in _train_steps(3):
        attr.observe(e)
    path = attr.write()
    assert path and os.path.basename(path) == "ATTRIB.json"
    assert not [p for p in os.listdir(str(tmp_path)) if ".tmp." in p]
    data = read_attrib(str(tmp_path))
    assert data["per_rank"]["0"]["steps"] == 3
    assert StepAttributor(str(tmp_path / "empty")).write() is None
    assert read_attrib(str(tmp_path / "empty")) is None


# -- per-device memory (None-safe CPU path) -----------------------------------

def test_per_device_memory_stats_cpu_safe():
    stats = per_device_memory_stats()
    assert isinstance(stats, dict)
    for dev, st in stats.items():
        assert isinstance(dev, int) and isinstance(st, dict)
    legacy = device_memory_stats()
    assert legacy is None or isinstance(legacy, dict)
    if not stats:
        assert legacy is None
    # the attributor's sampler never raises on a backend without stats
    gauges = StepAttributor(".").sample_memory()
    assert set(gauges) <= set(PROF_GAUGES)


def test_sample_memory_tracks_watermarks(tmp_path, monkeypatch):
    readings = iter([
        {0: {"bytes_in_use": 100, "peak_bytes_in_use": 150,
             "bytes_limit": 1000},
         1: {"bytes_in_use": 90, "peak_bytes_in_use": 95,
             "bytes_limit": 900}},
        {0: {"bytes_in_use": 50, "peak_bytes_in_use": 120,
             "bytes_limit": 1000},
         1: {"bytes_in_use": 200, "peak_bytes_in_use": 210,
             "bytes_limit": 900}},
    ])
    monkeypatch.setattr(profile_mod, "per_device_memory_stats",
                        lambda: next(readings))
    attr = StepAttributor(str(tmp_path))
    attr.sample_memory()
    gauges = attr.sample_memory()
    # peak is the running max across samples (device 1 hit 210); the
    # limit gauge is the tightest device's
    assert gauges[PROF_GAUGES[0]] == 210.0
    assert gauges[PROF_GAUGES[1]] == 200.0
    assert gauges[PROF_GAUGES[2]] == 900.0
    for e in _train_steps(2):
        attr.observe(e)
    attr.write()
    hbm = read_attrib(str(tmp_path))["hbm"]
    assert hbm["0"]["peak_bytes_in_use"] == 150
    assert hbm["1"]["peak_bytes_in_use"] == 210


# -- the real data:stall fault site -------------------------------------------

def test_data_stall_lands_in_data_segment(tmp_path):
    """The ISSUE acceptance stall path: a ``data:stall`` injected into
    the real ``read_with_retry`` site wedges the read until a timed
    ``release_data_stalls()``; the wedged time is emitted as the dequeue
    span and must dominate the ``data`` segment of ``ATTRIB.json``.
    (The post-release ``FaultInjected`` is the site's documented
    contract — the consumer catches it and finishes the window.)"""
    tel = Telemetry(str(tmp_path), rank=0, profile=True)
    set_data_hooks(fault_plan=FaultPlan.parse("data:stall@1"))
    timer = threading.Timer(0.25, release_data_stalls)
    timer.start()
    try:
        for step in range(3):
            t0 = time.perf_counter()
            try:
                read_with_retry(lambda: np.zeros(1), what="batch")
            except FaultInjected:
                pass  # the stall site raises once released, by contract
            tel.emit_span("prefetch.dequeue", t0,
                          time.perf_counter() - t0, step=step)
            with tel.span("train.step", step=step):
                time.sleep(0.01)
    finally:
        timer.cancel()
        release_data_stalls()
        set_data_hooks()
    res = tel.prof.result()
    tel.close()
    data = res["segments"]["data"]
    assert data["total_s"] >= 0.2, f"stall not attributed: {res}"
    assert res["dominant"]["segment"] == "data"
    # close() published the same verdict durably
    attrib = read_attrib(str(tmp_path))
    assert attrib["per_rank"]["0"]["dominant"]["segment"] == "data"


# -- Telemetry hookup ---------------------------------------------------------

def test_profile_off_means_off(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0)
    assert tel.prof is None
    with tel.span("train.step"):
        pass
    tel.profile_flush(step=1)  # no-op, must not raise
    tel.close()
    assert read_attrib(str(tmp_path)) is None


def test_profile_flush_emits_attr_gauges(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0, profile=True)
    for e in _train_steps(5):
        tel.emit(e["kind"], e["name"], ts=e["ts"], dur=e["dur"],
                 tid=e["tid"])
    tel.profile_flush(step=5)
    tel.close()
    events = []
    for p in sink_files(str(tmp_path)):
        events.extend(read_events(p))
    gauge_names = {e["name"] for e in events if e["kind"] == "gauge"}
    assert ATTR_GAUGE_BY_SEGMENT["compute"] in gauge_names
    assert ATTR_GAUGE_BY_SEGMENT["step"] in gauge_names
    assert os.path.exists(os.path.join(str(tmp_path), "ATTRIB.json"))


# -- integration: one 5-step CPU run ------------------------------------------

TINY = {
    "depth": 10, "widen": 1, "batch_size": 2, "image_size": 8,
    "n_train": 80, "n_val": 16, "n_epochs": 1, "precision": "fp32",
    "augment": False, "verbose": False,
}


@pytest.fixture(scope="module")
def prof_run(tmp_path_factory):
    """One 5-step BSP/psum run, telemetry + attribution on, a mid-run
    checkpoint cadence so checkpoint.snapshot lands inside the window."""
    d = str(tmp_path_factory.mktemp("tel_prof"))
    ck = str(tmp_path_factory.mktemp("ck_prof"))
    rule = BSP(config={"verbose": False, "telemetry_dir": d,
                       "print_freq": 2, "exch_strategy": "psum",
                       "checkpoint_dir": ck,
                       "checkpoint_every_n_iters": 2})
    rule.init(devices=8, model_config=dict(TINY))
    rec = rule.wait()
    events = []
    for p in sink_files(d):
        events.extend(read_events(p))
    return d, rec, events


def test_run_publishes_attrib_json(prof_run):
    d, _, _ = prof_run
    attrib = read_attrib(d)
    assert attrib is not None, "close() did not publish ATTRIB.json"
    res = attrib["per_rank"]["0"]
    assert res["mode"] == "train"
    assert res["steps"] == 5
    assert res["dominant"]["verdict"].endswith("-bound")


def test_run_checkpoint_lands_in_checkpoint_segment(prof_run):
    d, _, events = prof_run
    # the cadence fired: blocking snapshots are on the step thread
    assert any(e["name"] == "checkpoint.snapshot" for e in events
               if e["kind"] == "span")
    res = read_attrib(d)["per_rank"]["0"]
    assert res["segments"].get("checkpoint", {}).get("total_s", 0) > 0


def test_run_segments_sum_to_step_wall_time(prof_run):
    """Acceptance: segment totals sum to within 10% of the measured wall
    step time — both over the whole window and per measured step."""
    d, _, events = prof_run
    res = read_attrib(d)["per_rank"]["0"]
    total = sum(s["total_s"] for s in res["segments"].values())
    assert total == pytest.approx(res["window_s"], rel=0.10)
    # independently measure wall from the raw step spans
    steps = sorted((e for e in events if e.get("kind") == "span"
                    and e["name"] == "train.step" and e.get("rank") == 0),
                   key=lambda e: e["ts"])
    assert len(steps) == 5
    measured = (steps[-1]["ts"] + steps[-1]["dur"]) - steps[0]["ts"]
    recomputed = attribute_events(events)["0"]
    assert sum(s["total_s"] for s in recomputed["segments"].values()) \
        >= 0.9 * measured  # the step window is inside the span window


def test_run_attr_gauges_in_stream(prof_run):
    _, _, events = prof_run
    gauge_names = {e["name"] for e in events if e["kind"] == "gauge"}
    assert ATTR_GAUGE_BY_SEGMENT["compute"] in gauge_names
    assert ATTR_GAUGE_BY_SEGMENT["step"] in gauge_names


def test_run_chrome_trace_roundtrips_attributed_spans(prof_run):
    """The spans the attributor bills must survive the Chrome-trace
    export: every attributed train segment's source span appears as a
    complete ('X') event in the loadable trace JSON."""
    d, _, events = prof_run
    from theanompi_tpu.telemetry.chrome_trace import to_trace_events

    trace = to_trace_events(events)
    js = json.loads(json.dumps(trace))  # round-trip
    names = {ev.get("name") for ev in js if ev.get("ph") == "X"}
    for span in ("train.step", "recorder.wait", "prefetch.dequeue",
                 "checkpoint.snapshot"):
        assert span in names, f"{span} lost in trace export"


def test_run_tmprof_cli_attribution_table(prof_run, capsys):
    d, _, _ = prof_run
    from theanompi_tpu.telemetry import prof

    rc = prof.main([d])
    out = capsys.readouterr().out
    assert rc in (0, 1)  # 1 = host-bound verdict, still a valid table
    assert "rank 0" in out and "[train]" in out and "verdict:" in out
    # machine-readable form parses and agrees on the step count
    rc = prof.main([d, "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc in (0, 1)
    assert data["per_rank"]["0"]["steps"] == 5


def test_tmprof_usage_errors(tmp_path, capsys):
    from theanompi_tpu.telemetry import prof

    assert prof.main([str(tmp_path / "missing")]) == 2
    assert prof.main([]) == 2
    empty = tmp_path / "empty_dir"
    empty.mkdir()
    assert prof.main([str(empty)]) == 2
    capsys.readouterr()
