"""Error-path / import-wall / np.load lints (ISSUEs 4-6), now tmlint
shims (ISSUE 7).

The three AST walkers that lived here moved into the rule registry
(``swallow``, ``np-load``, and the serving wall generalized into the
``import-dag`` layer declaration in ``theanompi_tpu/analysis/layers.py``).
Each original test name stays green and re-proves its negative case
against the ported rule, so a bisect across the migration still lands on
the real culprit.
"""

from theanompi_tpu.analysis import core
from theanompi_tpu.analysis.layers import SERVING_FORBIDDEN_IMPORTS
from theanompi_tpu.analysis.rules import (
    NP_LOAD_ALLOWED_PREFIXES,
    SWALLOW_ALLOWLIST,
)

REPO = core.REPO_ROOT


def _unsuppressed(findings, rule):
    return [f.format() for f in findings
            if f.rule == rule and not f.suppressed]


def test_no_exception_swallowing_in_package_error_paths():
    findings, _ = core.lint_paths(rule_names=["swallow"])
    offenders = _unsuppressed(findings, "swallow")
    assert not offenders, (
        "exception swallowing in package error paths — the resilience "
        "layer needs failures to propagate (re-raise, stash for deferred "
        "delivery, narrow the type, or mark the line 'lint: swallow-ok — "
        "<why>'):\n" + "\n".join(offenders))


def test_swallow_rule_still_catches_the_original_negative_cases(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        work()\n"
        "    except:\n"
        "        pass\n"
        "def g():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        log('oops')\n"
        "def h():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception as e:\n"
        "        self._err = e\n")
    findings, _ = core.lint_paths([str(bad)], ["swallow"],
                                  root=str(tmp_path))
    lines = sorted(f.line for f in findings if not f.suppressed)
    assert lines == [4, 9], findings  # bare+pass at 4, broad swallow at 9
    # h()'s deferred-stash pattern stays allowed


def test_swallow_allowlist_still_names_the_documented_sites():
    """The exempt (file, function) pairs moved into the rule; the two
    teardown sites and the CLI mains must stay exactly the documented
    set — growth here needs review, not drift."""
    assert ("theanompi_tpu/parallel/trainer.py", "run") in SWALLOW_ALLOWLIST
    assert ("theanompi_tpu/parallel/trainer.py", "wait") in SWALLOW_ALLOWLIST
    assert ("theanompi_tpu/launcher.py", "main") in SWALLOW_ALLOWLIST
    assert ("theanompi_tpu/serving/cli.py", "main") in SWALLOW_ALLOWLIST
    assert ("theanompi_tpu/analysis/cli.py", "main") in SWALLOW_ALLOWLIST
    assert ("theanompi_tpu/fleet/cli.py", "main") in SWALLOW_ALLOWLIST
    assert ("theanompi_tpu/router/cli.py", "main") in SWALLOW_ALLOWLIST
    assert len(SWALLOW_ALLOWLIST) == 7


def test_faultinject_marker_registered():
    """The marker the fault-plan tests carry must stay registered, or a
    future `--strict-markers` run (and `-m faultinject` selection) breaks."""
    import pathlib

    pyproject = (pathlib.Path(REPO) / "pyproject.toml").read_text()
    assert "faultinject:" in pyproject


def test_serving_never_imports_training_paths():
    """The serving package is a consumer: no trainer, exchanger, optimizer,
    or supervisor imports anywhere under ``theanompi_tpu/serving/`` — now
    the any-depth wall of the ``import-dag`` rule (the wall list itself is
    asserted so a layers.py edit can't silently drop an entry)."""
    for mod in ("theanompi_tpu.parallel.trainer",
                "theanompi_tpu.parallel.exchanger",
                "theanompi_tpu.ops.opt",
                "theanompi_tpu.resilience.supervisor"):
        assert mod in SERVING_FORBIDDEN_IMPORTS
    findings, _ = core.lint_paths(rule_names=["import-dag"])
    offenders = _unsuppressed(findings, "import-dag")
    assert not offenders, (
        "package layering violated (serving wall / declared DAG):\n"
        + "\n".join(offenders))


def test_fleet_wall_names_the_supervised_machinery():
    """The mirror half of the serving ⊥ fleet wall (ISSUE 11): the fleet
    supervises the launcher/trainer as SUBPROCESSES and must never import
    them (even lazily) — the wall list itself is asserted so a layers.py
    edit can't silently drop an entry.  The clean run rides the
    import-dag check above."""
    from theanompi_tpu.analysis.layers import FLEET_FORBIDDEN_IMPORTS

    for mod in ("theanompi_tpu.serving", "theanompi_tpu.parallel",
                "theanompi_tpu.models", "theanompi_tpu.ops",
                "theanompi_tpu.launcher"):
        assert mod in FLEET_FORBIDDEN_IMPORTS
    assert "theanompi_tpu.fleet" in SERVING_FORBIDDEN_IMPORTS


def test_serving_wall_still_catches_the_original_negative_case(tmp_path):
    """A lazy (function-local) trainer import inside serving/ must fire:
    the wall holds at ANY depth, unlike the module-level-only layering."""
    pkg = tmp_path / "theanompi_tpu" / "serving"
    pkg.mkdir(parents=True)
    bad = pkg / "bad.py"
    bad.write_text(
        "def sneak():\n"
        "    from theanompi_tpu.parallel.trainer import BaseTrainer\n"
        "    return BaseTrainer\n")
    findings, _ = core.lint_paths([str(bad)], ["import-dag"],
                                  root=str(tmp_path))
    assert any("training machinery" in f.message for f in findings
               if not f.suppressed), findings


def test_serving_has_no_np_load_allowance():
    """Serving reads checkpoint bytes ONLY through the verified loader:
    no ``serving/`` prefix may appear in the np.load allowlist."""
    assert not any(p.startswith("theanompi_tpu/serving")
                   for p in NP_LOAD_ALLOWED_PREFIXES)


def test_checkpoint_npz_loads_confined_to_verified_loader():
    """No `np.load` outside the allowlist: new checkpoint-reading code is
    forced through `Checkpointer.load` / `load_latest_verified` /
    `verify_file`, where integrity verification lives."""
    findings, _ = core.lint_paths(rule_names=["np-load"])
    offenders = _unsuppressed(findings, "np-load")
    assert not offenders, (
        "np.load outside the verified checkpoint loader / dataset "
        "allowlist:\n" + "\n".join(offenders))


def test_np_load_rule_still_catches_the_original_negative_case(tmp_path):
    pkg = tmp_path / "theanompi_tpu" / "serving"
    pkg.mkdir(parents=True)
    bad = pkg / "bad.py"
    bad.write_text("import numpy as np\nd = np.load('ckpt.npz')\n")
    findings, _ = core.lint_paths([str(bad)], ["np-load"],
                                  root=str(tmp_path))
    assert _unsuppressed(findings, "np-load"), findings
