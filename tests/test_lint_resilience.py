"""Error-path discipline lint (ISSUE 4 satellite): no exception swallowing
in package error paths.

The resilience layer only works if failures actually PROPAGATE to it — a
``try: ... except: pass`` between a fault and the supervisor turns a clean
restart into a silent wedge.  This pytest-collected static check walks the
package AST and fails the build on:

A. **bare** ``except:`` clauses (catch-everything, including SystemExit);
B. handlers whose entire body is ``pass`` (the classic swallow);
C. **broad** handlers (``Exception``/``BaseException``) that neither
   re-``raise`` nor stash the caught error for deferred delivery (the
   ``self._err = e`` pattern the prefetcher and async checkpoint writer
   use — those re-raise at the consuming site).

Escapes, kept visible at the call site:

- an inline ``# lint: swallow-ok`` comment on the ``except`` line (used by
  the documented best-effort probes: telemetry hardware stats, the native
  kernel build, compile-cache compat shims);
- the allowlist below for the two documented correlated-failure teardown
  sites (``BaseTrainer.run``'s checkpoint-writer join and ``Rule.wait``'s
  telemetry finalize: a secondary error there must not mask the primary
  exception already unwinding) plus ``launcher.main`` and the serving
  CLI's ``main``, whose whole job is converting exceptions into the
  shared exit-code contract.

The companion ``faultinject`` pytest marker is registered in
``pyproject.toml`` so the fault-plan tests stay in tier-1 while remaining
individually selectable (``pytest -m faultinject``).
"""

import ast
import pathlib

REPO = pathlib.Path(__file__).resolve().parents[1]
ALLOW_MARK = "lint: swallow-ok"

#: (path-relative-to-repo, enclosing function) pairs exempt from rule C —
#: each one is documented at the site
ALLOWLIST = {
    ("theanompi_tpu/parallel/trainer.py", "run"),    # teardown join
    ("theanompi_tpu/parallel/trainer.py", "wait"),   # telemetry finalize
    ("theanompi_tpu/launcher.py", "main"),           # exit-code contract
    ("theanompi_tpu/serving/cli.py", "main"),        # tmserve exit-code contract
}

BROAD = {"Exception", "BaseException"}


def _python_files():
    yield from sorted((REPO / "theanompi_tpu").rglob("*.py"))


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    return any(isinstance(n, ast.Name) and n.id in BROAD for n in nodes)


def _stashes_error(handler: ast.ExceptHandler) -> bool:
    """Deferred-delivery pattern: the caught error is assigned somewhere
    (``self._err = e``) for a later re-raise at the consuming site."""
    if not handler.name:
        return False
    for node in ast.walk(handler):
        if isinstance(node, ast.Assign):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == handler.name:
                    return True
    return False


def _has_raise(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _marked_ok(handler: ast.ExceptHandler, lines: list[str]) -> bool:
    """The marker counts on the ``except`` line or its first body line."""
    for lineno in (handler.lineno, handler.body[0].lineno):
        if 0 < lineno <= len(lines) and ALLOW_MARK in lines[lineno - 1]:
            return True
    return False


def _enclosing_function(tree: ast.AST, handler: ast.ExceptHandler) -> str:
    name = "<module>"

    def visit(node, current):
        nonlocal name
        for child in ast.iter_child_nodes(node):
            nxt = current
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nxt = child.name
            if child is handler:
                name = current
            visit(child, nxt)

    visit(tree, "<module>")
    return name


def test_no_exception_swallowing_in_package_error_paths():
    offenders = []
    for path in _python_files():
        rel = str(path.relative_to(REPO))
        src = path.read_text()
        lines = src.splitlines()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            where = f"{rel}:{node.lineno}"
            if node.type is None and not _marked_ok(node, lines):
                offenders.append(f"{where}: bare `except:`")
                continue
            body_is_pass = (len(node.body) == 1
                            and isinstance(node.body[0], ast.Pass))
            if body_is_pass and not _marked_ok(node, lines):
                offenders.append(f"{where}: handler body is only `pass`")
                continue
            if (_is_broad(node.type) and not _has_raise(node)
                    and not _stashes_error(node)
                    and not _marked_ok(node, lines)
                    and (rel, _enclosing_function(tree, node))
                    not in ALLOWLIST):
                offenders.append(
                    f"{where}: broad handler swallows the error "
                    f"(no raise / no deferred stash)")
    assert not offenders, (
        "exception swallowing in package error paths — the resilience "
        "layer needs failures to propagate (re-raise, stash for deferred "
        "delivery, narrow the type, or mark the line 'lint: swallow-ok' "
        "with a reason):\n" + "\n".join(offenders))


def test_faultinject_marker_registered():
    """The marker the fault-plan tests carry must stay registered, or a
    future `--strict-markers` run (and `-m faultinject` selection) breaks."""
    pyproject = (REPO / "pyproject.toml").read_text()
    assert "faultinject:" in pyproject


#: files allowed to call np.load / numpy.load (ISSUE 5 satellite lint).
#: Checkpoint ``.npz`` bytes must only ever be read through the verified
#: loader entry points in utils/checkpoint.py — a `np.load(ckpt_path)`
#: anywhere else bypasses manifest verification, the fingerprint check,
#: and the recovery chain, silently resurrecting the blind-trust resume
#: this PR removed.  Dataset shards and recorder histories have their own
#: (non-checkpoint) formats and keep direct access.
NP_LOAD_ALLOWED_PREFIXES = (
    "theanompi_tpu/utils/checkpoint.py",   # THE verified loader
    "theanompi_tpu/utils/recorder.py",     # history .npy snapshots
    "theanompi_tpu/models/data/",          # dataset shard reads
)


#: training-side modules the serving package must NEVER import (ISSUE 6):
#: serving is a read-only consumer — a gradient, optimizer, exchanger or
#: supervisor import there means training machinery leaked into the
#: inference path (and with it, write access to training state)
SERVING_FORBIDDEN_IMPORTS = (
    "theanompi_tpu.parallel.trainer",
    "theanompi_tpu.parallel.bsp",
    "theanompi_tpu.parallel.easgd",
    "theanompi_tpu.parallel.gosgd",
    "theanompi_tpu.parallel.exchanger",
    "theanompi_tpu.parallel.pipeline",
    "theanompi_tpu.ops.opt",
    "theanompi_tpu.resilience.supervisor",
    "theanompi_tpu.resilience.sentinel",
    "theanompi_tpu.resilience.watchdog",
    "theanompi_tpu.resilience.faults",
)


def _imported_modules(tree: ast.AST):
    """Every module name an ``import`` / ``from ... import`` touches."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield node.lineno, alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            yield node.lineno, node.module
            # `from pkg import sub` can also bind submodules
            for alias in node.names:
                yield node.lineno, f"{node.module}.{alias.name}"


def test_serving_never_imports_training_paths():
    """The serving package is a consumer: no trainer, exchanger, optimizer,
    or supervisor imports anywhere under ``theanompi_tpu/serving/`` —
    its int8 quantization reuses ``ops/quant.py`` (the shared primitive
    extracted from the exchanger), never the exchanger itself."""
    offenders = []
    for path in sorted((REPO / "theanompi_tpu" / "serving").rglob("*.py")):
        rel = str(path.relative_to(REPO))
        tree = ast.parse(path.read_text())
        for lineno, mod in _imported_modules(tree):
            if any(mod == bad or mod.startswith(bad + ".")
                   for bad in SERVING_FORBIDDEN_IMPORTS):
                offenders.append(f"{rel}:{lineno}: imports {mod}")
    assert not offenders, (
        "serving/ imports training-side machinery — the inference path "
        "must stay a read-only consumer:\n" + "\n".join(offenders))


def test_serving_has_no_np_load_allowance():
    """Serving reads checkpoint bytes ONLY through the verified loader:
    no ``serving/`` prefix may appear in the np.load allowlist (and the
    package-wide np.load lint below therefore covers it)."""
    assert not any(p.startswith("theanompi_tpu/serving")
                   for p in NP_LOAD_ALLOWED_PREFIXES)


def test_checkpoint_npz_loads_confined_to_verified_loader():
    """No `np.load` outside the allowlist: new checkpoint-reading code is
    forced through `Checkpointer.load` / `load_latest_verified` /
    `verify_file`, where integrity verification lives."""
    offenders = []
    for path in _python_files():
        rel = str(path.relative_to(REPO))
        if rel.startswith(NP_LOAD_ALLOWED_PREFIXES):
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "load"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in ("np", "numpy")):
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "np.load outside the verified checkpoint loader / dataset "
        "allowlist — checkpoint .npz files must be read through "
        "theanompi_tpu.utils.checkpoint (verify + fingerprint + recovery "
        "chain), not raw numpy:\n" + "\n".join(offenders))
