"""Pipeline parallelism: the GPipe collective-permute schedule over 'pipe'.

The invariant: a dp2 x pp4 pipelined run must track the single-device run
of the SAME stacked model through multiple train steps (forward AND the
cross-pipe gradient path — embeddings/head cotangents exist only on the
injection/collection stages and must be psum-repaired, exactly the bug
class ADVICE.md round 1 found for tensor parallelism).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.models.transformer_lm import PipelineTransformerLM
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import make_mesh

CFG = {"batch_size": 8, "n_train": 64, "n_val": 32, "seq_len": 16,
       "vocab": 32, "dim": 32, "heads": 4, "n_layers": 4, "dropout": 0.0,
       "n_micro": 4,
       "l2": 1e-4, "n_epochs": 1, "precision": "fp32"}


def _run_steps(mesh, cfg, steps=3):
    model = PipelineTransformerLM(cfg)
    t = BSPTrainer(model, mesh=mesh)
    t.compile_iter_fns()
    t.init_state()
    batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
    costs = [
        float(t.train_iter(batches[i % len(batches)], lr=1e-2)["cost"])
        for i in range(steps)
    ]
    return t, costs


def test_pipeline_params_are_stacked_and_sharded():
    model = PipelineTransformerLM(dict(CFG))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    for leaf in jax.tree.leaves(params["blocks"]):
        assert leaf.shape[0] == CFG["n_layers"]
    specs = model.param_specs(params)
    from jax.sharding import PartitionSpec as P

    # every stacked leaf leads with pipe; TP-ruled leaves keep their
    # Megatron spec behind it (dp x pp x tp composition)
    for s in jax.tree.leaves(specs["blocks"], is_leaf=lambda x: isinstance(x, P)):
        assert s[0] == "pipe"
    assert specs["blocks"]["attn"]["q"]["w"] == P("pipe", None, "model")
    assert specs["blocks"]["attn"]["o"]["w"] == P("pipe", "model", None)
    assert specs["blocks"]["up"]["w"] == P("pipe", None, "model")
    assert specs["blocks"]["ln1"]["scale"] == P("pipe")
    assert all(s == P() for s in jax.tree.leaves(specs["head"]))


def test_pp4_matches_single_device():
    """dp2 x pp4 must track the unsharded model through 3 train steps.

    Per-batch costs see the forward; steps 2-3 see the updated params, so a
    wrong cross-pipe gradient (embed/head psum, stage routing) shows up as
    loss divergence."""
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, dict(CFG))

    mesh_pp = make_mesh(n_data=2, n_pipe=4)
    # same GLOBAL batch: data axis splits it in two
    cfg = {**CFG, "batch_size": CFG["batch_size"] // 2}
    t2, c2 = _run_steps(mesh_pp, cfg)

    np.testing.assert_allclose(c1, c2, rtol=2e-4, atol=2e-5)
    # a replicated (head) leaf must end identical too
    a = np.asarray(jax.tree.leaves(t1.params["head"])[0])
    b = np.asarray(jax.tree.leaves(t2.params["head"])[0])
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_pp8_trains_and_validates():
    """All 8 devices as pipeline stages (dp=1, pp=8): runs + learns-ish."""
    mesh = make_mesh(n_data=1, n_pipe=8)
    cfg = {**CFG, "n_layers": 8, "n_epochs": 2}
    model = PipelineTransformerLM(cfg)
    t = BSPTrainer(model, mesh=mesh)
    rec = t.run()
    costs = rec.val_history["cost"]
    assert len(costs) == 2 and all(np.isfinite(costs)), costs


def test_pp2_tp2_matches_single_device():
    """dp1 x pp2 x tp2: the full composition must track the unsharded model
    through 3 train steps (VERDICT r2 #4).  Steps 2-3 run on updated
    params, so any mis-composed collective (double-counted TP psum under
    the pipe schedule, missing pipe-pin on a replicated leaf) diverges."""
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, dict(CFG))

    mesh = make_mesh(n_data=1, n_pipe=2, n_model=2, devices=jax.devices()[:4])
    t2, c2 = _run_steps(mesh, dict(CFG))
    np.testing.assert_allclose(c1, c2, rtol=2e-4, atol=2e-5)
    a = np.asarray(jax.tree.leaves(t1.params["head"])[0])
    b = np.asarray(jax.tree.leaves(t2.params["head"])[0])
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
    # a TP'd stacked weight is actually SHARDED (device_set size alone is
    # vacuous: replicated arrays also span all devices)
    qw = t2.params["blocks"]["attn"]["q"]["w"]
    assert not qw.sharding.is_fully_replicated


def test_dp2_pp2_tp2_trains():
    """All three axes at once on the 8-device mesh: finite loss, val runs."""
    mesh = make_mesh(n_data=2, n_pipe=2, n_model=2)
    model = PipelineTransformerLM({**CFG, "n_epochs": 1})
    t = BSPTrainer(model, mesh=mesh)
    rec = t.run()
    costs = rec.val_history["cost"]
    assert len(costs) == 1 and all(np.isfinite(costs)), costs


def test_pp2_sp2_matches_single_device():
    """dp1 x pp2 x sp2: ring attention's KV laps inside the GPipe schedule
    (VERDICT r3 #5 — the last refusal on the parallelism surface).  Multi-
    step equivalence: steps 2-3 run on updated params, so a wrong hop
    order / mis-pinned cotangent on either ring diverges the loss."""
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, dict(CFG))

    cfg = {**CFG, "seq_parallel": True}
    mesh = make_mesh(n_data=1, n_pipe=2, n_seq=2, devices=jax.devices()[:4])
    t2, c2 = _run_steps(mesh, cfg)
    np.testing.assert_allclose(c1, c2, rtol=2e-4, atol=2e-5)
    a = np.asarray(jax.tree.leaves(t1.params["head"])[0])
    b = np.asarray(jax.tree.leaves(t2.params["head"])[0])
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_dp2_pp2_sp2_trains():
    """All of data x pipe x seq on the 8-device mesh: finite loss, val runs."""
    mesh = make_mesh(n_data=2, n_pipe=2, n_seq=2)
    model = PipelineTransformerLM(
        {**CFG, "seq_parallel": True, "n_epochs": 1})
    t = BSPTrainer(model, mesh=mesh)
    rec = t.run()
    costs = rec.val_history["cost"]
    assert len(costs) == 1 and all(np.isfinite(costs)), costs


def test_pipeline_rejects_indivisible_microbatch():
    from theanompi_tpu.parallel.mesh import shard_map
    from theanompi_tpu.parallel.pipeline import pipeline_apply
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(n_data=1, n_pipe=8)

    def f(x):
        return pipeline_apply(lambda p, a, t: a, None, x, n_micro=3)

    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(shard_map(f, mesh, in_specs=P(), out_specs=P()))(
            jnp.ones((8, 4))
        )


def test_pp2_tp2_with_fused_vocab_parallel_loss():
    """The full stack at once: GPipe over `pipe`, Megatron splits + the
    vocab-parallel fused loss over `model` — must track the single-device
    fused run through 3 steps, with the head actually vocab-sharded."""
    cfg = {**CFG, "fused_loss": True}
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, dict(cfg))

    mesh = make_mesh(n_data=1, n_pipe=2, n_model=2, devices=jax.devices()[:4])
    t2, c2 = _run_steps(mesh, dict(cfg))
    np.testing.assert_allclose(c1, c2, rtol=2e-4, atol=2e-5)
    hw = t2.params["head"]["w"]
    assert not hw.sharding.is_fully_replicated  # vocab actually sharded
    np.testing.assert_allclose(
        np.asarray(t1.params["head"]["w"]), np.asarray(hw),
        rtol=2e-4, atol=2e-5,
    )


def test_scan_unroll_matches_rolled():
    """layers_unroll/loss_unroll are scheduling hints: multi-step training
    must track the rolled (unroll=1) run on identical inits (r5 knobs for
    the while-self-time share in ROOFLINE_transformer_32k.json).

    loss_unroll is exercised on the base TransformerLM (its only scans are
    the fused-loss chunk scans); layers_unroll on PipelineTransformerLM —
    the ONLY model with a stacked-layer scan (the base trunk is a
    Python-loop Sequential, where the knob is inert by design).
    """
    from theanompi_tpu.models.transformer_lm import TransformerLM

    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    base = {"batch_size": 4, "n_train": 32, "n_val": 16, "seq_len": 16,
            "vocab": 4096, "dim": 32, "heads": 4, "n_layers": 4,
            "dropout": 0.0, "n_epochs": 1, "precision": "fp32",
            "fused_loss": True}

    def run(model_cls, extra):
        model = model_cls({**base, **extra})
        t = BSPTrainer(model, mesh=mesh)
        t.compile_iter_fns()
        t.init_state()
        batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
        return [
            float(t.train_iter(batches[i % len(batches)], lr=1e-2)["cost"])
            for i in range(3)
        ]

    rolled = run(TransformerLM, {})
    unrolled = run(TransformerLM, {"loss_unroll": 2})
    np.testing.assert_allclose(unrolled, rolled, rtol=1e-5)

    pp_cfg = {"n_micro": 2}
    pp_rolled = run(PipelineTransformerLM, pp_cfg)
    pp_unrolled = run(PipelineTransformerLM,
                      {**pp_cfg, "layers_unroll": 4, "loss_unroll": 2})
    np.testing.assert_allclose(pp_unrolled, pp_rolled, rtol=1e-5)
