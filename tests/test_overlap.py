"""ISSUE 12: comm/compute-overlapped exchange + quantization ramp schedule.

Locks the two contracts of ``theanompi_tpu/parallel/overlap.py``:

- **Overlap bit-equality** (acceptance): with ``exch_overlap=True`` the
  per-bucket collectives are chained into backward in reverse layout
  order, but every fence is value-preserving — final params are
  bit-equal to the fused path for ``psum_bucket`` and ``zero1`` on the
  8-device CPU mesh (``ring_int8`` at its documented wire tolerance),
  and the static wire/bucket accounting does not move at all.  The
  schedule proof itself (collective→collective dependency edges in the
  optimized HLO) lives in ``tests/test_hlo_audit.py``.

- **Ramp phases switch only at fenced epoch boundaries**: the active
  strategy is a pure function of the absolute epoch, the step fn
  recompiles at most once per phase (no recompile storm), wire-byte
  accounting tracks the active phase through telemetry, and a mid-ramp
  checkpoint resume lands in the phase its epoch dictates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.exchanger import BUCKETED_STRATEGIES, Exchanger
from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map
from theanompi_tpu.parallel.overlap import RampSchedule
from conftest import EXCHANGE_TINY  # noqa: E402

#: small enough that the tiny WRN packs into several fp32 buckets — an
#: overlap run with one bucket has no chain and proves nothing
CHAIN_MB = 0.05


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree.leaves(tree)]


@pytest.fixture(scope="module")
def mesh2():
    """2-device data mesh for the compile-heavy integration tests below:
    chain/ramp semantics are device-count-independent, and the unrolled
    2(n-1) ppermute hops per bucket dominate the ring compiles, so the
    smallest collective mesh keeps them inside the tier-1 budget."""
    from theanompi_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=2, devices=jax.devices()[:2])


# -- acceptance: fused-vs-overlapped bit-equality on mesh8 -------------------

@pytest.mark.parametrize("strategy", ["psum_bucket", "zero1"])
def test_overlap_bit_equal_on_mesh8(exchange_run, mesh8, strategy):
    """Acceptance: two full train steps with the chained schedule produce
    BIT-identical params to the fused schedule (the fences' true branch
    returns each buffer verbatim; zero1's chain sits on the update
    OUTPUTS precisely so XLA's fusion clusters — and therefore the FMA
    contractions — do not move)."""
    t_fused, fused = exchange_run(mesh8, strategy, bucket_mb=CHAIN_MB)
    t_over, over = exchange_run(mesh8, strategy, bucket_mb=CHAIN_MB,
                                overlap=True)
    assert not t_fused.exchanger.overlap and t_over.exchanger.overlap
    # the run must exercise a real chain, not a degenerate single bucket
    n_buckets = t_over.exchanger.bucket_summary(
        t_over.params, 8)["n_buckets"]
    assert n_buckets >= 2, n_buckets
    for a, b in zip(_leaves(fused), _leaves(over)):
        np.testing.assert_array_equal(a, b)


# -- exchange-level equivalence (every bucketed mean strategy) ---------------

def _exchange_tree(mesh, strategy, per_dev, overlap):
    """Run one multi-bucket exchange of ``per_dev`` (dict of [n, k] arrays
    sharded over data) and return the per-device outputs as numpy."""
    ex = Exchanger(strategy=strategy, bucket_bytes=256, overlap=overlap)
    step = jnp.zeros((), jnp.int32)

    def f(tree, step):
        inner = jax.tree.map(lambda a: a[0], tree)
        out = ex.exchange(inner, rng=jax.random.PRNGKey(3), step=step)
        return jax.tree.map(lambda a: a[None], out)

    out = shard_map(f, mesh=mesh, in_specs=(P(DATA_AXIS), P()),
                    out_specs=P(DATA_AXIS), check=False)(per_dev, step)
    return jax.tree.map(np.asarray, out)


def _per_dev_tree(n, leaves=("a", "b", "c")):
    rng = np.random.RandomState(0)
    # leaves of 192 bytes each against bucket_bytes=256 -> one bucket per
    # leaf, so len(leaves) buckets
    return {k: jnp.asarray(rng.randn(n, 48).astype(np.float32))
            for k in leaves}


@pytest.mark.parametrize("strategy", ["psum_bucket", "ring_int8"])
def test_overlap_exchange_matches_fused(mesh4, mesh2, strategy):
    """The chained walk returns the same reduction as the fused walk
    (bit-equal — ``ring_int8``'s rng folds by bucket INDEX, not walk
    order, so even its stochastic rounding noise is identical; a tiny
    atol keeps the lock honest about that claim without over-pinning
    XLA) and the result is the cross-replica mean within the strategy's
    documented wire tolerance.  Two representatives keep the matrix
    inside the tier-1 budget: the fence/rng plumbing is
    strategy-agnostic, so the ring/bf16 bucket variants add compile cost
    but no coverage (their fused numerics are locked in
    test_exchanger.py).  The ring case runs on the 2-device mesh with a
    2-bucket tree (one chain edge) for the same reason; psum keeps the
    deeper 3-bucket chain — its compiles are cheap."""
    if strategy == "ring_int8":
        mesh, n_dev, leaves = mesh2, 2, ("a", "b")
    else:
        mesh, n_dev, leaves = mesh4, 4, ("a", "b", "c")
    per_dev = _per_dev_tree(n_dev, leaves)
    fused = _exchange_tree(mesh, strategy, per_dev, overlap=False)
    over = _exchange_tree(mesh, strategy, per_dev, overlap=True)
    atol = 1e-6 if strategy == "ring_int8" else 0.0
    for a, b in zip(_leaves(fused), _leaves(over)):
        np.testing.assert_allclose(a, b, rtol=0, atol=atol)
    tol = 5e-2 if "int8" in strategy else (1e-2 if "bf16" in strategy
                                           else 1e-6)
    for k, v in per_dev.items():
        want = np.asarray(v).mean(axis=0)
        for i in range(n_dev):
            np.testing.assert_allclose(over[k][i], want, rtol=tol, atol=tol)


def test_overlap_requires_bucketed_strategy():
    with pytest.raises(ValueError, match="not bucketed"):
        Exchanger(strategy="psum", overlap=True)


def test_overlap_requires_step_scalar(mesh4):
    """The fence chain is anchored on the traced step scalar; forgetting
    to thread it through is a loud trace-time error, not a silent
    unchained schedule."""
    ex = Exchanger(strategy="psum_bucket", bucket_bytes=256, overlap=True)

    def f(x):
        return ex.exchange({"a": x[0]})["a"][None]

    with pytest.raises(ValueError, match="step scalar"):
        shard_map(f, mesh=mesh4, in_specs=P(DATA_AXIS),
                  out_specs=P(DATA_AXIS), check=False)(jnp.ones((4, 48)))


def test_overlap_changes_no_accounting():
    """Satellite invariant: overlap is a schedule change, not a traffic
    change — static wire bytes and the bucket layout are identical."""
    tree = {"w": np.zeros((1000,), np.float32),
            "b": np.zeros((10,), np.float32)}
    for strategy in BUCKETED_STRATEGIES:
        fused = Exchanger(strategy, bucket_bytes=1024)
        over = Exchanger(strategy, bucket_bytes=1024, overlap=True)
        assert fused.wire_bytes(tree, 8) == over.wire_bytes(tree, 8)
        assert fused.bucket_summary(tree, 8) == over.bucket_summary(tree, 8)


# -- RampSchedule parsing ----------------------------------------------------

def test_ramp_parse_phases_and_lookup():
    r = RampSchedule.parse("ring_int8:2,psum_bf16_bucket:4", "psum_bucket")
    assert r.phases == (("ring_int8", 2), ("psum_bf16_bucket", 4),
                        ("psum_bucket", None))
    assert [r.strategy_for_epoch(e) for e in range(6)] == (
        ["ring_int8"] * 2 + ["psum_bf16_bucket"] * 2 + ["psum_bucket"] * 2)
    assert r.phase_for_epoch(0) == 0 and r.phase_for_epoch(99) == 2
    assert r.describe() == "ring_int8:2,psum_bf16_bucket:4,psum_bucket"
    assert r.strategies == ("ring_int8", "psum_bf16_bucket", "psum_bucket")


@pytest.mark.parametrize("spec,base,msg", [
    ("ring_int8", "psum_bucket", "strategy:until_epoch"),
    ("ring_int8:x", "psum_bucket", "not an epoch"),
    ("nope:2", "psum_bucket", "unknown"),
    ("ring_int8:3,psum_bf16_bucket:2", "psum_bucket", "strictly increasing"),
    ("ring_int8:2,psum_bf16_bucket:2", "psum_bucket", "strictly increasing"),
    ("zero1:2", "psum_bucket", "zero1"),
    ("ring_int8:2", "zero1", "zero1"),
    ("", "psum_bucket", "empty"),
])
def test_ramp_parse_rejects(spec, base, msg):
    with pytest.raises(ValueError, match=msg):
        RampSchedule.parse(spec, base)


# -- ramp integration: boundaries, telemetry, resume -------------------------

def _ramp_trainer(mesh, n_epochs, telemetry=None, checkpoint_dir=None,
                  ramp="ring_int8:1,psum_bf16_bucket:2"):
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.utils.recorder import Recorder

    model = WideResNet({**EXCHANGE_TINY, "n_epochs": n_epochs,
                        "n_train": 16})
    t = BSPTrainer(model, mesh=mesh, exch_strategy="psum_bucket",
                   exch_bucket_mb=CHAIN_MB, exch_overlap=True,
                   exch_ramp=ramp, telemetry=telemetry,
                   checkpoint_dir=checkpoint_dir,
                   recorder=Recorder(verbose=False, print_freq=10**9))
    t.compile_iter_fns()
    t.init_state()
    return t


def _spy_train_iter(t, seen):
    orig = t.train_iter

    def spy(batch, lr, recorder=None):
        seen.append((t.epoch, t.exchanger.strategy, id(t._step_fn)))
        return orig(batch, lr, recorder)

    t.train_iter = spy


def test_ramp_switches_only_at_epoch_boundaries(mesh2, tmp_path):
    """Acceptance: over a 3-epoch run the active strategy follows the
    ramp exactly — every step inside an epoch uses that epoch's phase,
    each phase compiles its step fn ONCE (no recompile storm), and
    telemetry carries the phase gauge + per-phase wire accounting."""
    from theanompi_tpu.telemetry import Telemetry
    from theanompi_tpu.telemetry.sink import read_events, sink_files

    tel_dir = str(tmp_path / "tel")
    tel = Telemetry(tel_dir)
    t = _ramp_trainer(mesh2, n_epochs=3, telemetry=tel)
    seen = []
    _spy_train_iter(t, seen)
    t.run()
    tel.close()

    by_epoch = {}
    for epoch, strategy, fn_id in seen:
        by_epoch.setdefault(epoch, []).append((strategy, fn_id))
    assert sorted(by_epoch) == [0, 1, 2]
    want = {0: "ring_int8", 1: "psum_bf16_bucket", 2: "psum_bucket"}
    for epoch, steps in by_epoch.items():
        # one strategy AND one compiled step fn per epoch
        assert {s for s, _ in steps} == {want[epoch]}, (epoch, steps)
        assert len({fid for _, fid in steps}) == 1, (epoch, steps)
    # exactly one step fn per PHASE across the whole run
    assert len({fid for _, _, fid in seen}) == 3

    events = []
    for p in sink_files(tel_dir):
        events.extend(read_events(p))
    switches = [e for e in events if e["name"] == "exchange.ramp_switch"]
    assert [(e["epoch"], e["strategy"], e["phase"]) for e in switches] == [
        (0, "ring_int8", 0), (1, "psum_bf16_bucket", 1),
        (2, "psum_bucket", 2)]
    gauges = [e for e in events if e["name"] == "exchange.ramp_phase"]
    assert [e["value"] for e in gauges] == [0, 1, 2]
    # wire-byte accounting re-emitted per phase, at the phase's wire dtype:
    # int8 is exactly 1/4 and bf16 exactly 1/2 of the fp32 bucket bytes
    acct = [e for e in events if e["name"] == "exchange.accounting"]
    assert [e["strategy"] for e in acct] == [
        "ring_int8", "psum_bf16_bucket", "psum_bucket"]
    fp32 = acct[2]["bytes_per_exchange"]
    assert acct[0]["bytes_per_exchange"] * 4 == fp32
    assert acct[1]["bytes_per_exchange"] * 2 == fp32
    # the overlap span marks each (re)arming of the chained step fn: the
    # initial compile_iter_fns build + one per phase switch
    arms = [e for e in events if e["name"] == "exchange.overlap"]
    assert len(arms) == 4


def test_ramp_resume_restores_phase(mesh2, tmp_path):
    """Acceptance: the phase is a pure function of the absolute epoch, so
    a mid-ramp checkpoint resume lands in the right phase with no extra
    checkpoint state — and the resumed params lineage continues.  (A
    cheap two-phase ramp: the full int8→bf16→exact spec is exercised in
    test_ramp_switches_only_at_epoch_boundaries; re-compiling ring_int8's
    chained walk here would add ~12s of tier-1 for no new coverage.)"""
    ck = str(tmp_path / "ck")
    ramp = "psum_bf16_bucket:1"
    t1 = _ramp_trainer(mesh2, n_epochs=1, checkpoint_dir=ck, ramp=ramp)
    t1.run()
    assert t1.exchanger.strategy == "psum_bf16_bucket"  # ended mid-ramp

    t2 = _ramp_trainer(mesh2, n_epochs=3, checkpoint_dir=ck, ramp=ramp)
    assert t2.try_resume()
    assert t2.epoch == 1
    seen = []
    _spy_train_iter(t2, seen)
    t2.run()
    want = {1: "psum_bucket", 2: "psum_bucket"}
    assert {(e, s) for e, s, _ in seen} == set(want.items())
    # phase 0's exchanger never ran (and never compiled) in the resume
    assert all(s != "psum_bf16_bucket" for _, s, _ in seen)


def test_ramp_and_overlap_stamp_the_fingerprint(mesh4):
    """Changing the ramp or overlap knobs across a resume is a real
    topology change (different wire numerics / schedule): both are
    stamped, and the stamped exchange strategy is the ramp-invariant BASE
    (the active exchanger varies by epoch)."""
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.utils.recorder import Recorder

    def fp(**kw):
        t = BSPTrainer(WideResNet(dict(EXCHANGE_TINY)), mesh=mesh4,
                       recorder=Recorder(verbose=False, print_freq=10**9),
                       **kw)
        return t._run_fingerprint()

    plain = fp(exch_strategy="psum_bucket")
    assert plain["exchange"] == "psum_bucket"
    assert "exch_ramp" not in plain and "exch_overlap" not in plain

    ramped = fp(exch_strategy="psum_bucket", exch_overlap=True,
                exch_ramp="ring_int8:1")
    assert ramped["exchange"] == "psum_bucket"  # base, not epoch-0 phase
    assert ramped["exch_ramp"] == "ring_int8:1,psum_bucket"
    assert ramped["exch_overlap"] is True


def test_ramp_refuses_zero1_base_at_trainer_construction(mesh4):
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.utils.recorder import Recorder

    with pytest.raises(ValueError, match="zero1"):
        BSPTrainer(WideResNet(dict(EXCHANGE_TINY)), mesh=mesh4,
                   exch_strategy="zero1", exch_ramp="ring_int8:1",
                   recorder=Recorder(verbose=False, print_freq=10**9))


# -- telemetry names registry -------------------------------------------------

def test_exchange_telemetry_names_registered():
    """The overlap span and ramp gauge/instant are emitted through the
    registered names ONLY (one-source-of-truth contract — same as the
    serving/reshard/data/fleet names)."""
    from theanompi_tpu.telemetry.metrics import (
        EXCHANGE_GAUGES, EXCHANGE_INSTANTS, EXCHANGE_SPANS)

    assert set(EXCHANGE_SPANS) == {"exchange.overlap"}
    assert set(EXCHANGE_GAUGES) == {"exchange.ramp_phase"}
    assert set(EXCHANGE_INSTANTS) == {"exchange.ramp_switch"}
