"""Multi-host (multi-controller) integration: 2 jax.distributed processes.

VERDICT.md round-1 missing #2: the reference ran N real processes under
mpirun (SURVEY.md §3.1); round 1 had exactly one tested controller.  Here
two OS processes join a jax.distributed CPU runtime (Gloo collectives),
build one 8-device mesh spanning both, train BSP with sync-BN, checkpoint,
and resume — with process 1's checkpoint dir EMPTY, proving the resume
decision and arrays flow from process 0 (ADVICE.md: the non-shared-FS
desync).
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_bsp(tmp_path):
    port = _free_port()
    dir0 = str(tmp_path / "ckpt_proc0")
    dir1 = str(tmp_path / "ckpt_proc1")  # stays empty: proc 0 is authoritative
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": os.path.dirname(os.path.dirname(WORKER)),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(port), dir0, dir1],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out:\n" + "\n---\n".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK pid={pid}" in out, out[-3000:]
        assert f"MULTIHOST_RULES_OK pid={pid}" in out, out[-3000:]
    # proc 1 never wrote a checkpoint; proc 0 did
    assert any(f.startswith("ckpt_e") for f in os.listdir(dir0))
    assert not os.path.exists(os.path.join(dir1, "latest.json"))
