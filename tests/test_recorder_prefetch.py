"""Recorder honesty: the prefetch dequeue stall must land in the wait split.

Reference (SURVEY.md §3.5/§7 hard part 5): para_load's 'wait' segment
measured the residual input stall after overlap.  Round-1 regression: the
run() loop dequeued prefetched batches outside any recorder segment, so a
starved pipeline reported wait ~= 0 (VERDICT.md weak #2).
"""

import time

import jax
import numpy as np

from theanompi_tpu.models.wide_resnet import WideResNet
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import make_mesh
from theanompi_tpu.utils.recorder import Recorder

TINY = {
    "depth": 10,
    "widen": 1,
    "batch_size": 8,
    "n_epochs": 1,
    "lr": 0.05,
    "n_train": 64,
    "n_val": 16,
    "augment": False,
    "precision": "fp32",
    "verbose": False,
}


def _run_with_loader_delay(delay: float):
    model = WideResNet(dict(TINY))
    orig = model.data.train_batches

    def slow_batches(*args, **kwargs):
        for b in orig(*args, **kwargs):
            if delay:
                time.sleep(delay)
            yield b

    model.data.train_batches = slow_batches
    t = BSPTrainer(
        model,
        mesh=make_mesh(n_data=1, devices=jax.devices()[:1]),
        recorder=Recorder(verbose=False),
        prefetch_depth=1,
    )
    return t.run()


def test_starved_pipeline_reports_wait():
    """A throttled loader must show up as wait time, one entry per iter."""
    delay = 0.15
    rec = _run_with_loader_delay(delay)
    n_batches = TINY["n_train"] // TINY["batch_size"]
    waits = rec.time_history["wait"]
    assert len(waits) == n_batches
    # the first dequeue may be partially hidden by compile; over the epoch
    # the stall (8 x 150ms minus compute overlap) cannot stay near zero
    assert sum(waits) > 0.3, f"starved pipeline hid its stall: {waits}"


def test_profile_window_captures_trace(tmp_path, mesh8):
    """profile_dir + a [start, stop) window must produce a device trace on
    disk and switch tracing off afterwards (SURVEY.md §5 tracing row)."""
    import os

    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "profile_dir": str(tmp_path),
                       "profile_window": (1, 2)})
    rule.init(devices=8, model_config={
        "depth": 10, "widen": 1, "batch_size": 2, "image_size": 8,
        "n_train": 64, "n_val": 16, "n_epochs": 1, "precision": "fp32",
        "verbose": False})
    rule.wait()
    assert not rule.trainer._profiling
    found = [os.path.join(r, f) for r, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "no trace files written by the profile window"


def test_fed_pipeline_wait_is_small():
    """With an instant loader, wait must be a small share of calc."""
    rec = _run_with_loader_delay(0.0)
    wait, calc = sum(rec.time_history["wait"]), sum(rec.time_history["calc"])
    assert wait < max(0.25 * calc, 0.2), (wait, calc)


def test_cancel_discards_open_segment():
    r = Recorder(verbose=False)
    r.start("wait")
    r.cancel("wait")
    r.end_iteration()
    assert r.time_history["wait"] == [0.0]
    # cancel of a segment that was never started is a no-op
    r.cancel("calc")
