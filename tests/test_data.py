"""Data subsystem tests: sharded ImageNet loader, augmentation, prefetch."""

import os

import numpy as np
import pytest

from theanompi_tpu.models.data.imagenet import (
    ImageNetData,
    center_crop,
    random_crop_mirror,
    write_shards,
)
from theanompi_tpu.models.data.prefetch import Prefetcher, prefetch


def _fake_tree(tmp_path, n_train=40, n_val=24, size=40, classes=5, shard=16):
    r = np.random.RandomState(0)
    for split, n in (("train", n_train), ("val", n_val)):
        x = r.randint(0, 256, (n, size, size, 3)).astype(np.uint8)
        y = r.randint(0, classes, n).astype(np.int32)
        write_shards(os.path.join(tmp_path, split), x, y, shard)
    return str(tmp_path)


def test_shard_tree_roundtrip(tmp_path):
    path = _fake_tree(tmp_path)
    d = ImageNetData({"data_path": path, "image_size": 32, "n_classes": 5})
    assert not d.synthetic
    assert d.n_train == 40 and d.n_val == 24
    assert d.store_size == 40

    batches = list(d.train_batches(8, epoch=0, seed=0))
    assert len(batches) == 5  # 40 // 8, across shard boundaries (shard=16)
    for b in batches:
        assert b["x"].shape == (8, 32, 32, 3)
        # uint8 on the wire; normalization happens on device (norm_stats)
        assert b["x"].dtype == np.uint8
        assert b["y"].shape == (8,)
    vb = list(d.val_batches(8))
    assert len(vb) == 3


def test_epoch_shuffling_differs(tmp_path):
    path = _fake_tree(tmp_path)
    d = ImageNetData({"data_path": path, "image_size": 32})
    a = np.concatenate([b["y"] for b in d.train_batches(8, epoch=0)])
    b = np.concatenate([b["y"] for b in d.train_batches(8, epoch=1)])
    c = np.concatenate([b["y"] for b in d.train_batches(8, epoch=0)])
    assert not np.array_equal(a, b), "epochs must shuffle differently"
    np.testing.assert_array_equal(a, c)  # same epoch+seed reproducible


def test_val_deterministic_center_crop(tmp_path):
    path = _fake_tree(tmp_path)
    d = ImageNetData({"data_path": path, "image_size": 32})
    v1 = next(iter(d.val_batches(8)))
    v2 = next(iter(d.val_batches(8)))
    np.testing.assert_array_equal(v1["x"], v2["x"])


def test_synthetic_fallback_bounded_and_learnable():
    d = ImageNetData({"image_size": 32, "store_size": 40, "n_classes": 7,
                      "n_train": 64, "n_val": 32, "shard_size": 16})
    assert d.synthetic and d.n_classes == 7
    b = next(iter(d.train_batches(16, epoch=0)))
    assert b["x"].shape == (16, 32, 32, 3)
    assert set(np.unique(b["y"])) <= set(range(7))
    # deterministic: same epoch twice gives identical batches
    b2 = next(iter(d.train_batches(16, epoch=0)))
    np.testing.assert_array_equal(b["y"], b2["y"])


def test_crop_helpers():
    r = np.random.RandomState(0)
    x = np.arange(2 * 6 * 6 * 3, dtype=np.uint8).reshape(2, 6, 6, 3)
    c = center_crop(x, 4)
    assert c.shape == (2, 4, 4, 3)
    np.testing.assert_array_equal(c, x[:, 1:5, 1:5])
    a = random_crop_mirror(x, 4, r)
    assert a.shape == (2, 4, 4, 3)


def test_prefetcher_yields_everything_in_order():
    items = [{"x": np.full((2, 2), i)} for i in range(20)]
    out = list(Prefetcher(iter(items), depth=3))
    assert len(out) == 20
    for i, b in enumerate(out):
        assert b["x"][0, 0] == i


def test_prefetcher_propagates_errors():
    def gen():
        yield {"x": np.zeros(2)}
        raise RuntimeError("boom")

    p = Prefetcher(gen(), depth=2)
    next(p)
    with pytest.raises(RuntimeError, match="boom"):
        next(p)


def test_prefetcher_device_put(mesh8):
    import jax

    items = [{"x": np.zeros((8, 4), np.float32), "y": np.zeros((8,), np.int32)}]
    out = next(iter(prefetch(iter(items), mesh=mesh8, depth=2)))
    assert isinstance(out["x"], jax.Array)
    # leading dim sharded over the 8 data devices
    assert len(out["x"].sharding.device_set) == 8


def test_prefetch_depth_zero_passthrough():
    it = iter([1, 2, 3])
    assert prefetch(it, depth=0) is it


def test_bsp_with_imagenet_synthetic(mesh8):
    """End-to-end: BSP trainer consuming the sharded synthetic ImageNet."""
    from theanompi_tpu.models.alex_net import AlexNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh

    pytest.importorskip("jax")
    model = AlexNet({"batch_size": 4, "image_size": 64, "n_classes": 8,
                     "n_train": 64, "n_val": 32, "shard_size": 16,
                     "n_epochs": 1, "precision": "fp32", "lrn": False})
    t = BSPTrainer(model, mesh=make_mesh(n_data=8))
    t.compile_iter_fns()
    t.init_state()
    m = None
    for batch in model.data.train_batches(t.global_batch, 0, seed=0):
        m = t.train_iter(batch, lr=0.01)
    assert m is not None and np.isfinite(float(m["cost"]))


def test_synthetic_sequence_large_vocab_sparse():
    """vocab > dense limit: the procedural-sparse generator — no O(V^2)
    table, tokens in range, bigram structure learnable (<= 32 distinct
    successors per token), deterministic across constructions."""
    from theanompi_tpu.models.data.base import SyntheticSequenceDataset

    d1 = SyntheticSequenceDataset(n_train=64, n_val=8, seq_len=64,
                                  vocab=32768)
    d2 = SyntheticSequenceDataset(n_train=64, n_val=8, seq_len=64,
                                  vocab=32768)
    assert not hasattr(d1, "_probs")
    np.testing.assert_array_equal(d1._train, d2._train)
    assert d1._train.min() >= 0 and d1._train.max() < 32768
    succ = {}
    for row in d1._train:
        for a, b in zip(row[:-1], row[1:]):
            succ.setdefault(int(a), set()).add(int(b))
    assert max(len(s) for s in succ.values()) <= 32


def test_convert_hkl_tree_with_stubbed_hickle(tmp_path, monkeypatch):
    """The .hkl conversion loop, with ``hickle`` stubbed (VERDICT r4 #5).

    hickle is not installed in this image, so the real format has never
    been read here (stated in the docstring/README); this covers what CAN
    be covered without it: lexicographic file ordering, the CHW->HWC
    transpose branch, uint8 output, and that the output pairs with
    ``write_shards``-style label files into a loadable ``ImageNetData``.
    """
    import sys
    import types

    from theanompi_tpu.models.data.imagenet import convert_hkl_tree

    rng = np.random.RandomState(0)
    shards = {}  # abs path -> array the stub returns
    src = tmp_path / "hkl"
    src.mkdir()
    for i in range(3):
        # reference-era layout: CHW, one shard per file, float-ish storage
        arr = rng.randint(0, 255, size=(4, 3, 8, 8)).astype(np.float32)
        p = src / f"train_{i:02d}.hkl"
        p.write_bytes(b"")  # listdir needs the file to exist
        shards[str(p)] = arr
    (src / "ignore.txt").write_text("not a shard")

    stub = types.ModuleType("hickle")
    stub.load = lambda path: shards[str(path)]
    monkeypatch.setitem(sys.modules, "hickle", stub)

    dst = tmp_path / "npy" / "train"
    convert_hkl_tree(str(src), str(dst))

    xs = sorted(os.listdir(dst))
    assert xs == ["x_0000.npy", "x_0001.npy", "x_0002.npy"]
    for i, f in enumerate(xs):
        out = np.load(dst / f)
        assert out.dtype == np.uint8 and out.shape == (4, 8, 8, 3)  # HWC
        expect = shards[str(src / f"train_{i:02d}.hkl")]
        np.testing.assert_array_equal(
            out, expect.transpose(0, 2, 3, 1).astype(np.uint8))
        # labels live in sibling .npy files in the reference recipe
        np.save(dst / f.replace("x_", "y_"),
                np.arange(4, dtype=np.int32) % 2)
    # the converted tree is a loadable split for the production loader
    (tmp_path / "npy" / "val").mkdir()
    for f in xs:
        np.save(tmp_path / "npy" / "val" / f, np.load(dst / f))
        np.save(tmp_path / "npy" / "val" / f.replace("x_", "y_"),
                np.arange(4, dtype=np.int32) % 2)
    ds = ImageNetData({"data_path": str(tmp_path / "npy"), "image_size": 8,
                       "n_classes": 2})
    assert not ds.synthetic and ds.n_train == 12
    batch = next(iter(ds.train_batches(4, epoch=0, seed=0)))
    assert batch["x"].shape == (4, 8, 8, 3) and batch["x"].dtype == np.uint8


# -- bounded-retry reads (ISSUE 5 satellite) ----------------------------------

def test_read_with_retry_transient_then_success():
    from theanompi_tpu.models.data.base import read_with_retry

    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient EIO")
        return "payload"

    out = read_with_retry(flaky, what="x_0000.npy", retries=4,
                          backoff_s=0.05, sleep=sleeps.append)
    assert out == "payload" and calls["n"] == 3
    assert sleeps == [0.05, 0.1]  # doubling backoff, no sleep after success


def test_read_with_retry_exhaustion_raises_typed_error():
    from theanompi_tpu.models.data.base import DataReadError, read_with_retry

    def dead():
        raise OSError("mount is gone")

    with pytest.raises(DataReadError, match="4 attempts.*mount is gone") \
            as ei:
        read_with_retry(dead, what="x_0000.npy", retries=4,
                        sleep=lambda s: None)
    assert isinstance(ei.value.__cause__, OSError)


def test_shardset_load_raises_data_read_error_after_retries(tmp_path):
    """The imagenet shard reader goes through the retry wrapper: a shard
    that vanishes mid-run surfaces as the typed DataReadError, not the
    first raw IOError (total default backoff is ~0.35 s — bounded, not
    eternal, and cheap enough to pay for real here)."""
    from theanompi_tpu.models.data.base import DataReadError
    from theanompi_tpu.models.data.imagenet import _ShardSet

    path = _fake_tree(tmp_path)
    s = _ShardSet(os.path.join(path, "train"))
    x, y = s.load(0)  # healthy read
    assert len(x) == len(y)
    os.remove(s.x_files[0])
    with pytest.raises(DataReadError, match="attempts"):
        s.load(0)
