"""CPU-mesh dry-run of the BASELINE.md RUNBOOK commands (VERDICT r4 #8).

The v5e-16 north-star procedure can't execute on this image (one tunneled
chip), so this locks the *procedure*: the exact CLI entry points and flags
the RUNBOOK documents must parse, run end-to-end on the virtual mesh at
tiny scale, and emit artifacts with the fields the RUNBOOK's efficiency
arithmetic reads.  If a flag or artifact key changes, this breaks before
the doc rots.
"""

import json
import os

from theanompi_tpu import launcher
from theanompi_tpu.utils import scaling


def test_runbook_scaling_command(tmp_path):
    """RUNBOOK steps 1-3 at toy scale: same flags, tiny steps/batch/images
    (--set shrinks the conv geometry so the CPU dry-run compiles in seconds
    rather than minutes — the flags and artifact schema stay the real ones)."""
    out = str(tmp_path / "SCALING_v5e16_host.json")
    scaling.main([
        "--model", "resnet50",
        "--batch-size", "4", "--ns", "1,2", "--steps", "2", "--trials", "1",
        "--set", "image_size=32", "--set", "store_size=40",
        "--set", "stage_blocks=(1,1,1,1)",
        "--set", "n_classes=4", "--set", "n_train=32", "--set", "n_val=16",
        "--set", "shard_size=16", "--set", "precision=fp32",
        "--strategy", "psum_bf16_bucket", "--out", out,
    ])
    art = json.load(open(out))
    # the fields step 3's verdict arithmetic reads, per rung (JSON turns
    # the int keys into strings)
    for n in ("1", "2"):
        row = art["per_n"][n]
        assert row["imgs_per_sec_per_chip"] > 0
        assert "comm_share" in row and "efficiency" in row
    eff = (art["per_n"]["2"]["imgs_per_sec_per_chip"]
           / art["per_n"]["1"]["imgs_per_sec_per_chip"])
    assert eff > 0  # the cross-artifact ratio the RUNBOOK computes


def test_runbook_launcher_command(tmp_path):
    """RUNBOOK step 4's tmlauncher invocation, shrunk to one tiny epoch
    (now with the ISSUE 3 knobs: --compile-cache-dir, --checkpoint-dir and
    the checkpoint_async rule key the RUNBOOK documents)."""
    import jax

    record = str(tmp_path / "record")
    telemetry = str(tmp_path / "telemetry")
    cache = str(tmp_path / "ccache")
    ckpt = str(tmp_path / "ckpt")
    try:
        rc = launcher.main([
            "--rule", "BSP", "--devices", "8",
            "--modelfile", "theanompi_tpu.models.resnet50",
            "--modelclass", "ResNet50",
            "--set", "batch_size=2", "--set", "n_epochs=1",
            "--set", "image_size=32", "--set", "store_size=40",
            "--set", "stage_blocks=(1,1,1,1)",
            "--set", "n_classes=4", "--set", "n_train=32", "--set", "n_val=16",
            "--set", "shard_size=16", "--set", "precision=fp32",
            "--rule-set", "exch_strategy=psum_bf16_bucket",
            "--rule-set", "exch_bucket_mb=4",
            "--rule-set", "exch_overlap=True",
            "--rule-set", "checkpoint_async=True",
            "--checkpoint-dir", ckpt, "--compile-cache-dir", cache,
            "--record-dir", record, "--telemetry-dir", telemetry, "--quiet",
        ])
    finally:
        # the cache dir is a tmp_path about to vanish: un-wire it so later
        # tests' compiles don't try to persist into a deleted directory
        jax.config.update("jax_compilation_cache_dir", None)
    assert rc == 0
    # the recorder histories the RUNBOOK points at
    assert any(f.endswith(".npy") for f in os.listdir(record))
    # the telemetry artifacts the RUNBOOK's observability step points at
    files = os.listdir(telemetry)
    assert any(f.startswith("events-rank") for f in files)
    assert "trace.json" in files and "summary.json" in files
    trace = json.load(open(os.path.join(telemetry, "trace.json")))
    assert trace["traceEvents"]
    # the ISSUE 3 knobs did their jobs: compile cache populated, an async
    # checkpoint published with its latest pointer
    assert any(f.endswith("-cache") for f in os.listdir(cache))
    assert "latest.json" in os.listdir(ckpt)
    assert any(f.startswith("ckpt_e") and f.endswith(".npz")
               for f in os.listdir(ckpt))


def test_runbook_supervised_command(tmp_path, monkeypatch,
                                    subproc_compile_cache):
    """RUNBOOK step 5's supervised launch (`--supervise --max-restarts 3`)
    at toy scale: the supervisor parent runs in-process, the session runs
    in a child process, and the resilience.json audit trail lands next to
    the checkpoints (the exact flags BASELINE.md documents — ISSUE 4)."""
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
    monkeypatch.setenv("JAX_THREEFRY_PARTITIONABLE", "true")
    monkeypatch.setenv("PYTHONPATH",
                       repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    monkeypatch.delenv("THEANOMPI_FAULT_PLAN", raising=False)
    assert sys.executable
    ckpt = str(tmp_path / "ckpt")
    rc = launcher.main([
        "--rule", "BSP", "--devices", "4",
        "--modelfile", "theanompi_tpu.models.wide_resnet",
        "--modelclass", "WideResNet",
        "--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
        "--set", "image_size=8", "--set", "n_train=32", "--set", "n_val=16",
        "--set", "n_epochs=1", "--set", "precision='fp32'",
        "--checkpoint-dir", ckpt,
        "--compile-cache-dir", subproc_compile_cache,
        "--supervise", "--max-restarts", "3", "--backoff-base", "0.5",
        "--quiet",
    ])
    assert rc == 0
    art = json.load(open(os.path.join(ckpt, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["clean"]
    assert art["restarts"] == 0 and art["final_exit"] == 0
    assert "latest.json" in os.listdir(ckpt)


def test_runbook_data_resume_command(tmp_path, monkeypatch,
                                     subproc_compile_cache):
    """RUNBOOK step 5d's mid-epoch kill/resume rehearsal (ISSUE 10): the
    exact flag set BASELINE.md documents — `--rule-set
    checkpoint_every_n_iters=N` under `--supervise` with
    THEANOMPI_DATA_TRACE — killed one step INTO epoch 1, restarted, and
    the trace audit the runbook describes holds: one line per completed
    step, no batch replayed, none skipped."""
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
    monkeypatch.setenv("JAX_THREEFRY_PARTITIONABLE", "true")
    monkeypatch.setenv("PYTHONPATH",
                       repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    trace = str(tmp_path / "trace")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", trace)
    monkeypatch.setenv("THEANOMPI_FAULT_PLAN", "step:kill@3@1")
    assert sys.executable
    ckpt = str(tmp_path / "ckpt")
    rc = launcher.main([
        "--rule", "BSP", "--devices", "4",
        "--modelfile", "theanompi_tpu.models.wide_resnet",
        "--modelclass", "WideResNet",
        "--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
        "--set", "image_size=8", "--set", "n_train=32", "--set", "n_val=16",
        "--set", "n_epochs=2", "--set", "precision='fp32'",
        "--rule-set", "checkpoint_every_n_iters=1",
        # the runbook's determinism note: synchronous cadence saves
        "--rule-set", "checkpoint_async=False",
        "--checkpoint-dir", ckpt,
        "--compile-cache-dir", subproc_compile_cache,
        "--supervise", "--max-restarts", "3", "--backoff-base", "0.1",
        "--quiet",
    ])
    assert rc == 0
    art = json.load(open(os.path.join(ckpt, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]
    # the runbook's trace audit: gap-free, duplicate-free consumed-step
    # sequence across both attempts (2 epochs x 2 steps)
    lines = [tuple(int(v) for v in l.split())
             for l in open(trace) if l.strip()]
    assert lines == [(0, 0), (0, 1), (1, 0), (1, 1)]


def test_runbook_exchange_bench_command(tmp_path):
    """The RUNBOOK's exchange-strategy comparison sidebar: the exact
    --exchange-bench CLI must run and emit the per-strategy artifact
    (cross-strategy ratio/count assertions live in test_scaling — this
    locks the CLI flags + artifact schema at one-strategy cost)."""
    out = str(tmp_path / "EXCHANGE.json")
    scaling.main([
        "--model", "wide_resnet", "--exchange-bench", "--ns", "4",
        "--batch-size", "4", "--steps", "2",
        "--set", "depth=10", "--set", "widen=1", "--set", "image_size=8",
        "--set", "n_train=32", "--set", "n_val=16",
        "--set", "precision=fp32",
        "--strategies", "psum_bf16_bucket", "--bucket-mb", "4",
        "--overlap", "--out", out,
    ])
    art = json.load(open(out))
    assert art["overlap"] is True
    row = art["per_strategy"]["psum_bf16_bucket"]
    assert row["wire_bytes_per_step"] > 0
    assert row["collectives"].get("all-reduce", 0) >= 1
    assert row["buckets"]["bucket_bytes"] == 4 * 2**20
    assert row["step_ms"] > 0
    # the ISSUE 12 overlap column: fused-vs-overlapped step time, the
    # collective-count invariant, and both differential comm shares
    assert row["step_ms_overlap"] > 0
    assert row["overlap_collectives_equal"] is True
    assert 0.0 <= row["comm_share_differential"] <= 1.0
    assert 0.0 <= row["comm_share_differential_overlap"] <= 1.0


def test_runbook_serve_command(tmp_path, capsys):
    """RUNBOOK step 6 (ISSUE 6): the exact `tmserve` invocation — verified
    read-only checkpoint load (matching --set config), continuous-batching
    engine, --quantize-int8, --telemetry-dir, SERVE.json artifact with the
    fields the runbook's headroom procedure reads."""
    import jax
    import numpy as np

    from theanompi_tpu.launcher import _parse_kv
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.serving import cli as serve_cli
    from theanompi_tpu.utils.checkpoint import Checkpointer, model_fingerprint

    tiny = ["dim=32", "heads=2", "n_layers=1", "seq_len=32", "vocab=61",
            "dropout=0.0", "precision=fp32", "n_train=64", "n_val=32"]
    # a training-writer checkpoint with the FULL run fingerprint — the
    # serving load must match on the model-identity subset only
    model = TransformerLM(_parse_kv(tiny))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    writer = Checkpointer(ckpt, fingerprint={
        "mesh": {"data": 8}, "exchange": "psum_bf16_bucket", "n_subb": 1,
        **model_fingerprint(model)})
    writer.save(0, 5, {"params": jax.tree.map(np.asarray, params)})
    writer.mark_clean()

    out = str(tmp_path / "SERVE.json")
    tel = str(tmp_path / "telemetry-serve")
    rc = serve_cli.main([
        "--modelclass", "TransformerLM",
        *[a for s in tiny for a in ("--set", s)],
        "--checkpoint-dir", ckpt, "--requests", "4", "--arrival-rate", "50",
        "--prompt-len", "4", "--max-new-tokens", "4",
        "--max-batch", "2", "--block-size", "4", "--quantize-int8",
        "--telemetry-dir", tel, "--out", out, "--quiet",
    ])
    assert rc == 0
    art = json.load(open(out))
    # the fields step 6's headroom procedure reads
    assert art["metric"] == "serve_tokens_per_sec" and art["value"] > 0
    assert art["requests"] == 4 and art["checkpoint_epoch"] == 0
    assert "preemptions" in art and art["quantized_int8"]
    for h in ("ttft_ms", "token_ms"):
        assert "p50" in art[h] and "p99" in art[h]
    # one-JSON-line stdout (bench contract) + the Perfetto trace
    line = [l for l in capsys.readouterr().out.splitlines()
            if l.startswith("{")][-1]
    assert json.loads(line)["metric"] == "serve_tokens_per_sec"
    trace = json.load(open(os.path.join(tel, "trace.json")))
    names = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert "serve.prefill" in names and "serve.decode" in names


def test_runbook_serve_prefix_cache_command(tmp_path):
    """BASELINE step 6c (ISSUE 17): the exact multi-turn prefix-cache
    rehearsal invocation — --prefix-cache with --turns/--shared-prefix-len
    traffic — and the SERVE.json accounting fields the step reads
    (prefix_cache, prefix_hit_rate > 0, prefill_tokens_saved > 0)."""
    import jax
    import numpy as np

    from theanompi_tpu.launcher import _parse_kv
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.serving import cli as serve_cli
    from theanompi_tpu.utils.checkpoint import Checkpointer, model_fingerprint

    tiny = ["dim=32", "heads=2", "n_layers=1", "seq_len=32", "vocab=61",
            "dropout=0.0", "precision=fp32", "n_train=64", "n_val=32"]
    model = TransformerLM(_parse_kv(tiny))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    writer = Checkpointer(ckpt, fingerprint={
        "mesh": {"data": 8}, "exchange": "psum_bf16_bucket", "n_subb": 1,
        **model_fingerprint(model)})
    writer.save(0, 5, {"params": jax.tree.map(np.asarray, params)})
    writer.mark_clean()

    out = str(tmp_path / "SERVE.json")
    rc = serve_cli.main([
        "--modelclass", "TransformerLM",
        *[a for s in tiny for a in ("--set", s)],
        "--checkpoint-dir", ckpt, "--requests", "6", "--arrival-rate", "50",
        "--prompt-len", "4", "--max-new-tokens", "4",
        "--max-batch", "2", "--block-size", "4",
        "--prefix-cache", "--turns", "3", "--shared-prefix-len", "8",
        "--out", out, "--quiet",
    ])
    assert rc == 0
    art = json.load(open(out))
    # the fields step 6c's procedure reads
    assert art["prefix_cache"] is True
    assert art["prefix_hit_rate"] > 0
    assert art["prefill_tokens_saved"] > 0
    assert art["requests"] == 6 and art["value"] > 0


def test_runbook_serve_decode_kernel_ab(tmp_path):
    """BASELINE step 6d (ISSUE 18): the decode-kernel A/B pair — the
    exact step-6 invocation re-run with --decode-kernel on and off — and
    the SERVE.json fields the comparison reads (decode_kernel naming the
    served impl, decode_step_ms percentiles per variant).  On the CPU
    dry-run "on" resolves to the Mosaic interpreter (bit-identical to the
    fallback by the tier-1 parity lock)."""
    import json

    from theanompi_tpu.serving import cli as serve_cli

    tiny = ["dim=32", "heads=2", "n_layers=1", "seq_len=32", "vocab=61",
            "dropout=0.0", "precision=fp32", "n_train=64", "n_val=32"]
    impls = {}
    for variant in ("on", "off"):
        out = str(tmp_path / f"SERVE_{variant}.json")
        rc = serve_cli.main([
            "--modelclass", "TransformerLM",
            *[a for s in tiny for a in ("--set", s)],
            "--requests", "3", "--prompt-len", "4", "--max-new-tokens", "4",
            "--max-batch", "2", "--block-size", "4",
            "--decode-kernel", variant, "--out", out, "--quiet",
        ])
        assert rc == 0
        art = json.load(open(out))
        impls[variant] = art["decode_kernel"]
        assert art["value"] > 0
        assert "p50" in art["decode_step_ms"]
        assert "p99" in art["decode_step_ms"]
    assert impls["off"] == "fallback"
    assert impls["on"] == "kernel_interpret"  # CPU host: interpreter


def test_runbook_router_command(tmp_path, monkeypatch, capsys):
    """BASELINE step 6e (ISSUE 19): the exact `tmrouter` invocation at
    toy scale — two REAL tmserve replicas leased as ``kind="serving"``
    fleet jobs on the mesh8 pool, the seeded open-loop trace balanced
    over their durable queues, and the ROUTER.json fields the step's
    procedure reads (exactly_once, router-visible ttft_ms percentiles,
    replica_trajectory, fleet_exit).  The contention/autoscale half is
    locked at full depth in test_router_e2e.py; replicas here inherit
    the session compile cache through the fleet child env."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("PYTHONPATH",
                       repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    monkeypatch.delenv("THEANOMPI_FAULT_PLAN", raising=False)
    monkeypatch.delenv("THEANOMPI_DATA_TRACE", raising=False)
    from theanompi_tpu.router import cli as router_cli

    d = str(tmp_path / "fleet")
    out = str(tmp_path / "ROUTER.json")
    tel = str(tmp_path / "telemetry-router")
    # same tiny shapes as the other step-6 dry-runs: the replica
    # subprocesses hit the session compile cache those tests warmed
    rc = router_cli.main([
        "--fleet-dir", d, "--pool-size", "8",
        "--replicas", "2", "--max-replicas", "2", "--replica-devices", "2",
        "--modelclass", "TransformerLM",
        "--set", "dim=32", "--set", "heads=2", "--set", "n_layers=1",
        "--set", "seq_len=32", "--set", "vocab=61", "--set", "dropout=0.0",
        "--set", "precision='fp32'", "--set", "n_train=64",
        "--set", "n_val=32",
        "--replica-arg=--max-batch", "--replica-arg=2",
        "--replica-arg=--block-size", "--replica-arg=4",
        "--requests", "4", "--vocab", "61", "--prompt-len", "4",
        "--max-new-tokens", "4", "--timeout-s", "120",
        "--telemetry-dir", tel, "--out", out, "--quiet",
    ])
    assert rc == 0
    art = json.load(open(out))
    # the fields step 6e's procedure reads
    assert art["exactly_once"] is True
    assert art["requests"] == 4 and art["answered"] == 4
    assert art["terminal_states"] == {"done": 4}
    assert art["metric"] == "router_tokens_per_sec" and art["value"] > 0
    assert "p50" in art["ttft_ms"] and "p99" in art["ttft_ms"]
    assert art["replicas_spawned"] == 2 and art["replicas_dead"] == 0
    assert art["fleet_exit"] == 0
    assert art["replica_trajectory"][-1][1] == 2
    # one-JSON-line stdout (bench contract)
    line = [l for l in capsys.readouterr().out.splitlines()
            if l.startswith("{")][-1]
    assert json.loads(line)["metric"] == "router_tokens_per_sec"
    # router.* telemetry flowed through the registered names
    ev_files = [f for f in os.listdir(tel) if f.startswith("events-rank")]
    assert ev_files
    body = open(os.path.join(tel, ev_files[0])).read()
    assert "router.dispatch" in body
    # every lease returned: both replica jobs drained to done
    from theanompi_tpu.fleet import read_record

    for jid in ("replica-0", "replica-1"):
        assert read_record(d, jid).status == "done"


def test_runbook_serve_resilience_command(tmp_path):
    """RUNBOOK step 6b (ISSUE 14): the resilient-serving flags of the
    exact invocation — deadlines + --shed, --drain-s, --rollout-watch —
    and the SERVE.json fields the runbook reads (terminal_states summing
    to requests, the rollout block, attempt, REQUESTS.jsonl).  The
    --supervise half (drain-under-SIGTERM, crash restart) is locked by
    the subprocess e2es in tests/test_serving_resilience.py."""
    import jax
    import numpy as np

    from theanompi_tpu.launcher import _parse_kv
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.serving import TERMINAL_STATES, terminal_rids
    from theanompi_tpu.serving import cli as serve_cli
    from theanompi_tpu.utils.checkpoint import Checkpointer, model_fingerprint

    tiny = ["dim=32", "heads=2", "n_layers=1", "seq_len=32", "vocab=61",
            "dropout=0.0", "precision=fp32", "n_train=64", "n_val=32"]
    model = TransformerLM(_parse_kv(tiny))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    writer = Checkpointer(ckpt, fingerprint={
        "mesh": {"data": 8}, "exchange": "psum_bf16_bucket", "n_subb": 1,
        **model_fingerprint(model)})
    writer.save(0, 5, {"params": jax.tree.map(np.asarray, params)})
    writer.mark_clean()

    out = str(tmp_path / "SERVE.json")
    tel = str(tmp_path / "telemetry-serve")
    rc = serve_cli.main([
        "--modelclass", "TransformerLM",
        *[a for s in tiny for a in ("--set", s)],
        "--checkpoint-dir", ckpt, "--requests", "4", "--arrival-rate", "50",
        "--prompt-len", "4", "--max-new-tokens", "4",
        "--max-batch", "2", "--block-size", "4",
        "--total-deadline-ms", "30000", "--shed", "--drain-s", "20",
        "--rollout-watch", "--rollout-probation-s", "60",
        "--telemetry-dir", tel, "--out", out, "--quiet",
    ])
    assert rc == 0
    art = json.load(open(out))
    # the fields step 6b's procedure reads
    states = art["terminal_states"]
    assert set(states) <= set(TERMINAL_STATES)
    assert sum(states.values()) == art["requests"] == 4
    assert states.get("done") == 4  # nothing shed/expired at this load
    roll = art["rollout"]
    assert roll["rollouts"] == roll["rollbacks"] == roll["refused"] == 0
    assert roll["serving_epoch"] == 0
    assert art["attempt"] == 1 and art["drained"] is False
    # the durable per-request log a supervised restart dedups against
    assert terminal_rids(os.path.join(tel, "REQUESTS.jsonl")) == {0, 1, 2, 3}


def test_runbook_checkpoint_scrubber_command(tmp_path, capsys):
    """The RUNBOOK's checkpoint-hygiene step (ISSUE 5): the exact
    `python -m theanompi_tpu.utils.checkpoint --verify DIR` scrubber CLI
    must run, report per-checkpoint verdicts, and exit 0 on a healthy
    directory / 77 when anything fails verification."""
    import numpy as np

    from theanompi_tpu.utils import checkpoint as ck_mod

    d = str(tmp_path / "ckpt")
    ck = ck_mod.Checkpointer(d, keep=5)
    tree = {"a": np.arange(6, dtype=np.float32)}
    for e in range(2):
        ck.save(e, e, {"params": tree})
    assert ck_mod.main(["--verify", d]) == 0
    out = capsys.readouterr().out
    assert "2/2 checkpoints verifiable" in out
    # rot one file: the scrubber reports it and flips to the exit-code-
    # contract's checkpoint code (77)
    path = ck._path(1)
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        b = f.read(1)
        f.seek(os.path.getsize(path) // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    assert ck_mod.main(["--verify", d]) == 77
    assert "CORRUPT" in capsys.readouterr().out


def test_runbook_reshard_plan_command(tmp_path, capsys):
    """The RUNBOOK's elastic-resume dry run (ISSUE 8): the exact
    `python -m theanompi_tpu.utils.checkpoint --reshard-plan DIR
    --to-devices N` invocation must plan a topology transition from the
    manifest alone and exit 0 plannable / 79 refused."""
    import numpy as np

    from theanompi_tpu.resilience import EXIT_RESHARD
    from theanompi_tpu.utils import checkpoint as ck_mod

    d = str(tmp_path / "ckpt")
    ck = ck_mod.Checkpointer(d, fingerprint={
        "mesh": {"data": 16, "pipe": 1, "model": 1, "seq": 1},
        "exchange": "zero1", "n_subb": 1,
        "model": "ResNet50", "model_config_sha": "deadbeef"})
    ck.save(0, 40, {
        "params": {"w": np.zeros((30,), np.float32)},
        "opt_state": {"velocity": [np.zeros((32,), np.float32)]}})
    ck.mark_clean()
    assert ck_mod.main(["--reshard-plan", d, "--to-devices", "8"]) == 0
    out = capsys.readouterr().out
    assert "reshard plan: 16 -> 8 workers" in out
    assert "LR x0.5" in out and "plannable" in out
    # an unplannable transition flips to the contract's reshard code
    assert ck_mod.main(["--reshard-plan", d, "--to-devices", "8",
                        "--strategy", "psum"]) == EXIT_RESHARD
    assert "REFUSED" in capsys.readouterr().out
    # the launcher accepts the runbook's --elastic spelling
    args = launcher.build_parser().parse_args(
        ["--elastic", "--devices", "all"])
    assert args.elastic


def test_runbook_tmlint_command(tmp_path, capsys):
    """The RUNBOOK's static-analysis gate (ISSUE 7): the exact
    `python -m theanompi_tpu.analysis --report LINT.json` invocation must
    run clean over the tree (exit 0), write the artifact with an empty
    findings list, and keep the justified suppressions auditable."""
    from theanompi_tpu.analysis import cli as lint_cli

    report = str(tmp_path / "LINT.json")
    rc = lint_cli.main(["--report", report])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 finding(s)" in out
    rep = json.loads(open(report).read())
    assert rep["tool"] == "tmlint" and rep["findings"] == []
    assert rep["summary"]["suppressed"] > 0  # markers stay visible


def test_runbook_tmlint_concurrency_tier(capsys):
    """BASELINE step 7's concurrency dry-run (ISSUE 15): the exact
    `tmlint --rules atomic-publish,guarded-state,thread-lifecycle,lock-order`
    subset must sweep the package clean — every durable writer publishes
    via os.replace (or carries a justified suppression), mixed-guard
    state and unnamed/unjoined threads stay out, and every nested lock
    acquisition matches the declared LOCK_ORDER_DAG."""
    from theanompi_tpu.analysis import cli as lint_cli

    rc = lint_cli.main(["--rules",
                        "atomic-publish,guarded-state,thread-lifecycle,"
                        "lock-order", "--quiet"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 finding(s)" in out
    # the append-mode audit logs ride on justified suppressions — they
    # must stay visible in the summary, not vanish
    import re

    m = re.search(r"(\d+) suppressed", out)
    assert m and int(m.group(1)) > 0


def test_runbook_fleet_command(tmp_path, monkeypatch, subproc_compile_cache):
    """RUNBOOK step 8's fleet rehearsal (ISSUE 11) at toy scale: the exact
    `tmfleet submit` / `run` / `status` flags BASELINE.md documents must
    drive two jobs through one mesh8 pool to completion (they fit side by
    side here — the contention/preemption half of the rehearsal is locked
    at full depth in test_fleet.py) and leave the artifacts the runbook
    reads: per-job job.json + resilience.json, fleet_events.jsonl, and
    the status JSON with every lease returned."""
    import sys

    from theanompi_tpu.fleet import cli as fleet_cli
    from theanompi_tpu.fleet import read_fleet_events

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
    monkeypatch.setenv("JAX_THREEFRY_PARTITIONABLE", "true")
    monkeypatch.setenv("PYTHONPATH",
                       repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    monkeypatch.delenv("THEANOMPI_FAULT_PLAN", raising=False)
    monkeypatch.delenv("THEANOMPI_DATA_TRACE", raising=False)
    assert sys.executable
    d = str(tmp_path / "fleet")
    tiny = ["--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
            "--set", "image_size=8", "--set", "n_train=32",
            "--set", "n_val=16", "--set", "n_epochs=1",
            "--set", "precision='fp32'",
            f"--extra-arg=--compile-cache-dir={subproc_compile_cache}"]
    for jid, pri in (("nightly", 0), ("ablation", 5)):
        assert fleet_cli.main([
            "submit", "--fleet-dir", d, "--job-id", jid,
            "--priority", str(pri), "--min-devices", "4",
            "--max-devices", "4", "--max-restarts", "3",
            "--backoff-base", "0.1", *tiny]) == 0
    assert fleet_cli.main(["run", "--fleet-dir", d, "--pool-size", "8",
                           "--quiet"]) == 0
    # the status JSON the runbook's verdict step reads
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert fleet_cli.main(["status", "--fleet-dir", d]) == 0
    status = json.loads(buf.getvalue())
    assert {j["status"] for j in status["jobs"]} == {"done"}
    assert status["pool"]["pool_size"] == 8 and status["pool"]["leases"] == {}
    # per-job artifacts: supervisor audit trail + published checkpoint
    for jid in ("nightly", "ablation"):
        jdir = os.path.join(d, "jobs", jid)
        art = json.load(open(os.path.join(jdir, "resilience.json")))
        assert art["final_exit"] == 0
        assert "latest.json" in os.listdir(os.path.join(jdir, "ckpt"))
    names = [e["event"] for e in read_fleet_events(d)]
    assert names.count("fleet.schedule") == 2
    assert names.count("fleet.complete") == 2


def test_runbook_fleet_async_command(tmp_path, monkeypatch,
                                     subproc_compile_cache):
    """RUNBOOK step 8b's contended-async rehearsal (ISSUE 20) at toy
    scale: the exact `tmfleet submit --rule EASGD` flags BASELINE.md
    documents, with a straggler injected through the documented
    `THEANOMPI_FAULT_PLAN`/`THEANOMPI_EASGD_SLOW_S` env pair, must drive
    the EASGD job to completion and leave the artifacts the step's
    verdict reads: per-job telemetry with `easgd.exchange` instants and
    a HEALTH.json whose async_staleness verdict is ok/warn, never
    critical.  (The preemption/elastic-resume half runs at full depth in
    test_fleet.py's chaos acceptance — this locks the CLI surface.)"""
    import sys

    from theanompi_tpu.fleet import cli as fleet_cli
    from theanompi_tpu.fleet import read_fleet_events
    from theanompi_tpu.fleet.jobs import read_record
    from theanompi_tpu.telemetry.health import read_health

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
    monkeypatch.setenv("JAX_THREEFRY_PARTITIONABLE", "true")
    monkeypatch.setenv("PYTHONPATH",
                       repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    monkeypatch.delenv("THEANOMPI_DATA_TRACE", raising=False)
    # the documented injection pair: one straggler at the second
    # elastic exchange, shrunk from the runbook's 0.6 s to keep the
    # dry-run fast (the flag surface is what this test locks)
    monkeypatch.setenv("THEANOMPI_FAULT_PLAN", "easgd:worker_slow@1")
    monkeypatch.setenv("THEANOMPI_EASGD_SLOW_S", "0.05")
    assert sys.executable
    d = str(tmp_path / "pool")
    tel = os.path.join(d, "jobs", "nightly-easgd", "telemetry")
    assert fleet_cli.main([
        "submit", "--fleet-dir", d, "--job-id", "nightly-easgd",
        "--priority", "0", "--min-devices", "4", "--max-devices", "4",
        "--rule", "EASGD", "--rule-set", "tau=1",
        "--rule-set", "scale_lr=False",
        "--rule-set", "checkpoint_every_n_iters=1",
        "--rule-set", "telemetry_health={'tick_s': 0.05}",
        "--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
        "--set", "image_size=8", "--set", "n_train=32",
        "--set", "n_val=16", "--set", "n_epochs=1",
        "--set", "precision='fp32'",
        "--max-restarts", "3", "--backoff-base", "0.1",
        f"--extra-arg=--compile-cache-dir={subproc_compile_cache}",
        f"--extra-arg=--telemetry-dir={tel}"]) == 0
    assert fleet_cli.main(["run", "--fleet-dir", d, "--pool-size", "8",
                           "--quiet"]) == 0
    rec = read_record(d, "nightly-easgd")
    assert rec.status == "done" and rec.spec.rule == "EASGD"
    names = [e["event"] for e in read_fleet_events(d)]
    assert names.count("fleet.schedule") == 1
    assert names.count("fleet.complete") == 1
    # the per-job telemetry the step-8b verdict reads
    ev_files = [f for f in sorted(os.listdir(tel))
                if f.startswith("events-rank")]
    assert ev_files
    events = [json.loads(ln)
              for ln in open(os.path.join(tel, ev_files[0]))]
    rounds = [e for e in events if e.get("name") == "easgd.exchange"]
    assert rounds  # tau=1, 2 steps/epoch -> 2 exchange instants
    assert all("staleness" in e and "stretch" in e for e in rounds)
    health = read_health(tel)
    assert health is not None
    sevs = {v["detector"]: v["severity"] for v in health["verdicts"]}
    assert sevs.get("async_staleness", "ok") in ("ok", "warn")


def test_runbook_tmprof_command(tmp_path, capsys):
    """BASELINE step 9 (ISSUE 16): the exact `tmprof ./telemetry` and
    `tmprof --ledger update/check` invocations.  The attribution table
    must come from a real telemetry dir (segments partitioning the
    window), the update must ingest a RUNBOOK artifact, and the check
    over the repo's committed, backfilled PERF_LEDGER.jsonl must exit 0
    — the acceptance's no-false-regression half."""
    from theanompi_tpu.telemetry import Telemetry
    from theanompi_tpu.telemetry import prof

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tel_dir = str(tmp_path / "telemetry")
    tel = Telemetry(tel_dir, rank=0, profile=True)
    t = 100.0
    for step in range(3):
        tel.emit_span("recorder.wait", t, 0.004)
        t += 0.004
        tel.emit_span("train.step", t, 0.02, step=step)
        t += 0.02
        tel.emit_span("exchange.overlap", t, 0.002)
        t += 0.002
    tel.close()

    rc = prof.main([tel_dir])
    out = capsys.readouterr().out
    assert rc == 0, out  # compute-bound synthetic window: no host verdict
    assert "rank 0" in out and "[train]" in out and "verdict:" in out

    ledger = str(tmp_path / "PERF_LEDGER.jsonl")
    attrib = os.path.join(tel_dir, "ATTRIB.json")
    assert os.path.exists(attrib)  # close() published it
    rc = prof.main(["--ledger", "update", attrib, "--ledger-path", ledger])
    assert rc == 0
    assert "ingested" in capsys.readouterr().out

    rc = prof.main(["--ledger", "check", "--ledger-path",
                    os.path.join(repo, "PERF_LEDGER.jsonl")])
    capsys.readouterr()
    assert rc == 0, "repo's committed perf ledger reads as regressed"
