"""Pallas flash-attention kernel: numerics vs the XLA reference.

The kernel runs in interpreter mode on the CPU mesh (same code path the
Mosaic compiler takes on TPU).  Forward is checked against naive softmax
attention; the custom-VJP backward against autodiff of the reference.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.pallas_attention import (
    flash_attention,
    flash_attention_supported,
)


def _reference(q, k, v, causal):
    b, t, h, d = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _rand(shape, seed):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    b, t, h, d = 2, 256, 2, 64
    q, k, v = (jnp.asarray(_rand((b, t, h, d), i)) for i in range(3))
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    want = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    b, t, h, d = 1, 128, 2, 64
    q, k, v = (jnp.asarray(_rand((b, t, h, d), 10 + i)) for i in range(3))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                            interpret=True)
        return jnp.sum(jnp.sin(o))  # non-trivial cotangent

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_reference(q, k, v, causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=5e-4, atol=5e-5,
            err_msg=f"d{name} mismatch",
        )


def test_flash_uneven_blocks():
    """block_q != block_k and T a multiple of both."""
    b, t, h, d = 1, 256, 1, 64
    q, k, v = (jnp.asarray(_rand((b, t, h, d), 20 + i)) for i in range(3))
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference(q, k, v, True)),
                               rtol=2e-4, atol=2e-5)


def test_t1536_fits_blocks_and_matches():
    """T divisible by 512 but not the 1024 default block_k: the fitting
    clamp must halve the block instead of rejecting the shape."""
    b, t, h, d = 1, 1536, 1, 64
    q, k, v = (jnp.asarray(_rand((b, t, h, d), 40 + i)) for i in range(3))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference(q, k, v, True)),
                               rtol=2e-4, atol=2e-5)


def test_long_t_bf16_fwd_bwd_tolerance():
    """Pin the bf16-normalizer numerics trade (ADVICE r3 #4).

    The forward accumulates the softmax normalizer from bf16-cast p via
    the ones-column MXU pass, so l/lse inherit bf16 quantization that a
    standard fp32 row-sum would not have, and the backward recomputes p
    in fp32 against that slightly noisier lse.  This test runs bf16
    inputs at long T through fwd+bwd and bounds the drift against an
    fp32 reference evaluated at the SAME (bf16-quantized) input values —
    isolating kernel-internal error from input quantization.  If a
    future kernel change widens the trade, these tolerances catch it.
    """
    b, t, h, d = 1, 2048, 1, 64
    qf, kf, vf = (jnp.asarray(_rand((b, t, h, d), 50 + i)) for i in range(3))
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))
    # reference sees the bf16 values, computes in fp32
    q32, k32, v32 = (x.astype(jnp.float32) for x in (qb, kb, vb))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=512, block_k=1024,
                            interpret=True)
        return jnp.sum(jnp.sin(o.astype(jnp.float32))), o

    def loss_ref(q, k, v):
        o = _reference(q, k, v, True)
        return jnp.sum(jnp.sin(o)), o

    (_, o_flash), g_flash = jax.value_and_grad(
        loss_flash, argnums=(0, 1, 2), has_aux=True)(qb, kb, vb)
    (_, o_ref), g_ref = jax.value_and_grad(
        loss_ref, argnums=(0, 1, 2), has_aux=True)(q32, k32, v32)

    # forward: output is bf16, so quantization alone is ~4e-3 relative;
    # the normalizer trade must stay within the same order
    np.testing.assert_allclose(np.asarray(o_flash, np.float32),
                               np.asarray(o_ref), rtol=2e-2, atol=2e-2)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        err = np.abs(np.asarray(gf, np.float32) - np.asarray(gr))
        scale_ = np.abs(np.asarray(gr)).max()
        assert err.max() <= 4e-2 * max(scale_, 1e-3), (
            f"d{name} drift {err.max():.4g} exceeds bf16 budget "
            f"(ref scale {scale_:.4g})"
        )


def test_supported_gate():
    assert flash_attention_supported(256, 64)   # clamps blocks to 256
    assert flash_attention_supported(512, 128)
    assert flash_attention_supported(2048, 64)
    assert flash_attention_supported(1536, 64)  # block_k fits down to 512
    assert not flash_attention_supported(100, 64)   # ragged T (clamped
    # block 100 is not a multiple of the 128-lane tile)
    assert not flash_attention_supported(256, 8)    # tiny head dim
    # ragged T vs an explicit block size raises in any mode
    z = jnp.zeros((1, 100, 1, 8))
    with pytest.raises(ValueError, match="unsupported shape"):
        flash_attention(z, z, z, block_q=64, block_k=64)


def test_mha_forced_pallas_matches_blockwise(monkeypatch):
    """impl='pallas' must actually take the kernel path (call-counted) and
    match the forced blockwise path on the same params."""
    import theanompi_tpu.ops.pallas_attention as pa
    from theanompi_tpu.ops.attention import MultiHeadAttention

    calls = []
    real = pa.flash_attention
    monkeypatch.setattr(pa, "flash_attention",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))

    b, t, dim, heads = 2, 128, 128, 2  # head_dim 64 -> pallas-eligible
    x = jnp.asarray(_rand((b, t, dim), 30))
    pallas = MultiHeadAttention(dim, heads, causal=True, impl="pallas")
    blockwise = MultiHeadAttention(dim, heads, causal=True, impl="blockwise")
    params, _, _ = pallas.init(jax.random.PRNGKey(0), (t, dim))
    y_pallas, _ = pallas.apply(params, {}, x)
    assert calls, "impl='pallas' did not reach the flash kernel"
    n_after_pallas = len(calls)
    y_block, _ = blockwise.apply(params, {}, x)
    assert len(calls) == n_after_pallas, "blockwise path hit the kernel"
    np.testing.assert_allclose(np.asarray(y_pallas), np.asarray(y_block),
                               rtol=2e-4, atol=2e-5)


def test_mha_auto_gate_policy(monkeypatch):
    """auto = kernels only on TPU with supported shapes (train AND eval)."""
    import theanompi_tpu.ops.pallas_attention as pa
    from theanompi_tpu.ops import attention as attn_mod
    from theanompi_tpu.ops.attention import MultiHeadAttention

    calls = []
    real = pa.flash_attention
    monkeypatch.setattr(pa, "flash_attention",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))

    b, t, dim, heads = 1, 128, 128, 2
    x = jnp.asarray(_rand((b, t, dim), 31))
    auto = MultiHeadAttention(dim, heads, causal=True, impl="auto")
    params, _, _ = auto.init(jax.random.PRNGKey(0), (t, dim))

    # off-TPU (this suite runs on the CPU mesh): auto must NOT use pallas
    auto.apply(params, {}, x, train=False)
    auto.apply(params, {}, x, train=True, rng=jax.random.PRNGKey(0))
    assert not calls, "auto used the pallas interpreter off-TPU"

    # pretend we're on TPU: both inference and training use the kernels
    monkeypatch.setattr(attn_mod.jax, "default_backend", lambda: "tpu")
    # interpret must still be forced: jax.default_backend is patched
    # globally, but this process has no TPU, so the wrapper pins interpret
    monkeypatch.setattr(
        pa, "flash_attention",
        lambda q, k, v, **kw: calls.append(1) or real(
            q, k, v, **{**kw, "interpret": True}),
    )
    auto.apply(params, {}, x, train=False)
    assert calls, "auto skipped pallas for eligible TPU inference"
    n = len(calls)
    auto.apply(params, {}, x, train=True, rng=jax.random.PRNGKey(0))
    assert len(calls) > n, "auto skipped pallas for TPU training"


def test_mha_rejects_unknown_impl():
    from theanompi_tpu.ops.attention import MultiHeadAttention

    with pytest.raises(ValueError, match="impl"):
        MultiHeadAttention(128, 2, impl="flash")


def test_transformer_lm_trains_with_pallas_attention():
    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh

    model = TransformerLM({
        "batch_size": 2, "n_train": 64, "n_val": 32, "seq_len": 128,
        "vocab": 64, "dim": 128, "heads": 2, "n_layers": 1,
        "dropout": 0.0, "n_epochs": 1, "precision": "fp32",
        "attn_impl": "pallas",
    })
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=1e-3)
    assert np.isfinite(float(m["cost"]))
