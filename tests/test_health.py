"""ISSUE 13: live run-health watchdog, crash flight recorder, tmhealth.

Detector units run the streaming :class:`HealthMonitor` on an injected
clock (no sleeps, fully deterministic); the integration half drives the
ticker thread, the supervisor health-kill, and the fleet ``fleet.hang``
audit + ledger failure-cause path with millisecond ``python -c`` fakes.
The real-launcher hang e2e (``prefetch:stall`` fault -> hung verdict ->
supervised restart) is marked slow; the in-process crash test asserts a
crashed run leaves a parseable ``blackbox.json``.
"""

import json
import os
import sys
import threading
import time

import pytest

from theanompi_tpu.telemetry import (
    EventSink,
    FlightRecorder,
    HealthConfig,
    HealthMonitor,
    Telemetry,
    hung_verdict,
    read_blackbox,
    read_events,
    read_health,
    replay_events,
    sink_files,
    tail_events,
)
from theanompi_tpu.telemetry import cli as health_cli
from theanompi_tpu.telemetry.aggregate import summarize_events
from theanompi_tpu.telemetry.chrome_trace import to_trace_events
from theanompi_tpu.telemetry.flight_recorder import blackbox_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mon(tmp_path, **cfg):
    """Monitor on a frozen injected clock: every observe/tick passes an
    explicit ``now``."""
    return HealthMonitor(str(tmp_path), HealthConfig(**cfg),
                         clock=lambda: 0.0)


def _step(mon, step, now, dur=0.01, rank=0, **tags):
    mon.observe({"ts": now, "kind": "span", "name": "train.step",
                 "dur": dur, "rank": rank, "tid": 1, "step": step, **tags},
                now=now)


def _by_detector(verdicts):
    return {v["detector"]: v for v in verdicts}


# -- hang (arrival clock) -----------------------------------------------------

def test_hang_arms_only_after_warmup_steps(tmp_path):
    mon = _mon(tmp_path, hang_deadline_s=10.0, hang_warmup_steps=3)
    _step(mon, 0, 1.0)
    _step(mon, 1, 2.0)
    # 2 steps < warmup: a long silence is still "compiling", not hung
    assert mon.tick(now=100.0) == []
    assert "hang" not in _by_detector(mon.verdicts())
    _step(mon, 2, 101.0)  # third step arms the detector
    changed = mon.tick(now=112.0)  # 11s > 10s deadline
    assert [v.detector for v in changed] == ["hang"]
    hang = _by_detector(mon.verdicts())["hang"]
    assert hang["severity"] == "critical"
    assert hang["fields"]["deadline_s"] == 10.0
    assert mon.worst_severity() == "critical"
    # unchanged severity is not re-reported on the next tick
    assert mon.tick(now=113.0) == []


def test_hang_suspended_in_boundary_and_disarmed_at_session_end(tmp_path):
    mon = _mon(tmp_path, hang_deadline_s=5.0, hang_warmup_steps=1)
    _step(mon, 0, 1.0)
    mon.observe({"kind": "instant", "name": "train.boundary",
                 "phase": "begin", "rank": 0}, now=2.0)
    # inside a boundary (validate/checkpoint) silence is legitimate
    assert mon.tick(now=60.0) == []
    mon.observe({"kind": "instant", "name": "train.boundary",
                 "phase": "end", "rank": 0}, now=60.0)
    assert mon.tick(now=61.0) == []          # clock restarted at the end
    changed = mon.tick(now=70.0)             # 10s > 5s: now it is a hang
    assert [v.detector for v in changed] == ["hang"]
    # a new step clears it...
    _step(mon, 1, 70.5)
    ok = [v for v in mon.tick(now=71.0) if v.detector == "hang"]
    assert ok and ok[0].severity == "ok"
    # ...and session_end disarms for good
    mon.observe({"kind": "meta", "name": "session_end", "rank": 0}, now=72.0)
    assert mon.tick(now=500.0) == []
    assert _by_detector(mon.verdicts())["hang"]["severity"] == "ok"


# -- straggler ----------------------------------------------------------------

def test_straggler_flags_slow_rank_against_fleet_mean(tmp_path):
    mon = _mon(tmp_path, straggler_ratio=1.5, straggler_min_steps=4)
    for s in range(4):
        _step(mon, s, float(s), dur=0.010, rank=0)
        _step(mon, s, float(s) + 0.5, dur=0.030, rank=1)
    v = _by_detector(mon.verdicts())["straggler"]
    # rank 1 at 0.030 vs fleet mean 0.020 -> ratio 1.5 >= threshold
    assert v["severity"] == "warn"
    assert v["fields"]["rank"] == 1
    assert v["fields"]["step_skew_ms"]["steps_compared"] == 4
    assert v["fields"]["step_skew_ms"]["max"] == pytest.approx(20.0)


def test_straggler_needs_common_steps_and_two_ranks(tmp_path):
    mon = _mon(tmp_path, straggler_min_steps=4)
    for s in range(8):
        _step(mon, s, float(s), dur=0.010, rank=0)
    assert "straggler" not in _by_detector(mon.verdicts())
    # rank 1 reports DIFFERENT steps: no common window, no verdict
    for s in range(100, 103):
        _step(mon, s, float(s), dur=0.050, rank=1)
    assert "straggler" not in _by_detector(mon.verdicts())


# -- loss ---------------------------------------------------------------------

def test_loss_nan_is_immediately_critical(tmp_path):
    mon = _mon(tmp_path)
    _step(mon, 0, 1.0, loss=float("nan"))
    v = _by_detector(mon.verdicts())["loss"]
    assert v["severity"] == "critical" and "non-finite" in v["reason"]
    assert v["step"] == 0


def test_loss_spike_warns_only_after_warmup(tmp_path):
    mon = _mon(tmp_path, loss_warmup=8, loss_z=6.0)
    for s in range(7):
        _step(mon, s, float(s), loss=1.0 + 0.01 * s)
    assert _by_detector(mon.verdicts()).get(
        "loss", {"severity": "ok"}).get("severity") != "warn"
    _step(mon, 7, 7.0, loss=1.07)
    _step(mon, 8, 8.0, loss=1e6)  # past warmup: a huge spike is a warn
    v = _by_detector(mon.verdicts())["loss"]
    assert v["severity"] == "warn"
    assert v["fields"]["z"] > 6.0
    assert v["step"] == 8
    _step(mon, 9, 9.0, loss=1.0)  # hmm -- back in band relative to EWMA
    assert _by_detector(mon.verdicts())["loss"]["severity"] == "ok"


# -- throughput ---------------------------------------------------------------

def test_throughput_regression_warns_on_recent_median(tmp_path):
    mon = _mon(tmp_path, throughput_min_steps=16, throughput_recent=8,
               throughput_factor=2.0)
    for s in range(16):
        _step(mon, s, float(s), dur=0.010)
    v = _by_detector(mon.verdicts())["throughput"]
    assert v["severity"] == "ok"
    for s in range(16, 24):
        _step(mon, s, float(s), dur=0.050)  # 5x the baseline
    v = _by_detector(mon.verdicts())["throughput"]
    assert v["severity"] == "warn"
    assert v["fields"]["recent_ms"] == pytest.approx(50.0)
    assert v["fields"]["baseline_ms"] == pytest.approx(10.0)


# -- checkpoint cadence -------------------------------------------------------

def test_checkpoint_stall_warns_then_clears(tmp_path):
    mon = _mon(tmp_path, checkpoint_deadline_s=10.0, hang_warmup_steps=99)
    # no checkpoint ever seen: detector stays silent no matter how long
    _step(mon, 0, 1.0)
    assert mon.tick(now=1000.0) == []
    mon.observe({"kind": "span", "name": "checkpoint.write", "dur": 0.1,
                 "rank": 0}, now=1001.0)
    assert _by_detector(mon.verdicts())["checkpoint"]["severity"] == "ok"
    # steps advance past the deadline with no new checkpoint
    _step(mon, 1, 1002.0)
    changed = mon.tick(now=1015.0)
    assert [v.detector for v in changed] == ["checkpoint"]
    assert changed[0].severity == "warn"
    mon.observe({"kind": "span", "name": "checkpoint.write", "dur": 0.1,
                 "rank": 0}, now=1016.0)
    cleared = [v for v in mon.tick(now=1017.0) if v.detector == "checkpoint"]
    assert cleared and cleared[0].severity == "ok"


# -- serving SLO --------------------------------------------------------------

def test_slo_breach_from_metrics_histograms(tmp_path):
    mon = _mon(tmp_path, slo_ttft_p99_ms=50.0)
    mon.observe({"kind": "metrics", "name": "metrics", "rank": 0,
                 "histograms": {"serve.ttft_ms": {"p50": 10.0, "p99": 80.0}}},
                now=1.0)
    v = _by_detector(mon.verdicts())["slo"]
    assert v["severity"] == "warn"
    assert v["fields"] == {"p99_ms": 80.0, "slo_ms": 50.0}
    mon.observe({"kind": "metrics", "name": "metrics", "rank": 0,
                 "histograms": {"serve.ttft_ms": {"p50": 5.0, "p99": 20.0}}},
                now=2.0)
    assert _by_detector(mon.verdicts())["slo"]["severity"] == "ok"


def test_slo_detector_off_without_configured_target(tmp_path):
    mon = _mon(tmp_path)  # slo_ttft_p99_ms defaults to None
    mon.observe({"kind": "metrics", "name": "metrics", "rank": 0,
                 "histograms": {"serve.ttft_ms": {"p99": 1e9}}}, now=1.0)
    assert "slo" not in _by_detector(mon.verdicts())


# -- HEALTH.json + shared predicates ------------------------------------------

def test_health_json_roundtrip_and_hung_predicate(tmp_path):
    mon = _mon(tmp_path, hang_deadline_s=1.0, hang_warmup_steps=1)
    _step(mon, 0, 1.0)
    mon.tick(now=10.0)
    path = mon.write()
    assert os.path.basename(path) == "HEALTH.json"
    health = read_health(str(tmp_path))
    assert health["pid"] == os.getpid() and health["steps"] == 1
    assert abs(health["updated"] - time.time()) < 60
    hung = hung_verdict(health)
    assert hung is not None and hung["severity"] == "critical"
    assert hung_verdict(None) is None
    assert hung_verdict({"verdicts": [{"detector": "loss",
                                       "severity": "critical"}]}) is None
    assert read_health(str(tmp_path / "nope")) is None


def test_replay_events_runs_detectors_offline(tmp_path):
    events = [{"kind": "span", "name": "train.step", "dur": 0.01,
               "rank": 0, "step": s, "loss": float("nan") if s == 3 else 1.0}
              for s in range(4)]
    mon = replay_events(events, directory=str(tmp_path))
    verdicts = _by_detector(mon.verdicts())
    assert verdicts["loss"]["severity"] == "critical"
    # the arrival-clock hang detector cannot fire in a replay
    assert verdicts.get("hang", {}).get("severity", "ok") == "ok"


# -- flight recorder ----------------------------------------------------------

def test_flight_recorder_ring_and_blackbox_payload(tmp_path):
    with pytest.raises(ValueError):
        FlightRecorder(str(tmp_path), capacity=0)
    fr = FlightRecorder(str(tmp_path), capacity=4, rank=0)
    fr.set_fingerprint({"mesh": "2x2", "model": "WideResNet"})
    for i in range(10):
        fr.record({"kind": "instant", "name": "x", "i": i})
    path = fr.dump("crash", error="ValueError: boom",
                   health={"verdicts": []})
    assert path == blackbox_path(str(tmp_path), 0)
    bb = read_blackbox(str(tmp_path))
    assert bb["reason"] == "crash" and bb["error"] == "ValueError: boom"
    assert bb["fingerprint"]["model"] == "WideResNet"
    assert bb["n_events"] == 4  # ring bounded: only the newest survive
    assert [e["i"] for e in bb["events"]] == [6, 7, 8, 9]
    assert bb["pid"] == os.getpid() and bb["rank"] == 0
    # last dump wins (the outermost handler has the best error)
    fr.dump("hang")
    assert read_blackbox(str(tmp_path))["reason"] == "hang"
    assert "error" not in read_blackbox(str(tmp_path))


def test_flight_recorder_per_rank_paths(tmp_path):
    assert blackbox_path(str(tmp_path), 0).endswith("blackbox.json")
    assert blackbox_path(str(tmp_path), 3).endswith("blackbox-rank00003.json")
    fr = FlightRecorder(str(tmp_path), capacity=2, rank=3)
    fr.record({"kind": "instant", "name": "x"})
    fr.dump("sigterm")
    assert read_blackbox(str(tmp_path), rank=3)["reason"] == "sigterm"
    assert read_blackbox(str(tmp_path)) is None  # rank 0 never dumped


# -- Telemetry integration ----------------------------------------------------

def test_telemetry_ticker_publishes_hang_and_mirrors_transition(tmp_path):
    d = str(tmp_path)
    tel = Telemetry(d, rank=0, health={"tick_s": 0.05, "hang_deadline_s": 0.3,
                                       "hang_warmup_steps": 1},
                    flight_recorder=16)
    assert tel.health is not None and tel.flight is not None
    tel.emit_span("train.step", 0.0, 0.01, step=0, loss=1.0)
    deadline = time.time() + 20.0
    hung = None
    while time.time() < deadline:
        hung = hung_verdict(read_health(d))
        if hung is not None:
            break
        time.sleep(0.02)
    assert hung is not None, "ticker never published the hang verdict"
    assert "no events" in hung["reason"]
    tel.close()
    events = [e for p in sink_files(d) for e in read_events(p)]
    mirrored = [e for e in events if e.get("name") == "health.verdict"]
    assert any(e.get("detector") == "hang" and e.get("severity") == "critical"
               for e in mirrored)
    # close() emitted session_end -> the final published state is disarmed
    assert hung_verdict(read_health(d)) is None
    assert any(e.get("name") == "session_end" for e in events)


def test_disabled_telemetry_makes_zero_health_calls(tmp_path, monkeypatch):
    """A Telemetry without the opt-ins must never touch the monitor or
    the flight recorder (the ISSUE 13 off-means-off criterion)."""
    import theanompi_tpu.telemetry.flight_recorder as fr_mod
    import theanompi_tpu.telemetry.health as health_mod

    def bomb(*a, **k):
        raise AssertionError("health/flight call on a disabled run")

    for obj, meth in [(health_mod.HealthMonitor, "__init__"),
                      (health_mod.HealthMonitor, "observe"),
                      (health_mod.HealthMonitor, "tick"),
                      (health_mod.HealthMonitor, "write"),
                      (fr_mod.FlightRecorder, "__init__"),
                      (fr_mod.FlightRecorder, "record"),
                      (fr_mod.FlightRecorder, "dump")]:
        monkeypatch.setattr(obj, meth, bomb)
    tel = Telemetry(str(tmp_path))  # defaults: health off, recorder off
    assert tel.health is None and tel.flight is None
    with tel.span("train.step", step=0, loss=1.0):
        pass
    tel.instant("train.boundary", phase="begin")
    tel.count("bytes", 10, emit=True)
    tel.flush_metrics(step=0)
    tel.close()
    assert read_health(str(tmp_path)) is None
    assert read_blackbox(str(tmp_path)) is None


def test_rule_config_wires_health_and_blackbox_keys(tmp_path):
    from theanompi_tpu import BSP

    tel = BSP(config={"telemetry_dir": str(tmp_path / "on"),
                      "verbose": False}).make_telemetry()
    assert tel.health is not None           # default-on when telemetry is on
    assert tel.flight is not None and tel.flight.capacity == 256
    tel.close()
    tel = BSP(config={"telemetry_dir": str(tmp_path / "off"),
                      "verbose": False, "telemetry_health": False,
                      "telemetry_blackbox": 0}).make_telemetry()
    assert tel.health is None and tel.flight is None
    tel.close()
    tel = BSP(config={"telemetry_dir": str(tmp_path / "cfg"),
                      "verbose": False,
                      "telemetry_health": {"hang_deadline_s": 5.0},
                      }).make_telemetry()
    assert tel.health.config.hang_deadline_s == 5.0
    tel.close()


# -- tail_events (satellite: live tailing) ------------------------------------

def test_tail_events_never_consumes_a_partial_line(tmp_path):
    path = str(tmp_path / "events-rank00000.jsonl")
    with open(path, "wb") as f:
        f.write(b'{"a": 1}\n{"b"')
    events, off = tail_events(path)
    assert events == [{"a": 1}] and off == 9
    with open(path, "ab") as f:
        f.write(b': 2}\n')
    events, off = tail_events(path, off)
    assert events == [{"b": 2}]
    assert tail_events(path, off) == ([], off)
    assert tail_events(str(tmp_path / "missing.jsonl"), 7) == ([], 7)


def test_tail_events_races_a_live_writer_without_loss(tmp_path):
    """A tailer polling while the sink thread writes sees every event
    exactly once, in order — the contract tmhealth --follow leans on."""
    sink = EventSink(str(tmp_path), rank=0)
    n = 400

    def writer():
        for i in range(n):
            sink.emit({"kind": "instant", "name": "tick", "seq": i})
            if i % 50 == 0:
                time.sleep(0.002)
        sink.close()

    t = threading.Thread(target=writer)
    t.start()
    seen, offset = [], 0
    deadline = time.time() + 30.0
    while time.time() < deadline:
        events, offset = tail_events(sink.path, offset)
        seen.extend(events)
        if not t.is_alive() and not events and len(seen) >= n:
            break
        time.sleep(0.001)
    t.join()
    assert [e["seq"] for e in seen] == list(range(n))


# -- chrome trace two-rank alignment (satellite) ------------------------------

def test_chrome_trace_aligns_ranks_with_different_clock_epochs():
    """Per-rank ``ts`` values are per-process perf_counter epochs; the
    exporter must normalize each rank to its own start so two ranks render
    side by side at t=0 with durations preserved exactly."""
    events = []
    for rank, epoch in ((0, 100.0), (1, 5000.0)):
        for s in range(3):
            events.append({"kind": "span", "name": "train.step",
                           "ts": epoch + 0.1 * s, "dur": 0.02,
                           "rank": rank, "tid": 1, "step": s})
    trace = to_trace_events(events)
    spans = [t for t in trace if t.get("ph") == "X"]
    by_pid = {}
    for t in spans:
        by_pid.setdefault(t["pid"], []).append(t)
    assert set(by_pid) == {0, 1}
    for pid, ts in by_pid.items():
        starts = sorted(t["ts"] for t in ts)
        assert starts[0] == pytest.approx(0.0, abs=1e-6)
        # relative spacing survives (0.1s steps -> 1e5us apart)
        assert starts[1] == pytest.approx(1e5, rel=1e-6)
        assert all(t["dur"] == pytest.approx(2e4, rel=1e-6) for t in ts)


# -- aggregate partial fleets (satellite) -------------------------------------

def _span(rank, step, dur, ts=None):
    return {"kind": "span", "name": "train.step", "rank": rank, "tid": 1,
            "ts": 1.0 * step if ts is None else ts, "dur": dur, "step": step}


def test_summarize_partial_fleet_missing_ranks(tmp_path):
    # ranks 0 and 2 reported; rank 1's sink never made it back
    events = ([_span(0, s, 0.010) for s in range(4)]
              + [_span(2, s, 0.020) for s in range(2)])
    summary = summarize_events(events)
    assert summary["n_ranks"] == 2
    assert set(summary["per_rank"]) == {"0", "2"}
    assert summary["per_rank"]["0"]["steps"] == 4
    assert summary["per_rank"]["2"]["steps"] == 2
    # skew only over the steps BOTH ranks reported
    assert summary["step_skew_ms"]["steps_compared"] == 2
    assert summary["straggler"]["rank"] == 2


def test_summarize_rank_with_zero_steps_is_not_divided_by(tmp_path):
    events = [_span(0, s, 0.010) for s in range(3)]
    events.append({"kind": "instant", "name": "resilience.watchdog_stall",
                   "rank": 1, "ts": 0.5})
    summary = summarize_events(events)
    assert summary["n_ranks"] == 2
    assert summary["per_rank"]["1"]["steps"] == 0
    assert "step_ms" not in summary["per_rank"]["1"]
    # a zero-step rank suppresses the cross-rank skew, not the summary
    assert "step_skew_ms" not in summary
    assert summary["straggler"]["rank"] == 0  # judged over stepped ranks
    # no metrics event ever flushed -> no counters key anywhere
    assert "counters" not in summary["per_rank"]["0"]


def test_summarize_no_events_at_all():
    summary = summarize_events([])
    assert summary["n_ranks"] == 0 and summary["per_rank"] == {}


# -- tmhealth CLI -------------------------------------------------------------

def test_tmhealth_cli_exit_codes_and_json(tmp_path, capsys):
    assert health_cli.main([str(tmp_path / "missing")]) == 2
    capsys.readouterr()

    live = tmp_path / "live"
    live.mkdir()
    mon = HealthMonitor(str(live), HealthConfig(), clock=lambda: 0.0)
    for s in range(10):  # past loss warmup: an "ok" loss verdict exists
        _step(mon, s, float(s), loss=1.0)
    mon.write()
    assert health_cli.main([str(live)]) == 0
    out = capsys.readouterr().out
    assert "HEALTH.json" in out and "loss" in out

    mon2 = _mon(tmp_path / "live", hang_deadline_s=1.0, hang_warmup_steps=1)
    _step(mon2, 0, 1.0)
    mon2.tick(now=10.0)
    mon2.write()
    assert health_cli.main([str(live), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    rep = doc["reports"][0]
    assert rep["source"] == "HEALTH.json"
    assert hung_verdict(rep) is not None


def test_tmhealth_replays_events_and_flags_stale_runs(tmp_path, capsys):
    d = tmp_path / "old"
    d.mkdir()
    sink = EventSink(str(d), rank=0)
    for s in range(3):
        sink.emit(_span(0, s, 0.01))
    sink.close()  # no session_end meta, no HEALTH.json: a pre-13 run
    stale = time.time() - 120.0
    for p in sink_files(str(d)):
        os.utime(p, (stale, stale))
    assert health_cli.main([str(d), "--stale-hang-s", "60"]) == 1
    out = capsys.readouterr().out
    assert "[replay" in out and "hang" in out
    # a generous staleness budget keeps the same directory healthy
    assert health_cli.main([str(d), "--stale-hang-s", "99999"]) == 0


def test_tmhealth_fleet_mode_scans_per_job_dirs(tmp_path, capsys):
    fleet = tmp_path / "fleet"
    assert health_cli.main([str(fleet / "nope"), "--fleet"]) == 2
    capsys.readouterr()
    for jid in ("a", "b"):
        jdir = fleet / "jobs" / jid / "telemetry"
        jdir.mkdir(parents=True)
        mon = HealthMonitor(str(jdir), HealthConfig(), clock=lambda: 0.0)
        _step(mon, 0, 1.0, loss=1.0)
        mon.write()
    assert health_cli.main([str(fleet), "--fleet", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["reports"]) == 2
    assert all(r["source"] == "HEALTH.json" for r in doc["reports"])


# -- supervisor health-kill ---------------------------------------------------

def _hang_child(tmp_path, tdir):
    """A child that fakes a hung trainer on its first attempt: publishes a
    critical HEALTH.json + a blackbox, then sleeps; the resumed attempt
    exits clean.  (The real publication path is covered by the ticker and
    launcher tests — here the timing must be deterministic.)"""
    body = """
import json, os, sys, time
tdir = TDIR
marker = os.path.join(STATE, "hung_once")
if not os.path.exists(marker):
    open(marker, "w").close()
    os.makedirs(tdir, exist_ok=True)
    bb = {"wall_time": time.time(), "reason": "hang", "pid": os.getpid(),
          "rank": 0, "fingerprint": {"mesh": "fake"}, "n_events": 1,
          "events": [{"kind": "instant", "name": "x", "rank": 0, "ts": 0.0}]}
    json.dump(bb, open(os.path.join(tdir, "blackbox.json"), "w"))
    health = {"updated": time.time(), "pid": os.getpid(), "rank": 0,
              "steps": 7, "verdicts": [
                  {"detector": "hang", "severity": "critical",
                   "reason": "no events for 9.0s (deadline 3s)"}]}
    json.dump(health, open(os.path.join(tdir, "HEALTH.json"), "w"))
    time.sleep(120)
    sys.exit(1)
sys.exit(0)
"""
    body = body.replace("STATE", repr(str(tmp_path))).replace(
        "TDIR", repr(tdir))
    return [sys.executable, "-c", body]


def test_supervisor_kills_child_on_fresh_hung_verdict(tmp_path):
    from theanompi_tpu.resilience.supervisor import Supervisor

    tdir = str(tmp_path / "telemetry")
    sup = Supervisor(_hang_child(tmp_path, tdir), max_restarts=2,
                     backoff_base=0.01, jitter=0.0, poll_s=0.05,
                     telemetry_dir=tdir,
                     resilience_path=str(tmp_path / "resilience.json"),
                     resume_args=())
    assert sup.run() == 0
    rep = json.load(open(tmp_path / "resilience.json"))
    causes = [a["cause"] for a in rep["attempts"]]
    assert causes == ["hang", "clean"]
    first = rep["attempts"][0]
    assert first["exit_code"] < 0  # killed by signal, not a clean exit
    # the blackbox + health verdicts were harvested into the attempt
    assert first["blackbox"]["reason"] == "hang"
    assert first["blackbox"]["fingerprint"] == {"mesh": "fake"}
    assert "events" not in first["blackbox"]  # summary only, ring dropped
    assert any(v["detector"] == "hang" and v["severity"] == "critical"
               for v in first["health"])


def test_supervisor_ignores_stale_health_from_a_previous_run(tmp_path):
    from theanompi_tpu.resilience.supervisor import Supervisor

    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    health = {"updated": time.time(), "pid": 1, "rank": 0, "steps": 3,
              "verdicts": [{"detector": "hang", "severity": "critical",
                            "reason": "stale"}]}
    json.dump(health, open(tdir / "HEALTH.json", "w"))
    time.sleep(0.05)  # the file's mtime predates the supervisor's start
    sup = Supervisor([sys.executable, "-c", "import time; time.sleep(2.2)"],
                     max_restarts=0, poll_s=0.05, telemetry_dir=str(tdir),
                     resilience_path=str(tmp_path / "resilience.json"))
    assert sup.run() == 0
    rep = json.load(open(tmp_path / "resilience.json"))
    assert [a["cause"] for a in rep["attempts"]] == ["clean"]


# -- fleet: hang audit + failure cause ----------------------------------------

def test_fleet_records_hang_cause_in_ledger_and_events(tmp_path):
    from theanompi_tpu.fleet import (
        DeviceLedger,
        FleetScheduler,
        JobSpec,
        job_dir,
        read_fleet_events,
        read_record,
    )
    from theanompi_tpu.resilience.codes import EXIT_CRASH

    d = str(tmp_path / "fleet")
    jdir = job_dir(d, "wedged")
    tdir = os.path.join(jdir, "telemetry")
    body = """
import json, os, time
tdir = TDIR
os.makedirs(tdir, exist_ok=True)
bb = {"wall_time": time.time(), "reason": "hang", "pid": os.getpid(),
      "rank": 0, "fingerprint": {}, "n_events": 0, "events": []}
json.dump(bb, open(os.path.join(tdir, "blackbox.json"), "w"))
health = {"updated": time.time(), "pid": os.getpid(), "rank": 0, "steps": 5,
          "verdicts": [{"detector": "hang", "severity": "critical",
                        "reason": "no events for 9.0s"}]}
json.dump(health, open(os.path.join(tdir, "HEALTH.json"), "w"))
time.sleep(120)
""".replace("TDIR", repr(tdir))
    sched = FleetScheduler(d, 4, poll_s=0.02, telemetry=False)
    sched.submit(JobSpec(job_id="wedged", max_restarts=1,
                         argv=[sys.executable, "-c", body]))
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    t.join(60)
    assert not t.is_alive(), "fleet scheduler hung"
    assert box["rc"] == EXIT_CRASH

    rec = read_record(d, "wedged")
    assert rec.status == "failed"
    assert rec.failure_cause["cause"] == "hang"
    assert rec.failure_cause["blackbox"]["reason"] == "hang"
    assert any(v["detector"] == "hang"
               for v in rec.failure_cause["health"])
    # the ledger remembers WHY long after the record is gone
    led = DeviceLedger(d)
    assert led.last_failure("wedged")["cause"] == "hang"
    events = read_fleet_events(d)
    hangs = [e for e in events if e["event"] == "fleet.hang"]
    assert len(hangs) == 1 and hangs[0]["job"] == "wedged"
    fails = [e for e in events if e["event"] == "fleet.fail"]
    assert fails and fails[0]["cause"] == "hang" and fails[0]["blackbox"]


# -- crash blackbox (in-process, real trainer) --------------------------------

@pytest.mark.faultinject
def test_crashed_run_leaves_parseable_blackbox(tmp_path):
    from theanompi_tpu import BSP
    from theanompi_tpu.resilience import FaultInjected

    d = str(tmp_path / "telemetry")
    # 2 steps/epoch at global batch 16: step:raise@1 fires on the second
    cfg = {"depth": 10, "widen": 1, "batch_size": 4, "image_size": 8,
           "n_train": 32, "n_val": 8, "n_epochs": 1, "precision": "fp32"}
    rule = BSP(config={"verbose": False, "telemetry_dir": d,
                       "fault_plan": "step:raise@1"})
    rule.init(4, "theanompi_tpu.models.wide_resnet", "WideResNet", cfg)
    with pytest.raises(FaultInjected):
        rule.wait()
    bb = read_blackbox(d)
    assert bb is not None, "crash left no blackbox.json"
    assert bb["reason"] == "crash"
    assert "FaultInjected" in bb["error"]
    assert bb["fingerprint"], "fingerprint missing from blackbox"
    assert bb["n_events"] == len(bb["events"]) > 0
    assert all("name" in e for e in bb["events"])
    # the health monitor published alongside (hang never fired: no
    # warmup-steps-then-silence on a fast crash)
    health = read_health(d)
    assert health is not None and hung_verdict(health) is None


# -- launcher hang e2e (slow) -------------------------------------------------

TINY_ARGS = ["--set", "depth=10", "--set", "widen=1", "--set",
             "batch_size=4", "--set", "image_size=8", "--set", "n_train=32",
             "--set", "n_val=16", "--set", "n_epochs=2", "--set",
             "precision=fp32"]


def _child_env(**extra):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "JAX_THREEFRY_PARTITIONABLE": "true",
                "PYTHONPATH": REPO})
    env.pop("THEANOMPI_FAULT_PLAN", None)
    env.update(extra)
    return env


@pytest.mark.slow
@pytest.mark.faultinject
def test_launcher_hang_is_detected_killed_and_restarted(tmp_path,
                                                        subproc_compile_cache):
    """THE acceptance e2e: a prefetch stall wedges the real trainer after
    its first step; the in-process watchdog publishes the hung verdict,
    the supervisor health-kills the child citing it, and the resumed
    attempt finishes the job clean."""
    import subprocess

    tdir = str(tmp_path / "telemetry")
    cmd = [sys.executable, "-m", "theanompi_tpu.launcher",
           "--rule", "BSP", "--devices", "4",
           "--modelfile", "theanompi_tpu.models.wide_resnet",
           "--modelclass", "WideResNet", *TINY_ARGS, "--quiet",
           "--telemetry-dir", tdir,
           "--rule-set",
           "telemetry_health={'hang_deadline_s': 3.0, "
           "'hang_warmup_steps': 1, 'tick_s': 0.25}",
           "--checkpoint-dir", str(tmp_path / "ckpt"),
           "--compile-cache-dir", subproc_compile_cache,
           "--supervise", "--max-restarts", "2", "--backoff-base", "0.1"]
    env = _child_env(THEANOMPI_FAULT_PLAN="prefetch:stall@1@1")
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=900, cwd=str(tmp_path))
    rep = json.load(open(tmp_path / "ckpt" / "resilience.json"))
    causes = [a["cause"] for a in rep["attempts"]]
    assert causes == ["hang", "clean"], (causes, proc.stdout[-2000:],
                                         proc.stderr[-2000:])
    assert proc.returncode == 0
    first = rep["attempts"][0]
    assert any(v["detector"] == "hang" and v["severity"] == "critical"
               for v in first["health"])
    assert first["blackbox"]["reason"] == "hang"


# -- perf ledger detector (ISSUE 16) ------------------------------------------

def _perf_ledger(path, values):
    from theanompi_tpu.telemetry.ledger import PerfLedger, make_record

    led = PerfLedger(str(path))
    led.append([make_record("seed", "bench", "bench.imgs_per_sec", v,
                            "images/sec", run_id=f"r{i}")
                for i, v in enumerate(values)])
    return led


def test_perf_detector_warns_on_ledger_regression(tmp_path):
    ledger = tmp_path / "PERF_LEDGER.jsonl"
    _perf_ledger(ledger, [100.0, 101.0, 99.0, 100.0, 70.0])
    mon = _mon(tmp_path, perf_ledger_path=str(ledger),
               hang_warmup_steps=99)
    mon.tick(now=1.0)
    v = _by_detector(mon.verdicts())["perf"]
    assert v["severity"] == "warn"
    assert "bench.imgs_per_sec" in v["reason"]
    assert "-30" in v["reason"]  # the worst delta is stated


def test_perf_detector_clears_on_recovery(tmp_path):
    ledger = tmp_path / "PERF_LEDGER.jsonl"
    led = _perf_ledger(ledger, [100.0, 101.0, 99.0, 100.0, 70.0])
    mon = _mon(tmp_path, perf_ledger_path=str(ledger),
               hang_warmup_steps=99)
    mon.tick(now=1.0)
    assert _by_detector(mon.verdicts())["perf"]["severity"] == "warn"
    # a recovered point lands; force a distinct mtime so the gate reopens
    from theanompi_tpu.telemetry.ledger import make_record

    led.append([make_record("seed", "bench", "bench.imgs_per_sec", 100.0,
                            "images/sec", run_id="r5")])
    os.utime(str(ledger), (1.0, 2.0))
    mon.tick(now=2.0)
    assert _by_detector(mon.verdicts())["perf"]["severity"] == "ok"


def test_perf_detector_mtime_gated(tmp_path, monkeypatch):
    """An armed detector costs one stat per tick — the ledger is only
    re-read when its mtime moves."""
    ledger = tmp_path / "PERF_LEDGER.jsonl"
    _perf_ledger(ledger, [100.0, 100.0])
    os.utime(str(ledger), (1.0, 1.0))
    mon = _mon(tmp_path, perf_ledger_path=str(ledger),
               hang_warmup_steps=99)
    mon.tick(now=1.0)
    calls = []
    import theanompi_tpu.telemetry.ledger as ledger_mod

    real = ledger_mod.check_ledger
    monkeypatch.setattr(ledger_mod, "check_ledger",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    mon.tick(now=2.0)
    mon.tick(now=3.0)
    assert calls == []  # unchanged mtime -> no re-read
    os.utime(str(ledger), (1.0, 9.0))
    mon.tick(now=4.0)
    assert calls == [1]


def test_perf_detector_off_without_ledger(tmp_path):
    # unconfigured (default): detector never appears
    mon = _mon(tmp_path, hang_warmup_steps=99)
    mon.tick(now=1.0)
    assert "perf" not in _by_detector(mon.verdicts())
    # configured but no ledger file yet: stays silent, does not raise
    mon = _mon(tmp_path, perf_ledger_path=str(tmp_path / "nope.jsonl"),
               hang_warmup_steps=99)
    mon.tick(now=1.0)
    assert "perf" not in _by_detector(mon.verdicts())
