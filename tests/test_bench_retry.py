"""bench.py retry path: survive transient backend outages (VERDICT r4 #1).

Round 4's driver bench died on one transient ``UNAVAILABLE`` from the
tunneled TPU backend and the round lost its headline artifact.  bench.py now
retries by re-exec'ing itself (jax caches a failed backend init for the life
of the process, so only a fresh process can actually retry).  These tests
drive that path with the BENCH_FAIL_UNTIL_ATTEMPT fault-injection knob on
the CPU backend — the reference had no analogue (its launcher just died with
mpirun, SURVEY.md §4); this is harness hardening our driver contract needs.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    env.update({
        # BENCH_PLATFORM, not just JAX_PLATFORMS: sitecustomize bakes the
        # tunnel platform into jax's config defaults, so only the
        # config-level force keeps the subprocess off a (possibly downed,
        # init-blocking) tunnel backend
        "JAX_PLATFORMS": "cpu",
        "BENCH_PLATFORM": "cpu",
        "BENCH_MODEL": "wide_resnet",  # primary only: no side-bench
        "BENCH_BS": "8",
        "BENCH_STEPS": "2",
        "BENCH_TRIALS": "1",
        "BENCH_RETRY_BACKOFF": "0",
        # a test bench run must not append to the repo's committed
        # perf trajectory (ISSUE 16)
        "BENCH_LEDGER": "0",
    })
    env.update({k: str(v) for k, v in extra.items()})
    # a stale attempt counter inherited from the runner would skew the test
    env.pop("BENCH_ATTEMPT", None)
    env.pop("BENCH_ATTEMPT_LOG", None)
    return env


def test_retry_recovers_after_transient_failures():
    p = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True, timeout=600,
        env=_env(BENCH_FAIL_UNTIL_ATTEMPT=3, BENCH_INIT_RETRIES=5),
    )
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, p.stdout  # driver contract: ONE JSON line
    out = json.loads(lines[0])
    assert out["value"] > 0
    assert "run_id" in out  # staleness stamp (VERDICT r4 #1)
    # both failed attempts left a visible trace
    assert "attempt 1/5" in p.stderr and "attempt 2/5" in p.stderr


def test_retry_gives_up_with_attempt_log_in_error_tail():
    p = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True, timeout=120,
        env=_env(BENCH_FAIL_UNTIL_ATTEMPT=99, BENCH_INIT_RETRIES=2),
    )
    assert p.returncode != 0
    assert "giving up after 2 attempts" in p.stderr
    # the full per-attempt log survives into the terminal error
    assert "attempt 1/2" in p.stderr and "attempt 2/2" in p.stderr
    assert p.stdout.strip() == ""  # no half-measured JSON line


def test_backend_unavailable_message_is_one_actionable_line():
    """ISSUE 6 satellite: the BENCH_r04/r05 failure mode (requested TPU
    backend absent) must classify as deterministic and produce the
    one-line error naming the backend and JAX_PLATFORMS — not a raw jax
    traceback.  Canned phrasings cover both jax spellings."""
    import bench

    # the r04/r05 spelling: platform requested but not present
    e = RuntimeError("Unknown backend: 'tpu' requested, but no platforms "
                     "that are instances of tpu are present.")
    msg = bench.backend_unavailable_error(e)
    assert msg is not None and "\n" not in msg
    assert "'tpu'" in msg and "JAX_PLATFORMS" in msg
    assert "JAX_PLATFORMS=cpu" in msg  # the actionable remediation

    # the config-level spelling (BENCH_PLATFORM typo / missing plugin)
    e2 = RuntimeError("Unable to initialize backend 'nope': Backend 'nope' "
                      "is not in the list of known backends: ['cpu'].")
    msg2 = bench.backend_unavailable_error(e2)
    assert msg2 is not None and "'nope'" in msg2 and "JAX_PLATFORMS" in msg2


def test_backend_transient_init_failure_keeps_retry_path():
    """A flapped tunnel ("UNAVAILABLE") is NOT deterministic absence: the
    fail-fast classifier must decline it so the bounded re-exec retry
    still runs — but the hint lands in the final give-up line."""
    import bench

    e = RuntimeError("Unable to initialize backend 'tpu': UNAVAILABLE: "
                     "connection attempt failed")
    assert bench.backend_unavailable_error(e) is None
    hint = bench.backend_hint(e)
    assert hint is not None and "'tpu'" in hint and "JAX_PLATFORMS" in hint
    # non-backend errors classify as neither
    assert bench.backend_unavailable_error(ValueError("bad BENCH_BS")) is None
    assert bench.backend_hint(ValueError("bad BENCH_BS")) is None


def test_backend_unavailable_fails_fast_end_to_end(tmp_path):
    """The subprocess contract: an absent backend exits once with the
    one-line error — no 5 x 60 s retry burn, no raw jax traceback — and
    leaves a TYPED stub artifact (ISSUE 11 satellite: a fleet scraping
    bench outputs can tell "backend absent" from "bench never ran")."""
    stub_path = str(tmp_path / "BENCH_unavailable.json")
    p = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True, timeout=120,
        env=_env(BENCH_PLATFORM="nope", BENCH_INIT_RETRIES=5,
                 BENCH_UNAVAILABLE_OUT=stub_path),
    )
    assert p.returncode != 0
    assert "backend 'nope' unavailable" in p.stderr
    assert "JAX_PLATFORMS" in p.stderr
    assert "Traceback" not in p.stderr
    assert "attempt 1/" not in p.stderr  # no retries were burned
    assert p.stdout.strip() == ""  # the JSON-line contract: no artifact
    stub = json.load(open(stub_path))  # ... on stdout; the stub is a FILE
    assert stub["status"] == "backend_unavailable"
    assert "'nope'" in stub["error"] and "\n" not in stub["error"]
    assert "run_id" in stub
    assert not os.path.exists(stub_path + ".tmp")  # atomic publish


class _FakeRecorder:
    def __init__(self):
        import collections
        self.time_history = collections.defaultdict(list)

    def start(self, k):
        pass

    def end(self, k):
        pass

    def cancel(self, k):
        pass


class _FakeTrainer:
    """Deterministic stand-in: each train_iter costs ``step_s`` wall
    seconds (plus ``rtt_s`` once at the final sync, like the tunnel's
    scalar fetch) — lets the slope math be asserted against known time."""

    def __init__(self, step_s, rtt_s):
        self.step_s, self.rtt_s = step_s, rtt_s
        self.recorder = _FakeRecorder()
        self._pending = 0

    def train_iter(self, batch, lr):
        self._pending += 1
        return {"cost": self}

    def __float__(self):  # float(m["cost"]) = the one sync
        import time
        time.sleep(self._pending * self.step_s + self.rtt_s)
        self._pending = 0
        return 0.0


def test_slope_estimator_cancels_constant_fetch_cost():
    """The slope between a short and a long chain must recover the true
    per-step time even when every trial carries a constant final-fetch
    cost that inflates the chain estimate dt/n (VERDICT r4 #2)."""
    from theanompi_tpu.utils.benchlib import best_slope, best_trial

    # coarse times so a CI scheduler oversleep (~tens of ms) cannot flip
    # the verdict: min-over-positive-slopes favors deflated trials, so a
    # tight tolerance would get FLAKIER with more trials, not less
    t = _FakeTrainer(step_s=0.05, rtt_s=0.6)
    (chain_dt, chain_n, _), _ = best_trial(t, [{}], steps=10, trials=2)
    chain_est = chain_dt / chain_n
    (slope_est, _), results, fell_back = best_slope(
        t, [{}], n_lo=2, n_hi=10, trials=2)
    assert not fell_back and len(results) == 2
    # chain estimate carries rtt/n = 60 ms/step of bias; slope must not
    assert chain_est > 0.1
    assert abs(slope_est - 0.05) < 0.02


def test_slope_estimator_flags_fallback(monkeypatch):
    """All-non-positive slopes must surface used_fallback=True, not
    masquerade as a slope measurement."""
    from theanompi_tpu.utils import benchlib

    def fake_run_trial(trainer, batches, steps, feed_mode, lr=0.01):
        # hi chain reported FASTER than lo chain -> negative slope
        return (1.0 if steps <= 2 else 0.5), steps, 0.0

    monkeypatch.setattr(benchlib, "run_trial", fake_run_trial)
    (est, _), results, fell_back = benchlib.best_slope(
        None, [{}], n_lo=2, n_hi=10, trials=3)
    assert fell_back
    assert est == 0.05  # dt_hi / n_hi of the fastest trial
    assert all(r[0] <= 0 for r in results)
