"""bench.py retry path: survive transient backend outages (VERDICT r4 #1).

Round 4's driver bench died on one transient ``UNAVAILABLE`` from the
tunneled TPU backend and the round lost its headline artifact.  bench.py now
retries by re-exec'ing itself (jax caches a failed backend init for the life
of the process, so only a fresh process can actually retry).  These tests
drive that path with the BENCH_FAIL_UNTIL_ATTEMPT fault-injection knob on
the CPU backend — the reference had no analogue (its launcher just died with
mpirun, SURVEY.md §4); this is harness hardening our driver contract needs.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    env.update({
        # BENCH_PLATFORM, not just JAX_PLATFORMS: sitecustomize bakes the
        # tunnel platform into jax's config defaults, so only the
        # config-level force keeps the subprocess off a (possibly downed,
        # init-blocking) tunnel backend
        "JAX_PLATFORMS": "cpu",
        "BENCH_PLATFORM": "cpu",
        "BENCH_MODEL": "wide_resnet",  # primary only: no side-bench
        "BENCH_BS": "8",
        "BENCH_STEPS": "2",
        "BENCH_TRIALS": "1",
        "BENCH_RETRY_BACKOFF": "0",
    })
    env.update({k: str(v) for k, v in extra.items()})
    # a stale attempt counter inherited from the runner would skew the test
    env.pop("BENCH_ATTEMPT", None)
    env.pop("BENCH_ATTEMPT_LOG", None)
    return env


def test_retry_recovers_after_transient_failures():
    p = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True, timeout=600,
        env=_env(BENCH_FAIL_UNTIL_ATTEMPT=3, BENCH_INIT_RETRIES=5),
    )
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, p.stdout  # driver contract: ONE JSON line
    out = json.loads(lines[0])
    assert out["value"] > 0
    assert "run_id" in out  # staleness stamp (VERDICT r4 #1)
    # both failed attempts left a visible trace
    assert "attempt 1/5" in p.stderr and "attempt 2/5" in p.stderr


def test_retry_gives_up_with_attempt_log_in_error_tail():
    p = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True, timeout=120,
        env=_env(BENCH_FAIL_UNTIL_ATTEMPT=99, BENCH_INIT_RETRIES=2),
    )
    assert p.returncode != 0
    assert "giving up after 2 attempts" in p.stderr
    # the full per-attempt log survives into the terminal error
    assert "attempt 1/2" in p.stderr and "attempt 2/2" in p.stderr
    assert p.stdout.strip() == ""  # no half-measured JSON line
