"""Native C augmentation helper: bit-exact vs the numpy reference.

The C path is a host-runtime optimization; the numpy per-image loop
remains the source of truth.  Both loader call sites draw the rng BEFORE
choosing a path, so enabling/disabling the native library never changes
training data.
"""

import numpy as np
import pytest

from theanompi_tpu import native


def _numpy_ref(src, out_h, out_w, ys, xs, flips):
    n = src.shape[0]
    res = np.empty((n, out_h, out_w, src.shape[3]), src.dtype)
    for i in range(n):
        img = src[i, ys[i]: ys[i] + out_h, xs[i]: xs[i] + out_w]
        res[i] = img[:, ::-1] if flips[i] else img
    return res


@pytest.mark.parametrize("dtype", [np.uint8, np.float32])
def test_crop_mirror_batch_matches_numpy(dtype):
    if native.lib() is None:
        pytest.skip("no C compiler available")
    rng = np.random.RandomState(0)
    src = (rng.rand(16, 40, 40, 3) * 255).astype(dtype)
    ys = rng.randint(0, 9, 16)
    xs = rng.randint(0, 9, 16)
    flips = rng.rand(16) < 0.5
    got = native.crop_mirror_batch(src, 32, 32, ys, xs, flips)
    assert got is not None
    np.testing.assert_array_equal(got, _numpy_ref(src, 32, 32, ys, xs, flips))


def test_loader_paths_identical_with_and_without_native(monkeypatch):
    """pad_crop_mirror / random_crop_mirror must produce the same batches
    whether or not the native library loads (same rng draw order)."""
    from theanompi_tpu.models.data.cifar10 import pad_crop_mirror
    from theanompi_tpu.models.data.imagenet import random_crop_mirror

    rng = np.random.RandomState(3)
    x32 = rng.rand(8, 32, 32, 3).astype(np.float32)
    x48 = (rng.rand(8, 48, 48, 3) * 255).astype(np.uint8)

    with_native = (pad_crop_mirror(x32, np.random.RandomState(7)),
                   random_crop_mirror(x48, 40, np.random.RandomState(7)))
    monkeypatch.setattr(native, "crop_mirror_batch",
                        lambda *a, **k: None)  # force numpy fallback
    without = (pad_crop_mirror(x32, np.random.RandomState(7)),
               random_crop_mirror(x48, 40, np.random.RandomState(7)))
    for a, b in zip(with_native, without):
        np.testing.assert_array_equal(a, b)


def test_native_build_is_cached(tmp_path):
    if native.lib() is None:
        pytest.skip("no C compiler available")
    import os

    assert os.path.exists(native._SO)
    # second call must not rebuild (same handle)
    assert native.lib() is native.lib()
