"""Timing-discipline lint (ISSUE 1 satellite), now a tmlint shim (ISSUE 7).

The ad-hoc regex walker that lived here moved into the rule registry as
``theanompi_tpu/analysis/rules.py::WallClockRule`` — this file keeps the
original test name green (bisectability) and proves the ported rule
still catches the negative case it was born from.  Coverage is the rule
engine's default path set: the whole package (serving/ and resilience/
included) plus ``bench.py``.
"""

from theanompi_tpu.analysis import core


def test_no_wall_clock_in_timed_paths():
    """No unsuppressed ``time.time()`` anywhere tmlint scans — durations
    use ``time.perf_counter()``; genuine wall-clock stamps carry a
    justified ``lint: wall-ok`` marker."""
    findings, n_files = core.lint_paths(rule_names=["wall"])
    offenders = [f.format() for f in findings
                 if f.rule == "wall" and not f.suppressed]
    assert n_files > 70, f"suspiciously small scan: {n_files} files"
    assert not offenders, (
        "time.time() in timed paths — use time.perf_counter() for "
        "durations (or mark the line 'lint: wall-ok — <why>'):\n"
        + "\n".join(offenders))


def test_wall_rule_still_catches_the_original_negative_case(tmp_path):
    """The ported rule fires on a bare time.time() and honours a
    justified marker — the legacy lint's exact semantics."""
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nt0 = time.time()\n")
    findings, _ = core.lint_paths([str(bad)], ["wall"], root=str(tmp_path))
    assert any(f.rule == "wall" and not f.suppressed for f in findings)

    ok = tmp_path / "ok.py"
    ok.write_text("import time\n"
                  "t0 = time.perf_counter()\n"
                  "stamp = time.time()  # lint: wall-ok — run-id stamp\n")
    findings, _ = core.lint_paths([str(ok)], ["wall"], root=str(tmp_path))
    assert not [f for f in findings if not f.suppressed]
    assert [f for f in findings if f.suppressed]  # visible, not silent
