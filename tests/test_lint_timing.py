"""Timing-discipline lint (ISSUE 1 satellite): no wall-clock in timed paths.

``time.time()`` is NTP-steppable and low-resolution; every duration in
``theanompi_tpu/`` (recorder splits, telemetry spans, bench protocols)
must come from ``time.perf_counter()``.  This pytest-collected static
check fails the build the moment a wall-clock call sneaks into package
code or the bench entrypoint — wall-clock *stamps* (ISO strings for run
ids / session metadata) use ``time.strftime``/``datetime``, which the
lint deliberately permits.

A genuinely wall-clock-needing line can opt out with a ``lint: wall-ok``
comment, which keeps the exception visible at the call site.
"""

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parents[1]
PATTERN = re.compile(r"\btime\.time\(\)")
ALLOW_MARK = "lint: wall-ok"


def _python_files():
    yield from sorted((REPO / "theanompi_tpu").rglob("*.py"))
    yield REPO / "bench.py"


def test_no_wall_clock_in_timed_paths():
    offenders = []
    for path in _python_files():
        for lineno, line in enumerate(
                path.read_text().splitlines(), start=1):
            if PATTERN.search(line) and ALLOW_MARK not in line:
                offenders.append(
                    f"{path.relative_to(REPO)}:{lineno}: {line.strip()}")
    assert not offenders, (
        "time.time() in timed paths — use time.perf_counter() for "
        "durations (or mark the line 'lint: wall-ok' if wall time is "
        "genuinely required):\n" + "\n".join(offenders))
