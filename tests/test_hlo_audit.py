"""Compiled-artifact auditor tests (ISSUE 7 tentpole, HLO half).

The acceptance criterion: donation + the PR 2 collective-count lock
asserted for at least ``psum_bucket`` and ``zero1``, plus the serve
decode step.  Artifacts are ``lru_cache``'d in the auditor, so the
strategy compiles here are shared with ``test_lint_collectives.py``.
Negative proofs run on throwaway jitted toys (ms-scale compiles): a
pure_callback IS detected, an undonated step IS detected — the auditor
must be falsifiable, not a rubber stamp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.analysis import hlo_audit


# ---------------------------------------------------------------------------
# parsers (pure text)
# ---------------------------------------------------------------------------

HEADER = ("HloModule jit_step, is_scheduled=true, input_output_alias={ "
          "{0}: (0, {}, may-alias), {1}: (1, {}, may-alias), "
          "{2,0}: (3, {}, may-alias) }, entry_computation_layout=...")


def test_donation_alias_parser():
    assert hlo_audit.donation_alias_count(HEADER) == 3
    assert hlo_audit.donation_alias_count("HloModule jit_f, "
                                          "entry_computation_layout=x") == 0


def test_host_callback_parser():
    text = ('%cc = (f32[8]) custom-call(s64[] %c), '
            'custom_call_target="xla_python_cpu_callback"\n'
            '%ok = f32[8] custom-call(f32[8] %x), '
            'custom_call_target="SomeBlasGemm"\n')
    assert hlo_audit.host_callbacks(text) == ["xla_python_cpu_callback"]


# a synthetic optimized entry: ar.1 -> fusion.1 -> ar.2 is a chained,
# interleaved pair; ar.3 hangs off the same input with no collective
# ancestor (trailing)
CHAINED_ENTRY = """\
HloModule jit_step, is_scheduled=true

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8] parameter(0)
  %ar.1 = f32[8] all-reduce(f32[8] %p0), to_apply=%add
  %fusion.1 = f32[8] fusion(f32[8] %ar.1), kind=kLoop
  %ar.2 = f32[8] all-reduce(f32[8] %fusion.1), to_apply=%add
  %ar.3 = f32[8] all-reduce(f32[8] %p0), to_apply=%add
  %ag.1 = f32[8] all-gather(f32[8] %ar.2), dimensions={0}
  ROOT %out = f32[8] fusion(f32[8] %ag.1, f32[8] %ar.3), kind=kLoop
}
"""


def test_entry_dependency_graph_parser():
    graph, order = hlo_audit.entry_dependency_graph(CHAINED_ENTRY)
    assert order == ["p0", "ar.1", "fusion.1", "ar.2", "ar.3", "ag.1", "out"]
    assert graph["ar.2"][0] == "all-reduce"
    # %name extraction over-approximates (to_apply=%add rides along) —
    # safe for reachability, which only follows entry-defined names
    assert graph["ar.2"][1] == ["fusion.1", "add"]
    assert graph["out"][0] == "fusion"


def test_collective_chain_stats_discriminates():
    """ar.1->ar.2 is one same-kind chained pair, through a fusion; the
    all-gather's dependency on the all-reduces is CROSS-kind and must not
    count (zero1's scatter->update->gather exists in either schedule)."""
    stats = hlo_audit.collective_chain_stats(CHAINED_ENTRY)
    assert stats == {"n_collectives": 4, "chained_same_kind": 1,
                     "interleaved_pairs": 1}


def test_collective_chain_stats_on_trailing_schedule():
    trailing = CHAINED_ENTRY.replace("f32[8] %fusion.1), to_apply",
                                     "f32[8] %p0), to_apply")
    stats = hlo_audit.collective_chain_stats(trailing)
    assert stats["chained_same_kind"] == 0
    assert stats["interleaved_pairs"] == 0


# ---------------------------------------------------------------------------
# the locked artifacts
# ---------------------------------------------------------------------------


def test_psum_bucket_audit():
    r = hlo_audit.audit_train_step("psum_bucket")
    assert r["ok"], r["violations"]
    assert r["collectives"].get("all-reduce", 0) <= 4
    assert r["alias_count"] >= r["n_param_leaves"]  # donation applied
    assert r["host_callbacks"] == []


def test_zero1_audit():
    r = hlo_audit.audit_train_step("zero1")
    assert r["ok"], r["violations"]
    assert r["collectives"].get("reduce-scatter", 0) >= 1
    assert r["collectives"].get("all-gather", 0) >= 1
    assert r["collectives"].get("all-reduce", 0) <= 3
    assert r["alias_count"] >= r["n_param_leaves"]
    assert r["host_callbacks"] == []


def test_serve_decode_audit():
    r = hlo_audit.audit_serve_step()
    assert r["ok"], r["violations"]
    assert r["alias_count"] >= 2          # k and v pools donated
    assert r["collectives"] == {}         # single-device serve
    assert r["host_callbacks"] == []


def test_serve_prefill_audit():
    """ISSUE 17: the prefix-cache hit path (partial prefill) holds the
    same HLO contract as decode — donated pools, zero collectives."""
    r = hlo_audit.audit_serve_prefill()
    assert r["ok"], r["violations"]
    assert r["alias_count"] >= 2          # k and v pools donated
    assert r["collectives"] == {}
    assert r["host_callbacks"] == []


@pytest.mark.parametrize("strategy", hlo_audit.DEFAULT_OVERLAP_STRATEGIES)
def test_overlap_schedule_audit(strategy):
    """ISSUE 12 acceptance: the optimized HLO proves the overlapped
    schedule — a same-kind collective chain of >= n_buckets-1 edges
    running through backward fusions, identical collective counts, and
    the fused baseline still trailing (0 chain edges)."""
    r = hlo_audit.audit_overlap_schedule(strategy)
    assert r["ok"], r["violations"]
    assert r["n_buckets"] >= 2
    assert r["chain"]["chained_same_kind"] >= r["n_buckets"] - 1
    assert r["chain"]["interleaved_pairs"] >= r["n_buckets"] - 1
    # negative proof: fused still audits as trailing
    assert r["fused_chain"]["chained_same_kind"] == 0


def test_serve_decode_kernel_audit():
    """ISSUE 18: the decode fast path dispatches as TPU custom calls
    (with the kernel-off lowering as the negative proof), keeps the
    donation / zero-collective contract, and the kernel is bit-identical
    to the fallback on CPU."""
    r = hlo_audit.audit_serve_decode_kernel()
    assert r["ok"], r["violations"]
    assert r["custom_calls_on"] >= r["n_layers"]   # paged attn per layer
    assert r["custom_calls_off"] == 0              # negative proof
    assert r["custom_calls_int8"] >= 1             # fused int8 matmul
    assert r["alias_count"] >= 2                   # pools stay donated
    assert r["collectives"] == {}
    assert r["decode_parity_bitwise"]
    assert r["int8_rel_err"] <= hlo_audit.INT8_REL_TOL


def test_run_default_audits_is_green():
    reports = hlo_audit.run_default_audits()
    assert [(r["kind"], r.get("strategy")) for r in reports] == [
        ("train", "psum_bucket"), ("train", "zero1"),
        ("train-overlap", "psum_bucket"), ("train-overlap", "zero1"),
        ("serve", None), ("serve-prefill", None), ("serve-kernel", None)]
    assert all(r["ok"] for r in reports)


# ---------------------------------------------------------------------------
# negative proofs: the auditor detects what it claims to detect
# ---------------------------------------------------------------------------


def test_auditor_detects_a_host_callback():
    def cb(v):
        return v

    def step(x):
        return jax.pure_callback(
            cb, jax.ShapeDtypeStruct((4,), jnp.float32), x) * 2.0

    text = jax.jit(step).lower(jnp.ones((4,), jnp.float32)) \
        .compile().as_text()
    facts = hlo_audit.audit_text(text)
    assert facts["host_callbacks"], "pure_callback not detected in HLO"


def test_auditor_detects_missing_donation():
    def step(x, y):
        return x + y, x * y

    args = (jnp.ones((8,)), jnp.ones((8,)))
    undonated = jax.jit(step).lower(*args).compile().as_text()
    donated = jax.jit(step, donate_argnums=(0,)).lower(*args) \
        .compile().as_text()
    assert hlo_audit.donation_alias_count(undonated) == 0
    assert hlo_audit.donation_alias_count(donated) >= 1


def test_budget_violation_surfaces_in_report(monkeypatch):
    """Tighten the psum_bucket lock to an impossible bound: the audit
    must report the violation (and run_default_audits must raise)."""
    tight = dict(hlo_audit.TRAIN_COLLECTIVE_BUDGETS)
    tight["psum_bucket"] = {"all-reduce": (0, 0)}
    monkeypatch.setattr(hlo_audit, "TRAIN_COLLECTIVE_BUDGETS", tight)
    r = hlo_audit.audit_train_step("psum_bucket")
    assert not r["ok"] and any("locked maximum" in v
                               for v in r["violations"])
    with pytest.raises(hlo_audit.HLOAuditError, match="locked maximum") as ei:
        hlo_audit.run_default_audits()
    # the CLI publishes the artifact on failure: the completed reports
    # (showing WHAT failed) must ride the exception (review fix).  Only
    # the tightened psum_bucket TRAIN lock fails — the overlap audits
    # have their own invariants and stay green
    assert [rep["ok"] for rep in ei.value.reports] == [
        False, True, True, True, True, True, True]


def test_train_cfg_matches_the_locked_fixture():
    """The audit model must keep >=30 leaves or the bucket lock proves
    nothing (mirrors the PR 2 acceptance bar)."""
    r = hlo_audit.audit_train_step("psum_bucket")
    assert r["n_param_leaves"] >= 30
    assert np.isfinite(r["alias_count"])
