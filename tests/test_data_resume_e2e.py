"""Mid-epoch crash-resume equivalence (ISSUE 10 acceptance): a run killed
BETWEEN epoch boundaries resumes from the newest iteration-cadence
checkpoint and finishes with params bit-equal to an uninterrupted run —
and the consumed-batch witness trace (THEANOMPI_DATA_TRACE) proves no
batch was replayed and none skipped.  Covered for the supervised-SIGKILL
subprocess path (psum), the in-process zero1 exchange, and the elastic
mesh8->4 resharded resume (sample-cursor arithmetic at a different global
batch size).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from theanompi_tpu.resilience import FaultInjected

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_CFG = {"depth": 10, "widen": 1, "batch_size": 4, "image_size": 8,
            "n_train": 32, "n_val": 16, "n_epochs": 2, "precision": "fp32"}
TINY_ARGS = ["--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
             "--set", "image_size=8", "--set", "n_train=32",
             "--set", "n_val=16", "--set", "precision='fp32'"]


def _child_env(**extra):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_THREEFRY_PARTITIONABLE": "true",
        "PYTHONPATH": REPO,
    })
    env.pop("THEANOMPI_FAULT_PLAN", None)
    env.pop("THEANOMPI_DATA_TRACE", None)
    env.update(extra)
    return env


def _trace(path):
    """-> [(epoch, batch_index)] consumed-step witness lines."""
    if not os.path.exists(path):
        return []
    return [tuple(int(v) for v in line.split())
            for line in open(path) if line.strip()]


def _assert_ckpt_equal(path_a, path_b):
    with np.load(path_a) as a, np.load(path_b) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def _bsp(devices, ck, n_epochs=2, model_over=None, **cfg):
    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "checkpoint_dir": ck, **cfg})
    rule.init(devices=devices, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY_CFG, "n_epochs": n_epochs,
                            **(model_over or {})})
    return rule


@pytest.mark.faultinject
def test_mid_epoch_sigkill_supervised_resume_no_replay_no_skip(
        tmp_path, monkeypatch, subproc_compile_cache):
    """THE acceptance scenario: checkpoint_every_n_iters=1 + SIGKILL one
    step INTO epoch 1 (a non-boundary iteration).  The supervised restart
    resumes from the newest iteration-cadence checkpoint, re-enters epoch
    1 at the batch cursor, and (a) the final checkpoint is bit-equal to an
    uninterrupted run, (b) the concatenated consumed-batch trace across
    both attempts is EXACTLY the clean run's sequence — nothing replayed,
    nothing skipped."""
    clean_trace = str(tmp_path / "trace_clean")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", clean_trace)
    clean_ck = str(tmp_path / "ck_clean")
    _bsp(4, clean_ck).wait()
    monkeypatch.delenv("THEANOMPI_DATA_TRACE")
    assert _trace(clean_trace) == [(0, 0), (0, 1), (1, 0), (1, 1)]

    ck = str(tmp_path / "ck_fault")
    fault_trace = str(tmp_path / "trace_fault")
    p = subprocess.run(
        [sys.executable, "-m", "theanompi_tpu.launcher",
         "--rule", "BSP", "--devices", "4",
         "--modelfile", "theanompi_tpu.models.wide_resnet",
         "--modelclass", "WideResNet", *TINY_ARGS,
         "--set", "n_epochs=2", "--quiet",
         "--rule-set", "checkpoint_every_n_iters=1",
         # synchronous saves: with the async writer a SIGKILL one step
         # after the cadence point can beat the publish, and the restart
         # would (correctly, but nondeterministically for this test)
         # resume from the older boundary checkpoint instead
         "--rule-set", "checkpoint_async=False",
         "--checkpoint-dir", ck,
         "--compile-cache-dir", subproc_compile_cache,
         "--supervise", "--max-restarts", "3", "--backoff-base", "0.1"],
        # iteration 3 = the SECOND step of epoch 1: the newest cadence
        # checkpoint at kill time is epoch 1's mid-epoch save (cursor 1),
        # NOT the epoch-0 boundary — the restart must fast-forward, not
        # replay epoch 1 from its start
        env=_child_env(THEANOMPI_FAULT_PLAN="step:kill@3@1",
                       THEANOMPI_DATA_TRACE=fault_trace),
        cwd=REPO, capture_output=True, text=True, timeout=480)
    assert p.returncode == 0, p.stderr[-2000:]

    art = json.load(open(os.path.join(ck, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]
    # the no-replay/no-skip witness: both attempts appended to one trace
    assert _trace(fault_trace) == _trace(clean_trace)
    # bit-equal final lineage, including the __data_state__ leaf
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))


@pytest.mark.faultinject
def test_mid_epoch_crash_resume_zero1_inprocess(tmp_path, monkeypatch):
    """Mid-epoch resume across the sharded-optimizer exchange, in-process:
    the cadence checkpoint's data cursor round-trips through try_resume
    and the finished lineage is bit-equal to the uninterrupted one."""
    clean_trace = str(tmp_path / "trace_clean")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", clean_trace)
    clean_ck = str(tmp_path / "ck_clean")
    _bsp(4, clean_ck, exch_strategy="zero1").wait()

    ck = str(tmp_path / "ck_fault")
    fault_trace = str(tmp_path / "trace_fault")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", fault_trace)
    rule = _bsp(4, ck, exch_strategy="zero1", fault_plan="step:raise@3",
                checkpoint_every_n_iters=1)
    with pytest.raises(FaultInjected):
        rule.wait()  # dies at the second step of epoch 1
    assert rule.trainer.try_resume()
    # the resume point is MID-epoch-1 (the cadence save), not epoch 2
    assert rule.trainer.epoch == 1
    rds = rule.trainer._resume_data_state
    assert rds is not None and not rds["completed"]
    assert rds["batch_cursor"] == 1
    assert rds["sample_cursor"] == rds["batch_cursor"] * 16
    rule.wait()
    assert rule.trainer.epoch == 2
    assert _trace(fault_trace) == _trace(clean_trace)
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))


@pytest.mark.faultinject
def test_mid_epoch_elastic_reshard_resume_consumes_each_sample_once(
        tmp_path, monkeypatch):
    """Elastic mesh8->4 mid-epoch: the checkpointed cursor is in SAMPLES,
    so the mesh4 resume recomputes its own batch cursor (sample_cursor /
    its global batch) and consumes exactly the samples the mesh8 attempt
    had not — the per-attempt traces tile epoch 1's sample range with no
    overlap and no gap."""
    over = {"n_train": 64}  # mesh8: 2 steps/epoch @ GB=32; mesh4: 4 @ 16
    ck = str(tmp_path / "ck")
    t8 = str(tmp_path / "trace8")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", t8)
    rule8 = _bsp(8, ck, model_over=over, exch_strategy="psum_bucket",
                 fault_plan="step:raise@3", checkpoint_every_n_iters=1)
    with pytest.raises(FaultInjected):
        rule8.wait()  # epoch 0 done; one of epoch 1's two steps done
    assert _trace(t8) == [(0, 0), (0, 1), (1, 0)]

    t4 = str(tmp_path / "trace4")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", t4)
    rule4 = _bsp(4, ck, model_over=over, exch_strategy="psum_bucket",
                 resume_reshard=True, checkpoint_every_n_iters=1)
    t = rule4.trainer
    assert t.epoch == 1 and t.lr_scale == pytest.approx(0.5)
    rule4.wait()
    assert t.epoch == 2

    # sample-interval tiling: epoch-1 lines from the mesh8 attempt cover
    # [c*32, (c+1)*32), from the mesh4 resume [c*16, (c+1)*16); together
    # they must partition [0, 64) exactly
    spans = sorted([(c * 32, (c + 1) * 32)
                    for e, c in _trace(t8) if e == 1] +
                   [(c * 16, (c + 1) * 16)
                    for e, c in _trace(t4) if e == 1])
    assert spans[0][0] == 0 and spans[-1][1] == 64
    for (_, a_end), (b_start, _) in zip(spans, spans[1:]):
        assert a_end == b_start, f"replay or gap at sample {b_start}"
    # and the mesh4 attempt really started mid-epoch, at batch 2 of 4
    assert [c for e, c in _trace(t4) if e == 1] == [2, 3]
    # the boundary save after the resumed epoch carries the mesh4 stamp
    man = json.load(open(os.path.join(ck, "ckpt_e0001.manifest.json")))
    assert man["fingerprint"]["mesh"]["data"] == 4
    assert man["data_state"]["completed"] is True
    assert man["data_state"]["sample_cursor"] == 64
