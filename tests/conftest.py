"""Test bootstrap: fake an 8-chip mesh on host CPU.

The reference could only be tested on a real CUDA+MPI cluster (SURVEY.md §4 —
manual mpirun scripts, no CI).  We instead force 8 virtual CPU devices so
every collective path (psum, ppermute rings, shardings) runs in unit tests
with no TPU attached.  force_host_devices handles the platform/flag overrides.
"""


from theanompi_tpu.parallel.mesh import force_host_devices  # noqa: E402

force_host_devices(8)

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def subproc_compile_cache(tmp_path_factory):
    """Shared persistent compile cache for subprocess-spawning tests
    (resilience e2e, runbook supervision): the first child pays the XLA
    compile, every later child with the same program loads it.  Resumed
    children skip it by design (the launcher's jaxlib cache-load guard)."""
    return str(tmp_path_factory.mktemp("subproc-ccache"))


@pytest.fixture(scope="session", autouse=True)
def _session_compile_cache_env(subproc_compile_cache):
    """Tier-1 velocity (ISSUE 17 satellite): export the session compile
    cache as ``THEANOMPI_COMPILE_CACHE`` so every ``python -m
    theanompi_tpu.launcher`` subprocess — including the ones that never
    passed ``--compile-cache-dir`` — shares the one warm XLA cache (the
    launcher's ``__main__`` block injects the flag from the env).
    In-process ``launcher.main([...])`` calls are untouched: every
    production ``setup_compile_cache`` call site passes an explicit
    directory, so the env fallback never fires inside the test process."""
    import os

    prev = os.environ.get("THEANOMPI_COMPILE_CACHE")
    os.environ["THEANOMPI_COMPILE_CACHE"] = subproc_compile_cache
    yield
    if prev is None:
        os.environ.pop("THEANOMPI_COMPILE_CACHE", None)
    else:
        os.environ["THEANOMPI_COMPILE_CACHE"] = prev


@pytest.fixture(scope="session")
def mesh8():
    from theanompi_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=8)


@pytest.fixture(scope="session")
def mesh4():
    """4 data-parallel devices — the ISSUE 2 acceptance mesh; ring-strategy
    compiles unroll 2(n-1) hops, so exchange tests that don't need 8 workers
    run here at less than half the XLA compile cost."""
    from theanompi_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=4, devices=jax.devices()[:4])


@pytest.fixture(scope="session")
def mesh4x2():
    from theanompi_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=4, n_model=2)


# -- trained-model session fixtures (ISSUE 11 satellite) ----------------------
# Several files used to train their own tiny model per module; at session
# scope the training cost is paid once for the whole tier-1 run.

#: the serving test config (test_serving imports this as its TINY — one
#: source of truth, so the fixture and the per-test references can't drift)
SERVING_TINY = {
    "batch_size": 2, "n_train": 64, "n_val": 32, "seq_len": 32,
    "vocab": 61, "dim": 32, "heads": 2, "n_layers": 2,
    "dropout": 0.0, "n_epochs": 1, "precision": "fp32",
}


@pytest.fixture(scope="session")
def dense_model():
    """A tiny TransformerLM lightly trained on the synthetic bigram stream
    (40 plain-SGD steps, one jit) — serving tests run against weights with
    real structure: at random init the logits are near-tied and int8
    argmax agreement measures coin flips, not quantization quality.
    Session-scoped and treated as READ-ONLY by every consumer."""
    from theanompi_tpu.models.transformer_lm import TransformerLM

    model = TransformerLM(dict(SERVING_TINY))
    params, state = model.init_params(jax.random.PRNGKey(0))
    batches = list(model.data.train_batches(8, 0, seed=0))

    @jax.jit
    def step(p, batch):
        g = jax.grad(
            lambda p: model.loss_fn(p, state, batch, None, False)[0])(p)
        return jax.tree.map(lambda a, b: a - 0.5 * b, p, g)

    for i in range(40):
        params = step(params, batches[i % len(batches)])
    return model, params, state


@pytest.fixture(scope="session")
def serving_engine_factory(dense_model):
    """Memoizing InferenceEngine factory over the session ``dense_model``
    (ISSUE 18 satellite — tier-1 velocity): engines are keyed on their
    construction kwargs, so every test asking for the same configuration
    shares ONE engine and its compiled decode/prefill programs for the
    whole tier-1 run.  Defaults are the canonical serving geometry
    (``block_size=4, max_batch=2, seed=0``).

    Shared engines are READ-ONLY above the pools: tests may prefill /
    decode through them freely (pool contents are scratch — the position
    masks make stale blocks invisible, the same property eviction relies
    on) but must NOT ``swap_params`` or monkeypatch them.  Tests that
    mutate weights (the rollout suite) pass ``shared=False`` for a
    private engine with the same canonical construction."""
    from theanompi_tpu.serving.engine import InferenceEngine

    model, params, _state = dense_model
    cache: dict = {}

    def make(shared=True, **kw):
        kw.setdefault("block_size", 4)
        kw.setdefault("max_batch", 2)
        kw.setdefault("seed", 0)
        if not shared:
            return InferenceEngine(model, params, **kw)
        key = tuple(sorted(kw.items()))
        if key not in cache:
            cache[key] = InferenceEngine(model, params, **kw)
        return cache[key]

    return make


@pytest.fixture(scope="session")
def serving_engine(serving_engine_factory):
    """The canonical shared serving engine (see
    :func:`serving_engine_factory` for the READ-ONLY contract)."""
    return serving_engine_factory()


#: the checkpoint-integrity trainer config (test_checkpoint_integrity
#: imports this as its TINY — same one-source-of-truth contract)
WRN_TINY = {"depth": 10, "widen": 1, "batch_size": 8, "image_size": 8,
            "n_train": 32, "n_val": 16, "n_epochs": 2, "precision": "fp32",
            "augment": False, "verbose": False, "lr": 0.05}


def make_wrn_trainer(mesh, checkpoint_dir, n_epochs=2, **kw):
    """A compiled, initialized tiny-WRN BSP trainer over ``mesh`` — the
    shared builder behind :func:`trained_wrn_ckpt` and the checkpoint
    tests' resuming trainers (identical construction => identical resume
    fingerprint)."""
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.utils.recorder import Recorder

    t = BSPTrainer(
        WideResNet({**WRN_TINY, "n_epochs": n_epochs}), mesh=mesh,
        exch_strategy="psum",
        recorder=Recorder(verbose=False, print_freq=4),
        checkpoint_dir=checkpoint_dir, **kw,
    )
    t.compile_iter_fns()
    t.init_state()
    return t


# -- exchange strategy-equivalence runs (ISSUE 12 satellite) ------------------

#: the exchange-equivalence trainer config (test_exchanger / test_overlap
#: build their shared runs from this — one source of truth)
EXCHANGE_TINY = {
    "depth": 10, "widen": 1, "batch_size": 2, "image_size": 8,
    "n_train": 32, "n_val": 16, "n_epochs": 1, "precision": "fp32",
    "augment": False, "verbose": False,
}


@pytest.fixture(scope="session")
def exchange_run():
    """Memoized two-step tiny-WRN training runs keyed by exchange config.

    ``run(mesh, strategy, bucket_mb=4.0, overlap=False)`` ->
    ``(trainer, params_as_numpy)``.  The strategy-equivalence matrix in
    test_exchanger.py and the fused-vs-overlapped bit-equality locks in
    test_overlap.py both compare runs against shared baselines; memoizing
    at session scope trains each distinct configuration exactly once for
    the whole tier-1 run (ROADMAP item 4 — the XLA compiles dominate).
    Consumers treat the trainer AND the params as READ-ONLY.
    """
    import numpy as np

    cache: dict = {}

    def run(mesh, strategy, bucket_mb=4.0, overlap=False):
        key = (id(mesh), strategy, float(bucket_mb), bool(overlap))
        if key not in cache:
            from theanompi_tpu.models.wide_resnet import WideResNet
            from theanompi_tpu.parallel.bsp import BSPTrainer
            from theanompi_tpu.utils.recorder import Recorder

            model = WideResNet(dict(EXCHANGE_TINY))
            t = BSPTrainer(model, mesh=mesh, exch_strategy=strategy,
                           exch_bucket_mb=bucket_mb, exch_overlap=overlap,
                           recorder=Recorder(verbose=False,
                                             print_freq=10**9))
            t.compile_iter_fns()
            t.init_state()
            for batch in list(model.data.train_batches(
                    t.global_batch, 0, seed=0))[:2]:
                t.train_iter(batch, lr=0.05)
            cache[key] = (t, jax.tree.map(np.asarray, t.params))
        return cache[key]

    return run


@pytest.fixture(scope="session")
def trained_wrn_ckpt(tmp_path_factory, mesh4):
    """A completed 2-epoch tiny-WRN training run's checkpoint directory
    (epochs 0 and 1 published, clean-shutdown handshake done).  Tests
    that corrupt or resume MUST ``shutil.copytree`` it into their own
    tmp_path first — the session copy is read-only."""
    d = str(tmp_path_factory.mktemp("wrn-trained") / "ck")
    t = make_wrn_trainer(mesh4, d)
    t.run()
    assert not t.checkpointer.was_unclean()
    return d
