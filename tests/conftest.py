"""Test bootstrap: fake an 8-chip mesh on host CPU.

The reference could only be tested on a real CUDA+MPI cluster (SURVEY.md §4 —
manual mpirun scripts, no CI).  We instead force 8 virtual CPU devices so
every collective path (psum, ppermute rings, shardings) runs in unit tests
with no TPU attached.  force_host_devices handles the platform/flag overrides.
"""


from theanompi_tpu.parallel.mesh import force_host_devices  # noqa: E402

force_host_devices(8)

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def subproc_compile_cache(tmp_path_factory):
    """Shared persistent compile cache for subprocess-spawning tests
    (resilience e2e, runbook supervision): the first child pays the XLA
    compile, every later child with the same program loads it.  Resumed
    children skip it by design (the launcher's jaxlib cache-load guard)."""
    return str(tmp_path_factory.mktemp("subproc-ccache"))


@pytest.fixture(scope="session")
def mesh8():
    from theanompi_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=8)


@pytest.fixture(scope="session")
def mesh4():
    """4 data-parallel devices — the ISSUE 2 acceptance mesh; ring-strategy
    compiles unroll 2(n-1) hops, so exchange tests that don't need 8 workers
    run here at less than half the XLA compile cost."""
    from theanompi_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=4, devices=jax.devices()[:4])


@pytest.fixture(scope="session")
def mesh4x2():
    from theanompi_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=4, n_model=2)
