"""Checkpoint/resume + tmlauncher CLI tests."""

import os

import numpy as np
import pytest

import jax

from theanompi_tpu.launcher import main as tm_main
from theanompi_tpu.utils.checkpoint import Checkpointer

TINY = {"depth": 10, "widen": 1, "batch_size": 8, "image_size": 16,
        "n_train": 128, "n_val": 64, "n_epochs": 2, "precision": "fp32",
        "lr": 0.05}


def test_checkpointer_roundtrip(tmp_path):
    ck = Checkpointer(str(tmp_path), keep=2)
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": {"c": np.ones((4,), np.int32)}}
    ck.save(0, 10, {"params": tree})
    ck.save(1, 20, {"params": tree})
    ck.save(2, 30, {"params": tree})
    # retention: only 2 newest kept
    files = [f for f in os.listdir(tmp_path) if f.endswith(".npz")]
    assert len(files) == 2
    assert ck.latest_epoch() == 2 and ck.latest_iteration() == 30

    template = {"a": np.zeros((2, 3), np.float32),
                "b": {"c": np.zeros((4,), np.int32)}}
    out = ck.load(2, {"params": template})["params"]
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["b"]["c"], tree["b"]["c"])


def test_checkpointer_shape_mismatch(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(0, 1, {"params": {"a": np.zeros((2,), np.float32)}})
    with pytest.raises(ValueError, match="shape"):
        ck.load(0, {"params": {"a": np.zeros((3,), np.float32)}})


@pytest.mark.slow
@pytest.mark.parametrize("checkpoint_async", [True, False],
                         ids=["async", "sync"])
def test_bsp_resume_continues_state(tmp_path, mesh8, checkpoint_async):
    """Train 2 epochs with checkpointing; resume restores params exactly
    (parametrized over the async/sync writer — ISSUE 3)."""
    from theanompi_tpu import BSP

    cfg = {"verbose": False, "print_freq": 4,
           "checkpoint_dir": str(tmp_path / "ck"),
           "checkpoint_async": checkpoint_async}
    rule = BSP(config=cfg)
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config=dict(TINY))
    rule.wait()
    params_after = jax.tree.map(np.asarray, rule.trainer.params)
    iters_after = rule.trainer.iteration

    rule2 = BSP(config={**cfg, "resume": True})
    rule2.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
               modelclass="WideResNet", model_config=dict(TINY))
    t2 = rule2.trainer
    assert t2.epoch == TINY["n_epochs"], "resume should start after last epoch"
    assert t2.iteration == iters_after
    for a, b in zip(jax.tree.leaves(t2.params), jax.tree.leaves(params_after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # wait() is a no-op now (all epochs done) and must not crash
    rule2.wait()


@pytest.mark.slow
def test_easgd_checkpoint_includes_center(tmp_path):
    from theanompi_tpu import EASGD

    cfg = {"verbose": False, "tau": 2, "scale_lr": False,
           "checkpoint_dir": str(tmp_path / "ck")}
    rule = EASGD(config=cfg)
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config={**TINY, "n_epochs": 1})
    rule.wait()
    center = jax.tree.map(np.asarray, rule.trainer.center)

    rule2 = EASGD(config={**cfg, "resume": True})
    rule2.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
               modelclass="WideResNet", model_config={**TINY, "n_epochs": 1})
    for a, b in zip(jax.tree.leaves(rule2.trainer.center),
                    jax.tree.leaves(center)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_launcher_kv_parsing():
    from theanompi_tpu.launcher import _parse_kv

    d = _parse_kv(["lr=0.1", "lrn=False", "stage_blocks=(1,1,1,1)",
                   "name=foo"])
    assert d == {"lr": 0.1, "lrn": False, "stage_blocks": (1, 1, 1, 1),
                 "name": "foo"}
    with pytest.raises(SystemExit):
        _parse_kv(["novalue"])


def _launch_subprocess(tmp_path, cache_dir, tag):
    """One tmlauncher subprocess on a 4-virtual-device CPU mesh with a
    shared compile cache + telemetry; -> its compile.first_step_s gauge."""
    import subprocess
    import sys

    from theanompi_tpu.telemetry.sink import read_events, sink_files

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    tel = str(tmp_path / f"tel_{tag}")
    subprocess.run(
        [sys.executable, "-m", "theanompi_tpu.launcher",
         "--rule", "BSP", "--devices", "4",
         "--modelfile", "theanompi_tpu.models.wide_resnet",
         "--modelclass", "WideResNet",
         "--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
         "--set", "image_size=8", "--set", "n_train=16", "--set", "n_val=8",
         "--set", "n_epochs=1", "--set", "precision='fp32'",
         "--compile-cache-dir", str(cache_dir),
         "--telemetry-dir", tel, "--quiet"],
        env=env, check=True, timeout=480,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    gauges = [e["value"] for p in sink_files(tel) for e in read_events(p)
              if e.get("kind") == "gauge"
              and e.get("name") == "compile.first_step_s"]
    assert len(gauges) == 1, f"expected one first-compile gauge, got {gauges}"
    return gauges[0]


def test_compile_cache_smoke(tmp_path):
    """ISSUE 3 CI satellite: two launcher subprocesses sharing a compile
    cache — the first populates it, the second's recorded first-compile
    time drops (it loads the compiled executables instead of recompiling).
    Subprocesses, not in-process runs: the persistent-cache win is
    precisely the cross-process one, and jax wires the cache config at
    backend init."""
    cache = tmp_path / "ccache"
    cold = _launch_subprocess(tmp_path, cache, "cold")
    entries = [f for f in os.listdir(cache) if f.endswith("-cache")]
    assert entries, "first run did not populate the compile cache"
    warm = _launch_subprocess(tmp_path, cache, "warm")
    assert warm < cold, (
        f"cache hit did not drop first-compile time: cold {cold:.2f}s "
        f"-> warm {warm:.2f}s"
    )


@pytest.mark.slow
def test_launcher_end_to_end(tmp_path, capsys):
    rc = tm_main([
        "--rule", "BSP", "--devices", "4",
        "--modelfile", "theanompi_tpu.models.wide_resnet",
        "--modelclass", "WideResNet",
        "--set", "depth=10", "--set", "widen=1", "--set", "batch_size=8",
        "--set", "image_size=16", "--set", "n_train=64", "--set", "n_val=32",
        "--set", "n_epochs=1", "--set", "precision='fp32'",
        "--record-dir", str(tmp_path / "rec"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "tmlauncher: done" in out
    assert os.path.exists(tmp_path / "rec" / "summary.json")
