"""Checkpoint/resume + tmlauncher CLI tests."""

import os

import numpy as np
import pytest

import jax

from theanompi_tpu.launcher import main as tm_main
from theanompi_tpu.utils.checkpoint import Checkpointer

TINY = {"depth": 10, "widen": 1, "batch_size": 8, "image_size": 16,
        "n_train": 128, "n_val": 64, "n_epochs": 2, "precision": "fp32",
        "lr": 0.05}


def test_checkpointer_roundtrip(tmp_path):
    ck = Checkpointer(str(tmp_path), keep=2)
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": {"c": np.ones((4,), np.int32)}}
    ck.save(0, 10, {"params": tree})
    ck.save(1, 20, {"params": tree})
    ck.save(2, 30, {"params": tree})
    # retention: only 2 newest kept
    files = [f for f in os.listdir(tmp_path) if f.endswith(".npz")]
    assert len(files) == 2
    assert ck.latest_epoch() == 2 and ck.latest_iteration() == 30

    template = {"a": np.zeros((2, 3), np.float32),
                "b": {"c": np.zeros((4,), np.int32)}}
    out = ck.load(2, {"params": template})["params"]
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["b"]["c"], tree["b"]["c"])


def test_checkpointer_shape_mismatch(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(0, 1, {"params": {"a": np.zeros((2,), np.float32)}})
    with pytest.raises(ValueError, match="shape"):
        ck.load(0, {"params": {"a": np.zeros((3,), np.float32)}})


@pytest.mark.slow
def test_bsp_resume_continues_state(tmp_path, mesh8):
    """Train 2 epochs with checkpointing; resume restores params exactly."""
    from theanompi_tpu import BSP

    cfg = {"verbose": False, "print_freq": 4,
           "checkpoint_dir": str(tmp_path / "ck")}
    rule = BSP(config=cfg)
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config=dict(TINY))
    rule.wait()
    params_after = jax.tree.map(np.asarray, rule.trainer.params)
    iters_after = rule.trainer.iteration

    rule2 = BSP(config={**cfg, "resume": True})
    rule2.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
               modelclass="WideResNet", model_config=dict(TINY))
    t2 = rule2.trainer
    assert t2.epoch == TINY["n_epochs"], "resume should start after last epoch"
    assert t2.iteration == iters_after
    for a, b in zip(jax.tree.leaves(t2.params), jax.tree.leaves(params_after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # wait() is a no-op now (all epochs done) and must not crash
    rule2.wait()


@pytest.mark.slow
def test_easgd_checkpoint_includes_center(tmp_path):
    from theanompi_tpu import EASGD

    cfg = {"verbose": False, "tau": 2, "scale_lr": False,
           "checkpoint_dir": str(tmp_path / "ck")}
    rule = EASGD(config=cfg)
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config={**TINY, "n_epochs": 1})
    rule.wait()
    center = jax.tree.map(np.asarray, rule.trainer.center)

    rule2 = EASGD(config={**cfg, "resume": True})
    rule2.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
               modelclass="WideResNet", model_config={**TINY, "n_epochs": 1})
    for a, b in zip(jax.tree.leaves(rule2.trainer.center),
                    jax.tree.leaves(center)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_launcher_kv_parsing():
    from theanompi_tpu.launcher import _parse_kv

    d = _parse_kv(["lr=0.1", "lrn=False", "stage_blocks=(1,1,1,1)",
                   "name=foo"])
    assert d == {"lr": 0.1, "lrn": False, "stage_blocks": (1, 1, 1, 1),
                 "name": "foo"}
    with pytest.raises(SystemExit):
        _parse_kv(["novalue"])


@pytest.mark.slow
def test_launcher_end_to_end(tmp_path, capsys):
    rc = tm_main([
        "--rule", "BSP", "--devices", "4",
        "--modelfile", "theanompi_tpu.models.wide_resnet",
        "--modelclass", "WideResNet",
        "--set", "depth=10", "--set", "widen=1", "--set", "batch_size=8",
        "--set", "image_size=16", "--set", "n_train=64", "--set", "n_val=32",
        "--set", "n_epochs=1", "--set", "precision='fp32'",
        "--record-dir", str(tmp_path / "rec"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "tmlauncher: done" in out
    assert os.path.exists(tmp_path / "rec" / "summary.json")
