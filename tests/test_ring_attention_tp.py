"""Ring attention, tensor parallelism, and the dp x tp x sp transformer."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    make_mesh,
    shard_map,
)
from theanompi_tpu.parallel.ring_attention import (
    blockwise_attention,
    ring_attention,
)
from theanompi_tpu.parallel.tensor import specs_from_rules, TP_RULES


def _reference_attention(q, k, v, causal):
    """Naive softmax attention in fp64-ish fp32 (ground truth)."""
    b, t, h, d = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_reference(causal):
    r = np.random.RandomState(0)
    q, k, v = (r.randn(2, 16, 2, 8).astype(np.float32) for _ in range(3))
    out = np.asarray(blockwise_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal,
        block_size=4,
    ))
    np.testing.assert_allclose(out, _reference_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


def test_block_attend_fully_masked_block_first():
    """A fully-masked block hitting the -1e30-init accumulator must add NO
    mass (the p=exp(0)=1 hazard): accumulation is order-independent, no
    diagonal-first invariant required."""
    from theanompi_tpu.parallel.ring_attention import _block_attend

    r = np.random.RandomState(2)
    b, t, h, d = 1, 4, 1, 8
    q, k1, v1, k2, v2 = (
        jnp.asarray(r.randn(b, t, h, d).astype(np.float32)) for _ in range(5)
    )
    m0 = jnp.full((b, h, t), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    acc0 = jnp.zeros((b, t, h, d), jnp.float32)
    none_visible = jnp.zeros((1, 1, t, t), bool)
    all_visible = jnp.ones((1, 1, t, t), bool)

    # masked block FIRST, then the visible block
    m, l, acc = _block_attend(q, k1, v1, m0, l0, acc0, none_visible)
    assert float(jnp.max(l)) == 0.0, "fully-masked block accumulated mass"
    m, l, acc = _block_attend(q, k2, v2, m, l, acc, all_visible)
    got = np.asarray(acc / l.transpose(0, 2, 1)[..., None])
    want = _reference_attention(
        np.asarray(q), np.asarray(k2), np.asarray(v2), causal=False
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Ring over 8 seq shards == full attention over the whole sequence."""
    n = 8
    mesh = make_mesh(n_data=1, n_seq=n)
    r = np.random.RandomState(1)
    b, t, h, d = 2, 64, 2, 8  # t split into 8 shards of 8
    q, k, v = (r.randn(b, t, h, d).astype(np.float32) for _ in range(3))

    f = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=causal),
            mesh,
            in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
            out_specs=P(None, SEQ_AXIS),
        )
    )
    sh = NamedSharding(mesh, P(None, SEQ_AXIS))
    out = np.asarray(f(*(jax.device_put(x, sh) for x in (q, k, v))))
    np.testing.assert_allclose(out, _reference_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads_match_full(causal):
    """The ring's custom second-pass VJP (circulating (k,v,dk,dv) bundle)
    must match autodiff of full attention — without it, autodiff would save
    every hop's probability block (O(T^2/n) per device)."""
    n = 8
    mesh = make_mesh(n_data=1, n_seq=n)
    r = np.random.RandomState(7)
    b, t, h, d = 2, 64, 2, 8
    q, k, v = (r.randn(b, t, h, d).astype(np.float32) for _ in range(3))

    f = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=causal),
            mesh,
            in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
            out_specs=P(None, SEQ_AXIS),
        )
    )
    sh = NamedSharding(mesh, P(None, SEQ_AXIS))
    qd, kd, vd = (jax.device_put(x, sh) for x in (q, k, v))

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(f(q, k, v)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(jnp.asarray(
            _reference_attention_jnp(q, k, v, causal))))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(qd, kd, vd)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gf), rtol=5e-4, atol=5e-5,
            err_msg=f"ring d{name} mismatch",
        )


def _reference_attention_jnp(q, k, v, causal):
    b, t, h, d = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def test_specs_from_rules_paths():
    params = {
        "net": {
            "03_cpdense": {"w": np.zeros((4, 8)), "b": np.zeros((8,))},
            "04_rpdense": {"w": np.zeros((8, 4)), "b": np.zeros((4,))},
            "05_dense": {"w": np.zeros((4, 4)), "b": np.zeros((4,))},
            "06__block": {"attn": {"q": {"w": np.zeros((4, 4))},
                                   "o": {"w": np.zeros((4, 4))}}},
        }
    }
    specs = specs_from_rules(params, TP_RULES)
    assert specs["net"]["03_cpdense"]["w"] == P(None, MODEL_AXIS)
    assert specs["net"]["03_cpdense"]["b"] == P(MODEL_AXIS)
    assert specs["net"]["04_rpdense"]["w"] == P(MODEL_AXIS, None)
    assert specs["net"]["04_rpdense"]["b"] == P()
    assert specs["net"]["05_dense"]["w"] == P()
    assert specs["net"]["06__block"]["attn"]["q"]["w"] == P(None, MODEL_AXIS)
    assert specs["net"]["06__block"]["attn"]["o"]["w"] == P(MODEL_AXIS, None)


TINY_LM = {"batch_size": 4, "n_train": 64, "n_val": 32, "seq_len": 16,
           "vocab": 32, "dim": 32, "heads": 4, "n_layers": 2,
           "dropout": 0.1, "n_epochs": 1, "precision": "fp32"}


def _run_steps(mesh, cfg, steps=1):
    """-> (trainer, per-step costs).  Multi-step so the gradient/update path
    is verified, not just the forward (the step-1 cost is computed from
    pre-update params and cannot see a wrong gradient)."""
    model = TransformerLM(cfg)
    t = BSPTrainer(model, mesh=mesh)
    t.compile_iter_fns()
    t.init_state()
    batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
    costs = [
        float(t.train_iter(batches[i % len(batches)], lr=1e-2)["cost"])
        for i in range(steps)
    ]
    return t, costs


def _replicated_leaf(trainer):
    """A replicated (non-TP) param leaf: the final LayerNorm scale."""
    keys = sorted(k for k in trainer.params if "layernorm" in k)
    return np.asarray(trainer.params[keys[-1]]["scale"])


def test_transformer_dp_only():
    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    _, costs = _run_steps(mesh, dict(TINY_LM))
    assert np.isfinite(costs[0])


def test_transformer_tp_matches_single_device():
    """tp=4 must track the unsharded model through 3 train steps.

    Regression test for the replicated-grad bug: without the Megatron f/g
    operators (parallel/tensor.py) the grads of replicated params (embedding,
    LayerNorms) are per-shard partials and step 2+ diverges; without the
    spec-aware global-norm clip (ops/opt.py global_sq_norm) the clip scale is
    wrong under TP and drifts from the single-device trajectory.
    """
    cfg = {**TINY_LM, "dropout": 0.0}
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, dict(cfg), steps=3)

    mesh_tp = make_mesh(n_data=1, n_model=4, devices=jax.devices()[:4])
    t2, c2 = _run_steps(mesh_tp, dict(cfg), steps=3)
    np.testing.assert_allclose(c1, c2, rtol=1e-4)
    # post-update replicated params must match the single-device run (and
    # implicitly be consistent across shards: a divergent leaf could not
    # match a single trajectory)
    np.testing.assert_allclose(
        _replicated_leaf(t1), _replicated_leaf(t2), rtol=1e-4, atol=1e-6
    )
    # a TP'd weight is actually SHARDED (device_set size alone is vacuous)
    qw = t2.params["02__block"]["attn"]["q"]["w"]
    assert not qw.sharding.is_fully_replicated


def test_transformer_sp_matches_single_device():
    """seq-parallel (sp=4) must track the unsharded model through 3 steps."""
    cfg = {**TINY_LM, "dropout": 0.0}
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, {**cfg, "seq_parallel": False}, steps=3)

    mesh_sp = make_mesh(n_data=1, n_seq=4, devices=jax.devices()[:4])
    t2, c2 = _run_steps(mesh_sp, {**cfg, "seq_parallel": True}, steps=3)
    np.testing.assert_allclose(c1, c2, rtol=1e-4)
    np.testing.assert_allclose(
        _replicated_leaf(t1), _replicated_leaf(t2), rtol=1e-4, atol=1e-6
    )


def test_transformer_dp_tp_sp_combined():
    """The full 2x2x2 mesh: dp x tp x sp in one compiled step, loss drops."""
    mesh = make_mesh(n_data=2, n_model=2, n_seq=2)
    cfg = {**TINY_LM, "seq_parallel": True, "n_epochs": 2}
    model = TransformerLM(cfg)
    t = BSPTrainer(model, mesh=mesh)
    rec = t.run()
    costs = rec.train_history["cost"]
    assert all(np.isfinite(c) for c in costs)
    ppl = rec.val_history.get("perplexity")
    assert ppl and np.isfinite(ppl[-1])


def test_transformer_learns(mesh8):
    """dp=8: the LM should beat uniform perplexity quickly."""
    cfg = {**TINY_LM, "batch_size": 2, "n_train": 256, "n_epochs": 3,
           "dropout": 0.0, "lr": 3e-2}
    model = TransformerLM(cfg)
    t = BSPTrainer(model, mesh=mesh8)
    rec = t.run()
    ppl = rec.val_history["perplexity"]
    assert ppl[-1] < 32, f"should beat uniform(32): {ppl}"


def test_fused_loss_matches_naive_end_to_end():
    """fused_loss=True must reproduce the naive [B,T,V] path through two
    full train steps (loss + the updated-params trajectory)."""
    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    cfg = {**TINY_LM, "dropout": 0.0}
    t_naive, c_naive = _run_steps(mesh, {**cfg, "fused_loss": False}, steps=2)
    t_fused, c_fused = _run_steps(mesh, {**cfg, "fused_loss": True}, steps=2)
    np.testing.assert_allclose(c_naive, c_fused, rtol=1e-5)
    np.testing.assert_allclose(
        _replicated_leaf(t_naive), _replicated_leaf(t_fused),
        rtol=1e-5, atol=1e-7,
    )


def test_fused_loss_auto_enables_at_large_vocab():
    """vocab >= 8192 flips the fused path on by default and trains (the
    synthetic data switches to the procedural-sparse bigram generator)."""
    cfg = {**TINY_LM, "vocab": 8192, "batch_size": 2, "n_train": 8,
           "n_val": 4, "dim": 16, "heads": 2, "n_layers": 1}
    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    model = TransformerLM(cfg)
    assert model.fused_loss_enabled()
    t = BSPTrainer(model, mesh=mesh)
    t.compile_iter_fns()
    t.init_state()
    b = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    assert int(b["x"].max()) < 8192 and int(b["x"].min()) >= 0
    m = t.train_iter(b, lr=1e-2)
    assert np.isfinite(float(m["cost"]))


def test_fused_vocab_parallel_head_tp4_matches_single_device():
    """fused_loss + tp4: the head shards its vocab over `model` (Megatron
    parallel CE) and must track the single-device fused run through 3
    steps; the head weight must actually be distributed."""
    cfg = {**TINY_LM, "dropout": 0.0, "fused_loss": True}
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, dict(cfg), steps=3)

    mesh_tp = make_mesh(n_data=1, n_model=4, devices=jax.devices()[:4])
    t2, c2 = _run_steps(mesh_tp, dict(cfg), steps=3)
    np.testing.assert_allclose(c1, c2, rtol=1e-4)
    np.testing.assert_allclose(
        _replicated_leaf(t1), _replicated_leaf(t2), rtol=1e-4, atol=1e-6
    )
    hw = t2.params["head"]["w"]
    assert not hw.sharding.is_fully_replicated  # vocab actually sharded
    # and the head's post-update values still equal the unsharded run's
    np.testing.assert_allclose(
        np.asarray(t1.params["head"]["w"]), np.asarray(hw),
        rtol=1e-4, atol=1e-6,
    )


def test_fused_loss_seq_parallel_matches_single_device():
    """fused_loss + sp4: sequence-sharded tokens feed the chunked loss as
    local means with the gradient mean-reduced over `seq` — must track the
    single-device fused run through 3 steps."""
    cfg = {**TINY_LM, "dropout": 0.0, "fused_loss": True}
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    t1, c1 = _run_steps(mesh1, {**cfg, "seq_parallel": False}, steps=3)

    mesh_sp = make_mesh(n_data=1, n_seq=4, devices=jax.devices()[:4])
    t2, c2 = _run_steps(mesh_sp, {**cfg, "seq_parallel": True}, steps=3)
    np.testing.assert_allclose(c1, c2, rtol=1e-4)
    np.testing.assert_allclose(
        _replicated_leaf(t1), _replicated_leaf(t2), rtol=1e-4, atol=1e-6
    )
