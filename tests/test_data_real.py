"""Real-data branches (VERDICT #7): the paths synthetic-only CI never hit.

Covers CIFAR-10 ``.npz`` loading, PTB text-file loading, and the
``.hkl``-tree converter (with a stubbed ``hickle`` module — the real one is
not in this image), including the CHW→HWC transpose where a silent layout
bug would live.
"""

import sys
import types

import numpy as np
import pytest


def test_cifar10_npz_branch(tmp_path):
    rng = np.random.RandomState(0)
    xt = rng.randint(0, 256, (64, 32, 32, 3)).astype(np.uint8)
    yt = rng.randint(0, 10, (64, 1))  # shaped (N,1) as common dumps are
    xv = rng.randint(0, 256, (32, 32, 32, 3)).astype(np.uint8)
    yv = rng.randint(0, 10, (32, 1))
    path = tmp_path / "cifar10.npz"
    np.savez(path, x_train=xt, y_train=yt, x_test=xv, y_test=yv)

    from theanompi_tpu.models.data.cifar10 import MEAN, STD, Cifar10Data

    data = Cifar10Data({"data_path": str(path), "augment": False})
    assert not data.synthetic
    assert data.n_train == 64 and data.n_val == 32
    assert data.n_classes == 10
    # labels flattened to rank 1 int
    assert data.y_train.shape == (64,) and data.y_train.dtype == np.int32
    # normalization: x = (raw/255 - MEAN)/STD, exactly
    expect = (xt[0].astype(np.float32) / 255.0 - MEAN) / STD
    np.testing.assert_allclose(data.x_train[0], expect, rtol=1e-6)
    batch = next(iter(data.train_batches(8, epoch=0, seed=0)))
    assert batch["x"].shape == (8, 32, 32, 3)
    assert batch["y"].shape == (8,)


def test_cifar10_npz_tanh_normalize(tmp_path):
    xt = np.full((8, 32, 32, 3), 255, np.uint8)
    y = np.zeros((8,), np.int64)
    path = tmp_path / "c.npz"
    np.savez(path, x_train=xt, y_train=y, x_test=xt, y_test=y)

    from theanompi_tpu.models.data.cifar10 import Cifar10Data

    data = Cifar10Data({"data_path": str(path), "augment": False,
                        "normalize": "tanh"})
    # GAN mode maps [0,1] -> [-1,1]: 255 -> 1.0
    np.testing.assert_allclose(data.x_train, 1.0, atol=1e-6)


def test_ptb_text_branch(tmp_path):
    train_text = "the cat sat on the mat . " * 40
    val_text = "the dog sat on the unseen mat . " * 10
    (tmp_path / "ptb.train.txt").write_text(train_text)
    (tmp_path / "ptb.valid.txt").write_text(val_text)

    from theanompi_tpu.models.lstm import PTBData

    data = PTBData({"data_path": str(tmp_path), "seq_len": 6})
    assert not data.synthetic
    # vocab: 6 train words + <unk2>
    assert data.vocab == 7
    unk = data.vocab - 1
    # "dog"/"unseen" are not in train vocab -> mapped to unk in val
    assert "dog" not in data.word_to_id
    val_ids = data._val_seqs.reshape(-1)
    assert (val_ids == unk).any()
    # train ids never unk, and round-trip through the vocab mapping
    train_ids = data._train_seqs.reshape(-1)
    assert (train_ids != unk).all()
    assert train_ids.max() < data.vocab
    # sequences chopped to seq_len+1 and batched as (x, y)=(t[:-1], t[1:])
    assert data._train_seqs.shape[1] == 7
    b = next(iter(data.train_batches(4, epoch=0)))
    assert b["x"].shape == (4, 6) and b["y"].shape == (4, 6)
    # y is x shifted by one within the same chopped window
    order_row = b["x"][0]
    assert b["y"][0][0] != order_row[0] or len(set(order_row.tolist())) == 1


def test_ptb_text_trains_one_step(tmp_path):
    (tmp_path / "ptb.train.txt").write_text("a b c d e f g h " * 64)
    (tmp_path / "ptb.valid.txt").write_text("a b c d e f g h " * 16)
    import jax

    from theanompi_tpu.models.lstm import LSTM
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh

    model = LSTM({"data_path": str(tmp_path), "seq_len": 7, "batch_size": 4,
                  "hidden": 16, "embed_dim": 16, "n_layers": 1,
                  "n_epochs": 1, "precision": "fp32", "dropout": 0.0})
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=0.5)
    assert np.isfinite(float(m["cost"]))


def test_convert_hkl_tree_transposes_chw(tmp_path, monkeypatch):
    """Stubbed hickle: the converter must emit uint8 HWC .npy shards."""
    src = tmp_path / "hkl"
    dst = tmp_path / "npy"
    src.mkdir()
    rng = np.random.RandomState(3)
    # reference-era layout: (N, C, H, W) float batches in .hkl files
    shards = {
        "0000.hkl": rng.randint(0, 256, (4, 3, 8, 8)).astype(np.float32),
        "0001.hkl": rng.randint(0, 256, (4, 3, 8, 8)).astype(np.float32),
    }
    for name, arr in shards.items():
        (src / name).write_bytes(b"hkl-stub")

    stub = types.ModuleType("hickle")
    stub.load = lambda p: shards[p.split("/")[-1]]
    monkeypatch.setitem(sys.modules, "hickle", stub)

    from theanompi_tpu.models.data.imagenet import convert_hkl_tree

    convert_hkl_tree(str(src), str(dst))
    out0 = np.load(dst / "x_0000.npy")
    assert out0.shape == (4, 8, 8, 3), "CHW -> HWC transpose missing"
    assert out0.dtype == np.uint8
    np.testing.assert_array_equal(
        out0, shards["0000.hkl"].transpose(0, 2, 3, 1).astype(np.uint8)
    )
    assert sorted(p.name for p in dst.iterdir()) == ["x_0000.npy", "x_0001.npy"]


def test_convert_hkl_tree_without_hickle_raises(tmp_path, monkeypatch):
    monkeypatch.setitem(sys.modules, "hickle", None)  # force ImportError
    from theanompi_tpu.models.data.imagenet import convert_hkl_tree

    with pytest.raises(ImportError, match="hickle"):
        convert_hkl_tree(str(tmp_path), str(tmp_path / "out"))


def test_shm_loader_workers_deterministic_and_conserving(tmp_path):
    """loader_workers > 0: the shared-memory ring loader must produce a
    deterministic stream for a fixed seed (regardless of worker timing),
    conserve the label multiset, and emit identical shapes/dtypes to the
    inline path (VERDICT r2 #7's multiprocess loader)."""
    import numpy as np

    from theanompi_tpu.models.data.imagenet import ImageNetData, write_shards

    xs = np.random.RandomState(0).randint(
        0, 255, (256, 40, 40, 3)).astype(np.uint8)
    ys = np.random.RandomState(1).randint(0, 10, 256).astype(np.int32)
    write_shards(str(tmp_path / "train"), xs, ys, 64)
    write_shards(str(tmp_path / "val"), xs[:64], ys[:64], 64)

    data = ImageNetData({"data_path": str(tmp_path), "image_size": 32,
                         "loader_workers": 2})
    run1 = [{k: v.copy() for k, v in b.items()}
            for b in data.train_batches(64, epoch=0, seed=5)]
    run2 = [{k: v.copy() for k, v in b.items()}
            for b in data.train_batches(64, epoch=0, seed=5)]
    assert len(run1) == 4
    for a, b in zip(run1, run2):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])
    assert run1[0]["x"].shape == (64, 32, 32, 3)
    assert run1[0]["x"].dtype == np.uint8
    got = sorted(np.concatenate([b["y"] for b in run1]).tolist())
    assert got == sorted(ys.tolist())

    # inline path on the same data still works and yields the same labels
    inline = ImageNetData({"data_path": str(tmp_path), "image_size": 32})
    got0 = sorted(
        np.concatenate([b["y"] for b in inline.train_batches(64, 0, seed=5)]
                       ).tolist())
    assert got0 == sorted(ys.tolist())
    data.cleanup()  # closes the persistent worker ring


def test_shm_loader_closes_cleanly_on_early_stop(tmp_path):
    """Closing the batch generator mid-epoch (what the prefetcher does on
    early stop) must terminate the worker ring without leaking."""
    import numpy as np

    from theanompi_tpu.models.data.imagenet import ImageNetData, write_shards

    xs = np.zeros((256, 40, 40, 3), np.uint8)
    ys = np.zeros(256, np.int32)
    write_shards(str(tmp_path / "train"), xs, ys, 64)
    write_shards(str(tmp_path / "val"), xs[:64], ys[:64], 64)
    data = ImageNetData({"data_path": str(tmp_path), "image_size": 32,
                         "loader_workers": 2})
    gen = data.train_batches(64, epoch=0, seed=0)
    next(gen)
    gen.close()  # must not hang
    # the pool survives the early stop and serves the next epoch cleanly
    n = sum(1 for _ in data.train_batches(64, epoch=1, seed=0))
    assert n == 4
    data.cleanup()
