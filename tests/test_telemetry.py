"""ISSUE 1 telemetry layer: sinks, spans, Chrome traces, byte accounting.

Acceptance criteria under test (CPU mesh):

- a 5-step BSP run with telemetry enabled emits JSONL that validates
  against the documented schema (``telemetry/sink.py``), plus a Chrome
  trace-event file loadable as JSON whose nested spans sum consistently
  with the Recorder splits;
- per-exchange wire-byte counts halve when the strategy switches
  ``psum`` -> ``psum_bf16``;
- with telemetry disabled, ``run()`` makes zero telemetry calls.
"""

import json
import os
import time

import jax
import numpy as np
import pytest

from theanompi_tpu import BSP
from theanompi_tpu.models.wide_resnet import WideResNet
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.exchanger import (
    Exchanger,
    collective_wire_bytes,
    wire_itemsize,
)
from theanompi_tpu.parallel.mesh import make_mesh
from theanompi_tpu.telemetry import Telemetry, read_events, sink_files
from theanompi_tpu.telemetry.sink import EventSink
from theanompi_tpu.utils.recorder import Recorder

TINY = {
    "depth": 10, "widen": 1, "batch_size": 2, "image_size": 8,
    "n_train": 80, "n_val": 16, "n_epochs": 1, "precision": "fp32",
    "augment": False, "verbose": False,
}

# the schema contract from telemetry/sink.py — every event must carry these
REQUIRED_KEYS = {"ts", "kind", "name", "rank"}
KIND_KEYS = {"span": {"dur", "tid"}, "counter": {"value", "total"},
             "gauge": {"value"}}
KINDS = {"meta", "span", "instant", "counter", "gauge", "metrics"}


def _validate(ev: dict) -> None:
    missing = REQUIRED_KEYS - ev.keys()
    assert not missing, f"event missing {missing}: {ev}"
    assert ev["kind"] in KINDS, ev
    assert isinstance(ev["ts"], (int, float)) and isinstance(ev["rank"], int)
    extra = KIND_KEYS.get(ev["kind"], set()) - ev.keys()
    assert not extra, f"{ev['kind']} event missing {extra}: {ev}"


def _run_bsp(telemetry_dir: str, strategy: str, n_train: int = 80):
    cfg = dict(TINY, n_train=n_train)
    rule = BSP(config={"verbose": False, "telemetry_dir": telemetry_dir,
                       "print_freq": 2, "exch_strategy": strategy})
    rule.init(devices=8, model_config=cfg)
    return rule.wait()


@pytest.fixture(scope="module")
def bsp_run(tmp_path_factory):
    """One 5-step BSP/psum training run with telemetry on, shared below."""
    d = str(tmp_path_factory.mktemp("tel_psum"))
    rec = _run_bsp(d, "psum")
    events = []
    for p in sink_files(d):
        events.extend(read_events(p))
    return d, rec, events


def test_jsonl_validates_schema(bsp_run):
    d, _, events = bsp_run
    assert events, "no events emitted"
    for ev in events:
        _validate(ev)
    names = {e["name"] for e in events}
    # the spans the tentpole names: recorder splits, prefetch dequeues,
    # exchange accounting, step spans, validation
    for required in ("train.step", "recorder.calc", "recorder.wait",
                     "prefetch.dequeue", "validate", "exchange.accounting",
                     "metrics", "session"):
        assert required in names, f"missing {required} in {sorted(names)}"
    steps = [e for e in events if e["name"] == "train.step"]
    assert len(steps) == 5  # n_train=80 / (batch 2 * 8 workers)
    assert [e["step"] for e in steps] == list(range(5))


def test_chrome_trace_loads_and_nests(bsp_run):
    d, rec, events = bsp_run
    trace = json.load(open(os.path.join(d, "trace.json")))
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert xs, "no complete spans in the chrome trace"
    steps = [e for e in xs if e["name"] == "train.step"]
    calcs = [e for e in xs if e["name"] == "recorder.calc"]
    assert len(steps) == 5 and len(calcs) == 5
    # nesting: every calc span sits inside exactly one step span (eps for
    # float us rounding)
    for c in calcs:
        inside = [s for s in steps
                  if s["ts"] - 1 <= c["ts"]
                  and c["ts"] + c["dur"] <= s["ts"] + s["dur"] + 1]
        assert len(inside) == 1, (c, steps)
    # span durations sum consistently with the Recorder's splits: they are
    # the same measurements by construction
    for seg in ("calc", "wait"):
        span_sum = sum(e["dur"] for e in events
                       if e["kind"] == "span" and e["name"] == f"recorder.{seg}")
        assert span_sum == pytest.approx(sum(rec.time_history[seg]), rel=1e-9)


def test_summary_has_step_stats_and_counters(bsp_run):
    d, _, _ = bsp_run
    summary = json.load(open(os.path.join(d, "summary.json")))
    assert summary["n_ranks"] == 1
    row = summary["per_rank"]["0"]
    assert row["steps"] == 5
    assert row["step_ms"]["p50"] > 0
    assert row["segment_totals_s"]["calc"] > 0
    assert row["counters"]["exchange.wire_bytes"] > 0


def test_wire_bytes_halve_psum_to_bf16(bsp_run, tmp_path):
    """Acceptance: emitted per-exchange byte counts halve under bf16."""
    d, _, events = bsp_run
    acc = [e for e in events if e["name"] == "exchange.accounting"]
    assert len(acc) == 1 and acc[0]["strategy"] == "psum"
    per_exchange = acc[0]["bytes_per_exchange"]
    assert per_exchange > 0 and acc[0]["n_workers"] == 8
    # per-step counters accumulate one exchange per step
    counters = [e for e in events if e["kind"] == "counter"
                and e["name"] == "exchange.wire_bytes"]
    assert len(counters) == 5
    assert counters[-1]["total"] == 5 * per_exchange

    d2 = str(tmp_path / "tel_bf16")
    _run_bsp(d2, "psum_bf16", n_train=32)  # 2 steps: accounting is static
    ev2 = [e for p in sink_files(d2) for e in read_events(p)]
    acc2 = [e for e in ev2 if e["name"] == "exchange.accounting"]
    assert len(acc2) == 1 and acc2[0]["strategy"] == "psum_bf16"
    assert acc2[0]["bytes_per_exchange"] * 2 == per_exchange


def test_exchanger_wire_bytes_model():
    """Static accounting unit: wire dtype per strategy, ring factor, and
    the non-float skip matching what exchange() actually reduces."""
    tree = {"w": np.zeros((64, 32), np.float32),
            "b": np.zeros((32,), np.float32),
            "step": np.zeros((), np.int32)}
    n_float = 64 * 32 + 32
    n = 8
    ring = lambda b: 2 * (n - 1) * b // n  # noqa: E731
    assert Exchanger("psum").wire_bytes(tree, n) == ring(4 * n_float)
    assert Exchanger("psum_bf16").wire_bytes(tree, n) == ring(2 * n_float)
    assert Exchanger("ring").wire_bytes(tree, n) == ring(4 * n_float)
    assert Exchanger("ring_bf16").wire_bytes(tree, n) == ring(2 * n_float)
    assert Exchanger("none").wire_bytes(tree, n) == 0
    # single worker: no wire traffic at all
    assert Exchanger("psum").wire_bytes(tree, 1) == 0
    # bf16 never inflates an already-narrow dtype
    assert wire_itemsize("psum_bf16", np.float16) == 2
    assert collective_wire_bytes(100, 1) == 0
    # exact halving must survive element counts the ring factor floors
    odd = {"w": np.zeros((7, 3), np.float32)}
    assert (Exchanger("psum_bf16").wire_bytes(odd, n) * 2
            == Exchanger("psum").wire_bytes(odd, n))


def test_sink_rotation_bounded(tmp_path):
    sink = EventSink(str(tmp_path), rank=3, max_bytes=512, keep=2)
    for i in range(200):
        sink.emit({"ts": float(i), "kind": "instant", "name": "x", "rank": 3,
                   "i": i})
    sink.close()
    files = sink_files(str(tmp_path), rank=3)
    # live file + at most `keep` rotated generations, all parseable
    assert 1 <= len(files) <= 3
    assert all("rank00003" in f for f in files)
    events = [e for f in files for e in read_events(f)]
    assert events and all(e["name"] == "x" for e in events)
    # rotation keeps the NEWEST events (the live file ends at i=199)
    assert events[-1]["i"] == 199


def test_spans_nest_around_fake_train_loop(tmp_path):
    """Satellite: telemetry spans nest correctly around a fake train loop."""
    tel = Telemetry(str(tmp_path), rank=0)
    for step in range(3):
        with tel.span("train.step", step=step):
            with tel.span("recorder.wait"):
                pass
            with tel.span("recorder.calc"):
                time.sleep(0.002)
    tel.close()
    trace = json.load(open(tel.export_chrome_trace()))
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    steps = [e for e in xs if e["name"] == "train.step"]
    inner = [e for e in xs if e["name"].startswith("recorder.")]
    assert len(steps) == 3 and len(inner) == 6
    for child in inner:
        parents = [s for s in steps
                   if s["ts"] <= child["ts"] + 1e-3
                   and child["ts"] + child["dur"] <= s["ts"] + s["dur"] + 1e-3]
        assert len(parents) == 1, (child, steps)
    # spans carry their tags into trace args
    assert sorted(s["args"]["step"] for s in steps) == [0, 1, 2]


def test_multirank_aggregation_skew_and_straggler(tmp_path):
    """The multihost path: per-rank sink files merged by rank 0 into a
    cross-rank step-skew / straggler summary (durations only — perf_counter
    epochs differ across hosts, so simultaneity is never compared)."""
    from theanompi_tpu.telemetry import aggregate

    # rank 1 is a 2x straggler on every step
    for rank, scale in ((0, 1.0), (1, 2.0)):
        sink = EventSink(str(tmp_path), rank=rank)
        for step in range(4):
            sink.emit({"ts": 100.0 * rank + step, "kind": "span",
                       "name": "train.step", "rank": rank, "tid": 1,
                       "dur": 0.010 * scale, "step": step})
        sink.close()
    summary = aggregate.finalize(str(tmp_path))
    assert summary["n_ranks"] == 2
    assert summary["step_skew_ms"]["steps_compared"] == 4
    assert summary["step_skew_ms"]["mean"] == pytest.approx(10.0, rel=1e-6)
    assert summary["straggler"]["rank"] == 1
    assert summary["straggler"]["vs_fleet_mean"] == pytest.approx(4 / 3,
                                                                  abs=1e-3)
    # finalize also wrote the merged two-rank chrome trace
    trace = json.load(open(tmp_path / "trace.json"))
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 1}


def test_span_records_exception_and_still_closes(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0)
    with pytest.raises(ValueError):
        with tel.span("doomed"):
            raise ValueError("boom")
    # a manual fence-aware end() inside a with block must not double-emit
    # when __exit__ runs
    with tel.span("fenced") as s:
        s.end(fence=None)
    tel.close()
    evs = [e for p in sink_files(str(tmp_path)) for e in read_events(p)]
    doomed = [e for e in evs if e["name"] == "doomed"]
    assert len(doomed) == 1 and doomed[0]["error"] == "ValueError"
    assert len([e for e in evs if e["name"] == "fenced"]) == 1


def test_disabled_run_makes_zero_telemetry_calls(monkeypatch):
    """Acceptance: telemetry off (the default) -> not a single telemetry
    call on the hot path.  Any construction or emission raises."""

    def bomb(*a, **k):
        raise AssertionError("telemetry call on a disabled run")

    monkeypatch.setattr(EventSink, "__init__", bomb)
    monkeypatch.setattr(EventSink, "emit", bomb)
    monkeypatch.setattr(Telemetry, "__init__", bomb)
    model = WideResNet(dict(TINY, n_train=32))
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]),
                   recorder=Recorder(verbose=False))
    assert t.telemetry is None
    rec = t.run()
    assert len(rec.time_history["calc"]) == 16  # 32 / batch 2, ran clean


def test_recorder_end_without_start_raises():
    """Satellite: a clear error naming the segment, not a bare KeyError."""
    r = Recorder(verbose=False)
    with pytest.raises(RuntimeError, match=r"end\('comm'\).*never started"):
        r.end("comm")
    # an open unrelated segment is named in the message to aid debugging
    r.start("calc")
    with pytest.raises(RuntimeError, match="calc"):
        r.end("wait")
    r.cancel("calc")


def test_recorder_save_load_roundtrip(tmp_path):
    """Satellite: time/train/val histories + summary.json survive a
    save/load cycle bit-exact."""
    r = Recorder(verbose=False, print_freq=2, save_dir=str(tmp_path))
    for i in range(1, 5):
        r.start("wait"); r.end("wait")  # noqa: E702
        r.start("calc"); r.end("calc")  # noqa: E702
        r.end_iteration()
        r.train_metrics(cost=float(i), error=float(i) / 10)
        r.print_train_info(i)
    r.val_metrics(0, cost=0.5, error=0.25)
    r.save()
    summary = json.load(open(tmp_path / "summary.json"))
    assert summary["iters"] == 4
    assert summary["last_val"] == {"epoch": 0, "cost": 0.5, "error": 0.25}

    r2 = Recorder(verbose=False, save_dir=str(tmp_path))
    r2.load()
    for name in ("time_history", "train_history", "val_history"):
        a, b = getattr(r, name), getattr(r2, name)
        assert set(a) == set(b), name
        for k in a:
            assert list(a[k]) == list(b[k]), (name, k)


def test_profiler_stopped_when_run_raises(monkeypatch, tmp_path):
    """Satellite: run() must stop an open profiler window on ANY exit —
    here an exception thrown while the window is still open."""
    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.__setitem__("start", calls["start"] + 1))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.__setitem__("stop", calls["stop"] + 1))
    model = WideResNet(dict(TINY, n_train=32))
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]),
                   recorder=Recorder(verbose=False),
                   profile_dir=str(tmp_path), profile_window=(0, 10**9))

    def exploding_validate(epoch):
        raise RuntimeError("mid-run failure with the window open")

    monkeypatch.setattr(t, "validate", exploding_validate)
    with pytest.raises(RuntimeError, match="window open"):
        t.run()
    assert calls == {"start": 1, "stop": 1}
    assert not t._profiling
