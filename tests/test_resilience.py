"""Resilience layer (ISSUE 4): fault plan, supervisor, watchdog, sentinel,
prefetch stall, launcher exit-code contract, dist-init retry.

Subprocess-based supervisor units use plain ``python -c`` children (no jax
import) so they run in milliseconds; the e2e supervised-training paths live
in ``tests/test_resilience_e2e.py``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from theanompi_tpu.resilience import (
    EXIT_CONFIG,
    EXIT_CRASH,
    EXIT_HANG,
    EXIT_PREEMPTED,
    FaultPlan,
    FaultPlanError,
    NonFiniteLossError,
    Sentinel,
    Supervisor,
    Watchdog,
    classify_exit,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = ["--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
        "--set", "image_size=8", "--set", "n_train=32", "--set", "n_val=16",
        "--set", "precision='fp32'"]


# -- faults.py ---------------------------------------------------------------

def test_fault_plan_grammar():
    p = FaultPlan.parse("step:kill@12@1; prefetch:stall@3, checkpoint:fail@0")
    assert [(s.site, s.action, s.index, s.attempt) for s in p.specs] == [
        ("step", "kill", 12, 1), ("prefetch", "stall", 3, None),
        ("checkpoint", "fail", 0, None)]


@pytest.mark.parametrize("bad", [
    "nosite:raise@1",        # unknown site
    "step:stall@1",          # action invalid for site
    "step:raise",            # missing index
    "step:raise@x",          # non-integer index
    "step:raise@1@2@3",      # too many @
    "",                      # empty
])
def test_fault_plan_rejects(bad):
    with pytest.raises(FaultPlanError):
        FaultPlan.parse(bad)


def test_fault_plan_one_shot_and_attempt_gate(monkeypatch):
    p = FaultPlan.parse("step:raise@5@2")
    monkeypatch.setenv("THEANOMPI_ATTEMPT", "1")
    assert p.fire("step", 5) is None          # wrong attempt
    monkeypatch.setenv("THEANOMPI_ATTEMPT", "2")
    assert p.fire("step", 4) is None          # wrong index
    assert p.fire("prefetch", 5) is None      # wrong site
    assert p.fire("step", 5) == "raise"
    assert p.fire("step", 5) is None          # one-shot: never twice


def test_fault_plan_env_fallback(monkeypatch):
    monkeypatch.delenv("THEANOMPI_FAULT_PLAN", raising=False)
    assert FaultPlan.from_spec(None) is None
    monkeypatch.setenv("THEANOMPI_FAULT_PLAN", "step:nan@3")
    plan = FaultPlan.from_spec(None)
    assert plan.fire("step", 3) == "nan"
    # explicit spec beats env
    assert FaultPlan.from_spec("step:kill@1").specs[0].action == "kill"


# -- exit classification -----------------------------------------------------

def test_classify_exit_table():
    import signal

    assert classify_exit(0) == "clean"
    assert classify_exit(EXIT_PREEMPTED) == "preemption"
    assert classify_exit(-signal.SIGTERM) == "preemption"
    assert classify_exit(EXIT_HANG) == "hang"
    assert classify_exit(EXIT_CONFIG) == "config"
    assert classify_exit(2) == "config"      # argparse usage error
    assert classify_exit(EXIT_CRASH) == "crash"
    assert classify_exit(-9) == "crash"      # SIGKILL
    assert classify_exit(1) == "crash"


# -- supervisor.py (python -c children: no jax, milliseconds) ---------------

def _script_child(tmp_path, body: str) -> list:
    """A child command running ``body`` with a state dir for cross-attempt
    counters (the supervisor restarts fresh processes)."""
    return [sys.executable, "-c", body.replace("STATE", repr(str(tmp_path)))]


def test_supervisor_restarts_crash_then_clean(tmp_path):
    body = """
import os, sys
marker = os.path.join(STATE, "crashed_once")
if not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(70)
sys.exit(0)
"""
    sleeps = []
    sup = Supervisor(_script_child(tmp_path, body), max_restarts=3,
                     backoff_base=0.01, jitter=0.0,
                     resilience_path=str(tmp_path / "resilience.json"),
                     sleep=sleeps.append)
    assert sup.run() == 0
    art = json.load(open(tmp_path / "resilience.json"))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]
    assert art["restarts"] == 1 and art["final_exit"] == 0
    assert art["attempts"][0]["exit_code"] == 70
    assert art["attempts"][0]["time_lost_s"] >= 0
    assert len(sleeps) == 1  # one backoff before the restart


def test_supervisor_resume_args_added_from_second_attempt(tmp_path):
    body = """
import os, sys
marker = os.path.join(STATE, "n")
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
if "--resume" in sys.argv:
    sys.exit(0 if n == 1 else 71)   # resume must arrive exactly at attempt 2
sys.exit(70 if n == 0 else 71)
"""
    sup = Supervisor(_script_child(tmp_path, body), max_restarts=2,
                     backoff_base=0.0, jitter=0.0,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)
    assert sup.run() == 0


def test_supervisor_config_error_is_fatal(tmp_path):
    sup = Supervisor([sys.executable, "-c", f"import sys; sys.exit({EXIT_CONFIG})"],
                     max_restarts=5, backoff_base=0.0, jitter=0.0,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)
    assert sup.run() == EXIT_CONFIG
    art = json.load(open(tmp_path / "r.json"))
    assert [a["cause"] for a in art["attempts"]] == ["config"]
    assert art["restarts"] == 0


def test_supervisor_budget_exhausted(tmp_path):
    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(70)"],
                     max_restarts=2, backoff_base=0.0, jitter=0.0,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)
    assert sup.run() == 70
    art = json.load(open(tmp_path / "r.json"))
    assert len(art["attempts"]) == 3  # initial + 2 restarts
    assert art["restarts"] == 3


def test_supervisor_preemption_does_not_burn_budget(tmp_path):
    body = """
import os, sys
marker = os.path.join(STATE, "n")
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
sys.exit(75 if n < 2 else 0)
"""
    sup = Supervisor(_script_child(tmp_path, body), max_restarts=0,
                     backoff_base=0.0, jitter=0.0,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)
    assert sup.run() == 0  # two preemptions survived with a ZERO restart budget
    art = json.load(open(tmp_path / "r.json"))
    assert [a["cause"] for a in art["attempts"]] == [
        "preemption", "preemption", "clean"]
    assert art["restarts"] == 0 and art["preemptions"] == 2
    assert art["time_lost_s"] == 0  # preemptions are resumable, not lost


def test_supervisor_config_on_restart_is_retried(tmp_path):
    """A config exit on attempt 1 is fatal (wrong flags stay wrong); the
    SAME exit on a restart is suspect — attempt 1 got past init, so it is
    more likely environmental fallout of the previous death (a lazily
    released accelerator lock) and must burn budget, not end the run."""
    body = """
import os, sys
marker = os.path.join(STATE, "n")
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
sys.exit([70, 78, 0][n])
"""
    sup = Supervisor(_script_child(tmp_path, body), max_restarts=3,
                     backoff_base=0.0, jitter=0.0,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)
    assert sup.run() == 0
    art = json.load(open(tmp_path / "r.json"))
    assert [a["cause"] for a in art["attempts"]] == [
        "crash", "crash(config-on-restart)", "clean"]
    assert art["restarts"] == 2


def test_supervisor_clamps_sub_heartbeat_hang_timeout(tmp_path):
    sup = Supervisor(["true"], hang_timeout_s=0.5,
                     resilience_path=str(tmp_path / "r.json"))
    assert sup.hang_timeout_s == 3.0  # below the heartbeat write interval
    sup = Supervisor(["true"], hang_timeout_s=600,
                     resilience_path=str(tmp_path / "r.json"))
    assert sup.hang_timeout_s == 600


def test_resume_compile_cache_env_parsing(monkeypatch):
    from theanompi_tpu import launcher

    args = launcher.build_parser().parse_args(["--resume"])
    for off in ("0", "false", "False", "NO", " off "):
        monkeypatch.setenv("THEANOMPI_RESUME_COMPILE_CACHE", off)
        assert launcher._compile_cache_usable(args) is False, off
    monkeypatch.setenv("THEANOMPI_RESUME_COMPILE_CACHE", "1")
    assert launcher._compile_cache_usable(args) is True
    args = launcher.build_parser().parse_args([])
    monkeypatch.delenv("THEANOMPI_RESUME_COMPILE_CACHE")
    assert launcher._compile_cache_usable(args) is True  # not resuming


def test_supervisor_backoff_is_exponential_and_jittered(tmp_path):
    sleeps = []
    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(1)"],
                     max_restarts=3, backoff_base=1.0, backoff_cap=60.0,
                     jitter=0.5, seed=7,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=sleeps.append)
    assert sup.run() == 1
    assert len(sleeps) == 3
    for i, s in enumerate(sleeps):
        base = 2.0 ** i
        assert base <= s <= base * 1.5, (i, s)


def test_supervisor_forwards_sigterm_and_stops(tmp_path):
    """A preempted VM TERMs the supervisor too: it must forward the signal
    to the child, let it take its resumable exit, and NOT restart."""
    import signal as _signal
    import threading

    body = ("import signal, sys, time;"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75));"
            "time.sleep(60)")
    sup = Supervisor([sys.executable, "-c", body], max_restarts=3,
                     backoff_base=0.0, jitter=0.0, poll_s=0.05,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)
    killer = threading.Timer(
        1.0, lambda: os.kill(os.getpid(), _signal.SIGTERM))
    killer.start()
    try:
        rc = sup.run()
    finally:
        killer.cancel()
    assert rc == EXIT_PREEMPTED
    art = json.load(open(tmp_path / "r.json"))
    assert [a["cause"] for a in art["attempts"]] == ["preemption"]
    assert art["restarts"] == 0  # terminated supervisor never restarts


def test_supervisor_terminated_during_backoff_does_not_respawn(tmp_path):
    """SIGTERM landing BETWEEN attempts (mid-backoff, no child running)
    must end supervision — never spawn a fresh child into a dying VM."""
    import signal as _signal

    def term_during_backoff(delay):
        os.kill(os.getpid(), _signal.SIGTERM)  # handler runs on return

    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(70)"],
                     max_restarts=3, backoff_base=0.01, jitter=0.0,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=term_during_backoff)
    assert sup.run() == EXIT_PREEMPTED
    art = json.load(open(tmp_path / "r.json"))
    assert len(art["attempts"]) == 1  # the crash; no post-TERM respawn


def test_dist_init_address_in_use_is_not_double_init(monkeypatch):
    """grpc's 'Address already in use' (stale coordinator port) contains
    'already' but is a REAL failure: it must retry and then raise, not be
    mistaken for harmless double-init."""
    import jax

    from theanompi_tpu import launcher

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")

    def port_taken():
        raise RuntimeError("UNKNOWN: Address already in use")

    monkeypatch.setattr(jax.distributed, "initialize", port_taken)
    with pytest.raises(launcher.DistributedInitError):
        launcher._maybe_init_distributed(retries=2, backoff_base=0.0,
                                         sleep=lambda s: None)


def test_supervisor_hang_backstop_kills_silent_child(tmp_path):
    hb = str(tmp_path / "heartbeat.json")
    sup = Supervisor([sys.executable, "-c", "import time; time.sleep(60)"],
                     max_restarts=0, backoff_base=0.0, jitter=0.0,
                     hang_timeout_s=0.8, poll_s=0.05, heartbeat_path=hb,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)
    t0 = time.perf_counter()
    rc = sup.run()
    assert time.perf_counter() - t0 < 30  # killed, not waited out
    assert rc == EXIT_CRASH
    art = json.load(open(tmp_path / "r.json"))
    assert art["attempts"][0]["cause"] == "hang"


# -- watchdog.py -------------------------------------------------------------

def test_watchdog_median_adaptive_trigger(tmp_path):
    clock = [0.0]
    exits = []
    wd = Watchdog(multiple=4.0, min_timeout_s=1.0, escalate="exit",
                  exit_code=EXIT_HANG, _exit=exits.append,
                  _clock=lambda: clock[0])
    # calibration: no trigger before 3 step durations exist
    for step in range(4):
        wd.beat(step)
        clock[0] += 0.5
    assert wd.stall_threshold_s() == pytest.approx(2.0)  # max(4*0.5, 1.0)
    last_beat = clock[0] - 0.5  # the loop advanced the clock past the beat
    assert not wd.check(now=last_beat + 1.9)
    assert wd.check(now=last_beat + 2.1)
    assert exits == [EXIT_HANG]
    assert wd.check(now=last_beat + 10)  # latched, no double escalation
    assert exits == [EXIT_HANG]


def test_watchdog_warn_mode_does_not_exit(capsys):
    clock = [0.0]
    wd = Watchdog(multiple=2.0, min_timeout_s=0.1, escalate="warn",
                  _exit=lambda code: pytest.fail("escalated in warn mode"),
                  _clock=lambda: clock[0])
    for step in range(4):
        wd.beat(step)
        clock[0] += 0.1
    assert wd.check(now=clock[0] + 5.0)
    assert "watchdog: no train-step progress" in capsys.readouterr().err


def test_watchdog_pause_covers_beatfree_boundaries():
    """Epoch-boundary work (eval compile, val sweep, checkpoint joins)
    produces no beats; pause() must suspend detection and resume() must
    not count the paused stretch as no-progress time."""
    clock = [0.0]
    exits = []
    wd = Watchdog(multiple=2.0, min_timeout_s=0.1, escalate="exit",
                  _exit=exits.append, _clock=lambda: clock[0])
    for step in range(4):
        wd.beat(step)
        clock[0] += 0.1
    wd.pause()
    assert not wd.check(now=clock[0] + 100)  # paused: a long boundary is fine
    clock[0] += 100
    wd.resume()
    assert not wd.check(now=clock[0] + 0.05)  # boundary time not counted
    assert wd.check(now=clock[0] + 5)  # a real post-boundary stall still fires
    assert exits == [76]


def test_launcher_rejects_abbreviated_flags():
    """allow_abbrev must stay off: '--superv' forwarded to a child would
    make the child a supervisor too (recursive spawning)."""
    from theanompi_tpu import launcher

    with pytest.raises(SystemExit):
        launcher.build_parser().parse_args(["--superv"])


def test_supervise_refuses_recursion(monkeypatch, capsys):
    from theanompi_tpu import launcher

    monkeypatch.setenv("THEANOMPI_SUPERVISED", "1")
    rc = launcher.main(["--supervise", "--rule", "BSP", "--devices", "4"])
    assert rc == EXIT_CONFIG
    assert "recursive supervision" in capsys.readouterr().err


def test_watchdog_needs_calibration():
    wd = Watchdog(multiple=2.0, min_timeout_s=0.0)
    wd.beat(0)
    wd.beat(1)
    assert wd.stall_threshold_s() is None  # < 3 durations: still calibrating
    assert not wd.check(now=1e9)


@pytest.mark.faultinject
def test_heartbeat_written_even_with_watchdog_disabled(tmp_path, monkeypatch):
    """watchdog=False turns off the stall DETECTOR, not liveness: the
    supervisor's --hang-timeout backstop reads the heartbeat file and
    would kill a healthy-but-silent child."""
    hb = str(tmp_path / "hb.json")
    monkeypatch.setenv("THEANOMPI_HEARTBEAT", hb)
    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "watchdog": False})
    rule.init(devices=1, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={"depth": 10, "widen": 1, "batch_size": 4,
                            "image_size": 8, "n_train": 16, "n_val": 8,
                            "n_epochs": 1, "precision": "fp32"})
    rule.wait()
    assert json.load(open(hb))["step"] == rule.trainer.iteration


def test_heartbeat_file_roundtrip(tmp_path):
    from theanompi_tpu.resilience import Heartbeat, heartbeat_age_s

    path = str(tmp_path / "hb.json")
    assert heartbeat_age_s(path) is None
    hb = Heartbeat(path, min_interval_s=0.0)
    hb.beat(41)
    hb.beat(42)
    meta = json.load(open(path))
    assert meta["step"] == 42 and meta["pid"] == os.getpid()
    assert heartbeat_age_s(path) < 10


# -- sentinel.py (host side, no jax needed) ---------------------------------

def test_sentinel_rejects_unknown_policy():
    with pytest.raises(ValueError, match="policy"):
        Sentinel(policy="explode")


def test_sentinel_abort_on_nonfinite():
    s = Sentinel(policy="abort")
    s.watch(3, np.float32(1.5))
    s.check()  # finite: fine
    s.watch(4, np.float32("nan"))
    with pytest.raises(NonFiniteLossError) as ei:
        s.check()
    assert ei.value.step == 4


def test_sentinel_skip_budget():
    s = Sentinel(policy="skip_batch", max_skips=2)
    for step in (1, 2):
        s.watch(step, np.float32("nan"), skip_flag=np.float32(1.0))
        s.check()  # within budget
    assert s.skips == 2
    s.watch(3, np.float32("nan"), skip_flag=np.float32(1.0))
    with pytest.raises(NonFiniteLossError, match="budget"):
        s.check()


def test_sentinel_rollback_raises_control_flow():
    from theanompi_tpu.resilience import SentinelRollback

    s = Sentinel(policy="rollback")
    s.watch(7, np.float32("inf"))
    with pytest.raises(SentinelRollback):
        s.check()
    s.watch(8, np.float32("nan"))
    s.reset_pending()
    s.check()  # a rollback dropped the dead timeline's pending losses


# -- prefetch stall + fault sites -------------------------------------------

def test_prefetch_stall_timeout_raises():
    from theanompi_tpu.models.data.prefetch import (
        Prefetcher,
        PrefetchStallError,
    )

    def source():
        yield {"x": np.zeros(2)}
        time.sleep(10)  # a hung loader
        yield {"x": np.ones(2)}

    p = Prefetcher(source(), mesh=None, depth=1, stall_timeout=0.3)
    try:
        next(p)  # first batch flows
        t0 = time.perf_counter()
        with pytest.raises(PrefetchStallError, match="stalled"):
            next(p)
        assert time.perf_counter() - t0 < 5
    finally:
        p.close()


@pytest.mark.faultinject
def test_prefetch_fault_stall_site():
    from theanompi_tpu.models.data.prefetch import (
        Prefetcher,
        PrefetchStallError,
    )

    plan = FaultPlan.parse("prefetch:stall@1")
    p = Prefetcher(iter({"x": np.zeros(2)} for _ in range(10)), mesh=None,
                   depth=1, stall_timeout=0.3, fault_plan=plan)
    try:
        next(p)
        with pytest.raises(PrefetchStallError):
            next(p)
    finally:
        p.close()


@pytest.mark.faultinject
def test_prefetch_fault_raise_site():
    from theanompi_tpu.models.data.prefetch import Prefetcher
    from theanompi_tpu.resilience import FaultInjected

    plan = FaultPlan.parse("prefetch:raise@0")
    p = Prefetcher(iter({"x": np.zeros(2)} for _ in range(3)), mesh=None,
                   depth=1, fault_plan=plan)
    with pytest.raises(FaultInjected):
        next(p)


@pytest.mark.faultinject
def test_checkpoint_fail_fault_delivered_at_join(tmp_path):
    from theanompi_tpu.utils.checkpoint import Checkpointer

    plan = FaultPlan.parse("checkpoint:fail@1")
    ck = Checkpointer(str(tmp_path), async_save=True, fault_plan=plan)
    tree = {"a": np.arange(4, dtype=np.float32)}
    ck.save(0, 1, {"params": tree}).join()  # epoch 0 unaffected
    ck.save(1, 2, {"params": tree})
    with pytest.raises(OSError, match="injected checkpoint write"):
        ck.join_pending()
    # epoch 1 was never published; latest still points at epoch 0
    assert ck.latest_epoch() == 0


# -- launcher exit-code contract + dist-init retry --------------------------

def test_launcher_config_error_bad_kv(capsys):
    from theanompi_tpu import launcher

    rc = launcher.main(["--rule", "BSP", "--devices", "4", "--set", "novalue"])
    assert rc == EXIT_CONFIG
    err = capsys.readouterr().err
    assert "tmlauncher: error: config:" in err
    assert "Traceback" not in err  # one line, not a dump


def test_launcher_config_error_bad_modelfile(capsys):
    from theanompi_tpu import launcher

    rc = launcher.main(["--rule", "BSP", "--devices", "4",
                        "--modelfile", "theanompi_tpu.models.no_such_model"])
    assert rc == EXIT_CONFIG
    assert "tmlauncher: error: init:" in capsys.readouterr().err


@pytest.mark.faultinject
def test_launcher_crash_exit_code_with_injected_fault(tmp_path, capsys):
    """A training-phase exception -> one stderr line + EXIT_CRASH (the code
    the supervisor counts against the restart budget)."""
    from theanompi_tpu import launcher

    rc = launcher.main([
        "--rule", "BSP", "--devices", "4",
        "--modelfile", "theanompi_tpu.models.wide_resnet",
        "--modelclass", "WideResNet", *TINY, "--set", "n_epochs=1",
        "--rule-set", "fault_plan=step:raise@0", "--quiet",
    ])
    assert rc == EXIT_CRASH
    err = capsys.readouterr().err
    assert "tmlauncher: error: training: FaultInjected" in err
    assert "Traceback" not in err


def test_dist_init_retries_then_succeeds(monkeypatch):
    import jax

    from theanompi_tpu import launcher

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    launcher._maybe_init_distributed(retries=4, backoff_base=1.0,
                                     sleep=sleeps.append)
    assert len(calls) == 3
    assert sleeps == [1.0, 2.0]  # exponential backoff between attempts


def test_dist_init_hard_error_on_pod(monkeypatch):
    import jax

    from theanompi_tpu import launcher

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")

    def dead():
        raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", dead)
    with pytest.raises(launcher.DistributedInitError, match="3 attempts"):
        launcher._maybe_init_distributed(retries=3, backoff_base=0.0,
                                         sleep=lambda s: None)


def test_dist_init_already_initialized_short_circuits(monkeypatch, capsys):
    import jax

    from theanompi_tpu import launcher

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")

    def already():
        # the EXACT jax 0.4.37 double-init wording (no "already" in it!)
        raise RuntimeError(
            "distributed.initialize should only be called once.")

    monkeypatch.setattr(jax.distributed, "initialize", already)
    launcher._maybe_init_distributed(retries=3, backoff_base=0.0,
                                     sleep=lambda s: pytest.fail("slept"))
    assert "skipped" in capsys.readouterr().err


def test_dist_init_half_initialized_retry_is_not_success(monkeypatch):
    """jax assigns its global client BEFORE connect(): after a failed
    attempt, the retry raises 'only be called once' about the carcass —
    that must surface as DistributedInitError, not a silent 'skipped'
    success (the single-host-downgrade this satellite eliminates)."""
    import jax

    from theanompi_tpu import launcher

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    calls = []

    def half_init():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("deadline exceeded: failed to connect")
        raise RuntimeError("distributed.initialize should only be called "
                           "once.")

    monkeypatch.setattr(jax.distributed, "initialize", half_init)
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: None)  # nothing to tear down in the fake
    with pytest.raises(launcher.DistributedInitError,
                       match="failed to connect"):
        launcher._maybe_init_distributed(retries=3, backoff_base=0.0,
                                         sleep=lambda s: None)


def test_dist_init_shutdown_resets_between_retries(monkeypatch):
    """The retry calls jax.distributed.shutdown() so attempt 2 is a real
    fresh initialize (and succeeds when the coordinator recovers)."""
    import jax

    from theanompi_tpu import launcher

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    state = {"init": 0, "shutdown": 0}

    def flaky():
        state["init"] += 1
        if state["init"] == 1:
            raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(
        jax.distributed, "shutdown",
        lambda: state.__setitem__("shutdown", state["shutdown"] + 1))
    launcher._maybe_init_distributed(retries=3, backoff_base=0.0,
                                     sleep=lambda s: None)
    assert state["init"] == 2 and state["shutdown"] >= 1


def test_supervisor_heartbeat_path_honors_rule_key(tmp_path):
    from theanompi_tpu import launcher

    base = str(tmp_path)
    args = launcher.build_parser().parse_args(
        ["--supervise", "--rule-set", "heartbeat_path=/tmp/custom_hb.json"])
    assert launcher._supervisor_heartbeat_path(args, base) == \
        "/tmp/custom_hb.json"
    args = launcher.build_parser().parse_args(["--supervise"])
    assert launcher._supervisor_heartbeat_path(args, base) == \
        os.path.join(base, "heartbeat.json")


def test_supervisor_abnormal_exit_does_not_orphan_child(tmp_path):
    """An exception escaping the supervisor loop (a ^C delivered as
    KeyboardInterrupt, a bug) must terminate the running child, not leave
    it training unsupervised."""
    pidfile = str(tmp_path / "pid")
    body = (f"import os, time; open({pidfile!r}, 'w').write(str(os.getpid()));"
            f" time.sleep(60)")
    sup = Supervisor([sys.executable, "-c", body], max_restarts=0,
                     resilience_path=str(tmp_path / "r.json"),
                     sleep=lambda s: None)

    def interrupt_wait(proc, started_s):
        while not os.path.exists(pidfile):
            time.sleep(0.02)
        raise KeyboardInterrupt

    sup._wait = interrupt_wait
    with pytest.raises(KeyboardInterrupt):
        sup.run()
    pid = int(open(pidfile).read())
    deadline = time.perf_counter() + 10
    while time.perf_counter() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break  # child is gone — not orphaned
        time.sleep(0.05)
    else:
        os.kill(pid, 9)
        pytest.fail("child survived the supervisor's abnormal exit")


def test_dist_init_noop_off_pod(monkeypatch):
    import jax

    from theanompi_tpu import launcher

    for var in ("TPU_WORKER_HOSTNAMES", "JAX_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda: pytest.fail("initialized off-pod"))
    launcher._maybe_init_distributed()
