"""ISSUE 19: multi-replica serving router + autoscaler on the fleet ledger.

Unit matrix on milliseconds-fast fakes — no subprocess and no XLA
anywhere in this file except the two fleet-lifecycle tests that drive
``python -c`` serving fakes through the real scheduler:

- the durable lifecycle file contracts (queue tailing with torn tails,
  the drain sentinel, atomic snapshot publish/throttle);
- the balancer (least-wait choice, conversation stickiness + the
  stick-factor escape hatch, forget-on-death);
- the autoscale hysteresis state machine on an injected clock (sustain
  windows, cooldown, the TTFT-SLO fast path, min/max bounds);
- the ledger failure-history cap (satellite: last K causes per job, a
  bounded job set, the dropped-count witness, pre-19 shape back-compat);
- serving-kind fleet jobs (spec validation, the tmserve child command,
  drain-to-done classification, serving-never-a-preemption-victim);
- ``run_queue_loop`` on the FakeEngine (durable admission, restart
  dedup, queue-wait accounting, both drain paths);
- the Router itself against hand-written replica dirs (exactly-once
  harvest, duplicate audit, drain give-backs, death absorption +
  backfill, pressure, the ROUTER.json report).
"""

import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

from theanompi_tpu.fleet import (
    DeviceLedger,
    FleetScheduler,
    JobSpec,
    JobSpecError,
    build_child_cmd,
    job_dir,
    read_fleet_events,
    read_record,
)
from theanompi_tpu.fleet.ledger import FAILURES_JOBS, FAILURES_PER_JOB
from theanompi_tpu.resilience import EXIT_CLEAN
from theanompi_tpu.router import (
    AutoscaleConfig,
    AutoscalePolicy,
    Balancer,
    ReplicaPool,
    Router,
    est_wait_s,
)
from theanompi_tpu.router import cli as router_cli
from theanompi_tpu.serving.kv_cache import blocks_for
from theanompi_tpu.serving.lifecycle import (
    DRAIN_OP,
    RequestLog,
    SnapshotPublisher,
    append_queue,
    drain_entry,
    publish_snapshot,
    read_jsonl_since,
    read_snapshot,
    request_drain,
    terminal_records,
    terminal_rids,
)
from theanompi_tpu.serving.scheduler import Request, Scheduler, run_queue_loop

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the durable lifecycle files ----------------------------------------------

def test_read_jsonl_since_tails_only_complete_lines(tmp_path):
    p = str(tmp_path / "q.jsonl")
    append_queue(p, [{"rid": 0}, {"rid": 1}])
    recs, off = read_jsonl_since(p, 0)
    assert [r["rid"] for r in recs] == [0, 1]
    # nothing new: offset parks
    recs2, off2 = read_jsonl_since(p, off)
    assert recs2 == [] and off2 == off
    # a torn tail (no newline) is "not there yet" — NOT consumed
    with open(p, "a") as f:
        f.write('{"rid": 2')
    recs3, off3 = read_jsonl_since(p, off)
    assert recs3 == [] and off3 == off
    # the writer finishes the line: now it appears exactly once
    with open(p, "a") as f:
        f.write('}\n')
    recs4, off4 = read_jsonl_since(p, off3)
    assert [r["rid"] for r in recs4] == [2] and off4 > off3
    # a complete-but-corrupt line is skipped AND consumed (never valid)
    with open(p, "a") as f:
        f.write('{"rid": oops}\n')
        f.write('{"rid": 3}\n')
    recs5, _ = read_jsonl_since(p, off4)
    assert [r["rid"] for r in recs5] == [3]
    # missing file: empty, offset unchanged
    assert read_jsonl_since(str(tmp_path / "nope"), 7) == ([], 7)


def test_queue_drain_sentinel_and_request_drain(tmp_path):
    p = str(tmp_path / "q.jsonl")
    assert drain_entry() == {"op": DRAIN_OP}
    append_queue(p, [{"rid": 5}])
    request_drain(p)
    recs, _ = read_jsonl_since(p, 0)
    assert recs == [{"rid": 5}, {"op": DRAIN_OP}]


def test_snapshot_publish_read_and_absent(tmp_path):
    p = str(tmp_path / "SERVE_SNAPSHOT.json")
    assert read_snapshot(p) is None
    publish_snapshot(p, {"backlog_tokens": 12, "token_rate": 80.0})
    snap = read_snapshot(p)
    assert snap["backlog_tokens"] == 12
    assert not os.path.exists(p + ".tmp")  # atomic: no debris
    with open(p, "w") as f:
        f.write("{torn")
    assert read_snapshot(p) is None  # unreadable -> None, never raises


def test_snapshot_publisher_throttles_on_steps_and_wall(tmp_path):
    p = str(tmp_path / "snap.json")
    pub = SnapshotPublisher(p, every_steps=4, min_interval_s=3600.0)
    calls = []

    def snap_fn():
        calls.append(1)
        return {"n": len(calls)}

    assert pub.maybe(snap_fn, 0)          # first call always due
    assert not pub.maybe(snap_fn, 1)      # neither steps nor wall due
    assert not pub.maybe(snap_fn, 3)
    assert pub.maybe(snap_fn, 4)          # step cadence
    assert pub.maybe(snap_fn, 4, force=True)   # final-publish override
    assert read_snapshot(p) == {"n": 3}
    # the wall-interval path keeps an IDLE loop publishing freshness
    pub2 = SnapshotPublisher(p, every_steps=10**9, min_interval_s=0.0)
    assert pub2.maybe(snap_fn, 0) and pub2.maybe(snap_fn, 0)


def test_request_log_records_latency_and_extras(tmp_path):
    p = str(tmp_path / "REQUESTS.jsonl")
    log = RequestLog(p, attempt=2)
    req = Request(rid=7, prompt=[1, 2], max_new_tokens=4)
    req.state, req.reason, req.generated = "done", None, [5, 5]
    req.t_submit, req.t_first_token = 10.0, 10.25
    log.record(req, queue_wait_ms=33.5)
    log.close()
    (rec,) = terminal_records(p)
    assert rec["rid"] == 7 and rec["attempt"] == 2
    assert rec["ttft_ms"] == pytest.approx(250.0)
    assert rec["queue_wait_ms"] == 33.5 and rec["n_generated"] == 2
    assert terminal_rids(p) == {7}


# -- balancer -----------------------------------------------------------------

def test_est_wait_uses_worst_of_router_and_snapshot_backlog():
    # the router's owed ledger and the replica's own snapshot can skew
    # (in-flight queue appends): balance on the WORSE of the two
    assert est_wait_s(100, {"backlog_tokens": 40, "token_rate": 50.0}) == 2.0
    assert est_wait_s(10, {"backlog_tokens": 80, "token_rate": 40.0}) == 2.0
    # no snapshot / no measured rate: the configured default rate
    assert est_wait_s(100, None, default_rate=50.0) == 2.0
    assert est_wait_s(100, {"token_rate": None}, default_rate=25.0) == 4.0


def test_balancer_picks_least_wait_and_sticks_conversations():
    b = Balancer(stick_factor=2.0, stick_slack_s=0.0)
    jid, sticky = b.choose({"a": 1.0, "b": 0.4}, convo=9)
    assert jid == "b" and not sticky          # first touch binds
    jid, sticky = b.choose({"a": 0.5, "b": 0.6}, convo=9)
    assert jid == "b" and sticky              # held: within 2x of best
    jid, sticky = b.choose({"a": 0.1, "b": 0.9}, convo=9)
    assert jid == "a" and not sticky          # too far behind: rebind
    # no conversation: pure least-wait, ties break deterministically
    assert b.choose({"x": 0.2, "y": 0.2}) == ("x", False)
    with pytest.raises(ValueError):
        b.choose({})


def test_balancer_forget_replica_drops_its_conversations():
    b = Balancer()
    b.choose({"a": 0.1, "b": 5.0}, convo=1)
    b.choose({"a": 0.1, "b": 5.0}, convo=2)
    b.choose({"a": 5.0, "b": 0.1}, convo=3)
    assert b.forget_replica("a") == 2
    # rebinding after the death is fresh, not sticky
    jid, sticky = b.choose({"b": 0.1}, convo=1)
    assert jid == "b" and not sticky


# -- autoscale hysteresis -----------------------------------------------------

def _policy(**kw):
    clock = {"t": 0.0}
    cfg = AutoscaleConfig(**{
        "min_replicas": 1, "max_replicas": 4, "up_pressure_s": 4.0,
        "up_after_s": 1.0, "down_pressure_s": 0.5, "down_after_s": 2.0,
        "cooldown_s": 2.0, **kw})
    return AutoscalePolicy(cfg, clock=lambda: clock["t"]), clock


def test_autoscale_up_requires_sustained_pressure():
    pol, clock = _policy()
    assert pol.observe(1, 10.0) is None        # spike begins
    clock["t"] = 0.9
    assert pol.observe(1, 10.0) is None        # not sustained yet
    clock["t"] = 1.0
    assert pol.observe(1, 10.0) == "up"        # 1.0s above: scale
    clock["t"] = 1.5
    assert pol.observe(2, 10.0) is None        # cooldown gates
    # the spike that began DURING cooldown (t=1.5) is credited once the
    # cooldown ends: at t=3.0 it has already sustained 1.5s
    clock["t"] = 3.0
    assert pol.observe(2, 10.0) == "up"


def test_autoscale_band_clears_windows_and_down_needs_sustain():
    pol, clock = _policy(cooldown_s=0.0)
    pol.observe(2, 10.0)
    clock["t"] = 0.6
    pol.observe(2, 2.0)                        # inside the band: reset
    clock["t"] = 1.6
    assert pol.observe(2, 10.0) is None        # window restarted at 1.6
    # down: below 0.5 sustained for 2.0s
    clock["t"] = 2.0
    assert pol.observe(2, 0.1) is None
    clock["t"] = 3.9
    assert pol.observe(2, 0.1) is None
    clock["t"] = 4.0
    assert pol.observe(2, 0.1) == "down"


def test_autoscale_slo_breach_skips_the_sustain_wait():
    pol, clock = _policy(ttft_slo_ms=500.0, cooldown_s=0.0)
    # pressure fine, p99 blown: scale immediately (damage is happening)
    assert pol.observe(1, 1.0, ttft_p99_ms=900.0) == "up"
    # and an SLO breach at max_replicas still respects the bound
    assert pol.observe(4, 1.0, ttft_p99_ms=900.0) is None


def test_autoscale_respects_bounds():
    pol, clock = _policy(min_replicas=2, max_replicas=2, cooldown_s=0.0)
    clock["t"] = 10.0
    assert pol.observe(2, 100.0) is None       # at max: never up
    clock["t"] = 20.0
    assert pol.observe(2, 0.0) is None         # at min: never down
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscaleConfig(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError, match="hysteresis"):
        AutoscaleConfig(down_pressure_s=5.0, up_pressure_s=4.0).validate()


# -- ledger failure-history cap (satellite) -----------------------------------

def test_ledger_failures_bounded_per_job_with_dropped_witness(tmp_path):
    led = DeviceLedger(str(tmp_path), 8)
    for i in range(FAILURES_PER_JOB + 2):
        led.record_failure("j", {"cause": f"c{i}"})
    entry = led.failures["j"]
    assert [c["cause"] for c in entry["causes"]] == \
        [f"c{i}" for i in range(2, FAILURES_PER_JOB + 2)]
    assert entry["dropped"] == 2               # the witness: 2 fell off
    assert led.last_failure("j")["cause"] == f"c{FAILURES_PER_JOB + 1}"
    assert led.last_failure("ghost") is None


def test_ledger_failures_bounded_across_jobs_and_persisted(tmp_path):
    d = str(tmp_path / "pool")
    led = DeviceLedger(d, 8)
    for i in range(FAILURES_JOBS + 3):
        led.record_failure(f"job-{i:03d}", {"cause": "crash"})
    assert len(led.failures) == FAILURES_JOBS
    assert led.failures_dropped == 3           # oldest jobs evicted
    assert "job-000" not in led.failures and "job-002" not in led.failures
    assert f"job-{FAILURES_JOBS + 2:03d}" in led.failures
    # the cap and the witness survive a reopen
    re = DeviceLedger(d)
    assert len(re.failures) == FAILURES_JOBS
    assert re.failures_dropped == 3
    # and keeps evicting with a continuous sequence after the reload
    re.record_failure("late", {"cause": "hang"})
    assert len(re.failures) == FAILURES_JOBS and re.failures_dropped == 4


def test_ledger_failures_pre19_shape_back_compat(tmp_path):
    d = str(tmp_path / "pool")
    led = DeviceLedger(d, 8)
    led.persist()
    # hand-write the pre-19 shape: job -> bare cause dict
    path = os.path.join(d, "ledger.json")
    state = json.load(open(path))
    state["failures"] = {"old": {"cause": "crash", "exit_code": 70}}
    with open(path, "w") as f:
        json.dump(state, f)
    re = DeviceLedger(d)
    assert re.last_failure("old")["cause"] == "crash"
    assert re.failures["old"]["dropped"] == 0
    re.record_failure("old", {"cause": "hang"})  # appends, no crash
    assert [c["cause"] for c in re.failures["old"]["causes"]] == \
        ["crash", "hang"]


# -- serving-kind fleet jobs --------------------------------------------------

def test_jobspec_kind_validation_and_serving_child_cmd(tmp_path):
    with pytest.raises(JobSpecError, match="kind"):
        JobSpec(job_id="x", kind="batch").validate()
    spec = JobSpec(job_id="r0", kind="serving",
                   modelfile="theanompi_tpu.models.transformer_lm",
                   modelclass="TransformerLM",
                   model_config={"dim": 32, "precision": "fp32"},
                   extra_args=["--drain-s", "2"])
    spec.validate()
    jdir = str(tmp_path / "jobs" / "r0")
    cmd = build_child_cmd(spec, 2, jdir)
    assert cmd[:3] == [sys.executable, "-m", "theanompi_tpu.serving"]
    assert "--queue-file" in cmd
    assert cmd[cmd.index("--queue-file") + 1] == \
        os.path.join(jdir, "queue.jsonl")
    assert cmd[cmd.index("--requests-log") + 1] == \
        os.path.join(jdir, "REQUESTS.jsonl")
    assert "--set" in cmd and "precision='fp32'" in cmd
    assert cmd[-2:] == ["--drain-s", "2"]
    # restart continuity is REQUESTS.jsonl dedup: no training resume flags
    assert build_child_cmd(spec, 2, jdir, resume=True) == cmd
    assert "--resume" not in build_child_cmd(spec, 2, jdir, resume=True)


#: a serving fake: runs until SIGTERM (the drain_job path) or until the
#: durable drain sentinel appears in its queue file, then exits CLEAN —
#: the shape of a replica finishing in-flight work on request
_SERVE_FAKE = r'''
import json, os, signal, sys, time
jdir = os.environ["THEANOMPI_JOB_DIR"]
signal.signal(signal.SIGTERM, lambda s, f: sys.exit(0))
open(os.path.join(jdir, "replica.ready"), "w").write("1")
q = os.path.join(jdir, "queue.jsonl")
deadline = time.time() + 30
while time.time() < deadline:
    try:
        if any('"op": "drain"' in line for line in open(q)):
            sys.exit(0)
    except OSError:
        pass
    time.sleep(0.01)
sys.exit(1)
'''


def _wait_replica_ready(pool, jid, timeout_s=30.0):
    """Wait past the supervisor's startup window: ``running`` status only
    means the supervisor launched; the ready file means the child has its
    SIGTERM handler installed (draining earlier is a kill, not a drain)."""
    ready = os.path.join(pool.jdir(jid), "replica.ready")
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if os.path.exists(ready) and pool.status(jid) == "running":
            return
        time.sleep(0.005)
    raise AssertionError(f"{jid} never became ready")


def _serving_fake_spec(**kw):
    return {"priority": kw.pop("priority", 10),
            "min_devices": kw.pop("min_devices", 2),
            "max_devices": kw.pop("max_devices", 2),
            "max_restarts": 0, "backoff_base": 0.1,
            "argv": [sys.executable, "-c", _SERVE_FAKE], **kw}


def test_fleet_drain_job_classifies_serving_done(tmp_path):
    """drain_job SIGTERMs a running replica through its supervisor; the
    replica exits 0 — and despite ``preempted=True`` on the job result
    (the supervisor DID terminate it) the serving episode classifies
    DONE, never requeued."""
    d = str(tmp_path / "fleet")
    sched = FleetScheduler(d, 8, poll_s=0.01, telemetry=False)
    pool = ReplicaPool(sched, _serving_fake_spec())
    jid = pool.spawn()
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    _wait_replica_ready(pool, jid)
    assert sched.drain_job(jid)
    t.join(30)
    assert not t.is_alive() and box["rc"] == EXIT_CLEAN
    rec = read_record(d, jid)
    assert rec.status == "done" and rec.preemptions == 0
    assert rec.last_exit == 0
    names = [e["event"] for e in read_fleet_events(d)]
    assert "fleet.drain" in names and "fleet.complete" in names
    assert "fleet.preempt" not in names
    assert not sched.drain_job(jid)  # already terminal: no-op


def test_fleet_serving_replica_never_a_preemption_victim(tmp_path):
    """A low-priority serving replica holding the whole pool is NOT
    preempted by a high-priority training job — training waits until the
    replica drains (the inverse of the training-victim path)."""
    d = str(tmp_path / "fleet")
    sched = FleetScheduler(d, 8, poll_s=0.01, telemetry=False)
    pool = ReplicaPool(sched, _serving_fake_spec(
        priority=0, min_devices=8, max_devices=8))
    jid = pool.spawn()
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    _wait_replica_ready(pool, jid)
    sched.submit(JobSpec(job_id="urgent-train", priority=100,
                         min_devices=8, max_restarts=0,
                         argv=[sys.executable, "-c", "pass"]))
    time.sleep(0.3)  # several scheduler passes
    with sched._lock:
        assert sched.records["urgent-train"].status == "queued"
        assert sched.records[jid].status == "running"
    assert "fleet.preempt" not in [
        e["event"] for e in read_fleet_events(d)]
    pool.drain(jid)   # the durable sentinel: replica finishes and exits
    t.join(30)
    assert not t.is_alive() and box["rc"] == EXIT_CLEAN
    assert read_record(d, jid).status == "done"
    assert read_record(d, "urgent-train").status == "done"


# -- run_queue_loop on the FakeEngine -----------------------------------------

class FakeEngine:
    """Host-only engine double (the test_serving_resilience shape): the
    scheduler surface with no XLA behind it."""

    def __init__(self, max_batch=2, block_size=4, num_blocks=9,
                 max_context=64):
        self.max_batch = max_batch
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_context = max_context
        self.max_blocks_per_seq = blocks_for(max_context, block_size)
        self.quant_stats = None
        self.decode_impl = "fallback"

    @property
    def quantized(self):
        return False

    def prefill(self, row, tokens, temperature=0.0, rid=0, prefix_len=0):
        return 7, None

    def decode(self, tables, lengths, tokens, temps, rids):
        return np.full((self.max_batch,), 5, np.int32), None


def _entry(rid, new=4, **kw):
    return {"rid": rid, "prompt": [1, 2, 3], "max_new_tokens": new, **kw}


def test_run_queue_loop_serves_entries_with_queue_wait(tmp_path):
    q = str(tmp_path / "queue.jsonl")
    append_queue(q, [_entry(0, enq_wall=time.time() - 0.2), _entry(1)])
    request_drain(q)
    terminal = []
    results, wall = run_queue_loop(
        Scheduler(FakeEngine()), q, poll_s=0.001,
        on_terminal=lambda req, **ex: terminal.append((req, ex)))
    assert set(results) == {0, 1}
    assert all(r.state == "done" for r in results.values())
    by_rid = {req.rid: ex for req, ex in terminal}
    # rid 0 carried an enqueue stamp from 200ms ago: the dwell surfaces
    assert by_rid[0]["queue_wait_ms"] >= 150.0
    # rid 1 had no stamp: no fabricated queue_wait
    assert "queue_wait_ms" not in by_rid[1]


def test_run_queue_loop_restart_dedup_skips_answered(tmp_path):
    q = str(tmp_path / "queue.jsonl")
    append_queue(q, [_entry(0), _entry(1), _entry(2)])
    request_drain(q)
    results, _ = run_queue_loop(Scheduler(FakeEngine()), q,
                                poll_s=0.001, answered={0, 2})
    assert set(results) == {1}  # the previous attempt's answers skipped


def test_run_queue_loop_picks_up_late_arrivals_then_drains(tmp_path):
    q = str(tmp_path / "queue.jsonl")
    append_queue(q, [_entry(0)])
    box = {}

    def run():
        box["out"] = run_queue_loop(Scheduler(FakeEngine()), q,
                                    poll_s=0.001)

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.15)
    append_queue(q, [_entry(1)])   # late arrival while the loop idles
    time.sleep(0.15)
    request_drain(q)
    t.join(20)
    assert not t.is_alive(), "queue loop never drained"
    results, _ = box["out"]
    assert set(results) == {0, 1}
    assert all(r.state == "done" for r in results.values())


def test_run_queue_loop_sigterm_drain_sheds_as_give_back(tmp_path):
    """The SIGTERM path: queued-but-unserved entries shed with reason
    "draining" — the give-back record the router redistributes — while
    in-flight work finishes within the drain budget."""
    q = str(tmp_path / "queue.jsonl")
    # max_batch=1: rid 0 occupies the slot, 1 and 2 wait in the queue
    append_queue(q, [_entry(0, new=64), _entry(1), _entry(2)])
    flag = threading.Event()
    sched = Scheduler(FakeEngine(max_batch=1, num_blocks=40,
                                 max_context=128))
    stepped = []

    def trip(_s):
        stepped.append(1)
        if len(stepped) == 3:
            flag.set()

    results, _ = run_queue_loop(sched, q, poll_s=0.001,
                                drain=flag.is_set, drain_s=10.0,
                                between_steps=trip)
    assert set(results) == {0, 1, 2}
    assert results[0].state == "done"          # in-flight: finished
    assert results[1].state == "shed"
    assert results[1].reason == "draining"     # the give-back marker
    assert results[2].state == "shed"


def test_scheduler_snapshot_shape_and_queue_loop_publishing(tmp_path):
    q = str(tmp_path / "queue.jsonl")
    snap_path = str(tmp_path / "SERVE_SNAPSHOT.json")
    append_queue(q, [_entry(0), _entry(1)])
    request_drain(q)
    run_queue_loop(Scheduler(FakeEngine()), q, poll_s=0.001,
                   snapshot=SnapshotPublisher(snap_path, every_steps=1))
    snap = read_snapshot(snap_path)
    for key in ("updated", "backlog_tokens", "queue_len", "n_active",
                "token_rate", "decode_steps", "n_done", "n_expired",
                "n_shed", "n_failed", "draining", "prefix_hit_rate"):
        assert key in snap, key
    assert snap["n_done"] == 2 and snap["backlog_tokens"] == 0
    assert snap["draining"] is False


# -- the Router against hand-written replica dirs -----------------------------

def _mini_pool(tmp_path, n=2, **cfg):
    """A pool over a scheduler that is never run: jobs stay 'queued'
    (dispatchable — the durable queue IS the contract), and tests write
    REQUESTS.jsonl / snapshots into the job dirs by hand."""
    sched = FleetScheduler(str(tmp_path / "fleet"), 8, telemetry=False)
    pool = ReplicaPool(sched, _serving_fake_spec(**cfg))
    router = Router(pool, balancer=Balancer(),
                    policy=None, default_rate=100.0)
    for _ in range(n):
        pool.spawn()
    return sched, pool, router


def _answer(pool, jid, rid, state="done", reason=None, **extra):
    with open(pool.requests_log(jid), "a") as f:
        f.write(json.dumps({"rid": rid, "state": state, "reason": reason,
                            "n_generated": 4, **extra}) + "\n")


def test_router_submit_dispatches_to_durable_queue(tmp_path):
    _, pool, router = _mini_pool(tmp_path)
    jid = router.submit(_entry(0, new=8))
    assert jid in pool.replicas
    recs, _ = read_jsonl_since(pool.queue_path(jid), 0)
    assert recs[0]["rid"] == 0 and "enq_wall" in recs[0]
    assert router.n_requests == 1
    assert router.owed_tokens(jid) == 8
    # balancing: the next request goes to the OTHER (idle) replica
    jid2 = router.submit(_entry(1, new=8))
    assert jid2 != jid
    # conversation affinity: convo 5 sticks to its first replica
    first = router.submit(_entry(2, convo=5), convo=5)
    assert router.submit(_entry(3, convo=5), convo=5) == first


def test_router_poll_exactly_once_with_duplicate_audit(tmp_path):
    _, pool, router = _mini_pool(tmp_path)
    a, b = pool.replicas
    router.submit(_entry(0))
    router.entries[0], router.assigned[0] = router.entries[0], a
    _answer(pool, a, 0, ttft_ms=5.0, queue_wait_ms=10.0)
    assert router.poll() == 1
    assert router.results[0]["replica"] == a
    # the same rid answered AGAIN (slow-not-dead double serve): audited,
    # never double-counted
    _answer(pool, b, 0)
    assert router.poll() == 0
    assert router.n_duplicates == 1
    assert router.results[0]["replica"] == a   # first record won
    # router-visible TTFT = queue wait + replica ttft
    assert router.ttft_ms == [pytest.approx(15.0)]
    # foreign rids in a REQUESTS.jsonl (not this router's traffic) skip
    _answer(pool, a, 999)
    assert router.poll() == 0 and 999 not in router.results


def test_router_drain_give_back_redistributes(tmp_path):
    _, pool, router = _mini_pool(tmp_path)
    a, b = pool.replicas
    rid_jid = router.submit(_entry(0))
    other = b if rid_jid == a else a
    # the replica drained with rid 0 still queued: the shed give-back
    _answer(pool, rid_jid, 0, state="shed", reason="draining")
    router.poll()
    assert 0 not in router.results             # NOT a terminal answer
    assert router.assigned[0] == other         # moved to the survivor
    assert router.n_redistributed == 1
    recs, _ = read_jsonl_since(pool.queue_path(other), 0)
    assert recs[-1]["rid"] == 0
    # a real (non-drain) shed IS terminal — load shedding is an answer
    _answer(pool, other, 0, state="shed", reason="deadline infeasible")
    router.poll()
    assert router.results[0]["state"] == "shed"


def test_router_absorbs_dead_replica_and_retries_until_backfill(tmp_path):
    sched, pool, router = _mini_pool(tmp_path)
    a, b = pool.replicas
    router.submit(_entry(0))
    router.submit(_entry(1))
    # force both rids onto replica a, then kill a AND b (whole pool down)
    for rid in (0, 1):
        router.assigned[rid] = a
    with sched._lock:
        sched.records[a].status = "failed"
        sched.records[b].status = "failed"
    moved = router.absorb_dead()
    assert moved == 0                          # no survivor yet: owed
    assert router.unanswered(a) == [0, 1]
    # the floor backfill spawns a replacement, the next tick moves them
    router.policy = AutoscalePolicy(AutoscaleConfig(min_replicas=1,
                                                    max_replicas=2))
    assert router.scale_tick() == "up"         # backfill below the floor
    assert router.absorb_dead() == 2
    c = router.assigned[0]
    assert c not in (a, b)
    recs, _ = read_jsonl_since(pool.queue_path(c), 0)
    assert {r["rid"] for r in recs} == {0, 1}
    assert router.n_redistributed == 2
    rep = router.report(wall_s=1.0)
    assert rep["replicas_dead"] == 2 and rep["replicas_spawned"] == 3


def test_router_pressure_prefers_measured_rates(tmp_path):
    _, pool, router = _mini_pool(tmp_path)
    a, b = pool.replicas
    router.submit(_entry(0, new=100))
    router.submit(_entry(1, new=100))
    # no snapshots yet: default_rate=100 per replica -> 200/200 = 1.0s
    assert router.pool_pressure_s() == pytest.approx(1.0)
    publish_snapshot(os.path.join(pool.jdir(a), "SERVE_SNAPSHOT.json"),
                     {"token_rate": 700.0, "backlog_tokens": 0})
    publish_snapshot(os.path.join(pool.jdir(b), "SERVE_SNAPSHOT.json"),
                     {"token_rate": 100.0, "backlog_tokens": 0})
    assert router.pool_pressure_s() == pytest.approx(200.0 / 800.0)


def test_router_report_exactly_once_audit(tmp_path):
    _, pool, router = _mini_pool(tmp_path)
    router.submit(_entry(0))
    router.submit(_entry(1))
    _answer(pool, router.assigned[0], 0, ttft_ms=4.0, queue_wait_ms=6.0)
    router.tick()
    rep = router.report(wall_s=2.0)
    assert rep["requests"] == 2 and rep["answered"] == 1
    assert rep["exactly_once"] is False        # rid 1 still owed
    _answer(pool, router.assigned[1], 1, ttft_ms=4.0, queue_wait_ms=6.0)
    router.tick()
    rep = router.report(wall_s=2.0)
    assert rep["exactly_once"] is True
    assert rep["terminal_states"] == {"done": 2}
    assert rep["generated_tokens"] == 8 and rep["value"] == 4.0
    assert rep["ttft_ms"]["p50"] == pytest.approx(10.0)
    assert rep["max_attempts"] == 1
    assert rep["replica_trajectory"][0][1] == 2


def test_router_drain_all_sentinels_every_live_replica(tmp_path):
    _, pool, router = _mini_pool(tmp_path)
    router.drain_all()
    for jid in pool.replicas:
        recs, _ = read_jsonl_since(pool.queue_path(jid), 0)
        assert {"op": DRAIN_OP} in recs
        assert jid in pool.draining
    assert router._candidates() == []          # draining: undispatchable
    # idempotent: a second drain_all appends no second sentinel
    sizes = [os.path.getsize(pool.queue_path(j)) for j in pool.replicas]
    router.drain_all()
    assert sizes == [os.path.getsize(pool.queue_path(j))
                     for j in pool.replicas]


def test_router_telemetry_uses_registered_names_only(tmp_path):
    """Every event the router emits flows through the ISSUE 13 registry:
    drive the dispatch/duplicate/death/redistribute/scale paths with a
    real Telemetry and check each emitted name is registered."""
    from theanompi_tpu.telemetry import Telemetry
    from theanompi_tpu.telemetry.metrics import (
        ROUTER_COUNTERS,
        ROUTER_GAUGES,
        ROUTER_INSTANTS,
    )

    assert set(ROUTER_INSTANTS) == {
        "router.dispatch", "router.redistribute", "router.replica_dead",
        "router.scale_up", "router.scale_down", "router.duplicate"}
    assert set(ROUTER_GAUGES) == {
        "router.replicas", "router.backlog_tokens", "router.ttft_p99_ms"}
    assert set(ROUTER_COUNTERS) == {
        "router.requests", "router.redistributed"}

    sched, pool, router = _mini_pool(tmp_path)
    tel_dir = str(tmp_path / "tel")
    router.telemetry = Telemetry(tel_dir, rank=0)
    router.policy = AutoscalePolicy(AutoscaleConfig(min_replicas=2,
                                                    max_replicas=3))
    a, b = pool.replicas
    router.submit(_entry(0))
    router.submit(_entry(1))
    _answer(pool, router.assigned[0], 0, ttft_ms=1.0)
    _answer(pool, b if router.assigned[0] == a else a, 0)  # duplicate
    _answer(pool, router.assigned[1], 1, state="shed", reason="draining")
    router.tick()
    with sched._lock:
        sched.records[a].status = "failed"
    router.tick()                              # death + backfill
    router.telemetry.flush_metrics()
    router.telemetry.close()
    registered = (set(ROUTER_INSTANTS) | set(ROUTER_GAUGES)
                  | set(ROUTER_COUNTERS))
    seen = set()
    for fname in os.listdir(tel_dir):
        if not fname.startswith("events-rank"):
            continue
        for line in open(os.path.join(tel_dir, fname)):
            ev = json.loads(line)
            if ev.get("name", "").startswith("router."):
                seen.add(ev["name"])
    assert seen <= registered
    assert "router.dispatch" in seen and "router.replica_dead" in seen
    assert "router.duplicate" in seen and "router.redistribute" in seen


# -- the tmrouter CLI surface -------------------------------------------------

def test_parse_set_literal_grammar():
    out = router_cli._parse_set(["dim=64", "precision='fp32'", "name=raw"])
    assert out == {"dim": 64, "precision": "fp32", "name": "raw"}
    with pytest.raises(ValueError, match="K=V"):
        router_cli._parse_set(["oops"])


def test_synthetic_entries_turn_grammar_and_arrivals():
    entries = router_cli.synthetic_entries(6, vocab=64, prompt_len=4,
                                           max_new_tokens=8, rate=0.0,
                                           seed=0, turns=3)
    assert [e["rid"] for e in entries] == list(range(6))
    assert all(e["arrival_s"] == 0.0 for e in entries)   # burst
    assert [e["convo"] for e in entries] == [0, 0, 0, 1, 1, 1]
    # within a conversation, each turn EXTENDS the previous prompt — the
    # prefix-affinity traffic shape
    assert entries[1]["prompt"][:4] == entries[0]["prompt"]
    assert entries[2]["prompt"][:8] == entries[1]["prompt"]
    # a new conversation starts fresh
    assert len(entries[3]["prompt"]) == 4
    # seeded determinism + Poisson arrivals strictly increase
    again = router_cli.synthetic_entries(6, 64, 4, 8, 0.0, 0, turns=3)
    assert again == entries
    timed = router_cli.synthetic_entries(5, 64, 4, 8, rate=100.0, seed=1)
    arr = [e["arrival_s"] for e in timed]
    assert arr == sorted(arr) and arr[0] > 0 and timed[0]["convo"] is None


def test_router_cli_parser_defaults_and_script():
    args = router_cli.build_parser().parse_args(["--fleet-dir", "/x"])
    assert args.replicas == 1 and args.max_replicas == 4
    assert args.replica_priority == 10 and not args.no_autoscale
    # the console script is wired (same contract as tmserve/tmfleet)
    with open(os.path.join(REPO, "pyproject.toml")) as f:
        assert 'tmrouter = "theanompi_tpu.router.cli:main"' in f.read()


# -- perf-ledger classification -----------------------------------------------

def test_ledger_classifies_router_artifact():
    from theanompi_tpu.telemetry.ledger import classify_artifact

    recs = classify_artifact("ROUTER.json", {
        "metric": "router_tokens_per_sec", "value": 123.4,
        "ttft_ms": {"p50": 10.0, "p99": 40.0}, "replicas_peak": 3,
        "run_id": "r1"})
    by_metric = {r["metric"]: r for r in recs}
    assert by_metric["router.tokens_per_sec"]["value"] == 123.4
    assert by_metric["router.ttft_p99_ms"]["value"] == 40.0
    assert by_metric["router.ttft_p50_ms"]["value"] == 10.0
    assert by_metric["router.replicas_peak"]["value"] == 3
    assert all(r["kind"] == "router" for r in recs)
    # and the generic bench-line branch did NOT swallow it
    assert "router_tokens_per_sec" not in by_metric
