"""Cross-replica divergence checker (SURVEY.md §5 race-detection row)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.utils.divergence import (
    assert_replicas_in_sync,
    replica_divergence,
)


def _replicated(mesh, value):
    return jax.device_put(value, NamedSharding(mesh, P()))


def test_in_sync_replicated_is_zero(mesh8):
    x = _replicated(mesh8, np.arange(32, dtype=np.float32).reshape(4, 8))
    assert replica_divergence({"w": x}) == 0.0
    assert assert_replicas_in_sync({"w": x}) == 0.0


def test_sharded_leaves_are_ignored(mesh8):
    # fully sharded: every shard covers a different index -> no comparison
    x = jax.device_put(np.arange(8, dtype=np.float32),
                       NamedSharding(mesh8, P("data")))
    assert replica_divergence([x]) == 0.0


def test_diverged_copy_is_detected(mesh8):
    # hand-build a "replicated" array whose device copies disagree
    base = np.ones((8, 8), np.float32)
    bufs = []
    for i, d in enumerate(mesh8.devices.flat):
        v = base.copy()
        if i == 3:
            v[0, 0] += 0.5  # one device drifts
        bufs.append(jax.device_put(v, d))
    x = jax.make_array_from_single_device_arrays(
        (8, 8), NamedSharding(mesh8, P()), bufs
    )
    assert replica_divergence({"w": x}) == pytest.approx(0.5)
    with pytest.raises(AssertionError, match="replica divergence"):
        assert_replicas_in_sync({"w": x})
    # tolerance lets small drift pass
    assert assert_replicas_in_sync({"w": x}, atol=1.0) == pytest.approx(0.5)


def test_nan_on_one_copy_is_divergence(mesh8):
    """A NaN on one replica but not others must be flagged, not dropped."""
    base = np.ones((8, 8), np.float32)
    bufs = []
    for i, d in enumerate(mesh8.devices.flat):
        v = base.copy()
        if i == 5:
            v[0, 0] = np.nan
        bufs.append(jax.device_put(v, d))
    x = jax.make_array_from_single_device_arrays(
        (8, 8), NamedSharding(mesh8, P()), bufs
    )
    assert replica_divergence({"w": x}) == float("inf")
    with pytest.raises(AssertionError, match="replica divergence"):
        assert_replicas_in_sync({"w": x}, atol=1e9)  # no atol excuses NaN


def test_pairwise_spread_not_just_vs_first(mesh8):
    """Copies 0.6 / 1.0 / 1.4 diverge by 0.8 pairwise even though each is
    only 0.4 from copy 0."""
    vals = [0.6, 1.0, 1.4, 1.0, 1.0, 1.0, 1.0, 1.0]
    bufs = [jax.device_put(np.full((8, 8), vals[i], np.float32), d)
            for i, d in enumerate(mesh8.devices.flat)]
    x = jax.make_array_from_single_device_arrays(
        (8, 8), NamedSharding(mesh8, P()), bufs)
    assert replica_divergence({"w": x}) == pytest.approx(0.8, abs=1e-6)


def test_matching_infs_in_sync_but_real_divergence_still_seen(mesh8):
    """inf on every copy at one index must not mask a finite divergence at
    another (inf - inf = NaN would poison a naive max)."""
    bufs = []
    for i, d in enumerate(mesh8.devices.flat):
        v = np.ones((8, 8), np.float32)
        v[0, 0] = np.inf  # blow-up on EVERY copy: consistent
        if i == 2:
            v[1, 1] = 5.0  # the real divergence
        bufs.append(jax.device_put(v, d))
    x = jax.make_array_from_single_device_arrays(
        (8, 8), NamedSharding(mesh8, P()), bufs)
    assert replica_divergence({"w": x}) == pytest.approx(4.0)


def test_inf_on_one_copy_is_divergence(mesh8):
    bufs = []
    for i, d in enumerate(mesh8.devices.flat):
        v = np.ones((8, 8), np.float32)
        if i == 4:
            v[0, 0] = np.inf
        bufs.append(jax.device_put(v, d))
    x = jax.make_array_from_single_device_arrays(
        (8, 8), NamedSharding(mesh8, P()), bufs)
    assert replica_divergence({"w": x}) == float("inf")


def test_matching_nans_are_in_sync(mesh8):
    """Identical NaN patterns on every copy are consistent, not divergent."""
    base = np.ones((8, 8), np.float32)
    base[1, 1] = np.nan
    x = _replicated(mesh8, base)
    assert replica_divergence({"w": x}) == 0.0


def test_bsp_trainer_stays_in_sync(mesh8):
    """End-to-end: after BSP steps the trainer's replicated params must be
    bit-identical on all 8 devices (the invariant the checker exists for)."""
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer

    model = WideResNet({"depth": 10, "widen": 1, "batch_size": 2,
                        "image_size": 8, "n_train": 64, "n_val": 16,
                        "n_epochs": 1, "precision": "fp32",
                        "bn_axis": "data", "verbose": False})
    t = BSPTrainer(model, mesh=mesh8)
    t.compile_iter_fns()
    t.init_state()
    for i, batch in enumerate(model.data.train_batches(t.global_batch, 0, seed=0)):
        t.train_iter(batch, lr=0.05)
        if i >= 1:
            break
    assert t.check_divergence() == 0.0
