"""ISSUE 16 durable perf-regression ledger.

Pure-python on synthetic records plus the repo's own committed
artifacts: classification of every known artifact shape, fingerprint
idempotence, the torn-tail crash contract, trailing-median regression
verdicts in both metric directions, stub-run exclusion from baselines,
the ``tmprof --ledger`` exit contract (0 clean / 1 regression / 2
usage), and the bench.py append hook.  The acceptance fixture seeds a
throughput collapse and must exit 1; the repo's real backfilled
artifacts must exit 0.
"""

import json
import os

import pytest

from theanompi_tpu.telemetry import PerfLedger, check_ledger, read_ledger
from theanompi_tpu.telemetry import prof
from theanompi_tpu.telemetry.ledger import (
    LEDGER_FILENAME,
    bench_ledger_append,
    check_records,
    classify_artifact,
    lower_is_better,
    make_record,
    regressions,
    trajectories,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _seed(path, metric, values, unit="images/sec"):
    led = PerfLedger(str(path))
    for i, v in enumerate(values):
        led.append([make_record("seed", "bench", metric, v, unit,
                                run_id=f"r{i}")])
    return led


# -- records & fingerprints ---------------------------------------------------

def test_make_record_fingerprint_stable():
    a = make_record("s", "bench", "m", 1.5, "ms", run_id="r1")
    b = make_record("s", "bench", "m", 1.5, "ms", run_id="r1")
    c = make_record("s", "bench", "m", 1.6, "ms", run_id="r1")
    assert a["fp"] == b["fp"] != c["fp"]
    assert a["schema"] == 1 and a["value"] == 1.5


def test_lower_is_better_inference():
    assert lower_is_better("bench.step_ms", "ms")
    assert lower_is_better("serve.ttft_p99_ms", "ms")
    assert lower_is_better("attrib.train.step_ms", "ms")
    assert not lower_is_better("bench.imgs_per_sec", "images/sec")
    assert not lower_is_better("mfu_ladder.d256xL4.mfu", "mfu")
    assert not lower_is_better("scaling.wrn.psum.n8.efficiency",
                               "efficiency")


# -- artifact classification --------------------------------------------------

def test_classify_bench_wrapper_and_stub():
    ok = {"n": 1, "cmd": "x", "rc": 0,
          "parsed": {"metric": "imgs_per_sec", "value": 2481.0,
                     "unit": "images/sec", "run_id": "r03",
                     "step_ms": 103.2, "mfu": 0.299}}
    recs = classify_artifact("BENCH_r03.json", ok)
    by_metric = {r["metric"]: r for r in recs}
    assert by_metric["imgs_per_sec"]["value"] == 2481.0
    assert by_metric["imgs_per_sec.step_ms"]["unit"] == "ms"
    assert by_metric["imgs_per_sec.mfu"]["value"] == 0.299
    # rc!=0 / unparsed rounds become stub records, never baselines
    bad = {"n": 4, "cmd": "x", "rc": 1, "tail": "boom", "parsed": None}
    (rec,) = classify_artifact("BENCH_r04.json", bad)
    assert rec["kind"] == "backend_unavailable" and rec["value"] is None
    stub = {"status": "backend_unavailable", "error": "no TPU",
            "run_id": "r9"}
    (rec,) = classify_artifact("BENCH_unavailable.json", stub)
    assert rec["kind"] == "backend_unavailable"


def test_classify_scaling_and_attrib():
    scaling = {"model": "wrn", "strategy": "psum",
               "per_n": {"2": {"imgs_per_sec": 100.0, "step_ms": 20.0,
                               "efficiency": 0.9},
                         "1": {"imgs_per_sec": 55.0}}}
    recs = classify_artifact("SCALING.json", scaling)
    metrics = [r["metric"] for r in recs]
    assert "scaling.wrn.psum.n1.imgs_per_sec" in metrics
    assert "scaling.wrn.psum.n2.efficiency" in metrics
    attrib = {"pid": 7, "per_rank": {"0": {
        "mode": "train", "wall_step": {"p50_ms": 12.5},
        "segments": {"compute": {"share": 0.8},
                     "host": {"share": 0.2}}}}}
    recs = classify_artifact("ATTRIB.json", attrib)
    by_metric = {r["metric"]: r for r in recs}
    assert by_metric["attrib.train.step_ms"]["value"] == 12.5
    assert by_metric["attrib.train.compute_share"]["value"] == 0.8
    assert by_metric["attrib.train.step_ms"]["run_id"] == "pid7"


def test_classify_serve_report():
    """SERVE.json carries top-level metric/value like a bare bench line —
    the SERVE branch must win (basename precedence) so the nested latency
    percentiles and the ISSUE 17 prefix-cache accounting are kept."""
    serve = {"metric": "serve_tokens_per_sec", "value": 812.5,
             "unit": "tokens/sec", "run_id": "r9",
             "ttft_ms": {"p50": 11.0, "p99": 30.5},
             "token_ms": {"p50": 2.0, "p99": 4.5},
             "decode_kernel": "kernel", "decode_step_ms": {"p50": 1.8,
                                                           "p99": 3.9},
             "prefix_cache": True, "prefix_hit_rate": 0.72,
             "prefill_tokens_saved": 4096}
    by_metric = {r["metric"]: r for r in
                 classify_artifact("SERVE.json", serve)}
    # ISSUE 18: decode-step wall is keyed by the served variant so the
    # kernel-on trajectory never checks against a fallback baseline
    assert by_metric["serve.decode.kernel.step_p99_ms"]["value"] == 3.9
    assert by_metric["serve.decode.kernel.step_p99_ms"]["unit"] == "ms"
    pre18 = {m for m in by_metric if "decode." in m}
    assert {r["metric"] for r in classify_artifact(
        "SERVE.json", {k: v for k, v in serve.items()
                       if not k.startswith("decode")})}.isdisjoint(pre18)
    assert by_metric["serve.tokens_per_sec"]["value"] == 812.5
    assert by_metric["serve.tokens_per_sec"]["kind"] == "serve"
    assert by_metric["serve.ttft_p99_ms"]["value"] == 30.5
    assert by_metric["serve.ttft_p99_ms"]["unit"] == "ms"
    assert by_metric["serve.token_p50_ms"]["value"] == 2.0
    assert by_metric["serve.prefix_hit_rate"]["value"] == 0.72
    assert by_metric["serve.prefill_tokens_saved"]["value"] == 4096
    assert all(r["run_id"] == "r9" for r in by_metric.values())
    # cache-off runs keep the prefix metrics OUT of the trajectory (their
    # zeros would poison the baseline median)
    off = {r["metric"] for r in classify_artifact(
        "SERVE.json", {**serve, "prefix_cache": False})}
    assert not any("prefix" in m for m in off)
    assert "serve.tokens_per_sec" in off
    # direction inference: hit rate and tokens saved improve upward
    assert not lower_is_better("serve.prefix_hit_rate", "rate")
    assert not lower_is_better("serve.prefill_tokens_saved", "tokens")


def test_classify_roofline_report():
    """ROOFLINE*.json (utils/roofline.py): whole-step aggregates enter
    the trajectory; per-op rows stay out (fusion boundaries rename them
    every compiler bump).  Label prefers the payload's ``model``, falling
    back to the filename stem — ROOFLINE_transformer_32k.json ships
    without a model key."""
    roof = {"steps_profiled": 4, "device_step_ms": 97.8,
            "time_share_at_half_roof": 0.97,
            "time_share_at_80pct_roof": 0.85,
            "model": "resnet50", "platform": "tpu",
            "ops": [{"op": "fusion.1", "time_ms_per_step": 3.2}]}
    by_metric = {r["metric"]: r for r in
                 classify_artifact("ROOFLINE.json", roof)}
    assert set(by_metric) == {
        "roofline.resnet50.device_step_ms",
        "roofline.resnet50.time_share_at_half_roof",
        "roofline.resnet50.time_share_at_80pct_roof"}
    assert by_metric["roofline.resnet50.device_step_ms"]["value"] == 97.8
    assert by_metric["roofline.resnet50.device_step_ms"]["unit"] == "ms"
    assert all(r["kind"] == "roofline" for r in by_metric.values())
    # no per-op records ever
    assert not any("fusion" in m for m in by_metric)
    # model-less artifact: the filename stem names the trajectory
    no_model = {k: v for k, v in roof.items() if k != "model"}
    stems = {r["metric"] for r in classify_artifact(
        "ROOFLINE_transformer_32k.json", no_model)}
    assert "roofline.transformer_32k.device_step_ms" in stems
    assert {r["metric"] for r in classify_artifact(
        "ROOFLINE.json", no_model)} == {
        "roofline.default.device_step_ms",
        "roofline.default.time_share_at_half_roof",
        "roofline.default.time_share_at_80pct_roof"}
    # direction inference: step time down, roof-proximity shares up
    assert lower_is_better("roofline.resnet50.device_step_ms", "ms")
    assert not lower_is_better(
        "roofline.resnet50.time_share_at_half_roof", "share")


def test_classify_unknown_shape_yields_nothing():
    assert classify_artifact("WHAT.json", {"stuff": 1}) == []
    assert classify_artifact("X.json", ["not", "a", "dict"]) == []


# -- the writer & crash contract ----------------------------------------------

def test_append_dedup_idempotent(tmp_path):
    led = PerfLedger(str(tmp_path / LEDGER_FILENAME))
    recs = [make_record("s", "bench", "m", 1.0, "ms", run_id="r1")]
    assert len(led.append(recs)) == 1
    assert led.append(recs) == []  # same fingerprint -> skipped
    assert len(led.records()) == 1


def test_torn_tail_skipped(tmp_path):
    path = str(tmp_path / LEDGER_FILENAME)
    _seed(path, "m", [1.0, 2.0], unit="ms")
    with open(path, "a") as f:
        f.write('{"schema": 1, "metric": "m", "val')  # the crash tear
    recs = read_ledger(path)
    assert [r["value"] for r in recs] == [1.0, 2.0]
    # appending after the tear still works; reader drops only the tear
    PerfLedger(path).append(
        [make_record("s", "bench", "m", 3.0, "ms", run_id="r2")])
    assert len(read_ledger(path)) == 3


def test_read_ledger_missing_and_foreign_lines(tmp_path):
    assert read_ledger(str(tmp_path / "nope.jsonl")) == []
    path = str(tmp_path / LEDGER_FILENAME)
    with open(path, "w") as f:
        f.write("not json\n")
        f.write(json.dumps({"schema": 99, "metric": "x"}) + "\n")
        f.write(json.dumps(make_record("s", "bench", "m", 1.0)) + "\n")
    assert len(read_ledger(path)) == 1


def test_snapshot_atomic(tmp_path):
    path = str(tmp_path / LEDGER_FILENAME)
    led = _seed(path, "m", [1.0, 2.0])
    out = led.snapshot()
    data = json.load(open(out))
    assert data["n_records"] == 2
    assert data["verdicts"][0]["metric"] == "m"
    assert not [p for p in os.listdir(str(tmp_path)) if ".tmp." in p]


# -- verdicts -----------------------------------------------------------------

def test_regression_throughput_collapse(tmp_path):
    """The acceptance fixture: healthy throughput then a 30% drop."""
    led = _seed(tmp_path / "l.jsonl", "bench.imgs_per_sec",
                [100.0, 101.0, 99.0, 100.0, 70.0])
    (v,) = led.check()
    assert v["verdict"] == "regression"
    assert v["direction"] == "higher_is_better"
    assert v["delta_pct"] == pytest.approx(-30.0, abs=1.0)
    assert regressions([v]) == [v]


def test_regression_latency_direction(tmp_path):
    up = _seed(tmp_path / "up.jsonl", "serve.ttft_p99_ms",
               [10.0, 10.5, 9.9, 14.0], unit="ms")
    (v,) = up.check()
    assert v["verdict"] == "regression"  # latency UP is a regression
    down = _seed(tmp_path / "down.jsonl", "serve.ttft_p99_ms",
                 [10.0, 10.5, 9.9, 7.0], unit="ms")
    (v,) = down.check()
    assert v["verdict"] == "improvement"


def test_within_tolerance_is_ok(tmp_path):
    led = _seed(tmp_path / "l.jsonl", "m", [100.0, 101.0, 95.0])
    (v,) = led.check(tolerance=0.10)
    assert v["verdict"] == "ok"
    (v,) = led.check(tolerance=0.01)
    assert v["verdict"] == "regression"  # tolerance is stated, not fixed


def test_single_point_insufficient_history(tmp_path):
    led = _seed(tmp_path / "l.jsonl", "m", [100.0])
    (v,) = led.check()
    assert v["verdict"] == "insufficient_history"
    assert v["baseline"] is None and v["delta_pct"] is None


def test_stub_runs_never_enter_baselines(tmp_path):
    path = str(tmp_path / LEDGER_FILENAME)
    led = _seed(path, "m", [100.0, 100.0])
    led.append([make_record("BENCH_r04.json", "backend_unavailable",
                            None, None, run_id="r04")])
    led.append([make_record("s", "bench", "m", 99.0, "images/sec",
                            run_id="r5")])
    traj = trajectories(led.records())
    assert list(traj) == ["m"] and len(traj["m"]) == 3
    (v,) = led.check()
    assert v["verdict"] == "ok"  # the stub is not a 0-valued baseline
    # but the log keeps the stub as the gap's witness
    assert sum(1 for r in led.records()
               if r["kind"] == "backend_unavailable") == 1


def test_trailing_window_bounds_baseline(tmp_path):
    # 10 old slow points, then 5 recent fast ones: the window must
    # baseline on the recent regime, so the latest fast point is "ok"
    led = _seed(tmp_path / "l.jsonl", "m",
                [10.0] * 10 + [100.0] * 5 + [101.0])
    (v,) = led.check(window=5)
    assert v["verdict"] == "ok"
    assert v["baseline"] == pytest.approx(100.0)


def test_check_records_empty():
    assert check_records([]) == []
    assert check_ledger("/nonexistent/ledger.jsonl") == []


# -- backfill over the repo's committed artifacts -----------------------------

def test_backfill_repo_artifacts_idempotent(tmp_path):
    led = PerfLedger(str(tmp_path / LEDGER_FILENAME))
    written = led.backfill(REPO)
    assert len(written) >= 10, "repo artifacts did not classify"
    assert led.backfill(REPO) == []  # fingerprint-idempotent
    # the committed rc=1 rounds arrive as stubs, excluded from baselines
    kinds = {r["kind"] for r in led.records()}
    assert "backend_unavailable" in kinds
    assert not regressions(led.check()), \
        "repo's own artifacts must not read as a regression"


def test_committed_repo_ledger_is_clean():
    """The PR ships a backfilled PERF_LEDGER.jsonl: it must read, parse
    and check clean (the acceptance's exit-0 half)."""
    path = os.path.join(REPO, LEDGER_FILENAME)
    records = read_ledger(path)
    assert len(records) >= 10, "committed ledger missing or empty"
    assert not regressions(check_ledger(path))


# -- tmprof --ledger exit contract --------------------------------------------

def test_tmprof_check_exits_1_on_regression(tmp_path, capsys):
    path = str(tmp_path / "l.jsonl")
    _seed(path, "bench.imgs_per_sec", [100.0, 101.0, 99.0, 100.0, 70.0])
    rc = prof.main(["--ledger", "check", "--ledger-path", path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "regression" in out and "bench.imgs_per_sec" in out


def test_tmprof_check_exits_0_on_repo_ledger(capsys):
    rc = prof.main(["--ledger", "check", "--ledger-path",
                    os.path.join(REPO, LEDGER_FILENAME)])
    capsys.readouterr()
    assert rc == 0


def test_tmprof_check_json(tmp_path, capsys):
    path = str(tmp_path / "l.jsonl")
    _seed(path, "m", [100.0, 100.0, 100.0])
    rc = prof.main(["--ledger", "check", "--ledger-path", path, "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["verdicts"][0]["verdict"] == "ok"


def test_tmprof_update_and_show(tmp_path, capsys):
    art = tmp_path / "BENCH_r01.json"
    art.write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 0,
         "parsed": {"metric": "imgs_per_sec", "value": 100.0,
                    "unit": "images/sec", "run_id": "r1"}}))
    path = str(tmp_path / "l.jsonl")
    rc = prof.main(["--ledger", "update", str(art), "--ledger-path", path])
    assert rc == 0
    assert "ingested 1 new record(s)" in capsys.readouterr().out
    assert os.path.exists(str(tmp_path / "PERF_LEDGER.json"))
    rc = prof.main(["--ledger", "show", "--ledger-path", path])
    assert rc == 0
    assert "imgs_per_sec" in capsys.readouterr().out


def test_tmprof_ledger_usage_errors(tmp_path, capsys):
    # update without artifacts; missing artifact; check without a ledger
    assert prof.main(["--ledger", "update",
                      "--ledger-path", str(tmp_path / "l.jsonl")]) == 2
    assert prof.main(["--ledger", "update", str(tmp_path / "nope.json"),
                      "--ledger-path", str(tmp_path / "l.jsonl")]) == 2
    assert prof.main(["--ledger", "check",
                      "--ledger-path", str(tmp_path / "nope.jsonl")]) == 2
    assert prof.main(["--ledger", "backfill", str(tmp_path / "nodir"),
                      "--ledger-path", str(tmp_path / "l.jsonl")]) == 2
    capsys.readouterr()


def test_tmprof_backfill_cli(tmp_path, capsys):
    art = tmp_path / "SCALING.json"
    art.write_text(json.dumps(
        {"model": "wrn", "strategy": "psum",
         "per_n": {"1": {"imgs_per_sec": 50.0}}}))
    path = str(tmp_path / "l.jsonl")
    rc = prof.main(["--ledger", "backfill", str(tmp_path),
                    "--ledger-path", path])
    assert rc == 0
    assert "backfilled 1 record(s)" in capsys.readouterr().out


# -- the bench.py hook --------------------------------------------------------

def test_bench_ledger_append_env_override(tmp_path, monkeypatch):
    path = str(tmp_path / "l.jsonl")
    monkeypatch.setenv("BENCH_LEDGER", path)
    bench_ledger_append({"metric": "imgs_per_sec", "value": 123.0,
                         "unit": "images/sec", "run_id": "r1"}, "bench.wrn")
    (rec,) = read_ledger(path)
    assert rec["metric"] == "imgs_per_sec" and rec["source"] == "bench.wrn"


def test_bench_ledger_append_disabled_and_safe(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_LEDGER", "0")
    bench_ledger_append({"metric": "m", "value": 1.0}, "s",
                        repo_dir=str(tmp_path))
    assert not os.path.exists(str(tmp_path / LEDGER_FILENAME))
    # an unwritable destination must not raise (the bench line wins):
    # the parent "directory" is a regular file, so the append fails inside
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    monkeypatch.setenv("BENCH_LEDGER", str(blocker / "l.jsonl"))
    bench_ledger_append({"metric": "m", "value": 1.0}, "s")


def test_classify_converge_margin_records():
    """ISSUE 20: CONVERGE.json rows become higher-is-better margin
    records (target_error - best_val_error) the trend gate can hold a
    chaos acceptance to; rows without both numbers are skipped."""
    conv = {"run_id": "r20", "results": [
        {"model": "wrn_easgd", "rule": "EASGD", "target_error": 0.50,
         "best_val_error": 0.42, "passed": True, "epochs_to_target": 3},
        {"model": "incomplete", "target_error": 0.5},
        "not-a-row",
    ]}
    (rec,) = classify_artifact("CONVERGE.json", conv)
    assert rec["metric"] == "converge.wrn_easgd.margin"
    assert rec["value"] == pytest.approx(0.08)
    assert rec["kind"] == "converge" and rec["extra"]["rule"] == "EASGD"
    assert rec["extra"]["passed"] is True
    assert rec["extra"]["epochs_to_target"] == 3
    # margin trends UPWARD: a shrinking margin is the regression
    assert not lower_is_better("converge.wrn_easgd.margin", "margin")
    # the backfill sweep picks the artifact up
    from theanompi_tpu.telemetry.ledger import BACKFILL_PATTERNS
    import fnmatch
    assert any(fnmatch.fnmatch("CONVERGE.json", p)
               for p in BACKFILL_PATTERNS)
