"""EASGD and GOSGD rule tests on the fake 8-device mesh.

Covers the semantic invariants the reference's async rules promise
(SURVEY.md §3.3/§3.4, unverified): elastic-averaging math, gossip weight
conservation and uniform-peer routing, divergence between exchanges, and
end-to-end training (loss decreases through the rule facade).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu import EASGD, GOSGD
from theanompi_tpu.parallel.easgd import EASGDTrainer, elastic_exchange
from theanompi_tpu.parallel.gosgd import GOSGDTrainer, gossip_merge
from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map

TINY = {
    "depth": 10,
    "widen": 1,
    "batch_size": 8,
    "image_size": 16,
    "n_train": 256,
    "n_val": 64,
    "n_epochs": 2,
    "precision": "fp32",
    "lr": 0.05,
}


def test_elastic_exchange_math(mesh8):
    """p_i <- p_i - a(p_i - c);  c <- c + a*sum_i(p_i - c)  — exactly."""
    n, alpha = 8, 0.1
    p = np.arange(n, dtype=np.float32).reshape(n, 1) + 1.0  # worker i holds i+1
    c = np.zeros((1,), np.float32)

    f = jax.jit(
        shard_map(
            lambda p, c: elastic_exchange(
                jax.tree.map(lambda x: x[0], p), c, alpha
            ),
            mesh8,
            in_specs=(P(DATA_AXIS), P()),
            out_specs=(P(DATA_AXIS), P()),
        )
    )
    new_p, new_c = f(
        jax.device_put(p[:, None], NamedSharding(mesh8, P(DATA_AXIS))), c
    )
    expect_p = (p - alpha * (p - c)).reshape(-1)
    expect_c = (c + alpha * np.sum(p - c)).reshape(-1)
    np.testing.assert_allclose(np.asarray(new_p).reshape(-1), expect_p, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_c).reshape(-1), expect_c, rtol=1e-6)


def test_gossip_merge_shift_routing(mesh8):
    """Pusher i's payload lands at (i+shift)%n with halved weight; Σw = 1."""
    n = 8
    p = {"w": np.arange(n, dtype=np.float32).reshape(n, 1)}
    weights = np.full((n,), 1.0 / n, np.float32)
    push = np.zeros((n,), np.float32)
    push[2] = 1.0  # only worker 2 pushes
    shift = 3      # -> target worker 5

    def g(params, weight, push, shift):
        new_p, new_w = gossip_merge(
            jax.tree.map(lambda x: x[0], params),
            jax.tree.map(lambda x: x[0], weight),
            push,
            shift,
            n,
        )
        return jax.tree.map(lambda x: x[None], new_p), new_w[None]

    f = jax.jit(
        shard_map(
            g,
            mesh8,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        )
    )
    sh = NamedSharding(mesh8, P(DATA_AXIS))
    new_p, new_w = f(
        jax.device_put(p, sh), jax.device_put(weights, sh),
        jnp.asarray(push), jnp.int32(shift),
    )
    new_p, new_w = np.asarray(new_p["w"])[:, 0], np.asarray(new_w)

    w0 = 1.0 / n
    # sender halves its weight, params unchanged
    assert np.isclose(new_w[2], w0 / 2)
    assert np.isclose(new_p[2], 2.0)
    # receiver merges: (w0*5 + w0/2*2) / (w0 + w0/2)
    assert np.isclose(new_w[5], w0 * 1.5)
    assert np.isclose(new_p[5], (w0 * 5.0 + w0 / 2 * 2.0) / (w0 * 1.5), rtol=1e-6)
    # bystanders untouched; total weight conserved
    for i in (0, 1, 3, 4, 6, 7):
        assert np.isclose(new_w[i], w0) and np.isclose(new_p[i], float(i))
    assert np.isclose(new_w.sum(), 1.0)


def test_gossip_all_push_all_shifts(mesh8):
    """Every (all-push, shift) round conserves Σw and the weighted mean."""
    n = 8
    p = {"w": np.random.RandomState(0).randn(n, 3).astype(np.float32)}
    weights = np.random.RandomState(1).rand(n).astype(np.float32)
    weights /= weights.sum()
    consensus = np.einsum("i,ij->j", weights, p["w"])

    def g(params, weight, push, shift):
        new_p, new_w = gossip_merge(
            jax.tree.map(lambda x: x[0], params),
            jax.tree.map(lambda x: x[0], weight),
            push, shift, n,
        )
        return jax.tree.map(lambda x: x[None], new_p), new_w[None]

    f = jax.jit(
        shard_map(
            g, mesh8,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        )
    )
    sh = NamedSharding(mesh8, P(DATA_AXIS))
    push = np.ones((n,), np.float32)
    for shift in range(1, n):
        new_p, new_w = f(
            jax.device_put(p, sh), jax.device_put(weights, sh),
            jnp.asarray(push), jnp.int32(shift),
        )
        new_w = np.asarray(new_w)
        assert np.isclose(new_w.sum(), 1.0, atol=1e-6)
        new_consensus = np.einsum("i,ij->j", new_w, np.asarray(new_p["w"]))
        np.testing.assert_allclose(new_consensus, consensus, rtol=1e-5)


@pytest.mark.slow
def test_easgd_e2e(mesh8):
    rule = EASGD(config={"tau": 2, "verbose": False, "print_freq": 2,
                         "scale_lr": False})
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config={**TINY, "n_epochs": 4})
    rec = rule.wait()
    costs = rec.train_history["cost"]
    h = len(costs) // 2
    assert np.mean(costs[h:]) < np.mean(costs[:h]), f"no learning: {costs}"
    assert rec.val_history["error"], "no validation recorded"
    # exchange happened: comm segment recorded
    assert sum(rec.time_history["comm"]) > 0


@pytest.mark.slow
def test_easgd_workers_diverge_between_exchanges(mesh8):
    rule = EASGD(config={"tau": 1000, "verbose": False, "scale_lr": False})
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY, "n_epochs": 1})
    t = rule.trainer
    for batch in t.model.data.train_batches(t.global_batch, 0, seed=0):
        t.train_iter(batch, lr=0.05)
    leaf = np.asarray(jax.tree.leaves(t.params)[0])
    assert leaf.shape[0] == 8
    # different data per worker, no exchange before tau -> divergent params
    assert not np.allclose(leaf[0], leaf[1])


@pytest.mark.slow
def test_gosgd_e2e(mesh8):
    rule = GOSGD(config={"p_push": 0.5, "verbose": False, "print_freq": 2})
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config={**TINY, "n_epochs": 4})
    rec = rule.wait()
    costs = rec.train_history["cost"]
    h = len(costs) // 2
    assert np.mean(costs[h:]) < np.mean(costs[:h]), f"no learning: {costs}"
    w = np.asarray(rule.trainer.weights)
    assert np.isclose(w.sum(), 1.0, atol=1e-5)
    assert (w > 0).all()


@pytest.mark.parametrize("cls_name", ["EASGDTrainer", "GOSGDTrainer"])
def test_async_rules_refuse_sharded_model_axes(cls_name):
    """Async rules are data-parallel only: a tp/pp mesh must be refused
    loudly — their stacked-param layout ignores model param_specs, so TP
    collectives would silently double-count."""
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.easgd import EASGDTrainer
    from theanompi_tpu.parallel.gosgd import GOSGDTrainer
    from theanompi_tpu.parallel.mesh import make_mesh

    cls = {"EASGDTrainer": EASGDTrainer, "GOSGDTrainer": GOSGDTrainer}[cls_name]
    mesh = make_mesh(n_data=2, n_model=2, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="data-parallel only"):
        cls(WideResNet({**TINY, "n_epochs": 1}), mesh=mesh)


def test_easgd_single_worker_exact_exchange():
    """n=1 elastic exchange is exact: p' = p - a(p-c), c' = c + a(p-c)."""
    from theanompi_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    from theanompi_tpu.models.wide_resnet import WideResNet

    model = WideResNet({**TINY, "n_epochs": 1})
    t = EASGDTrainer(model, mesh=mesh, tau=10**9)  # no exchange inside step
    assert t.alpha == 0.9  # paper default 0.9/n at n=1
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    t.train_iter(batch, lr=0.05)  # diverge worker from center
    leaf = lambda tree, i: np.asarray(jax.tree.leaves(tree)[i])
    p0, c0 = leaf(t.params, 0)[0].copy(), leaf(t.center, 0).copy()
    assert not np.allclose(p0, c0)  # the step must have moved the worker
    new_p, new_c, drift = t._exchange_fn(t.params, t.center)
    assert float(drift[0]) > 0.0  # pre-exchange divergence is measured
    a = t.alpha
    np.testing.assert_allclose(
        leaf(new_p, 0)[0], p0 - a * (p0 - c0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        leaf(new_c, 0), c0 + a * (p0 - c0), rtol=1e-5, atol=1e-6)
