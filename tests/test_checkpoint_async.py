"""ISSUE 3 tentpole: async checkpoint engine + epoch-boundary overlap.

Covers the acceptance matrix on the 4-device CPU mesh: async-vs-sync
bit-identical published ``.npz`` contents, ``try_resume`` round-trips
(including ``zero1``, whose opt state is sharded flat buckets), a
slow-writer injection proving ``save_checkpoint`` returns before the write
completes, writer-exception surfacing at the next save/join, crash-mid-write
recovery (tmp debris swept, resume from the previous epoch), prune ignoring
crash debris, and ``checkpoint.snapshot`` / ``checkpoint.write`` span
disjointness in the telemetry JSONL.
"""

import os
import time

import numpy as np
import pytest

import jax

from theanompi_tpu.utils.checkpoint import Checkpointer

TINY = {"depth": 10, "widen": 1, "batch_size": 8, "image_size": 8,
        "n_train": 32, "n_val": 16, "n_epochs": 1, "precision": "fp32",
        "augment": False, "verbose": False, "lr": 0.05}

TREE = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": {"c": np.ones((4,), np.int32)}}


def _tiny_trainer(mesh4, strategy="psum", checkpoint_dir=None,
                  telemetry=None, checkpoint_async=True, n_epochs=1):
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.utils.recorder import Recorder

    t = BSPTrainer(
        WideResNet({**TINY, "n_epochs": n_epochs}), mesh=mesh4,
        exch_strategy=strategy,
        recorder=Recorder(verbose=False, print_freq=4),
        checkpoint_dir=checkpoint_dir, checkpoint_async=checkpoint_async,
        telemetry=telemetry,
    )
    t.compile_iter_fns()
    t.init_state()
    return t


def _npz_contents(path):
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def test_async_and_sync_publish_bit_identical(tmp_path, mesh4):
    """Same train state through both modes -> byte-equal array payloads
    (one shared ``_write`` path is the design guarantee; this locks it)."""
    trainer = _tiny_trainer(mesh4)
    batch = next(iter(trainer.model.data.train_batches(
        trainer.global_batch, 0, seed=0)))
    trainer.train_iter(batch, lr=0.05)
    trees = trainer.checkpoint_trees()

    sync_ck = Checkpointer(str(tmp_path / "sync"), async_save=False)
    sync_ck.save(0, 4, trees)
    async_ck = Checkpointer(str(tmp_path / "async"), async_save=True)
    handle = async_ck.save(0, 4, trees)
    handle.join()

    a = _npz_contents(sync_ck._path(0))
    b = _npz_contents(async_ck._path(0))
    assert sorted(a) == sorted(b)
    for k in a:
        assert a[k].dtype == b[k].dtype and a[k].shape == b[k].shape, k
        assert a[k].tobytes() == b[k].tobytes(), k


@pytest.mark.parametrize("strategy", ["psum", "zero1"])
def test_async_resume_roundtrip(tmp_path, mesh4, strategy):
    """Full run with async checkpointing resumes exactly — including
    ``zero1``, whose opt state is flat buckets sharded over ``data``."""
    ck = str(tmp_path / "ck")
    trainer = _tiny_trainer(mesh4, strategy=strategy, checkpoint_dir=ck)
    trainer.run()
    params = jax.tree.map(np.asarray, trainer.params)
    opt = jax.tree.map(np.asarray, trainer.opt_state)
    iters = trainer.iteration

    t2 = _tiny_trainer(mesh4, strategy=strategy, checkpoint_dir=ck)
    assert t2.try_resume()
    assert t2.epoch == 1 and t2.iteration == iters
    for a, b in zip(jax.tree.leaves(t2.params), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(t2.opt_state), jax.tree.leaves(opt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_save_checkpoint_returns_before_write_completes(tmp_path, mesh4):
    """Slow-writer injection: the boundary pays only the snapshot; the
    publish happens later on the writer thread."""
    trainer = _tiny_trainer(mesh4, checkpoint_dir=str(tmp_path / "ck"))
    trainer.checkpointer._pre_publish_hook = lambda epoch: time.sleep(0.8)
    t0 = time.perf_counter()
    handle = trainer.save_checkpoint(0)
    returned_in = time.perf_counter() - t0
    assert returned_in < 0.6, f"save_checkpoint blocked {returned_in:.2f}s"
    assert not handle.done(), "writer should still be running"
    assert not os.path.exists(handle.path), "published before the join!"
    handle.join()
    assert os.path.exists(handle.path)
    # the recorder histories were written by the writer too (satellite:
    # the boundary pays neither write)
    assert os.path.exists(tmp_path / "ck" / "time_history.npy")


def test_writer_exception_surfaces_at_next_save(tmp_path):
    ck = Checkpointer(str(tmp_path), async_save=True)

    def boom(epoch):
        raise ValueError("disk full")

    ck._pre_publish_hook = boom
    ck.save(0, 1, {"params": TREE})
    with pytest.raises(ValueError, match="disk full"):
        ck.save(1, 2, {"params": TREE})  # join_pending re-raises here
    # delivered exactly once; the engine keeps working afterwards
    ck._pre_publish_hook = None
    h = ck.save(2, 3, {"params": TREE})
    h.join()
    assert ck.latest_epoch() == 2


def test_writer_exception_surfaces_at_join(tmp_path):
    ck = Checkpointer(str(tmp_path), async_save=True)

    def boom(epoch):
        raise RuntimeError("torn write")

    ck._pre_publish_hook = boom
    handle = ck.save(0, 1, {"params": TREE})
    with pytest.raises(RuntimeError, match="torn write"):
        handle.join()


def test_crash_mid_write_resumes_previous_epoch(tmp_path, mesh4):
    """Kill the writer before ``os.replace``: the tmp debris must not count
    as a checkpoint, and a restarted process resumes from the previous
    epoch's published state."""
    ck_dir = str(tmp_path / "ck")
    trainer = _tiny_trainer(mesh4, checkpoint_dir=ck_dir, n_epochs=2)
    trainer.run()  # publishes epochs 0 and 1
    params_e1 = jax.tree.map(np.asarray, trainer.params)

    # epoch 2's save dies after serialization, before the atomic publish
    def crash(epoch):
        raise RuntimeError("simulated kill before publish")

    trainer.checkpointer._pre_publish_hook = crash
    trainer.iteration += 1
    handle = trainer.save_checkpoint(2)
    with pytest.raises(RuntimeError, match="simulated kill"):
        handle.join()
    debris = [f for f in os.listdir(ck_dir) if f.endswith(".tmp.npz")]
    assert debris == ["ckpt_e0002.npz.tmp.npz"]

    # "restart": a fresh trainer sweeps the debris and resumes from the
    # last PUBLISHED epoch (1), with its exact params
    t2 = _tiny_trainer(mesh4, checkpoint_dir=ck_dir, n_epochs=2)
    assert not any(f.endswith(".tmp.npz") for f in os.listdir(ck_dir))
    assert t2.try_resume()
    assert t2.epoch == 2  # epoch 1 completed; 2 is the resume point
    for a, b in zip(jax.tree.leaves(t2.params), jax.tree.leaves(params_e1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prune_ignores_tmp_debris(tmp_path):
    """A leftover ``ckpt_eNNNN.npz.tmp.npz`` startswith ``ckpt_e`` and
    endswith ``.npz`` — it must not consume a retention slot or shift which
    real checkpoints get deleted."""
    ck = Checkpointer(str(tmp_path), keep=2)
    for e in range(3):
        ck.save(e, e, {"params": TREE})
    debris = tmp_path / "ckpt_e0003.npz.tmp.npz"
    debris.touch()
    ck.save(4, 4, {"params": TREE})
    real = sorted(f for f in os.listdir(tmp_path)
                  if f.startswith("ckpt_e") and f.endswith(".npz")
                  and not f.endswith(".tmp.npz"))
    # keep=2 of the REAL checkpoints: 2 and 4 survive (debris uncounted;
    # since ISSUE 5 each survivor also has its ckpt_eNNNN.manifest.json)
    assert real == ["ckpt_e0002.npz", "ckpt_e0004.npz"]
    assert sorted(f for f in os.listdir(tmp_path)
                  if f.endswith(".manifest.json")) == [
        "ckpt_e0002.manifest.json", "ckpt_e0004.manifest.json"]
    assert debris.exists()  # prune never deletes debris; init sweeps it
    ck2 = Checkpointer(str(tmp_path), keep=2)
    assert not debris.exists()
    assert ck2.latest_epoch() == 4


def test_snapshot_and_write_spans_disjoint(tmp_path, mesh4):
    """Acceptance: the training-thread ``checkpoint.snapshot`` span ends
    before the writer's ``checkpoint.write`` span begins, on distinct
    threads, with byte accounting on the write."""
    from theanompi_tpu.telemetry import Telemetry
    from theanompi_tpu.telemetry.sink import read_events, sink_files

    tel_dir = str(tmp_path / "tel")
    tel = Telemetry(tel_dir)
    trainer = _tiny_trainer(mesh4, checkpoint_dir=str(tmp_path / "ck"),
                            telemetry=tel)
    trainer.run()
    tel.close()

    events = []
    for p in sink_files(tel_dir):
        events.extend(read_events(p))
    snaps = [e for e in events
             if e["kind"] == "span" and e["name"] == "checkpoint.snapshot"]
    writes = [e for e in events
              if e["kind"] == "span" and e["name"] == "checkpoint.write"]
    assert len(snaps) == 1 and len(writes) == 1
    snap, write = snaps[0], writes[0]
    assert snap["ts"] + snap["dur"] <= write["ts"], (
        "snapshot and write overlap")
    assert snap["tid"] != write["tid"], "write ran on the training thread"
    assert write["bytes"] > 0 and write["dur"] > 0
    # the old monolithic span is gone
    assert not any(e.get("name") == "checkpoint.save" for e in events)


def test_next_epoch_prefetcher_built_before_boundary(tmp_path, mesh4,
                                                     monkeypatch):
    """Satellite: the next epoch's prefetcher exists (queue refilling)
    before validate/checkpoint run at the boundary."""
    import theanompi_tpu.parallel.trainer as trainer_mod

    trainer = _tiny_trainer(mesh4, checkpoint_dir=str(tmp_path / "ck"),
                            n_epochs=2)
    order = []
    built = []

    orig_make = trainer_mod.BaseTrainer._make_prefetcher
    orig_validate = trainer_mod.BaseTrainer.validate
    orig_save = trainer_mod.BaseTrainer.save_checkpoint

    monkeypatch.setattr(
        trainer_mod.BaseTrainer, "_make_prefetcher",
        lambda self, epoch, start_batch=0: (
            order.append(("prefetch", epoch)),
            built.append(epoch),
            orig_make(self, epoch, start_batch))[-1])
    monkeypatch.setattr(
        trainer_mod.BaseTrainer, "validate",
        lambda self, epoch: (order.append(("validate", epoch)),
                             orig_validate(self, epoch))[-1])
    monkeypatch.setattr(
        trainer_mod.BaseTrainer, "save_checkpoint",
        lambda self, epoch: (order.append(("checkpoint", epoch)),
                             orig_save(self, epoch))[-1])

    trainer.run()
    assert built == [0, 1]  # one per epoch, none for past-the-end
    # at the epoch-0 boundary: epoch 1's prefetcher precedes validate(0)
    # and checkpoint(0)
    assert order.index(("prefetch", 1)) < order.index(("validate", 0))
    assert order.index(("validate", 0)) < order.index(("checkpoint", 0))
