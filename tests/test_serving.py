"""ISSUE 6 serving path: paged KV cache, continuous batching, int8 weights.

The contract under test, end to end on the CPU mesh:

- **parity** — incremental decode through the paged cache reproduces the
  full-sequence forward logits within float round-off, dense AND MoE;
- **continuous batching** — >= 8 concurrent synthetic requests through a
  block pool too small for the worst case: sequences join and leave
  mid-flight, the pool exhausts, the longest sequence is preempted and
  recomputed, and every greedy output is STILL bit-equal to the batched
  full-forward argmax reference;
- **int8** — quantized weights serve the same smoke with >= 99% argmax
  agreement against fp32;
- **read-only load** — ``load_for_inference`` restores through the
  verified chain without writing anything into a live trainer's directory;
- **telemetry** — serve.prefill/serve.decode spans export to a Chrome
  trace, disjoint, with per-request ids threaded.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.models.transformer_lm import MoETransformerLM, TransformerLM
from theanompi_tpu.serving import (
    BlockPool,
    InferenceEngine,
    Request,
    Scheduler,
    blocks_for,
    run_open_loop,
    sample_tokens,
    serve_report,
)
from theanompi_tpu.serving.quant import dequantize_tree, quantize_tree

# the lightly-trained ``dense_model`` fixture lives in conftest.py at
# session scope (ISSUE 11 satellite) — shared with any file that needs
# trained-LM weights; its config is imported here as TINY so per-test
# references can't drift from what the fixture trained
from conftest import SERVING_TINY as TINY  # noqa: E402


def _full_argmax_ref(model, params, state, seq):
    """Per-position argmax of the batched full forward over ``seq`` (end-
    padded to seq_len — causality keeps the padding out of real logits)."""
    toks = np.zeros((1, model.config["seq_len"]), np.int32)
    toks[0, : len(seq)] = seq
    logits = np.asarray(model.apply_logits(params, state, jnp.asarray(toks)))
    return logits[0]


def _assert_greedy_trace_matches(model, params, state, req):
    full = req.prompt + req.generated
    ref = _full_argmax_ref(model, params, state, full)
    for i in range(len(req.prompt) - 1, len(full) - 1):
        assert int(ref[i].argmax()) == full[i + 1], (
            f"request {req.rid}: token at position {i + 1} diverges from "
            f"the full-forward argmax reference")


# -- block pool ---------------------------------------------------------------

def test_block_pool_alloc_free_all_or_nothing():
    pool = BlockPool(6)  # block 0 reserved -> 5 usable
    assert pool.free_blocks == 5
    got = pool.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert pool.alloc(3) is None  # only 2 left: all-or-nothing
    assert pool.free_blocks == 2
    pool.free(got)
    assert pool.free_blocks == 5
    with pytest.raises(ValueError, match="double free"):
        pool.free([got[0], got[0]])
    with pytest.raises(ValueError, match="outside pool"):
        pool.free([0])
    assert blocks_for(5, 4) == 2 and blocks_for(8, 4) == 2


# -- prefill/decode parity ----------------------------------------------------

def _decode_parity(model, params, state, prompt_len=5, n_decode=12,
                   engine=None):
    """Drive prefill + incremental decode on slot 0; compare every decode
    step's logits against the full-forward logits at the same position."""
    if engine is None:
        engine = InferenceEngine(model, params, block_size=4, max_batch=2,
                                 seed=0)
    rng = np.random.RandomState(3)
    vocab = model.data.vocab
    prompt = [int(x) for x in rng.randint(0, vocab, prompt_len)]
    n_blocks = blocks_for(prompt_len, 4)
    pool = BlockPool(engine.num_blocks)
    row = pool.alloc(n_blocks)
    tok, last = engine.prefill(row, prompt, 0.0, rid=1)

    seq = list(prompt)
    nb = engine.max_blocks_per_seq
    tables = np.zeros((2, nb), np.int32)
    tables[0, :n_blocks] = row
    lengths = np.zeros(2, np.int32)
    lengths[0] = len(prompt)
    tokens = np.zeros(2, np.int32)
    tokens[0] = tok
    temps = np.zeros(2, np.float32)
    rids = np.zeros(2, np.int32)
    rids[0] = 1
    seq.append(tok)
    per_step_logits = [(len(prompt) - 1, np.asarray(last))]
    for _ in range(n_decode):
        if lengths[0] % engine.block_size == 0:
            new = pool.alloc(1)
            tables[0, lengths[0] // engine.block_size] = new[0]
        nxt, logits = engine.decode(tables, lengths, tokens, temps, rids)
        per_step_logits.append((int(lengths[0]), np.asarray(logits[0])))
        lengths[0] += 1
        tokens[0] = int(nxt[0])
        seq.append(int(nxt[0]))

    ref = _full_argmax_ref(model, params, state, seq)
    for pos, got in per_step_logits:
        np.testing.assert_allclose(
            got, ref[pos], rtol=1e-4, atol=1e-4,
            err_msg=f"decode logits at position {pos} diverge from the "
            f"full-sequence forward")
        assert int(ref[pos].argmax()) == seq[pos + 1]


def test_prefill_decode_parity_dense(dense_model, serving_engine):
    model, params, state = dense_model
    _decode_parity(model, params, state, engine=serving_engine)


def test_prefill_decode_parity_moe():
    """MoE variant: capacity_factor >= n_experts puts routing in the
    no-drop regime, where per-step routing is exactly the full-sequence
    routing (the documented equivalence in ops/moe.py) — so incremental
    decode must match the full forward like the dense block."""
    cfg = {**TINY, "n_experts": 4, "capacity_factor": 4.0,
           "moe_aux_weight": 0.01}
    model = MoETransformerLM(cfg)
    params, state = model.init_params(jax.random.PRNGKey(1))
    _decode_parity(model, params, state, prompt_len=6, n_decode=8)


# -- sampling -----------------------------------------------------------------

def test_sample_tokens_greedy_temperature_topk():
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 16), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    greedy = sample_tokens(logits, jnp.zeros((4,)), keys)
    assert (np.asarray(greedy) == np.asarray(logits).argmax(-1)).all()
    # temperature sampling is reproducible under the same keys...
    s1 = sample_tokens(logits, jnp.full((4,), 1.0), keys)
    s2 = sample_tokens(logits, jnp.full((4,), 1.0), keys)
    assert (np.asarray(s1) == np.asarray(s2)).all()
    # ...and top-k=1 collapses to argmax at any temperature
    s3 = sample_tokens(logits, jnp.full((4,), 5.0), keys, top_k=1)
    assert (np.asarray(s3) == np.asarray(logits).argmax(-1)).all()
    # mixed rows: temp 0 rows take the argmax path
    mixed = sample_tokens(logits, jnp.asarray([0.0, 1.0, 0.0, 1.0]), keys)
    m = np.asarray(mixed)
    assert m[0] == np.asarray(logits)[0].argmax()
    assert m[2] == np.asarray(logits)[2].argmax()


# -- continuous batching smoke ------------------------------------------------

def test_continuous_batching_smoke_with_eviction(dense_model):
    """The acceptance smoke: 12 requests (>= 8 concurrent demand) through 4
    decode slots and a block pool sized ~40% of worst case — sequences
    join/leave mid-flight, preemption fires, and every greedy output is
    bit-equal to the batched full-forward argmax reference; the report
    carries tokens/sec + p50/p99 latency."""
    model, params, state = dense_model
    # worst case: 12 requests x 6 blocks (8 prompt + 16 new = 24 tok / 4)
    # + null = 73; max_batch 4 alone would hold 24+1.  20 usable blocks
    # cannot hold 4 full sequences -> the pool must exhaust mid-decode.
    engine = InferenceEngine(model, params, block_size=4, max_batch=4,
                             num_blocks=21, seed=0)
    sched = Scheduler(engine)
    rng = np.random.RandomState(0)
    reqs = [Request(rid=i,
                    prompt=[int(x) for x in rng.randint(0, 61, 8)],
                    max_new_tokens=16)
            for i in range(12)]
    results, wall = run_open_loop(sched, reqs)
    assert len(results) == 12
    assert all(len(r.generated) == 16 for r in results.values())
    assert sched.n_preemptions > 0, (
        "pool was sized to force eviction but none happened — the "
        "continuous-batching pressure path went untested")
    # joins/leaves mid-flight: more requests than slots means the batch
    # composition changed while decoding
    assert sched.n_steps > 16  # > one straight-through batch's steps
    for req in results.values():
        _assert_greedy_trace_matches(model, params, state, req)
    rep = serve_report(results, wall, sched)
    assert rep["value"] > 0 and rep["unit"] == "tokens/sec"
    assert rep["generated_tokens"] == 12 * 16
    assert "p50" in rep["ttft_ms"] and "p99" in rep["ttft_ms"]
    assert "p50" in rep["token_ms"] and "p99" in rep["token_ms"]
    assert rep["preemptions"] == sched.n_preemptions


def test_preemption_recompute_is_deterministic(dense_model):
    """The same requests served WITHOUT pool pressure produce identical
    token streams: preemption + recompute-prefill changes scheduling, not
    results (sampling keys derive from (request, position) only)."""
    model, params, state = dense_model
    rng = np.random.RandomState(7)
    prompts = [[int(x) for x in rng.randint(0, 61, 6)] for _ in range(6)]

    def serve_all(num_blocks):
        engine = InferenceEngine(model, params, block_size=4, max_batch=3,
                                 num_blocks=num_blocks, seed=0)
        sched = Scheduler(engine)
        reqs = [Request(rid=i, prompt=list(p), max_new_tokens=12,
                        temperature=0.8 if i % 2 else 0.0)
                for i, p in enumerate(prompts)]
        results, _ = run_open_loop(sched, reqs)
        return {i: r.generated for i, r in results.items()}, sched

    tight, sched_tight = serve_all(num_blocks=12)
    roomy, sched_roomy = serve_all(num_blocks=3 * 5 + 1)
    assert sched_tight.n_preemptions > 0
    assert sched_roomy.n_preemptions == 0
    assert tight == roomy


def test_scheduler_refuses_oversized_and_impossible_requests(dense_model):
    model, params, _ = dense_model
    engine = InferenceEngine(model, params, block_size=4, max_batch=2,
                             num_blocks=5, seed=0)
    sched = Scheduler(engine)
    with pytest.raises(ValueError, match="max context"):
        sched.submit(Request(rid=0, prompt=[1] * 30, max_new_tokens=16))
    with pytest.raises(ValueError, match="num_blocks too small"):
        sched.submit(Request(rid=1, prompt=[1] * 8, max_new_tokens=12))
    with pytest.raises(ValueError, match="empty prompt"):
        sched.submit(Request(rid=2, prompt=[], max_new_tokens=4))


# -- int8 quantization --------------------------------------------------------

def test_quantize_tree_selects_matmul_weights(dense_model):
    model, params, _ = dense_model
    qtree, stats = quantize_tree(params, jax.random.PRNGKey(0))
    assert stats["quantized_leaves"] > 0
    assert stats["bytes_after"] < 0.35 * stats["bytes_before"]
    # embeddings / positions / LN stay full precision
    flat = jax.tree_util.tree_flatten_with_path(
        qtree, is_leaf=lambda x: hasattr(x, "dequantize"))[0]
    for path, leaf in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if "embedding" in name or "ln" in name:
            assert not hasattr(leaf, "dequantize"), name
    # round trip: per-chunk int8 with stochastic rounding stays within
    # ~1.2% of each chunk's max-abs, and is deterministic in the key
    deq = dequantize_tree(qtree)
    w = np.asarray(params["head"]["w"])
    wq = np.asarray(deq["head"]["w"])
    assert wq.shape == w.shape and wq.dtype == w.dtype
    assert np.abs(wq - w).max() <= 1.2 * np.abs(w).max() / 127.0
    qtree2, _ = quantize_tree(params, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(qtree["head"]["w"].q),
                                  np.asarray(qtree2["head"]["w"].q))


def test_int8_engine_serves_smoke_with_argmax_agreement(dense_model):
    """Acceptance: the int8 engine serves the same smoke (same pool
    pressure, eviction and all); teacher-forced on the int8 engine's own
    trajectories (identical contexts per comparison, so one flipped token
    cannot cascade into a false failure), quantization must NEVER flip an
    argmax the fp32 model actually decided (top-2 logit margin >= 0.1 —
    the overall median margin on this fixture is ~1.4, while int8 rounding
    perturbs logits by ~1e-2), and >= 95% agreement overall including the
    near-tied positions."""
    model, params, state = dense_model
    engine = InferenceEngine(model, params, block_size=4, max_batch=4,
                             num_blocks=21, quantize_int8=True, seed=0)
    assert engine.quantized
    sched = Scheduler(engine)
    rng = np.random.RandomState(0)
    reqs = [Request(rid=i,
                    prompt=[int(x) for x in rng.randint(0, 61, 8)],
                    max_new_tokens=16)
            for i in range(10)]
    results, wall = run_open_loop(sched, reqs)
    assert len(results) == 10
    rep = serve_report(results, wall, sched)
    assert rep["quantized_int8"] and rep["value"] > 0
    qparams = jax.jit(dequantize_tree)(engine.params)
    agree = total = 0
    decided_misses = []
    for req in results.values():
        seq = req.prompt + req.generated
        ref = _full_argmax_ref(model, params, state, seq)
        got = _full_argmax_ref(model, qparams, state, seq)
        for i in range(len(req.prompt) - 1, len(seq) - 1):
            total += 1
            if ref[i].argmax() == got[i].argmax():
                agree += 1
            else:
                top2 = np.sort(ref[i])[-2:]
                if top2[1] - top2[0] >= 0.1:
                    decided_misses.append(float(top2[1] - top2[0]))
    assert not decided_misses, \
        f"int8 flipped decided argmaxes (margins {decided_misses})"
    assert agree / total >= 0.95, f"int8 argmax agreement {agree}/{total}"


# -- verified read-only load --------------------------------------------------

def test_load_for_inference_verified_and_readonly(dense_model, tmp_path):
    """The consumer API restores through the chain without ever writing:
    no dirty marker, no debris sweep, no quarantine move, no
    resilience.json / latest.json rewrite — a live training writer's
    directory is left byte-identical apart from its own files."""
    from theanompi_tpu.utils.checkpoint import (
        Checkpointer,
        CheckpointFingerprintError,
        load_for_inference,
        model_fingerprint,
    )

    model, params, _ = dense_model
    d = str(tmp_path / "ckpt")
    fp = {"mesh": {"data": 8}, "exchange": "psum", "n_subb": 1,
          **model_fingerprint(model)}
    writer = Checkpointer(d, fingerprint=fp)
    p0 = jax.tree.map(lambda a: np.asarray(a), params)
    p1 = jax.tree.map(lambda a: np.asarray(a) + 1.0, p0)
    writer.save(0, 10, {"params": p0}).join()
    writer.save(1, 20, {"params": p1}).join()
    writer.mark_clean()
    # live-writer droppings the consumer must not sweep
    debris = os.path.join(d, "ckpt_e0002.npz.tmp.npz")
    open(debris, "w").write("partial")
    orphan = os.path.join(d, "ckpt_e0007.manifest.json")
    open(orphan, "w").write("{}")

    out = load_for_inference(d, {"params": params}, verify="full",
                             model=model)
    ep, it, trees = out
    assert (ep, it) == (1, 20)
    np.testing.assert_array_equal(
        np.asarray(trees["params"]["head"]["w"]), p1["head"]["w"])
    assert os.path.exists(debris) and os.path.exists(orphan)
    assert not os.path.exists(os.path.join(d, "dirty"))
    assert not os.path.exists(os.path.join(d, "resilience.json"))

    # corrupt the newest: the chain steps back WITHOUT quarantining
    npz1 = os.path.join(d, "ckpt_e0001.npz")
    blob = bytearray(open(npz1, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(npz1, "wb").write(bytes(blob))
    latest_before = open(os.path.join(d, "latest.json")).read()
    ep, it, trees = load_for_inference(d, {"params": params},
                                       verify="full", model=model)
    assert ep == 0
    np.testing.assert_array_equal(
        np.asarray(trees["params"]["head"]["w"]), p0["head"]["w"])
    assert os.path.exists(npz1), "read-only consumer moved a writer's file"
    assert not os.path.exists(os.path.join(d, "corrupt"))
    assert open(os.path.join(d, "latest.json")).read() == latest_before
    assert not os.path.exists(os.path.join(d, "resilience.json"))

    # model-identity fingerprint: a different config refuses, force warns
    other = TransformerLM({**TINY, "dim": 64, "heads": 4})
    oparams, _ = other.init_params(jax.random.PRNGKey(0))
    with pytest.raises(CheckpointFingerprintError):
        load_for_inference(d, {"params": oparams}, model=other)

    # the read-only handle refuses to write
    ro = Checkpointer(d, read_only=True)
    with pytest.raises(RuntimeError, match="read-only"):
        ro.save(2, 30, {"params": p0})


def test_load_for_inference_empty_dir_is_none(tmp_path):
    from theanompi_tpu.utils.checkpoint import load_for_inference

    assert load_for_inference(str(tmp_path / "none"), {}) is None


# -- telemetry ----------------------------------------------------------------

def test_serve_telemetry_chrome_trace(dense_model, tmp_path):
    """serve.prefill/serve.decode spans export to a Chrome trace: disjoint
    intervals (single-threaded loop, fenced closes) with per-request ids
    threaded through the span args."""
    from theanompi_tpu.telemetry import Telemetry
    from theanompi_tpu.telemetry.metrics import SERVE_SPANS

    model, params, _ = dense_model
    tel = Telemetry(str(tmp_path / "tel"))
    engine = InferenceEngine(model, params, block_size=4, max_batch=2,
                             num_blocks=11, seed=0)
    sched = Scheduler(engine, telemetry=tel)
    reqs = [Request(rid=i, prompt=[1 + i, 2, 3], max_new_tokens=6)
            for i in range(4)]
    results, _ = run_open_loop(sched, reqs)
    assert len(results) == 4
    tel.close()
    trace = json.load(open(tel.export_chrome_trace()))
    spans = [e for e in trace["traceEvents"]
             if e["ph"] == "X" and e["name"] in SERVE_SPANS]
    prefills = [e for e in spans if e["name"] == "serve.prefill"]
    decodes = [e for e in spans if e["name"] == "serve.decode"]
    assert len(prefills) == 4 and len(decodes) == sched.n_steps
    # per-request ids threaded: every prefill tags its request, every
    # decode lists the requests in that step's batch
    assert sorted(e["args"]["request"] for e in prefills) == [0, 1, 2, 3]
    assert all(e["args"]["requests"] for e in decodes)
    seen = {r for e in decodes for r in e["args"]["requests"]}
    assert seen == {0, 1, 2, 3}
    # disjoint: prefill and decode never overlap in the serve loop
    iv = sorted((e["ts"], e["ts"] + e["dur"]) for e in spans)
    for (s0, e0), (s1, _e1) in zip(iv, iv[1:]):
        assert s1 >= e0 - 1e-3, "serve spans overlap"
    # the registered histograms made it into the metrics snapshot
    snap = tel.metrics.snapshot()
    assert "serve.token_ms" in snap.get("histograms", {})
    assert "serve.ttft_ms" in snap.get("histograms", {})
    assert snap["counters"]["serve.tokens"] == sum(
        len(r.generated) for r in results.values())


# -- CLI / bench --------------------------------------------------------------

TMSERVE_TINY_ARGS = [
    "--modelclass", "TransformerLM",
    "--set", "dim=32", "--set", "heads=2", "--set", "n_layers=1",
    "--set", "seq_len=32", "--set", "vocab=61", "--set", "dropout=0.0",
    "--set", "precision=fp32", "--set", "n_train=64", "--set", "n_val=32",
    "--max-batch", "2", "--block-size", "4",
    "--requests", "3", "--prompt-len", "4", "--max-new-tokens", "4",
]


def test_tmserve_cli_end_to_end(tmp_path, capsys):
    from theanompi_tpu.serving import cli

    out = str(tmp_path / "SERVE.json")
    rc = cli.main(TMSERVE_TINY_ARGS + ["--out", out, "--quiet"])
    assert rc == 0
    report = json.load(open(out))
    assert report["requests"] == 3 and report["value"] > 0
    # the one-JSON-line stdout contract (same as bench.py)
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")][-1]
    assert json.loads(line)["metric"] == "serve_tokens_per_sec"


def test_tmserve_cli_exit_codes(tmp_path):
    from theanompi_tpu.resilience.codes import EXIT_CKPT, EXIT_CONFIG
    from theanompi_tpu.serving import cli

    # unknown model class -> config error, one-line contract
    rc = cli.main(["--modelclass", "NoSuchModel", "--requests", "1"])
    assert rc == EXIT_CONFIG
    # an empty checkpoint dir with only corrupt files -> EXIT_CKPT
    d = tmp_path / "ckpt"
    d.mkdir()
    (d / "latest.json").write_text('{"epoch": 0, "iteration": 0}')
    (d / "ckpt_e0000.npz").write_text("not a zip")
    (d / "ckpt_e0000.manifest.json").write_text(
        '{"format": 1, "leaves": {"params::x": {"shape": [1], '
        '"dtype": "float32", "nbytes": 4, "crc32": 0}}}')
    rc = cli.main(TMSERVE_TINY_ARGS + ["--checkpoint-dir", str(d)])
    assert rc == EXIT_CKPT
    # and read-only: the corrupt file was NOT quarantined
    assert (d / "ckpt_e0000.npz").exists()
    assert not (d / "corrupt").exists()


def test_bench_serve_mode_writes_serve_json(tmp_path, monkeypatch):
    """BENCH_SERVE=1 routes bench.py through the serving engine and
    publishes SERVE.json (atomic, run_id-stamped) next to bench.py."""
    import bench

    monkeypatch.setattr(bench, "__file__",
                        str(tmp_path / "bench.py"))
    for k, v in {
        "BENCH_SERVE": "1", "BENCH_SERVE_REQUESTS": "3",
        "BENCH_SERVE_PROMPT": "4", "BENCH_SERVE_NEW": "4",
        "BENCH_SERVE_BATCH": "2", "BENCH_SERVE_BLOCK_SIZE": "4",
        "BENCH_DIM": "32", "BENCH_LAYERS": "1", "BENCH_SEQ": "32",
        "BENCH_VOCAB": "61",
    }.items():
        monkeypatch.setenv(k, v)
    monkeypatch.delenv("BENCH_TELEMETRY_DIR", raising=False)
    bench._measure()
    art = json.load(open(tmp_path / "SERVE.json"))
    assert art["metric"] == "serve_tokens_per_sec"
    assert art["requests"] == 3 and "run_id" in art
    assert "p50" in art["token_ms"]
