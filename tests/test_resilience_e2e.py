"""Resilience e2e (ISSUE 4 acceptance): supervised SIGKILL -> restart ->
auto-resume with crash-resume EQUIVALENCE, NaN-sentinel policies on the
real trainer, and SIGTERM preemption's resumable exit.

Subprocess children run with the SAME virtual-device topology and RNG
flavor as the in-process session (8 forced CPU devices +
threefry_partitionable), which makes in-process and subprocess lineages
bit-comparable — verified by the equivalence asserts below.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from theanompi_tpu.resilience import EXIT_PREEMPTED, FaultInjected

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: one tiny config for every run in this file, so subprocess children share
#: one compile-cache entry (1-core CI: compile time IS the test budget)
TINY_CFG = {"depth": 10, "widen": 1, "batch_size": 4, "image_size": 8,
            "n_train": 32, "n_val": 16, "n_epochs": 2, "precision": "fp32"}
TINY_ARGS = ["--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
             "--set", "image_size=8", "--set", "n_train=32",
             "--set", "n_val=16", "--set", "precision='fp32'"]


def _adaptive_timeout(base: float) -> float:
    """Scale a subprocess deadline by the measured host load (ISSUE 20
    satellite: the supervised SIGKILL e2e failed under full-sweep load).
    The base is generous for an idle box; when the 1-minute load average
    says the cores are oversubscribed — xdist siblings compiling, the
    chaos e2e's own children — the child's wall time stretches with it,
    so the deadline must too.  Capped at 4x: past that a miss is a hang,
    not contention."""
    try:
        load = os.getloadavg()[0]
    except (OSError, AttributeError):
        return base
    per_core = load / max(os.cpu_count() or 1, 1)
    return base * min(4.0, max(1.0, per_core))


def _child_env(**extra):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # match the in-process session exactly (device topology changes
        # XLA:CPU partitioning, the RNG flag changes every random stream)
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_THREEFRY_PARTITIONABLE": "true",
        "PYTHONPATH": REPO,
    })
    env.pop("THEANOMPI_FAULT_PLAN", None)  # only ever injected explicitly
    env.update(extra)
    return env


def _launcher_cmd(*args):
    return [sys.executable, "-m", "theanompi_tpu.launcher",
            "--rule", "BSP", "--devices", "4",
            "--modelfile", "theanompi_tpu.models.wide_resnet",
            "--modelclass", "WideResNet", *TINY_ARGS, "--quiet", *args]


def _clean_run_inprocess(ckpt_dir, rule_cfg=None):
    """The unfaulted reference lineage, trained in-process on mesh4."""
    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "checkpoint_dir": ckpt_dir,
                       **(rule_cfg or {})})
    rule.init(devices=4, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config=dict(TINY_CFG))
    rule.wait()
    return rule


def _assert_ckpt_equal(path_a, path_b):
    with np.load(path_a) as a, np.load(path_b) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.faultinject
def test_supervised_sigkill_restarts_and_resumes_equivalently(
        tmp_path, subproc_compile_cache):
    """THE acceptance scenario: a supervised run SIGKILLed mid-epoch-1
    restarts, auto-resumes from latest.json, and finishes with params AND
    val metrics bit-equal to an uninterrupted run at the same seed;
    resilience.json reports the attempts and causes."""
    clean_ck = str(tmp_path / "ck_clean")
    rule = _clean_run_inprocess(clean_ck)
    clean_val = {k: list(v) for k, v in
                 rule.trainer.recorder.val_history.items()}

    ck = str(tmp_path / "ck_fault")
    rec = str(tmp_path / "rec_fault")
    tel = str(tmp_path / "tel_fault")
    p = subprocess.run(
        _launcher_cmd("--set", "n_epochs=2",
                      "--checkpoint-dir", ck, "--record-dir", rec,
                      "--telemetry-dir", tel,
                      "--compile-cache-dir", subproc_compile_cache,
                      "--supervise", "--max-restarts", "3",
                      "--backoff-base", "0.1"),
        # kill at the entry of iteration 3 = one step INTO epoch 1 (two
        # 2-step epochs), first attempt only — the restart must not re-die
        env=_child_env(THEANOMPI_FAULT_PLAN="step:kill@3@1"),
        cwd=REPO, capture_output=True, text=True,
        timeout=_adaptive_timeout(480))
    assert p.returncode == 0, p.stderr[-2000:]

    art = json.load(open(os.path.join(ck, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]
    assert art["attempts"][0]["exit_code"] == -signal.SIGKILL
    assert art["restarts"] == 1 and art["final_exit"] == 0
    assert art["attempts"][0]["time_lost_s"] > 0
    # the supervisor mirrors the attempt records into the telemetry dir
    # (its own JSONL, not an events-rank sink a child attempt would
    # truncate and rank-0 aggregation would misread)
    sup_events = [json.loads(line) for line in
                  open(os.path.join(tel, "supervisor.jsonl"))]
    assert [e["cause"] for e in sup_events
            if e["name"] == "supervisor.attempt"] == ["crash", "clean"]
    assert any(e["name"] == "supervisor.done" for e in sup_events)
    # the supervisor told the restarted child to resume, and it did:
    # epoch 1 was replayed from the epoch-0 checkpoint, so the final
    # lineage is bit-identical to the uninterrupted run
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))
    faulted_val = np.load(os.path.join(rec, "val_history.npy"),
                          allow_pickle=True).item()
    for k, v in clean_val.items():
        np.testing.assert_array_equal(np.asarray(v), faulted_val[k],
                                      err_msg=f"val history {k!r}")


@pytest.mark.faultinject
def test_crash_resume_equivalence_zero1(tmp_path):
    """Crash-resume equivalence holds for the sharded-optimizer exchange
    too (zero1's opt state lives in flat sharded buckets — the checkpoint
    and restore path must round-trip them exactly).  In-process: the
    supervised-subprocess machinery is already locked by the psum test."""
    from theanompi_tpu import BSP

    cfg = {"exch_strategy": "zero1"}
    clean_ck = str(tmp_path / "ck_clean")
    _clean_run_inprocess(clean_ck, rule_cfg=dict(cfg))

    ck = str(tmp_path / "ck_fault")
    rule = BSP(config={"verbose": False, "checkpoint_dir": ck,
                       "fault_plan": "step:raise@3", **cfg})
    rule.init(devices=4, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config=dict(TINY_CFG))
    with pytest.raises(FaultInjected):
        rule.wait()  # dies one step into epoch 1
    # in-place resume (same process, compiled fns retained): restore the
    # latest checkpoint and train to completion
    assert rule.trainer.try_resume()
    assert rule.trainer.epoch == 1  # epoch 0 published before the crash
    rule.wait()
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))


@pytest.mark.faultinject
def test_sentinel_skip_batch_device_guard(tmp_path):
    """A NaN-poisoned batch costs one skipped update: params stay finite,
    the run completes, the skip is counted against the bounded budget."""
    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "print_freq": 1,
                       "fault_plan": "step:nan@1",
                       "sentinel_policy": "skip_batch"})
    rule.init(devices=2, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config=dict(TINY_CFG))
    rule.wait()
    t = rule.trainer
    assert t.sentinel.skips == 1.0
    assert t.epoch == TINY_CFG["n_epochs"]  # ran to completion
    for leaf in jax.tree.leaves(t.params):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.faultinject
def test_sentinel_rollback_reloads_checkpoint(tmp_path):
    """rollback: a non-finite loss mid-epoch-1 reloads the epoch-0
    checkpoint in-process and the run still completes (the transient —
    one-shot by construction — does not recur on the replay)."""
    from theanompi_tpu import BSP

    ck = str(tmp_path / "ck")
    rule = BSP(config={"verbose": False, "print_freq": 1,
                       "fault_plan": "step:nan@5",
                       "sentinel_policy": "rollback",
                       "checkpoint_dir": ck})
    # devices=2 -> 4 steps/epoch; nan at step 5 = epoch 1, step 2
    rule.init(devices=2, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet", model_config=dict(TINY_CFG))
    rule.wait()
    t = rule.trainer
    assert t.sentinel.rollbacks == 1
    assert t.epoch == TINY_CFG["n_epochs"]
    for leaf in jax.tree.leaves(t.params):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.faultinject
def test_sigterm_mid_epoch_resumable_exit(tmp_path, subproc_compile_cache):
    """SIGTERM mid-training -> final synchronous checkpoint + the distinct
    EXIT_PREEMPTED code; a resumed run picks the lineage up and finishes."""
    ck = str(tmp_path / "ck")
    child = subprocess.Popen(
        _launcher_cmd("--set", "n_epochs=200",  # far more than we let run
                      "--checkpoint-dir", ck,
                      "--compile-cache-dir", subproc_compile_cache,
                      "--rule-set", "handle_preemption=True"),
        env=_child_env(), cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.perf_counter() + 240
        latest = os.path.join(ck, "latest.json")
        while not os.path.exists(latest):
            assert child.poll() is None, \
                f"child died early: {child.stderr.read()[-2000:]}"
            assert time.perf_counter() < deadline, "no checkpoint in 240s"
            time.sleep(0.05)
        time.sleep(0.3)  # let it get a step or two into the next epoch
        child.send_signal(signal.SIGTERM)
        rc = child.wait(timeout=120)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait()
    err = child.stderr.read()
    assert rc == EXIT_PREEMPTED, err[-2000:]
    assert "tmlauncher: preempted" in err
    meta = json.load(open(latest))
    saved_epoch = meta["epoch"]

    # resume in-process: the preemption checkpoint is a normal lineage
    # point — training continues to (a shrunk) completion
    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "checkpoint_dir": ck,
                       "resume": True})
    rule.init(devices=4, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY_CFG, "n_epochs": saved_epoch + 2})
    # resumed, not fresh: mid-epoch preemption saves the CURRENT epoch
    # with completed=False (resume re-enters it at the batch cursor), while
    # a boundary-timed SIGTERM leaves the completed=True save (resume moves
    # to the next epoch) — which one we hit is a timing race
    assert rule.trainer.epoch in (saved_epoch, saved_epoch + 1)
    rule.wait()
    assert rule.trainer.epoch == saved_epoch + 2
    assert json.load(open(latest))["epoch"] == saved_epoch + 1
