"""ISSUE 5 tentpole: checkpoint integrity + the verified recovery chain.

Covers the acceptance matrix: manifests published with every save (async
and sync byte-identical, manifest included), the corruption matrix
(truncate / bit-flip / missing manifest / fingerprint mismatch) against
fast vs full verify, the recovery chain (fallback + quarantine + the
``ckpt.fallback`` audit event), verified retention (`_prune` never
deletes the last verifiable checkpoint), the background scrub, the
``--verify`` scrubber CLI, the dirty-marker clean-shutdown handshake,
cold-``--resume`` fallback on a real trainer, sentinel ``rollback``
through the chain, exit 77 on an exhausted chain, and THE supervised
scenario: SIGKILL + corrupt-latest -> restart -> fallback to the previous
checkpoint -> run completes with the correct final epoch count.
"""

import json
import os
import shutil
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax

from theanompi_tpu.resilience import EXIT_CKPT, FaultPlan, FaultPlanError
from theanompi_tpu.utils.checkpoint import (
    CheckpointChainExhausted,
    CheckpointCorruptError,
    CheckpointFingerprintError,
    Checkpointer,
    main as scrubber_main,
    verify_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the trainer config + builder live in conftest.py (ISSUE 11 satellite):
# the session-scoped ``trained_wrn_ckpt`` fixture trains the 2-epoch run
# ONCE, and resuming trainers here are built by the same helper so their
# resume fingerprints match the fixture's checkpoints exactly
from conftest import WRN_TINY as TINY, make_wrn_trainer  # noqa: E402

#: subprocess flavor of TINY (shapes match tests/test_resilience_e2e.py so
#: the session-scoped compile cache is shared across both files' children)
SUB_ARGS = ["--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
            "--set", "image_size=8", "--set", "n_train=32",
            "--set", "n_val=16", "--set", "precision='fp32'"]


def _tree(e):
    return {"a": np.arange(6, dtype=np.float32).reshape(2, 3) + e,
            "b": {"c": np.full((4,), e, np.int32)}}


def _template():
    return {"params": {"a": np.zeros((2, 3), np.float32),
                       "b": {"c": np.zeros((4,), np.int32)}}}


def _bitflip(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


def _truncate(path):
    with open(path, "r+b") as f:
        f.truncate(max(1, os.path.getsize(path) // 2))


def _manifest_of(path):
    return path[:-len(".npz")] + ".manifest.json"


def _events(directory):
    return json.load(open(os.path.join(directory, "resilience.json")))[
        "events"]


# -- manifest + verify unit matrix -------------------------------------------

def test_manifest_published_with_save_and_bit_identical(tmp_path):
    """Every save publishes a manifest; async and sync produce byte-equal
    .npz AND manifest (the manifest carries no timestamps by design)."""
    fp = {"mesh": {"data": 4}, "exchange": "psum"}
    sync_ck = Checkpointer(str(tmp_path / "sync"), async_save=False,
                           fingerprint=fp)
    sync_ck.save(0, 7, {"params": _tree(1)})
    async_ck = Checkpointer(str(tmp_path / "async"), async_save=True,
                            fingerprint=fp)
    async_ck.save(0, 7, {"params": _tree(1)}).join()
    a_npz = open(sync_ck._path(0), "rb").read()
    b_npz = open(async_ck._path(0), "rb").read()
    assert a_npz == b_npz
    a_man = open(_manifest_of(sync_ck._path(0)), "rb").read()
    b_man = open(_manifest_of(async_ck._path(0)), "rb").read()
    assert a_man == b_man
    man = json.loads(a_man)
    assert man["epoch"] == 0 and man["iteration"] == 7
    assert set(man["leaves"]) == {"params::a", "params::b/c"}
    for meta in man["leaves"].values():
        assert {"shape", "dtype", "nbytes", "crc32"} <= set(meta)
    assert man["fingerprint"]["mesh"] == {"data": 4}


def test_snapshot_owns_its_bytes(tmp_path):
    """The save-time snapshot must copy, not view, device buffers: on the
    CPU backend ``np.asarray(jax.Array)`` aliases the buffer itself, and
    the next step's donation rewrites it under the async writer — a torn
    ``.npz`` whose manifest CRCs then (flakily) fail resume verification.
    Regression for the supervised-SIGKILL e2e flake."""
    ck = Checkpointer(str(tmp_path))
    dev = jax.device_put(np.arange(6, dtype=np.float32))
    flat = ck._snapshot({"params": {"a": dev, "b": np.ones((2,), np.int32)}})
    for key, arr in flat.items():
        assert arr.base is None and arr.flags.owndata, (
            f"snapshot leaf {key!r} does not own its bytes — it aliases "
            f"a (donatable) device buffer")


def test_verify_matrix_truncate_bitflip_manifest(tmp_path):
    """truncate fails even the fast check; a bit-flip passes fast (by
    design — it is structural only) and fails full; a dropped manifest
    fails fast."""
    ck = Checkpointer(str(tmp_path), fingerprint={"m": 1})
    ck.save(0, 1, {"params": _tree(0)})
    path = ck._path(0)
    verify_file(path, "fast")
    verify_file(path, "full")

    # fingerprint is checked on verify_epoch, not raw verify_file
    ck.verify_epoch(0, "full")
    ck_other = Checkpointer(str(tmp_path), fingerprint={"m": 2})
    with pytest.raises(CheckpointFingerprintError, match="resume-force"):
        ck_other.verify_epoch(0, "fast")

    _bitflip(path)
    verify_file(path, "fast")  # structural check cannot see a data flip
    with pytest.raises(CheckpointCorruptError, match="CRC|read failed"):
        verify_file(path, "full")

    ck.save(1, 2, {"params": _tree(1)})
    _truncate(ck._path(1))
    with pytest.raises(CheckpointCorruptError, match="unreadable|leaf set"):
        verify_file(ck._path(1), "fast")

    ck.save(2, 3, {"params": _tree(2)})
    os.remove(_manifest_of(ck._path(2)))
    with pytest.raises(CheckpointCorruptError, match="manifest"):
        verify_file(ck._path(2), "fast")


def test_chain_falls_back_quarantines_and_audits(tmp_path):
    """Corrupt newest two of three -> the chain restores epoch 0, moves the
    bad pairs under corrupt/, records ckpt.quarantine + ckpt.fallback in
    resilience.json, and repoints latest.json at the verified epoch."""
    d = str(tmp_path)
    ck = Checkpointer(d, keep=5, fingerprint={"m": 1})
    for e in range(3):
        ck.save(e, e * 10, {"params": _tree(e)})
    _bitflip(ck._path(2))
    os.remove(_manifest_of(ck._path(1)))

    ep, it, restored = ck.load_latest_verified(_template(), verify="full")
    assert (ep, it) == (0, 0)
    np.testing.assert_array_equal(restored["params"]["a"], _tree(0)["a"])
    q = sorted(os.listdir(os.path.join(d, "corrupt")))
    assert "ckpt_e0001.npz" in q and "ckpt_e0002.npz" in q
    names = [e["name"] for e in _events(d)]
    assert names.count("ckpt.quarantine") == 2
    fb = [e for e in _events(d) if e["name"] == "ckpt.fallback"][0]
    assert fb["bad_epochs"] == [2, 1] and fb["restored_epoch"] == 0
    # the pointer never advertises a quarantined file
    assert json.load(open(os.path.join(d, "latest.json")))["epoch"] == 0
    # and latest_epoch() agrees post-fallback
    assert ck.latest_epoch() == 0


def test_chain_exhausted_vs_fresh_start(tmp_path):
    ck = Checkpointer(str(tmp_path))
    assert ck.load_latest_verified(_template()) is None  # fresh: no error
    ck.save(0, 1, {"params": _tree(0)})
    _truncate(ck._path(0))
    with pytest.raises(CheckpointChainExhausted, match="corrupt/"):
        ck.load_latest_verified(_template())


def test_fingerprint_mismatch_refused_unless_forced(tmp_path):
    d = str(tmp_path)
    Checkpointer(d, fingerprint={"mesh": {"data": 4}}).save(
        0, 1, {"params": _tree(0)})
    with pytest.raises(CheckpointFingerprintError, match="mesh"):
        Checkpointer(d, fingerprint={"mesh": {"data": 8}}) \
            .load_latest_verified(_template())
    # the mismatch is a refusal, not a corruption: nothing was quarantined
    assert not os.path.exists(os.path.join(d, "corrupt"))
    ep, _, _ = Checkpointer(d, fingerprint={"mesh": {"data": 8}},
                            resume_force=True) \
        .load_latest_verified(_template())
    assert ep == 0


def test_corrupt_read_wrapped_even_without_verify(tmp_path):
    """verify='none' still surfaces a typed CheckpointCorruptError on an
    unreadable file (the chain must classify late rot too)."""
    ck = Checkpointer(str(tmp_path))
    ck.save(0, 1, {"params": _tree(0)})
    _truncate(ck._path(0))
    with pytest.raises(CheckpointCorruptError):
        ck.load(0, _template(), verify="none")


# -- verified retention + scrub ----------------------------------------------

def test_prune_never_deletes_last_verifiable(tmp_path):
    """keep=1 with every later publish torn: the only good ancestor must
    survive any number of newer corrupt files (keep-n used to count the
    corrupt ones and rotate the good ancestor out)."""
    plan = FaultPlan.parse(
        "checkpoint:manifest_drop@1;checkpoint:manifest_drop@2;"
        "checkpoint:manifest_drop@3")
    ck = Checkpointer(str(tmp_path), keep=1, fault_plan=plan)
    for e in range(4):
        ck.save(e, e, {"params": _tree(e)})
    files = sorted(f for f in os.listdir(tmp_path)
                   if f.startswith("ckpt_e") and f.endswith(".npz"))
    assert "ckpt_e0000.npz" in files  # the only verifiable one survived
    # the idle-time scrub already quarantined the older torn publishes
    # (e1, e2); the chain steps over whatever newer corruption remains
    ep, _, _ = ck.load_latest_verified(_template())
    assert ep == 0


def test_prune_counts_only_verified_toward_keep(tmp_path):
    """With no corruption, keep-n behaves exactly as before; with a
    corrupt file in the middle, the keep-n window is computed over the
    verified set only."""
    ck = Checkpointer(str(tmp_path), keep=2)
    for e in range(3):
        ck.save(e, e, {"params": _tree(e)})
    files = [f for f in os.listdir(tmp_path) if f.endswith(".npz")]
    assert sorted(files) == ["ckpt_e0001.npz", "ckpt_e0002.npz"]


def test_prune_protects_newest_full_verified_against_silent_rot(tmp_path):
    """Fast verification cannot see a data-byte bit-flip, so keep-n alone
    could rotate the last hash-proven checkpoint out while its newer
    sibling is silently rotten.  The newest FULL-verified (scrubbed)
    checkpoint must survive until a newer one is hash-proven."""
    ck = Checkpointer(str(tmp_path), keep=1)
    ck.save(0, 0, {"params": _tree(0)})
    ck.save(1, 1, {"params": _tree(1)})  # scrub full-verifies e0 here
    files = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    # keep=1 would have deleted e0; the full-verified protection spares it
    assert files == ["ckpt_e0000.npz", "ckpt_e0001.npz"]
    _bitflip(ck._path(1))  # newest rots; fast verify still passes it
    ep, _, restored = ck.load_latest_verified(_template(), verify="full")
    assert ep == 0  # fell back to the protected hash-proven ancestor
    np.testing.assert_array_equal(np.asarray(restored["params"]["a"]),
                                  _tree(0)["a"])


def test_scrub_and_prune_skip_foreign_files(tmp_path):
    """A stray operator file matching the retention glob (e.g. an
    out-of-band backup `ckpt_e0000.bak.npz`) must not crash the writer
    thread's scrub/quarantine (regression: unguarded int() on the epoch
    slice) and is never deleted, scrubbed, or quarantined."""
    foreign = tmp_path / "ckpt_e0000.bak.npz"
    foreign.write_bytes(b"not a checkpoint at all")
    ck = Checkpointer(str(tmp_path), keep=1)
    for e in range(3):
        ck.save(e, e, {"params": _tree(e)})  # scrub+prune run each save
    ck.join_pending()  # a writer-thread crash would re-raise here
    assert foreign.exists()
    assert not os.path.exists(tmp_path / "corrupt" / foreign.name)
    assert ck.available_epochs() == sorted(ck.available_epochs())
    # the scrubber CLI applies the same membership rule: a healthy chain
    # plus a foreign file exits 0, not 77
    assert scrubber_main(["--verify", str(tmp_path)]) == 0


def test_background_scrub_quarantines_rotted_older(tmp_path):
    """The writer's idle-time scrub full-verifies one older checkpoint per
    save and quarantines rot before a resume ever needs it."""
    ck = Checkpointer(str(tmp_path), keep=5)
    ck.save(0, 0, {"params": _tree(0)})
    _bitflip(ck._path(0))  # rots on disk after a good publish
    ck.save(1, 1, {"params": _tree(1)})  # scrub runs here
    q = os.path.join(tmp_path, "corrupt")
    assert os.path.isdir(q) and "ckpt_e0000.npz" in os.listdir(q)
    assert any(e["name"] == "ckpt.quarantine"
               and e["reason"].startswith("scrub:")
               for e in _events(str(tmp_path)))


def test_dirty_marker_lifecycle(tmp_path):
    ck = Checkpointer(str(tmp_path))
    assert not ck.was_unclean()
    ck.save(0, 0, {"params": _tree(0)})
    assert ck.was_unclean()  # held until the clean-shutdown handshake
    ck.mark_clean()
    assert not ck.was_unclean()


# -- fault-plan grammar -------------------------------------------------------

def test_corruption_fault_specs_parse_and_apply(tmp_path):
    plan = FaultPlan.parse("checkpoint:bitflip@0,checkpoint:truncate@1;"
                           "checkpoint:manifest_drop@2")
    assert [s.action for s in plan.specs] == ["bitflip", "truncate",
                                              "manifest_drop"]
    with pytest.raises(FaultPlanError, match="invalid for site"):
        FaultPlan.parse("checkpoint:explode@0")

    # one dir per action so the writer's own scrub can't quarantine the
    # evidence before the assertion reads it
    for epoch, action, level, match in (
            (0, "bitflip", "full", "CRC|read failed"),
            (1, "truncate", "fast", "unreadable|leaf set"),
            (2, "manifest_drop", "fast", "manifest")):
        d = str(tmp_path / action)
        ck = Checkpointer(
            d, fault_plan=FaultPlan.parse(f"checkpoint:{action}@{epoch}"))
        ck.save(epoch, 1, {"params": _tree(epoch)})
        with pytest.raises(CheckpointCorruptError, match=match):
            verify_file(ck._path(epoch), level)
    # a bit-flip is invisible to the structural fast check (by design)
    verify_file(Checkpointer(str(tmp_path / "bitflip"))._path(0), "fast")


# -- scrubber CLI -------------------------------------------------------------

def test_scrubber_cli_report_and_quarantine(tmp_path, capsys):
    d = str(tmp_path)
    ck = Checkpointer(d, keep=5)
    for e in range(2):
        ck.save(e, e, {"params": _tree(e)})
    assert scrubber_main(["--verify", d]) == 0
    out = capsys.readouterr().out
    assert "2/2 checkpoints verifiable" in out and ": OK (" in out

    _bitflip(ck._path(1))
    assert scrubber_main(["--verify", d]) == EXIT_CKPT
    assert "CORRUPT" in capsys.readouterr().out
    # --fast misses the data flip by design
    assert scrubber_main(["--verify", d, "--fast"]) == 0
    capsys.readouterr()
    # --quarantine moves the bad pair out
    assert scrubber_main(["--verify", d, "--quarantine"]) == EXIT_CKPT
    assert "ckpt_e0001.npz" in os.listdir(os.path.join(d, "corrupt"))
    assert scrubber_main(["--verify", d]) == 0  # what remains verifies


# -- trainer-level matrix -----------------------------------------------------

_tiny_trainer = make_wrn_trainer


def test_cold_resume_falls_back_on_corrupt_latest(tmp_path, mesh4,
                                                  trained_wrn_ckpt):
    """A cold try_resume whose latest checkpoint is bit-flipped lands on
    the previous epoch with its exact params (the zip-CRC read error is
    classified as corruption even under the fast verify a clean-exit
    directory gets)."""
    # corrupt a COPY of the session run (epochs 0+1 published, clean)
    ck = str(tmp_path / "ck")
    shutil.copytree(trained_wrn_ckpt, ck)

    t2 = _tiny_trainer(mesh4, ck)
    assert not t2.checkpointer.was_unclean()
    params_e0 = t2.checkpointer.load(
        0, {"params": t2.params}, verify="full")["params"]
    _bitflip(os.path.join(ck, "ckpt_e0001.npz"))
    assert t2.try_resume()
    assert t2.epoch == 1  # fell back: epoch 0 completed, 1 is next
    for a, b in zip(jax.tree.leaves(t2.params),
                    jax.tree.leaves(params_e0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert "ckpt_e0001.npz" in os.listdir(os.path.join(ck, "corrupt"))
    assert any(e["name"] == "ckpt.fallback" for e in _events(ck))


def test_trainer_fingerprint_mismatch_and_force(tmp_path, mesh8,
                                                trained_wrn_ckpt):
    """Resuming under a different mesh is refused with the typed error;
    resume_force turns it into a warned override (params are replicated
    under BSP, so the arrays themselves restore fine)."""
    ck = str(tmp_path / "ck")
    shutil.copytree(trained_wrn_ckpt, ck)  # the mesh4 session run
    t8 = _tiny_trainer(mesh8, ck)
    with pytest.raises(CheckpointFingerprintError, match="mesh"):
        t8.try_resume()
    t8f = _tiny_trainer(mesh8, ck, resume_force=True)
    assert t8f.try_resume()
    assert t8f.epoch == 2


def test_launcher_exit_77_on_exhausted_chain(tmp_path, capsys):
    """Acceptance: an exhausted chain exits 77 with a one-line
    `tmlauncher: error:` message."""
    from theanompi_tpu.launcher import main as tm_main

    ck = str(tmp_path / "ck")
    c = Checkpointer(ck)
    c.save(0, 1, {"params": _tree(0)})
    _truncate(c._path(0))
    rc = tm_main([
        "--rule", "BSP", "--devices", "4",
        "--modelfile", "theanompi_tpu.models.wide_resnet",
        "--modelclass", "WideResNet",
        "--set", "depth=10", "--set", "widen=1", "--set", "batch_size=8",
        "--set", "image_size=8", "--set", "n_train=32", "--set", "n_val=16",
        "--set", "n_epochs=1", "--set", "precision='fp32'",
        "--checkpoint-dir", ck, "--resume", "--quiet",
    ])
    assert rc == EXIT_CKPT == 77
    err = capsys.readouterr().err
    lines = [ln for ln in err.splitlines()
             if ln.startswith("tmlauncher: error:")]
    assert len(lines) == 1 and "checkpoint" in lines[0]


@pytest.mark.faultinject
def test_sentinel_rollback_through_verified_chain(tmp_path):
    """Satellite: a NaN-triggered rollback whose latest checkpoint is
    corrupt steps back to the verified ancestor and the run completes
    (it used to re-raise into the corrupt load)."""
    from theanompi_tpu import BSP

    ck = str(tmp_path / "ck")
    # devices=2, batch 8 -> global 16 -> 2 steps/epoch over n_train=32...
    # use batch_size=4 -> 4 steps/epoch: e0 saved (it 4), e1 saved+flipped
    # (it 8), NaN at step 9 (epoch 2) -> rollback -> chain lands on e0
    rule = BSP(config={"verbose": False, "print_freq": 1,
                       "fault_plan": "step:nan@9;checkpoint:bitflip@1",
                       "sentinel_policy": "rollback",
                       "checkpoint_dir": ck})
    rule.init(devices=2, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY, "batch_size": 4, "n_epochs": 3})
    rule.wait()
    t = rule.trainer
    assert t.sentinel.rollbacks == 1
    assert t.epoch == 3  # ran to completion after the rollback replay
    for leaf in jax.tree.leaves(t.params):
        assert np.isfinite(np.asarray(leaf)).all()
    assert "ckpt_e0001.npz" in os.listdir(os.path.join(ck, "corrupt"))
    events = _events(ck)
    assert any(e["name"] == "ckpt.fallback" and e["restored_epoch"] == 0
               for e in events)


# -- THE supervised acceptance scenario ---------------------------------------

def _child_env(**extra):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_THREEFRY_PARTITIONABLE": "true",
        "PYTHONPATH": REPO,
    })
    env.pop("THEANOMPI_FAULT_PLAN", None)
    env.update(extra)
    return env


@pytest.mark.faultinject
def test_supervised_sigkill_with_corrupt_latest_falls_back(
        tmp_path, subproc_compile_cache):
    """Acceptance: a supervised run whose latest checkpoint is
    fault-injected corrupt is SIGKILLed, restarts, full-verifies (attempt
    2 after an unclean death), quarantines the bad epoch-1 files, falls
    back to epoch 0, replays, and finishes all 3 epochs — with the
    fallback recorded in resilience.json alongside the supervisor's
    attempt records."""
    ck = str(tmp_path / "ck")
    p = subprocess.run(
        [sys.executable, "-m", "theanompi_tpu.launcher",
         "--rule", "BSP", "--devices", "4",
         "--modelfile", "theanompi_tpu.models.wide_resnet",
         "--modelclass", "WideResNet", *SUB_ARGS, "--quiet",
         "--set", "n_epochs=3",
         "--checkpoint-dir", ck,
         "--compile-cache-dir", subproc_compile_cache,
         "--supervise", "--max-restarts", "3", "--backoff-base", "0.1"],
        # 2 steps/epoch (batch 4 x 4 workers over n_train=32): epoch-1's
        # checkpoint is bit-flipped as it publishes, then the child is
        # SIGKILLed one step into epoch 2 — attempt 1 only
        env=_child_env(
            THEANOMPI_FAULT_PLAN="checkpoint:bitflip@1@1;step:kill@5@1"),
        cwd=REPO, capture_output=True, text=True, timeout=480)
    assert p.returncode == 0, p.stderr[-2000:]

    art = json.load(open(os.path.join(ck, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]
    assert art["attempts"][0]["exit_code"] == -signal.SIGKILL
    # the chain's audit events survived the supervisor's summary rewrites
    names = [e["name"] for e in art["events"]]
    assert "ckpt.quarantine" in names
    fb = [e for e in art["events"] if e["name"] == "ckpt.fallback"]
    assert fb and fb[0]["bad_epochs"] == [1] and fb[0]["restored_epoch"] == 0
    assert fb[0]["verify"] == "full"  # unclean exit -> full hash verify
    assert "ckpt_e0001.npz" in os.listdir(os.path.join(ck, "corrupt"))
    # correct final epoch count: all 3 epochs completed after the replay
    assert json.load(open(os.path.join(ck, "latest.json")))["epoch"] == 2
    assert os.path.exists(os.path.join(ck, "ckpt_e0002.npz"))
    # clean completion dropped the dirty marker
    assert not os.path.exists(os.path.join(ck, "dirty"))


def test_supervisor_classifies_exit_77_fatal():
    from theanompi_tpu.resilience import classify_exit

    assert classify_exit(77) == "checkpoint"
    assert classify_exit(70) == "crash"
