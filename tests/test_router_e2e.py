"""ISSUE 19 end-to-end: the router + autoscaler driving real fleet jobs.

Three process-level scenarios on the mesh8 CPU pool:

- THE acceptance run: two serving replicas and one low-priority training
  job share the 8-device pool; a traffic spike trips the autoscaler,
  which leases chips by preempting the training job through the existing
  cooperative SIGTERM→75 path (cadence checkpoint saved); a third
  replica serves the spike; when pressure subsides the pool shrinks, the
  lease releases, and training resumes — finishing with final params
  **bit-equal** to an uncontended run of the same config, while every
  request gets exactly one terminal state.
- chaos: SIGKILL one of two replicas mid-traffic; survivors absorb the
  orphaned requests, the REQUESTS.jsonl dedup keeps terminal states
  exactly-once, and the autoscaler backfills the dead replica's lease.
- capacity: the same burst through one replica vs two — the 2-replica
  p99 router-visible TTFT must be strictly below the 1-replica baseline
  (the "why a router at all" witness).

Replicas here are process fakes speaking the full durable contract
(queue.jsonl tail, REQUESTS.jsonl restart dedup, atomic SERVE_SNAPSHOT,
SIGTERM drain-with-give-back) with zero XLA, so the serving side costs
milliseconds; the real-``tmserve``-replica path is exercised by the
runbook dry-run in test_runbook.py.  The training job is the real
launcher stack end to end.
"""

import json
import os
import signal
import sys
import threading
import time

import pytest

from theanompi_tpu.fleet import (
    FleetScheduler,
    JobSpec,
    job_dir,
    read_fleet_events,
    read_record,
)
from theanompi_tpu.resilience import EXIT_CLEAN, EXIT_PREEMPTED
from theanompi_tpu.router import (
    AutoscaleConfig,
    AutoscalePolicy,
    Balancer,
    ReplicaPool,
    Router,
)
from theanompi_tpu.router.cli import drive_traffic, synthetic_entries

from test_fleet import (
    TINY_CFG,
    _assert_ckpt_equal,
    _bsp,
    _child_env,
    _trace,
)

#: a serving replica as a process: tails its durable queue, "serves" by
#: sleeping FAKE_MS_PER_TOKEN per generated token, answers into
#: REQUESTS.jsonl (skipping rids a previous attempt already answered —
#: the restart dedup), publishes atomic load snapshots, and on SIGTERM
#: sheds still-queued work with reason "draining" (the give-back the
#: router redistributes) before exiting clean.  The full replica
#: lifecycle contract with zero XLA behind it.
FAKE_REPLICA = r'''
import json, os, signal, sys, time
jdir = os.environ["THEANOMPI_JOB_DIR"]
ms = float(os.environ.get("FAKE_MS_PER_TOKEN", "0"))
qpath = os.path.join(jdir, "queue.jsonl")
rpath = os.path.join(jdir, "REQUESTS.jsonl")
spath = os.path.join(jdir, "SERVE_SNAPSHOT.json")
open(os.path.join(jdir, "replica.pid"), "w").write(str(os.getpid()))
flag = [False]
signal.signal(signal.SIGTERM, lambda s, f: flag.__setitem__(0, True))
answered = set()
try:
    for line in open(rpath):
        try: answered.add(json.loads(line)["rid"])
        except ValueError: pass
except OSError: pass
log = open(rpath, "a")
def rec(d):
    log.write(json.dumps(d) + "\n"); log.flush()
def snap(backlog, done):
    with open(spath + ".tmp", "w") as f:
        json.dump({"updated": time.time(), "backlog_tokens": backlog,
                   "token_rate": (1000.0 / ms if ms > 0 else 4000.0),
                   "n_done": done, "queue_len": 0, "n_active": 0,
                   "draining": flag[0]}, f)
    os.replace(spath + ".tmp", spath)
offset = 0; drain_seen = False; pending = []; n_done = 0
while True:
    try:
        with open(qpath, "rb") as f:
            f.seek(offset); data = f.read()
    except OSError: data = b""
    nl = data.rfind(b"\n")
    if nl >= 0:
        for line in data[:nl].split(b"\n"):
            if not line.strip(): continue
            try: e = json.loads(line)
            except ValueError: continue
            if e.get("op") == "drain": drain_seen = True; continue
            if "rid" not in e or e["rid"] in answered: continue
            pending.append(e)
        offset += nl + 1
    if flag[0]:
        for e in pending:
            answered.add(e["rid"])
            rec({"rid": e["rid"], "state": "shed", "reason": "draining",
                 "n_generated": 0})
        snap(0, n_done); sys.exit(0)
    if pending:
        e = pending.pop(0); answered.add(e["rid"])
        n = int(e.get("max_new_tokens", 8))
        if ms > 0: time.sleep(ms * n / 1000.0)
        qw = max(time.time() - e.get("enq_wall", time.time()), 0.0) * 1e3
        n_done += 1
        rec({"rid": e["rid"], "state": "done", "reason": None,
             "n_generated": n, "ttft_ms": ms, "queue_wait_ms": round(qw, 3)})
        snap(sum(int(p.get("max_new_tokens", 8)) for p in pending), n_done)
        continue
    if drain_seen: snap(0, n_done); sys.exit(0)
    time.sleep(0.004)
'''


def _replica_spec(ms_per_token, devices=2, priority=10):
    return {"priority": priority, "min_devices": devices,
            "max_devices": devices, "max_restarts": 0,
            "backoff_base": 0.1,
            "argv": [sys.executable, "-c", FAKE_REPLICA],
            "env": {"FAKE_MS_PER_TOKEN": str(ms_per_token)}}


def _run_fleet(sched):
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    return t, box


def test_router_autoscale_preempts_training_and_resumes_bit_equal(
        tmp_path, monkeypatch, subproc_compile_cache):
    """THE ISSUE 19 acceptance scenario (docstring at module top)."""
    monkeypatch.delenv("THEANOMPI_DATA_TRACE", raising=False)
    monkeypatch.delenv("THEANOMPI_FAULT_PLAN", raising=False)
    fleet_dir = str(tmp_path / "fleet")
    trace = str(tmp_path / "trace_train")
    sched = FleetScheduler(fleet_dir, 8, poll_s=0.02)
    pool = ReplicaPool(sched, _replica_spec(ms_per_token=4))
    policy = AutoscalePolicy(AutoscaleConfig(
        min_replicas=2, max_replicas=3, up_pressure_s=0.4, up_after_s=0.15,
        down_pressure_s=0.05, down_after_s=0.4, cooldown_s=0.3))
    router = Router(pool, balancer=Balancer(), policy=policy,
                    default_rate=250.0)
    pool.spawn()
    pool.spawn()
    # the contending training job: low priority, exactly the remaining 4
    # devices, every-iter synchronous cadence saves so the cooperative
    # preemption point is an exact checkpoint (the PR 9/14 determinism
    # contract), warm session compile cache for velocity
    sched.submit(JobSpec(
        job_id="train-lowpri", priority=0, min_devices=4, max_devices=4,
        model_config={**TINY_CFG, "n_train": 64, "n_epochs": 3},
        rule_config={"checkpoint_every_n_iters": 1,
                     "checkpoint_async": False},
        env={**_child_env(), "THEANOMPI_DATA_TRACE": trace},
        extra_args=["--compile-cache-dir", subproc_compile_cache],
        max_restarts=3, backoff_base=0.1))
    t, box = _run_fleet(sched)
    try:
        # spike only once training has really consumed a step — the
        # preemption must interrupt work in flight
        deadline = time.perf_counter() + 300
        while time.perf_counter() < deadline and not _trace(trace):
            time.sleep(0.02)
        assert _trace(trace), "training never completed a step"
        # the spike: a 24-request burst of long generations, then a light
        # trickle that keeps the loop alive long enough for the
        # down-hysteresis window to elapse while the pool is near-idle
        entries = synthetic_entries(24, vocab=256, prompt_len=4,
                                    max_new_tokens=50, rate=0.0, seed=0)
        tail = synthetic_entries(6, 256, 4, 4, 0.0, 1)
        for i, e in enumerate(tail):
            e["rid"] = 100 + i
            e["arrival_s"] = 2.0 + 0.3 * i
        results, wall = drive_traffic(router, entries + tail,
                                      poll_s=0.01, timeout_s=120)
        router.drain_all()
    finally:
        t.join(300)
    assert not t.is_alive(), "fleet scheduler hung"
    assert box["rc"] == EXIT_CLEAN

    # -- serving: every request exactly one terminal state, spike absorbed
    rep = router.report(wall_s=wall)
    assert rep["exactly_once"] is True
    assert rep["requests"] == 30 and rep["answered"] == 30
    assert rep["terminal_states"] == {"done": 30}
    assert rep["replicas_peak"] == 3, rep["replica_trajectory"]
    # pressure subsided: the pool shrank back before the final drain
    assert rep["replica_trajectory"][-1][1] <= 2, rep["replica_trajectory"]
    assert rep["replicas_dead"] == 0 and rep["duplicates"] == 0

    # -- the fleet story: lease via preemption, drain, elastic resume
    rec = read_record(fleet_dir, "train-lowpri")
    assert rec.status == "done"
    assert rec.preemptions == 1 and rec.episodes == 2
    assert rec.preempt_exits == [EXIT_PREEMPTED]   # cooperative 75
    events = read_fleet_events(fleet_dir)
    names = [e["event"] for e in events]
    assert "fleet.preempt" in names    # autoscale leased by preempting
    assert "fleet.resume" in names     # training got its devices back
    preempt = [e for e in events if e["event"] == "fleet.preempt"][0]
    assert preempt["job"] == "train-lowpri"
    assert preempt["victim_of"].startswith("replica-")
    # scale-down is the graceful queue-sentinel drain, not a SIGTERM: the
    # drained replica finishes its queue and COMPLETES, releasing the
    # lease — and only then can training (min 4 devices) resume.  The
    # event order is the lease-release witness.
    first_replica_done = next(i for i, e in enumerate(events)
                              if e["event"] == "fleet.complete"
                              and e["job"].startswith("replica-"))
    train_resume = next(i for i, e in enumerate(events)
                        if e["event"] == "fleet.resume"
                        and e["job"] == "train-lowpri")
    assert first_replica_done < train_resume
    # all three replica jobs ended clean — drained, never preempted
    for jid in pool.replicas:
        r = read_record(fleet_dir, jid)
        assert r.status == "done" and r.preemptions == 0, jid

    # -- numerics: bit-equal to the uncontended run ---------------------------
    # same mesh4 before and after the preemption, every-iter cadence
    # saves: the resumed trajectory must be EXACTLY the uncontended one —
    # the trace gap-free and the final params bit-identical
    ck_ref = str(tmp_path / "ck_ref")
    ref_trace = str(tmp_path / "trace_ref")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", ref_trace)
    _bsp(4, ck_ref, n_epochs=3, model_over={"n_train": 64},
         checkpoint_every_n_iters=1, checkpoint_async=False).wait()
    assert _trace(trace) == _trace(ref_trace)
    _assert_ckpt_equal(
        os.path.join(job_dir(fleet_dir, "train-lowpri"), "ckpt",
                     "ckpt_e0002.npz"),
        os.path.join(ck_ref, "ckpt_e0002.npz"))


def test_router_chaos_sigkill_replica_absorbed_exactly_once(tmp_path):
    """SIGKILL one of two replicas mid-traffic (satellite 3): the router
    marks it dead, redistributes its orphaned rids to the survivor, the
    floor backfill re-leases its chips to a fresh replica, and the
    REQUESTS.jsonl dedup keeps every terminal state exactly-once even
    though some rids were queued on two replicas across the kill."""
    fleet_dir = str(tmp_path / "fleet")
    sched = FleetScheduler(fleet_dir, 8, poll_s=0.01, telemetry=False)
    pool = ReplicaPool(sched, _replica_spec(ms_per_token=6))
    # min == max == 2: the policy's only job here is the floor backfill
    policy = AutoscalePolicy(AutoscaleConfig(
        min_replicas=2, max_replicas=2, up_pressure_s=10.0, up_after_s=5.0,
        down_pressure_s=0.01, down_after_s=30.0, cooldown_s=0.1))
    router = Router(pool, balancer=Balancer(), policy=policy,
                    default_rate=150.0)
    pool.spawn()
    pool.spawn()
    t, box = _run_fleet(sched)
    killed = []

    def chaos(router, now):
        # one kill, after the pool has demonstrably served something
        if killed or not router.results:
            return
        victim = router.pool.replicas[0]
        pid_file = os.path.join(router.pool.jdir(victim), "replica.pid")
        if not os.path.exists(pid_file):
            return
        os.kill(int(open(pid_file).read()), signal.SIGKILL)
        killed.append(victim)

    try:
        entries = synthetic_entries(20, vocab=256, prompt_len=4,
                                    max_new_tokens=40, rate=0.0, seed=0)
        results, wall = drive_traffic(router, entries, poll_s=0.01,
                                      timeout_s=120, between_ticks=chaos)
        router.drain_all()
    finally:
        t.join(120)
    assert not t.is_alive(), "fleet scheduler hung"
    assert killed, "chaos hook never fired"
    rep = router.report(wall_s=wall)
    assert rep["exactly_once"] is True
    assert rep["duplicates"] == 0
    assert set(results) == set(range(20))
    assert rep["replicas_dead"] == 1
    assert rep["redistributed"] > 0          # orphans moved, not lost
    assert rep["replicas_spawned"] >= 3      # the backfill replica
    assert rep["max_attempts"] >= 2          # some rid needed a 2nd home
    # the dead replica's job failed (SIGKILL, max_restarts=0) but the
    # fleet as a whole still drained; its lease was re-leased
    assert read_record(fleet_dir, killed[0]).status == "failed"
    events = read_fleet_events(fleet_dir)
    scheduled = [e["job"] for e in events if e["event"] == "fleet.schedule"]
    assert len(scheduled) >= 3


def test_router_two_replicas_beat_one_on_p99_ttft(tmp_path):
    """The capacity witness: the identical burst trace through 1 replica
    vs 2 — with queue wait dominating, the 2-replica p99 router-visible
    TTFT (queue wait + replica TTFT) must be strictly below the
    1-replica baseline."""
    def run(n_replicas, sub):
        sched = FleetScheduler(str(tmp_path / sub), 8, poll_s=0.01,
                               telemetry=False)
        pool = ReplicaPool(sched, _replica_spec(ms_per_token=3))
        router = Router(pool, balancer=Balancer(), policy=None,
                        default_rate=300.0)
        for _ in range(n_replicas):
            pool.spawn()
        t, box = _run_fleet(sched)
        try:
            entries = synthetic_entries(16, vocab=256, prompt_len=4,
                                        max_new_tokens=30, rate=0.0,
                                        seed=0)
            results, wall = drive_traffic(router, entries, poll_s=0.01,
                                          timeout_s=120)
            router.drain_all()
        finally:
            t.join(120)
        assert not t.is_alive() and box["rc"] == EXIT_CLEAN
        rep = router.report(wall_s=wall)
        assert rep["exactly_once"] is True
        return rep

    rep1 = run(1, "one")
    rep2 = run(2, "two")
    # same total work, so per-request outcomes are comparable
    assert rep1["generated_tokens"] == rep2["generated_tokens"]
    p99_1 = rep1["ttft_ms"]["p99"]
    p99_2 = rep2["ttft_ms"]["p99"]
    assert p99_2 < p99_1, (p99_1, p99_2)
    # and not marginally: the burst is ~16 serial generations, so two
    # replicas should roughly halve the tail wait
    assert p99_2 < 0.8 * p99_1, (p99_1, p99_2)
