"""ISSUE 18 parity locks: the serving decode fast path.

Three layers of lock, all CPU tier-1:

- **kernel vs fallback, bit-for-bit** — the pallas paged-decode kernel
  (``interpret=True``) and the pure-JAX blockwise fallback compute the
  SAME online-softmax recurrence in the same op order, so their outputs
  must be bit-identical across null-block padding, prefix-shared blocks
  (PR 17's copy-on-write cache) and ragged per-slot positions.
- **fallback vs the PR 17 formula** — the fallback was restructured from
  one global softmax into the blockwise recurrence; the two are the same
  math up to the rounding association of the normalizer, pinned here
  against the VERBATIM old formula at ~1e-6.
- **int8 kernel vs dequantize-then-matmul** — same int8 payload, the
  only difference is scale association (``(x*s) @ q`` vs ``x @ (s*q)``),
  so the tolerance is plain fp32 rounding, never quantization error.
  Engine-level: kernel-on decode logits bit-equal to kernel-off
  (unquantized) and argmax-identical (quantized — the PR 9 lock's bar).

Engine tests ride the session ``serving_engine_factory`` fixture
(compile-light: each configuration's decode program compiles once per
tier-1 run).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.ops import quant
from theanompi_tpu.ops.pallas_paged_attention import (
    paged_attend_decode,
    paged_decode_supported,
)
from theanompi_tpu.serving import BlockPool, blocks_for
from theanompi_tpu.serving.kv_cache import PagedKVCache

_NEG_INF = -1e30


# -- kernel vs fallback: bit-for-bit ------------------------------------------

def _pools(key, nblocks, bs, h, d, dtype=jnp.float32):
    kk, kv = jax.random.split(key)
    shape = (1, nblocks, bs, h, d)
    return (jax.random.normal(kk, shape, jnp.float32).astype(dtype),
            jax.random.normal(kv, shape, jnp.float32).astype(dtype))


#: (tables, positions): null-block padding, a prefix-SHARED block between
#: slots, an inactive null slot, ragged non-block-multiple positions and
#: completely full tables
TABLE_CASES = [
    ([[1, 2, 0, 0], [3, 4, 5, 0]], [5, 11]),
    ([[1, 2, 0, 0], [1, 3, 0, 0]], [7, 6]),
    ([[1, 0, 0, 0], [0, 0, 0, 0]], [2, 0]),
    ([[5, 4, 3, 2], [2, 3, 4, 5]], [15, 12]),
]


@pytest.mark.parametrize("tables,positions", TABLE_CASES)
@pytest.mark.parametrize("h,d", [(2, 16), (4, 8)])
def test_kernel_bit_equal_to_fallback(tables, positions, h, d):
    bs, nblocks = 4, 6
    kp, vp = _pools(jax.random.PRNGKey(h * 100 + d), nblocks, bs, h, d)
    tbl = jnp.asarray(tables, jnp.int32)
    pos = jnp.asarray(positions, jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(7), (len(tables), h, d),
                          jnp.float32)
    outs = {}
    for impl in ("kernel_interpret", "fallback"):
        cache = PagedKVCache(kp, vp, tbl, bs, decode_impl=impl)
        outs[impl] = np.asarray(cache.attend_decode(0, q, pos))
    assert np.isfinite(outs["fallback"]).all()
    np.testing.assert_array_equal(outs["kernel_interpret"],
                                  outs["fallback"])


def test_kernel_bit_equal_to_fallback_bf16():
    """Same lock in the serving cache's bf16 dtype: both paths upcast to
    fp32 for the recurrence and downcast once at the end."""
    bs, nblocks, h, d = 4, 6, 2, 16
    kp, vp = _pools(jax.random.PRNGKey(3), nblocks, bs, h, d,
                    dtype=jnp.bfloat16)
    tbl = jnp.asarray([[1, 2, 3, 0], [4, 1, 0, 0]], jnp.int32)
    pos = jnp.asarray([9, 4], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(8), (2, h, d),
                          jnp.float32).astype(jnp.bfloat16)
    outs = {}
    for impl in ("kernel_interpret", "fallback"):
        cache = PagedKVCache(kp, vp, tbl, bs, decode_impl=impl)
        outs[impl] = np.asarray(cache.attend_decode(0, q, pos)
                                .astype(jnp.float32))
    np.testing.assert_array_equal(outs["kernel_interpret"],
                                  outs["fallback"])


# -- fallback vs the PR 17 global softmax -------------------------------------

def _global_softmax_reference(cache, layer, q, positions):
    """VERBATIM PR 17 ``attend_decode`` (one softmax over the gathered
    context) — the formula the blockwise recurrence replaced."""
    scale = q.shape[-1] ** -0.5
    kb = jnp.take(cache.k[layer], cache.block_tables, axis=0)
    vb = jnp.take(cache.v[layer], cache.block_tables, axis=0)
    b = q.shape[0]
    t_max = cache.max_context
    kb = kb.reshape(b, t_max, *kb.shape[3:])
    vb = vb.reshape(b, t_max, *vb.shape[3:])
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("bhd,bthd->bht", qf, kb.astype(jnp.float32))
    valid = jnp.arange(t_max)[None, :] <= positions[:, None]
    s = jnp.where(valid[:, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    ctx = jnp.einsum("bht,bthd->bhd", p, vb.astype(jnp.float32))
    return ctx.astype(q.dtype)


@pytest.mark.parametrize("tables,positions", TABLE_CASES)
def test_fallback_matches_the_pr17_global_softmax(tables, positions):
    bs, nblocks, h, d = 4, 6, 2, 16
    kp, vp = _pools(jax.random.PRNGKey(11), nblocks, bs, h, d)
    tbl = jnp.asarray(tables, jnp.int32)
    pos = jnp.asarray(positions, jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(12), (len(tables), h, d),
                          jnp.float32)
    cache = PagedKVCache(kp, vp, tbl, bs, decode_impl="fallback")
    got = np.asarray(cache.attend_decode(0, q, pos))
    ref = np.asarray(_global_softmax_reference(cache, 0, q, pos))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


# -- shape gates --------------------------------------------------------------

def test_compiled_shape_gates_and_raise():
    assert paged_decode_supported(8, 128)
    assert not paged_decode_supported(2, 128)
    assert not paged_decode_supported(8, 64)
    assert paged_decode_supported(16, 128, jnp.bfloat16)
    assert not paged_decode_supported(8, 128, jnp.bfloat16)
    bs, h, d = 4, 2, 16
    kp, vp = _pools(jax.random.PRNGKey(0), 3, bs, h, d)
    with pytest.raises(ValueError, match="unsupported shape"):
        paged_attend_decode(kp[0], vp[0],
                            jnp.asarray([[1, 2]], jnp.int32), bs,
                            jnp.zeros((1, h, d), jnp.float32),
                            jnp.asarray([3], jnp.int32), interpret=False)


# -- fused int8 matmul --------------------------------------------------------

def _qt(key, din, dout, chunk):
    w = jax.random.normal(key, (din, dout), jnp.float32)
    qq, ss = quant.quantize_chunked(w, jax.random.fold_in(key, 1), chunk)
    return w, quant.QuantizedTensor(qq, ss, (din, dout),
                                    jnp.dtype(jnp.float32))


@pytest.mark.parametrize("din,dout,chunk", [
    (32, 24, 24),    # case A: one row per chunk
    (32, 24, 48),    # case A: two rows per chunk
    (16, 48, 16),    # case B: three chunks per row
    (64, 32, 32),
])
def test_int8_matmul_matches_dequantize(din, dout, chunk):
    assert quant.int8_matmul_supported((din, dout), chunk)
    _, qt = _qt(jax.random.PRNGKey(din + dout), din, dout, chunk)
    x = jax.random.normal(jax.random.PRNGKey(2), (3, din), jnp.float32)
    got = np.asarray(quant.int8_matmul(x, qt, interpret=True))
    ref = np.asarray(x @ qt.dequantize())
    # same int8 payload; only the scale association differs -> fp rounding
    np.testing.assert_allclose(got, ref, rtol=1e-5,
                               atol=1e-5 * np.abs(ref).max())


def test_int8_matmul_leading_dims_and_m_padding():
    """x with extra leading dims and a row count that is not a multiple
    of the 8-row sublane pad: the kernel pads M internally and slices."""
    _, qt = _qt(jax.random.PRNGKey(5), 32, 24, 24)
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 5, 32), jnp.float32)
    got = np.asarray(quant.int8_matmul(x, qt, interpret=True))
    ref = np.asarray(x @ qt.dequantize())
    assert got.shape == (2, 5, 24)
    np.testing.assert_allclose(got, ref, rtol=1e-5,
                               atol=1e-5 * np.abs(ref).max())


def test_int8_supported_gate_and_matmul_any_fallback():
    # the serving head's odd vocab never tiles -> dequantize path
    assert not quant.int8_matmul_supported((32, 61), 1024)
    assert not quant.int8_matmul_supported((32,), 32)
    # interpret takes any tiling; COMPILED needs Mosaic-tileable bands
    assert quant.int8_matmul_supported((32, 24), 24)
    assert not quant.int8_matmul_supported((32, 24), 24, compiled=True)
    assert quant.int8_matmul_supported((256, 128), 128, compiled=True)
    # matmul_any on an unsupported leaf == dequantize-then-matmul exactly
    _, qt = _qt(jax.random.PRNGKey(9), 32, 61, 1024)
    x = jax.random.normal(jax.random.PRNGKey(10), (3, 32), jnp.float32)
    np.testing.assert_array_equal(np.asarray(quant.matmul_any(x, qt)),
                                  np.asarray(x @ qt.dequantize()))
    # and on a plain array it is exactly x @ w
    w = jax.random.normal(jax.random.PRNGKey(13), (32, 8), jnp.float32)
    np.testing.assert_array_equal(np.asarray(quant.matmul_any(x, w)),
                                  np.asarray(x @ w))


# -- engine level -------------------------------------------------------------

def _drive(engine, prompt, n_decode=10):
    """Prefill + greedy decode on slot 0; -> [(token, logits)] per step."""
    pool = BlockPool(engine.num_blocks)
    row = pool.alloc(blocks_for(len(prompt), engine.block_size))
    tok, last = engine.prefill(row, prompt, 0.0, rid=1)
    b = engine.max_batch
    tables = np.zeros((b, engine.max_blocks_per_seq), np.int32)
    tables[0, :len(row)] = row
    lengths = np.zeros(b, np.int32)
    lengths[0] = len(prompt)
    tokens = np.zeros(b, np.int32)
    tokens[0] = tok
    temps = np.zeros(b, np.float32)
    rids = np.zeros(b, np.int32)
    rids[0] = 1
    outs = [(int(tok), np.asarray(last))]
    for _ in range(n_decode):
        if lengths[0] % engine.block_size == 0:
            tables[0, lengths[0] // engine.block_size] = pool.alloc(1)[0]
        nxt, logits = engine.decode(tables, lengths, tokens, temps, rids)
        outs.append((int(nxt[0]), np.asarray(logits[0])))
        lengths[0] += 1
        tokens[0] = int(nxt[0])
    return outs


PROMPT = [7, 3, 11, 42, 5, 60, 1, 19, 23, 2]


def test_engine_kernel_on_bit_equal_logits(serving_engine,
                                           serving_engine_factory):
    """decode_kernel="on" (interpreter on CPU) vs the fallback engine:
    every decode step's logits are BIT-identical — the whole decode
    program differs only in the attend dispatch, and the two attends are
    the same recurrence."""
    eng_on = serving_engine_factory(decode_kernel="on")
    assert serving_engine.decode_impl == "fallback"
    assert eng_on.decode_impl == "kernel_interpret"
    off = _drive(serving_engine, PROMPT)
    on = _drive(eng_on, PROMPT)
    assert [t for t, _ in on] == [t for t, _ in off]
    for (_, lo), (_, lf) in zip(on, off):
        np.testing.assert_array_equal(lo, lf)


def test_engine_kernel_quantized_argmax_agreement(serving_engine_factory):
    """The PR 9 bar under the fused int8 kernel: the kernel-on quantized
    engine greedy-decodes the SAME tokens as the kernel-off quantized
    engine (whose path the PR 9 argmax-agreement lock covers), with
    logits within fp32-rounding tolerance of each other."""
    eng_off = serving_engine_factory(quantize_int8=True)
    eng_on = serving_engine_factory(quantize_int8=True, decode_kernel="on")
    assert eng_on.quantized and eng_off.quantized
    off = _drive(eng_off, PROMPT)
    on = _drive(eng_on, PROMPT)
    assert [t for t, _ in on] == [t for t, _ in off]
    for (_, lo), (_, lf) in zip(on, off):
        np.testing.assert_allclose(lo, lf, rtol=1e-5, atol=1e-5)
