"""Checkpointable deterministic data plane (ISSUE 10): derive_seed
stability, the Dataset iterator-state contract (start_batch fast-forward
bit-equality), the streaming token mixture's cursors, the Prefetcher's
consumed accounting, the data fault sites + retry telemetry, and the
``__data_state__`` manifest/payload round-trip through the Checkpointer.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from theanompi_tpu.models.data.base import (
    ArrayDataset,
    derive_seed,
    read_with_retry,
    release_data_stalls,
    set_data_hooks,
)
from theanompi_tpu.models.data.prefetch import Prefetcher, prefetch
from theanompi_tpu.models.data.stream import StreamTokenDataset

# ---------------------------------------------------------------------------
# derive_seed: the one seed-derivation helper
# ---------------------------------------------------------------------------


def test_derive_seed_range_and_position_sensitivity():
    s = derive_seed("augment", 0, 3, 11)
    assert isinstance(s, int) and 0 <= s < 2**31
    assert derive_seed("augment", 0, 3, 11) == s  # pure
    assert derive_seed("augment", 0, 11, 3) != s  # positions matter
    # unambiguous joining: adjacent parts never merge
    assert derive_seed("ab", "c") != derive_seed("a", "bc")
    assert derive_seed(12, 3) != derive_seed(1, 23)


def test_derive_seed_stable_across_processes():
    """The raison d'etre: ``hash()`` of a str changes per interpreter via
    PYTHONHASHSEED — derive_seed must not.  Two child interpreters with
    different hash seeds must agree with this process bit-for-bit."""
    prog = ("from theanompi_tpu.models.data.base import derive_seed;"
            "print(derive_seed('shuffle', 7, 3), derive_seed('x', 'y', -1))")
    outs = []
    for hashseed in ("1", "2"):
        import os

        env = dict(os.environ, PYTHONHASHSEED=hashseed,
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        env.pop("JAX_PLATFORMS", None)  # irrelevant: no jax import
        p = subprocess.run([sys.executable, "-c", prog], env=env,
                           capture_output=True, text=True, timeout=60)
        assert p.returncode == 0, p.stderr[-1000:]
        outs.append(p.stdout.strip())
    expect = f"{derive_seed('shuffle', 7, 3)} {derive_seed('x', 'y', -1)}"
    assert outs == [expect, expect]


# ---------------------------------------------------------------------------
# the iterator-state contract: start_batch tails are bit-equal
# ---------------------------------------------------------------------------


def _noisy_augment(x, rng):
    return x + rng.randn(*x.shape).astype(np.float32)


def _array_ds(n=48):
    r = np.random.RandomState(0)
    x = r.randn(n, 4).astype(np.float32)
    y = r.randint(0, 5, n).astype(np.int32)
    return ArrayDataset(x, y, x[:8], y[:8], 5, augment_fn=_noisy_augment)


def test_array_dataset_resume_tail_bit_equal_including_augment():
    """THE satellite lock: batch i's augmentation rng is keyed
    (seed, epoch, i), NOT drawn from the permutation's stream — so a
    cursor fast-forward to batch k reproduces batches k.. bit-equal."""
    ds = _array_ds()
    full = list(ds.train_batches(8, epoch=2, seed=5))
    assert len(full) == 6
    for k in (0, 1, 3, 5):
        tail = list(ds.train_batches(8, epoch=2, seed=5, start_batch=k))
        assert len(tail) == len(full) - k
        for a, b in zip(full[k:], tail):
            np.testing.assert_array_equal(a["x"], b["x"])
            np.testing.assert_array_equal(a["y"], b["y"])


def test_array_dataset_state_is_empty_and_accepted():
    ds = _array_ds()
    assert ds.state() == {}  # pure function of (seed, epoch, cursor)
    ds.set_state({})  # no-op, must not raise


def test_imagenet_synthetic_resume_tail_bit_equal():
    from theanompi_tpu.models.data.imagenet import ImageNetData

    d = ImageNetData({"image_size": 16, "store_size": 40, "n_classes": 5,
                      "n_train": 48, "n_val": 16, "shard_size": 16})
    full = list(d.train_batches(8, epoch=1, seed=3))
    tail = list(d.train_batches(8, epoch=1, seed=3, start_batch=2))
    assert len(tail) == len(full) - 2
    for a, b in zip(full[2:], tail):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])


# ---------------------------------------------------------------------------
# streaming token mixture (models/data/stream.py)
# ---------------------------------------------------------------------------


def _stream(**over):
    cfg = {"seq_len": 16, "n_train": 64, "n_val": 16, "vocab": 64}
    cfg.update(over)
    return StreamTokenDataset(cfg)


def test_stream_epoch_deterministic_and_cursors_advance():
    a = _stream()
    b = _stream()
    ba = list(a.train_batches(8, epoch=0, seed=1))
    bb = list(b.train_batches(8, epoch=0, seed=1))
    assert len(ba) == 8
    for x, y in zip(ba, bb):
        np.testing.assert_array_equal(x["x"], y["x"])
        np.testing.assert_array_equal(x["y"], y["y"])
    # the stream does not rewind: epoch 1 continues from epoch 0's cursors
    st = a.state()
    assert st["base_epoch"] == 1
    assert sum(st["cursors"].values()) == 8 * 8  # one window per sample
    e1 = next(iter(a.train_batches(8, epoch=1, seed=1)))
    e0 = ba[0]
    assert not np.array_equal(e1["x"], e0["x"])


def test_stream_mid_epoch_state_plus_cursor_resumes_bit_equal():
    """The tentpole contract end-to-end at dataset level: a fresh dataset
    restored from the START-of-epoch state, fast-forwarded by start_batch,
    yields exactly the uninterrupted epoch's remaining batches — no window
    replayed, none skipped."""
    a = _stream()
    list(a.train_batches(8, epoch=0, seed=1))  # advance into epoch 1
    saved = a.state()  # start-of-epoch-1 base cursors
    full = list(a.train_batches(8, epoch=1, seed=1))

    b = _stream()
    b.set_state(saved)
    tail = list(b.train_batches(8, epoch=1, seed=1, start_batch=3))
    assert len(tail) == len(full) - 3
    for x, y in zip(full[3:], tail):
        np.testing.assert_array_equal(x["x"], y["x"])
        np.testing.assert_array_equal(x["y"], y["y"])
    # and the post-epoch cursors agree: the fast-forward replayed the
    # consumed batches' mixture choices exactly
    assert a.state() == b.state()


def test_stream_sample_cursor_is_device_count_independent():
    """mesh8->4 elastic resume: the same sample cursor expressed at a
    DIFFERENT global batch size must continue the identical global sample
    order.  24 samples in as 3 batches of 8, or 6 batches of 4 — the
    remaining windows concatenate to the same sequence."""
    a = _stream()
    fulla = list(a.train_batches(8, epoch=0, seed=9))
    flat_full = np.concatenate([b["x"] for b in fulla])

    c = _stream()
    tail = list(c.train_batches(4, epoch=0, seed=9, start_batch=6))
    flat_tail = np.concatenate([b["x"] for b in tail])
    np.testing.assert_array_equal(flat_full[24:], flat_tail)


def test_stream_state_roundtrips_weights_and_validates():
    a = _stream()
    a.set_mixture_weights({"syn-a": 1.0, "syn-b": 3.0})
    st = a.state()
    assert st["weights"]["syn-b"] == pytest.approx(0.75)
    b = _stream()
    b.set_state(json.loads(json.dumps(st)))  # must survive JSON
    assert b.state() == st
    with pytest.raises(ValueError, match="missing sources"):
        b.set_state({"weights": {"syn-a": 1.0}})
    with pytest.raises(ValueError, match="positive"):
        b.set_mixture_weights({"syn-a": 0.0, "syn-b": 1.0})


def test_stream_file_sources_window_addressing(tmp_path):
    """On-disk shards via read_with_retry: windows never straddle shards
    (ragged tails dropped) and resume tails stay bit-equal."""
    src = tmp_path / "tok"
    src.mkdir()
    r = np.random.RandomState(0)
    # window_len = 17; shard0 holds 3 windows + ragged tail, shard1 holds 2
    np.save(src / "s0.npy", r.randint(0, 50, 3 * 17 + 5).astype(np.int32))
    np.save(src / "s1.npy", r.randint(0, 50, 2 * 17).astype(np.int32))
    ds = _stream(stream_sources=[
        {"name": "disk", "weight": 1.0, "path": str(src)}], n_train=16)
    assert ds._sources[0].n_windows == 5
    full = list(ds.train_batches(4, epoch=0, seed=0))
    ds2 = _stream(stream_sources=[
        {"name": "disk", "weight": 1.0, "path": str(src)}], n_train=16)
    tail = list(ds2.train_batches(4, epoch=0, seed=0, start_batch=2))
    for a, b in zip(full[2:], tail):
        np.testing.assert_array_equal(a["x"], b["x"])
    toks = np.load(src / "s0.npy")
    np.testing.assert_array_equal(ds._sources[0].window(1), toks[17:34])


def test_stream_pool_warm_load_matches_inline(tmp_path):
    """loader_workers > 0 warm-loads file shards through the shm pool's
    token mode; the batches must be bit-identical to inline reads (the
    pool changes WHO reads, never WHAT is read)."""
    src = tmp_path / "tok"
    src.mkdir()
    r = np.random.RandomState(7)
    for i in range(3):
        np.save(src / f"s{i}.npy", r.randint(0, 96, 4 * 17).astype(np.int32))
    spec = [{"name": "disk", "weight": 1.0, "path": str(src)}]
    inline = _stream(stream_sources=spec, n_train=32)
    pooled = _stream(stream_sources=spec, n_train=32, loader_workers=2)
    bi = list(inline.train_batches(8, epoch=0, seed=2))
    bp = list(pooled.train_batches(8, epoch=0, seed=2))
    assert len(bi) == len(bp) == 4
    for a, b in zip(bi, bp):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])
    assert inline.state() == pooled.state()


def test_transformer_lm_selects_stream_dataset():
    """dataset='stream' swaps the LM's data plane for the checkpointable
    token stream; batch shapes feed the trainer unchanged and the model's
    vocab follows the stream's."""
    from theanompi_tpu.models.transformer_lm import TransformerLM

    m = TransformerLM({"dim": 32, "heads": 2, "n_layers": 1, "seq_len": 16,
                       "vocab": 64, "dataset": "stream", "n_train": 32,
                       "n_val": 16, "batch_size": 8, "precision": "fp32",
                       "dropout": 0.0})
    assert isinstance(m.data, StreamTokenDataset)
    assert m.data.vocab == 64
    b = next(iter(m.data.train_batches(8, epoch=0, seed=0)))
    assert b["x"].shape == (8, 16) and b["y"].shape == (8, 16)
    np.testing.assert_array_equal(b["x"][:, 1:], b["y"][:, :-1])
    assert m.data.state()["cursors"]  # checkpointable position exists


def test_stream_val_batches_fixed():
    a = _stream()
    v1 = [b["x"].copy() for b in a.val_batches(8)]
    list(a.train_batches(8, epoch=0, seed=1))  # move the train cursors
    v2 = [b["x"] for b in a.val_batches(8)]
    for x, y in zip(v1, v2):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# Prefetcher consumed-cursor accounting
# ---------------------------------------------------------------------------


def test_prefetcher_consumed_excludes_inflight_queue():
    """state()['consumed'] counts batches HANDED to the consumer, not
    batches the worker ran ahead and queued: a restore from this snapshot
    replays nothing and skips nothing."""
    items = [{"x": np.full(2, i)} for i in range(10)]
    p = Prefetcher(iter(items), depth=4)
    try:
        assert p.state() == {"consumed": 0}
        for want in range(3):
            got = next(p)
            assert got["x"][0] == want
        # worker has run well ahead into the queue by now; consumed must
        # still be exactly what __next__ returned
        assert p.state() == {"consumed": 3}
    finally:
        p.close()


def test_prefetcher_start_batch_offsets_cursor_and_fault_ordinals():
    from theanompi_tpu.resilience.faults import FaultPlan

    items = [{"x": np.full(2, i)} for i in range(5, 8)]  # a resumed tail
    plan = FaultPlan.parse("prefetch:raise@6")
    p = Prefetcher(iter(items), depth=2, start_batch=5, fault_plan=plan)
    try:
        assert next(p)["x"][0] == 5  # ordinal 5: before the fault
        assert p.state() == {"consumed": 6}
        with pytest.raises(Exception, match="batch 6"):
            # the fault indexed by GLOBAL batch ordinal, not tail position
            next(p)
    finally:
        p.close()


def test_prefetch_depth_zero_keeps_raw_iterator():
    it = iter([1, 2])
    assert prefetch(it, depth=0, start_batch=3) is it


# ---------------------------------------------------------------------------
# data fault sites + data.retries telemetry (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


@pytest.mark.faultinject
def test_data_torn_read_is_retried_and_counted(tmp_path):
    from theanompi_tpu.telemetry import Telemetry
    from theanompi_tpu.telemetry.metrics import DATA_COUNTERS
    from theanompi_tpu.resilience.faults import FaultPlan

    tel = Telemetry(str(tmp_path), rank=0)
    set_data_hooks(telemetry=tel,
                   fault_plan=FaultPlan.parse("data:torn_read@1"))
    try:
        # ordinal 0: clean; ordinal 1: torn first attempt, retry succeeds
        assert read_with_retry(lambda: "a", what="s0",
                               sleep=lambda s: None) == "a"
        assert read_with_retry(lambda: "b", what="s1",
                               sleep=lambda s: None) == "b"
        assert tel.metrics.counters["data.retries"] == 1
        assert "data.retries" in DATA_COUNTERS  # registered name
    finally:
        set_data_hooks()
        tel.close()
    # the retry rode the sink as a counter event tagged with the resource
    events = [json.loads(line)
              for f in __import__("os").listdir(tmp_path)
              if f.startswith("events-rank")
              for line in open(tmp_path / f)]
    hits = [e for e in events if e.get("name") == "data.retries"]
    assert len(hits) == 1 and hits[0]["what"] == "s1"


@pytest.mark.faultinject
def test_data_stall_site_raises_when_released():
    from theanompi_tpu.resilience.faults import FaultInjected, FaultPlan

    set_data_hooks(fault_plan=FaultPlan.parse("data:stall@0"))
    try:
        release_data_stalls()  # pre-release: the wedge returns immediately
        with pytest.raises(FaultInjected, match="stall"):
            read_with_retry(lambda: "x", what="s0", sleep=lambda s: None)
        # the spec fired once; the next read is clean
        assert read_with_retry(lambda: "y", what="s1",
                               sleep=lambda s: None) == "y"
    finally:
        set_data_hooks()


def test_set_data_hooks_resets_read_ordinal():
    from theanompi_tpu.resilience.faults import FaultPlan

    set_data_hooks(fault_plan=FaultPlan.parse("data:torn_read@0"))
    try:
        calls = {"n": 0}

        def count():
            calls["n"] += 1
            return calls["n"]

        # the injected torn attempt REPLACES the read (fn never runs),
        # the retry then reads cleanly: one real call
        assert read_with_retry(count, what="a", sleep=lambda s: None) == 1
        # re-install: ordinal counter back to 0, a fresh plan fires again
        set_data_hooks(fault_plan=FaultPlan.parse("data:torn_read@0"))
        assert read_with_retry(count, what="b", sleep=lambda s: None) == 2
    finally:
        set_data_hooks()


# ---------------------------------------------------------------------------
# __data_state__ through the Checkpointer
# ---------------------------------------------------------------------------


def _tiny_trees():
    return {"params": {"w": np.arange(6, dtype=np.float32)}}


def _templates():
    return {"params": {"w": np.zeros(6, dtype=np.float32)}}


def test_checkpoint_data_state_roundtrip(tmp_path):
    from theanompi_tpu.utils.checkpoint import (
        DATA_STATE_LEAF,
        Checkpointer,
    )

    ds = {"version": 1, "epoch": 1, "completed": False, "batch_cursor": 3,
          "sample_cursor": 48, "global_batch": 16, "seed": 0,
          "dataset": {"cursors": {"syn-a": 40, "syn-b": 8}}}
    ck = Checkpointer(str(tmp_path), fingerprint={"mesh": {"data": 1}})
    ck.save(1, 3, _tiny_trees(), data_state=ds)
    ck.mark_clean()
    # the payload leaf is a real npz member (CRC + member-set covered) ...
    with np.load(tmp_path / "ckpt_e0001.npz") as z:
        assert DATA_STATE_LEAF in z.files
        assert json.loads(bytes(z[DATA_STATE_LEAF]).decode()) == ds
    # ... and the manifest carries the same dict
    man = json.load(open(tmp_path / "ckpt_e0001.manifest.json"))
    assert man["data_state"] == ds
    assert DATA_STATE_LEAF in man["leaves"]

    # a verified restore ignores the leaf in the trees but hands the
    # manifest (and so the data state) to the trainer
    ep, it, restored = ck.load_latest_verified(_templates())
    assert (ep, it) == (1, 3)
    assert set(restored) == {"params"}
    np.testing.assert_array_equal(restored["params"]["w"],
                                  np.arange(6, dtype=np.float32))
    assert ck.last_loaded_manifest["data_state"] == ds


def test_checkpoint_without_data_state_has_no_manifest_key(tmp_path):
    """Old-lineage byte-compatibility: data_state=None writes NO key and
    NO payload leaf — not a null — so pre-ISSUE-10 manifests and new
    stateless saves are indistinguishable."""
    from theanompi_tpu.utils.checkpoint import (
        DATA_STATE_LEAF,
        Checkpointer,
    )

    ck = Checkpointer(str(tmp_path), fingerprint={"mesh": {"data": 1}})
    ck.save(0, 2, _tiny_trees())
    ck.mark_clean()
    man = json.load(open(tmp_path / "ckpt_e0000.manifest.json"))
    assert "data_state" not in man
    with np.load(tmp_path / "ckpt_e0000.npz") as z:
        assert DATA_STATE_LEAF not in z.files
    ep, it, restored = ck.load_latest_verified(_templates())
    assert (ep, it) == (0, 2)
    assert ck.last_loaded_manifest.get("data_state") is None


def test_data_state_survives_verify_none_resume(tmp_path):
    """The legacy trust-latest.json path still best-effort loads the
    manifest on a single host, so a mid-epoch cursor is never silently
    dropped (which would SKIP the epoch remainder on resume)."""
    from theanompi_tpu.utils.checkpoint import Checkpointer

    ds = {"version": 1, "epoch": 0, "completed": False, "batch_cursor": 1,
          "sample_cursor": 16, "global_batch": 16, "seed": 0, "dataset": {}}
    ck = Checkpointer(str(tmp_path), fingerprint={"mesh": {"data": 1}})
    ck.save(0, 1, _tiny_trees(), data_state=ds)
    ck.mark_clean()
    ck2 = Checkpointer(str(tmp_path), fingerprint={"mesh": {"data": 1}})
    ep, it, _ = ck2.load_latest_verified(_templates(), verify="none")
    assert (ep, it) == (0, 1)
    assert ck2.last_loaded_manifest["data_state"] == ds
