"""Elastic resharded resume (ISSUE 8): the fingerprint gate, the
manifest-only reshard planner, zero1 flat-bucket re-layout, the elastic
supervisor, the scrubber dry-run CLI, and the acceptance e2e — a
supervised run SIGKILLed on mesh8 resumes under ``--elastic`` onto mesh4
and back onto mesh8 with a continuous loss curve.

Planner units run on handcrafted manifests (milliseconds, no training);
the training matrix reuses the tiny wide_resnet config every resilience
e2e shares so subprocess children hit one compile-cache entry.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax

from theanompi_tpu.resilience import (
    EXIT_RESHARD,
    FaultPlan,
    Supervisor,
    classify_exit,
)
from theanompi_tpu.utils import checkpoint as ck_mod
from theanompi_tpu.utils.checkpoint import (
    CheckpointFingerprintError,
    CheckpointReshardError,
    CheckpointReshardableMismatch,
    Checkpointer,
    build_manifest,
    check_fingerprint,
    plan_reshard,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_CFG = {"depth": 10, "widen": 1, "batch_size": 4, "image_size": 8,
            "n_train": 32, "n_val": 16, "n_epochs": 1, "precision": "fp32"}
TINY_ARGS = ["--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
             "--set", "image_size=8", "--set", "n_train=32",
             "--set", "n_val=16", "--set", "precision='fp32'"]


def _fp(n=8, strategy="psum", **over):
    fp = {"mesh": {"data": n, "pipe": 1, "model": 1, "seq": 1},
          "exchange": strategy, "n_subb": 1,
          "model": "WideResNet", "model_config_sha": "abc123"}
    fp.update(over)
    return fp


def _zero1_manifest(n=8, lr_scale=1.0):
    """A handcrafted zero1 manifest: params 5+4=9 payload elems in one
    bucket, padded to 16 at n=8 / 12 at n=4."""
    flat = {
        "params::conv/w": np.zeros((5,), np.float32),
        "params::fc/w": np.zeros((4,), np.float32),
        "state::bn/mean": np.zeros((2,), np.float32),
        "opt_state::velocity/0": np.zeros((9 + (-9) % n,), np.float32),
    }
    return build_manifest(3, 7, flat, _fp(n, "zero1"), lr_scale=lr_scale)


# -- planner units -----------------------------------------------------------

def test_plan_reshard_zero1_relayout():
    plan = plan_reshard(_zero1_manifest(8), _fp(4, "zero1"))
    assert (plan.old_n, plan.new_n) == (8, 4)
    assert plan.lr_scale == pytest.approx(0.5)
    assert plan.buckets == [(9, 16, 12)]
    # transform: payload preserved, old padding stripped, new padding zero
    arr = np.arange(16, dtype=np.float32)
    out = plan.transform_arrays({"opt_state::velocity/0": arr})
    np.testing.assert_array_equal(
        out["opt_state::velocity/0"],
        np.concatenate([np.arange(9), np.zeros(3)]).astype(np.float32))
    # growing direction too
    up = plan_reshard(_zero1_manifest(4), _fp(8, "zero1"))
    assert up.buckets == [(9, 12, 16)]
    assert up.lr_scale == pytest.approx(2.0)


def test_plan_reshard_composes_carried_lr_scale():
    """mesh8 -> mesh4 stamps x0.5; resharding that checkpoint back to 8
    must net exactly 1.0 against the originally tuned LR."""
    plan = plan_reshard(_zero1_manifest(4, lr_scale=0.5), _fp(8, "zero1"))
    assert plan.lr_scale == pytest.approx(1.0)


def test_plan_reshard_non_zero1_is_passthrough():
    flat = {"params::w": np.zeros((4,), np.float32),
            "opt_state::velocity/w": np.zeros((4,), np.float32)}
    man = build_manifest(0, 1, flat, _fp(8, "psum_bucket"))
    plan = plan_reshard(man, _fp(2, "psum_bucket"))
    assert plan.buckets is None and plan.lr_scale == pytest.approx(0.25)
    arrays = {"opt_state::velocity/w": np.arange(4.0)}
    assert plan.transform_arrays(arrays) is arrays  # identity, no copy


@pytest.mark.parametrize("target,match", [
    (_fp(4, "zero1", mesh={"data": 2, "model": 2}), "non-data axes"),
    (_fp(4, "psum"), "layout changes"),
    (_fp(4, "zero1", model_config_sha="zzz"), "model-identity"),
])
def test_plan_reshard_refusals(target, match):
    with pytest.raises(CheckpointReshardError, match=match):
        plan_reshard(_zero1_manifest(8), target)


def test_plan_reshard_refuses_tp_checkpoint():
    man = _zero1_manifest(8)
    man["fingerprint"]["mesh"] = {"data": 4, "model": 2}
    with pytest.raises(CheckpointReshardError, match="non-data axes"):
        plan_reshard(man, _fp(4, "zero1"))


def test_plan_reshard_refuses_rule_extras():
    flat = {"params::w": np.zeros((4,), np.float32),
            "extras::center/w": np.zeros((4,), np.float32)}
    man = build_manifest(0, 1, flat, _fp(8, "psum"))
    with pytest.raises(CheckpointReshardError, match="rule extras"):
        plan_reshard(man, _fp(4, "psum"))


def test_plan_reshard_refuses_without_fingerprint():
    man = build_manifest(0, 1, {"params::w": np.zeros((2,), np.float32)},
                         None)
    with pytest.raises(CheckpointReshardError, match="no run fingerprint"):
        plan_reshard(man, _fp(4, "psum"))


def test_plan_reshard_refuses_bucket_padding_mismatch():
    """A stored shard whose length disagrees with the recomputed layout
    (exch_bucket_mb changed between runs) must refuse, never truncate."""
    man = _zero1_manifest(8)
    man["leaves"]["opt_state::velocity/0"]["shape"] = [24]
    with pytest.raises(CheckpointReshardError, match="bucket"):
        plan_reshard(man, _fp(4, "zero1"))


def test_check_fingerprint_reshardable_vs_fatal():
    """Mismatch errors name the differing keys and are typed: topology
    keys -> CheckpointReshardableMismatch, model identity -> fatal."""
    man = {"fingerprint": _fp(8, "psum")}
    with pytest.raises(CheckpointReshardableMismatch) as ei:
        check_fingerprint(man, _fp(4, "psum_bucket"), "/x/ckpt_e0000.npz")
    msg = str(ei.value)
    assert "mesh" in msg and "exchange" in msg
    assert "--resume-reshard" in msg and "RESHARDABLE" in msg

    with pytest.raises(CheckpointFingerprintError) as ei:
        check_fingerprint(man, _fp(8, "psum", model_config_sha="zzz"),
                          "/x/ckpt_e0000.npz")
    assert not isinstance(ei.value, CheckpointReshardableMismatch)
    assert "model_config_sha" in str(ei.value)
    assert "NOT reshardable" in str(ei.value)


def test_reshard_fault_site_grammar():
    plan = FaultPlan.parse("reshard:fail@2")
    assert plan.fire("reshard", 1) is None
    assert plan.fire("reshard", 2) == "fail"
    assert plan.fire("reshard", 2) is None  # one-shot


def test_classify_exit_reshard_is_distinct():
    assert classify_exit(EXIT_RESHARD) == "reshard"


def test_reshard_telemetry_names_registered():
    from theanompi_tpu.telemetry.metrics import RESHARD_INSTANTS

    assert set(RESHARD_INSTANTS) == {"reshard.plan", "reshard.apply"}


# -- supervisor elastic mode (python -c children, milliseconds) --------------

def _script_child(tmp_path, body: str) -> list:
    return [sys.executable, "-c", body.replace("STATE", repr(str(tmp_path)))]


def test_supervisor_elastic_rewrites_devices_and_resumes_reshard(tmp_path):
    """Attempt 2 must carry the probed --devices value plus the reshard
    resume args, and the attempt record must log the device count."""
    body = """
import os, sys
marker = os.path.join(STATE, "n")
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
if n == 0:
    sys.exit(70)  # crash: the "pod lost chips" event
ok = ("--devices" in sys.argv
      and sys.argv[sys.argv.index("--devices") + 1] == "4"
      and "--resume-reshard" in sys.argv and "--resume" in sys.argv)
sys.exit(0 if ok else 71)
"""
    probes = iter([4])
    sup = Supervisor(
        _script_child(tmp_path, body) + ["--devices", "8"],
        max_restarts=2, backoff_base=0.0, jitter=0.0,
        resilience_path=str(tmp_path / "r.json"),
        sleep=lambda s: None, elastic=True,
        resume_args=("--resume", "--resume-reshard"),
        device_probe=lambda: next(probes))
    assert sup.run() == 0
    art = json.load(open(tmp_path / "r.json"))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]
    assert "devices" not in art["attempts"][0]  # first attempt: as asked
    assert art["attempts"][1]["devices"] == 4


def test_supervisor_elastic_probe_failure_keeps_topology(tmp_path):
    """An unknowable device count must not block the restart — the child
    runs with the previous topology unchanged."""
    body = """
import os, sys
marker = os.path.join(STATE, "n")
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
if n == 0:
    sys.exit(70)
ok = sys.argv[sys.argv.index("--devices") + 1] == "8"
sys.exit(0 if ok else 71)
"""

    def broken_probe():
        raise OSError("probe exploded")

    sup = Supervisor(
        _script_child(tmp_path, body) + ["--devices", "8"],
        max_restarts=2, backoff_base=0.0, jitter=0.0,
        resilience_path=str(tmp_path / "r.json"),
        sleep=lambda s: None, elastic=True, device_probe=broken_probe)
    assert sup.run() == 0


def test_supervisor_reshard_exit_is_fatal(tmp_path):
    """reshard fails -> classified fatal, no restart loop (the faults
    satellite's contract)."""
    body = f"""
import os, sys
marker = os.path.join(STATE, "n")
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
sys.exit(70 if n == 0 else {EXIT_RESHARD})
"""
    sup = Supervisor(
        _script_child(tmp_path, body), max_restarts=5,
        backoff_base=0.0, jitter=0.0,
        resilience_path=str(tmp_path / "r.json"),
        sleep=lambda s: None, elastic=True, device_probe=lambda: 4)
    assert sup.run() == EXIT_RESHARD
    art = json.load(open(tmp_path / "r.json"))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "reshard"]
    assert art["attempts"][1]["reshard"] == "failed"
    assert art["restarts"] == 1  # the reshard failure did NOT restart


def test_launcher_elastic_flag_implies_supervision():
    """--elastic parses, is stripped from the child argv, and the child
    flags include the reshard resume pair."""
    from theanompi_tpu import launcher

    args = launcher.build_parser().parse_args(
        ["--elastic", "--devices", "8"])
    assert args.elastic and not args.supervise  # main() promotes it
    stripped = launcher._strip_supervision_args(
        ["--elastic", "--supervise", "--max-restarts", "3",
         "--devices", "8"])
    assert stripped == ["--devices", "8"]


# -- scrubber CLI dry run ----------------------------------------------------

def _write_zero1_dir(tmp_path, n=8, strategy="zero1"):
    d = str(tmp_path / "ckpt")
    ck = Checkpointer(d, fingerprint=_fp(n, strategy))
    flat_trees = {
        "params": {"conv": {"w": np.zeros((5,), np.float32)},
                   "fc": {"w": np.zeros((4,), np.float32)}},
        "opt_state": {"velocity": [np.zeros((9 + (-9) % n,), np.float32)]},
    }
    ck.save(0, 3, flat_trees)
    ck.mark_clean()
    return d


def test_reshard_plan_cli_is_manifest_only(tmp_path, capsys):
    """--reshard-plan --to-devices N prints the planned re-layout without
    reading a checkpoint byte: a truncated .npz (live-writer torn state)
    must not stop the dry run."""
    d = _write_zero1_dir(tmp_path)
    # destroy the archive — only the manifest may be consulted
    npz = os.path.join(d, "ckpt_e0000.npz")
    with open(npz, "r+b") as f:
        f.truncate(4)
    rc = ck_mod.main(["--reshard-plan", d, "--to-devices", "4"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "reshard plan: 8 -> 4 workers" in out
    assert "bucket 0: payload 9 elems" in out
    assert "LR x0.5" in out
    assert "plannable" in out


def test_reshard_plan_cli_refusal_exit_code(tmp_path, capsys):
    d = _write_zero1_dir(tmp_path)
    # zero1 -> psum is a layout-family change: refused, exit 79
    rc = ck_mod.main(["--reshard-plan", d, "--to-devices", "4",
                      "--strategy", "psum"])
    assert rc == EXIT_RESHARD
    assert "REFUSED" in capsys.readouterr().out


def test_reshard_plan_cli_usage_errors(tmp_path):
    d = _write_zero1_dir(tmp_path)
    with pytest.raises(SystemExit) as ei:
        ck_mod.main(["--reshard-plan", d])  # missing --to-devices
    assert ei.value.code == 2
    with pytest.raises(SystemExit) as ei:
        ck_mod.main(["--verify", d, "--reshard-plan", d,
                     "--to-devices", "4"])  # mutually exclusive
    assert ei.value.code == 2


def test_reshard_gate_outranks_resume_force(tmp_path):
    """resume_force must not silently defeat resume_reshard: with both
    set, a topology-only mismatch is REPLANNED (strictly safer than
    force's blind restore of old-n shards into new-n templates), while a
    model-identity mismatch still honors the force override."""
    d = _write_zero1_dir(tmp_path)  # mesh8 zero1
    t4 = {"params": {"conv": {"w": np.zeros((5,), np.float32)},
                     "fc": {"w": np.zeros((4,), np.float32)}},
          "opt_state": {"velocity": [np.zeros((12,), np.float32)]}}
    ck = Checkpointer(d, fingerprint=_fp(4, "zero1"), reshard=True,
                      resume_force=True, sweep_debris=False)
    ep, _, restored = ck.load_latest_verified(t4)
    assert ck.last_reshard_plan is not None  # resharded, NOT blind-forced
    assert restored["opt_state"]["velocity"][0].shape == (12,)

    # fatal (model-identity) mismatch + force: the documented blind
    # override still works, and no plan is invented for it
    t8 = {"params": t4["params"],
          "opt_state": {"velocity": [np.zeros((16,), np.float32)]}}
    ck2 = Checkpointer(d, reshard=True, resume_force=True,
                       sweep_debris=False,
                       fingerprint=_fp(8, "zero1", model_config_sha="zzz"))
    ep, _, _ = ck2.load_latest_verified(t8)
    assert ep == 0 and ck2.last_reshard_plan is None


def test_lr_scale_survives_verify_none(tmp_path):
    """The legacy no-verify resume path must still carry a resharded
    lineage's cumulative LR factor (best-effort manifest read)."""
    d = str(tmp_path / "ck")
    ck = Checkpointer(d, fingerprint=_fp(4, "psum"))
    tree = {"params": {"w": np.zeros((3,), np.float32)}}
    ck.save(0, 2, tree, lr_scale=0.5)
    ck.mark_clean()
    ck2 = Checkpointer(d, fingerprint=_fp(4, "psum"), sweep_debris=False)
    res = ck2.load_latest_verified(
        {"params": {"w": np.zeros((3,), np.float32)}}, verify="none")
    assert res is not None
    assert ck2.last_loaded_manifest["lr_scale"] == pytest.approx(0.5)


def test_supervisor_probe_rejects_nonsense_counts(tmp_path, monkeypatch):
    """A probed count of 0 (or a bogus THEANOMPI_ELASTIC_DEVICES) is a
    FAILED probe — the previous topology is kept, never --devices 0."""
    body = """
import os, sys
marker = os.path.join(STATE, "n")
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
if n == 0:
    sys.exit(70)
sys.exit(0 if sys.argv[sys.argv.index("--devices") + 1] == "8" else 71)
"""
    sup = Supervisor(
        _script_child(tmp_path, body) + ["--devices", "8"],
        max_restarts=2, backoff_base=0.0, jitter=0.0,
        resilience_path=str(tmp_path / "r.json"),
        sleep=lambda s: None, elastic=True, device_probe=lambda: 0)
    assert sup.run() == 0
    # the env-override route validates identically
    monkeypatch.setenv("THEANOMPI_ELASTIC_DEVICES", "0")
    sup2 = Supervisor(["true"], elastic=True,
                      resilience_path=str(tmp_path / "r2.json"))
    assert sup2._probe_devices(2) is None


def test_reshard_refuses_verify_none(tmp_path):
    """--resume-reshard + checkpoint_verify='none' is a typed refusal:
    the plan is computed from the manifest that verify='none' skips.
    An EMPTY directory is still a fresh start, not a refusal — an
    elastic restart that crashed before its first checkpoint must
    restart, not die with exit 79."""
    empty = Checkpointer(str(tmp_path / "empty"), reshard=True)
    assert empty.load_latest_verified({}, verify="none") is None

    d = _write_zero1_dir(tmp_path)
    ck = Checkpointer(d, fingerprint=_fp(4, "zero1"), reshard=True,
                      sweep_debris=False)
    with pytest.raises(CheckpointReshardError, match="verified loads"):
        ck.load_latest_verified({}, verify="none")


def test_reshard_plan_cli_rejects_unknown_strategy(tmp_path):
    """A --strategy typo must be a usage error, not a false 'plannable'
    verdict the real resume would then reject."""
    d = _write_zero1_dir(tmp_path)
    with pytest.raises(SystemExit) as ei:
        ck_mod.main(["--reshard-plan", d, "--to-devices", "4",
                     "--strategy", "psumbucket"])
    assert ei.value.code == 2


def test_supervisor_ignores_stale_reshard_events(tmp_path):
    """A fresh elastic supervisor over a directory holding YESTERDAY'S
    reshard.apply events must not stamp today's first attempt as
    'applied' — only events newer than this run count."""
    from theanompi_tpu.resilience.events import record_event

    rpath = str(tmp_path / "r.json")
    record_event(rpath, "reshard.apply", epoch=0, old_n=8, new_n=4)
    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(0)"],
                     max_restarts=0, resilience_path=rpath,
                     sleep=lambda s: None, elastic=True,
                     device_probe=lambda: 4)
    assert sup.run() == 0
    art = json.load(open(rpath))
    assert "reshard" not in art["attempts"][0]
    assert [e["name"] for e in art["events"]] == ["reshard.apply"]  # carried


# -- training matrix (in-process, tiny wide_resnet) --------------------------

def _rule(devices, n_epochs, ck, strategy, **cfg):
    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "checkpoint_dir": ck,
                       "exch_strategy": strategy, **cfg})
    rule.init(devices=devices, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY_CFG, "n_epochs": n_epochs})
    return rule


def _assert_params_match_ckpt(trainer, ck, epoch):
    leaves = jax.tree_util.tree_flatten_with_path(trainer.params)[0]
    with np.load(os.path.join(ck, f"ckpt_e{epoch:04d}.npz")) as z:
        for path, leaf in leaves:
            key = "params::" + ck_mod._leaf_key(path)
            np.testing.assert_array_equal(np.asarray(leaf), z[key],
                                          err_msg=key)


def test_reshard_roundtrip_psum_bucket(tmp_path):
    """mesh8 -> mesh4 -> mesh8 for psum_bucket: each resume restores the
    checkpoint params EXACTLY (replicated params re-place bit-equal), the
    LR factor tracks 1.0 -> 0.5 -> 1.0, the run completes with a
    continuous epoch sequence, and the blind resume refuses."""
    ck = str(tmp_path / "ck")
    _rule(8, 1, ck, "psum_bucket").wait()

    down = _rule(4, 2, ck, "psum_bucket", resume_reshard=True)
    # a blind (non-reshard) consumer at the same mesh4 topology still
    # refuses the mesh8 checkpoint with the typed, actionable mismatch
    blind = Checkpointer(ck, fingerprint=down.trainer._run_fingerprint(),
                         sweep_debris=False)
    with pytest.raises(CheckpointReshardableMismatch, match="mesh"):
        blind.verify_epoch(0)
    assert down.trainer.epoch == 1  # epoch 0 resumed, not restarted
    assert down.trainer.lr_scale == pytest.approx(0.5)
    _assert_params_match_ckpt(down.trainer, ck, 0)  # exact-param equality
    down.wait()
    assert down.trainer.epoch == 2

    up = _rule(8, 3, ck, "psum_bucket", resume_reshard=True)
    assert up.trainer.epoch == 2
    assert up.trainer.lr_scale == pytest.approx(1.0)  # back to baseline
    _assert_params_match_ckpt(up.trainer, ck, 1)
    up.wait()
    assert up.trainer.epoch == 3
    # loss-curve continuity: one val entry per epoch, no resets, finite
    hist = up.trainer.recorder.val_history
    assert hist["epoch"] == [0, 1, 2]
    assert np.isfinite(hist["cost"]).all()
    # audit trail: both transitions planned AND applied
    events = json.load(open(os.path.join(ck, "resilience.json")))["events"]
    names = [e["name"] for e in events]
    assert names.count("reshard.plan") == 2
    assert names.count("reshard.apply") == 2
    # final lineage is stamped with the mesh8 topology again
    man = json.load(open(os.path.join(ck, "ckpt_e0002.manifest.json")))
    assert man["fingerprint"]["mesh"]["data"] == 8
    assert man["lr_scale"] == pytest.approx(1.0)


def test_reshard_roundtrip_zero1_opt_state_survives(tmp_path):
    """The zero1 matrix: flat-bucket optimizer shards survive mesh8 ->
    mesh4 -> mesh8 payload-exactly (old padding stripped, new padding
    zero), and the re-scattered state trains on to completion."""
    ck = str(tmp_path / "ck")
    _rule(8, 1, ck, "zero1").wait()
    with np.load(os.path.join(ck, "ckpt_e0000.npz")) as z:
        saved = {k: z[k] for k in z.files
                 if k.startswith("opt_state::velocity/")}
    assert saved  # zero1 really stored flat buckets

    down = _rule(4, 2, ck, "zero1", resume_reshard=True)
    t = down.trainer
    _assert_params_match_ckpt(t, ck, 0)
    layout = t.exchanger.zero1_layout(t.params, 4)
    for key, old in saved.items():
        i = int(key.rsplit("/", 1)[1])
        new = np.asarray(t.opt_state["velocity"][i])
        elems = layout[i].elems
        np.testing.assert_array_equal(new[:elems], old[:elems], err_msg=key)
        assert not new[elems:].any()  # re-padding is zeros
    down.wait()

    up = _rule(8, 3, ck, "zero1", resume_reshard=True)
    t = up.trainer
    with np.load(os.path.join(ck, "ckpt_e0001.npz")) as z:
        for i, bucket in enumerate(t.exchanger.zero1_layout(t.params, 8)):
            old = z[f"opt_state::velocity/{i}"]
            new = np.asarray(t.opt_state["velocity"][i])
            np.testing.assert_array_equal(new[:bucket.elems],
                                          old[:bucket.elems])
    up.wait()
    assert t.epoch == 3
    assert t.lr_scale == pytest.approx(1.0)


@pytest.mark.faultinject
def test_elastic_supervised_sigkill_shrink_and_grow(tmp_path,
                                                    subproc_compile_cache):
    """THE acceptance scenario: a supervised zero1 run SIGKILLed one step
    into epoch 1 on mesh8 restarts under --elastic onto mesh4 (the probe
    says 4 chips survived), is SIGKILLed again one step into epoch 2, and
    finishes back on mesh8 — continuous loss curve, correct epoch count,
    reshard.plan/reshard.apply recorded, per-attempt device counts in
    resilience.json."""
    ck = str(tmp_path / "ck")
    rec = str(tmp_path / "rec")
    child = [sys.executable, "-m", "theanompi_tpu.launcher",
             "--rule", "BSP", "--devices", "8",
             "--modelfile", "theanompi_tpu.models.wide_resnet",
             "--modelclass", "WideResNet", *TINY_ARGS,
             # n_train=64 -> 2 steps/epoch on mesh8, 4 on mesh4: the kills
             # land one full step AFTER each epoch boundary, so the async
             # checkpoint writer has a step's worth of time to publish
             "--set", "n_train=64", "--set", "n_epochs=3",
             "--rule-set", "exch_strategy=zero1",
             "--checkpoint-dir", ck, "--record-dir", rec,
             "--compile-cache-dir", subproc_compile_cache, "--quiet"]
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_THREEFRY_PARTITIONABLE": "true",
        "PYTHONPATH": REPO,
        # attempt 1 (mesh8, 2 steps/epoch): kill at iteration 3 = epoch
        # 1's second step, after e0000 published; attempt 2 (mesh4, 4
        # steps/epoch, resumed at iteration 2): kill at iteration 7 =
        # epoch 2's second step, after e0001 published
        "THEANOMPI_FAULT_PLAN": "step:kill@3@1,step:kill@7@2",
    }
    probes = iter([4, 8])  # attempt 2 sees 4 chips, attempt 3 sees 8 again
    sup = Supervisor(
        child, max_restarts=3, backoff_base=0.1, jitter=0.0,
        resilience_path=os.path.join(ck, "resilience.json"),
        resume_args=("--resume", "--resume-reshard"),
        elastic=True, device_probe=lambda: next(probes),
        env=env, sleep=lambda s: None)
    os.makedirs(ck, exist_ok=True)
    rc = sup.run()
    art = json.load(open(os.path.join(ck, "resilience.json")))
    assert rc == 0, art
    assert [a["cause"] for a in art["attempts"]] == [
        "crash", "crash", "clean"]
    assert art["attempts"][0]["exit_code"] == -signal.SIGKILL
    assert art["attempts"][1]["devices"] == 4
    assert art["attempts"][2]["devices"] == 8
    assert art["attempts"][1]["reshard"] == "applied"  # 8 -> 4 mid-attempt
    assert art["attempts"][2]["reshard"] == "applied"  # 4 -> 8
    names = [e["name"] for e in art["events"]]
    assert names.count("reshard.plan") == 2
    assert names.count("reshard.apply") == 2
    # continuous loss curve + correct epoch count across both transitions
    val = np.load(os.path.join(rec, "val_history.npy"),
                  allow_pickle=True).item()
    assert list(val["epoch"]) == [0, 1, 2]
    assert np.isfinite(val["cost"]).all()
    # the final checkpoint is back on mesh8, fully verifiable, LR x1.0
    man = json.load(open(os.path.join(ck, "ckpt_e0002.manifest.json")))
    assert man["fingerprint"]["mesh"]["data"] == 8
    assert man["fingerprint"]["exchange"] == "zero1"
    assert man["lr_scale"] == pytest.approx(1.0)
    ck_mod.verify_file(os.path.join(ck, "ckpt_e0002.npz"), "full")


@pytest.mark.faultinject
def test_launcher_reshard_fault_exits_79(tmp_path, capsys):
    """The reshard:fail fault site drives the launcher's one-line error
    contract: CheckpointReshardError -> exit EXIT_RESHARD=79."""
    from theanompi_tpu import launcher
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.utils.checkpoint import model_fingerprint

    # a mesh8 checkpoint whose params are never read: the injected fault
    # fires between plan and apply, so only the manifest matters — but the
    # model identity must match or the mismatch would be fatal, not
    # reshardable
    model = WideResNet(dict(TINY_CFG))
    ck = str(tmp_path / "ck")
    writer = Checkpointer(ck, fingerprint={
        "mesh": {"data": 8, "pipe": 1, "model": 1, "seq": 1},
        "exchange": "psum", "n_subb": 1, **model_fingerprint(model)})
    writer.save(0, 1, {"params": {"w": np.zeros((2,), np.float32)}})
    writer.mark_clean()

    rc = launcher.main([
        "--rule", "BSP", "--devices", "4",
        "--modelfile", "theanompi_tpu.models.wide_resnet",
        "--modelclass", "WideResNet", *TINY_ARGS,
        "--checkpoint-dir", ck, "--resume-reshard",
        "--rule-set", "fault_plan=reshard:fail@1", "--quiet"])
    assert rc == EXIT_RESHARD
    err = capsys.readouterr().err
    assert "tmlauncher: error: reshard: CheckpointReshardError" in err
    assert "Traceback" not in err
