"""Rule-value comparison harness (VERDICT #5): BSP vs EASGD vs GOSGD.

The full grid at realistic targets is a bench-time artifact; here the
harness itself is proven: train-to-target early-stops correctly, every rule
row carries the steps/epochs/wall-clock accounting, and the artifact is
valid JSON on disk.
"""

import json

import numpy as np

from theanompi_tpu.utils.rulecomp import compare_rules, default_rulesets

FAST = {
    "depth": 10,
    "widen": 1,
    "batch_size": 8,
    "image_size": 8,
    "n_train": 128,
    "n_val": 64,
    "precision": "fp32",
    "lr": 0.05,
}


def test_compare_rules_artifact(tmp_path, mesh8):
    out = tmp_path / "rulecomp.json"
    art = compare_rules(
        devices=8, model_config=FAST, target_error=2.0,  # trivially reached
        max_epochs=3,
        rules=[("bsp", "BSP", {}), ("easgd_tau2", "EASGD", {"tau": 2})],
        out_path=str(out), verbose=False,
    )
    assert json.loads(out.read_text()) == art
    assert [r["rule"] for r in art["results"]] == ["bsp", "easgd_tau2"]
    for row in art["results"]:
        # target error 2.0 is reached at the first validation -> early stop
        assert row["reached"] and row["epochs_to_target"] == 0
        assert row["epochs_run"] == 1 and row["steps_run"] > 0
        assert row["steps_to_target"] == row["steps_run"]
        assert row["wall_s"] > 0
        assert len(row["val_error_curve"]) == row["epochs_run"]
        assert np.isfinite(row["best_val_error"])


def test_compare_rules_runs_to_max_epochs(mesh8):
    art = compare_rules(
        devices=8, model_config=FAST, target_error=0.0,  # unreachable
        max_epochs=2, rules=[("gosgd", "GOSGD", {})], verbose=False,
    )
    (row,) = art["results"]
    assert not row["reached"] and row["epochs_to_target"] is None
    assert row["epochs_run"] == 2


def test_warmup_compiles_then_resets(mesh8):
    """warmup() must leave the trainer at a fresh deterministic init."""
    import jax

    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.easgd import EASGDTrainer

    def fresh():
        t = EASGDTrainer(WideResNet({**FAST, "n_epochs": 1}), mesh=mesh8, tau=4)
        t.compile_iter_fns()
        t.init_state()
        return t

    t, ref = fresh(), fresh()
    t.warmup()
    assert t.iteration == 0 and t.epoch == 0
    for a, b in zip(jax.tree.leaves(t.params), jax.tree.leaves(ref.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(t.center), jax.tree.leaves(ref.center)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_warmup_resets_gosgd_host_schedule(mesh8):
    """Post-warmup GOSGD must replay the same push/shift draws as a fresh
    trainer.  The gossip schedule is stateless per iteration (ISSUE 20:
    ``_round_draws`` derives from (seed, iteration) alone, so a resumed
    lineage replays bit-equal), which makes the invariant hold by
    construction — warmup cannot perturb it."""
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.gosgd import GOSGDTrainer

    def fresh():
        t = GOSGDTrainer(WideResNet({**FAST, "n_epochs": 1}), mesh=mesh8)
        t.compile_iter_fns()
        t.init_state()
        return t

    t, ref = fresh(), fresh()
    t.warmup()
    for it in range(3):
        push, shift = t._round_draws(it)
        ref_push, ref_shift = ref._round_draws(it)
        assert np.asarray(push).tolist() == np.asarray(ref_push).tolist()
        assert int(shift) == int(ref_shift)


def test_default_rulesets_cover_verdict_grid():
    names = [n for n, _, _ in default_rulesets()]
    assert names == ["bsp", "easgd_tau1", "easgd_tau4", "easgd_tau16", "gosgd"]


def test_lr_sweep_reports_each_rule_at_its_best(mesh8):
    """VERDICT r2 #6: with a sweep, each rule's reported row must be its
    best-performing lr, with the full sweep recorded for audit."""
    from theanompi_tpu.utils.rulecomp import compare_rules

    art = compare_rules(
        devices=8,
        model_config=dict(FAST),
        target_error=0.9,  # easy target: tiny runs still differentiate lrs
        max_epochs=2,
        rules=[("bsp", "BSP", {})],
        lr_sweep=(0.005, 0.05),
        verbose=False,
    )
    row = art["results"][0]
    assert art["lr_sweep"] == [0.005, 0.05]
    assert len(row["sweep"]) == 2
    assert row["base_lr"] in (0.005, 0.05)
    swept = {s["base_lr"] for s in row["sweep"]}
    assert swept == {0.005, 0.05}
    # the chosen row must be at least as good as every swept row on the
    # primary criteria (reached, then epochs-to-target)
    if any(s["reached"] for s in row["sweep"]):
        assert row["reached"]
        best_epochs = min(s["epochs_to_target"] for s in row["sweep"]
                          if s["reached"])
        assert row["epochs_to_target"] == best_epochs


def test_rule_config_sweep_crosses_with_lr(mesh8):
    """VERDICT r3 #8 machinery: a 4-tuple ruleset sweeps rule-config
    overrides jointly with lr, and each swept row records its overrides."""
    from theanompi_tpu.utils.rulecomp import compare_rules

    art = compare_rules(
        devices=8,
        model_config=dict(FAST),
        target_error=0.9,
        max_epochs=1,
        rules=[("easgd_tau4", "EASGD", {"tau": 4},
                [{"alpha": 0.05}, {"alpha": 0.3}])],
        lr_sweep=(0.01, 0.05),
        verbose=False,
    )
    row = art["results"][0]
    assert len(row["sweep"]) == 4  # 2 lrs x 2 alphas
    combos = {(s["base_lr"], s["rule_overrides"]["alpha"])
              for s in row["sweep"]}
    assert combos == {(0.01, 0.05), (0.01, 0.3), (0.05, 0.05), (0.05, 0.3)}


def test_localsgd_rule_averages_params(mesh8):
    """The EASGD control: after one exchange, all worker copies equal the
    pre-exchange mean (plain averaging, no elastic force)."""
    import jax
    import numpy as np

    from theanompi_tpu import LocalSGD

    rule = LocalSGD(config={"tau": 2, "seed": 0, "verbose": False})
    rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**FAST, "n_epochs": 1})
    t = rule.trainer
    # two local steps diverge the workers (per-worker rng), third triggers
    # the tau=2 exchange inside post_step at iteration 2
    batches = list(t.model.data.train_batches(t.global_batch, 0, seed=0))
    t.train_iter(batches[0], lr=0.05)
    leaf = np.asarray(jax.tree.leaves(t.params)[0])
    assert not np.allclose(leaf[0], leaf[1]), "workers did not diverge"
    t.train_iter(batches[1 % len(batches)], lr=0.05)  # iteration 2 -> avg
    leaf = np.asarray(jax.tree.leaves(t.params)[0])
    np.testing.assert_allclose(leaf[0], leaf[1], rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(leaf[0], leaf.mean(0), rtol=1e-6, atol=1e-7)
