"""End-to-end BSP slice: Wide-ResNet on (synthetic) CIFAR-10, 8-worker mesh.

This is BASELINE.md config 1 ("Wide-ResNet on CIFAR-10, single BSP worker,
CPU mode") plus the multi-worker shape of config 2, on the fake CPU mesh.
"""

import numpy as np
import pytest

from theanompi_tpu import BSP

TINY = {
    "depth": 10,
    "widen": 1,
    "batch_size": 8,  # per worker
    "n_epochs": 6,
    "lr": 0.05,
    "weight_decay": 0.0,
    "n_train": 256,
    "n_val": 64,
    "augment": False,
    "precision": "fp32",
    "verbose": False,
}


def _run(devices, config=None, model_config=None):
    rule = BSP(config={"verbose": False, "print_freq": 4, **(config or {})})
    rule.init(
        devices=devices,
        modelfile="theanompi_tpu.models.wide_resnet",
        modelclass="WideResNet",
        model_config={**TINY, **(model_config or {})},
    )
    return rule.wait()

@pytest.mark.slow
def test_bsp_8worker_learns():
    rec = _run(devices=8)
    costs = rec.val_history["cost"]
    assert len(costs) == 6
    assert costs[-1] < costs[0], f"val cost did not decrease: {costs}"
    # synthetic blobs are very learnable: error should drop well below chance
    assert rec.val_history["error"][-1] < 0.2
    # recorder captured time splits
    assert len(rec.time_history["calc"]) == 6 * (256 // 64)


@pytest.mark.slow
def test_bsp_single_worker_matches_api():
    rec = _run(devices=1, model_config={"n_epochs": 1, "n_train": 64})
    assert len(rec.val_history["cost"]) == 1


@pytest.mark.slow
def test_bsp_ring_strategy_e2e():
    rec = _run(
        devices=8,
        config={"exch_strategy": "ring"},
        model_config={"n_epochs": 1, "n_train": 128},
    )
    assert np.isfinite(rec.val_history["cost"][0])


@pytest.mark.slow
def test_bsp_replicas_stay_in_sync():
    """After training, params must be identical on every device."""
    import jax

    rule = BSP(config={"verbose": False})
    rule.init(
        devices=8,
        modelfile="theanompi_tpu.models.wide_resnet",
        modelclass="WideResNet",
        model_config={**TINY, "n_epochs": 1, "n_train": 64},
    )
    rule.wait()
    leaf = jax.tree.leaves(rule.trainer.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(s, shards[0])
