"""End-to-end BSP slice: Wide-ResNet on (synthetic) CIFAR-10, 8-worker mesh.

This is BASELINE.md config 1 ("Wide-ResNet on CIFAR-10, single BSP worker,
CPU mode") plus the multi-worker shape of config 2, on the fake CPU mesh.
"""

import numpy as np
import pytest

from theanompi_tpu import BSP

TINY = {
    "depth": 10,
    "widen": 1,
    "batch_size": 8,  # per worker
    "n_epochs": 6,
    "lr": 0.05,
    "weight_decay": 0.0,
    "n_train": 256,
    "n_val": 64,
    "augment": False,
    "precision": "fp32",
    "verbose": False,
}


def _run(devices, config=None, model_config=None):
    rule = BSP(config={"verbose": False, "print_freq": 4, **(config or {})})
    rule.init(
        devices=devices,
        modelfile="theanompi_tpu.models.wide_resnet",
        modelclass="WideResNet",
        model_config={**TINY, **(model_config or {})},
    )
    return rule.wait()

def test_scalar_hoisting_caches_lr_and_carries_step():
    """ISSUE 2 satellite: the per-step jnp.float32(lr)/jnp.int32(step)
    host->device transfers are hoisted — the placed lr is reused until the
    schedule changes it, and the step counter rides the compiled step's
    `_next_step` output instead of re-crossing the host boundary."""
    import jax

    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.recorder import Recorder

    model = WideResNet({**TINY, "batch_size": 2, "image_size": 8,
                        "n_train": 16, "n_epochs": 1})
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]),
                   recorder=Recorder(verbose=False, print_freq=10**9))
    t.compile_iter_fns()
    t.init_state()
    batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
    t.train_iter(batches[0], lr=0.05)
    lr_dev = t._lr_dev
    assert t._step_dev is not None and int(t._step_dev) == 1
    t.train_iter(batches[1], lr=0.05)
    assert t._lr_dev is lr_dev, "same lr must reuse the placed scalar"
    assert int(t._step_dev) == 2, "step must carry as a device scalar"
    t.train_iter(batches[2], lr=0.01)
    assert t._lr_dev is not lr_dev, "schedule change must re-place the lr"
    # external counter changes (reset/resume) invalidate the carried step
    t.reset_iter()
    t.train_iter(batches[3], lr=0.01)
    assert int(t._step_dev) == 1


@pytest.mark.slow
def test_bsp_8worker_learns():
    rec = _run(devices=8)
    costs = rec.val_history["cost"]
    assert len(costs) == 6
    assert costs[-1] < costs[0], f"val cost did not decrease: {costs}"
    # synthetic blobs are very learnable: error should drop well below chance
    assert rec.val_history["error"][-1] < 0.2
    # recorder captured time splits
    assert len(rec.time_history["calc"]) == 6 * (256 // 64)


@pytest.mark.slow
def test_bsp_single_worker_matches_api():
    rec = _run(devices=1, model_config={"n_epochs": 1, "n_train": 64})
    assert len(rec.val_history["cost"]) == 1


@pytest.mark.slow
def test_bsp_ring_strategy_e2e():
    rec = _run(
        devices=8,
        config={"exch_strategy": "ring"},
        model_config={"n_epochs": 1, "n_train": 128},
    )
    assert np.isfinite(rec.val_history["cost"][0])


@pytest.mark.slow
def test_bsp_replicas_stay_in_sync():
    """After training, params must be identical on every device."""
    import jax

    rule = BSP(config={"verbose": False})
    rule.init(
        devices=8,
        modelfile="theanompi_tpu.models.wide_resnet",
        modelclass="WideResNet",
        model_config={**TINY, "n_epochs": 1, "n_train": 64},
    )
    rule.wait()
    leaf = jax.tree.leaves(rule.trainer.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(s, shards[0])
