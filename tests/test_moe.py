"""Expert parallelism: the switch-routed MoE FFN over the 'model' axis.

Invariants: token conservation under routing (a token reaches at most one
expert slot; dropped tokens contribute zero and survive via the residual),
and dp2 x ep4 numerical equivalence with the single-device model — forward
AND gradients (capacity_factor is set so nothing drops on either side,
making the comparison exact rather than routing-dependent).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.models.transformer_lm import MoETransformerLM
from theanompi_tpu.ops.moe import MoEFFN
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import MODEL_AXIS, make_mesh, shard_map

CFG = {"batch_size": 8, "n_train": 64, "n_val": 32, "seq_len": 16,
       "vocab": 32, "dim": 32, "heads": 4, "n_layers": 2, "dropout": 0.0,
       "n_experts": 8, "capacity_factor": 8.0,  # = n_experts: no drops
       "l2": 1e-4,
       "n_epochs": 1, "precision": "fp32"}


def test_moe_layer_single_device_shapes_and_aux():
    layer = MoEFFN(dim=16, n_experts=4, capacity_factor=4.0)
    params, state, _ = layer.init(jax.random.PRNGKey(0), (8, 16))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
    y, new_state = layer.apply(params, state, x)
    assert y.shape == x.shape
    assert np.isfinite(float(new_state["aux"]))
    # Switch aux loss is >= 1 (perfect balance) by Cauchy-Schwarz
    assert float(new_state["aux"]) >= 0.99


def test_moe_tight_capacity_drops_but_stays_finite():
    """capacity_factor << 1: most tokens drop; output stays finite and the
    dropped tokens' contribution is exactly zero (residual carries them)."""
    layer = MoEFFN(dim=8, n_experts=2, capacity_factor=0.1)
    params, state, _ = layer.init(jax.random.PRNGKey(1), (32, 8))
    x = jnp.asarray(np.random.RandomState(1).randn(1, 32, 8), jnp.float32)
    y, _ = layer.apply(params, state, x)
    assert np.isfinite(np.asarray(y)).all()
    # with cap = ceil(32*0.1/2) = 2 per expert, at most 4 rows are nonzero
    nonzero_rows = int((np.abs(np.asarray(y)[0]).sum(-1) > 1e-9).sum())
    assert nonzero_rows <= 4


def test_moe_ep4_matches_single_device():
    """dp2 x ep4 BSP training must track the unsharded model: 3 steps of
    losses and a replicated + an expert-sharded param leaf."""
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])

    def run(mesh, cfg, steps=3):
        model = MoETransformerLM(cfg)
        t = BSPTrainer(model, mesh=mesh)
        t.compile_iter_fns()
        t.init_state()
        batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
        costs = [
            float(t.train_iter(batches[i % len(batches)], lr=1e-2)["cost"])
            for i in range(steps)
        ]
        return t, costs

    t1, c1 = run(mesh1, dict(CFG))
    mesh_ep = make_mesh(n_data=2, n_model=4)
    t2, c2 = run(mesh_ep, {**CFG, "batch_size": CFG["batch_size"] // 2})
    np.testing.assert_allclose(c1, c2, rtol=3e-4, atol=3e-5)

    # gate (replicated) must match; experts (sharded) compare via gather
    def leafmap(t):
        return {
            "gate": np.asarray(
                t.params["net"]["02__moeblock"]["moe"]["gate"]["w"]
                if "net" in t.params else
                t.params["02__moeblock"]["moe"]["gate"]["w"]),
            "up_w": np.asarray(
                t.params["net"]["02__moeblock"]["moe"]["up_w"]
                if "net" in t.params else
                t.params["02__moeblock"]["moe"]["up_w"]),
        }

    a, b = leafmap(t1), leafmap(t2)
    np.testing.assert_allclose(a["gate"], b["gate"], rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(a["up_w"], b["up_w"], rtol=3e-4, atol=3e-5)


def test_moe_param_specs_shard_experts_only():
    model = MoETransformerLM(dict(CFG))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    specs = model.param_specs(params)

    def find(tree, key):
        for k, v in tree.items():
            if k == key:
                return v
            if isinstance(v, dict):
                r = find(v, key)
                if r is not None:
                    return r
        return None

    moe = find(specs, "moe")
    assert moe["up_w"] == P(MODEL_AXIS)
    assert moe["down_b"] == P(MODEL_AXIS)
    assert moe["gate"]["w"] == P()


def test_moe_ep4_drop_regime_per_rank_capacity():
    """Dropping regime under ep>1 (ADVICE r2): capacity is enforced per
    rank-chunk, so a routing skew concentrated in one chunk drops tokens a
    single-device (global-pool) run would keep — and tokens kept by BOTH
    runs produce identical outputs.  This pins the documented semantics
    instead of leaving the divergence unexercised."""
    D, E, N = 8, 2, 32
    layer = MoEFFN(dim=D, n_experts=E, capacity_factor=0.5)
    params, _, _ = layer.init(jax.random.PRNGKey(0), (N, D))
    # deterministic routing: feature 0 -> expert 0, feature 1 -> expert 1
    gate_w = np.zeros((D, E), np.float32)
    gate_w[0, 0] = gate_w[1, 1] = 10.0
    params = dict(params)
    params["gate"] = {"w": jnp.asarray(gate_w)}
    # tokens 0..15 (= ep-chunks 0 and 1) all want expert 0; 16..31 expert 1
    x = np.zeros((1, N, D), np.float32)
    x[0, :16, 0] = 1.0
    x[0, 16:, 1] = 1.0
    x += 0.01 * np.random.RandomState(0).randn(1, N, D).astype(np.float32)
    xj = jnp.asarray(x)

    y1, _ = layer.apply(params, {}, xj)  # single device: cap = ceil(32*.5/2)=8

    mesh = make_mesh(n_data=1, n_model=2)  # ep=2: E=2 experts, 1 per rank
    pspecs = {"gate": {"w": P()}, "up_w": P(MODEL_AXIS), "up_b": P(MODEL_AXIS),
              "down_w": P(MODEL_AXIS), "down_b": P(MODEL_AXIS)}
    f = jax.jit(shard_map(
        lambda p, x: layer.apply(p, {}, x)[0], mesh,
        in_specs=(pspecs, P()), out_specs=P(),
    ))
    placed = jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        dict(params), pspecs, is_leaf=lambda l: not isinstance(l, dict),
    )
    y4 = f(placed, xj)

    kept1 = np.abs(np.asarray(y1)[0]).sum(-1) > 1e-9
    kept4 = np.abs(np.asarray(y4)[0]).sum(-1) > 1e-9
    # single device: global pool cap=8 keeps 8 of the 16 expert-0 tokens;
    # ep=2: chunk 0 (= tokens 0..15, ALL expert 0) has per-rank cap
    # ceil(16*.5/2)=4 -> keeps only 4, though the global budget had room
    assert kept1[:16].sum() == 8
    assert kept4[:16].sum() == 4
    assert kept4[:4].sum() == 4
    both = kept1 & kept4
    assert both.sum() > 0
    np.testing.assert_allclose(
        np.asarray(y1)[0][both], np.asarray(y4)[0][both],
        rtol=1e-5, atol=1e-6,
    )
