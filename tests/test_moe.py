"""Expert parallelism: the switch-routed MoE FFN over the 'model' axis.

Invariants: token conservation under routing (a token reaches at most one
expert slot; dropped tokens contribute zero and survive via the residual),
and dp2 x ep4 numerical equivalence with the single-device model — forward
AND gradients (capacity_factor is set so nothing drops on either side,
making the comparison exact rather than routing-dependent).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.models.transformer_lm import MoETransformerLM
from theanompi_tpu.ops.moe import MoEFFN
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import MODEL_AXIS, make_mesh, shard_map

CFG = {"batch_size": 8, "n_train": 64, "n_val": 32, "seq_len": 16,
       "vocab": 32, "dim": 32, "heads": 4, "n_layers": 2, "dropout": 0.0,
       "n_experts": 8, "capacity_factor": 8.0,  # = n_experts: no drops
       "l2": 1e-4,
       "n_epochs": 1, "precision": "fp32"}


def test_moe_layer_single_device_shapes_and_aux():
    layer = MoEFFN(dim=16, n_experts=4, capacity_factor=4.0)
    params, state, _ = layer.init(jax.random.PRNGKey(0), (8, 16))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
    y, new_state = layer.apply(params, state, x)
    assert y.shape == x.shape
    assert np.isfinite(float(new_state["aux"]))
    # Switch aux loss is >= 1 (perfect balance) by Cauchy-Schwarz
    assert float(new_state["aux"]) >= 0.99


def test_moe_tight_capacity_drops_but_stays_finite():
    """capacity_factor << 1: most tokens drop; output stays finite and the
    dropped tokens' contribution is exactly zero (residual carries them)."""
    layer = MoEFFN(dim=8, n_experts=2, capacity_factor=0.1)
    params, state, _ = layer.init(jax.random.PRNGKey(1), (32, 8))
    x = jnp.asarray(np.random.RandomState(1).randn(1, 32, 8), jnp.float32)
    y, _ = layer.apply(params, state, x)
    assert np.isfinite(np.asarray(y)).all()
    # with cap = ceil(32*0.1/2) = 2 per expert, at most 4 rows are nonzero
    nonzero_rows = int((np.abs(np.asarray(y)[0]).sum(-1) > 1e-9).sum())
    assert nonzero_rows <= 4


def test_moe_ep4_matches_single_device():
    """dp2 x ep4 BSP training must track the unsharded model: 3 steps of
    losses and a replicated + an expert-sharded param leaf."""
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])

    def run(mesh, cfg, steps=3):
        model = MoETransformerLM(cfg)
        t = BSPTrainer(model, mesh=mesh)
        t.compile_iter_fns()
        t.init_state()
        batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
        costs = [
            float(t.train_iter(batches[i % len(batches)], lr=1e-2)["cost"])
            for i in range(steps)
        ]
        return t, costs

    t1, c1 = run(mesh1, dict(CFG))
    mesh_ep = make_mesh(n_data=2, n_model=4)
    t2, c2 = run(mesh_ep, {**CFG, "batch_size": CFG["batch_size"] // 2})
    np.testing.assert_allclose(c1, c2, rtol=3e-4, atol=3e-5)

    # gate (replicated) must match; experts (sharded) compare via gather
    def leafmap(t):
        return {
            "gate": np.asarray(
                t.params["net"]["02__moeblock"]["moe"]["gate"]["w"]
                if "net" in t.params else
                t.params["02__moeblock"]["moe"]["gate"]["w"]),
            "up_w": np.asarray(
                t.params["net"]["02__moeblock"]["moe"]["up_w"]
                if "net" in t.params else
                t.params["02__moeblock"]["moe"]["up_w"]),
        }

    a, b = leafmap(t1), leafmap(t2)
    np.testing.assert_allclose(a["gate"], b["gate"], rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(a["up_w"], b["up_w"], rtol=3e-4, atol=3e-5)


def test_moe_param_specs_shard_experts_only():
    model = MoETransformerLM(dict(CFG))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    specs = model.param_specs(params)

    def find(tree, key):
        for k, v in tree.items():
            if k == key:
                return v
            if isinstance(v, dict):
                r = find(v, key)
                if r is not None:
                    return r
        return None

    moe = find(specs, "moe")
    assert moe["up_w"] == P(MODEL_AXIS)
    assert moe["down_b"] == P(MODEL_AXIS)
    assert moe["gate"]["w"] == P()
