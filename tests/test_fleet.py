"""ISSUE 11: multi-job fleet orchestration on the elastic supervisor.

Unit matrix on milliseconds-fast fakes: the device-pool ledger (gang
alloc, crash-safe two-generation persistence, the ``ledger_torn_write``
fault site), the priority queue, spec validation, child-command
construction, and the scheduler lifecycle driven by ``python -c``
children (completion, priority preemption + requeue + elastic resume,
``kill_job`` fault absorbed by the per-job supervisor, crash -> failed).
The ``tmfleet`` CLI contract (submit/status/run, tmlauncher exit codes)
runs on the same fakes.

THE acceptance e2e drives two REAL ``tmlauncher`` jobs through one
mesh8 pool: contention, priority preemption (exit 75 + cadence
checkpoint), elastic resume on the 4 devices that remain, completion —
with final params of BOTH jobs bit-equal to uncontended single-job runs
and a gap-free concatenated data trace (the PR 9 witness).
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from theanompi_tpu.fleet import (
    DeviceLedger,
    FleetScheduler,
    JobQueue,
    JobRecord,
    JobSpec,
    JobSpecError,
    LedgerError,
    build_child_cmd,
    job_dir,
    read_fleet_events,
    read_record,
    write_record,
)
from theanompi_tpu.fleet import cli as fleet_cli
from theanompi_tpu.resilience import (
    EXIT_CLEAN,
    EXIT_CONFIG,
    EXIT_CRASH,
    EXIT_PREEMPTED,
    FaultInjected,
    FaultPlan,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the resilience-e2e tiny config — fleet children reuse these shapes so
#: every subprocess hits the session compile cache other files warmed
TINY_CFG = {"depth": 10, "widen": 1, "batch_size": 4, "image_size": 8,
            "n_train": 32, "n_val": 16, "n_epochs": 2, "precision": "fp32"}


def _trace(path):
    """-> [(epoch, batch_index)] consumed-step witness lines."""
    if not os.path.exists(path):
        return []
    return [tuple(int(v) for v in line.split())
            for line in open(path) if line.strip()]


def _assert_ckpt_equal(path_a, path_b):
    with np.load(path_a) as a, np.load(path_b) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


# -- device-pool ledger -------------------------------------------------------

def test_ledger_gang_alloc_all_or_nothing(tmp_path):
    led = DeviceLedger(str(tmp_path), 8)
    assert led.free == 8
    assert led.alloc("a", 5)
    assert led.free == 3 and led.lease_of("a") == 5
    assert not led.alloc("b", 4)  # all-or-nothing: nothing changed
    assert led.free == 3 and led.lease_of("b") == 0
    assert led.alloc("b", 3)
    assert led.free == 0
    with pytest.raises(LedgerError, match="already holds"):
        led.alloc("a", 1)
    with pytest.raises(LedgerError, match="pool"):
        led.alloc("c", 9)  # impossible even on an empty pool
    with pytest.raises(LedgerError, match="pool"):
        led.alloc("c", 0)
    assert led.release("a") == 5
    assert led.free == 5
    assert led.release("a") == 0  # idempotent, not an error


def test_ledger_persists_reopens_and_probes(tmp_path, monkeypatch):
    d = str(tmp_path / "pool")
    led = DeviceLedger(d, 8)
    led.alloc("a", 3)
    re = DeviceLedger(d)  # size + leases come from the persisted state
    assert re.pool_size == 8 and re.lease_of("a") == 3 and re.free == 5
    with pytest.raises(LedgerError, match="conflicts"):
        DeviceLedger(d, 4)
    # fresh pool with no explicit size: the elastic probe seam (PR 8's
    # env override route — instant, no subprocess)
    monkeypatch.setenv("THEANOMPI_ELASTIC_DEVICES", "6")
    assert DeviceLedger(str(tmp_path / "fresh")).pool_size == 6
    monkeypatch.delenv("THEANOMPI_ELASTIC_DEVICES")
    with pytest.raises(LedgerError, match="pool"):
        DeviceLedger(str(tmp_path / "zero"), 0)


@pytest.mark.faultinject
def test_ledger_torn_write_recovers_previous_generation(tmp_path):
    d = str(tmp_path / "pool")
    # persist ordinal 0 is the fresh-pool publish; the alloc's persist
    # (ordinal 1) tears the just-committed main file in half
    plan = FaultPlan.parse("fleet:ledger_torn_write@1")
    led = DeviceLedger(d, 8, fault_plan=plan)
    led.alloc("a", 2)
    with pytest.raises(ValueError):
        json.load(open(os.path.join(d, "ledger.json")))  # really torn
    # the next load steps back one generation instead of crashing
    rec = DeviceLedger(d)
    assert rec.pool_size == 8
    assert rec.free == 8  # generation 0 predates the lease
    # every generation unreadable -> typed refusal
    for p in ("ledger.json", "ledger.json.prev"):
        with open(os.path.join(d, p), "w") as f:
            f.write("{torn")
    with pytest.raises(LedgerError, match="unreadable"):
        DeviceLedger(d)


@pytest.mark.faultinject
def test_fleet_fault_actions_count_separate_ordinals(tmp_path):
    """The action filter on FaultPlan.fire: a kill_job spec at ordinal 0
    must NOT be consumed by the ledger's persist counter (the two fleet
    actions count different ordinal spaces)."""
    plan = FaultPlan.parse("fleet:kill_job@0")
    led = DeviceLedger(str(tmp_path), 4, fault_plan=plan)
    led.alloc("a", 1)  # persists 0 and 1: neither may tear nor consume
    assert json.load(open(led.path))["leases"] == {"a": 1}
    assert plan.fire("fleet", 0, action="kill_job") == "kill_job"


# -- specs, records, queue ----------------------------------------------------

def test_job_spec_validation():
    with pytest.raises(JobSpecError, match="invalid job id"):
        JobSpec(job_id="-bad").validate()
    with pytest.raises(JobSpecError, match="invalid job id"):
        JobSpec(job_id="a b").validate()
    with pytest.raises(JobSpecError, match="min_devices"):
        JobSpec(job_id="a", min_devices=0).validate()
    with pytest.raises(JobSpecError, match="max_devices"):
        JobSpec(job_id="a", min_devices=4, max_devices=2).validate()
    JobSpec(job_id="ok.job-1_x", min_devices=2, max_devices=2).validate()


def test_job_record_roundtrip_and_unknown_keys(tmp_path):
    spec = JobSpec(job_id="j", priority=3, min_devices=2,
                   model_config={"depth": 10}, env={"K": "v"})
    rec = JobRecord(spec=spec, status="preempted", preemptions=1,
                    preempt_exits=[75])
    write_record(str(tmp_path), rec)
    back = read_record(str(tmp_path), "j")
    assert back == rec
    with pytest.raises(JobSpecError, match="unknown job-spec keys"):
        JobSpec.from_dict({"job_id": "j", "nope": 1})
    with pytest.raises(JobSpecError, match="unknown job status"):
        JobRecord.from_dict({"spec": spec.to_dict(), "status": "zombie"})


def test_job_queue_priority_then_fifo():
    q = JobQueue()
    for jid, pri in (("a", 0), ("b", 5), ("c", 5), ("d", 1)):
        q.push(JobSpec(job_id=jid, priority=pri))
    assert [s.job_id for s in q.ordered()] == ["b", "c", "d", "a"]
    with pytest.raises(JobSpecError, match="already queued"):
        q.push(JobSpec(job_id="b", priority=5))
    q.remove("b")
    assert len(q) == 3 and "b" not in q
    # a requeued victim keeps its original submit sequence: it does not
    # jump peers that were already waiting at its priority
    q2 = JobQueue()
    q2.push(JobSpec(job_id="x", priority=0))
    q2.push(JobSpec(job_id="y", priority=0))
    q2.remove("x")          # x runs, then is preempted...
    q2.push(JobSpec(job_id="x", priority=0))  # ...and re-enters
    assert [s.job_id for s in q2.ordered()] == ["x", "y"]


def test_build_child_cmd_launcher_and_argv_seam(tmp_path):
    spec = JobSpec(job_id="j", model_config={"depth": 10,
                                             "precision": "fp32"},
                   rule_config={"exch_strategy": "zero1"},
                   extra_args=["--quiet2"])
    cmd = build_child_cmd(spec, 4, str(tmp_path))
    assert cmd[:4] == [sys.executable, "-m", "theanompi_tpu.launcher",
                       "--rule"]
    assert "--devices" in cmd and cmd[cmd.index("--devices") + 1] == "4"
    # values ride the launcher's --set literal grammar via repr
    assert "depth=10" in cmd and "precision='fp32'" in cmd
    assert "exch_strategy='zero1'" in cmd
    assert "--resume" not in cmd
    resumed = build_child_cmd(spec, 2, str(tmp_path), resume=True)
    assert resumed[-2:] == ["--resume", "--resume-reshard"]
    # the argv test seam bypasses the launcher entirely
    fake = JobSpec(job_id="j", argv=["echo", "hi"])
    assert build_child_cmd(fake, 4, str(tmp_path), resume=True) == \
        ["echo", "hi"]


# -- scheduler on python -c fakes --------------------------------------------

#: a cooperative victim: SIGTERM -> exit 75, like a supervised trainer
#: whose preemption handler checkpointed; sleeps long on its first
#: episode (so a preemption can land), finishes fast on the second
_COOP = r'''
import os, signal, sys, time
signal.signal(signal.SIGTERM, lambda s, f: sys.exit(75))
marker = os.environ["FLEET_TEST_MARKER"]
open(marker, "a").write("ep\n")
time.sleep(6.0 if len(open(marker).readlines()) < 2 else 0.05)
'''


def _fake(job_id, body, **kw):
    return JobSpec(job_id=job_id, argv=[sys.executable, "-c", body],
                   max_restarts=kw.pop("max_restarts", 0), **kw)


def _run_sched(sched, timeout=60):
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "scheduler hung"
    return box["rc"]


def test_scheduler_runs_jobs_to_completion_and_frees_pool(tmp_path):
    d = str(tmp_path / "fleet")
    sched = FleetScheduler(d, 8, poll_s=0.01, telemetry=False)
    sched.submit(_fake("a", "pass", min_devices=2, max_devices=2))
    sched.submit(_fake("b", "pass", min_devices=2, max_devices=2))
    assert _run_sched(sched) == EXIT_CLEAN
    for jid in ("a", "b"):
        rec = read_record(d, jid)
        assert rec.status == "done" and rec.episodes == 1
        assert rec.devices is None and rec.last_exit == 0
    assert sched.ledger.free == 8  # every lease returned
    names = [e["event"] for e in read_fleet_events(d)]
    assert names.count("fleet.schedule") == 2
    assert names.count("fleet.complete") == 2


def test_scheduler_submit_rejects_bad_and_duplicate(tmp_path):
    sched = FleetScheduler(str(tmp_path), 4, telemetry=False)
    sched.submit(_fake("a", "pass"))
    with pytest.raises(JobSpecError, match="already exists"):
        sched.submit(_fake("a", "pass"))
    with pytest.raises(JobSpecError, match="pool has only"):
        sched.submit(_fake("big", "pass", min_devices=5))


def test_scheduler_crash_is_failed_and_exit_crash(tmp_path):
    d = str(tmp_path / "fleet")
    sched = FleetScheduler(d, 4, poll_s=0.01, telemetry=False)
    sched.submit(_fake("bad", "import sys; sys.exit(3)"))
    sched.submit(_fake("good", "pass"))
    assert _run_sched(sched) == EXIT_CRASH
    assert read_record(d, "bad").status == "failed"
    assert read_record(d, "bad").last_exit == 3
    assert read_record(d, "good").status == "done"
    fails = [e for e in read_fleet_events(d) if e["event"] == "fleet.fail"]
    assert fails and fails[0]["cause"] == "crash"


def test_scheduler_priority_preemption_and_elastic_resume(tmp_path):
    """The fake-child lifecycle: A (low priority) holds all 8; B (high
    priority, needs 4) preempts it; A exits 75, is requeued, and resumes
    on the 4 devices B left — the event log records the whole story in
    order."""
    d = str(tmp_path / "fleet")
    marker = str(tmp_path / "marker")
    sched = FleetScheduler(d, 8, poll_s=0.01, telemetry=False,
                           env={"FLEET_TEST_MARKER": marker})
    sched.submit(_fake("low", _COOP, priority=0, min_devices=1))
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline and not os.path.exists(marker):
        time.sleep(0.005)
    assert os.path.exists(marker), "job 'low' never started"
    sched.submit(_fake("high", "pass", priority=5,
                       min_devices=4, max_devices=4))
    t.join(60)
    assert not t.is_alive() and box["rc"] == EXIT_CLEAN

    low, high = read_record(d, "low"), read_record(d, "high")
    assert low.status == "done" and high.status == "done"
    assert low.preemptions == 1 and low.episodes == 2
    assert low.preempt_exits == [75]  # cooperative, not SIGKILLed
    assert high.preemptions == 0 and high.episodes == 1
    ev = read_fleet_events(d)
    story = [(e["event"], e["job"]) for e in ev]
    assert story[:4] == [("fleet.schedule", "low"),
                         ("fleet.preempt", "low"),
                         ("fleet.schedule", "high"),
                         ("fleet.resume", "low")]
    assert sorted(story[4:]) == [("fleet.complete", "high"),
                                 ("fleet.complete", "low")]
    assert ev[0]["devices"] == 8
    assert ev[1]["victim_of"] == "high"
    assert ev[2]["devices"] == 4
    assert ev[3]["devices"] == 4  # elastic: resumed on what remained


@pytest.mark.faultinject
def test_scheduler_kill_job_fault_absorbed_by_supervisor(tmp_path):
    """fleet:kill_job@0 SIGKILLs the first launched child; the JOB's own
    supervisor classifies a crash and restarts it in place — the fleet
    sees one episode, and the per-job resilience.json records both
    attempts."""
    d = str(tmp_path / "fleet")
    body = ("import os, sys, time\n"
            "time.sleep(30 if os.environ['THEANOMPI_ATTEMPT'] == '1' "
            "else 0)\n")
    sched = FleetScheduler(d, 4, poll_s=0.01, telemetry=False,
                           fault_plan="fleet:kill_job@0")
    sched.submit(_fake("j", body, max_restarts=2, backoff_base=0.0))
    assert _run_sched(sched) == EXIT_CLEAN
    rec = read_record(d, "j")
    assert rec.status == "done"
    assert rec.episodes == 1 and rec.preemptions == 0
    art = json.load(open(os.path.join(job_dir(d, "j"), "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]


def test_scheduler_picks_up_live_submits_and_preempts(tmp_path):
    """The BASELINE step-8 flow: `tmfleet submit` publishes a queued
    job.json into the fleet dir WHILE `tmfleet run` owns the pool — the
    running scheduler must adopt it on its next pass and let it contend
    (here: preempt the incumbent).  An unschedulable live submit is
    marked failed on disk instead of wedging the loop."""
    d = str(tmp_path / "fleet")
    marker = str(tmp_path / "marker")
    sched = FleetScheduler(d, 8, poll_s=0.01, telemetry=False,
                           env={"FLEET_TEST_MARKER": marker})
    sched.submit(_fake("low", _COOP, priority=0, min_devices=1))
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline and not os.path.exists(marker):
        time.sleep(0.005)
    assert os.path.exists(marker), "job 'low' never started"
    # the other-process half: a bare queued record on disk, NOT submit()
    write_record(d, JobRecord(spec=_fake("high", "pass", priority=5,
                                         min_devices=4, max_devices=4)))
    write_record(d, JobRecord(spec=_fake("toobig", "pass",
                                         min_devices=99)))
    t.join(60)
    assert not t.is_alive()
    assert read_record(d, "low").preemptions == 1
    assert read_record(d, "low").status == "done"
    assert read_record(d, "high").status == "done"
    assert read_record(d, "toobig").status == "failed"
    fails = [e for e in read_fleet_events(d) if e["event"] == "fleet.fail"]
    assert fails and fails[0]["job"] == "toobig"
    assert "config" in fails[0]["cause"]


def test_scheduler_adopts_records_from_a_dead_scheduler(tmp_path):
    """A fleet dir whose scheduler died mid-flight: running/preempting
    records re-enter as preempted (their cadence checkpoints are on
    disk), queued ones re-queue, terminal ones are left alone."""
    d = str(tmp_path / "fleet")
    DeviceLedger(d, 4).alloc("was-running", 4)  # the dead owner's lease
    for status in ("running", "queued", "done"):
        write_record(d, JobRecord(
            spec=_fake(f"was-{status}", "pass"), status=status,
            devices=4 if status == "running" else None))
    sched = FleetScheduler(d, 4, poll_s=0.01, telemetry=False)
    from theanompi_tpu.fleet.jobs import list_records

    for rec in list_records(d):
        if rec.status not in ("done", "failed"):
            sched.adopt(rec)
    assert sched.ledger.free == 4  # the stale lease was released
    assert _run_sched(sched) == EXIT_CLEAN
    assert read_record(d, "was-running").status == "done"
    assert read_record(d, "was-queued").status == "done"


# -- tmfleet CLI --------------------------------------------------------------

def test_tmfleet_submit_and_status_contract(tmp_path, capsys):
    d = str(tmp_path / "fleet")
    rc = fleet_cli.main([
        "submit", "--fleet-dir", d, "--job-id", "a", "--priority", "2",
        "--min-devices", "2", "--max-devices", "4",
        "--set", "depth=10", "--set", "precision='fp32'",
        "--rule-set", "exch_strategy='zero1'",
        "--extra-arg=--compile-cache-dir=/cache"])
    assert rc == EXIT_CLEAN
    assert "queued 'a'" in capsys.readouterr().out
    rec = read_record(d, "a")
    assert rec.status == "queued" and rec.spec.priority == 2
    # the --set literal grammar: ints stay ints, strings stay strings
    assert rec.spec.model_config == {"depth": 10, "precision": "fp32"}
    assert rec.spec.rule_config == {"exch_strategy": "zero1"}
    assert rec.spec.extra_args == ["--compile-cache-dir=/cache"]
    # duplicate + invalid specs take the launcher's config exit code
    assert fleet_cli.main(["submit", "--fleet-dir", d,
                           "--job-id", "a"]) == EXIT_CONFIG
    assert fleet_cli.main(["submit", "--fleet-dir", d, "--job-id", "b",
                           "--min-devices", "0"]) == EXIT_CONFIG
    err = capsys.readouterr().err
    assert "tmfleet: error: config:" in err
    assert fleet_cli.main(["status", "--fleet-dir", d]) == EXIT_CLEAN
    out = json.loads(capsys.readouterr().out)
    assert [j["spec"]["job_id"] for j in out["jobs"]] == ["a"]
    assert out["pool"] is None  # no scheduler has sized the pool yet
    # argparse usage errors keep argparse's own exit code
    assert fleet_cli.main(["submit"]) == 2
    assert fleet_cli.main(["bogus-subcommand"]) == 2


def test_tmfleet_run_drives_persisted_jobs(tmp_path, capsys):
    """``tmfleet run`` adopts every persisted non-terminal record —
    including a dead scheduler's in-flight job — and returns the fleet
    verdict; a bad --fault-plan is a config error."""
    d = str(tmp_path / "fleet")
    write_record(d, JobRecord(spec=_fake("q", "pass")))
    write_record(d, JobRecord(
        spec=_fake("inflight", "pass"), status="running", devices=2))
    rc = fleet_cli.main(["run", "--fleet-dir", d, "--pool-size", "4",
                         "--poll-s", "0.01"])
    assert rc == EXIT_CLEAN
    out = json.loads(capsys.readouterr().out)
    assert {j["status"] for j in out["jobs"]} == {"done"}
    assert out["pool"]["pool_size"] == 4 and out["pool"]["leases"] == {}
    assert fleet_cli.main(["run", "--fleet-dir", d, "--pool-size", "4",
                           "--fault-plan", "fleet:bogus@1"]) == EXIT_CONFIG
    # a failed job flips the verdict to the crash exit code
    d2 = str(tmp_path / "fleet2")
    write_record(d2, JobRecord(spec=_fake("bad", "import sys; sys.exit(9)")))
    assert fleet_cli.main(["run", "--fleet-dir", d2, "--pool-size", "2",
                           "--poll-s", "0.01", "--quiet"]) == EXIT_CRASH


def test_fleet_telemetry_names_registered():
    from theanompi_tpu.telemetry.metrics import FLEET_INSTANTS

    assert set(FLEET_INSTANTS) == {"fleet.schedule", "fleet.preempt",
                                   "fleet.resume", "fleet.complete",
                                   "fleet.fail", "fleet.hang",
                                   "fleet.drain"}


def test_fleet_fault_grammar():
    plan = FaultPlan.parse("fleet:kill_job@1;fleet:ledger_torn_write@2")
    assert plan.fire("fleet", 1, action="ledger_torn_write") is None
    assert plan.fire("fleet", 1, action="kill_job") == "kill_job"
    assert plan.fire("fleet", 1, action="kill_job") is None  # one-shot
    assert plan.fire("fleet", 2, action="ledger_torn_write") == \
        "ledger_torn_write"
    with pytest.raises(Exception, match="invalid for site"):
        FaultPlan.parse("fleet:stall@1")


# -- THE acceptance e2e -------------------------------------------------------

def _child_env():
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_THREEFRY_PARTITIONABLE": "true",
        "PYTHONPATH": REPO,
    }


def _bsp(devices, ck, n_epochs=2, model_over=None, **cfg):
    from theanompi_tpu import BSP

    rule = BSP(config={"verbose": False, "checkpoint_dir": ck, **cfg})
    rule.init(devices=devices, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY_CFG, "n_epochs": n_epochs,
                            **(model_over or {})})
    return rule


def _find_split(lines, n_train, gb_hi, gb_lo, n_epochs):
    """The unique index splitting a concatenated two-episode trace into
    the big-batch prefix (episode 1) and small-batch suffix (the elastic
    resume): the one split whose sample spans tile every epoch's
    [0, n_train) exactly, in order — the PR 9 no-replay/no-skip witness
    generalized across a global-batch change."""
    def valid(split):
        pos, epoch = 0, 0
        for i, (e, c) in enumerate(lines):
            gb = gb_hi if i < split else gb_lo
            if pos == n_train:
                epoch, pos = epoch + 1, 0
            if e != epoch or c * gb != pos:
                return False
            pos += gb
        return epoch == n_epochs - 1 and pos == n_train
    hits = [s for s in range(len(lines) + 1) if valid(s)]
    assert len(hits) == 1, f"ambiguous or impossible trace split: {hits}"
    return hits[0]


def test_fleet_two_job_contention_preempt_elastic_resume_bit_equal(
        tmp_path, monkeypatch, subproc_compile_cache):
    """THE acceptance scenario, end to end on the CPU mesh8 pool:

    Job A (low priority, zero1, takes all 8) is preempted by job B
    (high priority, needs exactly 4), exits 75 with a cadence
    checkpoint, and resumes **elastically** on the 4 devices B left via
    ``--resume --resume-reshard``.  Both jobs complete; B's final
    checkpoint is bit-equal to an uncontended single-job run of the same
    config, and A's is bit-equal to a single-job run driven through the
    SAME transition (stop after the k steps episode 1 completed, then a
    mesh4 resharded resume) — the fleet added zero numerical
    perturbation, and the concatenated data trace is gap-free."""
    monkeypatch.delenv("THEANOMPI_DATA_TRACE", raising=False)
    monkeypatch.delenv("THEANOMPI_FAULT_PLAN", raising=False)
    fleet_dir = str(tmp_path / "fleet")
    trace_a = str(tmp_path / "trace_a")
    trace_b = str(tmp_path / "trace_b")
    cache_args = ["--compile-cache-dir", subproc_compile_cache]
    # A: mesh8 2 steps/epoch at GB=32; after the shrink, mesh4 4 at 16.
    # Synchronous every-iter cadence saves make the preemption point an
    # exact checkpoint (same determinism note as the PR 9 runbook).
    spec_a = JobSpec(
        job_id="big-lowpri", priority=0, min_devices=2,
        model_config={**TINY_CFG, "n_train": 64, "n_epochs": 3},
        rule_config={"exch_strategy": "zero1",
                     "checkpoint_every_n_iters": 1,
                     "checkpoint_async": False},
        env={**_child_env(), "THEANOMPI_DATA_TRACE": trace_a},
        extra_args=cache_args, max_restarts=3, backoff_base=0.1)
    spec_b = JobSpec(
        job_id="urgent", priority=10, min_devices=4, max_devices=4,
        model_config=dict(TINY_CFG),
        env={**_child_env(), "THEANOMPI_DATA_TRACE": trace_b},
        extra_args=cache_args, max_restarts=3, backoff_base=0.1)

    sched = FleetScheduler(fleet_dir, 8, poll_s=0.05)
    sched.submit(spec_a)
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    # contend only once A has really trained a step — the preemption must
    # interrupt work, and the trace line is the witness a step completed
    deadline = time.perf_counter() + 300
    while time.perf_counter() < deadline and not _trace(trace_a):
        time.sleep(0.02)
    assert _trace(trace_a), "job A never completed a step"
    sched.submit(spec_b)
    t.join(600)
    assert not t.is_alive(), "fleet scheduler hung"
    assert box["rc"] == EXIT_CLEAN

    # -- lifecycle: contention, cooperative exit 75, elastic resume ----------
    rec_a = read_record(fleet_dir, "big-lowpri")
    rec_b = read_record(fleet_dir, "urgent")
    assert rec_a.status == "done" and rec_b.status == "done"
    assert rec_a.preemptions == 1 and rec_a.episodes == 2
    assert rec_a.preempt_exits == [EXIT_PREEMPTED]  # checkpointed exit 75
    assert rec_b.preemptions == 0 and rec_b.episodes == 1
    ev = read_fleet_events(fleet_dir)
    story = [(e["event"], e["job"]) for e in ev]
    assert story[:4] == [("fleet.schedule", "big-lowpri"),
                         ("fleet.preempt", "big-lowpri"),
                         ("fleet.schedule", "urgent"),
                         ("fleet.resume", "big-lowpri")]
    assert ev[0]["devices"] == 8 and ev[1]["victim_of"] == "urgent"
    assert ev[2]["devices"] == 4
    assert ev[3]["devices"] == 4  # elastic: fewer devices than episode 1
    # the lifecycle mirrors into telemetry through the registered names
    tel_events = open([os.path.join(fleet_dir, "telemetry", f)
                       for f in os.listdir(
                           os.path.join(fleet_dir, "telemetry"))
                       if f.startswith("events-rank")][0]).read()
    assert "fleet.preempt" in tel_events and "fleet.resume" in tel_events

    # -- B: bit-equal to an uncontended single-job run -----------------------
    assert _trace(trace_b) == [(0, 0), (0, 1), (1, 0), (1, 1)]
    ck_b_ref = str(tmp_path / "ck_bref")
    _bsp(4, ck_b_ref).wait()
    _assert_ckpt_equal(
        os.path.join(job_dir(fleet_dir, "urgent"), "ckpt",
                     "ckpt_e0001.npz"),
        os.path.join(ck_b_ref, "ckpt_e0001.npz"))

    # -- A: gap-free trace across the shrink + bit-equal to the replay -------
    ta = _trace(trace_a)
    k = _find_split(ta, n_train=64, gb_hi=32, gb_lo=16, n_epochs=3)
    assert 1 <= k < 6, f"preemption landed outside episode 1's work: {k}"
    # the single-job reference: the SAME training trajectory with no
    # fleet — stop (deterministically) after the k steps episode 1
    # completed, then resume resharded onto mesh4, exactly as the
    # preempted job did
    ck_a_ref = str(tmp_path / "ck_aref")
    ref8 = str(tmp_path / "trace_ref8")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", ref8)
    rule8 = _bsp(8, ck_a_ref, n_epochs=3, model_over={"n_train": 64},
                 exch_strategy="zero1", checkpoint_every_n_iters=1,
                 checkpoint_async=False, fault_plan=f"step:raise@{k}")
    with pytest.raises(FaultInjected):
        rule8.wait()
    ref4 = str(tmp_path / "trace_ref4")
    monkeypatch.setenv("THEANOMPI_DATA_TRACE", ref4)
    rule4 = _bsp(4, ck_a_ref, n_epochs=3, model_over={"n_train": 64},
                 exch_strategy="zero1", checkpoint_every_n_iters=1,
                 checkpoint_async=False, resume_reshard=True)
    rule4.wait()
    assert rule4.trainer.epoch == 3
    # the fleet trace IS the reference's two traces concatenated —
    # nothing replayed, nothing skipped, across the global-batch change
    assert ta == _trace(ref8) + _trace(ref4)
    _assert_ckpt_equal(
        os.path.join(job_dir(fleet_dir, "big-lowpri"), "ckpt",
                     "ckpt_e0002.npz"),
        os.path.join(ck_a_ref, "ckpt_e0002.npz"))
    # and the final lineage is stamped with the post-shrink topology
    man = json.load(open(os.path.join(
        job_dir(fleet_dir, "big-lowpri"), "ckpt",
        "ckpt_e0002.manifest.json")))
    assert man["fingerprint"]["mesh"]["data"] == 4
    assert man["data_state"]["completed"] is True


@pytest.mark.faultinject
def test_fleet_chaos_easgd_straggler_absorbed_under_preemption(
        tmp_path, monkeypatch, subproc_compile_cache):
    """THE ISSUE 20 chaos acceptance, end to end on the CPU mesh8 pool:

    an EASGD job (low priority, tau=1, cadence saves) owns all 8
    devices; a priority-10 BSP job preempts it (cooperative exit 75); it
    resumes **elastically** on the 4 devices left via the new stacked
    reshard plan while absorbing injected stragglers — the
    async_staleness detector must reach WARN (degraded, absorbed) and
    never CRITICAL.  Both jobs finish; the BSP job is bit-equal to an
    uncontended run; the EASGD job's data trace is gap-free across the
    shrink and its convergence clears a margin gate against an
    uncontended same-seed run, recorded as a ledger-classifiable
    CONVERGE.json."""
    monkeypatch.delenv("THEANOMPI_DATA_TRACE", raising=False)
    monkeypatch.delenv("THEANOMPI_FAULT_PLAN", raising=False)
    fleet_dir = str(tmp_path / "fleet")
    trace_a = str(tmp_path / "trace_a")
    trace_b = str(tmp_path / "trace_b")
    tel_a = str(tmp_path / "tel_a")
    rec_dir_a = str(tmp_path / "rec_a")
    cache_args = ["--compile-cache-dir", subproc_compile_cache]
    easgd_model = {**TINY_CFG, "n_train": 64, "n_epochs": 5}
    # stragglers at exchange ordinals 8-12: late enough that the stretch
    # detector's rolling median is anchored by a majority of good rounds
    # (episode 2's FIRST interval is an eval-warmup outlier, and each
    # stall itself joins the window), consecutive enough to sustain the
    # bad-round streak past async_min_rounds — and the post-stall rounds
    # recover the verdict to ok before close.  The 0.05s health tick
    # cannot miss the multi-second warn window the 0.6s stalls hold open.
    spec_a = JobSpec(
        job_id="easgd-lowpri", priority=0, min_devices=4, rule="EASGD",
        model_config=easgd_model,
        rule_config={"tau": 1, "scale_lr": False,
                     "checkpoint_every_n_iters": 1,
                     "checkpoint_async": False,
                     "telemetry_health": {"tick_s": 0.05}},
        env={**_child_env(), "THEANOMPI_DATA_TRACE": trace_a,
             "THEANOMPI_EASGD_SLOW_S": "0.6",
             "THEANOMPI_FAULT_PLAN": ",".join(
                 f"easgd:worker_slow@{i}" for i in range(8, 13))},
        extra_args=[*cache_args, "--telemetry-dir", tel_a,
                    "--record-dir", rec_dir_a],
        max_restarts=3, backoff_base=0.1)
    spec_b = JobSpec(
        job_id="urgent", priority=10, min_devices=4, max_devices=4,
        model_config=dict(TINY_CFG),
        env={**_child_env(), "THEANOMPI_DATA_TRACE": trace_b},
        extra_args=cache_args, max_restarts=3, backoff_base=0.1)

    sched = FleetScheduler(fleet_dir, 8, poll_s=0.05)
    sched.submit(spec_a)
    box = {}
    t = threading.Thread(target=lambda: box.setdefault("rc", sched.run()))
    t.start()
    deadline = time.perf_counter() + 300
    while time.perf_counter() < deadline and not _trace(trace_a):
        time.sleep(0.02)
    assert _trace(trace_a), "EASGD job never completed a step"
    sched.submit(spec_b)
    t.join(600)
    assert not t.is_alive(), "fleet scheduler hung"
    assert box["rc"] == EXIT_CLEAN

    # -- lifecycle: preemption without a restart budget spent ---------------
    rec_a = read_record(fleet_dir, "easgd-lowpri")
    rec_b = read_record(fleet_dir, "urgent")
    assert rec_a.status == "done" and rec_b.status == "done"
    assert rec_a.preemptions == 1 and rec_a.episodes == 2
    assert rec_a.preempt_exits == [EXIT_PREEMPTED]
    story = [(e["event"], e["job"]) for e in read_fleet_events(fleet_dir)]
    assert story[:4] == [("fleet.schedule", "easgd-lowpri"),
                         ("fleet.preempt", "easgd-lowpri"),
                         ("fleet.schedule", "urgent"),
                         ("fleet.resume", "easgd-lowpri")]

    # -- the contender is untouched by the chaos ----------------------------
    ck_b_ref = str(tmp_path / "ck_bref")
    _bsp(4, ck_b_ref).wait()
    _assert_ckpt_equal(
        os.path.join(job_dir(fleet_dir, "urgent"), "ckpt",
                     "ckpt_e0001.npz"),
        os.path.join(ck_b_ref, "ckpt_e0001.npz"))

    # -- async health: stragglers WARN, never CRITICAL ----------------------
    # each relaunched attempt truncates events-rank0.jsonl, so the final
    # file is episode 2's — the elastic mesh4 resume that absorbed the
    # injected stalls
    events_path = [os.path.join(tel_a, f) for f in sorted(os.listdir(tel_a))
                   if f.startswith("events-rank")][0]
    events = [json.loads(line) for line in open(events_path)]
    exchanges = [e for e in events if e.get("name") == "easgd.exchange"]
    assert exchanges, "no exchange instants in episode 2"
    assert any(e.get("stretch", 0) >= 2.5 for e in exchanges), \
        "injected stalls never registered as interval stretch"
    async_verdicts = [e for e in events
                      if e.get("name") == "health.verdict"
                      and e.get("detector") == "async_staleness"]
    sevs = [v["severity"] for v in async_verdicts]
    assert "warn" in sevs, f"straggler absorption never warned: {sevs}"
    assert "critical" not in sevs, f"chaos escalated to critical: {sevs}"
    health = json.load(open(os.path.join(tel_a, "HEALTH.json")))
    by_det = {v["detector"]: v for v in health["verdicts"]}
    assert by_det["async_staleness"]["severity"] in ("ok", "warn")

    # -- gap-free trace across the shrink -----------------------------------
    ta = _trace(trace_a)
    k = _find_split(ta, n_train=64, gb_hi=32, gb_lo=16, n_epochs=5)
    # episode 2 must hold >= 14 exchange rounds so the ordinal-8..12
    # stalls all land there: 20 - 2k rounds remain after k mesh8 steps
    assert 1 <= k <= 3, f"preemption landed outside episode 1's work: {k}"

    # -- convergence gate vs an uncontended same-seed run -------------------
    from theanompi_tpu import EASGD

    ref = EASGD(config={"verbose": False, "scale_lr": False, "tau": 1})
    ref.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
             modelclass="WideResNet", model_config=dict(easgd_model))
    ref.wait()
    ref_best = float(np.min(ref.trainer.recorder.val_history["cost"]))
    hist = np.load(os.path.join(rec_dir_a, "val_history.npy"),
                   allow_pickle=True).item()
    assert list(hist["epoch"]) == [0, 1, 2, 3, 4]  # continuous curve
    best = float(np.min(hist["cost"]))
    target = ref_best * 1.25  # generous: tiny-data noise, not a tuning gate
    to_target = next((int(e) for e, c in zip(hist["epoch"], hist["cost"])
                      if c <= target), None)
    row = {"model": "wrn_easgd_chaos", "rule": "EASGD",
           "target_error": target, "best_val_error": best,
           "passed": best <= target, "epochs_to_target": to_target}
    conv_path = os.path.join(str(tmp_path), "CONVERGE.json")
    with open(conv_path, "w") as f:
        json.dump({"run_id": "chaos-e2e", "results": [row]}, f)
    assert row["passed"], (
        f"contended EASGD lost convergence: best {best:.4f} vs "
        f"uncontended {ref_best:.4f} (target {target:.4f})")
    # the artifact is ledger-classifiable as a higher-is-better margin
    from theanompi_tpu.telemetry.ledger import classify_artifact

    (margin_rec,) = classify_artifact(conv_path, json.load(open(conv_path)))
    assert margin_rec["metric"] == "converge.wrn_easgd_chaos.margin"
    assert margin_rec["value"] == pytest.approx(target - best)
