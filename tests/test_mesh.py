"""Mesh runtime tests (fake 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.parallel.mesh import (
    BF16,
    DATA_AXIS,
    FP32,
    Precision,
    data_sharded,
    make_mesh,
    mesh_axis_size,
    replica_rng,
    replicated,
)


def test_device_count():
    assert jax.device_count() == 8


def test_make_mesh_shapes():
    m = make_mesh(n_data=8)
    assert mesh_axis_size(m, DATA_AXIS) == 8
    m = make_mesh(n_data=4, n_model=2)
    assert m.shape["data"] == 4 and m.shape["model"] == 2
    m = make_mesh()  # auto: all devices on data
    assert m.shape["data"] == 8


def test_make_mesh_errors():
    with pytest.raises(ValueError):
        make_mesh(n_data=16)
    with pytest.raises(ValueError):
        make_mesh(n_model=3)  # 8 % 3 != 0


def test_single_device_mesh():
    m = make_mesh(n_data=1, devices=jax.devices()[:1])
    assert m.shape["data"] == 1


def test_data_sharding_placement(mesh8):
    x = jnp.arange(16.0).reshape(16, 1)
    xs = jax.device_put(x, data_sharded(mesh8, ndim=2))
    assert len(xs.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(xs), np.asarray(x))
    w = jax.device_put(jnp.ones((4,)), replicated(mesh8))
    assert w.sharding.is_fully_replicated


def test_precision_policy_casts():
    tree = {"w": jnp.ones((2,), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
    c = BF16.cast_to_compute(tree)
    assert c["w"].dtype == jnp.bfloat16
    assert c["i"].dtype == jnp.int32  # non-float leaves untouched
    back = BF16.cast_to_param(c)
    assert back["w"].dtype == jnp.float32
    assert FP32.compute_dtype == jnp.float32
    assert Precision(compute_dtype=jnp.float16).compute_dtype == jnp.float16


def test_replica_rng_distinct(mesh8):
    from theanompi_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    def f(key):
        k = replica_rng(key[0])
        return jax.random.uniform(k, (1,))

    out = shard_map(
        f, mesh=mesh8, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
        check=False,
    )(jnp.stack([jax.random.PRNGKey(0)] * 8))
    vals = np.asarray(out)
    assert len(np.unique(vals)) == 8  # every replica drew a different number
