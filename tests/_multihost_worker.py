"""Worker for the 2-process jax.distributed integration test.

Launched (twice) by tests/test_multihost.py.  Each process owns 4 virtual
CPU devices; the mesh spans all 8.  This is the multi-controller shape of a
TPU pod (SURVEY.md §3.1's mpirun process boundary, re-based on the JAX
runtime): the SAME program runs on every host, data/init are seed-identical,
and each process feeds only its addressable shards.

Usage: python _multihost_worker.py <pid> <port> <ckpt_dir0> <ckpt_dir1>
(the per-process checkpoint dirs differ to prove resume does NOT need a
shared filesystem: only process 0's disk is authoritative).
"""

import os
import sys


def main():
    pid, port, dir0, dir1 = (sys.argv[1], sys.argv[2], sys.argv[3],
                             sys.argv[4])
    pid = int(pid)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=2,
        process_id=pid,
        local_device_ids=list(range(4)),
    )
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8

    import numpy as np

    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.recorder import Recorder

    cfg = {
        "depth": 10, "widen": 1, "batch_size": 4, "n_epochs": 2,
        "lr": 0.05, "n_train": 64, "n_val": 32, "augment": False,
        "precision": "fp32", "verbose": False, "bn_axis": "data",
    }
    my_ckpt = dir0 if pid == 0 else dir1

    def build():
        model = WideResNet(dict(cfg))
        t = BSPTrainer(
            model,
            mesh=make_mesh(n_data=8),
            recorder=Recorder(verbose=False),
            checkpoint_dir=my_ckpt,
        )
        t.compile_iter_fns()
        t.init_state()
        return t

    trainer = build()
    rec = trainer.run()
    costs = rec.val_history["cost"]
    assert len(costs) == 2 and all(np.isfinite(c) for c in costs), costs

    # resume on a FRESH trainer: process 1's dir is empty — the resume
    # decision and the arrays must both come from process 0 via broadcast
    resumed = build()
    ok = resumed.try_resume()
    assert ok, "resume failed"
    assert resumed.epoch == 2, resumed.epoch

    # restored params must equal the trained ones on every process
    for a, b in zip(jax.tree.leaves(trainer.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_array_equal(
            np.asarray(a.addressable_shards[0].data),
            np.asarray(b.addressable_shards[0].data),
        )

    # one more epoch from the restored state exercises train-after-resume
    resumed.model.n_epochs = 3
    resumed.run()

    # the async rules across the process boundary: EASGD's elastic exchange
    # and GOSGD's gossip are collectives spanning both processes; GOSGD's
    # host-drawn push/shift schedule must agree because both processes seed
    # identically (the SPMD contract)
    from theanompi_tpu.parallel.easgd import EASGDTrainer
    from theanompi_tpu.parallel.gosgd import GOSGDTrainer

    # n_train=128 -> 4 global batches of 32; with tau=2 EASGD exchanges
    # twice, with p_push=1 GOSGD gossips every step
    async_cfg = {**{k: v for k, v in cfg.items() if k != "bn_axis"},
                 "n_train": 128}
    for cls, kwargs, expect_comm in (
        (EASGDTrainer, {"tau": 2}, 2),
        (GOSGDTrainer, {"p_push": 1.0}, 4),
    ):
        model = WideResNet(dict(async_cfg))
        t = cls(model, mesh=make_mesh(n_data=8),
                recorder=Recorder(verbose=False), **kwargs)
        t.compile_iter_fns()
        t.init_state()
        n_steps = 0
        for batch in model.data.train_batches(t.global_batch, 0, seed=0):
            m = t.train_iter(batch, lr=0.05)  # post_step fires the exchange
            n_steps += 1
        assert n_steps == 4, n_steps
        # the exchange collectives MUST have fired: post_step records a
        # nonzero "comm" segment for every executed exchange round
        comm = t.recorder.time_history["comm"]
        fired = sum(1 for c in comm if c > 0)
        assert fired == expect_comm, (
            f"{cls.__name__}: {fired} exchanges fired, expected {expect_comm}"
        )
        # per-worker metrics are sharded across processes: read local shards
        cost = float(np.mean([np.asarray(s.data)
                              for s in m["cost"].addressable_shards]))
        assert np.isfinite(cost), f"{cls.__name__} diverged on multihost"
        ep, es = t.eval_args()  # consensus/center collectives span processes
        leaf = np.asarray(jax.tree.leaves(ep)[0].addressable_shards[0].data)
        assert np.isfinite(leaf).all(), f"{cls.__name__} consensus not finite"
    print(f"MULTIHOST_RULES_OK pid={pid}", flush=True)

    print(f"MULTIHOST_OK pid={pid} val_cost={costs[-1]:.4f}", flush=True)


if __name__ == "__main__":
    main()
