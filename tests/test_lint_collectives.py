"""HLO collective-count lint (ISSUE 2), now a tmlint shim (ISSUE 7).

The one-off compile-and-count here became the general compiled-artifact
auditor (``theanompi_tpu/analysis/hlo_audit.py``): same wide_resnet
step, same lock (>=30-leaf model + psum_bucket -> <=4 all-reduce ops),
plus donation and host-callback checks this file never had.  The audit
artifacts are ``lru_cache``'d, so this shim and ``test_hlo_audit.py``
share one XLA compile per strategy.
"""

from theanompi_tpu.analysis import hlo_audit
from theanompi_tpu.telemetry.metrics import hlo_collective_counts


def test_bucketed_step_compiles_to_few_allreduces():
    """Acceptance: >=30-leaf model + psum_bucket -> <=4 all-reduce HLO ops
    (grad bucket + fused metrics pmean + fused state pmean); the leaf-wise
    psum baseline compiles to one all-reduce per gradient leaf and MUST
    count higher — if it stops doing so, XLA started combining leaf-wise
    collectives itself and this lint (plus the bucket machinery's perf
    rationale) needs re-evaluating."""
    bucketed = hlo_audit.audit_train_step("psum_bucket")
    n_leaves = bucketed["n_param_leaves"]
    assert n_leaves >= 30, f"model too small to prove bucketing: {n_leaves}"
    assert bucketed["ok"], bucketed["violations"]
    n_bucketed = bucketed["collectives"].get("all-reduce", 0)
    assert n_bucketed <= 4, bucketed["collectives"]

    leafwise = hlo_audit.audit_train_step("psum")
    assert leafwise["ok"], leafwise["violations"]
    n_leafwise = leafwise["collectives"]["all-reduce"]
    assert n_leafwise > 4, leafwise["collectives"]
    assert n_leafwise > n_bucketed, (leafwise, bucketed)
    # one all-reduce per grad leaf, plus the two fused pmeans
    assert n_leafwise >= n_leaves, (leafwise, n_leaves)


def test_hlo_collective_counts_parser():
    """Parser unit: defs count, -start/-done pairs count once, operand
    references (no parens) and metadata mentions don't."""
    text = """
  %all-reduce.1 = f32[16]{0} all-reduce(f32[16]{0} %p), to_apply=%add
  %ars = (f32[4]{0}, f32[4]{0}) all-reduce-start(f32[4]{0} %q)
  %ard = f32[4]{0} all-reduce-done((f32[4]{0}, f32[4]{0}) %ars)
  %rs = f32[4]{0} reduce-scatter(f32[16]{0} %all-reduce.1), dimensions={0}
  %ag = f32[16]{0} all-gather(f32[4]{0} %rs), dimensions={0}
  %cp = f32[4]{0} collective-permute(f32[4]{0} %x)
  %y = f32[4]{0} add(f32[4]{0} %cp, f32[4]{0} %cp)
"""
    counts = hlo_collective_counts(text)
    assert counts == {"all-reduce": 2, "reduce-scatter": 1,
                      "all-gather": 1, "collective-permute": 1}
