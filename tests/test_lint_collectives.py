"""HLO collective-count lint (ISSUE 2): bucketing regressions fail fast.

The bucketed exchange's whole point is O(buckets) collectives instead of
O(leaves).  That property is invisible to numeric tests (the mean is the
mean either way) and unmeasurable without hardware — but it IS statically
checkable: compile the BSP step on the CPU mesh and count ``all-reduce``
op definitions in the HLO.  A refactor that silently falls back to
leaf-wise collectives (or un-fuses the metrics/state pmeans) breaks this
file long before anyone profiles a TPU.
"""

import jax

from theanompi_tpu.models.wide_resnet import WideResNet
from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import make_mesh
from theanompi_tpu.telemetry.metrics import hlo_collective_counts
from theanompi_tpu.utils.helper_funcs import shard_batch
from theanompi_tpu.utils.recorder import Recorder

# depth 16 -> 43 param leaves: comfortably past the >=30-leaf bar the
# acceptance criterion sets, still tiny enough to compile in seconds
WIDE_CFG = {
    "depth": 16, "widen": 1, "batch_size": 2, "image_size": 8,
    "n_train": 32, "n_val": 16, "n_epochs": 1, "precision": "fp32",
    "augment": False, "verbose": False,
}


def _compiled_counts(strategy):
    model = WideResNet(dict(WIDE_CFG))
    mesh = make_mesh(n_data=4, devices=jax.devices()[:4])
    t = BSPTrainer(model, mesh=mesh, exch_strategy=strategy,
                   recorder=Recorder(verbose=False, print_freq=10**9))
    t.compile_iter_fns()
    t.init_state()
    batch = shard_batch(
        mesh, next(iter(model.data.train_batches(t.global_batch, 0, seed=0))),
        spec=t.batch_spec)
    n_leaves = len(jax.tree.leaves(t.params))
    return hlo_collective_counts(t.compiled_step_text(batch)), n_leaves


def test_bucketed_step_compiles_to_few_allreduces():
    """Acceptance: >=30-leaf model + psum_bucket -> <=4 all-reduce HLO ops
    (grad bucket + fused metrics pmean + fused state pmean); the leaf-wise
    psum baseline compiles to one all-reduce per gradient leaf and MUST
    count higher — if it stops doing so, XLA started combining leaf-wise
    collectives itself and this lint (plus the bucket machinery's perf
    rationale) needs re-evaluating."""
    bucketed, n_leaves = _compiled_counts("psum_bucket")
    assert n_leaves >= 30, f"model too small to prove bucketing: {n_leaves}"
    assert bucketed.get("all-reduce", 0) <= 4, bucketed

    leafwise, _ = _compiled_counts("psum")
    assert leafwise["all-reduce"] > 4, leafwise
    assert leafwise["all-reduce"] > bucketed.get("all-reduce", 0), (
        leafwise, bucketed)
    # one all-reduce per grad leaf, plus the two fused pmeans
    assert leafwise["all-reduce"] >= n_leaves, (leafwise, n_leaves)


def test_hlo_collective_counts_parser():
    """Parser unit: defs count, -start/-done pairs count once, operand
    references (no parens) and metadata mentions don't."""
    text = """
  %all-reduce.1 = f32[16]{0} all-reduce(f32[16]{0} %p), to_apply=%add
  %ars = (f32[4]{0}, f32[4]{0}) all-reduce-start(f32[4]{0} %q)
  %ard = f32[4]{0} all-reduce-done((f32[4]{0}, f32[4]{0}) %ars)
  %rs = f32[4]{0} reduce-scatter(f32[16]{0} %all-reduce.1), dimensions={0}
  %ag = f32[16]{0} all-gather(f32[4]{0} %rs), dimensions={0}
  %cp = f32[4]{0} collective-permute(f32[4]{0} %x)
  %y = f32[4]{0} add(f32[4]{0} %cp, f32[4]{0} %cp)
"""
    counts = hlo_collective_counts(text)
    assert counts == {"all-reduce": 2, "reduce-scatter": 1,
                      "all-gather": 1, "collective-permute": 1}
