"""Scaling-efficiency harness: structure + the none-strategy plug point.

VERDICT.md round-1 weak #3: the headline metric (>=90% linear scaling,
BASELINE.json north star) had no measurement harness.  These tests assert
the harness runs end-to-end on the fake mesh and emits the artifact the
judge/driver can read; the *numbers* only mean something on real chips.
"""

import json

import numpy as np
import pytest

from theanompi_tpu.utils.scaling import (
    _have_xplane_protos,
    exchange_microbench,
    measure_scaling,
)

# the profiler-backed comm-share tests parse xplanes via tensorflow's
# protos; on a JAX-only install they skip (the harness itself records
# comm_share as null there — covered by test_scaling_harness_artifact)
needs_xplane = pytest.mark.skipif(
    not _have_xplane_protos(), reason="tensorflow xplane protos unavailable")

TINY = {
    "depth": 10, "widen": 1, "batch_size": 8, "n_train": 64, "n_val": 16,
    "n_epochs": 1, "augment": False, "precision": "fp32", "verbose": False,
}


def test_scaling_harness_artifact(tmp_path):
    out = tmp_path / "scaling.json"
    art = measure_scaling(
        "wide_resnet", dict(TINY), ns=(1, 2), steps=2, trials=1,
        out_path=str(out),
    )
    assert art["ns"] == [1, 2]
    for n in (1, 2):
        r = art["per_n"][n]
        assert r["global_batch"] == 8 * n
        assert r["imgs_per_sec"] > 0
        # None on a JAX-only install (no xplane protos — ADVICE r3 #1);
        # a numeric share otherwise
        if r["comm_share"] is not None:
            assert 0.0 <= r["comm_share"] <= 1.0
        assert r["efficiency"] > 0
    assert art["per_n"][1]["efficiency"] == 1.0
    # artifact round-trips (per_n keys become strings in json)
    loaded = json.loads(out.read_text())
    assert loaded["per_n"]["2"]["imgs_per_sec"] > 0


def test_exchange_microbench_artifact(tmp_path):
    """ISSUE 2: the exchange microbenchmark emits, per strategy, HLO
    collective counts + static wire bytes that encode the tentpole's
    claims — fewer fused all-reduces, exact compression ratios, and
    zero1's reduce-scatter/all-gather pair."""
    out = tmp_path / "exchange.json"
    art = exchange_microbench(
        "wide_resnet", dict(TINY, batch_size=4, n_train=32),
        n=4, strategies=("psum", "zero1"),
        steps=2, out_path=str(out),
    )
    rows = art["per_strategy"]
    # (the psum_bucket-vs-psum all-reduce collapse is locked by
    # tests/test_lint_collectives.py on the same counter)
    assert rows["zero1"]["wire_bytes_per_step"] == \
        rows["psum"]["wire_bytes_per_step"]
    # zero1 lowers its grad path to reduce-scatter + all-gather; the
    # remaining all-reduces (sync-BN statistics + fused pmeans — _build
    # runs the production multi-worker config, sync-BN on) must come in
    # strictly below leaf-wise psum's, which carries those PLUS one
    # all-reduce per gradient leaf
    z = rows["zero1"]["collectives"]
    assert z.get("reduce-scatter", 0) >= 1 and z.get("all-gather", 0) >= 1
    assert z.get("all-reduce", 0) < rows["psum"]["collectives"]["all-reduce"]
    for row in rows.values():
        assert row["step_ms"] > 0
    assert rows["zero1"]["buckets"]["n_buckets"] >= 1
    # artifact round-trips
    loaded = json.loads(out.read_text())
    assert loaded["per_strategy"]["psum"]["collectives"]["all-reduce"] > 0


def test_none_strategy_skips_exchange(mesh8):
    """'none' must leave per-worker grads unreduced (replicas diverge)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from theanompi_tpu.parallel.exchanger import Exchanger
    from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map

    ex = Exchanger(strategy="none")
    f = jax.jit(
        shard_map(
            lambda x: ex.exchange(x), mesh8,
            in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
        )
    )
    x = np.arange(8, dtype=np.float32)
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, x)  # untouched, NOT the mean


@needs_xplane
def test_comm_share_injection_detects_fat_collective(mesh8):
    """VERDICT r2 #5: a measurement tool that has only ever output 0.0 is
    unvalidated.  Plant a deliberately fat psum against a tiny compute op
    and assert the profiler-backed extractor reports a clearly nonzero
    collective share — and a near-zero one for the same loop without the
    collective."""
    import jax
    import jax.numpy as jnp
    import tempfile
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map
    from theanompi_tpu.utils.scaling import _trace_comm_split

    big = jnp.ones((512, 2048), jnp.float32)  # 4 MB psum'd every step

    fat = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, DATA_AXIS) * 0.125, mesh8,
        in_specs=P(), out_specs=P(),
    ))
    lean = jax.jit(shard_map(
        lambda x: x * 0.125, mesh8, in_specs=P(), out_specs=P(),
    ))

    def traced_share(fn):
        fn(big).block_until_ready()
        d = tempfile.mkdtemp(prefix="inject_")
        with jax.profiler.trace(d):
            y = None
            for _ in range(4):
                y = fn(big)
            y.block_until_ready()
        comm, total = _trace_comm_split(d)
        assert total > 0, "no device op events captured"
        return comm / total

    share_fat = traced_share(fat)
    share_lean = traced_share(lean)
    assert share_fat > 0.05, f"fat collective invisible: {share_fat}"
    assert share_lean < share_fat / 2, (share_lean, share_fat)


@needs_xplane
def test_measure_comm_share_on_trainer(mesh8):
    """The trainer-level wrapper: ring strategy (ppermute chain) on the
    8-device mesh must show nonzero comm share."""
    import jax

    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.utils.helper_funcs import shard_batch
    from theanompi_tpu.utils.scaling import measure_comm_share

    model = WideResNet({**TINY, "batch_size": 4, "bn_axis": "data"})
    t = BSPTrainer(model, mesh=mesh8, exch_strategy="ring")
    t.compile_iter_fns()
    t.init_state()
    batches = [shard_batch(mesh8, b, spec=t.batch_spec)
               for b in model.data.train_batches(t.global_batch, 0, seed=0)]
    jax.block_until_ready(batches)
    share, comm_s, total_s = measure_comm_share(t, batches, steps=3)
    assert total_s > 0
    assert share > 0.0, "trainer comm share measured exactly zero"
