"""Scaling-efficiency harness: structure + the none-strategy plug point.

VERDICT.md round-1 weak #3: the headline metric (>=90% linear scaling,
BASELINE.json north star) had no measurement harness.  These tests assert
the harness runs end-to-end on the fake mesh and emits the artifact the
judge/driver can read; the *numbers* only mean something on real chips.
"""

import json

import numpy as np

from theanompi_tpu.utils.scaling import measure_scaling

TINY = {
    "depth": 10, "widen": 1, "batch_size": 8, "n_train": 64, "n_val": 16,
    "n_epochs": 1, "augment": False, "precision": "fp32", "verbose": False,
}


def test_scaling_harness_artifact(tmp_path):
    out = tmp_path / "scaling.json"
    art = measure_scaling(
        "wide_resnet", dict(TINY), ns=(1, 2), steps=2, trials=1,
        out_path=str(out),
    )
    assert art["ns"] == [1, 2]
    for n in (1, 2):
        r = art["per_n"][n]
        assert r["global_batch"] == 8 * n
        assert r["imgs_per_sec"] > 0
        assert 0.0 <= r["comm_share"] <= 1.0
        assert r["efficiency"] > 0
    assert art["per_n"][1]["efficiency"] == 1.0
    # artifact round-trips (per_n keys become strings in json)
    loaded = json.loads(out.read_text())
    assert loaded["per_n"]["2"]["imgs_per_sec"] > 0


def test_none_strategy_skips_exchange(mesh8):
    """'none' must leave per-worker grads unreduced (replicas diverge)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from theanompi_tpu.parallel.exchanger import Exchanger
    from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map

    ex = Exchanger(strategy="none")
    f = jax.jit(
        shard_map(
            lambda x: ex.exchange(x), mesh8,
            in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
        )
    )
    x = np.arange(8, dtype=np.float32)
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, x)  # untouched, NOT the mean
