"""Unit tests for the roofline HLO/xplane parsers (the MFU evidence path).

These pure functions back ROOFLINE.json's flops/bytes numbers; they are
tested against hand-built HLO snippets covering every conv form the
ResNet-50/transformer steps emit (fwd, strided dgrad with lhs_dilate,
padded wgrad, negative pads, windowless matmul-as-convolution) plus a real
compiled module round trip.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.utils.roofline import (
    _conv_flops,
    _dot_flops,
    _text_bytes,
    hlo_flops_map,
)


def test_text_bytes_sums_all_literals():
    t = ("%f = (bf16[8,4]{1,0}, f32[2]{0}) fusion(bf16[8,4]{1,0} %a, "
         "s32[3]{0} %b)")
    assert _text_bytes(t) == 8 * 4 * 2 + 2 * 4 + 8 * 4 * 2 + 3 * 4


def test_dot_flops_basic_and_batched():
    shapes = {"a": "128,64", "b": "64,256", "c": "4,128,64", "d": "4,64,32"}
    line = ("%r = f32[128,256] dot(%a, %b), lhs_contracting_dims={1}, "
            "rhs_contracting_dims={0}")
    assert _dot_flops(line, shapes) == 2 * 128 * 256 * 64
    line_b = ("%r = f32[4,128,32] dot(%c, %d), lhs_batch_dims={0}, "
              "lhs_contracting_dims={2}, rhs_batch_dims={0}, "
              "rhs_contracting_dims={1}")
    assert _dot_flops(line_b, shapes) == 2 * 4 * 128 * 32 * 64


def test_conv_flops_forward():
    # 3x3 SAME conv, 16x16 spatial, 8->8 features, batch 2
    shapes = {"x": "2,16,16,8", "w": "3,3,8,8"}
    line = ("%c = f32[2,16,16,8] convolution(%x, %w), "
            "window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f")
    # interior outputs see 9 taps, edges fewer: per-dim taps = sum over 16
    # positions of window overlap = 16*3 - 2 = 46
    assert _conv_flops(line, shapes) == 2 * 2 * 8 * 8 * 46 * 46


def test_conv_flops_strided_dgrad_counts_true_macs():
    """lhs_dilate (input-grad of a strided conv) must not over-count: the
    dilation holes carry no MACs."""
    shapes = {"dy": "1,8,8,4", "w": "2,2,4,4"}
    line = ("%c = f32[1,16,16,4] convolution(%dy, %w), "
            "window={size=2x2 pad=1_0x1_0 lhs_dilate=2x2 rhs_reversal=1x1}, "
            "dim_labels=b01f_01oi->b01f")
    f = _conv_flops(line, shapes)
    # exact per-dim tap count (out 16, K=2, stride 1, pad_lo 1, ld 2):
    taps = 0
    for o in range(16):
        for k in range(2):
            j = o - 1 + k
            if 0 <= j < 15 and j % 2 == 0:
                taps += 1
    assert f == 2 * 1 * 4 * 4 * taps * taps
    # and the naive out*window*feat product would have been 2x bigger
    assert f < 2 * 1 * 4 * 4 * (16 * 2) * (16 * 2)


def test_conv_flops_negative_pad_parses():
    shapes = {"x": "1,8,8,4", "w": "3,3,4,4"}
    line = ("%c = f32[1,6,6,4] convolution(%x, %w), "
            "window={size=3x3 pad=0_-2x0_-2}, dim_labels=b01f_01io->b01f")
    assert _conv_flops(line, shapes) > 0


def test_conv_flops_windowless_matmul():
    """Matmuls lowered to HLO convolution carry no window= — they must
    count as plain M*N*K, not zero (the silent-undercount class)."""
    shapes = {"a": "128,64", "b": "64,256"}
    line = "%c = f32[128,256] convolution(%a, %b), dim_labels=bf_io->bf"
    assert _conv_flops(line, shapes) == 2 * 128 * 256 * 64


def test_hlo_flops_map_attributes_fused_conv_to_caller():
    hlo = """
HloModule m

%fused_computation.1 (p0: f32[2,8,8,4], p1: f32[3,3,4,4]) -> f32[2,8,8,4] {
  %p0 = f32[2,8,8,4]{3,2,1,0} parameter(0)
  %p1 = f32[3,3,4,4]{3,2,1,0} parameter(1)
  ROOT %conv.1 = f32[2,8,8,4]{3,2,1,0} convolution(%p0, %p1), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f
}

ENTRY %main (a: f32[2,8,8,4], w: f32[3,3,4,4]) -> f32[2,8,8,4] {
  %a = f32[2,8,8,4]{3,2,1,0} parameter(0)
  %w = f32[3,3,4,4]{3,2,1,0} parameter(1)
  ROOT %fusion.9 = f32[2,8,8,4]{3,2,1,0} fusion(%a, %w), kind=kOutput, calls=%fused_computation.1
}
"""
    fmap = hlo_flops_map(hlo)
    taps = 8 * 3 - 2
    want = 2 * 2 * 4 * 4 * taps * taps
    assert fmap.get("fusion.9") == want
    assert fmap.get("conv.1") == want


def test_hlo_flops_map_on_real_compiled_module():
    """Round trip: a compiled matmul chain's total parsed flops must match
    the analytic count regardless of whether XLA lowers to dot or
    windowless convolution on this backend."""
    m, k, n = 64, 32, 128

    @jax.jit
    def f(a, b, c):
        return (a @ b) @ c

    a = jnp.ones((m, k), jnp.float32)
    b = jnp.ones((k, n), jnp.float32)
    c = jnp.ones((n, k), jnp.float32)
    txt = jax.jit(f).lower(a, b, c).compile().as_text()
    fmap = hlo_flops_map(txt)
    total = sum(fmap.values())
    want = 2 * m * n * k + 2 * m * k * n
    assert total == want, f"parsed {total} != analytic {want}"
