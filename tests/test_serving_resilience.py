"""ISSUE 14 serving resilience tier: deadlines, shedding, drain, rollout.

The contract under test:

- **typed terminal states** — every request ends in exactly one of
  ``done|expired|shed|failed``; deadlines are enforced at admission AND
  between scheduler steps, and a preempted-requeued request past its
  deadline expires WITHOUT burning a recompute-prefill;
- **load shedding** — with ``shed=True`` a deadline-carrying request the
  backlog provably cannot meet at the recent token rate is refused at
  admission (never mid-flight), deadline-less requests never shed;
- **livelock guard** — a request whose prefix can never fit the KV pool
  fails typed instead of crashing the server or preempting forever;
- **graceful drain** — on the drain trigger the loop stops admitting,
  finishes or expires in-flight within the budget, and a supervised
  replica's drained exit classifies CLEAN (subprocess e2e);
- **verified live rollout** — a half-published or corrupt candidate is
  refused (never quarantined) while the old weights keep serving; a good
  candidate hot-swaps with zero dropped requests; a critical SLO verdict
  during probation rolls back to the previous weights.

Units run against a host-only fake engine (no XLA compile); the drain
e2e drives the real ``tmserve --supervise`` as a subprocess; the full
chaos drive (crash-restart + corrupt-then-good rollout + forced
rollback) is tier-2 (``-m slow``).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from theanompi_tpu.resilience.faults import FaultInjected, FaultPlan
from theanompi_tpu.serving import (
    Request,
    RequestLog,
    RolloutManager,
    Scheduler,
    TERMINAL_STATES,
    blocks_for,
    newest_manifest_epoch,
    run_open_loop,
    serve_report,
    terminal_rids,
)

from conftest import SERVING_TINY as TINY  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeEngine:
    """Host-only engine double: the scheduler's surface (pool geometry +
    prefill/decode) with no XLA behind it — lifecycle units stay
    compile-free.  Emits a fixed token so nothing ever hits EOS."""

    def __init__(self, max_batch=2, block_size=4, num_blocks=9,
                 max_context=64):
        self.max_batch = max_batch
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_context = max_context
        self.max_blocks_per_seq = blocks_for(max_context, block_size)
        self.n_prefills = 0
        self.n_decodes = 0
        self.quant_stats = None
        self.decode_impl = "fallback"

    @property
    def quantized(self):
        return False

    def prefill(self, row, tokens, temperature=0.0, rid=0, prefix_len=0):
        self.n_prefills += 1
        return 7, None

    def decode(self, tables, lengths, tokens, temps, rids):
        self.n_decodes += 1
        return np.full((self.max_batch,), 5, np.int32), None


def _req(rid, prompt_len=4, new=8, **kw):
    return Request(rid=rid, prompt=[1] * prompt_len, max_new_tokens=new,
                   **kw)


# -- deadlines ----------------------------------------------------------------

def test_deadline_expiry_queued_and_active():
    """Between-steps enforcement: an overrun ACTIVE request evicts and
    expires (its blocks free immediately), an overrun QUEUED one never
    prefills; both carry the typed state + reason."""
    sched = Scheduler(FakeEngine(max_batch=1, num_blocks=20))
    active = _req(0, new=50, total_deadline_ms=10_000.0)
    queued = _req(1, new=50, total_deadline_ms=10_000.0)
    assert sched.submit(active) and sched.submit(queued)
    out = sched.step()  # admits rid 0 (1 slot), rid 1 stays queued
    assert not out and sched.n_active == 1 and len(sched.queue) == 1
    free_before = sched.pool.free_blocks
    # blow both deadlines between steps
    active.t_submit -= 11.0
    queued.t_submit -= 11.0
    prefills = sched.engine.n_prefills
    out = sched.step()
    assert {r.rid for r in out} == {0, 1}
    assert all(r.state == "expired" and r.terminal for r in out)
    assert {r.reason for r in out} == {
        "total deadline exceeded (active)",
        "total deadline exceeded (queued)"}
    assert sched.engine.n_prefills == prefills, \
        "an expired queued request burned a prefill"
    assert sched.pool.free_blocks > free_before, \
        "the expired active request did not free its blocks"
    assert sched.n_expired == 2 and sched.idle


def test_ttft_deadline_only_applies_before_first_token():
    """A request past its TTFT deadline but already emitting tokens keeps
    going — TTFT is a first-token promise, not a lifetime."""
    sched = Scheduler(FakeEngine(max_batch=1, num_blocks=20))
    req = _req(0, new=6, ttft_deadline_ms=10_000.0)
    sched.submit(req)
    sched.step()  # prefill -> first token exists
    assert req.t_first_token is not None
    req.t_submit -= 60.0  # way past the TTFT deadline
    while not req.terminal:
        sched.step()
    assert req.state == "done" and len(req.generated) == 6


def test_preempted_requeued_past_deadline_expires_without_prefill():
    """ISSUE 14 satellite: preemption requeues to the queue FRONT, so the
    admission path must deadline-check BEFORE prefilling — the expired
    request costs nothing on its way out."""
    sched = Scheduler(FakeEngine(max_batch=2, num_blocks=30))
    victim = _req(0, new=20, total_deadline_ms=10_000.0)
    sched.submit(victim)
    sched.step()
    assert victim.state == "active"
    assert sched.preempt_all() == 1
    assert victim.state == "queued" and victim.n_preemptions == 1
    victim.t_submit -= 11.0  # the deadline passed while it waited
    prefills = sched.engine.n_prefills
    finished = []
    sched._admit(finished)  # the exact front-of-queue guard
    assert finished == [victim] and victim.state == "expired"
    assert "queued" in victim.reason
    assert sched.engine.n_prefills == prefills, \
        "a dead-on-arrival requeue burned a recompute-prefill"


# -- load shedding ------------------------------------------------------------

def test_load_shedding_refuses_hopeless_deadline_requests():
    sched = Scheduler(FakeEngine(max_batch=1, num_blocks=30), shed=True)
    # before any rate evidence exists, shedding never fires
    early = _req(0, new=8, total_deadline_ms=1.0)
    assert sched.submit(early) is True
    # measured rate: 4 steps, 1 token each, 0.3 s span -> ~13 tok/s
    sched._rate.extend([(0.0, 1), (0.1, 1), (0.2, 1), (0.3, 1)])
    assert 10 < sched.recent_token_rate() < 20
    # backlog of 8 owed tokens needs ~600ms at that rate: a 50ms-deadline
    # arrival is hopeless and sheds AT ADMISSION (never queued)
    doomed = _req(1, new=8, total_deadline_ms=50.0)
    assert sched.submit(doomed) is False
    assert doomed.state == "shed" and doomed.terminal
    assert "backlog" in doomed.reason
    assert sched.n_shed == 1 and len(sched.queue) == 1
    # deadline-less requests are NEVER shed, whatever the backlog
    free_rider = _req(2, new=8)
    assert sched.submit(free_rider) is True
    # a generous deadline clears the estimate and admits
    patient = _req(3, new=8, total_deadline_ms=60_000.0)
    assert sched.submit(patient) is True


# -- livelock guard -----------------------------------------------------------

def test_livelock_guard_fails_impossible_prefix_and_keeps_serving():
    """A preempted request whose prompt+generated prefix outgrew the whole
    pool can never re-admit: pre-ISSUE-14 this raised out of the serve
    loop (killing every other request); now it FAILS typed and the rest
    of the traffic completes."""
    eng = FakeEngine(max_batch=2, block_size=4, num_blocks=5, max_context=64)
    sched = Scheduler(eng)
    # passes submit() (4+8=12 tokens -> 3 blocks <= 4 usable), then the
    # prefix grows past the pool, as preemption + generation can make it
    doomed = _req(0, prompt_len=4, new=8)
    doomed.generated = [1] * 13  # prefix 17 tokens -> 5 blocks > 4 usable
    survivor = _req(1, prompt_len=4, new=4)
    results, _ = run_open_loop(sched, [doomed, survivor])
    assert results[0].state == "failed" and results[0].terminal
    assert "can never be admitted" in results[0].reason
    assert results[1].state == "done" and len(results[1].generated) == 4
    assert sched.n_failed == 1
    rep = serve_report(results, 1.0, sched)
    assert rep["terminal_states"]["failed"] == 1
    assert rep["terminal_states"]["done"] == 1


# -- graceful drain (in-process) ----------------------------------------------

def test_drain_sheds_queued_finishes_active_in_process():
    eng = FakeEngine(max_batch=2, num_blocks=40)
    sched = Scheduler(eng)
    reqs = [_req(i, new=12) for i in range(6)]
    drain = lambda: sched.n_steps >= 2  # noqa: E731 — trip mid-drive
    results, _ = run_open_loop(sched, reqs, drain=drain, drain_s=30.0)
    assert len(results) == 6, "a request was lost in the drain"
    states = {rid: r.state for rid, r in results.items()}
    assert set(states.values()) <= set(TERMINAL_STATES)
    done = [r for r in results.values() if r.state == "done"]
    shed = [r for r in results.values() if r.state == "shed"]
    assert len(done) == 2, "the in-flight pair should finish inside drain_s"
    assert all(len(r.generated) == 12 for r in done)
    assert len(shed) == 4 and all(r.reason == "draining" for r in shed)
    assert sched.draining
    assert serve_report(results, 1.0, sched)["drained"] is True
    # once draining, submit() sheds on arrival
    late = _req(9, new=4)
    assert sched.submit(late) is False and late.state == "shed"


def test_drain_deadline_force_expires_stragglers():
    eng = FakeEngine(max_batch=2, num_blocks=40)
    sched = Scheduler(eng)
    reqs = [_req(i, new=50) for i in range(2)]  # outlive a zero budget
    results, _ = run_open_loop(
        sched, reqs, drain=lambda: sched.n_steps >= 1, drain_s=0.0)
    assert len(results) == 2
    assert all(r.state == "expired" for r in results.values())
    assert all("drain deadline" in r.reason for r in results.values())


# -- chaos sites in the scheduler --------------------------------------------

def test_serve_raise_and_stall_faults(monkeypatch):
    plan = FaultPlan.parse("serve:raise@1")
    sched = Scheduler(FakeEngine(max_batch=1, num_blocks=20),
                      fault_plan=plan)
    sched.submit(_req(0, new=30))
    sched.step()  # decode step 0: below the ordinal
    with pytest.raises(FaultInjected, match="decode step 1"):
        sched.step()

    monkeypatch.setenv("THEANOMPI_SERVE_STALL_S", "0.15")
    sched2 = Scheduler(FakeEngine(max_batch=1, num_blocks=20),
                       fault_plan=FaultPlan.parse("serve:stall@0"))
    sched2.submit(_req(1, new=4))
    t0 = time.perf_counter()
    sched2.step()
    assert time.perf_counter() - t0 >= 0.15
    t0 = time.perf_counter()
    sched2.step()  # one-shot: fired specs never re-trigger
    assert time.perf_counter() - t0 < 0.1


# -- request log --------------------------------------------------------------

def test_request_log_roundtrip_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "REQUESTS.jsonl")
    assert terminal_rids(path) == set()  # no file yet: nothing answered
    log = RequestLog(path, attempt=1)
    done = _req(3, new=2)
    done.state, done.generated = "done", [5, 5]
    shed = _req(7, new=2)
    shed.state, shed.reason = "shed", "draining"
    log.record(done)
    log.record(shed)
    log.close()
    with open(path, "a") as f:
        f.write('{"rid": 9, "state": "do')  # the SIGKILL-torn tail
    assert terminal_rids(path) == {3, 7}
    recs = [json.loads(l) for l in open(path) if l.strip().endswith("}")]
    assert recs[0] == {"rid": 3, "state": "done", "reason": None,
                      "n_generated": 2, "attempt": 1}
    assert recs[1]["reason"] == "draining"


# -- rollout watcher ----------------------------------------------------------

def _publish(ckpt, model, params, epoch, shift=0.0):
    """One verified checkpoint publish, the training writer's way."""
    from theanompi_tpu.utils.checkpoint import Checkpointer, model_fingerprint

    writer = Checkpointer(ckpt, fingerprint={
        "mesh": {"data": 8}, "exchange": "psum", "n_subb": 1,
        **model_fingerprint(model)})
    trees = {"params": jax.tree.map(
        lambda a: np.asarray(a) + shift, params)}
    writer.save(epoch, 10 * (epoch + 1), trees).join()
    writer.mark_clean()
    return trees


class _SchedStub:
    """preempt_all() is the rollout barrier; count the calls."""

    def __init__(self):
        self.n_preempt_calls = 0

    def preempt_all(self):
        self.n_preempt_calls += 1
        return 2


def _manager(engine, ckpt, model, params, **kw):
    kw.setdefault("poll_s", 0.0)
    return RolloutManager(engine, ckpt, {"params": params}, model=model,
                          current_epoch=0, **kw)


def test_rollout_tolerates_half_published_then_adopts(
        dense_model, serving_engine_factory, tmp_path):
    """ISSUE 14 satellite: a manifest whose .npz is mid-replace (or still
    missing) is 'not yet published' — refused, NEVER quarantined, and the
    very same epoch adopts once its bytes verify."""
    model, params, _ = dense_model
    ckpt = str(tmp_path / "ckpt")
    _publish(ckpt, model, params, 0)
    # private engine: rollouts swap weights, the shared one is read-only
    engine = serving_engine_factory(shared=False)
    mgr = _manager(engine, ckpt, model, params)
    sched = _SchedStub()
    assert newest_manifest_epoch(ckpt) == 0
    assert mgr.poll(sched) is None  # nothing newer than what's serving

    # half-published epoch 1: manifest visible, npz bytes still the
    # writer's in-flight garbage (the torn-publish race at serving's edge)
    man = os.path.join(ckpt, "ckpt_e0001.manifest.json")
    npz = os.path.join(ckpt, "ckpt_e0001.npz")
    open(man, "w").write(open(
        os.path.join(ckpt, "ckpt_e0000.manifest.json")).read())
    open(npz, "wb").write(b"PK-but-not-really")
    assert mgr.poll(sched) == "refused"
    assert mgr.poll(sched) == "refused"  # re-polls, still patient
    assert mgr.n_refused == 1            # but one event per candidate
    assert mgr.current_epoch == 0 and sched.n_preempt_calls == 0
    # never quarantined, never deleted: the live writer still owns these
    assert os.path.exists(man) and os.path.exists(npz)
    assert not os.path.exists(os.path.join(ckpt, "corrupt"))

    # the writer finishes the publish -> the SAME epoch now adopts
    os.remove(man)
    os.remove(npz)
    p1 = _publish(ckpt, model, params, 1, shift=1.0)
    assert mgr.poll(sched) == "rollout"
    assert mgr.current_epoch == 1 and mgr.n_rollouts == 1
    assert sched.n_preempt_calls == 1, "adopt must preempt before swapping"
    np.testing.assert_array_equal(
        np.asarray(engine.params["head"]["w"]),
        np.asarray(p1["params"]["head"]["w"]))


def test_rollout_corrupt_fault_refused_old_weights_keep_serving(
        dense_model, serving_engine_factory, tmp_path):
    """serve:rollout_corrupt@0 bit-flips the FIRST candidate before
    verification: it must be refused with the old weights intact, and the
    next (ordinal 1) candidate adopts untouched."""
    model, params, _ = dense_model
    ckpt = str(tmp_path / "ckpt")
    _publish(ckpt, model, params, 0)
    engine = serving_engine_factory(shared=False)
    w0 = np.asarray(engine.params["head"]["w"]).copy()
    mgr = _manager(engine, ckpt, model, params,
                   fault_plan=FaultPlan.parse("serve:rollout_corrupt@0"))
    sched = _SchedStub()
    _publish(ckpt, model, params, 1, shift=1.0)
    assert mgr.poll(sched) == "refused"  # the fault ate candidate 0
    np.testing.assert_array_equal(
        np.asarray(engine.params["head"]["w"]), w0)
    assert os.path.exists(os.path.join(ckpt, "ckpt_e0001.npz"))
    assert not os.path.exists(os.path.join(ckpt, "corrupt"))
    p2 = _publish(ckpt, model, params, 2, shift=2.0)
    assert mgr.poll(sched) == "rollout"  # ordinal 1: no spec, clean adopt
    assert mgr.current_epoch == 2 and mgr.n_refused == 1
    np.testing.assert_array_equal(
        np.asarray(engine.params["head"]["w"]),
        np.asarray(p2["params"]["head"]["w"]))


def test_rollout_probation_rollback_and_commit(
        dense_model, serving_engine_factory, tmp_path):
    """A critical SLO verdict inside the probation window rolls back to
    the previous weights and blacklists the epoch; a quiet probation
    commits, after which verdicts no longer matter."""
    model, params, _ = dense_model
    ckpt = str(tmp_path / "ckpt")
    _publish(ckpt, model, params, 0)
    engine = serving_engine_factory(shared=False)
    w0 = np.asarray(engine.params["head"]["w"]).copy()
    t = [0.0]
    verdicts = []
    mgr = _manager(engine, ckpt, model, params, probation_s=100.0,
                   health_verdicts=lambda: verdicts, clock=lambda: t[0])
    sched = _SchedStub()

    _publish(ckpt, model, params, 1, shift=1.0)
    t[0] = 1.0
    assert mgr.poll(sched) == "rollout" and mgr.current_epoch == 1
    # a WARN verdict is not enough; an unrelated detector is not enough
    verdicts[:] = [{"detector": "slo", "severity": "warn"},
                   {"detector": "loss", "severity": "critical"}]
    t[0] = 2.0
    assert mgr.poll(sched) != "rollback"
    # critical SLO inside probation -> roll back, blacklist epoch 1
    verdicts[:] = [{"detector": "slo", "severity": "critical",
                    "reason": "ttft p99 blew the SLO"}]
    t[0] = 3.0
    assert mgr.poll(sched) == "rollback"
    assert mgr.current_epoch == 0 and mgr.n_rollbacks == 1
    assert sched.n_preempt_calls == 2  # once on adopt, once on rollback
    np.testing.assert_array_equal(
        np.asarray(engine.params["head"]["w"]), w0)
    t[0] = 4.0
    assert mgr.poll(sched) is None, "a rolled-back epoch was re-adopted"

    # a NEW epoch adopts, survives probation quietly, and commits
    verdicts[:] = []
    p2 = _publish(ckpt, model, params, 2, shift=2.0)
    t[0] = 5.0
    assert mgr.poll(sched) == "rollout" and mgr.current_epoch == 2
    t[0] = 200.0  # past the probation window
    assert mgr.poll(sched) is None
    verdicts[:] = [{"detector": "throughput", "severity": "critical"}]
    t[0] = 201.0
    assert mgr.poll(sched) != "rollback", "probation already committed"
    assert mgr.current_epoch == 2
    np.testing.assert_array_equal(
        np.asarray(engine.params["head"]["w"]),
        np.asarray(p2["params"]["head"]["w"]))


# -- graceful drain under load: the supervised subprocess e2e ----------------

TMSERVE_TINY = [
    "--modelclass", "TransformerLM",
    "--set", "dim=32", "--set", "heads=2", "--set", "n_layers=1",
    "--set", "seq_len=32", "--set", "vocab=61", "--set", "dropout=0.0",
    "--set", "precision=fp32", "--set", "n_train=64", "--set", "n_val=32",
    "--max-batch", "2", "--block-size", "4", "--prompt-len", "4",
]


def _child_env(cache, **extra):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "JAX_THREEFRY_PARTITIONABLE": "true",
                "JAX_COMPILATION_CACHE_DIR": cache,
                "PYTHONPATH": REPO})
    env.pop("THEANOMPI_FAULT_PLAN", None)
    env.update(extra)
    return env


def _wait_for(path, deadline_s, proc=None):
    deadline = time.perf_counter() + deadline_s
    while time.perf_counter() < deadline:
        if os.path.exists(path):
            return True
        if proc is not None and proc.poll() is not None:
            return False
        time.sleep(0.05)
    return False


@pytest.mark.faultinject
def test_graceful_drain_under_load_supervised_classifies_clean(
        tmp_path, subproc_compile_cache):
    """ISSUE 14 satellite e2e: tmserve --supervise with 8 burst requests
    in flight takes a SIGTERM, every request reaches a terminal state
    within --drain-s, none is lost, the replica exits 0 and the
    supervisor classifies the episode CLEAN (no restart burned)."""
    tel = str(tmp_path / "tel")
    # serve:stall@1 holds decode step 1 for 8s — a deterministic window
    # where all 8 requests are in flight (none can have finished: a
    # completion needs >= 15 decode steps), however fast the compile was
    child = subprocess.Popen(
        [sys.executable, "-m", "theanompi_tpu.serving", *TMSERVE_TINY,
         "--requests", "8", "--max-new-tokens", "16",
         "--drain-s", "30", "--telemetry-dir", tel, "--quiet",
         "--supervise", "--max-restarts", "2", "--backoff-base", "0.1"],
        env=_child_env(subproc_compile_cache,
                       THEANOMPI_FAULT_PLAN="serve:stall@1",
                       THEANOMPI_SERVE_STALL_S="8"),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        log = os.path.join(tel, "REQUESTS.jsonl")
        assert _wait_for(log, 240, child), \
            f"replica never reached the serve loop: {child.communicate()}"
        time.sleep(1.0)  # into the loop (handler installed, stall armed)
        child.send_signal(signal.SIGTERM)  # supervisor forwards to replica
        out, err = child.communicate(timeout=240)
    finally:
        if child.poll() is None:
            child.kill()
            child.communicate()
    assert child.returncode == 0, f"drained exit was not clean:\n{err}"
    recs = [json.loads(l) for l in open(log) if l.strip()]
    assert sorted(r["rid"] for r in recs) == list(range(8)), \
        "a request was lost in the drain"
    assert {r["state"] for r in recs} <= set(TERMINAL_STATES)
    assert any(r["state"] == "shed" for r in recs), \
        "SIGTERM landed with nothing queued — the window logic broke"
    # the supervisor saw exit 0 after its SIGTERM forward: CLEAN, one
    # attempt, nothing restarted
    art = json.load(open(os.path.join(tel, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["clean"]
    assert art["final_exit"] == 0


# -- the chaos acceptance drive (tier-2) --------------------------------------

@pytest.mark.slow
@pytest.mark.faultinject
def test_chaos_crash_restart_and_corrupt_then_good_rollout_with_rollback(
        tmp_path, subproc_compile_cache):
    """THE acceptance e2e: a 24-request supervised drive survives a
    serve:raise crash-restart AND a corrupt-then-good rollout published
    mid-drive — zero requests lost across attempts, the corrupt candidate
    refused with the old weights still serving, the good one swapped in
    (rollout event), and a forced SLO-critical probation auto-rolls back."""
    from theanompi_tpu.launcher import _parse_kv
    from theanompi_tpu.models.transformer_lm import TransformerLM

    tiny = _parse_kv([a for a in TMSERVE_TINY if "=" in a])
    model = TransformerLM(tiny)
    params, _ = model.init_params(jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    _publish(ckpt, model, params, 0)
    tel = str(tmp_path / "tel")
    out_json = str(tmp_path / "SERVE.json")

    child = subprocess.Popen(
        [sys.executable, "-m", "theanompi_tpu.serving", *TMSERVE_TINY,
         "--requests", "24", "--max-new-tokens", "16",
         "--arrival-rate", "2",  # ~12s of arrivals: a real mid-drive window
         "--checkpoint-dir", ckpt, "--rollout-watch",
         "--rollout-poll-s", "0.1", "--rollout-probation-s", "60",
         "--slo-ttft-ms", "0.001",  # every real TTFT is SLO-critical
         "--telemetry-dir", tel, "--out", out_json, "--quiet",
         "--supervise", "--max-restarts", "3", "--backoff-base", "0.1"],
        env=_child_env(subproc_compile_cache,
                       # crash attempt 1 at decode step 20 — past the first
                       # request's ~15 completion steps (so BOTH attempts
                       # have terminal records), attempt-gated so attempt 2
                       # rides through
                       THEANOMPI_FAULT_PLAN="serve:raise@20@1"),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        log = os.path.join(tel, "REQUESTS.jsonl")
        assert _wait_for(log, 300, child), \
            f"replica never reached the serve loop: {child.communicate()}"
        # wait until attempt 2 is live (its records carry attempt: 2) —
        # the crash itself happened at decode step 6 of attempt 1
        deadline = time.perf_counter() + 300
        while time.perf_counter() < deadline:
            recs = [json.loads(l) for l in open(log)
                    if l.strip().endswith("}")]
            if any(r["attempt"] >= 2 for r in recs):
                break
            assert child.poll() is None, \
                f"supervisor died early: {child.communicate()}"
            time.sleep(0.1)
        else:
            pytest.fail("attempt 2 never produced a terminal request")
        # corrupt-then-good, published mid-drive by the training writer:
        # epoch 1's npz is garbage under a visible manifest (refused, old
        # weights keep serving), epoch 2 is the real thing (adopted)
        open(os.path.join(ckpt, "ckpt_e0001.manifest.json"), "w").write(
            open(os.path.join(ckpt, "ckpt_e0000.manifest.json")).read())
        open(os.path.join(ckpt, "ckpt_e0001.npz"), "wb").write(b"garbage")
        time.sleep(2.0)  # >= 20 watcher polls on the corrupt candidate
        _publish(ckpt, model, params, 2, shift=1.0)
        out, err = child.communicate(timeout=300)
    finally:
        if child.poll() is None:
            child.kill()
            child.communicate()
    assert child.returncode == 0, f"chaos drive did not end clean:\n{err}"

    # zero requests lost: every id reached exactly one terminal state
    recs = [json.loads(l) for l in open(log) if l.strip()]
    assert sorted(r["rid"] for r in recs) == list(range(24))
    assert {r["state"] for r in recs} <= set(TERMINAL_STATES)
    assert {r["attempt"] for r in recs} == {1, 2}, \
        "both attempts must have served requests"

    # supervisor audit: one crash (the injected raise), then clean
    art = json.load(open(os.path.join(tel, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]

    # rollout audit: corrupt refused, good adopted, probation rolled back
    rep = json.load(open(out_json))
    assert rep["rollout"]["refused"] >= 1, "the corrupt candidate slipped by"
    assert rep["rollout"]["rollouts"] == 1
    assert rep["rollout"]["rollbacks"] == 1, \
        "the SLO-critical probation did not roll back"
    assert rep["rollout"]["serving_epoch"] == 0  # back on the old weights
    assert rep["attempt"] == 2
    # refused-never-quarantined, even from a subprocess
    assert os.path.exists(os.path.join(ckpt, "ckpt_e0001.npz"))
    assert not os.path.exists(os.path.join(ckpt, "corrupt"))
