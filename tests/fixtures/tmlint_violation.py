"""Deliberately-seeded tmlint violations (ISSUE 7 satellite).

NOT package code — lives under tests/fixtures/ so the clean-package
tier-1 sweep never sees it.  ``test_tmlint.py`` points the CLI at this
file and asserts a non-zero exit with one finding per seeded class.
"""

import json
import threading
import time

import numpy as np


def wall_clock_violation():
    return time.time()  # seeded: rule `wall`


def swallow_violation():
    try:
        wall_clock_violation()
    except Exception:
        pass  # seeded: rule `swallow`


def np_load_violation(path):
    return np.load(path)  # seeded: rule `np-load`


def donated_escape_violation(x):
    return np.asarray(x)  # seeded: rule `donated-escape`


def exit_code_violation(rc):
    return rc == 77  # seeded: rule `exit-code`


def suppression_violation():
    # seeded: rule `suppression` (marker with no justification)
    stamp = time.time()  # lint: wall-ok
    return stamp


def atomic_publish_violation(path, obj):
    with open(path, "w") as f:  # seeded: rule `atomic-publish`
        json.dump(obj, f)


def thread_lifecycle_violation(fn):
    t = threading.Thread(target=fn, daemon=True)  # seeded: `thread-lifecycle`
    t.start()
    return t
