"""ISSUE 17: prefix-cached paged KV — refcounted copy-on-write shared
blocks, radix prompt matching, partial prefill.

The contract under test, end to end on the CPU mesh:

- **refcounts** — BlockPool alloc/acquire/free keep shared blocks live
  until the LAST holder releases them; double frees and out-of-pool ids
  raise instead of corrupting the free list (O(1) free-set membership);
- **radix tree** — full-block token chunks chained on the parent map
  prompt prefixes to block ids; insert dedups, match acquires, LRU
  eviction only takes zero-ref leaves, and the params-version stamp
  drops the whole tree on a weight swap;
- **partial prefill** — a prefix-cache hit computes K/V and logits for
  the SUFFIX only, bucketed power-of-two on suffix length, and
  reproduces the full-prefill next token + last-position logits;
- **bit-equality (the acceptance lock)** — 12+ multi-turn shared-prefix
  requests through a pool tight enough to force eviction, greedy AND
  temperature sampling: cache-ON token streams identical to cache-OFF,
  with prefix_hit_rate > 0 and prefill_tokens_saved exactly the sum of
  matched-prefix lengths the engine was handed;
- **invalidation-on-rollout** — the NEGATIVE test: with the stamp
  defeated, a weight swap serves stale cached K/V and the streams
  diverge from a cold-cache run under the new weights; with the stamp
  honored they are bit-equal;
- **eviction under sharing** — preempting one of two prefix-sharing
  requests mid-decode leaves the survivor's blocks live, the preempted
  request's recompute-prefill hits the cache, and both streams stay
  bit-equal to a cold run.
"""

import numpy as np
import pytest

import jax

from theanompi_tpu.serving import (
    BlockPool,
    InferenceEngine,
    PrefixCache,
    Request,
    Scheduler,
    blocks_for,
    run_open_loop,
    serve_report,
)
from theanompi_tpu.serving.cli import synthetic_requests

VOCAB = 61  # SERVING_TINY's vocab (the dense_model fixture)


# -- BlockPool refcounts ------------------------------------------------------

def test_block_pool_refcount_lifecycle():
    pool = BlockPool(6)  # block 0 reserved -> 5 usable
    row = pool.alloc(2)
    assert all(pool.ref(b) == 1 for b in row)
    pool.acquire(row)  # a second holder
    assert all(pool.ref(b) == 2 for b in row)
    free_before = pool.free_blocks
    pool.free(row)  # first holder leaves: blocks stay live
    assert all(pool.ref(b) == 1 for b in row)
    assert pool.free_blocks == free_before
    pool.free(row)  # last holder leaves: blocks return to the free list
    assert all(pool.ref(b) == 0 for b in row)
    assert pool.free_blocks == free_before + 2
    # freed blocks are allocatable again
    again = pool.alloc(5)
    assert again is not None and set(row) <= set(again)


def test_block_pool_double_free_and_range_checks():
    pool = BlockPool(6)
    row = pool.alloc(2)
    pool.free(row)
    with pytest.raises(ValueError, match="double free"):
        pool.free([row[0]])
    with pytest.raises(ValueError, match="double free"):
        # duplicate within ONE call: the free-set catches it mid-batch
        two = pool.alloc(1)
        pool.free([two[0], two[0]])
    with pytest.raises(ValueError, match="outside pool"):
        pool.free([0])  # the reserved null block is never pool-managed
    with pytest.raises(ValueError, match="outside pool"):
        pool.free([6])
    with pytest.raises(ValueError, match="outside pool"):
        pool.acquire([99])
    with pytest.raises(ValueError, match="acquiring free block"):
        pool.acquire([row[1]])  # unallocated: nothing to share


# -- PrefixCache radix tree ---------------------------------------------------

def test_prefix_cache_match_insert_dedup():
    pool = BlockPool(16)
    cache = PrefixCache(pool, 4)
    row = pool.alloc(2)
    assert cache.insert([1, 2, 3, 4, 5, 6, 7, 8], row) == 2
    assert cache.n_nodes == 2
    # match acquires IN SEQUENCE ORDER and caps below the full prompt:
    # at least one token must stay uncached for next-token logits
    assert cache.match([1, 2, 3, 4, 5, 6, 7, 8, 9]) == row
    assert all(pool.ref(b) == 2 for b in row)  # tree + the match
    assert cache.match([1, 2, 3, 4, 5, 6, 7, 8]) == row[:1]
    assert cache.match([1, 2, 3, 4]) == []
    assert cache.match([9, 9, 9, 9, 9]) == []  # divergent first chunk
    # divergence mid-prefix stops the walk at the last matching block
    assert cache.match([1, 2, 3, 4, 9, 9, 9, 9, 9]) == row[:1]
    pool.free(row + row[:2])  # release every matched ref
    # dedup: inserting an already-cached chunk releases the caller's copy
    dup = pool.alloc(2)
    free_before = pool.free_blocks
    assert cache.insert([1, 2, 3, 4, 5, 6, 7, 8], dup) == 0
    assert cache.n_nodes == 2
    assert pool.free_blocks == free_before + 2  # both dup refs released
    with pytest.raises(ValueError, match="full"):
        cache.insert([1, 2, 3], pool.alloc(1))


def test_prefix_cache_lru_eviction_spares_shared_blocks():
    pool = BlockPool(16)
    cache = PrefixCache(pool, 2)
    a = pool.alloc(2)
    b = pool.alloc(1)
    cache.insert([1, 2, 3, 4], a)     # chain a: two blocks
    cache.insert([9, 9], b)           # chain b: one block
    # touch chain a -> chain b is now LRU
    held = cache.match([1, 2, 3, 4, 5])
    assert held == a
    # chain a's blocks are shared (ref 2): only b is evictable
    assert cache.evict(3) == 1
    assert cache.n_nodes == 2 and pool.ref(b[0]) == 0
    pool.free(held)  # the match's refs released: tree is sole holder
    # leaves evict deepest-first: a[1] (leaf) then a[0] (exposed parent)
    assert cache.evict(2) == 2
    assert cache.n_nodes == 0
    assert pool.free_blocks == 15


def test_prefix_cache_version_stamp():
    pool = BlockPool(8)
    cache = PrefixCache(pool, 2)
    # first stamp: adopts the version, nothing to invalidate
    assert cache.check_version(0) is False
    row = pool.alloc(2)
    cache.insert([1, 2, 3, 4], row)
    assert cache.check_version(0) is False  # same version: no-op
    assert cache.n_nodes == 2
    free_before = pool.free_blocks
    assert cache.check_version(1) is True  # weight swap: whole tree drops
    assert cache.n_nodes == 0
    assert pool.free_blocks == free_before + 2
    assert cache.params_version == 1
    assert cache.check_version(1) is False


# -- partial prefill (engine level) -------------------------------------------

def test_partial_prefill_matches_full_prefill(serving_engine):
    """A prefix-cache hit reuses the cached blocks' K/V and computes the
    suffix only — same next token, same last-position logits (within
    float round-off of the paged-gather attention path)."""
    engine = serving_engine
    pool = BlockPool(engine.num_blocks)
    rng = np.random.RandomState(5)
    prompt = [int(x) for x in rng.randint(0, VOCAB, 10)]

    row_a = pool.alloc(blocks_for(len(prompt), 4))
    tok_a, last_a = engine.prefill(row_a, prompt, 0.0, rid=1)
    # partial: the first two blocks' K/V is already in the pool (row_a
    # wrote it) — share them, compute only tokens 8..9
    row_b = row_a[:2] + pool.alloc(1)
    tok_b, last_b = engine.prefill(row_b, prompt, 0.0, rid=1, prefix_len=8)
    assert tok_a == tok_b
    np.testing.assert_allclose(last_b, last_a, rtol=1e-4, atol=1e-4)
    # temperature path: the sample key derives from (rid, position) only,
    # so the partial-prefill sample reproduces the full-prefill sample
    row_c = pool.alloc(blocks_for(len(prompt), 4))
    tok_c, _ = engine.prefill(row_c, prompt, 0.9, rid=7)
    row_d = row_c[:1] + pool.alloc(2)
    tok_d, _ = engine.prefill(row_d, prompt, 0.9, rid=7, prefix_len=4)
    assert tok_c == tok_d

    with pytest.raises(ValueError, match="whole number"):
        engine.prefill(row_a, prompt, 0.0, rid=1, prefix_len=3)
    with pytest.raises(ValueError, match="at least one token"):
        engine.prefill(row_a, prompt, 0.0, rid=1, prefix_len=12)


def test_partial_prefill_program_count_is_log_bounded(serving_engine):
    """Suffix programs bucket power-of-two on the PADDED SUFFIX length
    (the full row is fixed-width), so a serve accumulates at most
    log2(max_blocks_per_seq)+1 partial-prefill programs — compile cost
    stays bounded no matter the prefix/suffix mix.  Runs on the SHARED
    session engine deliberately: the bound must hold over the whole
    tier-1 run's accumulated suffix mix, not a fresh engine's."""
    engine = serving_engine
    pool = BlockPool(engine.num_blocks)
    rng = np.random.RandomState(6)
    bound = int(np.log2(engine.max_blocks_per_seq)) + 1
    for total, prefix_len in ((6, 4), (10, 4), (12, 8), (16, 4), (20, 8),
                              (24, 20), (30, 8)):
        prompt = [int(x) for x in rng.randint(0, VOCAB, total)]
        row = pool.alloc(blocks_for(total, 4))
        engine.prefill(row, prompt, 0.0, rid=1, prefix_len=prefix_len)
        pool.free(row)
    assert len(engine._prefill_suffix_fns) <= bound
    # and the buckets are exactly power-of-two block multiples
    assert all(s % 4 == 0 and (s // 4) & (s // 4 - 1) == 0
               for s in engine._prefill_suffix_fns)


# -- the acceptance lock ------------------------------------------------------

def _serve_traffic(model, params, *, prefix_cache, num_blocks, spy=None):
    """12 multi-turn shared-prefix requests, greedy/temperature mixed, one
    scheduler; -> ({rid: token tuple}, scheduler, report)."""
    engine = InferenceEngine(model, params, block_size=4, max_batch=4,
                             num_blocks=num_blocks, seed=0)
    if spy is not None:
        orig = engine.prefill

        def record(table_row, tokens, temperature=0.0, rid=0, prefix_len=0):
            spy.append(prefix_len)
            return orig(table_row, tokens, temperature, rid,
                        prefix_len=prefix_len)

        engine.prefill = record
    sched = Scheduler(engine, prefix_cache=prefix_cache)
    reqs = synthetic_requests(12, VOCAB, 4, 8, 0.0, 0, temperature=0.0,
                              turns=3, shared_prefix=8)
    for r in reqs:
        if r.rid % 2:
            r.temperature = 0.8
    results, wall = run_open_loop(sched, reqs)
    rep = serve_report(results, wall, sched)
    return {r.rid: tuple(r.generated) for r in results.values()}, sched, rep


def test_prefix_cache_on_off_bit_equal_under_eviction(dense_model):
    """THE acceptance lock: cache-ON greedy AND temperature token streams
    are bit-equal to cache-OFF across 12 multi-turn shared-prefix
    requests through a pool tight enough to force preemption, with
    prefix_hit_rate > 0 and prefill_tokens_saved EXACTLY the sum of the
    matched-prefix lengths handed to engine.prefill."""
    model, params, _state = dense_model
    off, sched_off, rep_off = _serve_traffic(
        model, params, prefix_cache=False, num_blocks=20)
    seen = []
    on, sched_on, rep_on = _serve_traffic(
        model, params, prefix_cache=True, num_blocks=20, spy=seen)

    assert all(len(t) == 8 for t in off.values())
    assert on == off, {k: (off[k], on[k]) for k in off if off[k] != on[k]}
    # the pool was sized to force eviction WITH the tree holding blocks
    assert sched_on.n_preemptions > 0
    # accounting is exact, not sampled: every prefill's prefix_len summed
    assert rep_on["prefix_cache"] is True
    assert rep_on["prefix_hit_rate"] > 0
    assert rep_on["prefill_tokens_saved"] == sum(seen) > 0
    assert sched_on.n_prefix_hits == sum(1 for s in seen if s)
    assert rep_off["prefix_cache"] is False
    assert rep_off["prefix_hit_rate"] == 0.0
    assert rep_off["prefill_tokens_saved"] == 0
    # nothing leaked: finished requests released their refs, only the
    # radix tree still pins blocks
    assert sched_on.pool.free_blocks + sched_on.prefix_cache.n_nodes == 19


def test_swap_params_invalidates_prefix_cache(dense_model):
    """The rollout-invalidation contract, proven in BOTH directions.

    Negative half (the bug the stamp prevents): defeat the stamp by
    hand-setting the cache's params_version after a weight swap — cached
    K/V computed under the OLD weights then serves the new requests, and
    their token streams DIVERGE from a cold-cache run under the new
    weights.  Positive half: with the stamp honored, the swap drops the
    whole tree and the streams are bit-equal to the cold run."""
    model, params, _state = dense_model
    params2, _ = model.init_params(jax.random.PRNGKey(123))

    def batch():
        rng = np.random.RandomState(11)
        shared = [int(x) for x in rng.randint(0, VOCAB, 12)]
        return [Request(rid=i,
                        prompt=shared + [int(x) for x in
                                         rng.randint(0, VOCAB, 2)],
                        max_new_tokens=6)
                for i in range(4)]

    def mk(tree):
        engine = InferenceEngine(model, tree, block_size=4, max_batch=2,
                                 num_blocks=40, seed=0)
        return engine, Scheduler(engine, prefix_cache=True)

    def streams(results):
        return {r.rid: tuple(r.generated) for r in results.values()}

    # cold-cache reference under the NEW weights
    _eng_ref, sched_ref = mk(params2)
    ref = streams(run_open_loop(sched_ref, batch())[0])

    # negative: warm the tree under the old weights, swap, TAMPER the
    # stamp so the invalidation check can't fire, serve again
    eng, sched = mk(params)
    run_open_loop(sched, batch())
    assert sched.prefix_cache.n_nodes > 0
    eng.swap_params(params2)
    sched.prefix_cache.params_version = eng.params_version  # defeat stamp
    hits_before = sched.n_prefix_hits
    stale = streams(run_open_loop(sched, batch())[0])
    assert sched.n_prefix_hits > hits_before  # stale K/V WAS served
    assert stale != ref, (
        "stale cached K/V across a weight swap produced the new-weight "
        "streams — the negative test lost its teeth")

    # positive: same flow with the stamp honored — the tree drops at the
    # first admission after the swap and the streams match the cold run
    eng3, sched3 = mk(params)
    run_open_loop(sched3, batch())
    assert sched3.prefix_cache.n_nodes > 0
    eng3.swap_params(params2)
    ok = streams(run_open_loop(sched3, batch())[0])
    assert ok == ref
    assert sched3.prefix_cache.params_version == eng3.params_version
    # restore_params is a THIRD weight state: the stamp moves again
    v = eng3.params_version
    eng3.restore_params(eng3.params)
    assert eng3.params_version == v + 1


def test_eviction_under_sharing_keeps_survivor_blocks_live(dense_model):
    """Preempt one of two prefix-SHARING requests mid-decode: refcounts
    keep the shared blocks live for the survivor, the preempted request's
    recompute-prefill hits the cache, and both token streams stay
    bit-equal to a cold-cache (cache-OFF, roomy-pool) run."""
    model, params, _state = dense_model
    rng = np.random.RandomState(4)
    shared = [int(x) for x in rng.randint(0, VOCAB, 12)]
    sfx_b, sfx_c = ([int(x) for x in rng.randint(0, VOCAB, 2)]
                    for _ in range(2))

    def reqs():
        return [Request(rid=1, prompt=shared + sfx_b, max_new_tokens=8),
                Request(rid=2, prompt=shared + sfx_c, max_new_tokens=8,
                        temperature=0.8)]

    # cold reference: no cache, no pressure, no preemption
    eng_ref = InferenceEngine(model, params, block_size=4, max_batch=2,
                              num_blocks=24, seed=0)
    ref_res, _ = run_open_loop(Scheduler(eng_ref), reqs())
    ref = {r.rid: tuple(r.generated) for r in ref_res.values()}

    engine = InferenceEngine(model, params, block_size=4, max_batch=2,
                             num_blocks=24, seed=0)
    sched = Scheduler(engine, prefix_cache=True)
    # warm the tree: one completed request whose prompt IS the shared
    # prefix (its 3 full blocks land in the radix tree)
    run_open_loop(sched, [Request(rid=0, prompt=list(shared),
                                  max_new_tokens=4)])
    finished = []
    for r in reqs():
        sched.submit(r)
    finished += sched.step()
    # both admissions matched the tree's 3 shared blocks
    assert sched.n_prefix_hits >= 2
    slot_b = next(s for s, r in enumerate(sched.slots)
                  if r is not None and r.rid == 1)
    slot_c = next(s for s, r in enumerate(sched.slots)
                  if r is not None and r.rid == 2)
    shared_ids = sched._blocks[slot_b][:3]
    assert sched._blocks[slot_c][:3] == shared_ids  # genuinely shared
    assert all(sched.pool.ref(b) == 3 for b in shared_ids)  # tree + b + c

    finished += sched.step()
    victim = sched.slots[slot_b]
    sched._preempt(slot_b)  # forced mid-decode eviction of ONE sharer
    # the survivor (and the tree) still hold the shared blocks
    assert all(sched.pool.ref(b) == 2 for b in shared_ids)
    assert sched.slots[slot_c] is not None
    hits_before = sched.n_prefix_hits
    while not sched.idle:
        finished += sched.step()
    assert victim.n_preemptions == 1
    # the recompute-prefill re-matched the cache instead of recomputing
    # the shared prefix from scratch
    assert sched.n_prefix_hits > hits_before
    got = {r.rid: tuple(r.generated) for r in finished if r.rid in (1, 2)}
    assert got == ref
