"""ISSUE 15: the deterministic interleaving harness, end to end.

Three tiers:

- **Harness unit tests** — ``Interleaver`` grants sync-points in the
  exact armed order, unscheduled names pass through, infeasible heads
  are dropped deterministically (``skipped`` in the trace, never a
  hang), and both schedule generators (``schedules`` permutations,
  ``interleavings`` order-preserving merges) are seeded-stable.
- **Real-seam suites** — every order-preserving interleaving of the
  three instrumented seams, with state invariants asserted after each:
  (a) async checkpoint writer vs. the next ``save``/``join_pending``
  (the PR 5 torn-snapshot seam), (b) fleet scheduler pass vs. episode
  completion vs. ``adopt()`` (the PR 10/11 registration seam),
  (c) health ticker tick vs. ``Telemetry.close()``.
- **Negative proof** — ``race_audit`` detects the seeded lost-update
  race (and only it), and ``tmlint --race-audit`` exits 1 the moment
  the harness stops detecting it (the ``hlo_audit`` philosophy: the
  checker must prove it still has teeth).

Everything here is compile-light: numpy trees, ``python -c`` job specs
that a stubbed ``run_job`` never actually executes, no XLA compiles.
"""

import json
import os
import sys
import threading

import numpy as np
import pytest

from theanompi_tpu.analysis import cli as lint_cli
from theanompi_tpu.analysis import interleave
from theanompi_tpu.analysis.interleave import (
    RACE_CHAINS,
    GuardedCounter,
    Interleaver,
    RaceAuditError,
    RacyCounter,
    interleavings,
    race_audit,
    schedules,
    sp,
)

CLEAN_SRC = "def f(x):\n    return x + 1\n"


# -- harness unit tests ------------------------------------------------------

def test_sp_is_noop_when_disarmed():
    # must return instantly for any name — the production-path contract
    sp("never.armed.point")
    sp("ckpt.write.publish")


def test_interleaver_realizes_the_exact_order():
    # the two RacyCounter outcomes ARE the proof of exact control: the
    # same code loses the update iff the armed order says so
    lost = ["a.load", "b.load", "a.store", "b.store"]
    serial = ["a.load", "a.store", "b.load", "b.store"]
    assert interleave._run_counter(RacyCounter, list(lost), 2.0) == 1
    assert interleave._run_counter(RacyCounter, list(serial), 2.0) == 2


def test_interleaver_trace_records_grants_in_order():
    order = ["a.load", "b.load", "a.store", "b.store"]
    c = RacyCounter()
    il = Interleaver(list(order))
    with il:
        ts = [threading.Thread(target=c.bump, args=(lbl,),
                               name=f"test-bump-{lbl}") for lbl in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert il.trace == [(n, "granted") for n in order]
    assert il.order == []


def test_unscheduled_names_pass_through():
    il = Interleaver(["only.this"], timeout_s=0.2)
    with il:
        sp("something.else")  # same thread: would deadlock if it blocked
        sp("only.this")
    assert il.trace == [("only.this", "granted")]


def test_unreachable_head_is_skipped_not_hung():
    il = Interleaver(["ghost.point", "real.point"], timeout_s=0.1)
    with il:
        sp("real.point")  # blocks behind the ghost until the timeout
    assert il.trace == [("ghost.point", "skipped"), ("real.point", "granted")]


def test_arm_is_exclusive():
    with Interleaver(["x"]):
        with pytest.raises(RuntimeError, match="already armed"):
            interleave.arm(Interleaver(["y"]))
    interleave.disarm()  # idempotent


def test_schedules_full_factorial_and_seeded_sample():
    full = schedules(["a", "b", "c"])
    assert len(full) == 6 and len({tuple(s) for s in full}) == 6
    sample = schedules(list("abcde"), limit=10, seed=3)
    assert sample == schedules(list("abcde"), limit=10, seed=3)
    assert len(sample) == 10
    assert len({tuple(s) for s in sample}) == 10
    for s in sample:
        assert sorted(s) == sorted("abcde")


def _is_subsequence(chain, merged):
    it = iter(merged)
    return all(x in it for x in chain)


def test_interleavings_preserve_every_chain_order():
    chains = [["s1", "s2"], ["w1", "w2", "w3"]]
    merges = interleavings(chains)
    assert len(merges) == 10  # C(5,2)
    assert len({tuple(m) for m in merges}) == 10
    for m in merges:
        for c in chains:
            assert _is_subsequence(c, m)
    sample = interleavings(chains, limit=4, seed=7)
    assert sample == interleavings(chains, limit=4, seed=7)
    assert len(sample) == 4
    for m in sample:
        for c in chains:
            assert _is_subsequence(c, m)


# -- the negative proof (race_audit + CLI) -----------------------------------

def test_race_audit_detects_the_seeded_race():
    report = race_audit()
    # two 2-chains -> 6 merges; the update is lost exactly when both
    # loads land before either store (4 of the 6)
    assert report["orderings"] == 6
    assert report["racy_lost_updates"] == 4
    assert report["guarded_lost_updates"] == 0
    assert report["detected"] is True


def test_race_audit_raises_when_defanged(monkeypatch):
    # swap the racy twin for the guarded one: the audit must notice the
    # harness no longer detects anything and refuse to pass
    monkeypatch.setattr(interleave, "RacyCounter", GuardedCounter)
    with pytest.raises(RaceAuditError, match="lost its teeth") as ei:
        race_audit()
    assert ei.value.report["racy_lost_updates"] == 0
    assert ei.value.report["detected"] is False


def test_guarded_counter_clean_under_every_merge():
    for order in interleavings(RACE_CHAINS):
        assert interleave._run_counter(GuardedCounter, order, 2.0) == 2


def test_cli_race_audit_clean(tmp_path, capsys):
    p = tmp_path / "clean.py"
    p.write_text(CLEAN_SRC)
    rc = lint_cli.main([str(p), "--race-audit"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "race-audit: seeded race detected in 4/6 orderings" in out
    assert "guarded twin clean" in out


def test_cli_race_audit_failure_exits_1(tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(interleave, "RacyCounter", GuardedCounter)
    p = tmp_path / "clean.py"
    p.write_text(CLEAN_SRC)
    rc = lint_cli.main([str(p), "--race-audit"])
    cap = capsys.readouterr()
    assert rc == 1
    assert "tmlint: error: race-audit" in cap.err
    assert "lost its teeth" in cap.err


def test_cli_race_audit_lands_in_report(tmp_path, capsys):
    p = tmp_path / "clean.py"
    p.write_text(CLEAN_SRC)
    rpath = tmp_path / "report.json"
    rc = lint_cli.main([str(p), "--race-audit", "--report", str(rpath),
                        "--quiet"])
    capsys.readouterr()
    assert rc == 0
    report = json.loads(rpath.read_text())
    assert report["race_audit"]["detected"] is True
    assert report["race_audit"]["orderings"] == 6
    assert "race_audit_error" not in report


def test_cli_race_audit_failure_report_carries_error(tmp_path, monkeypatch,
                                                     capsys):
    monkeypatch.setattr(interleave, "RacyCounter", GuardedCounter)
    p = tmp_path / "clean.py"
    p.write_text(CLEAN_SRC)
    rpath = tmp_path / "report.json"
    rc = lint_cli.main([str(p), "--race-audit", "--report", str(rpath),
                        "--quiet"])
    capsys.readouterr()
    assert rc == 1
    report = json.loads(rpath.read_text())
    assert "lost its teeth" in report["race_audit_error"]
    assert report["race_audit"]["racy_lost_updates"] == 0


# -- seam (a): async checkpoint writer vs. next save/join --------------------

def _ckpt_orders():
    """save(0) is pinned first (it spawns the writer); then every merge
    of the writer's chain against the *next* save's trainer chain — the
    overlap window where the PR 5 torn snapshot lived."""
    overlap = interleavings([
        ["ckpt.save", "ckpt.join"],
        ["ckpt.write.begin", "ckpt.write.publish", "ckpt.write.done"],
    ])
    return [["ckpt.save", "ckpt.join"] + m for m in overlap]


@pytest.mark.parametrize("order", _ckpt_orders(),
                         ids=lambda o: "-".join(n.split(".")[-1] for n in o[2:]))
def test_checkpoint_async_overlap(tmp_path, order):
    from theanompi_tpu.utils.checkpoint import Checkpointer

    trees = {"params": {"w": np.arange(8, dtype=np.float32),
                        "b": np.ones((3,), dtype=np.float32)}}
    d = str(tmp_path / "ckpt")
    ck = Checkpointer(d, async_save=True)
    with Interleaver(list(order), timeout_s=2.0):
        ck.save(0, 10, trees)
        ck.save(1, 20, trees)   # joins writer-0 per the armed order
        ck.join_pending()       # writer-1 (its points ran post-order)
    # invariants under EVERY interleaving: both epochs published whole,
    # verification passes, latest points at the newest, no tmp debris
    assert ck.latest_epoch() == 1
    assert ck.latest_iteration() == 20
    for epoch, iteration in ((0, 10), (1, 20)):
        man = ck.verify_epoch(epoch, level="full")
        assert man["iteration"] == iteration
    assert not [f for f in os.listdir(d) if ".tmp" in f]


# -- seam (b): fleet scheduler pass vs. episode done vs. adopt ---------------

class _StubSupervisor:
    def terminate(self):
        pass


def _stub_run_job(child_cmd, *, on_supervisor=None, **kw):
    from theanompi_tpu.resilience import EXIT_CLEAN
    from theanompi_tpu.resilience.supervisor import JobResult

    if on_supervisor is not None:
        on_supervisor(_StubSupervisor())
    return JobResult(exit_code=EXIT_CLEAN, cause="clean", attempts=[],
                     preempted=False)


def _job_spec(jid):
    from theanompi_tpu.fleet import JobSpec

    return JobSpec(job_id=jid, argv=[sys.executable, "-c", "pass"],
                   max_restarts=0)


FLEET_ORDERS = interleavings([["fleet.pass"], ["fleet.episode.done"],
                              ["fleet.adopt"]])


@pytest.mark.parametrize("order", FLEET_ORDERS,
                         ids=lambda o: "-".join(n.split(".")[-1] for n in o))
def test_fleet_pass_vs_episode_vs_adopt(tmp_path, monkeypatch, order):
    from theanompi_tpu.fleet import JobRecord, read_fleet_events
    from theanompi_tpu.fleet import scheduler as fleet_scheduler
    from theanompi_tpu.fleet.jobs import TERMINAL
    from theanompi_tpu.resilience import EXIT_CLEAN

    monkeypatch.setattr(fleet_scheduler, "run_job", _stub_run_job)
    d = str(tmp_path / "fleet")
    sched = fleet_scheduler.FleetScheduler(d, 2, poll_s=0.01, telemetry=False)
    sched.submit(_job_spec("j1"))
    rec2 = JobRecord(spec=_job_spec("j2"))  # not persisted: adopt() owns it

    box = {}
    runner = threading.Thread(target=lambda: box.update(rc=sched.run()),
                              name="test-fleet-run")
    with Interleaver(list(order), timeout_s=0.5):
        runner.start()
        sched.adopt(rec2)       # main thread races the scheduler loop
        runner.join(timeout=60)
    assert not runner.is_alive()
    assert box["rc"] == EXIT_CLEAN
    if any(r.status not in TERMINAL for r in sched.records.values()):
        # the adopt landed after run() drained; one more run picks it up
        # (the documented dead-scheduler re-own flow), deterministically
        assert sched.run() == EXIT_CLEAN
    # invariants under EVERY interleaving: both jobs done exactly once,
    # all devices back in the pool, the audit log shows both completions
    assert set(sched.records) == {"j1", "j2"}
    assert all(r.status == "done" for r in sched.records.values())
    assert all(r.devices is None for r in sched.records.values())
    assert sched.ledger.free == 2
    completes = [e for e in read_fleet_events(d)
                 if e["event"] == "fleet.complete"]
    assert sorted(e["job"] for e in completes) == ["j1", "j2"]


# -- seam (c): health ticker tick vs. Telemetry.close() ----------------------

HEALTH_ORDERS = interleavings([["health.tick", "health.tick"],
                               ["health.close"]])


@pytest.mark.parametrize("order", HEALTH_ORDERS,
                         ids=lambda o: "-".join(n.split(".")[-1] for n in o))
def test_health_tick_vs_close(tmp_path, order):
    from theanompi_tpu.telemetry import Telemetry
    from theanompi_tpu.telemetry.sink import read_events

    d = str(tmp_path / "tel")
    tel = Telemetry(d, rank=0, health={"tick_s": 0.005})
    with Interleaver(list(order), timeout_s=0.5):
        tel.close()
    # invariants under EVERY interleaving: ticker joined, exactly one
    # session_end, HEALTH.json published whole (atomic replace)
    assert tel._health_thread is None
    events = read_events(os.path.join(d, "events-rank00000.jsonl"))
    ends = [e for e in events
            if e["kind"] == "meta" and e["name"] == "session_end"]
    assert len(ends) == 1
    with open(os.path.join(d, "HEALTH.json")) as f:
        health = json.load(f)
    assert health["rank"] == 0
