"""Model zoo tests: every model initializes and takes one finite BSP step.

The reference validated models by full training curves (SURVEY.md §4 —
convergence-as-test); the fast equivalents here assert init shapes, one
train step with finite loss, and (slow-marked) short-loop learning.
"""

import numpy as np
import pytest

import jax

from theanompi_tpu.parallel.bsp import BSPTrainer
from theanompi_tpu.parallel.mesh import make_mesh
from theanompi_tpu.utils.helper_funcs import tree_count

# (modelfile, modelclass, tiny-config, expected logits trailing dim)
ZOO = [
    ("theanompi_tpu.models.alex_net", "AlexNet",
     {"image_size": 64, "n_classes": 11, "lrn": True}, 11),
    ("theanompi_tpu.models.vggnet_16", "VGGNet_16",
     {"image_size": 32, "n_classes": 7, "fc_width": 64}, 7),
    ("theanompi_tpu.models.vggnet_16", "VGGNet_11_Shallow",
     {"image_size": 32, "n_classes": 7, "fc_width": 64}, 7),
    ("theanompi_tpu.models.resnet50", "ResNet50",
     {"image_size": 32, "n_classes": 9, "stage_blocks": (1, 1, 1, 1)}, 9),
    ("theanompi_tpu.models.googlenet", "GoogLeNet",
     {"image_size": 64, "n_classes": 13, "lrn": True}, 13),
]

COMMON = {"batch_size": 4, "n_train": 32, "n_val": 16, "shard_size": 16,
          "n_epochs": 1, "precision": "fp32"}


def _load(modelfile, modelclass, cfg):
    import importlib

    cls = getattr(importlib.import_module(modelfile), modelclass)
    return cls({**COMMON, **cfg})


@pytest.mark.parametrize("modelfile,modelclass,cfg,n_out", ZOO)
def test_model_one_step(modelfile, modelclass, cfg, n_out):
    model = _load(modelfile, modelclass, cfg)
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    assert tree_count(t.params) > 0
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=0.01)
    assert np.isfinite(float(m["cost"])), f"{modelclass}: non-finite loss"
    v = t.validate(0)
    assert np.isfinite(v["cost"])


def test_resnet50_full_depth_param_count():
    """Real ResNet-50 (3,4,6,3) should land near the canonical 25.6M params."""
    from theanompi_tpu.models.resnet50 import ResNet50

    model = ResNet50({**COMMON, "image_size": 64, "n_classes": 1000})
    params, _ = model.init_params(jax.random.PRNGKey(0))
    n = tree_count(params)
    assert 24e6 < n < 27e6, f"ResNet-50 param count off: {n/1e6:.1f}M"


def test_alexnet_param_count():
    """AlexNet at 224/1000 has ~60-62M params (canonical)."""
    from theanompi_tpu.models.alex_net import AlexNet

    model = AlexNet({**COMMON, "image_size": 224, "n_classes": 1000})
    params, _ = model.init_params(jax.random.PRNGKey(0))
    n = tree_count(params)
    assert 55e6 < n < 65e6, f"AlexNet param count off: {n/1e6:.1f}M"


def test_googlenet_aux_heads():
    """aux=True: two aux heads add params, train loss includes them (weight
    0.3), eval drops them (SURVEY.md §2.1 GoogLeNet row; Szegedy 2014 §5)."""
    from theanompi_tpu.models.googlenet import GoogLeNet

    cfg = {**COMMON, "image_size": 64, "n_classes": 13, "lrn": True}
    plain = GoogLeNet(cfg)
    auxed = GoogLeNet({**cfg, "aux": True})
    p0, _ = plain.init_params(jax.random.PRNGKey(0))
    p1, _ = auxed.init_params(jax.random.PRNGKey(0))
    assert "aux0" in p1 and "aux1" in p1 and "aux0" not in p0
    assert tree_count(p1) > tree_count(p0)

    t = BSPTrainer(auxed, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    before = jax.tree.map(np.array, t.params["aux0"])
    batch = next(iter(auxed.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=0.01)
    assert np.isfinite(float(m["cost"]))
    moved = any(
        not np.allclose(np.asarray(a), b)
        for a, b in zip(jax.tree.leaves(t.params["aux0"]), jax.tree.leaves(before))
    )
    assert moved, "aux head got no gradient"
    # train cost includes the 0.3-weighted aux losses; eval must not
    v = t.validate(0)
    assert np.isfinite(v["cost"])
    train_loss, _ = auxed.loss_fn(t.params, t.state, {
        "x": batch["x"][:4], "y": batch["y"][:4]}, jax.random.PRNGKey(1), True)
    eval_loss, _ = auxed.loss_fn(t.params, t.state, {
        "x": batch["x"][:4], "y": batch["y"][:4]}, None, False)
    # near init all three heads sit at ~ln(13) each, so train > eval strictly
    assert float(train_loss) > float(eval_loss)


def test_googlenet_bn_knob():
    """bn=True (BN-GoogLeNet / Inception-v2 recipe): BN state exists, LRN
    and conv biases are dropped, one BSP step is finite and sync-BN rides
    the data axis on a multi-worker mesh (same knob surface as VGG-11)."""
    from theanompi_tpu.models.googlenet import GoogLeNet
    from theanompi_tpu.parallel.mesh import DATA_AXIS

    cfg = {**COMMON, "image_size": 64, "n_classes": 13, "lrn": True,
           "bn": True, "bn_axis": DATA_AXIS, "batch_size": 2}
    model = GoogLeNet(cfg)
    _, state = model.init_params(jax.random.PRNGKey(0))
    assert state, "bn=True produced no BN state"
    # biases gone from convs (BN owns the shift)
    flat = dict(
        ("/".join(str(getattr(p, "key", p)) for p in path), leaf)
        for path, leaf in
        jax.tree_util.tree_flatten_with_path(model.init_params(
            jax.random.PRNGKey(0))[0])[0]
    )
    conv_biases = [k for k in flat if "conv" in k and k.endswith("/b")]
    assert not conv_biases, f"bn=True kept conv biases: {conv_biases[:3]}"

    mesh = make_mesh(n_data=2, devices=jax.devices()[:2])
    t = BSPTrainer(model, mesh=mesh)
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=0.01)
    assert np.isfinite(float(m["cost"]))


def test_googlenet_aux_full_size_pool_shape():
    """At 224 the aux tap is 14x14 -> the paper's 5x5/3 pool path is used."""
    from theanompi_tpu.models.googlenet import GoogLeNet

    m = GoogLeNet({**COMMON, "image_size": 224, "n_classes": 1000,
                   "aux": True})
    # conv (not dense) first aux layer == the 5x5/3 avgpool branch
    head = m.net.heads[0]
    from theanompi_tpu.ops import layers as L

    assert isinstance(head.layers[0], L.AvgPool)
    assert isinstance(head.layers[1], L.Conv2D)


def test_resnet50_s2d_stem_matches_conv7():
    """The space-to-depth stem is the SAME linear map as the 7x7/2 conv
    (MLPerf trick, kept in the logical [7,7,C,F] param layout): forward
    and gradient must match to fp tolerance."""
    import jax.numpy as jnp

    from theanompi_tpu.models.resnet50 import _SpaceToDepthStem
    from theanompi_tpu.ops import layers as L

    x = jnp.asarray(np.random.RandomState(0).randn(2, 64, 64, 3)
                    .astype(np.float32))
    stem = _SpaceToDepthStem(16)
    params, _, out_shape = stem.init(jax.random.PRNGKey(3), (64, 64, 3))
    ref = L.Conv2D(16, 7, stride=2, padding=3, use_bias=False)
    y_s2d, _ = stem.apply(params, {}, x)
    y_ref, _ = ref.apply({"w": params["w"]}, {}, x)
    assert y_s2d.shape == (2, *out_shape)
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-4)
    g1 = jax.grad(lambda w: jnp.sum(jnp.sin(
        stem.apply({"w": w}, {}, x)[0])))(params["w"])
    g2 = jax.grad(lambda w: jnp.sum(jnp.sin(
        ref.apply({"w": w}, {}, x)[0])))(params["w"])
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)


def test_resnet50_remat_matches_none():
    """remat='save_convs' is a scheduling knob, not a numerics knob: two
    train steps must reproduce the default path's params to float
    round-off.  Not bit-exact: jax < 0.5 CPU reorders reductions when
    replaying rematerialized regions, giving ~1e-6-relative drift on the
    loss — a real semantics bug (wrong policy, dropped residual) would
    diverge orders of magnitude past the tolerance here."""
    cfg = {"image_size": 32, "n_classes": 9, "stage_blocks": (1, 1, 1, 1),
           "batch_size": 4, "n_train": 32, "n_val": 16, "shard_size": 16,
           "n_epochs": 1, "precision": "fp32"}

    def run(remat):
        from theanompi_tpu.models.resnet50 import ResNet50

        model = ResNet50({**cfg, "remat": remat})
        t = BSPTrainer(model,
                       mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
        t.compile_iter_fns()
        t.init_state()
        batches = list(model.data.train_batches(t.global_batch, 0, seed=0))
        for i in range(2):
            m = t.train_iter(batches[i % len(batches)], lr=0.05)
        return t.params, float(m["cost"])

    p0, c0 = run("none")
    p1, c1 = run("save_convs")
    assert c0 == pytest.approx(c1, rel=5e-6)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(p0),
            jax.tree_util.tree_leaves_with_path(p1)):
        # atol dominates for near-zero weights: the reordered reductions
        # drift ~3e-6 absolute after two steps, on weights O(0.1)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=1e-5,
                                   err_msg=str(path))


def test_alexnet_grouped_convs():
    """grouped=True: 2-group conv2/4/5 (Krizhevsky split) — fewer params,
    still trains."""
    from theanompi_tpu.models.alex_net import AlexNet

    cfg = {**COMMON, "image_size": 224, "n_classes": 1000}
    n_plain = tree_count(AlexNet(cfg).init_params(jax.random.PRNGKey(0))[0])
    grouped = AlexNet({**cfg, "grouped": True})
    n_grouped = tree_count(grouped.init_params(jax.random.PRNGKey(0))[0])
    # grouping halves conv2/4/5 weight fan-in: exactly
    # (5*5*96*256 + 3*3*384*384 + 3*3*384*256)/2 = 1,413,120 fewer params
    assert n_plain - n_grouped == 1_413_120, (n_plain, n_grouped)
    assert 55e6 < n_grouped < 62e6

    small = AlexNet({**COMMON, "image_size": 64, "n_classes": 11,
                     "grouped": True})
    t = BSPTrainer(small, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(small.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=0.01)
    assert np.isfinite(float(m["cost"]))


def test_lstm_one_step_and_perplexity():
    from theanompi_tpu.models.lstm import LSTM

    model = LSTM({"batch_size": 8, "n_train": 64, "n_val": 32, "seq_len": 12,
                  "vocab": 50, "hidden": 32, "embed_dim": 32, "n_layers": 2,
                  "n_epochs": 1, "precision": "fp32", "dropout": 0.1})
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=0.5)
    assert np.isfinite(float(m["cost"]))
    # perplexity metric present and consistent with cost
    np.testing.assert_allclose(
        float(m["perplexity"]), np.exp(float(m["cost"])), rtol=1e-3
    )


@pytest.mark.slow
def test_lstm_learns_bigram_structure():
    from theanompi_tpu.models.lstm import LSTM
    from theanompi_tpu.parallel.trainer import BaseTrainer  # noqa: F401

    model = LSTM({"batch_size": 16, "n_train": 256, "n_val": 64, "seq_len": 16,
                  "vocab": 32, "hidden": 64, "embed_dim": 32, "n_layers": 1,
                  "n_epochs": 4, "precision": "fp32", "dropout": 0.0,
                  "lr": 0.5, "momentum": 0.9})
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    rec = t.run()
    ppl = rec.val_history["perplexity"]
    assert ppl[-1] < 32, f"perplexity should beat uniform(32): {ppl}"
    assert ppl[-1] < ppl[0]


def test_dcgan_one_step():
    from theanompi_tpu.models.dcgan import DCGAN

    model = DCGAN({"batch_size": 8, "n_train": 64, "n_val": 32,
                   "image_size": 32, "gen_base": 32, "disc_base": 16,
                   "z_dim": 16, "n_epochs": 1, "precision": "fp32"})
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    before = jax.tree.map(np.array, t.params)
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=2e-4)
    for k in ("cost", "d_loss", "g_loss"):
        assert np.isfinite(float(m[k])), f"{k} not finite"
    # both nets' params actually moved
    for net in ("gen", "disc"):
        moved = any(
            not np.allclose(np.asarray(a), b)
            for a, b in zip(
                jax.tree.leaves(t.params[net]), jax.tree.leaves(before[net])
            )
        )
        assert moved, f"{net} params did not move"


def test_dcgan_bsp_multiworker(mesh8):
    from theanompi_tpu.models.dcgan import DCGAN

    model = DCGAN({"batch_size": 2, "n_train": 64, "n_val": 32,
                   "image_size": 32, "gen_base": 32, "disc_base": 16,
                   "z_dim": 16, "n_epochs": 1, "precision": "fp32"})
    t = BSPTrainer(model, mesh=mesh8)
    t.compile_iter_fns()
    t.init_state()
    batch = next(iter(model.data.train_batches(t.global_batch, 0, seed=0)))
    m = t.train_iter(batch, lr=2e-4)
    assert np.isfinite(float(m["cost"]))


def test_wgan_critic_clipped():
    from theanompi_tpu.models.dcgan import WGAN

    model = WGAN({"batch_size": 8, "n_train": 64, "n_val": 32,
                  "image_size": 32, "gen_base": 32, "disc_base": 16,
                  "z_dim": 16, "n_epochs": 1, "precision": "fp32",
                  "clip": 0.01, "n_critic": 2})
    t = BSPTrainer(model, mesh=make_mesh(n_data=1, devices=jax.devices()[:1]))
    t.compile_iter_fns()
    t.init_state()
    for i, batch in enumerate(model.data.train_batches(t.global_batch, 0, seed=0)):
        t.train_iter(batch, lr=5e-5)
        if i >= 2:
            break
    for leaf in jax.tree.leaves(t.params["disc"]):
        a = np.asarray(leaf)
        assert (np.abs(a) <= 0.01 + 1e-6).all(), "critic weights not clipped"
