"""Optimizer and loss unit tests against hand-computed values."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.ops import SGD, Adam, RMSProp
from theanompi_tpu.ops.losses import (
    sigmoid_binary_cross_entropy,
    softmax_cross_entropy,
    top_k_error,
)


def test_sgd_vanilla_matches_formula():
    opt = SGD()
    params = {"w": jnp.array([1.0, 2.0])}
    grads = {"w": jnp.array([0.5, -1.0])}
    st = opt.init(params)
    new, st = opt.update(grads, st, params, lr=0.1)
    np.testing.assert_allclose(np.asarray(new["w"]), [0.95, 2.1], rtol=1e-6)


def test_sgd_momentum_two_steps():
    opt = SGD(momentum=0.9)
    p = {"w": jnp.zeros(1)}
    g = {"w": jnp.ones(1)}
    st = opt.init(p)
    p, st = opt.update(g, st, p, lr=1.0)  # v=-1, p=-1
    np.testing.assert_allclose(np.asarray(p["w"]), [-1.0])
    p, st = opt.update(g, st, p, lr=1.0)  # v=-1.9, p=-2.9
    np.testing.assert_allclose(np.asarray(p["w"]), [-2.9], rtol=1e-6)


def test_sgd_nesterov_differs_from_classic():
    g = {"w": jnp.ones(1)}
    p0 = {"w": jnp.zeros(1)}
    classic = SGD(momentum=0.9)
    nest = SGD(momentum=0.9, nesterov=True)
    pc, _ = classic.update(g, classic.init(p0), p0, lr=1.0)
    pn, _ = nest.update(g, nest.init(p0), p0, lr=1.0)
    np.testing.assert_allclose(np.asarray(pn["w"]), [-1.9], rtol=1e-6)
    assert not np.allclose(np.asarray(pc["w"]), np.asarray(pn["w"]))


def test_weight_decay_shrinks_params():
    opt = SGD(weight_decay=0.1)
    p = {"w": jnp.array([10.0])}
    g = {"w": jnp.array([0.0])}
    new, _ = opt.update(g, opt.init(p), p, lr=0.5)
    np.testing.assert_allclose(np.asarray(new["w"]), [9.5])  # 10 - 0.5*0.1*10


@pytest.mark.parametrize("opt", [SGD(momentum=0.9), Adam(), RMSProp()])
def test_optimizers_descend_quadratic(opt):
    def loss(p):
        return jnp.sum((p["w"] - 3.0) ** 2)

    p = {"w": jnp.zeros(4)}
    st = opt.init(p)
    lr = 0.1 if not isinstance(opt, Adam) else 0.3
    for _ in range(60):
        g = jax.grad(loss)(p)
        p, st = opt.update(g, st, p, lr)
    assert float(loss(p)) < 0.05


def test_softmax_cross_entropy_matches_manual():
    logits = jnp.array([[2.0, 1.0, 0.0], [0.0, 0.0, 0.0]])
    labels = jnp.array([0, 2])
    got = float(softmax_cross_entropy(logits, labels))
    p0 = np.exp(2.0) / (np.exp(2.0) + np.exp(1.0) + 1.0)
    expect = (-np.log(p0) - np.log(1 / 3)) / 2
    np.testing.assert_allclose(got, expect, rtol=1e-6)
    # bf16 logits still give fp32-precision loss
    got16 = float(softmax_cross_entropy(logits.astype(jnp.bfloat16), labels))
    np.testing.assert_allclose(got16, expect, rtol=1e-2)


def test_bce_matches_manual():
    logits = jnp.array([0.0, 100.0, -100.0])
    targets = jnp.array([0.5, 1.0, 0.0])
    got = float(sigmoid_binary_cross_entropy(logits, targets))
    np.testing.assert_allclose(got, np.log(2.0) / 3, rtol=1e-5)


def test_top_k_error():
    logits = jnp.array([[3.0, 2.0, 1.0], [3.0, 2.0, 1.0]])
    labels = jnp.array([0, 2])
    assert float(top_k_error(logits, labels, k=1)) == 0.5
    assert float(top_k_error(logits, labels, k=3)) == 0.0


# -- fused LM-head cross entropy ----------------------------------------------


def _naive_lm_loss(h, w, b, y):
    logits = (h @ w.astype(h.dtype) + b.astype(h.dtype)).astype(jnp.float32)
    return softmax_cross_entropy(logits, y)


# (vocab, chunk, t): chunk=8 over t=16 -> 4 genuine chunks; t=13 -> n=26
# pads to 32 and masks; chunk=None/1024 -> single chunk (both regimes of
# the scan carry are exercised: cross-chunk dw/db/lse accumulation AND the
# degenerate one-chunk path)
@pytest.mark.parametrize("vocab,chunk,t", [(37, 8, 16), (37, 8, 13),
                                           (64, None, 16), (64, 1024, 16)])
def test_fused_lm_xent_matches_naive_fp32(vocab, chunk, t):
    """Loss, metrics, and ALL grads (h, w, b) must match the naive
    [N, V]-materializing path — fwd+bwd equivalence (VERDICT r2 #3)."""
    from theanompi_tpu.ops.losses import fused_lm_xent

    r = np.random.RandomState(0)
    bsz, d = 2, 12
    h = jnp.asarray(r.randn(bsz, t, d).astype(np.float32))
    w = jnp.asarray(r.randn(d, vocab).astype(np.float32) * 0.2)
    b = jnp.asarray(r.randn(vocab).astype(np.float32) * 0.1)
    y = jnp.asarray(r.randint(0, vocab, size=(bsz, t)))

    def fused(h, w, b):
        return fused_lm_xent(h, w, b, y, chunk_tokens=chunk)[0]

    def naive(h, w, b):
        return _naive_lm_loss(h, w, b, y)

    lf, gf = jax.value_and_grad(fused, argnums=(0, 1, 2))(h, w, b)
    ln, gn = jax.value_and_grad(naive, argnums=(0, 1, 2))(h, w, b)
    np.testing.assert_allclose(float(lf), float(ln), rtol=1e-5)
    for a, bb, name in zip(gf, gn, ("dh", "dw", "db")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-6, err_msg=name)

    # error metrics ride the same pass and must equal top_k_error
    logits = h @ w + b
    _, e1, e5 = fused_lm_xent(h, w, b, y, chunk_tokens=chunk)
    np.testing.assert_allclose(float(e1), float(top_k_error(logits, y, k=1)),
                               rtol=1e-6)
    np.testing.assert_allclose(float(e5), float(top_k_error(logits, y, k=5)),
                               rtol=1e-6)


def test_fused_lm_xent_bf16_close_to_naive():
    """bf16 inputs: the fused path accumulates scores in fp32 on the MXU, so
    it may only be MORE accurate than the naive bf16-logit path; assert
    agreement at bf16 tolerance."""
    from theanompi_tpu.ops.losses import fused_lm_xent

    r = np.random.RandomState(1)
    h = jnp.asarray(r.randn(2, 8, 16).astype(np.float32)).astype(jnp.bfloat16)
    w = jnp.asarray((r.randn(16, 96) * 0.2).astype(np.float32)).astype(jnp.bfloat16)
    b = jnp.zeros((96,), jnp.bfloat16)
    y = jnp.asarray(r.randint(0, 96, size=(2, 8)))
    lf = float(fused_lm_xent(h, w, b, y)[0])
    ln = float(_naive_lm_loss(h, w, b, y))
    assert abs(lf - ln) / max(abs(ln), 1e-6) < 2e-2


def test_fused_lm_xent_no_bias():
    from theanompi_tpu.ops.losses import fused_lm_xent

    r = np.random.RandomState(2)
    h = jnp.asarray(r.randn(1, 8, 8).astype(np.float32))
    w = jnp.asarray(r.randn(8, 32).astype(np.float32))
    y = jnp.asarray(r.randint(0, 32, size=(1, 8)))
    lf = float(fused_lm_xent(h, w, None, y)[0])
    ln = float(_naive_lm_loss(h, w, jnp.zeros((32,)), y))
    np.testing.assert_allclose(lf, ln, rtol=1e-5)


@pytest.mark.parametrize("unroll", [1, 2])
def test_fused_lm_xent_vocab_parallel_matches_unsharded(unroll):
    """Megatron parallel CE: the vocab-sharded fused loss (head
    P(None, model)) must reproduce the unsharded fused loss — value,
    metrics, and all grads, including the psum-pinned h-cotangent.
    ``unroll=2`` proves the r5 scan-unroll knob composes with the
    collective-assembled softmax (the reference here stays rolled, so
    this is a cross-unroll equality, stronger than same-vs-same)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from theanompi_tpu.ops.losses import fused_lm_xent, fused_lm_xent_vp
    from theanompi_tpu.parallel.mesh import MODEL_AXIS, make_mesh, shard_map

    r = np.random.RandomState(0)
    bsz, t, d, v = 2, 10, 12, 64  # n=20 tokens: pads inside an 8-chunk
    h = jnp.asarray(r.randn(bsz, t, d).astype(np.float32))
    w = jnp.asarray(r.randn(d, v).astype(np.float32) * 0.2)
    b = jnp.asarray(r.randn(v).astype(np.float32) * 0.1)
    y = jnp.asarray(r.randint(0, v, size=(bsz, t)))

    def ref(h, w, b):
        loss, e1, e5 = fused_lm_xent(h, w, b, y, chunk_tokens=8)
        return loss, (e1, e5)

    (lr_, (e1r, e5r)), gr = jax.value_and_grad(ref, argnums=(0, 1, 2),
                                               has_aux=True)(h, w, b)

    mesh = make_mesh(n_data=1, n_model=4)

    def vp(h, w, b):
        loss, e1, e5 = fused_lm_xent_vp(h, w, b, y, MODEL_AXIS,
                                        chunk_tokens=8, unroll=unroll)
        return loss, (e1, e5)

    f = jax.jit(shard_map(
        jax.value_and_grad(vp, argnums=(0, 1, 2), has_aux=True), mesh,
        in_specs=(P(), P(None, MODEL_AXIS), P(MODEL_AXIS)),
        out_specs=((P(), (P(), P())), (P(), P(None, MODEL_AXIS), P(MODEL_AXIS))),
    ))
    hw = jax.device_put(w, NamedSharding(mesh, P(None, MODEL_AXIS)))
    hb = jax.device_put(b, NamedSharding(mesh, P(MODEL_AXIS)))
    (lv, (e1v, e5v)), gv = f(h, hw, hb)

    np.testing.assert_allclose(float(lv), float(lr_), rtol=1e-5)
    np.testing.assert_allclose(float(e1v), float(e1r), rtol=1e-6)
    np.testing.assert_allclose(float(e5v), float(e5r), rtol=1e-6)
    for a, bb, name in zip(gv, gr, ("dh", "dw", "db")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-6, err_msg=name)


def test_fused_lm_xent_unroll_exact_match():
    """unroll>1 is a scheduling hint, not a numerics change: loss, metrics,
    and all grads must be bit-comparable to the unroll=1 scan (r5 knob for
    the while-self-time share in ROOFLINE_transformer_32k.json).  Also
    covers the non-divisible case (4 chunks, unroll=3)."""
    from theanompi_tpu.ops.losses import fused_lm_xent

    r = np.random.RandomState(1)
    bsz, t, d, vocab = 2, 16, 12, 37
    h = jnp.asarray(r.randn(bsz, t, d).astype(np.float32))
    w = jnp.asarray(r.randn(d, vocab).astype(np.float32) * 0.2)
    b = jnp.asarray(r.randn(vocab).astype(np.float32) * 0.1)
    y = jnp.asarray(r.randint(0, vocab, size=(bsz, t)))

    def run(unroll):
        def f(h, w, b):
            out = fused_lm_xent(h, w, b, y, chunk_tokens=8, unroll=unroll)
            return out[0], (out[1], out[2])

        (loss, errs), grads = jax.value_and_grad(
            f, argnums=(0, 1, 2), has_aux=True)(h, w, b)
        return loss, errs, grads

    l1, e1, g1 = run(1)
    for u in (3, 4):
        lu, eu, gu = run(u)
        np.testing.assert_allclose(float(lu), float(l1), rtol=1e-6)
        for a, bb in zip(eu, e1):
            np.testing.assert_allclose(float(a), float(bb), rtol=1e-6)
        for a, bb, name in zip(gu, g1, ("dh", "dw", "db")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=1e-5, atol=1e-7, err_msg=name)
