"""Optimizer and loss unit tests against hand-computed values."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.ops import SGD, Adam, RMSProp
from theanompi_tpu.ops.losses import (
    sigmoid_binary_cross_entropy,
    softmax_cross_entropy,
    top_k_error,
)


def test_sgd_vanilla_matches_formula():
    opt = SGD()
    params = {"w": jnp.array([1.0, 2.0])}
    grads = {"w": jnp.array([0.5, -1.0])}
    st = opt.init(params)
    new, st = opt.update(grads, st, params, lr=0.1)
    np.testing.assert_allclose(np.asarray(new["w"]), [0.95, 2.1], rtol=1e-6)


def test_sgd_momentum_two_steps():
    opt = SGD(momentum=0.9)
    p = {"w": jnp.zeros(1)}
    g = {"w": jnp.ones(1)}
    st = opt.init(p)
    p, st = opt.update(g, st, p, lr=1.0)  # v=-1, p=-1
    np.testing.assert_allclose(np.asarray(p["w"]), [-1.0])
    p, st = opt.update(g, st, p, lr=1.0)  # v=-1.9, p=-2.9
    np.testing.assert_allclose(np.asarray(p["w"]), [-2.9], rtol=1e-6)


def test_sgd_nesterov_differs_from_classic():
    g = {"w": jnp.ones(1)}
    p0 = {"w": jnp.zeros(1)}
    classic = SGD(momentum=0.9)
    nest = SGD(momentum=0.9, nesterov=True)
    pc, _ = classic.update(g, classic.init(p0), p0, lr=1.0)
    pn, _ = nest.update(g, nest.init(p0), p0, lr=1.0)
    np.testing.assert_allclose(np.asarray(pn["w"]), [-1.9], rtol=1e-6)
    assert not np.allclose(np.asarray(pc["w"]), np.asarray(pn["w"]))


def test_weight_decay_shrinks_params():
    opt = SGD(weight_decay=0.1)
    p = {"w": jnp.array([10.0])}
    g = {"w": jnp.array([0.0])}
    new, _ = opt.update(g, opt.init(p), p, lr=0.5)
    np.testing.assert_allclose(np.asarray(new["w"]), [9.5])  # 10 - 0.5*0.1*10


@pytest.mark.parametrize("opt", [SGD(momentum=0.9), Adam(), RMSProp()])
def test_optimizers_descend_quadratic(opt):
    def loss(p):
        return jnp.sum((p["w"] - 3.0) ** 2)

    p = {"w": jnp.zeros(4)}
    st = opt.init(p)
    lr = 0.1 if not isinstance(opt, Adam) else 0.3
    for _ in range(60):
        g = jax.grad(loss)(p)
        p, st = opt.update(g, st, p, lr)
    assert float(loss(p)) < 0.05


def test_softmax_cross_entropy_matches_manual():
    logits = jnp.array([[2.0, 1.0, 0.0], [0.0, 0.0, 0.0]])
    labels = jnp.array([0, 2])
    got = float(softmax_cross_entropy(logits, labels))
    p0 = np.exp(2.0) / (np.exp(2.0) + np.exp(1.0) + 1.0)
    expect = (-np.log(p0) - np.log(1 / 3)) / 2
    np.testing.assert_allclose(got, expect, rtol=1e-6)
    # bf16 logits still give fp32-precision loss
    got16 = float(softmax_cross_entropy(logits.astype(jnp.bfloat16), labels))
    np.testing.assert_allclose(got16, expect, rtol=1e-2)


def test_bce_matches_manual():
    logits = jnp.array([0.0, 100.0, -100.0])
    targets = jnp.array([0.5, 1.0, 0.0])
    got = float(sigmoid_binary_cross_entropy(logits, targets))
    np.testing.assert_allclose(got, np.log(2.0) / 3, rtol=1e-5)


def test_top_k_error():
    logits = jnp.array([[3.0, 2.0, 1.0], [3.0, 2.0, 1.0]])
    labels = jnp.array([0, 2])
    assert float(top_k_error(logits, labels, k=1)) == 0.5
    assert float(top_k_error(logits, labels, k=3)) == 0.0
