"""Async rules under the full resilience stack (ISSUE 20): the stacked
reshard planner, the rule-typed fingerprint contract, the async_staleness
health detector, the straggler/gossip-drop fault sites, wire-byte
exactness against the ISSUE 2 per-dtype contract, and the resume matrix
(verified-chain round-trip, cadence mid-epoch crash, elastic mesh8->4).

Planner and detector units run on handcrafted manifests / synthetic
events (milliseconds); the training matrix reuses the tiny wide_resnet
config every resilience e2e shares so subprocess children hit one
compile-cache entry.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu import EASGD, GOSGD
from theanompi_tpu.resilience import FaultInjected, FaultPlan
from theanompi_tpu.resilience.faults import SITES, FaultPlanError
from theanompi_tpu.telemetry.health import (
    SEV_CRITICAL,
    SEV_OK,
    SEV_WARN,
    HealthConfig,
    HealthMonitor,
)
from theanompi_tpu.telemetry.metrics import (
    ASYNC_GAUGES,
    ASYNC_INSTANTS,
    EXCHANGE_COUNTS,
)
from theanompi_tpu.utils import checkpoint as ck_mod
from theanompi_tpu.utils.checkpoint import (
    Checkpointer,
    CheckpointReshardableMismatch,
    CheckpointReshardError,
    build_manifest,
    plan_reshard,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_CFG = {"depth": 10, "widen": 1, "batch_size": 4, "image_size": 8,
            "n_train": 32, "n_val": 16, "n_epochs": 2, "precision": "fp32"}
TINY_ARGS = ["--set", "depth=10", "--set", "widen=1", "--set", "batch_size=4",
             "--set", "image_size=8", "--set", "n_train=32",
             "--set", "n_val=16", "--set", "precision='fp32'"]


# -- planner units (handcrafted manifests, no training) ----------------------

def _fp(n, exchange, **over):
    fp = {"mesh": {"data": n, "pipe": 1, "model": 1, "seq": 1},
          "exchange": exchange, "n_subb": 1,
          "model": "WideResNet", "model_config_sha": "abc123"}
    fp.update(over)
    return fp


def _easgd_fp(n, **over):
    return _fp(n, "EASGDTrainer", rule="easgd", tau=2, alpha="auto", **over)


def _gosgd_fp(n, **over):
    return _fp(n, "GOSGDTrainer", rule="gosgd", p_push="auto", **over)


def _stacked_flat(n):
    """Stacked per-worker trees with recognizable per-replica payloads."""
    return {
        "params::conv/w": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
        "state::bn/mean": np.arange(n * 2, dtype=np.float32).reshape(n, 2),
        "opt_state::velocity/conv/w":
            np.arange(n * 3, dtype=np.float32).reshape(n, 3) * 10.0,
    }


def _easgd_manifest(n=8, **fpover):
    flat = dict(_stacked_flat(n))
    flat["center::conv/w"] = np.array([7.0, 8.0, 9.0], np.float32)
    return build_manifest(1, 4, flat, _easgd_fp(n, **fpover)), flat


def _gosgd_manifest(n=8, weights=None, **fpover):
    flat = dict(_stacked_flat(n))
    flat["weights::"] = (np.full((n,), 1.0 / n, np.float32)
                         if weights is None else np.asarray(weights))
    return build_manifest(1, 4, flat, _gosgd_fp(n, **fpover)), flat


def test_plan_easgd_shrink_keeps_first_center_passthrough():
    man, flat = _easgd_manifest(8)
    plan = plan_reshard(man, _easgd_fp(4))
    assert (plan.old_n, plan.new_n) == (8, 4)
    assert plan.stacked == "easgd" and plan.buckets is None
    assert plan.lr_scale == pytest.approx(1.0)  # carried, NOT rescaled
    assert plan.summary()["stacked"] == "easgd"
    assert "keep the first 4" in plan.describe()
    out = plan.transform_arrays(flat)
    for key in ("params::conv/w", "state::bn/mean",
                "opt_state::velocity/conv/w"):
        np.testing.assert_array_equal(out[key], flat[key][:4], err_msg=key)
    # the center is replicated and n-independent: restored untouched
    np.testing.assert_array_equal(out["center::conv/w"],
                                  flat["center::conv/w"])
    assert any("LR carried unrescaled" in w for w in plan.warnings)


def test_plan_easgd_grow_clones_cyclically():
    man, flat = _easgd_manifest(4)
    plan = plan_reshard(man, _easgd_fp(8))
    out = plan.transform_arrays(flat)
    idx = np.arange(8) % 4
    np.testing.assert_array_equal(out["params::conv/w"],
                                  flat["params::conv/w"][idx])
    np.testing.assert_array_equal(out["opt_state::velocity/conv/w"],
                                  flat["opt_state::velocity/conv/w"][idx])
    assert any("cloned" in w for w in plan.warnings)


def test_plan_gosgd_weights_renormalized():
    w8 = np.array([.30, .20, .10, .10, .10, .10, .05, .05], np.float32)
    man, flat = _gosgd_manifest(8, weights=w8)
    plan = plan_reshard(man, _gosgd_fp(4))
    assert plan.stacked == "gosgd"
    out = plan.transform_arrays(flat)
    got = out["weights::"]
    assert got.shape == (4,) and got.dtype == np.float32
    np.testing.assert_allclose(got, w8[:4] / w8[:4].sum(), rtol=1e-6)
    assert got.sum() == pytest.approx(1.0, abs=1e-6)  # conservation
    # grow direction: cyclic index map then renormalize
    man4, flat4 = _gosgd_manifest(4, weights=np.array([.4, .3, .2, .1],
                                                      np.float32))
    up = plan_reshard(man4, _gosgd_fp(6))
    w = up.transform_arrays(flat4)["weights::"]
    ref = np.array([.4, .3, .2, .1, .4, .3])
    np.testing.assert_allclose(w, ref / ref.sum(), rtol=1e-6)


def test_plan_async_same_n_is_identity():
    man, flat = _easgd_manifest(8)
    plan = plan_reshard(man, _easgd_fp(8))
    assert plan.stacked == "easgd"
    assert plan.transform_arrays(flat) is flat  # identity, no copy


def test_plan_async_lr_scale_carries_composed_factor():
    """A lineage that picked up x0.5 as BSP before converting would carry
    it; the async plan composes the carried factor and never applies the
    linear-scaling rule on top."""
    flat = dict(_stacked_flat(8))
    flat["center::conv/w"] = np.zeros((3,), np.float32)
    man = build_manifest(1, 4, flat, _easgd_fp(8), lr_scale=0.5)
    plan = plan_reshard(man, _easgd_fp(4))
    assert plan.lr_scale == pytest.approx(0.5)


def test_plan_async_refusals():
    # rule tag promises "center" but the checkpoint doesn't carry it
    man = build_manifest(1, 4, _stacked_flat(8), _easgd_fp(8))
    with pytest.raises(CheckpointReshardError, match="promises the extra"):
        plan_reshard(man, _easgd_fp(4))
    # extras without a recognized rule tag stay a refusal (unknown layout)
    flat = dict(_stacked_flat(8))
    flat["center::conv/w"] = np.zeros((3,), np.float32)
    man = build_manifest(1, 4, flat, _fp(8, "EASGDTrainer"))
    with pytest.raises(CheckpointReshardError, match="no recognized"):
        plan_reshard(man, _fp(4, "EASGDTrainer"))
    # stacked re-layout is rule-specific: no cross-trainer-class reshard
    man, _ = _easgd_manifest(8)
    with pytest.raises(CheckpointReshardError, match="one trainer class"):
        plan_reshard(man, _fp(4, "LocalSGDTrainer",
                              rule="easgd", tau=2, alpha="auto"))


def test_plan_async_transform_refuses_unstacked_leaves():
    man, flat = _easgd_manifest(8)
    plan = plan_reshard(man, _easgd_fp(4))
    bad = dict(flat)
    bad["params::conv/w"] = np.zeros((3,), np.float32)  # no worker axis
    with pytest.raises(CheckpointReshardError, match="layout tag"):
        plan.transform_arrays(bad)
    man, flat = _gosgd_manifest(8)
    plan = plan_reshard(man, _gosgd_fp(4))
    bad = dict(flat)
    bad["weights::"] = np.full((3,), 1 / 3, np.float32)
    with pytest.raises(CheckpointReshardError, match="consensus weights"):
        plan.transform_arrays(bad)


# -- fault grammar -----------------------------------------------------------

def test_async_fault_sites_parse_and_fire_once():
    assert SITES["easgd"] == ("worker_slow",)
    assert SITES["gosgd"] == ("gossip_drop",)
    plan = FaultPlan.parse("easgd:worker_slow@2, gosgd:gossip_drop@0")
    assert plan.fire("easgd", 1, "worker_slow") is None  # wrong ordinal
    assert plan.fire("gosgd", 2, "worker_slow") is None  # wrong action
    assert plan.fire("easgd", 2, "worker_slow") == "worker_slow"
    assert plan.fire("easgd", 2, "worker_slow") is None  # one-shot
    assert plan.fire("gosgd", 0, "gossip_drop") == "gossip_drop"
    with pytest.raises(FaultPlanError, match="valid"):
        FaultPlan.parse("easgd:kill@1")
    with pytest.raises(FaultPlanError, match="valid"):
        FaultPlan.parse("gosgd:worker_slow@1")


def test_async_telemetry_names_registered():
    """The emitting modules bind these spellings by index — a drift here
    is a tmlint finding AND a silently dead health detector."""
    assert ASYNC_INSTANTS == ("easgd.exchange", "gosgd.round")
    assert ASYNC_GAUGES == ("easgd.staleness", "easgd.center_drift",
                            "gosgd.staleness_max", "gosgd.staleness_mean")
    assert EXCHANGE_COUNTS == ("exchange.wire_bytes",)


# -- async_staleness detector units (synthetic events) -----------------------

def _mon(tmp_path, **cfg):
    return HealthMonitor(str(tmp_path), HealthConfig(**cfg),
                         clock=lambda: 0.0)


def _round(mon, name="easgd.exchange", step=0, **fields):
    mon.observe({"kind": "instant", "name": name, "step": step, **fields},
                now=0.0)


def _verdict(mon, detector="async_staleness"):
    for v in mon.verdicts():
        if v["detector"] == detector:
            return v
    return None


def test_async_staleness_warn_needs_sustained_rounds(tmp_path):
    mon = _mon(tmp_path)
    _round(mon, step=4, staleness=4, expected=4, stretch=1.0)
    assert _verdict(mon)["severity"] == SEV_OK
    # one bad round is noise, not a verdict flip
    _round(mon, step=16, staleness=12, expected=4, stretch=1.0)
    assert _verdict(mon)["severity"] == SEV_OK
    assert _verdict(mon)["fields"]["bad_rounds"] == 1
    _round(mon, step=28, staleness=12, expected=4, stretch=1.0)
    v = _verdict(mon)
    assert v["severity"] == SEV_WARN
    assert "straggler being absorbed" in v["reason"]
    assert v["fields"]["bad_rounds"] == 2
    # a healthy round resets the streak
    _round(mon, step=32, staleness=4, expected=4, stretch=1.0)
    v = _verdict(mon)
    assert v["severity"] == SEV_OK and v["fields"]["bad_rounds"] == 0


def test_async_stretch_alone_warns(tmp_path):
    mon = _mon(tmp_path)
    for step in (4, 8):
        _round(mon, step=step, staleness=4, expected=4, stretch=3.0)
    v = _verdict(mon)
    assert v["severity"] == SEV_WARN and "stretched" in v["reason"]


def test_async_drift_critical_is_immediate(tmp_path):
    mon = _mon(tmp_path)
    _round(mon, step=4, staleness=4, expected=4, stretch=1.0, drift=6.0)
    v = _verdict(mon)
    assert v["severity"] == SEV_CRITICAL
    assert v["fields"]["critical_at"] == pytest.approx(5.0)
    # sub-threshold drift rides along as a field, not a verdict
    mon2 = _mon(tmp_path)
    _round(mon2, step=4, staleness=4, expected=4, stretch=1.0, drift=0.02)
    v2 = _verdict(mon2)
    assert v2["severity"] == SEV_OK
    assert v2["fields"]["drift"] == pytest.approx(0.02)


def test_gosgd_round_feeds_same_detector(tmp_path):
    mon = _mon(tmp_path, async_min_rounds=1)
    _round(mon, name="gosgd.round", step=9, staleness=20, expected=4.0)
    v = _verdict(mon)
    assert v["severity"] == SEV_WARN and "gosgd.round" in v["reason"]


# -- wire-byte exactness (ISSUE 2 per-dtype contract audit) ------------------

def _easgd(devices, n_epochs, ck=None, **cfg):
    rule = EASGD(config={"verbose": False, "scale_lr": False, "tau": 2,
                         **({"checkpoint_dir": ck} if ck else {}), **cfg})
    rule.init(devices=devices, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY_CFG, "n_epochs": n_epochs})
    return rule


def _gosgd(devices, n_epochs, ck=None, **cfg):
    rule = GOSGD(config={"verbose": False,
                         **({"checkpoint_dir": ck} if ck else {}), **cfg})
    rule.init(devices=devices, modelfile="theanompi_tpu.models.wide_resnet",
              modelclass="WideResNet",
              model_config={**TINY_CFG, "n_epochs": n_epochs})
    return rule


def test_easgd_wire_bytes_match_dtype_contract():
    """The elastic psum ships ``p - c`` in each leaf's OWN dtype: the
    static accounting must equal ring traffic over the float center
    leaves at their verbatim itemsize — recomputed here from first
    principles, not via the audited helpers."""
    from theanompi_tpu.parallel.exchanger import wire_itemsize

    assert wire_itemsize("elastic", jnp.float32) == 4
    assert wire_itemsize("elastic", jnp.bfloat16) == 2  # verbatim, no cast
    rule = _easgd(4, 1)
    t = rule.trainer
    total = sum(int(leaf.size) * np.dtype(leaf.dtype).itemsize
                for leaf in jax.tree.leaves(t.center)
                if jnp.issubdtype(leaf.dtype, jnp.inexact))
    assert total > 0
    assert t._periodic_wire_bytes() == int(2 * (4 - 1) * total // 4)


def test_gosgd_hop_bytes_are_fp32_wire():
    """gossip_merge casts every outgoing leaf to fp32 on the wire, so a
    hop moves 4 bytes per float element of ONE worker's tree plus the
    4-byte consensus-weight scalar — independently recomputed."""
    rule = _gosgd(4, 1)
    t = rule.trainer
    elems = sum(int(leaf.size) // 4 for leaf in jax.tree.leaves(t.params)
                if jnp.issubdtype(leaf.dtype, jnp.inexact))
    assert elems > 0
    assert t._gossip_hop_bytes() == 4 * (elems + 1)


# -- resume matrix (in-process, tiny wide_resnet) ----------------------------

def _assert_ckpt_equal(path_a, path_b):
    with np.load(path_a) as a, np.load(path_b) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.faultinject
def test_easgd_crash_resume_bit_equal(tmp_path):
    """EASGD through the PR 5 verified chain: a crash one step into
    epoch 1 resumes from the epoch-0 boundary save — params, center and
    opt state round-trip bit-exactly, and the finished lineage matches an
    uninterrupted run; the manifest carries the rule-typed fingerprint."""
    clean_ck = str(tmp_path / "ck_clean")
    _easgd(4, 2, clean_ck).wait()

    ck = str(tmp_path / "ck")
    rule = _easgd(4, 2, ck, fault_plan="step:raise@3")
    with pytest.raises(FaultInjected):
        rule.wait()
    assert rule.trainer.try_resume()
    assert rule.trainer.epoch == 1
    rule.wait()
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))
    with np.load(os.path.join(ck, "ckpt_e0001.npz")) as z:
        assert any(k.startswith("center::") for k in z.files)
    fp = json.load(open(os.path.join(
        ck, "ckpt_e0001.manifest.json")))["fingerprint"]
    assert fp["rule"] == "easgd" and fp["tau"] == 2
    assert fp["alpha"] == "auto" and fp["exchange"] == "EASGDTrainer"


@pytest.mark.faultinject
def test_easgd_cadence_midepoch_crash_resume_bit_equal(tmp_path):
    """Cadence saves + a crash INSIDE epoch 1: resume re-enters the epoch
    at the data cursor (not its start) and still finishes bit-equal to
    the uninterrupted run — the (center, weights, cursor) contract holds
    mid-epoch, not just at boundaries."""
    clean_ck = str(tmp_path / "ck_clean")
    _easgd(4, 2, clean_ck).wait()

    ck = str(tmp_path / "ck")
    rule = _easgd(4, 2, ck, fault_plan="step:raise@3",
                  checkpoint_every_n_iters=1, checkpoint_async=False)
    with pytest.raises(FaultInjected):
        rule.wait()  # 2 steps/epoch: dies ONE step into epoch 1, whose
        # cadence save (iteration 3, completed=False) is the latest
    assert rule.trainer.try_resume()
    assert rule.trainer.epoch == 1  # re-entered, not restarted
    assert rule.trainer.iteration == 3  # the cadence save's mid-epoch cursor
    rule.wait()
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))


@pytest.mark.faultinject
def test_gosgd_crash_resume_bit_equal(tmp_path):
    """GOSGD resume replays the gossip draws it would have made (stateless
    (seed, iteration) derivation): the resumed lineage is bit-equal with
    NO extra RNG state in the checkpoint; consensus mass stays 1."""
    cfg = {"p_push": 0.9}
    clean_ck = str(tmp_path / "ck_clean")
    _gosgd(4, 2, clean_ck, **cfg).wait()

    ck = str(tmp_path / "ck")
    rule = _gosgd(4, 2, ck, fault_plan="step:raise@3", **cfg)
    with pytest.raises(FaultInjected):
        rule.wait()
    assert rule.trainer.try_resume()
    rule.wait()
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))
    w = np.asarray(rule.trainer.weights)
    assert w.sum() == pytest.approx(1.0, abs=1e-5)
    fp = json.load(open(os.path.join(
        ck, "ckpt_e0001.manifest.json")))["fingerprint"]
    assert fp["rule"] == "gosgd" and fp["p_push"] == 0.9


@pytest.mark.faultinject
def test_easgd_elastic_mesh8_to_4(tmp_path):
    """The tentpole acceptance unit: an EASGD mesh8 lineage resumes onto
    mesh4 as a TYPED stacked plan — first 4 worker replicas kept, center
    restored bit-exactly, LR carried unrescaled — and trains on; a blind
    (non-reshard) consumer still refuses with the actionable mismatch."""
    ck = str(tmp_path / "ck")
    _easgd(8, 1, ck, tau=1).wait()
    with np.load(os.path.join(ck, "ckpt_e0000.npz")) as z:
        saved = {k: z[k] for k in z.files
                 if k.startswith(("params::", "center::"))}

    down = _easgd(4, 2, ck, tau=1, resume_reshard=True)
    blind = Checkpointer(ck, fingerprint=down.trainer._run_fingerprint(),
                         sweep_debris=False)
    with pytest.raises(CheckpointReshardableMismatch, match="mesh"):
        blind.verify_epoch(0)

    t = down.trainer
    assert t.epoch == 1  # epoch 0 resumed, not restarted
    assert t.lr_scale == pytest.approx(1.0)  # carried, not linear-scaled
    plan = t.checkpointer.last_reshard_plan
    assert plan is not None and plan.stacked == "easgd"
    assert (plan.old_n, plan.new_n) == (8, 4)
    # live params are the first 4 rows of the saved stacked trees; the
    # center re-placed bit-exactly
    for path, leaf in jax.tree_util.tree_flatten_with_path(t.params)[0]:
        key = "params::" + ck_mod._leaf_key(path)
        np.testing.assert_array_equal(np.asarray(leaf), saved[key][:4],
                                      err_msg=key)
    for path, leaf in jax.tree_util.tree_flatten_with_path(t.center)[0]:
        key = "center::" + ck_mod._leaf_key(path)
        np.testing.assert_array_equal(np.asarray(leaf), saved[key],
                                      err_msg=key)
    down.wait()
    assert t.epoch == 2
    assert t.recorder.val_history["epoch"] == [0, 1]  # continuous curve
    events = json.load(open(os.path.join(ck, "resilience.json")))["events"]
    names = [e["name"] for e in events]
    assert "reshard.plan" in names and "reshard.apply" in names
    man = json.load(open(os.path.join(ck, "ckpt_e0001.manifest.json")))
    assert man["fingerprint"]["mesh"]["data"] == 4
    assert man["fingerprint"]["rule"] == "easgd"


@pytest.mark.faultinject
def test_easgd_worker_slow_degrades_throughput_not_trajectory(
        tmp_path, monkeypatch, capfd):
    """A straggler stall before the synchronous exchange costs wall time
    only: the faulted run's params are bit-equal to the unfaulted one."""
    monkeypatch.setenv("THEANOMPI_EASGD_SLOW_S", "0.01")
    ref = _easgd(4, 1, tau=1)
    ref.wait()
    slow = _easgd(4, 1, tau=1, fault_plan="easgd:worker_slow@1")
    slow.wait()
    assert "injected EASGD straggler" in capfd.readouterr().err
    for a, b in zip(jax.tree.leaves(ref.trainer.params),
                    jax.tree.leaves(slow.trainer.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.faultinject
def test_gosgd_gossip_drop_conserves_consensus(tmp_path, capfd):
    """A dropped gossip round skips the collective but consumes its
    draws: the run completes, weights still sum to 1, later rounds keep
    the uninterrupted schedule (the round counter advanced)."""
    rule = _gosgd(4, 1, p_push=1.0, fault_plan="gosgd:gossip_drop@0")
    rule.wait()
    assert "injected gossip drop" in capfd.readouterr().err
    t = rule.trainer
    assert t._round_count >= 2  # rounds kept flowing after the drop
    assert np.asarray(t.weights).sum() == pytest.approx(1.0, abs=1e-5)


# -- supervised SIGKILL e2e (subprocess) -------------------------------------

def _adaptive_timeout(base: float) -> float:
    try:
        load = os.getloadavg()[0]
    except (OSError, AttributeError):
        return base
    per_core = load / max(os.cpu_count() or 1, 1)
    return base * min(4.0, max(1.0, per_core))


def _child_env(**extra):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_THREEFRY_PARTITIONABLE": "true",
        "PYTHONPATH": REPO,
    })
    env.pop("THEANOMPI_FAULT_PLAN", None)
    env.update(extra)
    return env


@pytest.mark.faultinject
def test_easgd_supervised_sigkill_cadence_resume_bit_equal(
        tmp_path, subproc_compile_cache):
    """The ISSUE 20 resume-matrix e2e: a supervised EASGD run with
    cadence saves SIGKILLed mid-epoch-1 restarts, auto-resumes through
    the verified chain, and finishes bit-equal to an uninterrupted
    in-process run at the same seed."""
    clean_ck = str(tmp_path / "ck_clean")
    _easgd(4, 2, clean_ck).wait()

    ck = str(tmp_path / "ck")
    p = subprocess.run(
        [sys.executable, "-m", "theanompi_tpu.launcher",
         "--rule", "EASGD", "--devices", "4",
         "--modelfile", "theanompi_tpu.models.wide_resnet",
         "--modelclass", "WideResNet", *TINY_ARGS,
         "--set", "n_epochs=2", "--quiet",
         "--rule-set", "tau=2", "--rule-set", "scale_lr=False",
         "--rule-set", "checkpoint_every_n_iters=1",
         "--rule-set", "checkpoint_async=False",
         "--checkpoint-dir", ck,
         "--compile-cache-dir", subproc_compile_cache,
         "--supervise", "--max-restarts", "3", "--backoff-base", "0.1"],
        env=_child_env(THEANOMPI_FAULT_PLAN="step:kill@3@1"),
        cwd=REPO, capture_output=True, text=True,
        timeout=_adaptive_timeout(480))
    assert p.returncode == 0, p.stderr[-2000:]
    art = json.load(open(os.path.join(ck, "resilience.json")))
    assert [a["cause"] for a in art["attempts"]] == ["crash", "clean"]
    assert art["attempts"][0]["exit_code"] == -signal.SIGKILL
    _assert_ckpt_equal(os.path.join(clean_ck, "ckpt_e0001.npz"),
                       os.path.join(ck, "ckpt_e0001.npz"))
