"""Benchmark: flagship-model training throughput on the available hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The reference's primary metric (BASELINE.json) is ImageNet images/sec/chip
under the BSP rule.  No published reference numbers were recoverable (the
reference mount was empty — see BASELINE.md), so ``vs_baseline`` is the ratio
to the round-1 nominal recorded below; it tracks our own improvement across
rounds.

Measurement protocol (matters on TPU, doubly so through a remote tunnel):

- **Pipelined timing.**  jax dispatch is async; a per-step device sync
  measures round-trip latency, not throughput (on this image's tunneled chip
  a single sync costs ~0.5 s — round 1's 356 img/s was mostly that artifact).
  We dispatch all timed steps back-to-back and read one scalar at the end;
  the chain of donated param buffers forces sequential execution on device.
- **Best of N trials.**  The tunneled chip is shared: identical runs vary
  >10x wall-clock.  Each trial pipelines ``BENCH_STEPS`` steps; the best
  trial is the capability number (min-time, the standard protocol for noisy
  shared machines).  Trial spread is reported as ``trial_throughput``.
- **Feed modes.**  ``BENCH_FEED=placed`` (default): a rotation of batches is
  pre-placed on device outside the timed region — measures the training step
  itself.  ``BENCH_FEED=prefetch``: host uint8 batches stream through the
  production Prefetcher as ``BaseTrainer.run`` does — includes host→device
  transfer (on this tunnel, transfers contend with dispatch on one link, so
  this mode understates a real TPU VM's pipeline; synthetic-data RNG stays
  outside the timed loop in both modes).
- **MFU accounting.**  Conv nets: FLOPs/step from XLA's cost analysis of
  the compiled step (fallback: an analytic table).  Transformer: fully
  analytic STRICT model flops (3x theoretical forward, no remat credit) —
  cost analysis counts Pallas custom-calls as zero AND scan bodies once
  instead of per trip, both of which understate the LM step.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

import jax
import jax.numpy as jnp

# Best prior-round measured throughput per (model, platform) — the
# denominator for vs_baseline, so driver artifacts track round-over-round
# progress (VERDICT r2 #9: anchored to the BASELINE.md ladder, not the
# round-1 guess).  Backfill real reference numbers if the reference mount is
# ever fixed.
NOMINAL = {
    ("wide_resnet", "tpu"): 25044.5,   # round 4, measured (replaces the
    #                                    round-1 guess of 4000 — VERDICT r3
    #                                    weak #4; trial spread 20.1-25.0k
    #                                    on the shared chip, best-of kept)
    ("wide_resnet", "cpu"): 40.0,
    ("resnet50", "tpu"): 2481.5,       # round 3, BENCH_r03.json
    ("resnet50", "cpu"): 4.0,
    # transformer rows are tokens/sec (unit switches with the model).
    # Round 4 re-baselined the config to vocab 32k + fused loss (the real
    # LM setting — r3's 290k was measured at the V=2048 toy vocab and is
    # not comparable); this is the round-4 measured number at the new
    # default config.
    ("transformer", "tpu"): 234_000.0,
    ("transformer", "cpu"): 1_000.0,
}

#: bf16 peak FLOP/s per chip by device-kind substring (override:
#: BENCH_PEAK_TFLOPS); first match wins
PEAK_TFLOPS = (
    ("v5 lite", 197.0),   # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v6", 918.0),        # v6e (Trillium)
    ("v4", 275.0),
)

#: analytic fwd+bwd FLOPs per sample for the conv nets (fallback when cost
#: analysis is absent; the transformer always uses the strict analytic
#: formula in run_bench instead)
ANALYTIC_FLOPS = {"resnet50": 3 * 4.1e9, "wide_resnet": 3 * 0.1e9}


def chip_peak_flops() -> float | None:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = jax.devices()[0].device_kind.lower()
    for sub, tf in PEAK_TFLOPS:
        if sub in kind:
            return tf * 1e12
    return None


def build_trainer(model_name: str, platform: str):
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.recorder import Recorder

    bs_env = os.environ.get("BENCH_BS")
    if model_name == "resnet50":
        from theanompi_tpu.models.resnet50 import ResNet50 as cls

        bs = int(bs_env) if bs_env else (256 if platform == "tpu" else 16)
        cfg = {"batch_size": bs, "n_train": bs * 4, "n_val": bs,
               "shard_size": bs}
    elif model_name == "transformer":
        from theanompi_tpu.models.transformer_lm import TransformerLM as cls

        bs = int(bs_env) if bs_env else (16 if platform == "tpu" else 2)
        seq = int(os.environ.get("BENCH_SEQ", "2048" if platform == "tpu"
                                 else "256"))
        # Default vocab 32k on TPU: the REAL configuration — >=8192 flips
        # the model onto the fused chunked cross-entropy path (VERDICT r3
        # #3: the old 2048 default measured the naive path at a toy vocab,
        # the setting the fused loss exists to replace).  The synthetic
        # generator switches to the procedural-sparse bigram at >4096, so
        # host setup stays cheap.
        vocab = int(os.environ.get(
            "BENCH_VOCAB", "32768" if platform == "tpu" else "2048"))
        dim = int(os.environ.get("BENCH_DIM", "512"))
        layers = int(os.environ.get("BENCH_LAYERS", "8"))
        # heads = dim/64 ⇒ head_dim is exactly 64, lane-aligned for the
        # pallas kernels at every ladder rung.  dim < 512 would need a
        # clamped head count whose head_dim (< 64) silently falls off the
        # flash path — refuse instead of mismeasuring (ADVICE r4).
        if dim % 64 or dim < 512:
            raise SystemExit(
                f"BENCH_DIM={dim} must be a multiple of 64 and >= 512")
        heads = dim // 64
        cfg = {"batch_size": bs, "seq_len": seq, "vocab": vocab,
               "dim": dim, "heads": heads, "n_layers": layers,
               "dropout": 0.0, "n_train": bs * 8, "n_val": bs * 2}
        if "BENCH_FUSED_LOSS" in os.environ:
            cfg["fused_loss"] = bool(int(os.environ["BENCH_FUSED_LOSS"]))
        # scan-unroll A/B knob (r5): the V=32k roofline puts ~27% of the
        # step in while self-time, and the bench model's ONLY scans are
        # the fused-loss chunk scans (the base TransformerLM trunk is a
        # Python-loop Sequential — layers_unroll applies to the pipeline
        # variant, which bench never builds)
        if "BENCH_LOSS_UNROLL" in os.environ:
            cfg["loss_unroll"] = int(os.environ["BENCH_LOSS_UNROLL"])
    else:
        from theanompi_tpu.models.wide_resnet import WideResNet as cls

        bs = int(bs_env) if bs_env else (256 if platform == "tpu" else 64)
        cfg = {"batch_size": bs, "n_train": max(1024, bs * 4), "n_val": bs}
    if os.environ.get("BENCH_NSUBB"):
        # gradient accumulation: n_subb micro-batches per step (activation
        # memory per micro-batch — the large-effective-batch lever)
        cfg["n_subb"] = int(os.environ["BENCH_NSUBB"])
    model = cls(cfg)
    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    # huge print_freq: train_iter fences on metrics at print boundaries,
    # which would inject the per-step-sync artifact mid-trial.
    # BENCH_EXCH / BENCH_EXCH_BUCKET_MB select the exchange strategy and
    # fused-bucket size (single-chip runs exchange nothing, but the knobs
    # make multi-chip bench invocations strategy-comparable)
    trainer = BSPTrainer(model, mesh=mesh,
                         exch_strategy=os.environ.get("BENCH_EXCH", "psum"),
                         exch_bucket_mb=float(
                             os.environ.get("BENCH_EXCH_BUCKET_MB", "4")),
                         recorder=Recorder(verbose=False, print_freq=10**9))
    trainer.compile_iter_fns()
    trainer.init_state()
    return trainer, model


def step_flops(trainer, batch) -> float | None:
    """FLOPs per compiled train step, from XLA's cost analysis."""
    try:
        analysis = trainer.compiled_step(batch).cost_analysis()
        if isinstance(analysis, list):  # older jax: one dict per device
            analysis = analysis[0]
        fl = float(analysis.get("flops", 0.0))
        return fl if fl > 0 else None
    except Exception:  # lint: swallow-ok — best-effort probe, None = n/a
        return None


def run_bench(model_name: str) -> dict:
    """Measure one model; -> the result-line dict (the old main body)."""
    platform = jax.devices()[0].platform
    feed_mode = os.environ.get("BENCH_FEED", "placed")
    # the tunneled chip throttles in multi-second windows: many short
    # trials catch an unthrottled window; best-of is the capability number
    trials = int(os.environ.get("BENCH_TRIALS", "6"))
    trainer, model = build_trainer(model_name, platform)
    steps = int(os.environ.get(
        "BENCH_STEPS", "20" if platform == "tpu" else "10"))
    bs = trainer.global_batch

    from theanompi_tpu.utils.helper_funcs import shard_batch

    # fixed rotation of host batches, built outside the timed region
    host_batches = list(model.data.train_batches(bs, epoch=0, seed=0))

    # warmup: compile + first dispatch + tunnel establishment, then sync
    m = trainer.train_iter(host_batches[0], lr=0.01)
    float(m["cost"])

    if model_name == "transformer":
        # Fully analytic STRICT model flops (train = 3x the theoretical
        # forward; rematerialization inside flash-attention and the fused
        # loss is real work but NOT counted — the PaLM-style MFU
        # convention).  Cost analysis is unusable here twice over: it
        # counts Pallas custom-calls as zero flops AND counts each
        # lax.scan body once instead of per trip, so at V=32k it missed
        # ~4 TF of the fused-loss head per step (reported MFU 0.26 where
        # the honest number is ~0.36).
        cfgm = model.config
        t, d, heads, layers = (cfgm["seq_len"], cfgm["dim"], cfgm["heads"],
                               cfgm["n_layers"])
        n_tok = bs * t
        v = model.data.vocab
        mm_params = layers * 12 * d * d          # qkvo (4d^2) + ffn (8d^2)
        trunk = 6.0 * n_tok * mm_params
        attn = 3.0 * layers * 0.5 * 4.0 * bs * heads * t * t * (d // heads)
        head = 6.0 * n_tok * d * v
        flops = trunk + attn + head
    else:
        flops = step_flops(trainer, host_batches[0])
        if flops is None:
            flops = ANALYTIC_FLOPS.get(model_name, 0.0) * bs
        elif int(model.config.get("n_subb", 1) or 1) > 1:
            # cost analysis counts a lax.scan body ONCE; with gradient
            # accumulation nearly the whole step lives inside the
            # micro-batch scan, so scale by n_subb (exchange/update
            # outside the scan are a rounding error next to fwd+bwd)
            flops *= int(model.config["n_subb"])
    peak = chip_peak_flops()

    if feed_mode == "placed":
        batches = [shard_batch(trainer.mesh, b, spec=trainer.batch_spec)
                   for b in host_batches]
        jax.block_until_ready(batches)
    else:
        batches = host_batches

    from theanompi_tpu.utils.benchlib import best_slope, best_trial

    # transformer throughput is tokens/s (samples/s x seq_len); conv nets
    # report images/s — the reference's headline unit (BASELINE.md)
    if model_name == "transformer":
        per_sample = model.config["seq_len"]
        unit, noun = "tokens/sec", "tokens"
    else:
        per_sample, unit, noun = 1, "images/sec", "images"
    # slope protocol on TPU (default): cancels the constant final-fetch
    # round trip every chained trial's wall time carries (see
    # benchlib.slope_trial) — the r4 chain artifact sat ~10 % below the
    # measured capability for exactly that constant (VERDICT r4 #2).
    # BENCH_PROTOCOL=chain restores the old estimator (also the CPU
    # default, where there is no tunnel RTT to cancel).
    protocol = os.environ.get(
        "BENCH_PROTOCOL", "slope" if platform == "tpu" else "chain")
    if protocol == "slope" and steps < 4:
        protocol = "chain"  # no lo/hi spread to take a slope over
    if protocol == "slope":
        n_lo = max(2, steps // 5)
        (step_s, wait_s), sresults, used_fallback = best_slope(
            trainer, batches, n_lo, steps, trials, feed_mode=feed_mode)
        if used_fallback:
            # every trial straddled a throttle transition: the number is
            # the chain estimate (RTT-inflated) — say so in the artifact
            protocol = "slope-fallback-chain"
        # non-positive slopes (throttle transition mid-trial) surface as
        # 0.0 in the spread rather than silently vanishing
        per_trial = [(bs * per_sample / r[0]) if r[0] > 0 else 0.0
                     for r in sresults]
        n = steps
        dt = step_s * n
    else:
        (dt, n, wait_s), results = best_trial(
            trainer, batches, steps, trials, feed_mode=feed_mode)
        per_trial = [tn * bs * per_sample / tdt for tdt, tn, _ in results]
    images_per_sec = n * bs * per_sample / dt
    base = NOMINAL.get((model_name, platform), images_per_sec)
    out = {
        "metric": f"{model_name}_train_{noun}_per_sec_per_chip_{platform}",
        "value": round(images_per_sec, 2),
        "unit": unit,
        "vs_baseline": round(images_per_sec / base, 3),
        "batch_size": bs,
        "steps": n,
        "feed": feed_mode,
        "protocol": protocol,
        "step_ms": round(dt / n * 1e3, 2),
        "input_wait_s": round(wait_s, 3),
        "trial_throughput": [round(v, 1) for v in per_trial],
    }
    if flops:
        out["gflops_per_step"] = round(flops / 1e9, 1)
        if peak:
            out["mfu"] = round(flops * n / dt / peak, 4)
    if model_name == "transformer":
        from theanompi_tpu.ops.attention import resolve_attn_impl

        # the model's own resolver, so the artifact records which attention
        # path actually ran (ADVICE r4: a shape falling off the flash path
        # must be visible, not silent)
        impl = resolve_attn_impl(
            model.config["attn_impl"], model.config["seq_len"],
            model.config["dim"] // model.config["heads"])
        # self-describing artifact: the config IS the claim at real vocab
        out["config"] = {
            "seq_len": model.config["seq_len"], "dim": model.config["dim"],
            "n_layers": model.config["n_layers"], "vocab": model.data.vocab,
            "fused_loss": model.fused_loss_enabled(),
            "attention_impl": impl,
            "flops_accounting": "strict analytic 3x-forward (no remat credit)",
        }
    return out


def _maybe_telemetry():
    """BENCH_TELEMETRY_DIR set -> a Telemetry sink for this bench run, else
    None (zero telemetry calls — same off-by-default contract as training)."""
    tel_dir = os.environ.get("BENCH_TELEMETRY_DIR")
    if not tel_dir:
        return None
    from theanompi_tpu.telemetry import Telemetry

    return Telemetry(tel_dir)


def run_serve_bench() -> dict:
    """BENCH_SERVE mode (ISSUE 6): synthetic open-loop serving through the
    continuous-batching engine; -> the SERVE.json report dict.

    Knobs (all optional): BENCH_SERVE_REQUESTS / _PROMPT / _NEW / _BATCH /
    _BLOCK_SIZE / _BLOCKS / _RATE (req/s, 0 = burst) / _QUANT (int8
    weights) / _CKPT (verified checkpoint dir) / _SET (semicolon-separated
    model k=v pairs layered over the bench transformer geometry) /
    _PREFIX_CACHE (radix prefix cache, ISSUE 17) / _TURNS (multi-turn
    sessions of this many requests each) / _SHARED_PREFIX (identical
    system-prompt tokens on every request) — the last three surface in
    SERVE.json as prefix_hit_rate / prefill_tokens_saved — /
    _DECODE_KERNEL (on|off|auto, ISSUE 18 fused decode-attention A/B;
    SERVE.json reports the served variant and its decode_step_ms
    percentiles).
    """
    from theanompi_tpu.serving import cli as serve_cli

    env = os.environ.get
    platform = jax.devices()[0].platform
    dim = int(env("BENCH_DIM", "512" if platform == "tpu" else "64"))
    model_set = [
        f"dim={dim}", f"heads={max(1, dim // 64)}",
        f"n_layers={env('BENCH_LAYERS', '8' if platform == 'tpu' else '2')}",
        f"seq_len={env('BENCH_SEQ', '2048' if platform == 'tpu' else '64')}",
        f"vocab={env('BENCH_VOCAB', '32768' if platform == 'tpu' else '256')}",
        "dropout=0.0", "precision=" + ("bf16" if platform == "tpu"
                                       else "fp32"),
    ]
    for pair in (env("BENCH_SERVE_SET", "") or "").split(";"):
        if pair.strip():
            model_set.append(pair.strip())
    # start from the CLI parser's own defaults so new tmserve flags
    # (deadlines, drain, rollout, ...) can never drift out of sync with
    # this hand-built namespace
    args = serve_cli.build_parser().parse_args([])
    vars(args).update(
        modelfile="theanompi_tpu.models.transformer_lm",
        modelclass="TransformerLM", model_set=model_set,
        checkpoint_dir=env("BENCH_SERVE_CKPT") or None,
        serve_verify="fast", serve_force=False,
        max_batch=int(env("BENCH_SERVE_BATCH", "8")),
        block_size=int(env("BENCH_SERVE_BLOCK_SIZE", "16")),
        num_blocks=(int(env("BENCH_SERVE_BLOCKS"))
                    if env("BENCH_SERVE_BLOCKS") else None),
        quantize_int8=bool(int(env("BENCH_SERVE_QUANT", "0"))),
        decode_kernel=env("BENCH_SERVE_DECODE_KERNEL", "auto"),
        top_k=0,
        prefix_cache=bool(int(env("BENCH_SERVE_PREFIX_CACHE", "0"))),
        requests=int(env("BENCH_SERVE_REQUESTS", "16")),
        prompt_len=int(env("BENCH_SERVE_PROMPT", "16")),
        max_new_tokens=int(env("BENCH_SERVE_NEW", "32")),
        arrival_rate=float(env("BENCH_SERVE_RATE", "0")),
        turns=int(env("BENCH_SERVE_TURNS", "1")),
        shared_prefix_len=int(env("BENCH_SERVE_SHARED_PREFIX", "0")),
        temperature=0.0, seed=int(env("BENCH_SEED", "0")),
        telemetry_dir=env("BENCH_TELEMETRY_DIR") or None,
        out=None, quiet=True,
    )
    return serve_cli.serve(args)


def run_router_bench() -> dict:
    """BENCH_ROUTER mode (ISSUE 19): multi-replica serving through the
    tmrouter fleet pool; -> the ROUTER.json report dict.

    Replicas are real tmserve subprocesses leased from a fleet ledger in
    BENCH_ROUTER_FLEET_DIR (default: a fresh dir next to this file —
    wiped per run so stale leases never block the pool).  Knobs (all
    optional): BENCH_ROUTER_REQUESTS / _REPLICAS / _MIN_REPLICAS /
    _MAX_REPLICAS / _DEVICES (gang lease per replica) / _POOL (device
    pool size) / _RATE (req/s, 0 = burst) / _NEW / _PROMPT / _TURNS
    (sticky conversations) / _SET (semicolon-separated model k=v pairs
    over the CPU-sized bench transformer).  The report lands in
    ROUTER.json (p50/p99 router-visible TTFT, tokens/sec, the replica
    trajectory, the exactly-once audit) and the perf ledger.
    """
    import shutil

    from theanompi_tpu.router import cli as router_cli

    env = os.environ.get
    fleet_dir = env("BENCH_ROUTER_FLEET_DIR")
    if not fleet_dir:
        fleet_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "fleet_router_bench")
        shutil.rmtree(fleet_dir, ignore_errors=True)
    model_set = [
        "dim=64", "heads=1", "n_layers=2", "seq_len=64", "vocab=256",
        "dropout=0.0", "precision=fp32",
    ]
    for pair in (env("BENCH_ROUTER_SET", "") or "").split(";"):
        if pair.strip():
            model_set.append(pair.strip())
    args = router_cli.build_parser().parse_args(["--fleet-dir", fleet_dir])
    vars(args).update(
        pool_size=(int(env("BENCH_ROUTER_POOL"))
                   if env("BENCH_ROUTER_POOL") else None),
        replicas=int(env("BENCH_ROUTER_REPLICAS", "2")),
        min_replicas=(int(env("BENCH_ROUTER_MIN_REPLICAS"))
                      if env("BENCH_ROUTER_MIN_REPLICAS") else None),
        max_replicas=int(env("BENCH_ROUTER_MAX_REPLICAS", "2")),
        replica_devices=int(env("BENCH_ROUTER_DEVICES", "1")),
        model_set=model_set,
        requests=int(env("BENCH_ROUTER_REQUESTS", "8")),
        prompt_len=int(env("BENCH_ROUTER_PROMPT", "8")),
        max_new_tokens=int(env("BENCH_ROUTER_NEW", "8")),
        arrival_rate=float(env("BENCH_ROUTER_RATE", "0")),
        turns=int(env("BENCH_ROUTER_TURNS", "1")),
        seed=int(env("BENCH_SEED", "0")),
        timeout_s=float(env("BENCH_ROUTER_TIMEOUT", "600")),
        telemetry_dir=env("BENCH_TELEMETRY_DIR") or None,
        out=None, quiet=True,
    )
    return router_cli.run_router(args)


def _ledger_append(payload: dict, source: str) -> None:
    """ISSUE 16: append one published artifact to PERF_LEDGER.jsonl next
    to this file — every publish site calls through here (including the
    backend_unavailable stub, which the ledger records but never
    baselines).  BENCH_LEDGER overrides the path; BENCH_LEDGER=0
    disables; never raises."""
    try:
        from theanompi_tpu.telemetry.ledger import bench_ledger_append

        bench_ledger_append(
            payload, source,
            repo_dir=os.path.dirname(os.path.abspath(__file__)))
    except Exception:  # lint: swallow-ok — advisory trajectory, bench line wins
        pass


def _measure():
    """One full measurement pass: primary line + transformer side artifact."""
    if os.environ.get("BENCH_COMPILE_CACHE"):
        # persistent XLA compile cache (ISSUE 3): repeated bench runs of the
        # same config skip the compile; deliberately NOT scrubbed for the
        # transformer side-bench below — sharing the cache is the point
        from theanompi_tpu.parallel.mesh import setup_compile_cache

        setup_compile_cache(os.environ["BENCH_COMPILE_CACHE"])
    if os.environ.get("BENCH_SERVE"):
        # serving bench (ISSUE 6): one JSON line + the SERVE.json artifact
        # (atomic publish, same run_id staleness contract as the side-bench)
        run_id = (time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
                  + f"-p{os.getpid()}")
        out = run_serve_bench()
        out["run_id"] = run_id
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "SERVE.json")
        with open(path + ".tmp", "w") as f:
            json.dump(out, f, indent=1)
        os.replace(path + ".tmp", path)
        _ledger_append(out, "SERVE.json")
        print(json.dumps(out))
        return
    if os.environ.get("BENCH_ROUTER"):
        # multi-replica router bench (ISSUE 19): same atomic-publish +
        # ledger contract as the serve bench, ROUTER.json artifact
        run_id = (time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
                  + f"-p{os.getpid()}")
        out = run_router_bench()
        out["run_id"] = run_id
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ROUTER.json")
        with open(path + ".tmp", "w") as f:
            json.dump(out, f, indent=1)
        os.replace(path + ".tmp", path)
        _ledger_append(out, "ROUTER.json")
        print(json.dumps(out))
        return
    model_name = os.environ.get("BENCH_MODEL", "resnet50")
    # run id stamped onto every artifact this process emits: a stale side
    # artifact surviving a failed later run is detectable by its id not
    # matching the round's BENCH_r* capture (VERDICT r4 #1 — in round 4 a
    # 10:24 side file outlived an 11:11 crashed driver run, undetectably)
    run_id = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()) + f"-p{os.getpid()}"
    tel = _maybe_telemetry()
    if tel is None:
        out = run_bench(model_name)
    else:
        with tel.span("bench.run", model=model_name, run_id=run_id):
            out = run_bench(model_name)
    out["run_id"] = run_id
    if tel is not None:
        # the single JSON line, mirrored as structured events so a fleet
        # scraping telemetry dirs sees bench results without stdout parsing
        tel.instant("bench.result", **{
            k: v for k, v in out.items()
            if isinstance(v, (int, float, str, bool))})
        tel.gauge("bench.throughput", out["value"])
        if "mfu" in out:
            tel.gauge("bench.mfu", out["mfu"])
        tel.close()
        tel.export_chrome_trace()
    # the driver contract is ONE JSON line on stdout (the primary model);
    # the transformer's line goes to a sibling artifact so every round
    # records the LM number at the real config too (VERDICT r3 #3).  The
    # side-bench only fires on the default invocation (no BENCH_MODEL):
    # explicit sweeps shouldn't re-bench the LM per model, and their env
    # overrides (BENCH_BS/BENCH_FUSED_LOSS/...) would measure an off-label
    # config, so those knobs are scrubbed for the side run.
    print(json.dumps(out))
    _ledger_append(out, f"bench.{model_name}")
    if "BENCH_MODEL" in os.environ or os.environ.get("BENCH_SKIP_EXTRA"):
        return
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_transformer.json")
    saved = {}
    for k in ("BENCH_BS", "BENCH_SEQ", "BENCH_VOCAB", "BENCH_FUSED_LOSS",
              "BENCH_STEPS", "BENCH_TRIALS", "BENCH_FEED",
              "BENCH_DIM", "BENCH_LAYERS", "BENCH_NSUBB",
              "BENCH_LOSS_UNROLL"):
        if k in os.environ:
            saved[k] = os.environ.pop(k)
    try:
        extra = run_bench("transformer")
        extra["run_id"] = run_id
        # atomic publish: only success replaces the old artifact.  On any
        # failure the previous file stays in place — deleting it would
        # erase the last good measurement on a transient failure (ADVICE
        # r4), and the run_id stamp already makes staleness detectable.
        with open(path + ".tmp", "w") as f:
            json.dump(extra, f, indent=1)
        os.replace(path + ".tmp", path)
        _ledger_append(extra, "BENCH_transformer.json")
    except Exception as e:  # lint: swallow-ok — the primary bench line
        # must survive a side-bench failure; the error is printed, not lost
        print(f"transformer side-bench failed: {e}", file=sys.stderr)
    finally:
        os.environ.update(saved)
        try:
            os.remove(path + ".tmp")
        except OSError:  # lint: swallow-ok — no leftover, or something
            pass         # unremovable: not worth failing the primary line


def _names_backend_init(msg_low: str) -> bool:
    """Does this error message describe backend initialization at all?"""
    return ("unknown backend" in msg_low
            or "unable to initialize backend" in msg_low
            or "failed to initialize" in msg_low
            or ("platform" in msg_low and "present" in msg_low))


def backend_hint(e: BaseException) -> str | None:
    """The one-line actionable message for a backend-init failure: names
    the backend and the JAX_PLATFORMS remediation (ISSUE 6 satellite — the
    BENCH_r04/r05 failure mode previously surfaced as a raw jax traceback).
    None when the error is not backend-init shaped."""
    msg = str(e)
    low = msg.lower()
    if not _names_backend_init(low):
        return None
    import re

    m = re.search(r"backend:?\s+'?([a-z0-9_]+)'?", low)
    name = m.group(1) if m else (os.environ.get("JAX_PLATFORMS")
                                 or os.environ.get("BENCH_PLATFORM")
                                 or "requested")
    first = " ".join(msg.split())[:200]
    return (f"bench: backend {name!r} unavailable ({first}) — set "
            f"JAX_PLATFORMS (or BENCH_PLATFORM) to an available backend, "
            f"e.g. JAX_PLATFORMS=cpu")


def backend_unavailable_error(e: BaseException) -> str | None:
    """The FAIL-FAST classifier: the hint, but only for deterministic
    absence — "Unknown backend" / "no ... platforms ... present", or an
    init failure WITHOUT transient markers (UNAVAILABLE / DEADLINE /
    connection), which retrying cannot fix.  A flapped tunnel ("Unable to
    initialize backend 'tpu': UNAVAILABLE ...") returns None and keeps the
    bounded retry path; the hint still lands in the final give-up line.
    Unit-tested against the canned phrasings in ``tests/test_bench_retry.py``.
    """
    low = str(e).lower()
    if not _names_backend_init(low):
        return None
    deterministic = ("unknown backend" in low
                     or ("platform" in low and "present" in low))
    if not deterministic and _transient(e):
        return None
    return backend_hint(e)


def _transient(e: BaseException) -> bool:
    """Does this failure look like a backend/tunnel outage worth a re-exec?

    Deterministic errors (a bad BENCH_* combination, a model bug) must NOT
    burn 5 attempts x 60 s on the shared chip; only infrastructure-shaped
    failures retry.  The match is on type name + message because jaxlib's
    XlaRuntimeError class path varies across versions.
    """
    name = type(e).__name__
    msg = str(e)
    return ("XlaRuntimeError" in name
            or "backend init still blocked" in msg
            or "UNAVAILABLE" in msg
            or "DEADLINE_EXCEEDED" in msg
            or "backend setup" in msg
            or "Connection" in msg
            or "socket" in msg.lower())


def _acquire_backend(timeout_s: float):
    """``jax.devices()`` behind a watchdog thread.

    A downed tunnel does not always raise: measured on this image, backend
    init can BLOCK for >10 minutes inside the PJRT client instead of
    failing (the r4 driver loss was the raising variant; this is the other
    one).  A hung init cannot be cancelled in-process, so on timeout we
    raise — and the retry path re-execs the whole process, hung thread and
    all.
    """
    import threading

    out = {}

    def probe():
        try:
            out["devices"] = jax.devices()
        except Exception as e:  # re-raised on the main thread below
            out["error"] = e

    t = threading.Thread(target=probe, name="bench-backend-probe",
                         daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise RuntimeError(
            f"backend init still blocked after {timeout_s:.0f}s")
    if "error" in out:
        raise out["error"]
    return out["devices"]


def main():
    """Run ``_measure`` with a bounded process-level retry.

    Round 4's driver bench died on the first ``jax.devices()`` call with a
    transient ``UNAVAILABLE: TPU backend setup/compile error`` (the shared
    tunnel was down for a moment) and the round lost its headline perf
    artifact (VERDICT r4 #1).  jax caches a *failed* backend init for the
    life of the process, so an in-process retry would re-raise the cached
    error; instead each retry re-execs this script — a fresh process, a
    fresh PJRT client, a fresh tunnel connection.  The attempt count and a
    one-line-per-attempt log thread through the environment and the final
    failure re-raises with that log in the error tail.

    Knobs: BENCH_INIT_RETRIES (default 5 attempts), BENCH_RETRY_BACKOFF
    (default 60 s between attempts), BENCH_INIT_TIMEOUT (default 300 s —
    see ``_acquire_backend``), BENCH_PLATFORM (force a jax platform at the
    config level: this image's sitecustomize imports jax with the tunnel
    platform baked into config defaults, so the plain JAX_PLATFORMS env
    var is too late to stop a downed-tunnel init from blocking).
    BENCH_FAIL_UNTIL_ATTEMPT=N is fault injection for the retry-path
    test: attempts < N raise a simulated UNAVAILABLE before touching the
    backend.
    """
    attempt = int(os.environ.get("BENCH_ATTEMPT", "1"))
    retries = int(os.environ.get("BENCH_INIT_RETRIES", "5"))
    backoff = float(os.environ.get("BENCH_RETRY_BACKOFF", "60"))
    try:
        if attempt < int(os.environ.get("BENCH_FAIL_UNTIL_ATTEMPT", "0")):
            raise RuntimeError("UNAVAILABLE: injected backend failure"
                               " (BENCH_FAIL_UNTIL_ATTEMPT)")
        if os.environ.get("BENCH_PLATFORM"):
            jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
        _acquire_backend(float(os.environ.get("BENCH_INIT_TIMEOUT", "300")))
        _measure()
    except Exception as e:
        # a backend that is deterministically ABSENT (vs a flapped tunnel)
        # cannot be retried into existence: fail fast with the one-line
        # actionable error instead of 5 x 60 s + a raw jax traceback
        unavailable = backend_unavailable_error(e)
        if unavailable:
            # a deterministic absence still leaves a TYPED artifact (ISSUE
            # 11 satellite): a fleet scraping bench outputs can tell "the
            # backend isn't here" from "the bench never ran".  Stdout stays
            # empty — the one-JSON-line driver contract is for measurements
            # only.  BENCH_UNAVAILABLE_OUT redirects the stub (tests).
            stub_path = os.environ.get("BENCH_UNAVAILABLE_OUT") or \
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_unavailable.json")
            stub = {"status": "backend_unavailable",
                    "error": unavailable.splitlines()[0],
                    "run_id": (time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
                               + f"-p{os.getpid()}")}
            with open(stub_path + ".tmp", "w") as f:
                json.dump(stub, f, indent=1)
            os.replace(stub_path + ".tmp", stub_path)
            _ledger_append(stub, os.path.basename(stub_path))
            # SystemExit's string arg is printed to stderr by the
            # interpreter — no explicit print, or the line doubles
            raise SystemExit(unavailable)
        line = f"attempt {attempt}/{retries}: {type(e).__name__}: {str(e)[:300]}"
        log = os.environ.get("BENCH_ATTEMPT_LOG", "")
        log = (log + " | " if log else "") + line
        print(f"bench: {line}", file=sys.stderr)
        if attempt >= retries or not _transient(e):
            traceback.print_exc()
            hint = backend_hint(e)
            raise SystemExit(
                f"bench: giving up after {attempt} attempts"
                f"{'' if _transient(e) else ' (non-transient error)'};"
                f" log: {log}" + (f"\n{hint}" if hint else ""))
        os.environ["BENCH_ATTEMPT"] = str(attempt + 1)
        os.environ["BENCH_ATTEMPT_LOG"] = log
        time.sleep(backoff)
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)])


if __name__ == "__main__":
    main()
