"""Benchmark: flagship-model training throughput on the available hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference's primary metric (BASELINE.json) is ImageNet images/sec/chip
under the BSP rule.  No published reference numbers were recoverable (the
reference mount was empty — see BASELINE.md), so ``vs_baseline`` is the ratio
to the round-1 nominal recorded below; it starts at 1.0 and tracks our own
improvement across rounds.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

# Round-1 nominal throughput (images/sec) per (model, platform) — the
# denominator for vs_baseline.  Backfill real reference numbers if the
# reference mount is ever fixed.
NOMINAL = {
    ("wide_resnet", "tpu"): 4000.0,
    ("wide_resnet", "cpu"): 40.0,
    ("resnet50", "tpu"): 800.0,
    ("resnet50", "cpu"): 4.0,
}


def build_trainer(model_name: str):
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh

    if model_name == "resnet50":
        from theanompi_tpu.models.resnet50 import ResNet50 as cls

        cfg = {"batch_size": 64, "n_train": 256, "n_val": 64}
    else:
        from theanompi_tpu.models.wide_resnet import WideResNet as cls

        cfg = {"batch_size": 256, "n_train": 1024, "n_val": 256}
    model = cls(cfg)
    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    trainer = BSPTrainer(model, mesh=mesh)
    trainer.compile_iter_fns()
    trainer.init_state()
    return trainer, model


def main():
    model_name = os.environ.get("BENCH_MODEL", "resnet50")
    trainer, model = build_trainer(model_name)
    platform = jax.devices()[0].platform
    steps = int(os.environ.get("BENCH_STEPS", "30" if platform == "tpu" else "10"))

    batches = list(model.data.train_batches(trainer.global_batch, epoch=0, seed=0))
    # warmup: trigger compile + first dispatch
    for b in batches[:2]:
        m = trainer.train_iter(b, lr=0.01)
    jax.block_until_ready(m["cost"])

    t0 = time.perf_counter()
    for i in range(steps):
        m = trainer.train_iter(batches[i % len(batches)], lr=0.01)
    jax.block_until_ready(m["cost"])
    dt = time.perf_counter() - t0

    images_per_sec = steps * trainer.global_batch / dt
    base = NOMINAL.get((model_name, platform), images_per_sec)
    print(
        json.dumps(
            {
                "metric": f"{model_name}_train_images_per_sec_per_chip_{platform}",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(images_per_sec / base, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
