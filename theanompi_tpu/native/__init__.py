"""Native (C) host-runtime components, loaded via ctypes.

The TPU compute path is jax/XLA/pallas; the *host* runtime around it —
here the input pipeline's per-image crop/mirror gather, the one loader
step that can't vectorize in numpy — is native C, compiled on first use
with the system compiler into ``_build/`` next to this file.  Everything
degrades to the numpy reference implementation when no compiler is
available (``lib() -> None``), and the numpy path stays the source of
truth the C path is tested against.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "augment.c")
_SO = os.path.join(_DIR, "_build", "libaugment.so")

_lib = None
_tried = False


_build_lock = __import__("threading").Lock()


def lib():
    """The loaded native library, building it on first call; None if the
    build fails (no compiler, missing source in a wheel, read-only tree,
    hung compiler, ...) — callers always have the numpy fallback."""
    global _lib, _tried
    with _build_lock:  # threads: prefetch daemons may race the first call
        if _tried:
            return _lib
        _tried = True
        try:
            _lib = _build_and_load()
        except Exception:  # lint: swallow-ok — optional native fast path
            _lib = None
        return _lib


def available() -> bool:
    """Whether the native kernel is loadable (builds on first call)."""
    return lib() is not None


def _build_and_load():
    if not (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        # build to a per-process temp name, then atomic rename: concurrent
        # PROCESSES (multi-worker launch) must never CDLL a half-written .so
        tmp = f"{_SO}.{os.getpid()}.tmp"
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", _SRC, "-o", tmp],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, _SO)
                break
            except (FileNotFoundError, subprocess.CalledProcessError,
                    subprocess.TimeoutExpired):
                continue
        else:
            return None
    return _load(_SO)


def _load(path):
    handle = ctypes.CDLL(path)
    handle.crop_mirror_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_long, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        ctypes.c_long, ctypes.c_long, ctypes.c_long,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
    ]
    handle.crop_mirror_batch.restype = None
    return handle


def crop_mirror_batch(src: np.ndarray, out_h: int, out_w: int,
                      ys: np.ndarray, xs: np.ndarray,
                      flips: np.ndarray) -> np.ndarray | None:
    """Native per-image crop+mirror; -> result, or None when unavailable
    (caller falls back to the numpy loop).

    ``src``: [N, H, W, C] any fixed-size dtype; ``ys``/``xs``: per-image
    top-left offsets; ``flips``: per-image horizontal-mirror booleans.
    """
    handle = lib()
    if handle is None:
        return None
    src = np.ascontiguousarray(src)
    n, h, w, c = src.shape
    out = np.empty((n, out_h, out_w, c), src.dtype)
    handle.crop_mirror_batch(
        src.ctypes.data_as(ctypes.c_char_p),
        out.ctypes.data_as(ctypes.c_char_p),
        n, h, w, c, src.dtype.itemsize, out_h, out_w,
        np.ascontiguousarray(ys, np.int64),
        np.ascontiguousarray(xs, np.int64),
        np.ascontiguousarray(flips, np.uint8),
    )
    return out
