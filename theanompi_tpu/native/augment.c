/* Per-image crop + horizontal mirror for NHWC batches, dtype-generic.
 *
 * The host-side inner loop of the input pipeline (the para_load analogue):
 * python-level per-image slicing is the one part of the loader that doesn't
 * vectorize in numpy (per-image offsets), so it lives here as row memcpys.
 * Element size is a parameter, so uint8 and float32 batches share one
 * implementation.  Compiled at first use by theanompi_tpu.native (cc -O3);
 * the pure-numpy fallback remains the reference implementation.
 */
#include <string.h>

void crop_mirror_batch(const char *src, char *dst,
                       long n, long src_h, long src_w, long c, long esize,
                       long out_h, long out_w,
                       const long *ys, const long *xs,
                       const unsigned char *flips) {
    const long px = c * esize;
    const long src_img = src_h * src_w * px, src_row = src_w * px;
    const long dst_img = out_h * out_w * px, dst_row = out_w * px;
    for (long i = 0; i < n; ++i) {
        const char *s0 = src + i * src_img + ys[i] * src_row + xs[i] * px;
        char *d0 = dst + i * dst_img;
        if (!flips[i]) {
            for (long r = 0; r < out_h; ++r)
                memcpy(d0 + r * dst_row, s0 + r * src_row, dst_row);
        } else {
            for (long r = 0; r < out_h; ++r) {
                const char *sr = s0 + r * src_row;
                char *dr = d0 + r * dst_row;
                for (long q = 0; q < out_w; ++q)
                    memcpy(dr + q * px, sr + (out_w - 1 - q) * px, px);
            }
        }
    }
}
