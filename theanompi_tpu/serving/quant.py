"""int8 weight-only quantization for serving (ISSUE 6).

Matmul weights are the HBM resident set of an inference server; int8 halves
(vs bf16) or quarters (vs fp32) it, which is capacity for more KV-cache
blocks — i.e. more concurrent sequences — on the same chip.  The format
reuses the ``ring_int8`` exchange strategy's primitives
(:mod:`theanompi_tpu.ops.quant`): per-chunk fp32 scale + stochastic
rounding under an explicit PRNG key, so quantization is a seeded,
reproducible, zero-mean transform.

Quantized leaves become :class:`QuantizedTensor` pytree nodes (int8 payload
+ fp32 scales as children, shape/dtype static), so a quantized param tree
jits through the same prefill/decode step functions — the engine calls
:func:`dequantize_tree` INSIDE the compiled step, which keeps the int8
bytes resident and materializes fp32 weights only transiently per step
(XLA fuses the dequant into the consuming matmul's operand read).

Only matmul weights quantize: attention q/k/v/o and FFN ``w`` leaves, MoE
expert ``up_w``/``down_w`` stacks, and the LM head.  Embedding and position
tables (gathers, not matmuls), LayerNorm scale/bias, biases, and MoE gate
weights (tiny, routing-critical) stay in their checkpoint dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.quant import (  # noqa: F401  (QuantizedTensor
    QuantizedTensor,  # re-exported: it moved to ops/quant.py in ISSUE 18
    quantize_chunked,  # so the fused int8 kernel and the wire format it
)  # consumes live in one kernels-layer module

#: default elements per quantization chunk (one fp32 scale each): small
#: enough that a tiny test model gets real per-chunk granularity, large
#: enough that scale overhead stays < 0.1% at fp32
DEFAULT_CHUNK_ELEMS = 1024

#: leaf names that are matmul weights (see module docstring)
_MATMUL_LEAF_NAMES = ("w", "up_w", "down_w")
#: path components whose subtrees never quantize
_SKIP_COMPONENTS = ("embedding", "positionembedding", "gate")


def _should_quantize(path, leaf) -> bool:
    parts = [str(getattr(p, "key", p)) for p in path]
    if any(skip in part for part in parts for skip in _SKIP_COMPONENTS):
        return False
    if parts[-1] not in _MATMUL_LEAF_NAMES:
        return False
    return (hasattr(leaf, "dtype")
            and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
            and getattr(leaf, "ndim", 0) >= 2)


def quantize_tree(params, key, chunk_elems: int = DEFAULT_CHUNK_ELEMS,
                  predicate=_should_quantize):
    """Quantize the matmul-weight leaves of a param tree; -> (tree with
    :class:`QuantizedTensor` nodes, stats dict).  Deterministic in ``key``
    (each leaf folds its flat index into the stream)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out, n_q, bytes_before, bytes_after = [], 0, 0, 0
    for i, (path, leaf) in enumerate(flat):
        if predicate(path, leaf):
            q, scales = quantize_chunked(
                jnp.asarray(leaf), jax.random.fold_in(key, i), chunk_elems)
            qt = QuantizedTensor(q, scales, tuple(leaf.shape),
                                 jnp.asarray(leaf).dtype)
            out.append(qt)
            n_q += 1
            bytes_before += int(jnp.asarray(leaf).nbytes)
            bytes_after += qt.nbytes_quantized
        else:
            out.append(leaf)
    stats = {"quantized_leaves": n_q, "total_leaves": len(flat),
             "bytes_before": bytes_before, "bytes_after": bytes_after}
    return jax.tree_util.tree_unflatten(treedef, out), stats


def dequantize_tree(params, keep=None):
    """Materialize fp-typed weights from a (possibly) quantized tree.
    Identity on unquantized leaves; call INSIDE jit so XLA fuses the
    dequant into the consuming matmuls.

    ``keep`` (ISSUE 18): a predicate over :class:`QuantizedTensor` leaves
    to RETAIN quantized — the serving fast path keeps every leaf the
    fused int8 kernel can consume (``ops.quant.int8_matmul_supported``)
    and dequantizes only the stragglers (odd-vocab heads, 3D MoE expert
    stacks)."""

    def _leaf(leaf):
        if isinstance(leaf, QuantizedTensor):
            if keep is not None and keep(leaf):
                return leaf
            return leaf.dequantize()
        return leaf

    return jax.tree_util.tree_map(
        _leaf, params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def is_quantized_tree(params) -> bool:
    return any(isinstance(leaf, QuantizedTensor)
               for leaf in jax.tree_util.tree_leaves(
                   params, is_leaf=lambda x: isinstance(x, QuantizedTensor)))
