"""Prefix cache: a radix tree over full-block token chunks (ISSUE 17).

Under a multi-turn / shared-system-prompt traffic mix most prefill FLOPs
recompute K/V the paged pool already holds.  SGLang's RadixAttention
(Zheng et al., 2024) showed the fix at the right granularity for a paged
cache (vLLM, Kwon et al., SOSP 2023): index FULL cache blocks by the exact
``block_size``-token chunk they hold, chained parent->child — a path from
the root spells a prompt prefix, and the nodes along it name the block ids
whose K/V that prefix already computed.

Design points, in the order they bite:

- **Chunk keys, chained on the parent.**  Each node's children are keyed
  by the exact ``block_size``-token tuple of the child block.  The "chunk
  hash chained on the parent" is literally the dict's tuple hashing scoped
  per parent node — collision-SAFE (tuple equality decides, never the
  hash), so a match can never hand a request someone else's K/V.
- **Full blocks only.**  A partially filled block is never shared: the
  last (partial) block of any sequence stays exclusively owned
  (copy-on-write by construction — decode appends land only in blocks the
  request alloc'd itself), so a hit is always a whole number of blocks and
  the suffix prefill starts at a block boundary.
- **Refcount discipline.**  The tree holds ONE pool reference per node
  (:meth:`insert` transfers the caller's ref, or releases it when the
  chunk is already cached); :meth:`match` ``acquire``\\ s the matched
  blocks into the requesting sequence, so an eviction of one holder never
  invalidates another (:class:`theanompi_tpu.serving.kv_cache.BlockPool`).
- **LRU eviction of zero-ref leaves.**  When the pool runs dry the
  scheduler asks the tree to give blocks back; only LEAF nodes whose block
  the tree is the SOLE holder of (``pool.ref == 1``) are evictable, oldest
  ``last_use`` first — a parent becomes evictable once its children are
  gone, so the tree drains deepest-first.
- **Params-version stamp.**  Cached K/V is only valid under the weights
  that computed it: a live rollout (``engine.swap_params`` /
  ``restore_params``, ISSUE 14) bumps the engine's ``params_version``, and
  the scheduler invalidates the whole tree on mismatch.  Without the stamp
  the cache silently serves stale K/V across a weight swap — the negative
  test in ``tests/test_prefix_cache.py`` proves that bug exists.

Host-side and single-threaded like the scheduler that owns it; LRU ticks
come from a monotone counter, not the wall clock, so replays are
deterministic.
"""

from __future__ import annotations


class _Node:
    """One cached full block: the chunk that fills it, the block id the
    tree's reference pins, and the LRU stamp."""

    __slots__ = ("chunk", "block", "parent", "children", "last_use")

    def __init__(self, chunk, block, parent):
        self.chunk = chunk
        self.block = block
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.last_use = 0


class PrefixCache:
    """Radix tree mapping prompt prefixes to cached KV block ids.

    Owns one :class:`BlockPool` reference per cached block; all methods
    keep the pool and the tree consistent — no caller ever frees a block
    the tree still names.
    """

    def __init__(self, pool, block_size: int):
        self.pool = pool
        self.block_size = int(block_size)
        self._root = _Node(None, None, None)
        self._clock = 0  # monotone LRU tick (deterministic, not wall time)
        self.params_version: int | None = None
        self.n_nodes = 0

    # -- invalidation ---------------------------------------------------------
    def check_version(self, version: int) -> bool:
        """Stamp check against the engine's ``params_version``; on mismatch
        the WHOLE tree invalidates (cached K/V was computed under the old
        weights — silently wrong under the new ones).  -> True when an
        invalidation happened."""
        if self.params_version == version:
            return False
        stale = self.params_version is not None and self.n_nodes > 0
        if stale:
            self.invalidate()
        self.params_version = version
        return stale

    def invalidate(self) -> int:
        """Release every tree-held block back to the pool (refcount
        decrement — blocks live requests still hold stay live for them)
        and clear the tree.  -> number of nodes dropped."""
        dropped = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.pool.free([node.block])
            dropped += 1
        self._root.children.clear()
        self.n_nodes = 0
        return dropped

    # -- lookup ---------------------------------------------------------------
    def match(self, tokens) -> list[int]:
        """Longest cached full-block prefix of ``tokens``; -> the matched
        block ids IN SEQUENCE ORDER, each ``acquire``\\ d for the caller
        (the caller now co-owns them and must ``pool.free`` them like its
        own).  Capped so at least ONE token stays uncached — prefill must
        compute the last real position's logits to sample the next token.
        """
        bs = self.block_size
        max_blocks = max(len(tokens) - 1, 0) // bs
        node, nodes = self._root, []
        while len(nodes) < max_blocks:
            i = len(nodes) * bs
            child = node.children.get(tuple(tokens[i:i + bs]))
            if child is None:
                break
            nodes.append(child)
            node = child
        if not nodes:
            return []
        blocks = [n.block for n in nodes]
        self.pool.acquire(blocks)
        self._clock += 1
        for n in nodes:
            n.last_use = self._clock
        return blocks

    # -- insertion ------------------------------------------------------------
    def insert(self, tokens, blocks) -> int:
        """Offer a finished/evicted sequence's FULL blocks back to the
        tree: ``tokens`` (length a multiple of ``block_size``) are the
        cached positions, ``blocks`` the ids backing them in order.  The
        caller's reference on each block TRANSFERS to the tree when the
        chunk is new, and is released when the chunk is already cached
        (dedup — the tree keeps its existing copy).  -> new nodes added."""
        bs = self.block_size
        if len(tokens) != len(blocks) * bs:
            raise ValueError(
                f"insert: {len(tokens)} tokens != {len(blocks)} full "
                f"blocks x block_size {bs}")
        node, added = self._root, 0
        self._clock += 1
        for i, block in enumerate(blocks):
            chunk = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, block, node)
                node.children[chunk] = child
                self.n_nodes += 1
                added += 1
            else:
                # chunk already cached: release the caller's ref on its
                # copy (the tree's copy — possibly the very same block id
                # the request acquired at admission — stays pinned)
                self.pool.free([block])
            child.last_use = self._clock
            node = child
        return added

    # -- eviction -------------------------------------------------------------
    def evict(self, n: int) -> int:
        """Free up to ``n`` blocks back to the pool, LRU leaves first, and
        ONLY leaves the tree is the sole holder of (``pool.ref == 1``) — a
        block a live request shares is never invalidated under it.  A
        freed leaf may expose its parent as the next candidate.  -> blocks
        actually freed."""
        freed = 0
        while freed < n:
            victim = None
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                if node.children:
                    stack.extend(node.children.values())
                elif self.pool.ref(node.block) == 1 and (
                        victim is None or node.last_use < victim.last_use):
                    victim = node
            if victim is None:
                break
            del victim.parent.children[victim.chunk]
            self.pool.free([victim.block])
            self.n_nodes -= 1
            freed += 1
        return freed
