"""Durable serving lifecycle files: request log, queue, live snapshot.

Three small on-disk contracts shared by a serving replica and the
multi-replica router (ISSUE 19) — deliberately **stdlib-only** and free
of engine/scheduler imports, so the router layer can consume them
without touching serving machinery (the ``serve_lifecycle`` layer in
``analysis/layers.py``):

- ``REQUESTS.jsonl`` (ISSUE 14): the durable witness that every request
  id reached exactly one terminal state across ALL attempts.  The
  replica appends one JSON line the moment a request turns terminal
  (``done|expired|shed|failed``); a restarted attempt reads the log back
  to skip already-answered ids, and the router tails it for terminal
  records (first record per rid wins across replicas).
- ``queue.jsonl`` (ISSUE 19): the per-replica durable admission queue.
  The router appends request entries (plain dicts: rid, prompt, token
  budget, ``enq_wall``); the replica polls it by byte offset and serves
  in order.  A ``{"op": "drain"}`` sentinel asks the replica to drain
  and exit clean — durable, so a replica that restarts mid-drain still
  drains.
- ``SERVE_SNAPSHOT.json`` (ISSUE 19 satellite): the replica's live load
  published atomically (tmp → ``os.replace``) every N scheduler steps,
  so the router balances on *current* backlog/rate instead of the
  end-of-drive SERVE.json.

Append-mode JSONL files are flushed per line: a SIGKILL can lose at most
the in-flight line, and readers tolerate (skip) a torn tail.  Byte-offset
tailing (:func:`read_jsonl_since`) never consumes a line that does not
yet end in a newline — a half-written tail is simply "not there yet".
"""

from __future__ import annotations

import json
import os
import time

REQUESTS_LOG = "REQUESTS.jsonl"
QUEUE_LOG = "queue.jsonl"
SNAPSHOT = "SERVE_SNAPSHOT.json"

#: queue sentinel asking the replica to drain and exit clean
DRAIN_OP = "drain"


class RequestLog:
    """Append-only terminal-state writer for one serving attempt."""

    def __init__(self, path: str, attempt: int = 1):
        self.path = path
        self.attempt = attempt
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # lint: atomic-publish-ok — append-only JSONL request log; the
        # harvest parses per line and drops an unparseable torn tail
        self._f = open(path, "a")

    def record(self, req, **extra) -> None:
        """One line per terminal request: rid, state, reason, tokens.

        The replica-side latency breakdown rides along when known
        (ISSUE 19): ``ttft_ms`` from the request's own submit/first-token
        stamps, plus caller extras (``queue_wait_ms`` — the durable-queue
        dwell the replica never sees in perf-counter time) so the router
        can aggregate router-visible TTFT without a shared clock.
        """
        rec = {"rid": req.rid, "state": req.state,
               "reason": req.reason,
               "n_generated": len(req.generated),
               "attempt": self.attempt}
        if req.t_submit is not None and req.t_first_token is not None:
            rec["ttft_ms"] = round(
                (req.t_first_token - req.t_submit) * 1e3, 3)
        rec.update(extra)
        json.dump(rec, self._f)
        self._f.write("\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def terminal_rids(path: str) -> set[int]:
    """Request ids already recorded terminal (any attempt); a restarted
    replica excludes them from its request stream.  Partial trailing
    lines (the SIGKILL race) are skipped, not fatal."""
    return {int(rec["rid"]) for rec in terminal_records(path)}


def terminal_records(path: str) -> list[dict]:
    """Every terminal record in a REQUESTS.jsonl, in append order (all
    attempts).  Torn/partial lines are skipped, missing file -> []."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn final line from a killed attempt
                if isinstance(rec, dict) and "rid" in rec:
                    out.append(rec)
    except OSError:
        return []
    return out


# -- the durable per-replica admission queue (ISSUE 19) -----------------------

def append_queue(path: str, entries: list[dict]) -> None:
    """Append request entries (or the drain sentinel) to a replica's
    durable queue.  One JSON line per entry, flushed once at the end —
    the reader side never consumes a line without its newline, so a
    torn append is invisible rather than corrupt."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    # lint: atomic-publish-ok — append-only JSONL queue; read_jsonl_since
    # only consumes newline-complete lines, a torn tail stays pending
    with open(path, "a") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")


def drain_entry() -> dict:
    return {"op": DRAIN_OP}


def request_drain(path: str) -> None:
    """Ask the replica owning ``path`` to drain and exit clean (durable:
    a replica restarting mid-drain re-reads the sentinel)."""
    append_queue(path, [drain_entry()])


def read_jsonl_since(path: str, offset: int = 0) -> tuple[list[dict], int]:
    """Tail a JSONL file from byte ``offset``; -> (new records, new
    offset).  Only newline-complete lines are consumed — a half-written
    tail keeps the offset parked before it (it is "not there yet", and
    the writer's per-line flush means it will complete or never will).
    A complete-but-unparseable line (a torn write the process died past)
    is skipped AND consumed: it can never become valid.  Missing file ->
    ([], offset)."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return [], offset
    out: list[dict] = []
    consumed = 0
    while True:
        nl = data.find(b"\n", consumed)
        if nl < 0:
            break
        line = data[consumed:nl]
        consumed = nl + 1
        if not line.strip():
            continue
        try:
            rec = json.loads(line.decode("utf-8", errors="replace"))
        except ValueError:
            continue  # torn-but-terminated line: skip, never valid
        if isinstance(rec, dict):
            out.append(rec)
    return out, offset + consumed


# -- the live load snapshot (ISSUE 19 satellite) ------------------------------

def publish_snapshot(path: str, snap: dict) -> None:
    """Atomically publish a replica's live-load snapshot (tmp →
    ``os.replace``): the router reads either the previous generation or
    this one, never a torn file."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path + ".tmp", "w") as f:
        json.dump(snap, f)
    os.replace(path + ".tmp", path)


def read_snapshot(path: str) -> dict | None:
    """The last published snapshot, or None (absent/unreadable — the
    replica may not have published yet; callers fall back to their own
    bookkeeping)."""
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return None
    return snap if isinstance(snap, dict) else None


class SnapshotPublisher:
    """Throttled snapshot publishing for a serving drive loop.

    Publishes when either ``every_steps`` scheduler steps elapsed since
    the last publish or ``min_interval_s`` wall seconds did (the
    idle-loop case: a replica with an empty queue still refreshes its
    ``updated`` stamp so the router can tell live-and-idle from dead).
    """

    def __init__(self, path: str, every_steps: int = 8,
                 min_interval_s: float = 0.25):
        self.path = path
        self.every_steps = max(1, int(every_steps))
        self.min_interval_s = float(min_interval_s)
        self._last_step = -1
        self._last_wall = 0.0

    def maybe(self, snap_fn, n_steps: int, force: bool = False) -> bool:
        """Publish ``snap_fn()`` when due; -> whether it published."""
        now = time.time()  # lint: wall-ok — cross-process freshness stamp
        due = (force
               or n_steps - self._last_step >= self.every_steps
               or now - self._last_wall >= self.min_interval_s)
        if not due:
            return False
        self._last_step = n_steps
        self._last_wall = now
        publish_snapshot(self.path, snap_fn())
        return True
