"""Durable request terminal-state log (ISSUE 14).

A supervised serving replica can crash and restart mid-drive; the
in-memory results dict dies with it.  ``REQUESTS.jsonl`` is the durable
witness that every request id reached exactly one terminal state across
ALL attempts: the replica appends one JSON line the moment a request
turns terminal (``done|expired|shed|failed``), and a restarted attempt
reads the log back to skip already-answered ids instead of re-serving
them — the "zero requests lost" half of the chaos acceptance test.

Plain append-mode JSONL, flushed per line: a SIGKILL can lose at most the
in-flight line, and a lost line only means the restarted attempt serves
that request again (idempotent for the synthetic open-loop driver, whose
request streams are seed-deterministic).
"""

from __future__ import annotations

import json
import os

REQUESTS_LOG = "REQUESTS.jsonl"


class RequestLog:
    """Append-only terminal-state writer for one serving attempt."""

    def __init__(self, path: str, attempt: int = 1):
        self.path = path
        self.attempt = attempt
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # lint: atomic-publish-ok — append-only JSONL request log; the
        # harvest parses per line and drops an unparseable torn tail
        self._f = open(path, "a")

    def record(self, req) -> None:
        """One line per terminal request: rid, state, reason, tokens."""
        json.dump({"rid": req.rid, "state": req.state,
                   "reason": req.reason,
                   "n_generated": len(req.generated),
                   "attempt": self.attempt}, self._f)
        self._f.write("\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def terminal_rids(path: str) -> set[int]:
    """Request ids already recorded terminal (any attempt); a restarted
    replica excludes them from its regenerated synthetic stream.  Partial
    trailing lines (the SIGKILL race) are skipped, not fatal."""
    rids: set[int] = set()
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn final line from a killed attempt
                if isinstance(rec, dict) and "rid" in rec:
                    rids.add(int(rec["rid"]))
    except OSError:
        return set()
    return rids
