"""Continuous batching: admission, per-step join/evict, preemption (Orca).

Static batching serves a batch until its LONGEST member finishes — every
other slot idles at the tail, and new arrivals wait for the whole batch.
Continuous batching (Yu et al., OSDI 2022) rebuilds the batch every
iteration instead: finished sequences evict and free their cache blocks at
the step they finish, queued requests join (prefill) the moment a slot and
blocks are available, and the decode step always runs the full fixed-shape
batch with inactive slots masked (so the compiled program never changes).

Block-pool pressure resolves by **preempting the longest active sequence**
(free all its blocks, push the request back to the queue front): longest
frees the most blocks per eviction, and its recompute-prefill is the one
most amortized by batching.  Preemption is recompute-style (vLLM's default):
the re-prefilled prefix is ``prompt + tokens generated so far``, and because
sampling keys derive from ``(request id, position)`` only
(:mod:`theanompi_tpu.serving.engine`), the replayed sequence continues
exactly where it left off — greedy or sampled.

All telemetry flows through the names registered in
:mod:`theanompi_tpu.telemetry.metrics` (``SERVE_*``); latency percentiles
are also tracked host-side so the SERVE report works with telemetry off.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from dataclasses import field

import numpy as np

from theanompi_tpu.serving.kv_cache import BlockPool, PagedKVCache, blocks_for
from theanompi_tpu.telemetry.metrics import (  # registered names (ISSUE 6)
    SERVE_COUNTERS,
    SERVE_HISTOGRAMS,
    SERVE_INSTANTS,
    SERVE_SPANS,
)

_SPAN_PREFILL, _SPAN_DECODE = SERVE_SPANS
_INST_ADMIT, _INST_PREEMPT, _INST_FINISH = SERVE_INSTANTS
_HIST_TOKEN_MS, _HIST_TTFT_MS = SERVE_HISTOGRAMS
_CNT_TOKENS, _CNT_PREEMPTIONS, _CNT_REQUESTS = SERVE_COUNTERS


@dataclasses.dataclass
class Request:
    """One generation request.  ``arrival_s`` is the open-loop arrival
    offset (seconds from traffic start) — the driver submits the request
    when the clock passes it, regardless of server state (open loop)."""

    rid: int
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    arrival_s: float = 0.0
    # -- filled in by the scheduler -----------------------------------------
    generated: list[int] = field(default_factory=list)
    n_preemptions: int = 0
    t_submit: float | None = None
    t_first_token: float | None = None
    t_done: float | None = None


class Scheduler:
    """Continuous-batching scheduler over one :class:`InferenceEngine`."""

    def __init__(self, engine, telemetry=None, eos_token: int | None = None):
        self.engine = engine
        self.telemetry = telemetry
        self.eos_token = eos_token
        self.pool = BlockPool(engine.num_blocks)
        self.queue: deque[Request] = deque()
        b, nb = engine.max_batch, engine.max_blocks_per_seq
        self.slots: list[Request | None] = [None] * b
        self._blocks: list[list[int]] = [[] for _ in range(b)]
        self._tables = np.zeros((b, nb), np.int32)
        self._lengths = np.zeros((b,), np.int32)
        self._tokens = np.zeros((b,), np.int32)
        self._temps = np.zeros((b,), np.float32)
        self._rids = np.zeros((b,), np.int32)
        self.n_steps = 0
        self.token_ms: list[float] = []
        self.ttft_ms: list[float] = []
        self.n_preemptions = 0

    # -- introspection -------------------------------------------------------
    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self.slots)

    @property
    def idle(self) -> bool:
        return self.n_active == 0 and not self.queue

    # -- submission ----------------------------------------------------------
    def submit(self, req: Request) -> None:
        total = len(req.prompt) + req.max_new_tokens
        if not req.prompt:
            raise ValueError(f"request {req.rid}: empty prompt")
        if total > self.engine.max_context:
            raise ValueError(
                f"request {req.rid}: prompt+max_new_tokens = {total} > "
                f"max context {self.engine.max_context}")
        if blocks_for(total, self.engine.block_size) > self.pool.num_blocks - 1:
            raise ValueError(
                f"request {req.rid}: needs "
                f"{blocks_for(total, self.engine.block_size)} blocks, pool "
                f"has {self.pool.num_blocks - 1} — num_blocks too small for "
                f"even one sequence")
        req.t_submit = time.perf_counter()
        self.queue.append(req)

    # -- internals -----------------------------------------------------------
    def _emit(self, name: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.instant(name, **fields)

    def _clear_slot(self, slot: int) -> None:
        self.slots[slot] = None
        self._blocks[slot] = []
        self._tables[slot, :] = PagedKVCache.NULL_BLOCK
        self._lengths[slot] = 0
        self._tokens[slot] = 0
        self._temps[slot] = 0.0
        self._rids[slot] = 0

    def _finish(self, slot: int, finished: list[Request]) -> None:
        req = self.slots[slot]
        self.pool.free(self._blocks[slot])
        self._clear_slot(slot)
        req.t_done = time.perf_counter()
        if self.telemetry is not None:
            self.telemetry.count(_CNT_REQUESTS)
        self._emit(_INST_FINISH, request=req.rid,
                   generated=len(req.generated))
        finished.append(req)

    def _preempt(self, slot: int) -> None:
        req = self.slots[slot]
        self.pool.free(self._blocks[slot])
        self._clear_slot(slot)
        req.n_preemptions += 1
        self.n_preemptions += 1
        if self.telemetry is not None:
            self.telemetry.count(_CNT_PREEMPTIONS)
        self._emit(_INST_PREEMPT, request=req.rid,
                   held_tokens=len(req.prompt) + len(req.generated))
        self.queue.appendleft(req)  # rejoin first: it already holds work

    def _admit(self, finished: list[Request]) -> None:
        """Prefill queued requests into free slots while blocks last."""
        while self.queue:
            try:
                slot = self.slots.index(None)
            except ValueError:
                return
            req = self.queue[0]
            prefix = req.prompt + req.generated
            need = blocks_for(len(prefix), self.engine.block_size)
            row = self.pool.alloc(need)
            if row is None:
                if self.n_active == 0:
                    # cannot happen for a submit()-validated request unless
                    # the pool leaked; fail loudly rather than spin forever
                    raise RuntimeError(
                        f"request {req.rid} cannot be admitted into an "
                        f"EMPTY server ({need} blocks needed, "
                        f"{self.pool.free_blocks} free)")
                return
            self.queue.popleft()
            span = (self.telemetry.span(_SPAN_PREFILL, request=req.rid,
                                        prompt=len(prefix), slot=slot)
                    if self.telemetry is not None else None)
            if span is not None:
                span.__enter__()
            try:
                # prefill returns a host int — already materialized, so the
                # span close measures execution, not dispatch
                tok, _ = self.engine.prefill(row, prefix, req.temperature,
                                             req.rid)
            finally:
                if span is not None:
                    span.__exit__(None, None, None)
            now = time.perf_counter()
            if req.t_first_token is None:
                req.t_first_token = now
                ttft = (now - req.t_submit) * 1e3
                self.ttft_ms.append(ttft)
                if self.telemetry is not None:
                    self.telemetry.observe(_HIST_TTFT_MS, ttft)
            req.generated.append(tok)
            if self.telemetry is not None:
                self.telemetry.count(_CNT_TOKENS)
            self._emit(_INST_ADMIT, request=req.rid, slot=slot,
                       prefix=len(prefix), blocks=need,
                       resumed=req.n_preemptions > 0)
            self.slots[slot] = req
            self._blocks[slot] = row
            self._tables[slot, :] = PagedKVCache.NULL_BLOCK
            self._tables[slot, :need] = row
            self._lengths[slot] = len(prefix)
            self._tokens[slot] = tok
            self._temps[slot] = req.temperature
            self._rids[slot] = req.rid
            if self._done(req):
                self._finish(slot, finished)

    def _done(self, req: Request) -> bool:
        if len(req.generated) >= req.max_new_tokens:
            return True
        return (self.eos_token is not None
                and req.generated
                and req.generated[-1] == self.eos_token)

    def _ensure_capacity(self) -> None:
        """Every active slot whose NEXT token starts a new cache block must
        get one before the decode step; exhaustion preempts the longest
        active sequence and retries."""
        for slot in range(self.engine.max_batch):
            if self.slots[slot] is None:
                continue
            if self._lengths[slot] % self.engine.block_size != 0:
                continue
            while self.slots[slot] is not None:
                got = self.pool.alloc(1)
                if got is not None:
                    n_used = blocks_for(int(self._lengths[slot]),
                                        self.engine.block_size)
                    self._blocks[slot].extend(got)
                    self._tables[slot, n_used] = got[0]
                    break
                victim = max(
                    (s for s in range(self.engine.max_batch)
                     if self.slots[s] is not None),
                    key=lambda s: int(self._lengths[s]))
                self._preempt(victim)

    def step(self) -> list[Request]:
        """One scheduler iteration: admit, secure blocks, decode the fixed
        batch, account the new tokens; -> the requests finished this step."""
        finished: list[Request] = []
        self._admit(finished)
        if self.n_active == 0:
            return finished
        self._ensure_capacity()
        active = [s for s in range(self.engine.max_batch)
                  if self.slots[s] is not None]
        if not active:  # capacity pressure preempted everyone admitted
            return finished
        span = None
        if self.telemetry is not None:
            span = self.telemetry.span(
                _SPAN_DECODE, step=self.n_steps, batch=len(active),
                requests=[int(self._rids[s]) for s in active])
            span.__enter__()
        t0 = time.perf_counter()
        try:
            nxt, _ = self.engine.decode(self._tables, self._lengths,
                                        self._tokens, self._temps,
                                        self._rids)
        finally:
            if span is not None:  # decode() returned host arrays: fenced
                span.__exit__(None, None, None)
        step_ms = (time.perf_counter() - t0) * 1e3
        self.n_steps += 1
        for slot in active:
            req = self.slots[slot]
            self._lengths[slot] += 1  # the fed token is now cached
            tok = int(nxt[slot])
            req.generated.append(tok)
            self._tokens[slot] = tok
            self.token_ms.append(step_ms)
            if self.telemetry is not None:
                self.telemetry.count(_CNT_TOKENS)
                self.telemetry.observe(_HIST_TOKEN_MS, step_ms)
            if self._done(req):
                self._finish(slot, finished)
        if self.telemetry is not None and self.n_steps % 16 == 0:
            # periodic flush (ISSUE 13): the ttft/token histograms must
            # reach the event stream while serving is LIVE — the health
            # monitor's SLO detector reads p99 from ``metrics`` events,
            # and a flush only at shutdown would blind it
            self.telemetry.flush_metrics(step=self.n_steps)
        return finished


def run_open_loop(scheduler: Scheduler, requests: list[Request],
                  poll_s: float = 0.002) -> tuple[dict[int, Request], float]:
    """Drive synthetic open-loop traffic: each request is submitted when the
    wall clock passes its ``arrival_s`` (arrivals never wait on the server —
    that is what makes the load open-loop), then the scheduler steps until
    every request finishes.  -> ({rid: finished request}, wall seconds)."""
    pending = deque(sorted(requests, key=lambda r: r.arrival_s))
    results: dict[int, Request] = {}
    t0 = time.perf_counter()
    while len(results) < len(requests):
        now = time.perf_counter() - t0
        while pending and pending[0].arrival_s <= now:
            scheduler.submit(pending.popleft())
        if scheduler.idle:
            if pending:
                time.sleep(min(poll_s, max(pending[0].arrival_s - now, 0.0)))
            continue
        for req in scheduler.step():
            results[req.rid] = req
    return results, time.perf_counter() - t0


def serve_report(results: dict[int, Request], wall_s: float,
                 scheduler: Scheduler) -> dict:
    """The SERVE.json artifact: throughput + latency percentiles."""
    eng = scheduler.engine
    n_tokens = sum(len(r.generated) for r in results.values())

    def pct(xs):
        if not xs:
            return {}
        arr = np.asarray(xs)
        return {"p50": round(float(np.percentile(arr, 50)), 3),
                "p99": round(float(np.percentile(arr, 99)), 3)}

    return {
        "metric": "serve_tokens_per_sec",
        "value": round(n_tokens / wall_s, 2) if wall_s > 0 else 0.0,
        "unit": "tokens/sec",
        "requests": len(results),
        "generated_tokens": n_tokens,
        "wall_s": round(wall_s, 3),
        "ttft_ms": pct(scheduler.ttft_ms),
        "token_ms": pct(scheduler.token_ms),
        "preemptions": scheduler.n_preemptions,
        "decode_steps": scheduler.n_steps,
        "quantized_int8": eng.quantized,
        "config": {
            "block_size": eng.block_size,
            "num_blocks": eng.num_blocks,
            "max_batch": eng.max_batch,
            "max_context": eng.max_context,
        },
    }
