"""Continuous batching: admission, per-step join/evict, preemption (Orca).

Static batching serves a batch until its LONGEST member finishes — every
other slot idles at the tail, and new arrivals wait for the whole batch.
Continuous batching (Yu et al., OSDI 2022) rebuilds the batch every
iteration instead: finished sequences evict and free their cache blocks at
the step they finish, queued requests join (prefill) the moment a slot and
blocks are available, and the decode step always runs the full fixed-shape
batch with inactive slots masked (so the compiled program never changes).

Block-pool pressure resolves by **preempting the longest active sequence**
(free all its blocks, push the request back to the queue front): longest
frees the most blocks per eviction, and its recompute-prefill is the one
most amortized by batching.  Preemption is recompute-style (vLLM's default):
the re-prefilled prefix is ``prompt + tokens generated so far``, and because
sampling keys derive from ``(request id, position)`` only
(:mod:`theanompi_tpu.serving.engine`), the replayed sequence continues
exactly where it left off — greedy or sampled.

Request lifecycle (ISSUE 14): every request ends in exactly one typed
terminal state —

- ``done``     — generation completed (max tokens or EOS);
- ``expired``  — a per-request deadline (``ttft_deadline_ms`` before the
  first token, ``total_deadline_ms`` overall) passed; checked at the queue
  front BEFORE a prefill is burned (a preempted-and-requeued request past
  its deadline expires immediately) and between scheduler steps for both
  queued and active requests;
- ``shed``     — refused at admission: load shedding (the queue's backlog
  at the recently measured token rate cannot meet the request's deadline)
  or a graceful drain in progress;
- ``failed``   — the livelock guard: a request that can never fit the KV
  pool is refused with a typed terminal state instead of crashing the
  server or preempting forever.

All telemetry flows through the names registered in
:mod:`theanompi_tpu.telemetry.metrics` (``SERVE_*``); latency percentiles
are also tracked host-side so the SERVE report works with telemetry off.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from dataclasses import field

import numpy as np

from theanompi_tpu.resilience.faults import FaultInjected, FaultPlan
from theanompi_tpu.serving.kv_cache import BlockPool, PagedKVCache, blocks_for
from theanompi_tpu.serving.lifecycle import DRAIN_OP, read_jsonl_since
from theanompi_tpu.serving.prefix_cache import PrefixCache
from theanompi_tpu.telemetry.metrics import (  # registered names (ISSUE 6)
    SERVE_COUNTERS,
    SERVE_HISTOGRAMS,
    SERVE_INSTANTS,
    SERVE_LIFECYCLE_COUNTERS,
    SERVE_LIFECYCLE_INSTANTS,
    SERVE_PREFIX_COUNTERS,
    SERVE_PREFIX_INSTANTS,
    SERVE_SPANS,
)

_SPAN_PREFILL, _SPAN_DECODE = SERVE_SPANS
_INST_ADMIT, _INST_PREEMPT, _INST_FINISH = SERVE_INSTANTS
_HIST_TOKEN_MS, _HIST_TTFT_MS = SERVE_HISTOGRAMS
_CNT_TOKENS, _CNT_PREEMPTIONS, _CNT_REQUESTS = SERVE_COUNTERS
_INST_EXPIRE, _INST_SHED, _INST_FAIL, _INST_DRAIN = SERVE_LIFECYCLE_INSTANTS
_CNT_EXPIRED, _CNT_SHED, _CNT_FAILED = SERVE_LIFECYCLE_COUNTERS
_CNT_PREFIX_HIT, _CNT_PREFIX_TOKENS = SERVE_PREFIX_COUNTERS
(_INST_PREFIX_INVALIDATE,) = SERVE_PREFIX_INSTANTS

#: every request ends in exactly one of these (ISSUE 14)
TERMINAL_STATES = ("done", "expired", "shed", "failed")


@dataclasses.dataclass
class Request:
    """One generation request.  ``arrival_s`` is the open-loop arrival
    offset (seconds from traffic start) — the driver submits the request
    when the clock passes it, regardless of server state (open loop).
    Deadlines are milliseconds from ``t_submit`` (None = no deadline)."""

    rid: int
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    arrival_s: float = 0.0
    ttft_deadline_ms: float | None = None
    total_deadline_ms: float | None = None
    # -- filled in by the scheduler -----------------------------------------
    state: str = "queued"       # queued | active | done|expired|shed|failed
    reason: str | None = None   # why a non-done terminal state was reached
    generated: list[int] = field(default_factory=list)
    n_preemptions: int = 0
    t_submit: float | None = None
    t_first_token: float | None = None
    t_done: float | None = None

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES


class Scheduler:
    """Continuous-batching scheduler over one :class:`InferenceEngine`.

    ``shed=True`` enables admission-time load shedding for requests that
    carry a deadline; ``fault_plan`` arms the ``serve:raise``/
    ``serve:stall`` chaos sites at decode-step ordinals (constructor-only
    here — the CLI threads the ``THEANOMPI_FAULT_PLAN`` env through);
    ``prefix_cache=True`` turns on the radix prefix cache over the block
    pool (ISSUE 17): admissions reuse cached full-block prompt-prefix K/V
    via partial prefill, finished/evicted sequences offer their full
    blocks back, and the whole tree invalidates when the engine's
    ``params_version`` moves (live rollout).  Token streams are unchanged
    by the cache — bit-equal to ``prefix_cache=False`` — only the prefill
    work is.
    """

    def __init__(self, engine, telemetry=None, eos_token: int | None = None,
                 shed: bool = False,
                 fault_plan: FaultPlan | None = None,
                 prefix_cache: bool = False):
        self.engine = engine
        self.telemetry = telemetry
        self.eos_token = eos_token
        self.shed = shed
        self.fault_plan = fault_plan
        self.pool = BlockPool(engine.num_blocks)
        # ISSUE 17: radix prefix cache over the pool — OFF by default (the
        # cache-OFF token streams are the bit-equality reference)
        self.prefix_cache = (PrefixCache(self.pool, engine.block_size)
                             if prefix_cache else None)
        self.n_prefix_hits = 0
        self.n_prefix_lookups = 0
        self.prefix_tokens_saved = 0
        self.queue: deque[Request] = deque()
        b, nb = engine.max_batch, engine.max_blocks_per_seq
        self.slots: list[Request | None] = [None] * b
        self._blocks: list[list[int]] = [[] for _ in range(b)]
        self._tables = np.zeros((b, nb), np.int32)
        self._lengths = np.zeros((b,), np.int32)
        self._tokens = np.zeros((b,), np.int32)
        self._temps = np.zeros((b,), np.float32)
        self._rids = np.zeros((b,), np.int32)
        self.n_steps = 0
        self.token_ms: list[float] = []
        self.step_ms: list[float] = []  # one entry per decode step
        self.ttft_ms: list[float] = []
        self.n_preemptions = 0
        self.n_done = 0
        self.n_expired = 0
        self.n_shed = 0
        self.n_failed = 0
        self.draining = False
        # recent decode throughput: (host time, tokens emitted that step),
        # the load-shedding estimator's evidence window
        self._rate: deque[tuple[float, int]] = deque(maxlen=64)

    # -- introspection -------------------------------------------------------
    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self.slots)

    @property
    def idle(self) -> bool:
        return self.n_active == 0 and not self.queue

    def recent_token_rate(self) -> float | None:
        """Decoded tokens/sec over the recent window; None until at least
        4 decode steps spanning a measurable interval exist (shedding
        never fires on guesswork)."""
        if len(self._rate) < 4:
            return None
        span = self._rate[-1][0] - self._rate[0][0]
        if span <= 1e-6:
            return None
        return sum(n for _, n in self._rate) / span

    def _backlog_tokens(self) -> int:
        """Tokens the server still owes the queue + active slots."""
        owed = 0
        for req in list(self.queue):
            owed += max(req.max_new_tokens - len(req.generated), 0)
        for req in self.slots:
            if req is not None:
                owed += max(req.max_new_tokens - len(req.generated), 0)
        return owed

    def snapshot(self) -> dict:
        """Live load for the router's balancer (ISSUE 19 satellite):
        backlog, recent rate, terminal tallies, prefix-hit rate.  Plain
        host ints/floats only — this dict goes straight through
        :func:`theanompi_tpu.serving.lifecycle.publish_snapshot`."""
        rate = self.recent_token_rate()
        return {
            # wall (not perf_counter) so the ROUTER side can judge
            # freshness across processes
            "updated": time.time(),  # lint: wall-ok — cross-process stamp
            "backlog_tokens": self._backlog_tokens(),
            "queue_len": len(self.queue),
            "n_active": self.n_active,
            "token_rate": round(rate, 3) if rate is not None else None,
            "decode_steps": self.n_steps,
            "n_done": self.n_done,
            "n_expired": self.n_expired,
            "n_shed": self.n_shed,
            "n_failed": self.n_failed,
            "draining": self.draining,
            "prefix_hit_rate": (
                round(self.n_prefix_hits / self.n_prefix_lookups, 4)
                if self.n_prefix_lookups else 0.0),
        }

    # -- submission ----------------------------------------------------------
    def submit(self, req: Request) -> bool:
        """Queue ``req``; -> True when admitted, False when it was SHED
        (a typed terminal state — load shedding or a drain in progress).
        Structurally invalid requests still raise ValueError."""
        total = len(req.prompt) + req.max_new_tokens
        if not req.prompt:
            raise ValueError(f"request {req.rid}: empty prompt")
        if total > self.engine.max_context:
            raise ValueError(
                f"request {req.rid}: prompt+max_new_tokens = {total} > "
                f"max context {self.engine.max_context}")
        if blocks_for(total, self.engine.block_size) > self.pool.num_blocks - 1:
            raise ValueError(
                f"request {req.rid}: needs "
                f"{blocks_for(total, self.engine.block_size)} blocks, pool "
                f"has {self.pool.num_blocks - 1} — num_blocks too small for "
                f"even one sequence")
        req.t_submit = time.perf_counter()
        if self.draining:
            self.mark_shed(req, "draining")
            return False
        if self.shed:
            est_ms = self._shed_estimate_ms(req)
            if est_ms is not None:
                self.mark_shed(
                    req, f"backlog needs ~{est_ms:.0f}ms at the recent "
                    f"token rate, past the deadline", est_wait_ms=est_ms)
                return False
        req.state = "queued"
        self.queue.append(req)
        return True

    def _shed_estimate_ms(self, req: Request) -> float | None:
        """Estimated wait (ms) when it provably exceeds the request's
        deadline budget, else None (admit).  Deadline-less requests are
        never shed; neither is anything before the rate is measurable."""
        budget = min((d for d in (req.ttft_deadline_ms,
                                  req.total_deadline_ms) if d is not None),
                     default=None)
        if budget is None:
            return None
        rate = self.recent_token_rate()
        if rate is None or rate <= 0:
            return None
        est_ms = self._backlog_tokens() / rate * 1e3
        return est_ms if est_ms > budget else None

    # -- internals -----------------------------------------------------------
    def _emit(self, name: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.instant(name, **fields)

    def _clear_slot(self, slot: int) -> None:
        self.slots[slot] = None
        self._blocks[slot] = []
        self._tables[slot, :] = PagedKVCache.NULL_BLOCK
        self._lengths[slot] = 0
        self._tokens[slot] = 0
        self._temps[slot] = 0.0
        self._rids[slot] = 0

    def _evict(self, slot: int) -> Request:
        """Release a slot's blocks.  With the prefix cache on, the FULL
        blocks are offered back to the radix tree first (their K/V is
        complete and valid — a multi-turn follow-up or this request's own
        recompute-prefill hits them); the partial tail block stays
        exclusive and frees normally (copy-on-write by construction:
        shared blocks are never written again)."""
        req = self.slots[slot]
        blocks = self._blocks[slot]
        if self.prefix_cache is not None and blocks:
            cached = int(self._lengths[slot])  # tokens with K/V in blocks
            n_full = cached // self.engine.block_size
            tokens = (req.prompt + req.generated)[
                :n_full * self.engine.block_size]
            self.prefix_cache.insert(tokens, blocks[:n_full])
            self.pool.free(blocks[n_full:])
        else:
            self.pool.free(blocks)
        self._clear_slot(slot)
        return req

    def _finish(self, slot: int, finished: list[Request]) -> None:
        req = self._evict(slot)
        req.state = "done"
        req.t_done = time.perf_counter()
        self.n_done += 1
        if self.telemetry is not None:
            self.telemetry.count(_CNT_REQUESTS)
        self._emit(_INST_FINISH, request=req.rid,
                   generated=len(req.generated))
        finished.append(req)

    def _expire(self, req: Request, which: str, where: str,
                finished: list[Request]) -> None:
        """Typed terminal: a deadline passed.  The caller already removed
        ``req`` from the queue or evicted its slot."""
        req.state = "expired"
        req.reason = f"{which} deadline exceeded ({where})"
        req.t_done = time.perf_counter()
        self.n_expired += 1
        if self.telemetry is not None:
            self.telemetry.count(_CNT_EXPIRED)
        self._emit(_INST_EXPIRE, request=req.rid, which=which, where=where)
        finished.append(req)

    def mark_shed(self, req: Request, reason: str,
                  est_wait_ms: float | None = None) -> None:
        """Typed terminal: refused at admission (shedding or drain).  The
        request was never queued — no blocks, no prefill, no tokens."""
        now = time.perf_counter()
        if req.t_submit is None:
            req.t_submit = now
        req.state = "shed"
        req.reason = reason
        req.t_done = now
        self.n_shed += 1
        if self.telemetry is not None:
            self.telemetry.count(_CNT_SHED)
        fields = {"request": req.rid, "reason": reason}
        if est_wait_ms is not None:
            fields["est_wait_ms"] = round(est_wait_ms, 1)
        self._emit(_INST_SHED, **fields)

    def _fail(self, req: Request, need: int,
              finished: list[Request]) -> None:
        """Typed terminal: the livelock guard.  A request whose prefix can
        never fit the pool is refused — NOT crashed on, NOT preempted
        around forever (the pre-ISSUE-14 behavior raised RuntimeError and
        took the whole server down with it)."""
        req.state = "failed"
        req.reason = (f"needs {need} KV blocks, pool has "
                      f"{self.pool.num_blocks - 1} — can never be admitted")
        req.t_done = time.perf_counter()
        self.n_failed += 1
        if self.telemetry is not None:
            self.telemetry.count(_CNT_FAILED)
        self._emit(_INST_FAIL, request=req.rid, need_blocks=need,
                   pool_blocks=self.pool.num_blocks - 1)
        finished.append(req)

    def _deadline_overrun(self, req: Request,
                          now: float | None = None) -> str | None:
        """Which deadline ``req`` has blown ("ttft" | "total"), or None."""
        if req.t_submit is None:
            return None
        now = time.perf_counter() if now is None else now
        elapsed_ms = (now - req.t_submit) * 1e3
        if (req.total_deadline_ms is not None
                and elapsed_ms > req.total_deadline_ms):
            return "total"
        if (req.t_first_token is None and req.ttft_deadline_ms is not None
                and elapsed_ms > req.ttft_deadline_ms):
            return "ttft"
        return None

    def _sweep_deadlines(self, finished: list[Request]) -> None:
        """Between-steps deadline enforcement: expire overrun queued AND
        active requests (active ones free their blocks — an expired
        request must stop consuming decode slots immediately)."""
        now = time.perf_counter()
        kept: deque[Request] = deque()
        while self.queue:
            req = self.queue.popleft()
            which = self._deadline_overrun(req, now)
            if which:
                self._expire(req, which, "queued", finished)
            else:
                kept.append(req)
        self.queue = kept
        for slot in range(self.engine.max_batch):
            req = self.slots[slot]
            if req is None:
                continue
            which = self._deadline_overrun(req, now)
            if which:
                self._evict(slot)
                self._expire(req, which, "active", finished)

    def _preempt(self, slot: int) -> None:
        req = self._evict(slot)
        req.n_preemptions += 1
        self.n_preemptions += 1
        req.state = "queued"
        if self.telemetry is not None:
            self.telemetry.count(_CNT_PREEMPTIONS)
        self._emit(_INST_PREEMPT, request=req.rid,
                   held_tokens=len(req.prompt) + len(req.generated))
        self.queue.appendleft(req)  # rejoin first: it already holds work

    def preempt_all(self) -> int:
        """Evict every active request back to the queue front (recompute
        preemption) — the rollout watcher's weight-swap barrier: the KV
        cache was computed under the OLD weights, so active sequences
        re-prefill under the new ones.  -> number preempted."""
        n = 0
        for slot in range(self.engine.max_batch):
            if self.slots[slot] is not None:
                self._preempt(slot)
                n += 1
        return n

    def _alloc(self, n: int) -> list[int] | None:
        """Pool allocation with prefix-cache pressure relief: when the
        free list can't cover ``n``, ask the radix tree to evict LRU
        zero-ref leaves before giving up (cached-but-unreferenced blocks
        are reclaimable capacity, not leaks)."""
        row = self.pool.alloc(n)
        if row is None and self.prefix_cache is not None:
            self.prefix_cache.evict(n - self.pool.free_blocks)
            row = self.pool.alloc(n)
        return row

    def _admit(self, finished: list[Request]) -> None:
        """Prefill queued requests into free slots while blocks last."""
        if self.prefix_cache is not None:
            # cached K/V is only valid under the weights that computed it:
            # a rollout swap/rollback bumped the engine's params_version,
            # so the whole tree drops BEFORE any lookup (ISSUE 17)
            version = self.engine.params_version
            if self.prefix_cache.params_version != version:
                dropped = self.prefix_cache.n_nodes
                if self.prefix_cache.check_version(version):
                    self._emit(_INST_PREFIX_INVALIDATE,
                               params_version=version, dropped=dropped)
        while self.queue:
            req = self.queue[0]
            # deadline check BEFORE any prefill work (ISSUE 14 satellite):
            # preemption re-queues to the FRONT unconditionally, so a
            # requeued request past its deadline must expire here, not
            # burn a recompute-prefill first
            which = self._deadline_overrun(req)
            if which:
                self.queue.popleft()
                self._expire(req, which, "queued", finished)
                continue
            try:
                slot = self.slots.index(None)
            except ValueError:
                return
            prefix = req.prompt + req.generated
            need = blocks_for(len(prefix), self.engine.block_size)
            if need > self.pool.num_blocks - 1:
                # livelock guard: this prefix can NEVER fit, even into an
                # empty pool — refuse it and keep serving everyone else
                self.queue.popleft()
                self._fail(req, need, finished)
                continue
            matched: list[int] = []
            prefix_len = 0
            if self.prefix_cache is not None:
                self.n_prefix_lookups += 1
                matched = self.prefix_cache.match(prefix)
                prefix_len = len(matched) * self.engine.block_size
            new = self._alloc(need - len(matched))
            if new is None:
                if matched:
                    # release the acquired prefix refs: admission failed,
                    # and holding them would wedge the eviction pressure
                    # valve (the tree's own refs keep the entries alive)
                    self.pool.free(matched)
                if self.n_active == 0 and (self.prefix_cache is None
                                           or self.prefix_cache.n_nodes
                                           == 0):
                    # an empty server (and a drained cache) that still
                    # can't allocate means the pool leaked: refuse THIS
                    # request (typed terminal) instead of raising and
                    # killing every other request
                    self.queue.popleft()
                    self._fail(req, need, finished)
                    continue
                return
            row = matched + new
            self.queue.popleft()
            span = (self.telemetry.span(_SPAN_PREFILL, request=req.rid,
                                        prompt=len(prefix), slot=slot)
                    if self.telemetry is not None else None)
            if span is not None:
                span.__enter__()
            try:
                # prefill returns a host int — already materialized, so the
                # span close measures execution, not dispatch
                tok, _ = self.engine.prefill(row, prefix, req.temperature,
                                             req.rid, prefix_len=prefix_len)
            finally:
                if span is not None:
                    span.__exit__(None, None, None)
            if prefix_len:
                # exact accounting: tokens_saved is the sum of matched-
                # prefix lengths — prefill K/V the engine did not recompute
                self.n_prefix_hits += 1
                self.prefix_tokens_saved += prefix_len
                if self.telemetry is not None:
                    self.telemetry.count(_CNT_PREFIX_HIT)
                    self.telemetry.count(_CNT_PREFIX_TOKENS, prefix_len)
            now = time.perf_counter()
            if req.t_first_token is None:
                req.t_first_token = now
                ttft = (now - req.t_submit) * 1e3
                self.ttft_ms.append(ttft)
                if self.telemetry is not None:
                    self.telemetry.observe(_HIST_TTFT_MS, ttft)
            req.generated.append(tok)
            if self.telemetry is not None:
                self.telemetry.count(_CNT_TOKENS)
            self._emit(_INST_ADMIT, request=req.rid, slot=slot,
                       prefix=len(prefix), blocks=need,
                       prefix_cached=prefix_len,
                       resumed=req.n_preemptions > 0)
            req.state = "active"
            self.slots[slot] = req
            self._blocks[slot] = row
            self._tables[slot, :] = PagedKVCache.NULL_BLOCK
            self._tables[slot, :need] = row
            self._lengths[slot] = len(prefix)
            self._tokens[slot] = tok
            self._temps[slot] = req.temperature
            self._rids[slot] = req.rid
            if self._done(req):
                self._finish(slot, finished)

    def _done(self, req: Request) -> bool:
        if len(req.generated) >= req.max_new_tokens:
            return True
        return (self.eos_token is not None
                and req.generated
                and req.generated[-1] == self.eos_token)

    def _ensure_capacity(self) -> None:
        """Every active slot whose NEXT token starts a new cache block must
        get one before the decode step; exhaustion preempts the longest
        active sequence and retries."""
        for slot in range(self.engine.max_batch):
            if self.slots[slot] is None:
                continue
            if self._lengths[slot] % self.engine.block_size != 0:
                continue
            while self.slots[slot] is not None:
                got = self.pool.alloc(1)
                if got is not None:
                    n_used = blocks_for(int(self._lengths[slot]),
                                        self.engine.block_size)
                    self._blocks[slot].extend(got)
                    self._tables[slot, n_used] = got[0]
                    break
                victim = max(
                    (s for s in range(self.engine.max_batch)
                     if self.slots[s] is not None),
                    key=lambda s: int(self._lengths[s]))
                self._preempt(victim)

    def _fire_faults(self) -> None:
        """serve:raise / serve:stall chaos sites, indexed by decode-step
        ordinal.  Action-narrowed fires: the rollout watcher counts a
        DIFFERENT ordinal (candidates) for serve:rollout_corrupt."""
        if self.fault_plan is None:
            return
        if self.fault_plan.fire("serve", self.n_steps, "stall"):
            time.sleep(float(os.environ.get("THEANOMPI_SERVE_STALL_S",
                                            "2.0")))
        if self.fault_plan.fire("serve", self.n_steps, "raise"):
            raise FaultInjected(
                f"serve:raise at decode step {self.n_steps}")

    def step(self) -> list[Request]:
        """One scheduler iteration: enforce deadlines, admit, secure
        blocks, decode the fixed batch, account the new tokens; -> every
        request that reached a TERMINAL state this step (done + expired +
        failed — run loops key on ``req.state``)."""
        finished: list[Request] = []
        self._sweep_deadlines(finished)
        self._admit(finished)
        if self.n_active == 0:
            return finished
        self._ensure_capacity()
        active = [s for s in range(self.engine.max_batch)
                  if self.slots[s] is not None]
        if not active:  # capacity pressure preempted everyone admitted
            return finished
        self._fire_faults()
        span = None
        if self.telemetry is not None:
            span = self.telemetry.span(
                _SPAN_DECODE, step=self.n_steps, batch=len(active),
                requests=[int(self._rids[s]) for s in active])
            span.__enter__()
        t0 = time.perf_counter()
        try:
            nxt, _ = self.engine.decode(self._tables, self._lengths,
                                        self._tokens, self._temps,
                                        self._rids)
        finally:
            if span is not None:  # decode() returned host arrays: fenced
                span.__exit__(None, None, None)
        t1 = time.perf_counter()
        step_ms = (t1 - t0) * 1e3
        self.step_ms.append(step_ms)
        self.n_steps += 1
        self._rate.append((t1, len(active)))
        for slot in active:
            req = self.slots[slot]
            self._lengths[slot] += 1  # the fed token is now cached
            tok = int(nxt[slot])
            req.generated.append(tok)
            self._tokens[slot] = tok
            self.token_ms.append(step_ms)
            if self.telemetry is not None:
                self.telemetry.count(_CNT_TOKENS)
                self.telemetry.observe(_HIST_TOKEN_MS, step_ms)
            if self._done(req):
                self._finish(slot, finished)
        if self.telemetry is not None and self.n_steps % 16 == 0:
            # periodic flush (ISSUE 13): the ttft/token histograms must
            # reach the event stream while serving is LIVE — the health
            # monitor's SLO detector reads p99 from ``metrics`` events,
            # and a flush only at shutdown would blind it
            self.telemetry.flush_metrics(step=self.n_steps)
        return finished

    # -- graceful drain (ISSUE 14) -------------------------------------------
    def begin_drain(self) -> list[Request]:
        """Stop admitting: every queued request is shed (typed terminal,
        reason "draining") and further ``submit`` calls shed on arrival.
        Active requests keep decoding — the drain loop finishes or
        expires them.  -> the newly shed requests."""
        self.draining = True
        shed: list[Request] = []
        self._emit(_INST_DRAIN, phase="begin",
                   in_flight=self.n_active + len(self.queue))
        while self.queue:
            req = self.queue.popleft()
            self.mark_shed(req, "draining")
            shed.append(req)
        return shed

    def expire_all_active(self, reason: str) -> list[Request]:
        """Force every in-flight request terminal (drain deadline): evict
        and expire with ``reason``.  -> the expired requests."""
        out: list[Request] = []
        for slot in range(self.engine.max_batch):
            if self.slots[slot] is None:
                continue
            req = self._evict(slot)
            self._expire(req, "drain", reason, out)
        return out

    def end_drain(self) -> None:
        self._emit(_INST_DRAIN, phase="end", in_flight=self.n_active)


def run_open_loop(scheduler: Scheduler, requests: list[Request],
                  poll_s: float = 0.002, *, drain=None,
                  drain_s: float = 5.0, on_terminal=None,
                  between_steps=None,
                  snapshot=None) -> tuple[dict[int, Request], float]:
    """Drive synthetic open-loop traffic: each request is submitted when the
    wall clock passes its ``arrival_s`` (arrivals never wait on the server —
    that is what makes the load open-loop), then the scheduler steps until
    every request reaches a TERMINAL state (done/expired/shed/failed — no
    request is ever silently lost).  -> ({rid: terminal request}, wall s).

    ``drain``: a zero-arg callable polled every loop pass; once true the
    loop stops admitting (queued + not-yet-arrived requests shed with
    reason "draining"), keeps decoding in-flight requests for up to
    ``drain_s`` seconds, then force-expires the remainder — the SIGTERM
    half of ``tmserve --drain-s``.  ``on_terminal(req)`` fires once per
    terminal request (the CLI's REQUESTS.jsonl writer).
    ``between_steps(scheduler)`` runs every pass — the rollout watcher's
    between-steps poll point.  ``snapshot``: an optional
    :class:`~theanompi_tpu.serving.lifecycle.SnapshotPublisher` whose
    ``maybe`` is offered the live scheduler load every pass (ISSUE 19
    satellite — the router balances on this, not the end-of-drive
    SERVE.json).
    """
    pending = deque(sorted(requests, key=lambda r: r.arrival_s))
    results: dict[int, Request] = {}

    def _terminal(req: Request) -> None:
        results[req.rid] = req
        if on_terminal is not None:
            on_terminal(req)

    draining = False
    drain_deadline = 0.0
    t0 = time.perf_counter()
    while len(results) < len(requests):
        if between_steps is not None:
            between_steps(scheduler)
        if snapshot is not None:
            snapshot.maybe(scheduler.snapshot, scheduler.n_steps)
        if drain is not None and not draining and drain():
            draining = True
            drain_deadline = time.perf_counter() + drain_s
            for req in scheduler.begin_drain():
                _terminal(req)
            while pending:  # never-submitted arrivals shed too: every id
                req = pending.popleft()  # must reach a terminal state
                scheduler.mark_shed(req, "draining")
                _terminal(req)
        now = time.perf_counter() - t0
        if not draining:
            while pending and pending[0].arrival_s <= now:
                req = pending.popleft()
                if not scheduler.submit(req):
                    _terminal(req)
        if scheduler.idle:
            if draining:
                break
            if pending:
                time.sleep(min(poll_s, max(pending[0].arrival_s - now, 0.0)))
            continue
        for req in scheduler.step():
            _terminal(req)
        if draining and time.perf_counter() >= drain_deadline:
            for req in scheduler.expire_all_active("drain deadline"):
                _terminal(req)
            break
    if draining:
        scheduler.end_drain()
    if snapshot is not None:  # final publish: terminal tallies land
        snapshot.maybe(scheduler.snapshot, scheduler.n_steps, force=True)
    return results, time.perf_counter() - t0


def run_queue_loop(scheduler: Scheduler, queue_path: str,
                   poll_s: float = 0.002, *, drain=None,
                   drain_s: float = 5.0, on_terminal=None,
                   between_steps=None, snapshot=None,
                   answered: set[int] | None = None,
                   ) -> tuple[dict[int, Request], float]:
    """Drive a replica off its durable admission queue (ISSUE 19).

    The router appends request entries to ``queue_path`` (see
    :func:`theanompi_tpu.serving.lifecycle.append_queue`); this loop tails
    the file by byte offset, submits each entry as it appears, and keeps
    running until a ``{"op": "drain"}`` sentinel arrives (finish what is
    in flight, then exit) or the ``drain`` callable trips (the SIGTERM
    path: shed queued work with reason "draining", decode in-flight
    requests for up to ``drain_s``, force-expire the rest).

    ``answered``: rids already terminal in a previous attempt (restart
    dedup off REQUESTS.jsonl) — their queue entries are skipped silently,
    NOT re-served and NOT re-recorded.  Each terminal callback receives
    the extra ``queue_wait_ms`` (wall delta from the entry's ``enq_wall``
    stamp to submission) so the router can reconstruct router-visible
    TTFT without a shared monotonic clock.

    -> ({rid: terminal request}, wall seconds).
    """
    results: dict[int, Request] = {}
    answered = set() if answered is None else set(answered)
    queue_wait_ms: dict[int, float] = {}

    def _terminal(req: Request) -> None:
        results[req.rid] = req
        if on_terminal is not None:
            extra = {}
            if req.rid in queue_wait_ms:
                extra["queue_wait_ms"] = queue_wait_ms[req.rid]
            on_terminal(req, **extra)

    def _entry_to_request(e: dict) -> Request:
        return Request(
            rid=int(e["rid"]),
            prompt=list(e["prompt"]),
            max_new_tokens=int(e.get("max_new_tokens", 16)),
            temperature=float(e.get("temperature", 0.0)),
            ttft_deadline_ms=e.get("ttft_deadline_ms"),
            total_deadline_ms=e.get("total_deadline_ms"),
        )

    offset = 0
    drain_seen = False        # durable sentinel: finish in-flight, exit
    sig_draining = False      # SIGTERM: shed + bounded decode + expire
    drain_deadline = 0.0
    t0 = time.perf_counter()
    while True:
        if between_steps is not None:
            between_steps(scheduler)
        if snapshot is not None:
            snapshot.maybe(scheduler.snapshot, scheduler.n_steps)
        if not sig_draining:
            entries, offset = read_jsonl_since(queue_path, offset)
            for e in entries:
                if e.get("op") == DRAIN_OP:
                    drain_seen = True
                    continue
                if "rid" not in e or int(e["rid"]) in answered:
                    continue
                req = _entry_to_request(e)
                if "enq_wall" in e:
                    # wall (not perf_counter): the enqueue stamp came from
                    # the router's process
                    now = time.time()  # lint: wall-ok — cross-process dwell
                    queue_wait_ms[req.rid] = round(
                        max(now - float(e["enq_wall"]), 0.0) * 1e3, 3)
                answered.add(req.rid)  # one submission per rid per attempt
                if not scheduler.submit(req):
                    _terminal(req)
        if drain is not None and not sig_draining and drain():
            sig_draining = True
            drain_deadline = time.perf_counter() + drain_s
            for req in scheduler.begin_drain():
                _terminal(req)
        if scheduler.idle:
            if drain_seen or sig_draining:
                break
            time.sleep(poll_s)
            continue
        for req in scheduler.step():
            _terminal(req)
        if sig_draining and time.perf_counter() >= drain_deadline:
            for req in scheduler.expire_all_active("drain deadline"):
                _terminal(req)
            break
    if sig_draining:
        scheduler.end_drain()
    if snapshot is not None:
        snapshot.maybe(scheduler.snapshot, scheduler.n_steps, force=True)
    return results, time.perf_counter() - t0


def serve_report(results: dict[int, Request], wall_s: float,
                 scheduler: Scheduler) -> dict:
    """The SERVE.json artifact: throughput + latency percentiles."""
    eng = scheduler.engine
    n_tokens = sum(len(r.generated) for r in results.values())

    def pct(xs):
        if not xs:
            return {}
        arr = np.asarray(xs)
        return {"p50": round(float(np.percentile(arr, 50)), 3),
                "p99": round(float(np.percentile(arr, 99)), 3)}

    states = {s: 0 for s in TERMINAL_STATES}
    for r in results.values():
        states[r.state] = states.get(r.state, 0) + 1
    return {
        "metric": "serve_tokens_per_sec",
        "value": round(n_tokens / wall_s, 2) if wall_s > 0 else 0.0,
        "unit": "tokens/sec",
        "requests": len(results),
        "generated_tokens": n_tokens,
        "wall_s": round(wall_s, 3),
        "ttft_ms": pct(scheduler.ttft_ms),
        "token_ms": pct(scheduler.token_ms),
        "preemptions": scheduler.n_preemptions,
        "decode_steps": scheduler.n_steps,
        # ISSUE 18 kernel A/B: which decode path served this run, plus its
        # per-step wall percentiles — the variant key the ledger trends
        "decode_kernel": eng.decode_impl,
        "decode_step_ms": pct(scheduler.step_ms),
        "terminal_states": states,
        "drained": scheduler.draining,
        "quantized_int8": eng.quantized,
        # ISSUE 17 prefix-cache accounting (exact: tokens_saved is the sum
        # of matched-prefix lengths across admissions; zeros when off)
        "prefix_cache": scheduler.prefix_cache is not None,
        "prefix_hit_rate": (
            round(scheduler.n_prefix_hits / scheduler.n_prefix_lookups, 4)
            if scheduler.n_prefix_lookups else 0.0),
        "prefill_tokens_saved": scheduler.prefix_tokens_saved,
        "config": {
            "block_size": eng.block_size,
            "num_blocks": eng.num_blocks,
            "max_batch": eng.max_batch,
            "max_context": eng.max_context,
        },
    }
