"""Verified live weight rollout with auto-rollback (ISSUE 14).

ROADMAP item 3(e): train → serve as one continuous pipeline.  A
:class:`RolloutManager` watches a checkpoint directory a live trainer may
still own and hot-swaps newly *verified* checkpoints into the serving
engine between scheduler steps:

- **discovery** is manifest-name-only (a ``listdir`` — no checkpoint byte
  is read until a new epoch shows up), so the idle-poll cost is one
  directory scan;
- **verification** goes through the PR 5 read-only chain
  (:func:`theanompi_tpu.utils.checkpoint.load_for_inference`): a corrupt
  or HALF-PUBLISHED candidate (manifest visible, ``.npz`` mid-replace —
  the PR 9 known race at its serving edge) simply fails to verify as the
  newest epoch, which the watcher treats as "not yet published": it
  stamps one ``serve.rollout_refused`` event, keeps serving the old
  weights, and re-polls.  It never quarantines, moves, or deletes a live
  writer's file — ``load_for_inference`` is read-only by contract;
- **adoption** preempts every active sequence first (their KV cache was
  computed under the old weights; recompute-preemption replays them
  exactly, so no request is dropped), then swaps the params — same
  shapes, so the compiled decode program is reused — and stamps a
  ``serve.rollout`` event;
- **probation**: for ``probation_s`` after a swap the watcher reads the
  PR 12 health monitor's verdicts; an SLO or throughput verdict turning
  CRITICAL rolls back to the previous weights (``serve.rollback``), and
  the rolled-back epoch is remembered as bad so it is never re-adopted.

Chaos site ``serve:rollout_corrupt@i`` (action-narrowed: candidate
ordinal, not decode step) bit-flips the i-th candidate's ``.npz`` before
verification — the acceptance test's proof that a bad rollout is refused
while the old weights keep serving.
"""

from __future__ import annotations

import os
import time

from theanompi_tpu.resilience.faults import FaultPlan
from theanompi_tpu.telemetry.metrics import SERVE_ROLLOUT_INSTANTS
from theanompi_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    load_for_inference,
)

_INST_ROLLOUT, _INST_REFUSED, _INST_ROLLBACK = SERVE_ROLLOUT_INSTANTS

#: health detectors whose CRITICAL verdict triggers the probation rollback
ROLLBACK_DETECTORS = ("slo", "throughput")


def newest_manifest_epoch(directory: str) -> int | None:
    """Highest ``ckpt_eNNNN.manifest.json`` epoch by FILENAME only — no
    file content is read, so polling a live writer's directory is free of
    torn-read hazards.  None when the directory has no manifests."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    best = None
    for f in names:
        if not (f.startswith("ckpt_e") and f.endswith(".manifest.json")):
            continue
        try:
            ep = int(f[len("ckpt_e"):-len(".manifest.json")])
        except ValueError:
            continue
        best = ep if best is None or ep > best else best
    return best


class RolloutManager:
    """Between-steps checkpoint watcher for one engine + scheduler.

    ``health_verdicts``: zero-arg callable returning the current verdict
    dicts (``[{"detector", "severity", ...}]``); defaults to the owning
    telemetry's in-process :class:`HealthMonitor`.  Injectable so tests
    drive the probation window without a live monitor.
    """

    def __init__(self, engine, checkpoint_dir: str, templates: dict, *,
                 model=None, verify: str = "fast",
                 current_epoch: int | None = None,
                 poll_s: float = 0.5, probation_s: float = 10.0,
                 telemetry=None, health_verdicts=None,
                 fault_plan: FaultPlan | None = None,
                 clock=time.perf_counter):
        self.engine = engine
        self.checkpoint_dir = checkpoint_dir
        self.templates = templates
        self.model = model
        self.verify = verify
        self.poll_s = float(poll_s)
        self.probation_s = float(probation_s)
        self.telemetry = telemetry
        self._health_verdicts = health_verdicts
        self.fault_plan = fault_plan
        self._clock = clock
        self.current_epoch = -1 if current_epoch is None else current_epoch
        self._next_poll = 0.0
        self._prev: tuple[object, int] | None = None  # (engine params, epoch)
        self._probation_until: float | None = None
        self._bad_epochs: set[int] = set()
        self._refused: set[int] = set()
        self._candidate_ordinals: dict[int, int] = {}
        self.n_rollouts = 0
        self.n_rollbacks = 0
        self.n_refused = 0

    # -- helpers -------------------------------------------------------------
    def _emit(self, name: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.instant(name, **fields)

    def _verdicts(self) -> list[dict]:
        if self._health_verdicts is not None:
            return list(self._health_verdicts() or ())
        mon = getattr(self.telemetry, "health", None)
        return mon.verdicts() if mon is not None else []

    def _maybe_corrupt_candidate(self, epoch: int) -> None:
        """serve:rollout_corrupt chaos site: bit-flip the candidate's
        ``.npz`` mid-file before verification (candidate ordinal — each
        distinct epoch considered draws the next ordinal)."""
        if self.fault_plan is None:
            return
        if epoch not in self._candidate_ordinals:
            self._candidate_ordinals[epoch] = len(self._candidate_ordinals)
        ordinal = self._candidate_ordinals[epoch]
        if not self.fault_plan.fire("serve", ordinal, "rollout_corrupt"):
            return
        npz = os.path.join(self.checkpoint_dir, f"ckpt_e{epoch:04d}.npz")
        try:
            with open(npz, "r+b") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
        except OSError:
            pass  # lint: swallow-ok — a chaos hook must not crash serving

    # -- the between-steps poll ----------------------------------------------
    def poll(self, scheduler) -> str | None:
        """Run between scheduler steps; -> "rollout" | "rollback" |
        "refused" | None for this pass (tests key on it)."""
        now = self._clock()
        outcome = self._check_probation(scheduler, now)
        if outcome:
            return outcome
        if now < self._next_poll:
            return None
        self._next_poll = now + self.poll_s
        candidate = newest_manifest_epoch(self.checkpoint_dir)
        if (candidate is None or candidate <= self.current_epoch
                or candidate in self._bad_epochs):
            return None
        self._maybe_corrupt_candidate(candidate)
        try:
            restored = load_for_inference(
                self.checkpoint_dir, self.templates, verify=self.verify,
                model=self.model)
        except CheckpointCorruptError as e:
            # the WHOLE chain failed to verify — nothing newer to adopt;
            # keep serving the weights already loaded and re-poll
            return self._refuse(candidate, f"chain unverifiable: {e}")
        if restored is None:
            return self._refuse(candidate, "no verifiable checkpoint yet")
        epoch, _it, trees = restored
        if epoch <= self.current_epoch or epoch in self._bad_epochs:
            # the chain stepped BACK over the candidate: its .npz is
            # corrupt or mid-replace (half-published) — not yet published
            # as far as serving is concerned; never quarantine, re-poll
            return self._refuse(candidate, "candidate did not verify "
                                "(corrupt or half-published)")
        self._adopt(scheduler, epoch, trees)
        return "rollout"

    def _refuse(self, epoch: int, reason: str) -> str:
        if epoch not in self._refused:  # one event per candidate, not
            self._refused.add(epoch)    # one per poll
            self.n_refused += 1
            self._emit(_INST_REFUSED, epoch=epoch, reason=reason)
        return "refused"

    def _adopt(self, scheduler, epoch: int, trees: dict) -> None:
        preempted = scheduler.preempt_all()
        prev_params = self.engine.swap_params(trees["params"])
        self._prev = (prev_params, self.current_epoch)
        from_epoch = self.current_epoch
        self.current_epoch = epoch
        self._refused.discard(epoch)
        self._probation_until = self._clock() + self.probation_s
        self.n_rollouts += 1
        self._emit(_INST_ROLLOUT, from_epoch=from_epoch, to_epoch=epoch,
                   preempted=preempted)

    def _check_probation(self, scheduler, now: float) -> str | None:
        if self._probation_until is None:
            return None
        if now >= self._probation_until:
            # probation survived: the swap is committed, the old weights
            # are no longer a rollback target
            self._probation_until = None
            self._prev = None
            return None
        critical = next(
            (v for v in self._verdicts()
             if v.get("detector") in ROLLBACK_DETECTORS
             and v.get("severity") == "critical"), None)
        if critical is None or self._prev is None:
            return None
        prev_params, prev_epoch = self._prev
        preempted = scheduler.preempt_all()
        self.engine.restore_params(prev_params)
        bad = self.current_epoch
        self._bad_epochs.add(bad)
        self.current_epoch = prev_epoch
        self._prev = None
        self._probation_until = None
        self.n_rollbacks += 1
        self._emit(_INST_ROLLBACK, from_epoch=bad, to_epoch=prev_epoch,
                   detector=critical.get("detector"),
                   reason=critical.get("reason"), preempted=preempted)
        return "rollback"
