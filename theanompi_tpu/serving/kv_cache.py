"""Paged KV cache: fixed-size blocks, per-sequence block tables, alloc/free
pool (the vLLM layout — Kwon et al., SOSP 2023 — at this repo's scale).

Why paged: a contiguous per-sequence KV buffer must be sized for the WORST
case (``max_batch x seq_len``), and continuous batching (Orca) makes the
resident set churn — sequences of wildly different lengths join and leave
every step.  Fixed-size blocks turn that into a heap problem: a sequence
holds ``ceil(len / block_size)`` blocks scattered anywhere in the pool, the
allocator hands blocks out and takes them back O(1), and the pool can be
deliberately oversubscribed (admission is bounded by actual tokens, not
worst-case reservations) with preemption as the pressure valve
(:mod:`theanompi_tpu.serving.scheduler`).

Layout: one pool per model, ``[L, num_blocks, block_size, H, Dh]`` for K and
V — a block id indexes the same slot in every layer, so one block table per
sequence serves the whole stack.  Block 0 is RESERVED as the null block:
inactive batch slots and prefill padding point their table entries at it, so
the fixed-shape decode step can scatter/gather unconditionally and the
garbage lands where nothing unmasked ever reads.

Attention here is the pure-JAX paged path (gather the table, mask by
length) — the CPU tier-1 reference semantics.  Prefill attention does NOT
go through this module at all: it runs inside the prompt through
``MultiHeadAttention.attend`` (:mod:`theanompi_tpu.ops.attention`), i.e. the
pallas flash kernels of ``ops/pallas_attention.py`` whenever the shape gate
admits them — on TPU the O(P²) half of serving rides the same kernels as
training.  The O(P) per-token decode has two implementations selected by
the static ``decode_impl`` field (ISSUE 18): the pure-JAX blockwise gather
below (``"fallback"``), and the fused pallas kernel of
``ops/pallas_paged_attention.py`` (``"kernel"``) whose block table drives
the DMA index_map directly.  Both compute the SAME blockwise
online-softmax recurrence in the same op order, so they are bit-identical
on CPU (`interpret=True`) — the parity lock the HLO audit and
tests/test_paged_decode_kernel.py enforce.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.pallas_paged_attention import paged_attend_decode

_NEG_INF = -1e30


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedKVCache:
    """The device-side half of the cache: K/V pools + per-slot block tables.

    A pytree (k/v/block_tables are leaves; ``block_size`` is static), so it
    threads through jit-compiled prefill/decode steps functionally — every
    write returns a new cache whose arrays XLA updates in place when the
    caller donates the old ones.  Host-side bookkeeping (which blocks are
    free, which slot maps to which request) lives in :class:`BlockPool` /
    the scheduler, never on device.
    """

    k: jax.Array             # [L, num_blocks, block_size, H, Dh]
    v: jax.Array             # [L, num_blocks, block_size, H, Dh]
    block_tables: jax.Array  # [max_batch, max_blocks_per_seq] int32
    block_size: int
    #: decode attention implementation, static: "fallback" (pure-JAX
    #: blockwise gather), "kernel" (compiled pallas paged decode) or
    #: "kernel_interpret" (same kernel, pallas interpreter — the CPU
    #: parity-lock mode).  Static aux, so each variant compiles its own
    #: program; compiled-vs-interpret is pinned here rather than sniffed
    #: from the backend at trace time so a CPU host can still lower the
    #: compiled variant for TPU (the HLO audit does exactly that).
    decode_impl: str = "fallback"

    NULL_BLOCK = 0

    def tree_flatten(self):
        return ((self.k, self.v, self.block_tables),
                (self.block_size, self.decode_impl))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, block_size=aux[0], decode_impl=aux[1])

    # -- shape properties ----------------------------------------------------
    @property
    def n_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def max_context(self) -> int:
        return self.block_tables.shape[1] * self.block_size

    @classmethod
    def create(cls, n_layers: int, num_blocks: int, block_size: int,
               heads: int, head_dim: int, max_batch: int,
               max_context: int, dtype=jnp.float32,
               decode_impl: str = "fallback") -> "PagedKVCache":
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved null block)")
        if decode_impl not in ("fallback", "kernel", "kernel_interpret"):
            raise ValueError(f"unknown decode_impl {decode_impl!r}")
        max_blocks_per_seq = -(-max_context // block_size)
        shape = (n_layers, num_blocks, block_size, heads, head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            block_tables=jnp.zeros((max_batch, max_blocks_per_seq),
                                   jnp.int32),
            block_size=block_size,
            decode_impl=decode_impl,
        )

    def with_tables(self, tables) -> "PagedKVCache":
        """New cache view with the given ``[max_batch, max_blocks]`` tables
        (the scheduler re-materializes these from host state each step)."""
        return PagedKVCache(self.k, self.v,
                            jnp.asarray(tables, jnp.int32), self.block_size,
                            decode_impl=self.decode_impl)

    # -- writes --------------------------------------------------------------
    def write_prefill(self, layer: int, k, v, table_row) -> "PagedKVCache":
        """Write a whole prompt's K/V for one layer: ``k``/``v``
        ``[1, P_pad, H, Dh]`` with ``P_pad`` a multiple of ``block_size``;
        ``table_row`` ``[P_pad // block_size]`` block ids (padding entries
        point at the null block — duplicate scatter indices are fine, the
        null block's content is never read unmasked)."""
        bs = self.block_size
        p_pad = k.shape[1]
        blocks_k = k[0].reshape(p_pad // bs, bs, *k.shape[2:])
        blocks_v = v[0].reshape(p_pad // bs, bs, *v.shape[2:])
        idx = jnp.asarray(table_row, jnp.int32)
        return PagedKVCache(
            self.k.at[layer, idx].set(blocks_k.astype(self.k.dtype)),
            self.v.at[layer, idx].set(blocks_v.astype(self.v.dtype)),
            self.block_tables, self.block_size,
            decode_impl=self.decode_impl)

    def write_decode(self, layer: int, k, v, positions) -> "PagedKVCache":
        """Append one token's K/V per batch slot: ``k``/``v`` ``[B, H, Dh]``
        at ``positions`` ``[B]`` (inactive slots' tables point at the null
        block, so their writes land in reserved garbage)."""
        b = k.shape[0]
        blk_idx = positions // self.block_size
        blk = jnp.take_along_axis(
            self.block_tables, blk_idx[:, None], axis=1)[:, 0]
        off = positions % self.block_size
        return PagedKVCache(
            self.k.at[layer, blk, off].set(k.astype(self.k.dtype)),
            self.v.at[layer, blk, off].set(v.astype(self.v.dtype)),
            self.block_tables, self.block_size,
            decode_impl=self.decode_impl)

    # -- paged attention (suffix prefill) --------------------------------------
    def attend_prefill(self, layer: int, q, table_row, prefix_len):
        """Masked attention of a SUFFIX of queries over one sequence's full
        cached context (the partial-prefill path, ISSUE 17): ``q``
        ``[1, S_pad, H, Dh]`` — the uncached suffix starting at absolute
        position ``prefix_len`` — attends over every position the row's
        blocks hold, cached-prefix K/V included.  ``table_row``
        ``[max_blocks_per_seq]`` block ids -> context ``[1, S_pad, H, Dh]``.

        Same fp32 softmax / ``_NEG_INF`` mask discipline as
        :meth:`attend_decode`; the causal mask admits absolute positions
        ``<= prefix_len + s`` for suffix query ``s``.  End-padding queries
        past the true suffix attend over masked-in garbage (null-block and
        unwritten positions) — finite, never NaN, and discarded: the engine
        samples only from the last REAL position's logits."""
        scale = q.shape[-1] ** -0.5
        # [nb, bs, H, Dh] -> [T_max, H, Dh]
        kb = jnp.take(self.k[layer], table_row, axis=0)
        vb = jnp.take(self.v[layer], table_row, axis=0)
        t_max = kb.shape[0] * self.block_size
        kb = kb.reshape(t_max, *kb.shape[2:])
        vb = vb.reshape(t_max, *vb.shape[2:])
        qf = q[0].astype(jnp.float32) * scale           # [S, H, Dh]
        s = jnp.einsum("shd,thd->sht", qf, kb.astype(jnp.float32))
        pos_q = prefix_len + jnp.arange(q.shape[1])     # absolute positions
        valid = jnp.arange(t_max)[None, :] <= pos_q[:, None]
        s = jnp.where(valid[:, None, :], s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        ctx = jnp.einsum("sht,thd->shd", p, vb.astype(jnp.float32))
        return ctx[None].astype(q.dtype)

    # -- paged attention (decode) --------------------------------------------
    def attend_decode(self, layer: int, q, positions):
        """Masked attention of one query token per slot over its cached
        context: ``q`` ``[B, H, Dh]``, ``positions`` ``[B]`` (the query's
        own 0-based position, already written) -> context ``[B, H, Dh]``.

        fp32 softmax like the training paths; the mask admits positions
        ``<= positions[b]``.  Inactive slots (position 0 pointing at the
        null block) attend over one garbage token — finite garbage out,
        discarded by the scheduler, and crucially never NaN (an all-masked
        softmax would poison the lane).

        ``decode_impl == "kernel"`` dispatches to the fused pallas kernel
        (:mod:`theanompi_tpu.ops.pallas_paged_attention`); the default is
        the pure-JAX masked gather below, restructured (ISSUE 18) from one
        global softmax into the blockwise online-softmax recurrence so the
        two paths share an op-for-op schedule and stay BIT-identical on
        CPU (a fully-masked block is an exact no-op of the recurrence:
        correction ``exp(0) == 1``, masked probabilities underflow to 0 —
        so the kernel gating trailing null blocks off changes nothing).
        The recurrence equals the old single softmax to ~1e-7 (the
        running max ends at the global max; only the rounding association
        of the normalizer differs), which test_paged_decode_kernel.py pins
        against the verbatim old formula."""
        if self.decode_impl != "fallback":
            return paged_attend_decode(
                self.k[layer], self.v[layer], self.block_tables,
                self.block_size, q, jnp.asarray(positions, jnp.int32),
                interpret=(self.decode_impl == "kernel_interpret"))
        # [B, nb, bs, H, Dh]: gather each slot's blocks, then run the
        # recurrence over the block axis
        kb = jnp.take(self.k[layer], self.block_tables, axis=0)
        vb = jnp.take(self.v[layer], self.block_tables, axis=0)
        b, h, d = q.shape
        bs = self.block_size
        nb = self.block_tables.shape[1]
        qf = q.astype(jnp.float32) * (d ** -0.5)

        # multiply+reduce, NOT einsum/dot: gemm kernels change their
        # accumulation strategy with batching layout, which breaks
        # bit-parity with the pallas kernel's per-head products; sum/max
        # reductions over an explicit axis are order-stable (see the
        # kernel module docstring)
        def body(j, carry):
            m, l, acc = carry
            k_j = jax.lax.dynamic_index_in_dim(kb, j, axis=1,
                                               keepdims=False)
            v_j = jax.lax.dynamic_index_in_dim(vb, j, axis=1,
                                               keepdims=False)
            s = jnp.sum(k_j.astype(jnp.float32) * qf[:, None, :, :],
                        axis=-1)                               # [B, bs, H]
            t_abs = j * bs + jnp.arange(bs)
            valid = t_abs[None, :, None] <= positions[:, None, None]
            s = jnp.where(valid, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            corr = jnp.exp(m - m_new)                          # [B, 1, H]
            p = jnp.exp(s - m_new)                             # [B, bs, H]
            l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
            ctx = jnp.sum(p[..., None] * v_j.astype(jnp.float32),
                          axis=1)                              # [B, H, Dh]
            acc_new = acc * jnp.swapaxes(corr, 1, 2) + ctx
            return m_new, l_new, acc_new

        m0 = jnp.full((b, 1, h), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, 1, h), jnp.float32)
        a0 = jnp.zeros((b, h, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
        return (acc / jnp.swapaxes(l, 1, 2)).astype(q.dtype)


class BlockPool:
    """Host-side refcounted allocator over the pool's block ids.

    Block 0 (the null block) is never handed out.  ``alloc`` is
    all-or-nothing: a request that cannot get every block it asked for gets
    none (the scheduler then preempts or defers — partial grants would
    deadlock two half-admitted sequences against each other).

    **Refcounts (ISSUE 17)**: an ``alloc``'d block starts at refcount 1;
    ``acquire`` bumps blocks another holder already owns (the prefix cache
    handing cached blocks to a new request); ``free`` decrements and only
    returns a block to the free list when its count reaches zero — so
    evicting or preempting ONE holder of a shared prefix never invalidates
    another.  The free *set* mirrors the free stack for O(1) double-free
    detection (the old ``b in list`` scan was O(pool) per freed block)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() -> low ids
        self._free_set = set(self._free)
        self._refs: dict[int, int] = {}  # held block -> holder count

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def ref(self, block: int) -> int:
        """Current holder count of ``block`` (0 = on the free list)."""
        return self._refs.get(block, 0)

    def alloc(self, n: int) -> list[int] | None:
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._free_set.discard(b)
            self._refs[b] = 1
        return out

    def acquire(self, blocks) -> None:
        """Bump the refcount of blocks another holder already owns (they
        must be live — acquiring a free block would hand out K/V nobody is
        keeping coherent)."""
        for b in blocks:
            if not 0 < b < self.num_blocks:
                raise ValueError(f"acquiring block {b} outside pool "
                                 f"(1..{self.num_blocks - 1})")
            if self._refs.get(b, 0) < 1:
                raise ValueError(f"acquiring free block {b} (acquire only "
                                 f"bumps blocks a holder already owns)")
            self._refs[b] += 1

    def free(self, blocks) -> None:
        for b in blocks:
            if not 0 < b < self.num_blocks:
                raise ValueError(f"freeing block {b} outside pool "
                                 f"(1..{self.num_blocks - 1})")
            if b in self._free_set or b not in self._refs:
                raise ValueError(f"double free of block {b}")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
                self._free_set.add(b)


def blocks_for(n_tokens: int, block_size: int) -> int:
    """Blocks a sequence of ``n_tokens`` occupies (ceil division)."""
    return -(-n_tokens // block_size)
