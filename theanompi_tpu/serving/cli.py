"""tmserve: the serving CLI (ISSUE 6).

Serves synthetic open-loop traffic against a ``TransformerLM``-family
checkpoint through the continuous-batching engine and reports tokens/sec +
p50/p99 time-to-first-token and per-token latency — the serving twin of
``tmlauncher``, sharing its config surface (``--set`` k=v pairs must
reproduce the training config: the verified load checks the model
class + config sha recorded in the checkpoint manifest) and its exit-code
contract (0 clean, 70 crash, 77 no verifiable checkpoint, 78 config error,
one ``tmserve: error:`` stderr line each).

Checkpoints load STRICTLY via the PR 5 verified chain
(:func:`theanompi_tpu.utils.checkpoint.load_for_inference` — read-only:
safe against a directory a live trainer owns); ``--serve-force`` mirrors
``--resume-force`` for deliberate config drift.  Without
``--checkpoint-dir`` the model serves its random init (a throughput bench
needs weights, not learning).

Example::

    tmserve --modelclass TransformerLM \
        --set dim=256 --set n_layers=4 --set seq_len=256 \
        --checkpoint-dir ./ckpt --requests 64 --arrival-rate 32 \
        --max-batch 8 --num-blocks 96 --quantize-int8 --out SERVE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from theanompi_tpu.launcher import _parse_kv
from theanompi_tpu.resilience.codes import EXIT_CKPT, EXIT_CONFIG, EXIT_CRASH


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmserve",
        description="Serve synthetic open-loop traffic from a trained "
        "checkpoint through the continuous-batching inference engine.",
        allow_abbrev=False,
    )
    p.add_argument("--modelfile",
                   default="theanompi_tpu.models.transformer_lm")
    p.add_argument("--modelclass", default="TransformerLM")
    p.add_argument("--set", dest="model_set", action="append", default=[],
                   metavar="K=V", help="model config entry (repeatable; "
                   "must reproduce the training config for the checkpoint "
                   "fingerprint to match)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="load weights via the verified chain (read-only; "
                   "absent = serve the random init)")
    p.add_argument("--serve-verify", default="fast",
                   choices=["fast", "full", "none"],
                   help="checkpoint verification level (default fast)")
    p.add_argument("--serve-force", action="store_true",
                   help="override the model-fingerprint check on load "
                   "(mirrors tmlauncher --resume-force)")
    # -- engine ------------------------------------------------------------
    p.add_argument("--max-batch", type=int, default=8,
                   help="fixed decode batch width (slots)")
    p.add_argument("--block-size", type=int, default=16,
                   help="KV-cache tokens per block")
    p.add_argument("--num-blocks", type=int, default=None,
                   help="KV block pool size (default: worst case; smaller "
                   "values oversubscribe and rely on preemption)")
    p.add_argument("--quantize-int8", action="store_true",
                   help="int8 weight-only quantization of matmul weights "
                   "(ring_int8 per-chunk-scale format)")
    p.add_argument("--top-k", type=int, default=0,
                   help="restrict sampling to the top-k logits (0 = off)")
    p.add_argument("--decode-kernel", default="auto",
                   choices=("on", "off", "auto"),
                   help="fused paged-attention decode kernel (ISSUE 18): "
                   "on forces the pallas path (Mosaic interpreter off-TPU "
                   "— bit-identical, A/B and parity runs), off pins the "
                   "pure-JAX fallback, auto compiles it on TPU when the "
                   "head geometry tiles and falls back otherwise")
    p.add_argument("--prefix-cache", action="store_true",
                   help="radix prefix cache over the KV block pool (ISSUE "
                   "17): admissions reuse cached full-block prompt-prefix "
                   "K/V via partial prefill; token streams are unchanged "
                   "and the cache invalidates on live weight rollout")
    # -- synthetic traffic -------------------------------------------------
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--prompt-len", type=int, default=16,
                   help="synthetic prompt length (tokens; with --turns>1, "
                   "the per-turn extension length)")
    p.add_argument("--turns", type=int, default=1,
                   help="multi-turn sessions: each consecutive group of "
                   "this many requests is one conversation whose turn t "
                   "prompt extends turn t-1's by --prompt-len new tokens "
                   "(prefix-cache traffic; 1 = independent requests)")
    p.add_argument("--shared-prefix-len", type=int, default=0,
                   help="identical 'system prompt' tokens prepended to "
                   "EVERY request (cross-session prefix-cache traffic)")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--arrival-rate", type=float, default=0.0,
                   help="open-loop Poisson arrival rate in requests/sec "
                   "(0 = all requests arrive at t=0)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 samples under explicit PRNG keys")
    p.add_argument("--seed", type=int, default=0)
    # -- resilience (ISSUE 14) ---------------------------------------------
    p.add_argument("--ttft-deadline-ms", type=float, default=None,
                   help="per-request time-to-first-token deadline; a "
                   "request past it EXPIRES (typed terminal state) "
                   "instead of occupying a slot")
    p.add_argument("--total-deadline-ms", type=float, default=None,
                   help="per-request end-to-end deadline (expire beyond)")
    p.add_argument("--shed", action="store_true",
                   help="admission-time load shedding: refuse (terminal "
                   "state 'shed') a deadline-carrying request the queue "
                   "backlog provably cannot meet at the recent token rate")
    p.add_argument("--drain-s", type=float, default=5.0,
                   help="graceful-drain budget: on SIGTERM stop admitting, "
                   "finish or expire in-flight requests within this many "
                   "seconds, then exit clean (0)")
    p.add_argument("--requests-log", default=None,
                   help="append one JSONL line per terminal request here "
                   "(default <telemetry-dir>/REQUESTS.jsonl when telemetry "
                   "is on); a restarted --supervise attempt reads it back "
                   "and skips already-answered ids")
    # -- router replica mode (ISSUE 19) ------------------------------------
    p.add_argument("--queue-file", default=None,
                   help="serve a durable admission queue instead of "
                   "synthetic traffic: tail this JSONL file for request "
                   "entries appended by tmrouter, exit clean on its "
                   "{\"op\": \"drain\"} sentinel (REQUESTS.jsonl and "
                   "SERVE_SNAPSHOT.json default into its directory)")
    p.add_argument("--snapshot", default=None,
                   help="publish the scheduler's live load here atomically "
                   "(default: next to --queue-file, else "
                   "<telemetry-dir>/SERVE_SNAPSHOT.json; the router "
                   "balances on this)")
    p.add_argument("--snapshot-every", type=int, default=8,
                   help="scheduler steps between live-snapshot publishes")
    p.add_argument("--supervise", action="store_true",
                   help="run the replica as a supervised child through the "
                   "shared run_job seam: crash classification, bounded "
                   "backoff restarts, per-attempt resilience.json "
                   "(written to --telemetry-dir, never the read-only "
                   "--checkpoint-dir)")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--backoff-base", type=float, default=1.0)
    # -- live weight rollout (ISSUE 14) ------------------------------------
    p.add_argument("--rollout-watch", action="store_true",
                   help="watch --checkpoint-dir and hot-swap newly "
                   "VERIFIED checkpoints between scheduler steps (active "
                   "requests recompute under the new weights — none are "
                   "dropped); corrupt/half-published candidates are "
                   "refused and re-polled, never quarantined")
    p.add_argument("--rollout-poll-s", type=float, default=0.5,
                   help="checkpoint-dir poll interval (listdir only)")
    p.add_argument("--rollout-probation-s", type=float, default=10.0,
                   help="after a swap, auto-roll back to the previous "
                   "weights if the health monitor's SLO/throughput "
                   "verdict turns critical within this window")
    # -- output ------------------------------------------------------------
    p.add_argument("--telemetry-dir", default=None,
                   help="serve.prefill/serve.decode spans + serve.* "
                   "gauges as per-rank JSONL (trace.json exported at exit; "
                   "also enables live HEALTH.json — see tmhealth)")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="serving SLO (ISSUE 13): flag a health verdict "
                   "when the live p99 time-to-first-token exceeds this "
                   "many ms (requires --telemetry-dir)")
    p.add_argument("--out", default=None,
                   help="write the report dict as JSON here (SERVE.json)")
    p.add_argument("--quiet", action="store_true")
    return p


def _error_line(phase: str, e: BaseException) -> None:
    print(f"tmserve: error: {phase}: {type(e).__name__}: {e}",
          file=sys.stderr, flush=True)
    if os.environ.get("THEANOMPI_DEBUG"):
        import traceback

        traceback.print_exc()


def synthetic_requests(n: int, vocab: int, prompt_len: int,
                       max_new_tokens: int, rate: float, seed: int,
                       temperature: float = 0.0,
                       ttft_deadline_ms: float | None = None,
                       total_deadline_ms: float | None = None,
                       turns: int = 1, shared_prefix: int = 0):
    """Seeded open-loop request stream: uniform-random prompts, Poisson
    arrivals at ``rate`` req/s (``rate=0`` = one burst at t=0).  The
    stream is a pure function of its arguments — a restarted supervised
    replica regenerates the identical stream and filters out the ids its
    REQUESTS.jsonl already answered.

    Prefix-cache traffic shapes (ISSUE 17, both default off):
    ``shared_prefix`` tokens are drawn once and prepended to EVERY prompt
    (a shared system prompt); ``turns > 1`` groups consecutive rids into
    sessions where turn t's prompt is turn t-1's plus ``prompt_len`` new
    tokens — turn t re-sends the conversation so far, the traffic the
    prefix cache exists for.  The shapes only change which tokens the
    prompts contain; every downstream contract (rid dedup, determinism,
    arrivals) is untouched."""
    import numpy as np

    from theanompi_tpu.serving.scheduler import Request

    rng = np.random.RandomState(seed)
    shared = ([int(x) for x in rng.randint(0, vocab, shared_prefix)]
              if shared_prefix > 0 else [])
    t = 0.0
    out = []
    convo: list[int] = []
    for rid in range(n):
        if rate > 0:
            t += float(rng.exponential(1.0 / rate))
        if turns <= 1 or rid % turns == 0:
            convo = []
        convo = convo + [int(x) for x in rng.randint(0, vocab, prompt_len)]
        out.append(Request(
            rid=rid,
            prompt=shared + convo,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            arrival_s=t if rate > 0 else 0.0,
            ttft_deadline_ms=ttft_deadline_ms,
            total_deadline_ms=total_deadline_ms,
        ))
    return out


def serve(args) -> dict:
    """Build model + engine + scheduler, run the synthetic load; -> report.

    The resilience tier (ISSUE 14) hangs off this one loop: SIGTERM flips
    a drain event the open-loop driver polls (stop admitting, finish or
    expire in-flight within ``--drain-s``, exit clean); every terminal
    request appends to REQUESTS.jsonl so a supervised restart can skip
    already-answered ids; ``--rollout-watch`` polls the checkpoint dir
    between steps and hot-swaps verified checkpoints.
    """
    import importlib
    import signal
    import threading

    from theanompi_tpu.resilience.faults import FaultPlan
    from theanompi_tpu.serving.engine import InferenceEngine
    from theanompi_tpu.serving.lifecycle import (
        REQUESTS_LOG,
        SNAPSHOT,
        RequestLog,
        SnapshotPublisher,
        terminal_rids,
    )
    from theanompi_tpu.serving.scheduler import (
        Scheduler,
        run_open_loop,
        run_queue_loop,
        serve_report,
    )
    from theanompi_tpu.utils.checkpoint import load_for_inference

    if os.environ.get("THEANOMPI_COMPILE_CACHE"):
        # router/fleet replica children inherit the session compile cache
        # the same way tmlauncher's __main__ does (ISSUE 19 satellite):
        # the first replica compiles, every later one loads
        from theanompi_tpu.parallel.mesh import setup_compile_cache

        setup_compile_cache()

    cls = getattr(importlib.import_module(args.modelfile), args.modelclass)
    model = cls(_parse_kv(args.model_set))
    import jax

    params, _state = model.init_params(jax.random.PRNGKey(args.seed))
    epoch = None
    if args.checkpoint_dir:
        restored = load_for_inference(
            args.checkpoint_dir, {"params": params},
            verify=args.serve_verify, model=model, force=args.serve_force)
        if restored is None:
            raise FileNotFoundError(
                f"no checkpoint in {args.checkpoint_dir} (tmserve does not "
                f"serve random inits when a directory was given)")
        epoch, _it, trees = restored
        params = trees["params"]
    if args.rollout_watch and not args.checkpoint_dir:
        raise ValueError("--rollout-watch needs --checkpoint-dir (there is "
                         "nothing to watch)")

    telemetry = None
    if args.telemetry_dir:
        from theanompi_tpu.telemetry import Telemetry

        # ISSUE 13: live health rides the telemetry opt-in, same default
        # as training; --slo-ttft-ms arms the serving SLO detector
        health: bool | dict = True
        if args.slo_ttft_ms is not None:
            health = {"slo_ttft_p99_ms": float(args.slo_ttft_ms)}
        # ISSUE 16: serve-step attribution (queue-wait/prefill/decode/
        # rollout-swap) rides the same opt-in; ATTRIB.json lands in the
        # telemetry dir at close
        telemetry = Telemetry(args.telemetry_dir, health=health,
                              flight_recorder=256, profile=True)

    fault_plan = FaultPlan.from_spec(None)  # THEANOMPI_FAULT_PLAN env
    try:
        attempt = int(os.environ.get("THEANOMPI_ATTEMPT", "1"))
    except ValueError:
        attempt = 1
    engine = InferenceEngine(
        model, params, block_size=args.block_size,
        num_blocks=args.num_blocks, max_batch=args.max_batch,
        quantize_int8=args.quantize_int8, top_k=args.top_k, seed=args.seed,
        decode_kernel=getattr(args, "decode_kernel", "auto"))
    sched = Scheduler(engine, telemetry=telemetry, shed=args.shed,
                      fault_plan=fault_plan,
                      prefix_cache=getattr(args, "prefix_cache", False))
    if telemetry is not None:
        from theanompi_tpu.telemetry.metrics import (
            SERVE_DECODE_KERNEL_INSTANTS,
        )

        # ISSUE 18: record the resolved decode path once per run — the
        # A/B trace needs to know WHICH impl produced its decode spans
        telemetry.instant(SERVE_DECODE_KERNEL_INSTANTS[0],
                          impl=engine.decode_impl,
                          requested=getattr(args, "decode_kernel", "auto"))
    queue_file = getattr(args, "queue_file", None)
    reqs = [] if queue_file else synthetic_requests(
        args.requests, model.data.vocab, args.prompt_len,
        args.max_new_tokens, args.arrival_rate, args.seed,
        args.temperature, ttft_deadline_ms=args.ttft_deadline_ms,
        total_deadline_ms=args.total_deadline_ms,
        turns=getattr(args, "turns", 1),
        shared_prefix=getattr(args, "shared_prefix_len", 0))

    # -- durable terminal-state log + restart dedup (ISSUE 14) -------------
    # queue mode (ISSUE 19): the log defaults NEXT TO the queue file so
    # the router finds it without extra plumbing
    log_path = args.requests_log or (
        os.path.join(args.telemetry_dir, REQUESTS_LOG)
        if args.telemetry_dir else
        os.path.join(os.path.dirname(os.path.abspath(queue_file)),
                     REQUESTS_LOG) if queue_file else None)
    req_log = None
    answered: set[int] = set()
    n_skipped = 0
    if log_path:
        answered = terminal_rids(log_path)
        if answered and not queue_file:
            before = len(reqs)
            reqs = [r for r in reqs if r.rid not in answered]
            n_skipped = before - len(reqs)
        req_log = RequestLog(log_path, attempt=attempt)
    if queue_file and answered:
        n_skipped = len(answered)

    # -- live load snapshot (ISSUE 19 satellite) ---------------------------
    snap_path = getattr(args, "snapshot", None) or (
        os.path.join(os.path.dirname(os.path.abspath(queue_file)), SNAPSHOT)
        if queue_file else
        os.path.join(args.telemetry_dir, SNAPSHOT)
        if args.telemetry_dir else None)
    snapshot = (SnapshotPublisher(
        snap_path, every_steps=getattr(args, "snapshot_every", 8))
        if snap_path else None)

    # -- graceful drain: SIGTERM -> drain within --drain-s, exit clean -----
    drain_ev = threading.Event()
    prev_term = None
    if threading.current_thread() is threading.main_thread():
        prev_term = signal.signal(signal.SIGTERM,
                                  lambda _sig, _frm: drain_ev.set())

    # -- verified live rollout watcher -------------------------------------
    rollout = None
    if args.rollout_watch:
        from theanompi_tpu.serving.rollout import RolloutManager

        rollout = RolloutManager(
            engine, args.checkpoint_dir, {"params": params}, model=model,
            verify=args.serve_verify, current_epoch=epoch,
            poll_s=args.rollout_poll_s,
            probation_s=args.rollout_probation_s,
            telemetry=telemetry, fault_plan=fault_plan)

    try:
        if queue_file:
            results, wall_s = run_queue_loop(
                sched, queue_file, drain=drain_ev.is_set,
                drain_s=args.drain_s,
                on_terminal=req_log.record if req_log else None,
                between_steps=rollout.poll if rollout else None,
                snapshot=snapshot, answered=answered)
        else:
            results, wall_s = run_open_loop(
                sched, reqs, drain=drain_ev.is_set, drain_s=args.drain_s,
                on_terminal=req_log.record if req_log else None,
                between_steps=rollout.poll if rollout else None,
                snapshot=snapshot)
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        if req_log is not None:
            req_log.close()
    report = serve_report(results, wall_s, sched)
    report["checkpoint_epoch"] = (rollout.current_epoch if rollout
                                  else epoch)
    report["attempt"] = attempt
    if n_skipped:
        report["skipped_already_answered"] = n_skipped
    if log_path:
        report["requests_log"] = log_path
    if queue_file:
        report["queue_file"] = queue_file
    if rollout is not None:
        report["rollout"] = {"rollouts": rollout.n_rollouts,
                             "rollbacks": rollout.n_rollbacks,
                             "refused": rollout.n_refused,
                             "serving_epoch": rollout.current_epoch}
    if engine.quant_stats:
        report["quantization"] = engine.quant_stats
    if telemetry is not None:
        from theanompi_tpu.telemetry.metrics import (
            SERVE_DECODE_KERNEL_GAUGES,
            SERVE_GAUGES,
        )

        g_tps, g_active, g_free = SERVE_GAUGES
        telemetry.gauge(g_tps, report["value"])
        telemetry.gauge(g_active, 0)
        telemetry.gauge(g_free, sched.pool.free_blocks)
        step_p50 = (report.get("decode_step_ms") or {}).get("p50")
        if step_p50 is not None:
            telemetry.gauge(SERVE_DECODE_KERNEL_GAUGES[0], step_p50,
                            impl=engine.decode_impl)
        telemetry.close()
        telemetry.export_chrome_trace(
            os.path.join(args.telemetry_dir, "trace.json"))
    return report


def main(argv: list[str] | None = None) -> int:
    """Exit-code contract (shared with tmlauncher; see the README table):
    0 clean, 70 serving crash, 77 checkpoint chain exhausted, 78 config
    error — one ``tmserve:`` stderr line each."""
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on bad flags — keep its contract
        return int(e.code or 0)

    if args.supervise:
        # the supervision half lives across the wall in the resilience
        # layer (serving may never import resilience.supervisor); one lazy
        # import reaches it, mirroring the launcher's _supervise seam
        if os.environ.get("THEANOMPI_SUPERVISED"):
            _error_line("config", RuntimeError(
                "--supervise inside a supervised child (recursion guard)"))
            return EXIT_CONFIG
        from theanompi_tpu.resilience.replica import serve_supervised

        return serve_supervised(
            argv, max_restarts=args.max_restarts,
            backoff_base=args.backoff_base,
            telemetry_dir=args.telemetry_dir, seed=args.seed)

    from theanompi_tpu.utils.checkpoint import (
        CheckpointCorruptError,
        CheckpointFingerprintError,
    )

    try:
        report = serve(args)
    except CheckpointFingerprintError as e:
        _error_line("load", e)
        return EXIT_CONFIG
    except CheckpointCorruptError as e:
        _error_line("checkpoint", e)
        return EXIT_CKPT
    except (ImportError, AttributeError, TypeError, ValueError, KeyError,
            FileNotFoundError, NotImplementedError) as e:
        _error_line("config", e)
        return EXIT_CONFIG
    except Exception as e:
        _error_line("serving", e)
        return EXIT_CRASH
    if args.out:
        with open(args.out + ".tmp", "w") as f:
            json.dump(report, f, indent=1)
        os.replace(args.out + ".tmp", args.out)
    print(json.dumps(report))
    if not args.quiet and args.telemetry_dir:
        print(f"tmserve: telemetry in {args.telemetry_dir} (trace.json "
              f"for Perfetto)", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
