"""tmserve: the serving CLI (ISSUE 6).

Serves synthetic open-loop traffic against a ``TransformerLM``-family
checkpoint through the continuous-batching engine and reports tokens/sec +
p50/p99 time-to-first-token and per-token latency — the serving twin of
``tmlauncher``, sharing its config surface (``--set`` k=v pairs must
reproduce the training config: the verified load checks the model
class + config sha recorded in the checkpoint manifest) and its exit-code
contract (0 clean, 70 crash, 77 no verifiable checkpoint, 78 config error,
one ``tmserve: error:`` stderr line each).

Checkpoints load STRICTLY via the PR 5 verified chain
(:func:`theanompi_tpu.utils.checkpoint.load_for_inference` — read-only:
safe against a directory a live trainer owns); ``--serve-force`` mirrors
``--resume-force`` for deliberate config drift.  Without
``--checkpoint-dir`` the model serves its random init (a throughput bench
needs weights, not learning).

Example::

    tmserve --modelclass TransformerLM \
        --set dim=256 --set n_layers=4 --set seq_len=256 \
        --checkpoint-dir ./ckpt --requests 64 --arrival-rate 32 \
        --max-batch 8 --num-blocks 96 --quantize-int8 --out SERVE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from theanompi_tpu.launcher import _parse_kv
from theanompi_tpu.resilience.codes import EXIT_CKPT, EXIT_CONFIG, EXIT_CRASH


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmserve",
        description="Serve synthetic open-loop traffic from a trained "
        "checkpoint through the continuous-batching inference engine.",
        allow_abbrev=False,
    )
    p.add_argument("--modelfile",
                   default="theanompi_tpu.models.transformer_lm")
    p.add_argument("--modelclass", default="TransformerLM")
    p.add_argument("--set", dest="model_set", action="append", default=[],
                   metavar="K=V", help="model config entry (repeatable; "
                   "must reproduce the training config for the checkpoint "
                   "fingerprint to match)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="load weights via the verified chain (read-only; "
                   "absent = serve the random init)")
    p.add_argument("--serve-verify", default="fast",
                   choices=["fast", "full", "none"],
                   help="checkpoint verification level (default fast)")
    p.add_argument("--serve-force", action="store_true",
                   help="override the model-fingerprint check on load "
                   "(mirrors tmlauncher --resume-force)")
    # -- engine ------------------------------------------------------------
    p.add_argument("--max-batch", type=int, default=8,
                   help="fixed decode batch width (slots)")
    p.add_argument("--block-size", type=int, default=16,
                   help="KV-cache tokens per block")
    p.add_argument("--num-blocks", type=int, default=None,
                   help="KV block pool size (default: worst case; smaller "
                   "values oversubscribe and rely on preemption)")
    p.add_argument("--quantize-int8", action="store_true",
                   help="int8 weight-only quantization of matmul weights "
                   "(ring_int8 per-chunk-scale format)")
    p.add_argument("--top-k", type=int, default=0,
                   help="restrict sampling to the top-k logits (0 = off)")
    # -- synthetic traffic -------------------------------------------------
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--prompt-len", type=int, default=16,
                   help="synthetic prompt length (tokens)")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--arrival-rate", type=float, default=0.0,
                   help="open-loop Poisson arrival rate in requests/sec "
                   "(0 = all requests arrive at t=0)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 samples under explicit PRNG keys")
    p.add_argument("--seed", type=int, default=0)
    # -- output ------------------------------------------------------------
    p.add_argument("--telemetry-dir", default=None,
                   help="serve.prefill/serve.decode spans + serve.* "
                   "gauges as per-rank JSONL (trace.json exported at exit; "
                   "also enables live HEALTH.json — see tmhealth)")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="serving SLO (ISSUE 13): flag a health verdict "
                   "when the live p99 time-to-first-token exceeds this "
                   "many ms (requires --telemetry-dir)")
    p.add_argument("--out", default=None,
                   help="write the report dict as JSON here (SERVE.json)")
    p.add_argument("--quiet", action="store_true")
    return p


def _error_line(phase: str, e: BaseException) -> None:
    print(f"tmserve: error: {phase}: {type(e).__name__}: {e}",
          file=sys.stderr, flush=True)
    if os.environ.get("THEANOMPI_DEBUG"):
        import traceback

        traceback.print_exc()


def synthetic_requests(n: int, vocab: int, prompt_len: int,
                       max_new_tokens: int, rate: float, seed: int,
                       temperature: float = 0.0):
    """Seeded open-loop request stream: uniform-random prompts, Poisson
    arrivals at ``rate`` req/s (``rate=0`` = one burst at t=0)."""
    import numpy as np

    from theanompi_tpu.serving.scheduler import Request

    rng = np.random.RandomState(seed)
    t = 0.0
    out = []
    for rid in range(n):
        if rate > 0:
            t += float(rng.exponential(1.0 / rate))
        out.append(Request(
            rid=rid,
            prompt=[int(x) for x in rng.randint(0, vocab, prompt_len)],
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            arrival_s=t if rate > 0 else 0.0,
        ))
    return out


def serve(args) -> dict:
    """Build model + engine + scheduler, run the synthetic load; -> report."""
    import importlib

    from theanompi_tpu.serving.engine import InferenceEngine
    from theanompi_tpu.serving.scheduler import (
        Scheduler,
        run_open_loop,
        serve_report,
    )
    from theanompi_tpu.utils.checkpoint import load_for_inference

    cls = getattr(importlib.import_module(args.modelfile), args.modelclass)
    model = cls(_parse_kv(args.model_set))
    import jax

    params, _state = model.init_params(jax.random.PRNGKey(args.seed))
    epoch = None
    if args.checkpoint_dir:
        restored = load_for_inference(
            args.checkpoint_dir, {"params": params},
            verify=args.serve_verify, model=model, force=args.serve_force)
        if restored is None:
            raise FileNotFoundError(
                f"no checkpoint in {args.checkpoint_dir} (tmserve does not "
                f"serve random inits when a directory was given)")
        epoch, _it, trees = restored
        params = trees["params"]

    telemetry = None
    if args.telemetry_dir:
        from theanompi_tpu.telemetry import Telemetry

        # ISSUE 13: live health rides the telemetry opt-in, same default
        # as training; --slo-ttft-ms arms the serving SLO detector
        health: bool | dict = True
        if args.slo_ttft_ms is not None:
            health = {"slo_ttft_p99_ms": float(args.slo_ttft_ms)}
        telemetry = Telemetry(args.telemetry_dir, health=health,
                              flight_recorder=256)

    engine = InferenceEngine(
        model, params, block_size=args.block_size,
        num_blocks=args.num_blocks, max_batch=args.max_batch,
        quantize_int8=args.quantize_int8, top_k=args.top_k, seed=args.seed)
    sched = Scheduler(engine, telemetry=telemetry)
    reqs = synthetic_requests(
        args.requests, model.data.vocab, args.prompt_len,
        args.max_new_tokens, args.arrival_rate, args.seed,
        args.temperature)
    results, wall_s = run_open_loop(sched, reqs)
    report = serve_report(results, wall_s, sched)
    report["checkpoint_epoch"] = epoch
    if engine.quant_stats:
        report["quantization"] = engine.quant_stats
    if telemetry is not None:
        from theanompi_tpu.telemetry.metrics import SERVE_GAUGES

        g_tps, g_active, g_free = SERVE_GAUGES
        telemetry.gauge(g_tps, report["value"])
        telemetry.gauge(g_active, 0)
        telemetry.gauge(g_free, sched.pool.free_blocks)
        telemetry.close()
        telemetry.export_chrome_trace(
            os.path.join(args.telemetry_dir, "trace.json"))
    return report


def main(argv: list[str] | None = None) -> int:
    """Exit-code contract (shared with tmlauncher; see the README table):
    0 clean, 70 serving crash, 77 checkpoint chain exhausted, 78 config
    error — one ``tmserve:`` stderr line each."""
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on bad flags — keep its contract
        return int(e.code or 0)

    from theanompi_tpu.utils.checkpoint import (
        CheckpointCorruptError,
        CheckpointFingerprintError,
    )

    try:
        report = serve(args)
    except CheckpointFingerprintError as e:
        _error_line("load", e)
        return EXIT_CONFIG
    except CheckpointCorruptError as e:
        _error_line("checkpoint", e)
        return EXIT_CKPT
    except (ImportError, AttributeError, TypeError, ValueError, KeyError,
            FileNotFoundError, NotImplementedError) as e:
        _error_line("config", e)
        return EXIT_CONFIG
    except Exception as e:
        _error_line("serving", e)
        return EXIT_CRASH
    if args.out:
        with open(args.out + ".tmp", "w") as f:
            json.dump(report, f, indent=1)
        os.replace(args.out + ".tmp", args.out)
    print(json.dumps(report))
    if not args.quiet and args.telemetry_dir:
        print(f"tmserve: telemetry in {args.telemetry_dir} (trace.json "
              f"for Perfetto)", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
