"""Inference engine: compiled prefill/decode steps for ``TransformerLM``.

The engine is the pure-compute half of serving (the policy half — admission,
preemption, batching — is :mod:`theanompi_tpu.serving.scheduler`): it owns
the paged KV pools, the (optionally int8-quantized) params, and two jitted
step functions driven against the model's serving path
(``apply_prefill``/``apply_decode`` — the SAME block stack and param tree
the trainer checkpoints, see :mod:`theanompi_tpu.models.transformer_lm`):

- **prefill**: one sequence, the whole prompt in one forward.  Prompts pad
  to power-of-two block multiples (bounded compile count: at most
  ``log2(max_blocks_per_seq)+1`` prefill programs); causal masking keeps
  end-padding out of every real position's context, and the first output
  token samples from the last REAL position's logits.
- **decode**: one token for every slot of a FIXED ``max_batch`` — the
  continuous-batching invariant.  Inactive slots ride along masked (their
  block tables point at the cache's null block); the step is compiled once.

Sampling runs inside the step under explicit PRNG keys derived from
``(request id, position)`` only — so a preempted-and-recomputed sequence
resamples identically, and greedy (``temperature=0``) is pure argmax.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.pallas_paged_attention import paged_decode_supported
from theanompi_tpu.ops.quant import int8_matmul_supported
from theanompi_tpu.serving.kv_cache import PagedKVCache, blocks_for
from theanompi_tpu.serving.quant import (
    dequantize_tree,
    is_quantized_tree,
    quantize_tree,
)


def sample_tokens(logits, temps, keys, top_k: int = 0):
    """Per-row sampling: argmax where ``temps <= 0``, else temperature
    softmax sampling (optionally over the top-``top_k`` logits).  ``logits``
    ``[B, V]`` fp32, ``temps`` ``[B]``, ``keys`` ``[B]`` PRNG keys."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if top_k and top_k < logits.shape[-1]:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
    sampled = jax.vmap(
        lambda l, k: jax.random.categorical(k, l))(scaled, keys)
    return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)


def _sample_key(base_key, rid, position):
    """The (request, position)-only key derivation: preemption recompute
    replays the identical sampling stream."""
    return jax.random.fold_in(jax.random.fold_in(base_key, rid), position)


class InferenceEngine:
    """Compiled serving steps + cache state for one ``TransformerLM``.

    ``num_blocks`` deliberately admits oversubscription: sized below
    ``max_batch * blocks_per_seq + 1`` the pool can run out mid-decode,
    which is the scheduler's preemption trigger (and the smoke test's).
    """

    def __init__(self, model, params, *, block_size: int = 16,
                 num_blocks: int | None = None, max_batch: int = 8,
                 quantize_int8: bool = False, quant_chunk: int = 1024,
                 top_k: int = 0, seed: int = 0,
                 decode_kernel: str = "auto"):
        cfg = model.config
        self.model = model
        self.max_batch = int(max_batch)
        self.block_size = int(block_size)
        self.max_context = int(cfg["seq_len"])
        self.max_blocks_per_seq = blocks_for(self.max_context, block_size)
        if num_blocks is None:
            num_blocks = max_batch * self.max_blocks_per_seq + 1
        self.num_blocks = int(num_blocks)
        self.top_k = int(top_k)
        self._base_key = jax.random.PRNGKey(seed)
        self.quant_stats = None
        # the serving fast path (ISSUE 18): "auto" takes the pallas paged
        # decode kernel + fused int8 matmuls on TPU when the shape gate
        # admits them, the pure-JAX paths otherwise; "on" forces the
        # kernels (interpreter off-TPU — the parity locks run exactly
        # this); "off" forces the fallback.
        if decode_kernel not in ("auto", "on", "off"):
            raise ValueError(f"decode_kernel={decode_kernel!r} not in "
                             f"('auto', 'on', 'off')")
        self.decode_kernel = decode_kernel
        heads, dim = cfg["heads"], cfg["dim"]
        on_tpu = jax.default_backend() == "tpu"
        use_kernel = decode_kernel == "on" or (
            decode_kernel == "auto" and on_tpu and paged_decode_supported(
                heads, dim // heads, model.precision.compute_dtype))
        #: resolved decode-attention variant — "kernel" (compiled pallas,
        #: TPU), "kernel_interpret" (same kernel through the pallas
        #: interpreter, the off-TPU "on" mode the parity locks run) or
        #: "fallback".  SERVE.json and the serve.decode_kernel gauge
        #: report whether the kernel tier is active.
        self.decode_impl = (
            ("kernel" if on_tpu else "kernel_interpret")
            if use_kernel else "fallback")
        # int8 leaves the fused matmul can consume stay quantized inside
        # the decode step; the rest (odd-vocab head, MoE stacks)
        # dequantize as before.  None = dequantize everything.
        self._keep_quant = (
            (lambda qt: int8_matmul_supported(
                qt.shape, int(qt.q.shape[1]), compiled=on_tpu))
            if use_kernel else None)
        # kept for swap_params: a live weight rollout must re-quantize the
        # incoming tree EXACTLY as __init__ did (same key, same chunking)
        self._quantize_int8 = bool(quantize_int8)
        self._quant_key = jax.random.PRNGKey(seed ^ 0x51)
        self._quant_chunk = int(quant_chunk)
        if quantize_int8:
            params, self.quant_stats = quantize_tree(
                params, self._quant_key, quant_chunk)
        self.params = params
        cache = PagedKVCache.create(
            n_layers=cfg["n_layers"], num_blocks=self.num_blocks,
            block_size=block_size, heads=heads, head_dim=dim // heads,
            max_batch=max_batch, max_context=self.max_context,
            dtype=model.precision.compute_dtype,
            decode_impl=self.decode_impl)
        self._k, self._v = cache.k, cache.v
        # k/v pools are donated: the step's .at[].set() writes update the
        # pool buffers in place instead of copying two [L, blocks, bs, H,
        # Dh] arrays per generated token (the cache docstring's contract)
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        self._prefill_fns: dict[int, object] = {}
        # partial-prefill programs, keyed on PADDED SUFFIX length (same
        # power-of-two bucketing as full prefill -> same log2 bound on
        # program count per bucket family)
        self._prefill_suffix_fns: dict[int, object] = {}
        # bumped on every swap_params/restore_params: cached prefix K/V was
        # computed under the OLD weights, so the scheduler's prefix cache
        # stamps itself against this and invalidates on mismatch (ISSUE 17)
        self.params_version = 0

    @property
    def quantized(self) -> bool:
        return is_quantized_tree(self.params)

    def swap_params(self, params):
        """Hot-swap the serving weights (ISSUE 14 live rollout); -> the
        PREVIOUS engine-format param tree (the rollback token: pass it
        back to :meth:`restore_params` to undo the swap exactly).

        The new tree re-quantizes with the key/chunking ``__init__`` used,
        so it lands in the same engine format; identical shapes mean the
        jitted prefill/decode programs are reused — no recompile, and the
        swap is a host-side pointer update between scheduler steps.  The
        KV cache is NOT touched: the caller (the rollout watcher) preempts
        active sequences first, since their cache was computed under the
        old weights.
        """
        prev = self.params
        if self._quantize_int8:
            params, self.quant_stats = quantize_tree(
                params, self._quant_key, self._quant_chunk)
        self.params = params
        self.params_version += 1
        return prev

    def restore_params(self, engine_params) -> None:
        """Reinstall a tree previously returned by :meth:`swap_params`
        (already in engine format — never re-quantized).  Bumps
        ``params_version`` too: the rollback is a THIRD weight state as far
        as cached K/V is concerned (entries cached during probation were
        computed under the rolled-back-FROM weights)."""
        self.params = engine_params
        self.params_version += 1

    # -- compiled bodies -----------------------------------------------------
    def _decode_impl(self, params, k, v, tables, lengths, tokens, temps,
                     rids, base_key):
        # fast path keeps kernel-consumable int8 leaves quantized; the
        # fallback dequantizes everything exactly as before (the PR 9
        # argmax-agreement lock rides on that path staying bit-stable)
        params = dequantize_tree(params, keep=self._keep_quant)
        cache = PagedKVCache(k, v, tables, self.block_size,
                             decode_impl=self.decode_impl)
        # the incoming token's 0-based position == tokens already cached
        positions = lengths
        logits, cache = self.model.apply_decode(
            params, {}, cache, positions, tokens)
        keys = jax.vmap(functools.partial(_sample_key, base_key))(
            rids, positions + 1)
        nxt = sample_tokens(logits, temps, keys, self.top_k)
        return nxt, logits, cache.k, cache.v

    def _prefill_impl(self, params, k, v, table_row, tokens, true_len,
                      temp, rid, base_key):
        params = dequantize_tree(params)
        cache = PagedKVCache(
            k, v, jnp.zeros((1, self.max_blocks_per_seq), jnp.int32),
            self.block_size)
        logits, cache = self.model.apply_prefill(
            params, {}, cache, table_row, tokens[None, :])
        last = jnp.take(logits[0], true_len - 1, axis=0)
        key = _sample_key(base_key, rid, true_len)
        nxt = sample_tokens(last[None], temp[None], key[None], self.top_k)
        return nxt[0], last, cache.k, cache.v

    def _prefill_suffix_impl(self, params, k, v, full_row, suffix_row,
                             tokens, prefix_len, true_len, temp, rid,
                             base_key):
        """Partial prefill (ISSUE 17): ``tokens`` ``[S_pad]`` is the
        UNCACHED suffix only; K/V and logits are computed for it alone,
        attending over the full row (cached prefix included) via the paged
        gather.  ``full_row`` is fixed at ``[max_blocks_per_seq]`` so the
        program shape depends on the SUFFIX bucket only.  Sampling keys
        stay absolute-position-derived — a partial prefill samples the
        identical stream a full prefill (or a decode at the same position)
        would."""
        params = dequantize_tree(params)
        cache = PagedKVCache(
            k, v, jnp.zeros((1, self.max_blocks_per_seq), jnp.int32),
            self.block_size)
        logits, cache = self.model.apply_prefill_partial(
            params, {}, cache, suffix_row, full_row, tokens[None, :],
            prefix_len)
        last = jnp.take(logits[0], true_len - prefix_len - 1, axis=0)
        key = _sample_key(base_key, rid, true_len)
        nxt = sample_tokens(last[None], temp[None], key[None], self.top_k)
        return nxt[0], last, cache.k, cache.v

    # -- host API (the scheduler's surface) ----------------------------------
    def pad_len(self, n_tokens: int) -> int:
        """Prompt bucket: the smallest power-of-two number of blocks that
        holds ``n_tokens`` (>= one block), capped at the max context."""
        nb = 1
        while nb * self.block_size < n_tokens:
            nb *= 2
        return min(nb, self.max_blocks_per_seq) * self.block_size

    def prefill(self, table_row, tokens, temperature: float = 0.0,
                rid: int = 0, prefix_len: int = 0):
        """Prefill one sequence; -> (first generated token: int, last-
        position logits ``[V]`` np).  ``table_row``: the block ids backing
        the prompt (padded internally with the null block).

        ``prefix_len > 0`` (ISSUE 17): the first ``prefix_len`` tokens'
        K/V already sit in ``table_row``'s leading blocks (a prefix-cache
        hit); only the suffix is computed, in a program bucketed on the
        padded SUFFIX length.  ``prefix_len`` must be a whole number of
        blocks (the cache shares full blocks only) and must leave at least
        one uncached token to produce the next-token logits."""
        p = len(tokens)
        if p > self.max_context:
            raise ValueError(f"prompt of {p} tokens > max context "
                             f"{self.max_context}")
        if prefix_len:
            return self._prefill_suffix(table_row, tokens, temperature,
                                        rid, prefix_len)
        p_pad = self.pad_len(p)
        if p_pad < p:
            raise ValueError(f"prompt {p} > padded bucket {p_pad}")
        row = list(table_row) + [PagedKVCache.NULL_BLOCK] * (
            p_pad // self.block_size - len(table_row))
        fn = self._prefill_fns.get(p_pad)
        if fn is None:
            fn = self._prefill_fns[p_pad] = jax.jit(
                self._prefill_impl, donate_argnums=(1, 2))
        toks = np.zeros((p_pad,), np.int32)
        toks[:p] = tokens
        nxt, last, self._k, self._v = fn(
            self.params, self._k, self._v,
            jnp.asarray(row, jnp.int32), jnp.asarray(toks),
            jnp.asarray(p, jnp.int32),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(rid, jnp.int32), self._base_key)
        # lint: donated-escape-ok — prefill outputs are fresh XLA result
        # buffers; only the k/v pools are donated, never sampled tokens
        return int(nxt), np.asarray(last)

    def _prefill_suffix(self, table_row, tokens, temperature, rid,
                        prefix_len):
        """The ``prefix_len > 0`` half of :meth:`prefill`."""
        p = len(tokens)
        if prefix_len % self.block_size:
            raise ValueError(f"prefix_len {prefix_len} is not a whole "
                             f"number of {self.block_size}-token blocks")
        if not 0 < prefix_len < p:
            raise ValueError(f"prefix_len {prefix_len} outside (0, {p}) — "
                             f"at least one token must stay uncached")
        s = p - prefix_len
        s_pad = self.pad_len(s)
        # the full row at FIXED width: program shape keyed on s_pad only
        full_row = list(table_row) + [PagedKVCache.NULL_BLOCK] * (
            self.max_blocks_per_seq - len(table_row))
        n_prefix = prefix_len // self.block_size
        suffix_row = list(table_row[n_prefix:]) + [
            PagedKVCache.NULL_BLOCK] * (
            s_pad // self.block_size - (len(table_row) - n_prefix))
        fn = self._prefill_suffix_fns.get(s_pad)
        if fn is None:
            fn = self._prefill_suffix_fns[s_pad] = jax.jit(
                self._prefill_suffix_impl, donate_argnums=(1, 2))
        toks = np.zeros((s_pad,), np.int32)
        toks[:s] = tokens[prefix_len:]
        nxt, last, self._k, self._v = fn(
            self.params, self._k, self._v,
            jnp.asarray(full_row, jnp.int32),
            jnp.asarray(suffix_row, jnp.int32), jnp.asarray(toks),
            jnp.asarray(prefix_len, jnp.int32), jnp.asarray(p, jnp.int32),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(rid, jnp.int32), self._base_key)
        # lint: donated-escape-ok — prefill outputs are fresh XLA result
        # buffers; only the k/v pools are donated, never sampled tokens
        return int(nxt), np.asarray(last)

    def decode(self, tables, lengths, tokens, temps, rids):
        """One decode step over the fixed batch; -> (next tokens ``[B]``
        np.int32, logits ``[B, V]`` np).  All arguments are host arrays of
        length ``max_batch``; inactive slots pass table rows of nulls and
        length 0 (their outputs are garbage by contract)."""
        nxt, logits, self._k, self._v = self._decode_fn(
            self.params, self._k, self._v,
            jnp.asarray(tables, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(rids, jnp.int32), self._base_key)
        # lint: donated-escape-ok — decode outputs are fresh XLA result
        # buffers; only the k/v pools are donated, never tokens/logits
        return np.asarray(nxt), np.asarray(logits)

    def fence(self):
        """Block until the cache state is materialized (honest timing)."""
        jax.block_until_ready((self._k, self._v))
