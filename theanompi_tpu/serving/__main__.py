"""``python -m theanompi_tpu.serving`` == the ``tmserve`` console script."""

from theanompi_tpu.serving.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
