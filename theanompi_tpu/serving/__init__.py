"""Serving path (ISSUE 6): continuous-batching inference for transformer_lm.

The consumer the train-only stack was missing — trained checkpoints load
read-only through the PR 5 verified chain and serve through:

- :mod:`theanompi_tpu.serving.kv_cache` — paged KV cache (fixed blocks,
  per-sequence block tables, alloc/free pool, reserved null block);
- :mod:`theanompi_tpu.serving.engine` — compiled prefill/decode steps over
  the model's own block stack, greedy + temperature/top-k sampling under
  explicit ``(request, position)`` PRNG keys, optional int8 weights;
- :mod:`theanompi_tpu.serving.scheduler` — continuous batching: admission
  queue, per-step join/evict, longest-first preemption on pool pressure;
- :mod:`theanompi_tpu.serving.quant` — int8 weight-only quantization in the
  ``ring_int8`` per-chunk-scale + stochastic-rounding format;
- :mod:`theanompi_tpu.serving.cli` — the ``tmserve`` entry point
  (synthetic open-loop traffic, SERVE.json report);
- :mod:`theanompi_tpu.serving.prefix_cache` — radix tree over full-block
  token chunks (ISSUE 17): refcounted copy-on-write block sharing across
  requests, longest-prefix match feeding partial prefill, LRU eviction
  under pool pressure, params-version invalidation on live rollout.

The resilience tier (ISSUE 14) adds:

- typed request terminal states (``done|expired|shed|failed``) with
  per-request TTFT/total deadlines, admission-time load shedding, and a
  livelock guard (all in the scheduler);
- :mod:`theanompi_tpu.serving.lifecycle` — the durable REQUESTS.jsonl
  terminal-state log a supervised restart dedups against;
- :mod:`theanompi_tpu.serving.rollout` — verified live weight rollout
  with health-probation auto-rollback;
- graceful drain on SIGTERM and ``tmserve --supervise`` (the supervision
  half lives in :mod:`theanompi_tpu.resilience.replica`, across the wall).

Import discipline (lint-enforced, ``tests/test_lint_resilience.py``): this
package never imports the training side — no trainer, exchanger, optimizer
or supervisor — and reads checkpoint bytes only through the verified
loader.  ISSUE 14 deliberately relaxed the wall for exactly two resilience
leaves: the fault grammar (``resilience.faults``) and the exit codes;
``resilience.supervisor`` stays forbidden at any depth (``--supervise``
reaches it through one lazy import of ``resilience.replica``).
"""

from theanompi_tpu.serving.engine import InferenceEngine, sample_tokens
from theanompi_tpu.serving.kv_cache import BlockPool, PagedKVCache, blocks_for
from theanompi_tpu.serving.lifecycle import RequestLog, terminal_rids
from theanompi_tpu.serving.prefix_cache import PrefixCache
from theanompi_tpu.serving.quant import (
    QuantizedTensor,
    dequantize_tree,
    quantize_tree,
)
from theanompi_tpu.serving.rollout import RolloutManager, newest_manifest_epoch
from theanompi_tpu.serving.scheduler import (
    TERMINAL_STATES,
    Request,
    Scheduler,
    run_open_loop,
    serve_report,
)

__all__ = [
    "BlockPool", "InferenceEngine", "PagedKVCache", "PrefixCache",
    "QuantizedTensor", "Request", "RequestLog", "RolloutManager",
    "Scheduler", "TERMINAL_STATES", "blocks_for", "dequantize_tree",
    "newest_manifest_epoch", "quantize_tree", "run_open_loop",
    "sample_tokens", "serve_report", "terminal_rids",
]
