"""Wide-ResNet for CIFAR-10 (BASELINE.md config 1 — the CPU-testable slice).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/wide_resnet.py``,
a fork addition per BASELINE.json; WRN-d-k follows Zagoruyko & Komodakis 2016
(pre-activation BN-ReLU-Conv blocks, three stages, global average pool).

Config: ``depth`` (6n+4), ``widen`` (k), standard WRN-16-4 by default; tests
use a tiny variant.  Sync-BN across the data axis is on by default under
multi-worker rules (``bn_axis``), fixing the reference's per-GPU BN drift.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.cifar10 import Cifar10Data
from theanompi_tpu.ops import layers as L
from theanompi_tpu.ops import initializers as init_lib


@dataclasses.dataclass(frozen=True)
class _WRNBlock(L.Layer):
    """Pre-activation residual block: BN-ReLU-Conv ×2 (+ projection)."""

    filters: int
    stride: int = 1
    bn_axis: str | None = None

    def _sub(self):
        return (
            L.BatchNorm(axis_name=self.bn_axis),
            L.Conv2D(self.filters, 3, stride=self.stride, use_bias=False),
            L.BatchNorm(axis_name=self.bn_axis),
            L.Conv2D(self.filters, 3, use_bias=False),
        )

    def _proj(self):
        return L.Conv2D(self.filters, 1, stride=self.stride, use_bias=False)

    def init(self, key, in_shape):
        bn1, conv1, bn2, conv2 = self._sub()
        keys = jax.random.split(key, 5)
        params, state = {}, {}
        shape = in_shape
        for name, layer, k in (
            ("bn1", bn1, keys[0]), ("conv1", conv1, keys[1]),
            ("bn2", bn2, keys[2]), ("conv2", conv2, keys[3]),
        ):
            p, s, shape = layer.init(k, shape)
            if p:
                params[name] = p
            if s:
                state[name] = s
        if in_shape[-1] != self.filters or self.stride != 1:
            p, _, _ = self._proj().init(keys[4], in_shape)
            params["proj"] = p
        return params, state, shape

    def apply(self, params, state, x, *, train=False, rng=None):
        bn1, conv1, bn2, conv2 = self._sub()
        new_state = dict(state)
        h, s = bn1.apply(params["bn1"], state["bn1"], x, train=train)
        new_state["bn1"] = s
        h = jax.nn.relu(h)
        shortcut = x
        if "proj" in params:
            shortcut, _ = self._proj().apply(params["proj"], {}, h)
        h, _ = conv1.apply(params["conv1"], {}, h)
        h, s = bn2.apply(params["bn2"], state["bn2"], h, train=train)
        new_state["bn2"] = s
        h = jax.nn.relu(h)
        h, _ = conv2.apply(params["conv2"], {}, h)
        return h + shortcut, new_state


class WideResNet(SupervisedModel):
    """WRN-depth-widen on CIFAR-10."""

    default_config = {
        "depth": 16,
        "widen": 4,
        "batch_size": 128,
        "n_epochs": 60,
        "lr": 0.1,
        "lr_decay_epochs": (30, 45),
        "lr_decay_factor": 0.2,
        "momentum": 0.9,
        "weight_decay": 5e-4,
        "nesterov": True,
        "image_size": 32,
        "bn_axis": None,  # set to "data" by multi-worker rules for sync-BN
    }

    def build_data(self):
        return Cifar10Data(self.config)

    def build_net(self):
        cfg = self.config
        depth, k = cfg["depth"], cfg["widen"]
        if (depth - 4) % 6 != 0:
            raise ValueError("WRN depth must be 6n+4")
        n = (depth - 4) // 6
        bn_axis = cfg["bn_axis"]
        widths = [16, 16 * k, 32 * k, 64 * k]
        layers: list[L.Layer] = [L.Conv2D(widths[0], 3, use_bias=False)]
        for stage, width in enumerate(widths[1:]):
            for i in range(n):
                stride = 2 if (stage > 0 and i == 0) else 1
                layers.append(_WRNBlock(width, stride=stride, bn_axis=bn_axis))
        layers += [
            L.BatchNorm(axis_name=bn_axis),
            L.Activation("relu"),
            L.GlobalAvgPool(),
            L.Dense(self.data.n_classes, w_init=init_lib.glorot_normal),
        ]
        s = cfg["image_size"]
        return L.Sequential(layers), (s, s, 3)
