"""The model contract: the duck-typed interface rules drive models through.

Reference (unverified — SURVEY.md §2.3): upstream documents "how to add a
customized model" — a class with ``__init__(config)``, attributes
``batch_size``/``n_epochs``/``data``/``params``, methods
``compile_iter_fns``/``train_iter``/``val_iter``/``adjust_hyperp``/
``scale_lr``/``cleanup``.  The split here is the idiomatic-jax factoring of
exactly that contract:

- the **model** owns hyperparameters, the data object, pure ``init_params``
  and ``loss_fn``, the LR schedule (``adjust_hyperp``) and the optimizer
  choice — everything that defines *what* is trained;
- the **rule's trainer** owns compilation and iteration
  (``compile_iter_fns``/``train_iter``/``val_iter`` live there) — everything
  about *how* steps execute and exchange.

``loss_fn`` is pure and traced once; there is no ``theano.function``
compile-per-model machinery to port — ``jax.jit`` over the rule's step *is*
the ``mode=XLA`` linker the north star asks for.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from theanompi_tpu.ops import SGD, softmax_cross_entropy, top_k_error
from theanompi_tpu.ops.layers import Layer
from theanompi_tpu.parallel.mesh import BF16, FP32, DATA_AXIS, Precision


class Model:
    """Base model: config merging + the contract surface.

    Subclasses must provide ``build_data()`` and either override
    ``init_params``/``loss_fn`` or use :class:`SupervisedModel`.
    """

    default_config: dict[str, Any] = {}

    def __init__(self, config: dict[str, Any] | None = None):
        self.config = {**self.default_config, **(config or {})}
        self.verbose = self.config.get("verbose", True)
        self.batch_size = self.config.get("batch_size", 128)
        self.n_epochs = self.config.get("n_epochs", 10)
        self.precision: Precision = (
            BF16 if self.config.get("precision", "bf16") == "bf16" else FP32
        )
        self.data = self.build_data()

    # -- construction hooks -------------------------------------------------
    def build_data(self):
        raise NotImplementedError

    def build_optimizer(self):
        return SGD(
            momentum=self.config.get("momentum", 0.9),
            weight_decay=self.config.get("weight_decay", 0.0),
            nesterov=self.config.get("nesterov", False),
            grad_clip=self.config.get("grad_clip"),
        )

    def init_opt_state(self, optimizer, params):
        """Optimizer-state layout; GANs override to split per network."""
        return optimizer.init(params)

    # -- sharding hooks (defaults = pure data parallelism) -------------------
    def param_specs(self, params):
        """PartitionSpec per param leaf (tensor-parallel models override
        with :func:`theanompi_tpu.parallel.tensor.specs_from_rules`)."""
        return jax.tree.map(lambda _: P(), params)

    def state_specs(self, state):
        return jax.tree.map(lambda _: P(), state)

    def opt_state_specs(self, optimizer, param_specs):
        """Mirrors ``init_opt_state``; GANs override to split per network."""
        return optimizer.init_specs(param_specs)

    def batch_partition(self) -> P:
        """Leading-dims spec for batches (truncated per leaf rank).
        Sequence-parallel models return ``P("data", "seq")``."""
        return P(DATA_AXIS)

    def grad_reduce_axes(self) -> tuple[str, ...]:
        """Mesh axes gradients are mean-reduced over (the BSP exchange).
        Sequence-parallel models add ``"seq"`` — each seq shard computes a
        partial gradient of the token-mean loss."""
        return (DATA_AXIS,)

    # -- pure functions the trainer compiles --------------------------------
    def init_params(self, rng):
        """-> (params, state) pytrees (fp32 params; state = BN buffers etc.)."""
        raise NotImplementedError

    def loss_fn(self, params, state, batch, rng, train: bool):
        """-> (loss, (new_state, metrics)).  Pure; traced under jit."""
        raise NotImplementedError

    # -- schedule -----------------------------------------------------------
    def adjust_hyperp(self, epoch: int) -> float:
        """Learning rate for ``epoch`` (reference method name preserved).

        Default: base LR with step decay at configured epochs.
        """
        lr = self.config.get("lr", 0.1)
        for e in self.config.get("lr_decay_epochs", ()):
            if epoch >= e:
                lr *= self.config.get("lr_decay_factor", 0.1)
        return lr

    def scale_lr(self, size: int) -> None:
        """Linear LR scaling with worker count (reference EASGD hook)."""
        self.config["lr"] = self.config.get("lr", 0.1) * size

    def cleanup(self) -> None:
        if hasattr(self.data, "cleanup"):
            self.data.cleanup()


class SupervisedModel(Model):
    """Classification models: a net (ops layers) + softmax CE + top-k error.

    Subclasses implement ``build_net() -> (Layer, in_shape)``; batches are
    ``{"x": [B, ...], "y": [B] int}``.
    """

    #: weight on auxiliary-head losses (train-time only; GoogLeNet paper §5)
    aux_loss_weight = 0.3

    def __init__(self, config=None):
        super().__init__(config)
        self.net, self.in_shape = self.build_net()

    def build_net(self) -> tuple[Layer, tuple]:
        raise NotImplementedError

    def init_params(self, rng):
        params, state, out_shape = self.net.init(rng, self.in_shape)
        self._out_shape = out_shape
        return params, state

    def apply_net(self, params, state, x, *, train, rng):
        """-> (logits, aux_logits, new_state).  Models with auxiliary
        classifier heads override to return per-head logits during training;
        the shared ``loss_fn`` folds them in at ``aux_loss_weight`` so l2 and
        metrics handling stay in one place."""
        logits, new_state = self.net.apply(params, state, x, train=train, rng=rng)
        return logits, (), new_state

    def l2_sq_norm(self, params):
        """Squared L2 norm of the params, sharding-aware: leaves whose spec
        shards mesh axes (pipe-stacked blocks, expert weights) are psummed
        over those axes so the l2 term — and hence the loss — is replicated
        on every shard."""
        from theanompi_tpu.ops.opt import global_sq_norm

        return global_sq_norm(params, self.param_specs(params))

    def prepare_x(self, x):
        if x.dtype == jnp.uint8:
            # images travel host->device as uint8 (4x fewer bytes than
            # fp32 — the transfer is the input pipeline's scarce resource);
            # the cast+normalize runs on device, where XLA fuses it into
            # the first conv
            x = x.astype(self.precision.compute_dtype)
            stats = getattr(self.data, "norm_stats", None)
            if stats is not None:
                mean, inv_std = stats
                x = (x - jnp.asarray(mean, x.dtype)) * jnp.asarray(
                    inv_std, x.dtype
                )
        elif jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(self.precision.compute_dtype)  # int tokens stay int
        return x

    def loss_fn(self, params, state, batch, rng, train: bool):
        x = self.prepare_x(batch["x"])
        compute_params = self.precision.cast_to_compute(params)
        logits, aux_logits, new_state = self.apply_net(
            compute_params, state, x, train=train, rng=rng
        )
        loss = softmax_cross_entropy(logits, batch["y"])
        for a in aux_logits:
            loss = loss + self.aux_loss_weight * softmax_cross_entropy(
                a, batch["y"]
            )
        if self.config.get("l2", 0.0):
            # reference models folded L2 into the graph cost; weight_decay on
            # the optimizer is the decoupled alternative
            loss = loss + self.config["l2"] * self.l2_sq_norm(params)
        metrics = {
            "cost": loss,
            "error": top_k_error(logits, batch["y"], k=1),
            "error_top5": top_k_error(logits, batch["y"], k=5)
            if logits.shape[-1] >= 5
            else jnp.zeros((), jnp.float32),
        }
        return loss, (new_state, metrics)
