"""Model zoo conforming to the framework's model contract.

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/`` — AlexNet,
GoogLeNet, VGG16, ResNet-50, Wide-ResNet, PTB LSTM, DCGAN/WGAN, each a class
satisfying the duck-typed contract the rules drive (SURVEY.md §2.3).
"""

from theanompi_tpu.models.contract import Model, SupervisedModel

__all__ = ["Model", "SupervisedModel"]
