"""ResNet-50 (BASELINE.md config 4 — the EASGD / north-star model).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/resnet50.py`` or
``lasagne_model_zoo/resnet50.py`` [MED]; He et al. 2015: 7x7/2 stem, 3x3/2
max-pool, four stages of bottleneck blocks (3/4/6/3) with post-activation
BN-ReLU, global average pool, FC-1000.

TPU notes: bottleneck 1x1-3x3-1x1 convs are exactly MXU-shaped; BN runs in
fp32 with optional cross-replica stats (``bn_axis``); the final BN of each
block is zero-init (``bn_scale_zero``) so residual branches start as
identity — the standard large-batch trick, on by default.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.imagenet import ImageNetData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L


@dataclasses.dataclass(frozen=True)
class _Bottleneck(L.Layer):
    """1x1 reduce → 3x3 → 1x1 expand, post-activation BN, projection shortcut.

    ``remat="save_convs"`` wraps the block in ``jax.checkpoint`` with a
    save-only-conv-outputs policy: the backward recomputes the elementwise
    BN-normalize/ReLU chain from the saved conv outputs instead of reading
    stored post-activation tensors.  On a bandwidth-bound step (ResNet-50
    at batch 256 — ROOFLINE.json proves 85% of time at ≥80% of the HBM
    roof) stored-activation traffic is throughput, and the recompute is
    elementwise work that fuses into reads the backward performs anyway.
    This is a BYTES lever, not a memory-capacity lever — full-block remat
    (recompute convs too) would re-materialize intermediates to HBM twice
    and lose."""

    filters: int          # bottleneck width; output is 4x
    stride: int = 1
    bn_axis: str | None = None
    zero_init_last: bool = True
    remat: str = "none"   # "none" | "save_convs"

    def _subs(self):
        f = self.filters
        last_scale = init_lib.zeros if self.zero_init_last else init_lib.ones
        return (
            ("conv1", L.Conv2D(f, 1, use_bias=False)),
            ("bn1", L.BatchNorm(axis_name=self.bn_axis)),
            ("conv2", L.Conv2D(f, 3, stride=self.stride, padding=1, use_bias=False)),
            ("bn2", L.BatchNorm(axis_name=self.bn_axis)),
            ("conv3", L.Conv2D(4 * f, 1, use_bias=False)),
            ("bn3", L.BatchNorm(axis_name=self.bn_axis, scale_init=last_scale)),
        )

    def _proj(self):
        return (
            ("proj", L.Conv2D(4 * self.filters, 1, stride=self.stride,
                              use_bias=False)),
            ("proj_bn", L.BatchNorm(axis_name=self.bn_axis)),
        )

    def init(self, key, in_shape):
        subs = list(self._subs())
        need_proj = in_shape[-1] != 4 * self.filters or self.stride != 1
        if need_proj:
            subs += list(self._proj())
        keys = jax.random.split(key, len(subs))
        params, state = {}, {}
        shape = in_shape
        proj_shape = in_shape
        for (name, layer), k in zip(subs, keys):
            src = proj_shape if name.startswith("proj") else shape
            p, s, out = layer.init(k, src)
            if name.startswith("proj"):
                proj_shape = out
            else:
                shape = out
            if p:
                params[name] = p
            if s:
                state[name] = s
        return params, state, shape

    def apply(self, params, state, x, *, train=False, rng=None):
        if self.remat not in ("none", "save_convs"):
            raise ValueError(f"remat {self.remat!r} not in ('none', 'save_convs')")
        if self.remat == "save_convs":
            fn = jax.checkpoint(
                functools.partial(self._apply_impl, train=train),
                policy=jax.checkpoint_policies.save_only_these_names(
                    "conv_out"),
            )
            return fn(params, state, x)
        return self._apply_impl(params, state, x, train=train)

    def _apply_impl(self, params, state, x, train=False):
        from jax.ad_checkpoint import checkpoint_name

        def tag(h):
            # the save-policy anchor: conv outputs are kept; everything
            # downstream of them (BN normalize, relu, stats) is recomputed
            # in the backward when remat is on (no-op name otherwise)
            return checkpoint_name(h, "conv_out")

        new_state = dict(state)
        h = x
        for name, layer in self._subs():
            h, s = layer.apply(
                params.get(name, {}), state.get(name, {}), h, train=train
            )
            if name.startswith("conv"):
                h = tag(h)
            if s:
                new_state[name] = s
            if name in ("bn1", "bn2"):
                h = jax.nn.relu(h)
        shortcut = x
        if "proj" in params:
            for name, layer in self._proj():
                shortcut, s = layer.apply(
                    params.get(name, {}), state.get(name, {}), shortcut,
                    train=train,
                )
                if name == "proj":
                    shortcut = tag(shortcut)
                if s:
                    new_state[name] = s
        return jax.nn.relu(h + shortcut), new_state


@dataclasses.dataclass(frozen=True)
class _SpaceToDepthStem(L.Layer):
    """The 7×7/2 stem conv, math-identical but MXU-shaped (MLPerf trick).

    A 7×7 stride-2 conv on ``[H, W, 3]`` runs the MXU at 3 input channels
    — measured 16% utilization, 0.59 of the HBM roof (ROOFLINE.json
    fusion.903).  Rearranging 2×2 pixel blocks into channels
    (space-to-depth) and the zero-padded 8×8 kernel into ``[4, 4, 12, F]``
    gives the SAME linear map as a stride-1 conv with asymmetric padding
    (2, 1): output[i,j] = Σ_a,b xpad[2i-4+a, 2j-4+b]·Kpad[a,b] with
    Kpad[0,·]=Kpad[·,0]=0 reproduces the original Σ x[2i-3+a']·K[a']
    exactly.  Params stay in the logical ``[7, 7, C, F]`` layout (init
    statistics and param-tree shape unchanged); the pad+reshape of the
    9 KB kernel happens at apply time.
    """

    filters: int = 64
    w_init: Callable = init_lib.he_normal

    def init(self, key, in_shape):
        h, w, c = in_shape
        if h % 2 or w % 2:
            raise ValueError(f"space-to-depth stem needs even H/W, got {in_shape}")
        params = {"w": self.w_init(key, (7, 7, c, self.filters))}
        return params, {}, (h // 2, w // 2, self.filters)

    def apply(self, params, state, x, *, train=False, rng=None):
        n, h, w, c = x.shape
        f = self.filters
        xs = x.reshape(n, h // 2, 2, w // 2, 2, c)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        k = params["w"].astype(x.dtype)
        kp = jnp.pad(k, ((1, 0), (1, 0), (0, 0), (0, 0)))   # zero row/col 0
        # [8,8,c,f] -> [(p,di),(q,dj),c,f] -> [p,q,(di,dj,c),f]
        kp = kp.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
        kp = kp.reshape(4, 4, 4 * c, f)
        y = jax.lax.conv_general_dilated(
            xs, kp, window_strides=(1, 1), padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y, state


class ResNet50(SupervisedModel):
    default_config = {
        "batch_size": 64,
        "n_epochs": 90,
        "lr": 0.1,
        "lr_decay_epochs": (30, 60, 80),
        "lr_decay_factor": 0.1,
        "momentum": 0.9,
        "weight_decay": 1e-4,
        "nesterov": True,
        "image_size": 224,
        "n_classes": 1000,
        "bn_axis": None,
        "bn_scale_zero": True,
        "stage_blocks": (3, 4, 6, 3),  # -> ResNet-50
        # "save_convs": per-block checkpoint policy that keeps conv outputs
        # and recomputes the elementwise BN/ReLU chain in the backward —
        # an HBM-bytes lever for the bandwidth-bound train step.
        # MEASURED (interleaved A/B slope, v5e): 113.8 vs 93.8 ms/step —
        # the stat/normalize recompute costs more reads than it saves on
        # this step; kept as a knob (it IS the memory lever for batch
        # sizes that don't otherwise fit), default off.
        "remat": "none",
        # "space_to_depth": math-identical MXU-shaped stem (see
        # _SpaceToDepthStem); "conv7" is the plain 7x7/2 conv
        "stem": "conv7",
    }

    def build_data(self):
        return ImageNetData(self.config)

    def build_net(self):
        cfg = self.config
        bn_axis = cfg["bn_axis"]
        if cfg["stem"] not in ("conv7", "space_to_depth"):
            raise ValueError(
                f"stem {cfg['stem']!r} not in ('conv7', 'space_to_depth')")
        stem: L.Layer = (
            _SpaceToDepthStem(64) if cfg["stem"] == "space_to_depth"
            else L.Conv2D(64, 7, stride=2, padding=3, use_bias=False))
        layers: list[L.Layer] = [
            stem,
            L.BatchNorm(axis_name=bn_axis),
            L.Activation("relu"),
            L.MaxPool(3, stride=2, padding="SAME"),
        ]
        widths = (64, 128, 256, 512)
        for stage, (w, blocks) in enumerate(zip(widths, cfg["stage_blocks"])):
            for i in range(blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                layers.append(
                    _Bottleneck(w, stride=stride, bn_axis=bn_axis,
                                zero_init_last=cfg["bn_scale_zero"],
                                remat=cfg["remat"])
                )
        layers += [
            L.GlobalAvgPool(),
            L.Dense(cfg["n_classes"], w_init=init_lib.glorot_normal),
        ]
        s = cfg["image_size"]
        return L.Sequential(layers), (s, s, 3)
