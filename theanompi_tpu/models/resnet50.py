"""ResNet-50 (BASELINE.md config 4 — the EASGD / north-star model).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/resnet50.py`` or
``lasagne_model_zoo/resnet50.py`` [MED]; He et al. 2015: 7x7/2 stem, 3x3/2
max-pool, four stages of bottleneck blocks (3/4/6/3) with post-activation
BN-ReLU, global average pool, FC-1000.

TPU notes: bottleneck 1x1-3x3-1x1 convs are exactly MXU-shaped; BN runs in
fp32 with optional cross-replica stats (``bn_axis``); the final BN of each
block is zero-init (``bn_scale_zero``) so residual branches start as
identity — the standard large-batch trick, on by default.
"""

from __future__ import annotations

import dataclasses

import jax

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.imagenet import ImageNetData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L


@dataclasses.dataclass(frozen=True)
class _Bottleneck(L.Layer):
    """1x1 reduce → 3x3 → 1x1 expand, post-activation BN, projection shortcut."""

    filters: int          # bottleneck width; output is 4x
    stride: int = 1
    bn_axis: str | None = None
    zero_init_last: bool = True

    def _subs(self):
        f = self.filters
        last_scale = init_lib.zeros if self.zero_init_last else init_lib.ones
        return (
            ("conv1", L.Conv2D(f, 1, use_bias=False)),
            ("bn1", L.BatchNorm(axis_name=self.bn_axis)),
            ("conv2", L.Conv2D(f, 3, stride=self.stride, padding=1, use_bias=False)),
            ("bn2", L.BatchNorm(axis_name=self.bn_axis)),
            ("conv3", L.Conv2D(4 * f, 1, use_bias=False)),
            ("bn3", L.BatchNorm(axis_name=self.bn_axis, scale_init=last_scale)),
        )

    def _proj(self):
        return (
            ("proj", L.Conv2D(4 * self.filters, 1, stride=self.stride,
                              use_bias=False)),
            ("proj_bn", L.BatchNorm(axis_name=self.bn_axis)),
        )

    def init(self, key, in_shape):
        subs = list(self._subs())
        need_proj = in_shape[-1] != 4 * self.filters or self.stride != 1
        if need_proj:
            subs += list(self._proj())
        keys = jax.random.split(key, len(subs))
        params, state = {}, {}
        shape = in_shape
        proj_shape = in_shape
        for (name, layer), k in zip(subs, keys):
            src = proj_shape if name.startswith("proj") else shape
            p, s, out = layer.init(k, src)
            if name.startswith("proj"):
                proj_shape = out
            else:
                shape = out
            if p:
                params[name] = p
            if s:
                state[name] = s
        return params, state, shape

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        h = x
        for name, layer in self._subs():
            h, s = layer.apply(
                params.get(name, {}), state.get(name, {}), h, train=train
            )
            if s:
                new_state[name] = s
            if name in ("bn1", "bn2"):
                h = jax.nn.relu(h)
        shortcut = x
        if "proj" in params:
            for name, layer in self._proj():
                shortcut, s = layer.apply(
                    params.get(name, {}), state.get(name, {}), shortcut,
                    train=train,
                )
                if s:
                    new_state[name] = s
        return jax.nn.relu(h + shortcut), new_state


class ResNet50(SupervisedModel):
    default_config = {
        "batch_size": 64,
        "n_epochs": 90,
        "lr": 0.1,
        "lr_decay_epochs": (30, 60, 80),
        "lr_decay_factor": 0.1,
        "momentum": 0.9,
        "weight_decay": 1e-4,
        "nesterov": True,
        "image_size": 224,
        "n_classes": 1000,
        "bn_axis": None,
        "bn_scale_zero": True,
        "stage_blocks": (3, 4, 6, 3),  # -> ResNet-50
    }

    def build_data(self):
        return ImageNetData(self.config)

    def build_net(self):
        cfg = self.config
        bn_axis = cfg["bn_axis"]
        layers: list[L.Layer] = [
            L.Conv2D(64, 7, stride=2, padding=3, use_bias=False),
            L.BatchNorm(axis_name=bn_axis),
            L.Activation("relu"),
            L.MaxPool(3, stride=2, padding="SAME"),
        ]
        widths = (64, 128, 256, 512)
        for stage, (w, blocks) in enumerate(zip(widths, cfg["stage_blocks"])):
            for i in range(blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                layers.append(
                    _Bottleneck(w, stride=stride, bn_axis=bn_axis,
                                zero_init_last=cfg["bn_scale_zero"])
                )
        layers += [
            L.GlobalAvgPool(),
            L.Dense(cfg["n_classes"], w_init=init_lib.glorot_normal),
        ]
        s = cfg["image_size"]
        return L.Sequential(layers), (s, s, 3)
