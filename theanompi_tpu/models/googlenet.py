"""GoogLeNet / Inception-v1 (BASELINE.md config 3).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/googlenet.py`` —
Szegedy et al. 2014: stem (7x7/2 conv, LRN-era norms), nine inception
modules (1x1 / 1x1→3x3 / 1x1→5x5 / pool→1x1 branches, channel-concat),
global average pool, FC, plus two auxiliary classifiers tapped after
inception 4a and 4d whose losses are added at weight 0.3 during training
and dropped at eval (paper §5).

The aux heads are behind the ``aux`` config knob, **off by default**: they
existed to help 2014-era optimization, and without them the training graph
is a single path XLA fuses well.  With ``aux=True`` the trunk runs in three
segments so the tap activations feed the heads; eval always runs the main
path only.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.imagenet import ImageNetData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L


def _branch(*layers: L.Layer) -> L.Sequential:
    return L.Sequential(tuple(layers))


@dataclasses.dataclass(frozen=True)
class _Inception(L.Layer):
    """Four parallel branches, concatenated on channels.

    ``spec`` = (n1x1, n3x3_reduce, n3x3, n5x5_reduce, n5x5, pool_proj).
    ``bn`` inserts BatchNorm between every conv and its relu (the
    Inception-v2 / "BN-GoogLeNet" training recipe) — same knob VGG-11
    grew for the bounded convergence gate.
    """

    spec: tuple
    lrn: bool = False
    bn: bool = False
    bn_axis: str | None = None

    def _conv(self, c, k, padding=0):
        conv = L.Conv2D(c, k, padding=padding, use_bias=not self.bn)
        relu = L.Activation("relu")
        if self.bn:
            return (conv, L.BatchNorm(axis_name=self.bn_axis), relu)
        return (conv, relu)

    def _branches(self):
        n1, r3, n3, r5, n5, pp = self.spec
        return (
            _branch(*self._conv(n1, 1)),
            _branch(*self._conv(r3, 1), *self._conv(n3, 3, padding=1)),
            _branch(*self._conv(r5, 1), *self._conv(n5, 5, padding=2)),
            _branch(L.MaxPool(3, stride=1, padding="SAME"),
                    *self._conv(pp, 1)),
        )

    def init(self, key, in_shape):
        keys = jax.random.split(key, 4)
        params, state = {}, {}
        out_c = 0
        for i, (b, k) in enumerate(zip(self._branches(), keys)):
            p, s, shape = b.init(k, in_shape)
            if p:
                params[f"b{i}"] = p
            if s:
                state[f"b{i}"] = s
            out_c += shape[-1]
        return params, state, (*in_shape[:-1], out_c)

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        outs = []
        for i, b in enumerate(self._branches()):
            y, s = b.apply(
                params.get(f"b{i}", {}), state.get(f"b{i}", {}), x, train=train
            )
            if s:
                new_state[f"b{i}"] = s
            outs.append(y)
        return jnp.concatenate(outs, axis=-1), new_state


# (module name, spec) in network order, with 'P' = 3x3/2 max-pool; the two
# aux-classifier taps (paper §5) sit after 4a and 4d
_PLAN = (
    ("3a", (64, 96, 128, 16, 32, 32)),
    ("3b", (128, 128, 192, 32, 96, 64)),
    "P",
    ("4a", (192, 96, 208, 16, 48, 64)),
    ("4b", (160, 112, 224, 24, 64, 64)),
    ("4c", (128, 128, 256, 24, 64, 64)),
    ("4d", (112, 144, 288, 32, 64, 64)),
    ("4e", (256, 160, 320, 32, 128, 128)),
    "P",
    ("5a", (256, 160, 320, 32, 128, 128)),
    ("5b", (384, 192, 384, 48, 128, 128)),
)


@dataclasses.dataclass(frozen=True)
class _TrunkWithTaps(L.Layer):
    """Trunk split at the aux taps; heads consume the tap activations.

    ``apply`` is the main path only (eval, and training with ``aux=False``);
    ``apply_with_aux`` additionally returns the two aux-head logits.
    """

    segs: tuple  # (stem..4a, 4b..4d, 4e..logits)
    heads: tuple = ()  # (aux1, aux2) or empty

    def init(self, key, in_shape):
        keys = jax.random.split(key, len(self.segs) + len(self.heads))
        params, state = {}, {}
        shape = tuple(in_shape)
        taps = []
        for i, seg in enumerate(self.segs):
            p, s, shape = seg.init(keys[i], shape)
            params[f"seg{i}"] = p
            if s:
                state[f"seg{i}"] = s
            taps.append(shape)
        for i, head in enumerate(self.heads):
            p, s, _ = head.init(keys[len(self.segs) + i], taps[i])
            params[f"aux{i}"] = p
            if s:
                state[f"aux{i}"] = s
        return params, state, shape

    def _run_trunk(self, params, state, x, *, train, rng):
        new_state = dict(state)
        rngs = (
            jax.random.split(rng, len(self.segs))
            if rng is not None
            else [None] * len(self.segs)
        )
        taps = []
        for i, seg in enumerate(self.segs):
            x, s = seg.apply(
                params[f"seg{i}"], state.get(f"seg{i}", {}), x,
                train=train, rng=rngs[i],
            )
            if s:
                new_state[f"seg{i}"] = s
            taps.append(x)
        return x, taps, new_state

    def apply(self, params, state, x, *, train=False, rng=None):
        out, _, new_state = self._run_trunk(params, state, x, train=train, rng=rng)
        return out, new_state

    def apply_with_aux(self, params, state, x, *, train=False, rng=None):
        rng, aux_rng = (
            jax.random.split(rng) if rng is not None else (None, None)
        )
        out, taps, new_state = self._run_trunk(
            params, state, x, train=train, rng=rng
        )
        aux_rngs = (
            jax.random.split(aux_rng, len(self.heads))
            if aux_rng is not None
            else [None] * len(self.heads)
        )
        aux_logits = []
        for i, head in enumerate(self.heads):
            a, s = head.apply(
                params[f"aux{i}"], state.get(f"aux{i}", {}), taps[i],
                train=train, rng=aux_rngs[i],
            )
            if s:
                new_state[f"aux{i}"] = s
            aux_logits.append(a)
        return (out, tuple(aux_logits)), new_state


class GoogLeNet(SupervisedModel):
    default_config = {
        "batch_size": 32,
        "n_epochs": 80,
        "lr": 0.01,
        "lr_decay_epochs": (30, 55, 70),
        "lr_decay_factor": 0.1,
        "momentum": 0.9,
        "weight_decay": 2e-4,
        "image_size": 224,
        "n_classes": 1000,
        "lrn": True,
        "dropout": 0.4,
        "aux": False,  # paper §5 auxiliary classifiers (train-time only)
        # BN-GoogLeNet variant: BatchNorm after every conv, biases and LRN
        # dropped — the trainable-at-small-scale recipe (Inception-v2)
        "bn": False,
        "bn_axis": None,
    }

    def build_data(self):
        return ImageNetData(self.config)

    def _aux_head(self) -> L.Sequential:
        """Paper §5 head: avgpool 5x5/3, 1x1x128 conv, FC-1024, drop 0.7, FC.

        On inputs too small for a 5x5 valid pool at the tap (tests run tiny
        images; the tap sits at image_size/16) the pool degrades to global.
        """
        cfg = self.config
        relu = L.Activation("relu")
        tap_hw = cfg["image_size"] // 16
        pool = (L.AvgPool(5, stride=3) if tap_hw >= 5 else L.GlobalAvgPool())
        return L.Sequential((
            pool,
            L.Conv2D(128, 1) if tap_hw >= 5 else L.Dense(128),
            relu,
            L.Flatten(),
            L.Dense(1024),
            relu,
            L.Dropout(0.7),
            L.Dense(cfg["n_classes"], w_init=init_lib.glorot_normal),
        ))

    def build_net(self):
        cfg = self.config
        self.aux = bool(cfg["aux"])
        bn, bn_axis = bool(cfg["bn"]), cfg["bn_axis"]
        relu = L.Activation("relu")

        def conv(c, k, stride=1, padding=0):
            out: list[L.Layer] = [
                L.Conv2D(c, k, stride=stride, padding=padding,
                         use_bias=not bn)]
            if bn:
                out.append(L.BatchNorm(axis_name=bn_axis))
            out.append(relu)
            return out

        # BN replaces the LRN-era norms entirely (Inception-v2 recipe)
        maybe_lrn = [L.LRN(size=5)] if (cfg["lrn"] and not bn) else []
        stem: list[L.Layer] = [
            *conv(64, 7, stride=2, padding=3),
            L.MaxPool(3, stride=2, padding="SAME"),
            *maybe_lrn,
            *conv(64, 1),
            *conv(192, 3, padding=1),
            *maybe_lrn,
            L.MaxPool(3, stride=2, padding="SAME"),
        ]
        head = [
            L.GlobalAvgPool(),
            L.Dropout(cfg["dropout"]),
            L.Dense(cfg["n_classes"], w_init=init_lib.glorot_normal),
        ]
        # trunk segments split at the aux taps: [stem..4a], [4b..4d],
        # [4e..logits]
        segs: list[list[L.Layer]] = [stem, [], []]
        seg = 0
        for item in _PLAN:
            if item == "P":
                segs[seg].append(L.MaxPool(3, stride=2, padding="SAME"))
            else:
                segs[seg].append(_Inception(item[1], bn=bn, bn_axis=bn_axis))
                if item[0] == "4a":
                    seg = 1
                elif item[0] == "4d":
                    seg = 2
        segs[2] += head
        s = cfg["image_size"]
        if not self.aux:
            # flat Sequential: the single fused path, and the param-tree
            # layout aux=False checkpoints have always had
            return L.Sequential(tuple(segs[0] + segs[1] + segs[2])), (s, s, 3)
        net = _TrunkWithTaps(
            segs=tuple(L.Sequential(tuple(s)) for s in segs),
            heads=(self._aux_head(), self._aux_head()),
        )
        return net, (s, s, 3)

    def apply_net(self, params, state, x, *, train, rng):
        # paper §5: aux losses join at weight 0.3 (loss_fn's
        # aux_loss_weight) during training only; eval runs the main path
        if not (train and self.aux):
            return super().apply_net(params, state, x, train=train, rng=rng)
        (logits, aux_logits), new_state = self.net.apply_with_aux(
            params, state, x, train=train, rng=rng
        )
        return logits, aux_logits, new_state
