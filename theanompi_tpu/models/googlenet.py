"""GoogLeNet / Inception-v1 (BASELINE.md config 3).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/googlenet.py`` —
Szegedy et al. 2014: stem (7x7/2 conv, LRN-era norms), nine inception
modules (1x1 / 1x1→3x3 / 1x1→5x5 / pool→1x1 branches, channel-concat),
global average pool, FC.  The paper's auxiliary classifiers existed only to
help 2014-era optimization; they are off by default here (``aux=False``) —
with BN available ("bn": True) they are unnecessary, and omitting them keeps
the training graph a single path XLA fuses well.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.imagenet import ImageNetData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L


def _branch(*layers: L.Layer) -> L.Sequential:
    return L.Sequential(tuple(layers))


@dataclasses.dataclass(frozen=True)
class _Inception(L.Layer):
    """Four parallel branches, concatenated on channels.

    ``spec`` = (n1x1, n3x3_reduce, n3x3, n5x5_reduce, n5x5, pool_proj).
    """

    spec: tuple
    lrn: bool = False

    def _branches(self):
        n1, r3, n3, r5, n5, pp = self.spec
        relu = L.Activation("relu")
        return (
            _branch(L.Conv2D(n1, 1), relu),
            _branch(L.Conv2D(r3, 1), relu, L.Conv2D(n3, 3, padding=1), relu),
            _branch(L.Conv2D(r5, 1), relu, L.Conv2D(n5, 5, padding=2), relu),
            _branch(L.MaxPool(3, stride=1, padding="SAME"), L.Conv2D(pp, 1), relu),
        )

    def init(self, key, in_shape):
        keys = jax.random.split(key, 4)
        params, state = {}, {}
        out_c = 0
        for i, (b, k) in enumerate(zip(self._branches(), keys)):
            p, s, shape = b.init(k, in_shape)
            if p:
                params[f"b{i}"] = p
            if s:
                state[f"b{i}"] = s
            out_c += shape[-1]
        return params, state, (*in_shape[:-1], out_c)

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        outs = []
        for i, b in enumerate(self._branches()):
            y, s = b.apply(
                params.get(f"b{i}", {}), state.get(f"b{i}", {}), x, train=train
            )
            if s:
                new_state[f"b{i}"] = s
            outs.append(y)
        return jnp.concatenate(outs, axis=-1), new_state


# (module name, spec) in network order, with 'P' = 3x3/2 max-pool
_PLAN = (
    ("3a", (64, 96, 128, 16, 32, 32)),
    ("3b", (128, 128, 192, 32, 96, 64)),
    "P",
    ("4a", (192, 96, 208, 16, 48, 64)),
    ("4b", (160, 112, 224, 24, 64, 64)),
    ("4c", (128, 128, 256, 24, 64, 64)),
    ("4d", (112, 144, 288, 32, 64, 64)),
    ("4e", (256, 160, 320, 32, 128, 128)),
    "P",
    ("5a", (256, 160, 320, 32, 128, 128)),
    ("5b", (384, 192, 384, 48, 128, 128)),
)


class GoogLeNet(SupervisedModel):
    default_config = {
        "batch_size": 32,
        "n_epochs": 80,
        "lr": 0.01,
        "lr_decay_epochs": (30, 55, 70),
        "lr_decay_factor": 0.1,
        "momentum": 0.9,
        "weight_decay": 2e-4,
        "image_size": 224,
        "n_classes": 1000,
        "lrn": True,
        "dropout": 0.4,
    }

    def build_data(self):
        return ImageNetData(self.config)

    def build_net(self):
        cfg = self.config
        relu = L.Activation("relu")
        maybe_lrn = [L.LRN(size=5)] if cfg["lrn"] else []
        layers: list[L.Layer] = [
            L.Conv2D(64, 7, stride=2, padding=3),
            relu,
            L.MaxPool(3, stride=2, padding="SAME"),
            *maybe_lrn,
            L.Conv2D(64, 1),
            relu,
            L.Conv2D(192, 3, padding=1),
            relu,
            *maybe_lrn,
            L.MaxPool(3, stride=2, padding="SAME"),
        ]
        for item in _PLAN:
            if item == "P":
                layers.append(L.MaxPool(3, stride=2, padding="SAME"))
            else:
                layers.append(_Inception(item[1]))
        layers += [
            L.GlobalAvgPool(),
            L.Dropout(cfg["dropout"]),
            L.Dense(cfg["n_classes"], w_init=init_lib.glorot_normal),
        ]
        s = cfg["image_size"]
        return L.Sequential(layers), (s, s, 3)
