"""Word-level LSTM language model (BASELINE.md config 5 — PTB lineage).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/lstm.py``, from
the Theano-tutorial PTB LM lineage: embedding → LSTM stack (BPTT) → softmax
over the vocabulary, perplexity-tracked.

Real PTB loads from ``$PTB_PATH``/``config["data_path"]`` pointing at a dir
with ``ptb.train.txt``/``ptb.valid.txt`` (space-tokenized words); otherwise a
synthetic bigram-structured stream stands in (zero-egress image), exercising
the identical pipeline.  The time dimension runs under ``lax.scan`` (the
compiled analogue of Theano ``scan`` BPTT); the input projection is hoisted
out of the scan to keep the MXU busy (see ops.layers.LSTM).
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.base import (
    Dataset,
    SyntheticSequenceDataset,
    derive_seed,
)
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L


class PTBData(Dataset):
    """Contiguous token stream chopped into [B, T] next-word batches."""

    def __init__(self, config: dict | None = None):
        config = config or {}
        self.seq_len = config.get("seq_len", 35)
        path = config.get("data_path") or os.environ.get("PTB_PATH")
        if path and os.path.exists(os.path.join(path, "ptb.train.txt")):
            self.synthetic = False
            train_words = open(os.path.join(path, "ptb.train.txt")).read().split()
            val_words = open(os.path.join(path, "ptb.valid.txt")).read().split()
            vocab = sorted(set(train_words)) + ["<unk2>"]
            self.word_to_id = {w: i for i, w in enumerate(vocab)}
            unk = len(vocab) - 1
            self.vocab = len(vocab)
            train_ids = np.array(
                [self.word_to_id.get(w, unk) for w in train_words], np.int32
            )
            val_ids = np.array(
                [self.word_to_id.get(w, unk) for w in val_words], np.int32
            )
            self._train_seqs = self._chop(train_ids)
            self._val_seqs = self._chop(val_ids)
        else:
            self.synthetic = True
            syn = SyntheticSequenceDataset(
                n_train=config.get("n_train", 512),
                n_val=config.get("n_val", 128),
                seq_len=self.seq_len,
                vocab=config.get("vocab", 256),
            )
            self.vocab = syn.vocab
            self._train_seqs = syn._train
            self._val_seqs = syn._val
        self.n_classes = self.vocab
        self.n_train = len(self._train_seqs)
        self.n_val = len(self._val_seqs)
        self.sample_shape = (self.seq_len,)

    def _chop(self, ids: np.ndarray) -> np.ndarray:
        t = self.seq_len + 1  # +1: targets are inputs shifted by one
        n = len(ids) // t
        return ids[: n * t].reshape(n, t)

    def train_batches(self, batch_size: int, epoch: int, seed: int = 0,
                      start_batch: int = 0):
        rng = np.random.RandomState(derive_seed("shuffle", seed, epoch))
        order = rng.permutation(self.n_train)
        for i in range(int(start_batch), self.n_train // batch_size):
            s = self._train_seqs[order[i * batch_size : (i + 1) * batch_size]]
            yield {"x": s[:, :-1], "y": s[:, 1:]}

    def val_batches(self, batch_size: int):
        for i in range(self.n_val // batch_size):
            s = self._val_seqs[i * batch_size : (i + 1) * batch_size]
            yield {"x": s[:, :-1], "y": s[:, 1:]}


class LSTM(SupervisedModel):
    """PTB-style LM.  ``error`` in metrics is next-word top-1 error;
    ``perplexity`` = exp(loss) is appended for the reference's headline LM
    metric."""

    default_config = {
        "batch_size": 32,
        "n_epochs": 13,
        "lr": 1.0,        # the tutorial-era SGD schedule
        "lr_decay_epochs": (4, 6, 8, 10, 12),
        "lr_decay_factor": 0.5,
        "momentum": 0.0,
        "seq_len": 35,
        "hidden": 650,
        "n_layers": 2,
        "embed_dim": 650,
        "dropout": 0.5,
        "grad_clip": 5.0,
    }

    def build_data(self):
        return PTBData(self.config)

    def build_net(self):
        cfg = self.config
        layers: list[L.Layer] = [
            L.Embedding(self.data.vocab, cfg["embed_dim"]),
        ]
        for _ in range(cfg["n_layers"]):
            layers += [L.Dropout(cfg["dropout"]), L.LSTM(cfg["hidden"])]
        layers += [
            L.Dropout(cfg["dropout"]),
            L.Dense(self.data.vocab, w_init=init_lib.glorot_normal),
        ]
        return L.Sequential(layers), (cfg["seq_len"],)

    def loss_fn(self, params, state, batch, rng, train: bool):
        loss, (new_state, metrics) = super().loss_fn(
            params, state, batch, rng, train
        )
        metrics = dict(metrics)
        metrics["perplexity"] = jnp.exp(metrics["cost"])
        return loss, (new_state, metrics)
