"""VGG-16 (BASELINE.md config 3, with the reference's shallow VGG-11 variant).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/vggnet_16.py``
plus a ``vggnet_11_shallow`` variant [LOW confidence]; Simonyan & Zisserman
2014 configuration D (13 conv + 3 FC) / A (8 conv + 3 FC).

``config["shallow"]=True`` selects VGG-11.  BN is off by default (parity with
the paper-era reference); ``config["bn"]=True`` inserts BatchNorm after every
conv (the modern trainable-at-scale variant, sync across ``bn_axis``).
"""

from __future__ import annotations

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.imagenet import ImageNetData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L

# conv widths per stage; 'M' = 2x2 max-pool
_VGG16 = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M")
_VGG11 = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")


class VGGNet_16(SupervisedModel):
    default_config = {
        "batch_size": 64,
        "n_epochs": 74,
        "lr": 0.01,
        "lr_decay_epochs": (50, 65),
        "lr_decay_factor": 0.1,
        "momentum": 0.9,
        "weight_decay": 5e-4,
        "image_size": 224,
        "n_classes": 1000,
        "dropout": 0.5,
        "shallow": False,
        "bn": False,
        "bn_axis": None,
        "fc_width": 4096,
    }

    def build_data(self):
        return ImageNetData(self.config)

    def build_net(self):
        cfg = self.config
        plan = _VGG11 if cfg["shallow"] else _VGG16
        layers: list[L.Layer] = []
        for item in plan:
            if item == "M":
                layers.append(L.MaxPool(2, stride=2))
                continue
            layers.append(L.Conv2D(item, 3, padding=1, use_bias=not cfg["bn"]))
            if cfg["bn"]:
                layers.append(L.BatchNorm(axis_name=cfg["bn_axis"]))
            layers.append(L.Activation("relu"))
        w = cfg["fc_width"]
        layers += [
            L.Flatten(),
            L.Dense(w),
            L.Activation("relu"),
            L.Dropout(cfg["dropout"]),
            L.Dense(w),
            L.Activation("relu"),
            L.Dropout(cfg["dropout"]),
            L.Dense(cfg["n_classes"], w_init=init_lib.glorot_normal),
        ]
        s = cfg["image_size"]
        return L.Sequential(layers), (s, s, 3)


class VGGNet_11_Shallow(VGGNet_16):
    """Reference's shallow variant as its own class (import-by-string)."""

    default_config = {**VGGNet_16.default_config, "shallow": True}
